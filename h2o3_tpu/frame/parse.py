"""Ingest — successor of ``water.parser.ParseDataset`` / ``ParseSetup`` /
``CsvParser`` [UNVERIFIED upstream paths, SURVEY.md §0].

H2O's distributed parse maps ``CsvParser.parseChunk`` over file-block chunks
and unifies categorical domains in a second cluster pass (SURVEY.md §3.2).
The TPU-native shape of that work (SURVEY.md §7 step 3) is host-side columnar
ingest — pandas/pyarrow do vectorized tokenization — followed by type
inference, global categorical interning (single-process: one pass), and
``device_put`` of each column's padded buffer with the row sharding. The
three-call REST surface (ImportFiles → ParseSetup → Parse) is preserved by
:func:`parse_setup` + :func:`parse` for API parity.

Formats: CSV (+gz), Parquet, ORC, Feather/Arrow, SVMLight; XLS via pandas
when openpyxl is present.
"""

from __future__ import annotations

import os
from typing import Mapping, Sequence

import numpy as np
import pandas as pd

from h2o3_tpu.frame.frame import CAT, INT, NUM, STR, TIME, Frame, Vec
from h2o3_tpu.utils.log import Log

# H2O parses low-cardinality strings as enums and high-cardinality ones as
# strings; this mirrors that heuristic (upstream constant lives in the parser
# setup logic [UNVERIFIED]).
_MAX_CAT_FRACTION = 0.95
_MAX_CAT_LEVELS = 10_000_000


def _read_any(
    path: str,
    sep: str | None = None,
    header: int | None = 0,
    nrows: int | None = None,
) -> pd.DataFrame:
    ext = os.path.splitext(path.removesuffix(".gz"))[1].lower()
    if ext in (".parquet", ".pq"):
        return pd.read_parquet(path)
    if ext == ".orc":
        return pd.read_orc(path)
    if ext in (".feather", ".arrow"):
        return pd.read_feather(path)
    if ext in (".xls", ".xlsx"):
        return pd.read_excel(path, nrows=nrows)
    if ext == ".svm" or ext == ".svmlight":
        from sklearn.datasets import load_svmlight_file

        X, y = load_svmlight_file(path)
        df = pd.DataFrame(X.toarray(), columns=[f"C{i + 1}" for i in range(X.shape[1])])
        df.insert(0, "target", y)
        return df
    # CSV / TSV / txt (+ .gz transparently via pandas)
    sep = sep or _sniff_sep(path)
    if header == 0 and nrows is None:
        from h2o3_tpu import config

        if config.get_bool("H2O3_TPU_NATIVE_PARSE"):
            df = _try_native_csv(path, sep)
            if df is not None:
                return df
    return pd.read_csv(path, sep=sep, header=header, engine="c", nrows=nrows)


def _try_native_csv(path: str, sep: str) -> pd.DataFrame | None:
    """Native chunked-parse fast path (native/fastcsv.cpp via native_csv.py)
    — the ParseDataset tokenizer analog. Returns None whenever the file is
    outside the strict fast path, and the caller uses pandas: eligibility
    is decided from a 2000-row pandas sample so both paths agree on types.

    Known value-semantics deviation (documented): a column whose sampled
    rows are integers narrows to int64 iff the FULL column is NA-free and
    integral-valued — a decimal-formatted integral value ("2.0") past the
    sample keeps it int where pandas would flip the dtype to float. H2O
    types by value, so this is the upstream-faithful choice.
    """
    import gzip
    import io

    from h2o3_tpu import native_csv

    if not native_csv.available():
        return None
    opener = (lambda: gzip.open(path, "rb")) if path.endswith(".gz") else (
        lambda: open(path, "rb")
    )
    try:
        # eligibility from a BOUNDED prefix first — an ineligible multi-GB
        # file must not be slurped (and then re-read by pandas anyway)
        with opener() as f:
            prefix = f.read(4 << 20)
        if len(prefix) == (4 << 20):
            # likely truncated mid-line: drop the partial last line so it
            # cannot poison the dtype sniff
            cut = prefix.rfind(b"\n")
            if cut < 0:
                return None
            prefix = prefix[: cut + 1]
        sample = pd.read_csv(io.BytesIO(prefix), sep=sep, nrows=2000, engine="c")
    except Exception:  # noqa: BLE001 — any sniff trouble means pandas decides
        return None
    names = [str(c) for c in sample.columns]
    if len(set(names)) != len(names):
        return None  # duplicate headers: pandas mangles, we won't guess
    kinds: list[int] = []
    int_named = []
    for c in sample.columns:
        s = sample[c]
        if pd.api.types.is_bool_dtype(s):
            return None  # pandas bool semantics
        if pd.api.types.is_integer_dtype(s):
            kinds.append(0)
            int_named.append(str(c))
        elif pd.api.types.is_float_dtype(s):
            kinds.append(0)
        elif (
            pd.api.types.is_object_dtype(s) or pd.api.types.is_string_dtype(s)
        ) and infer_kind(s) == CAT:
            # string-ish AND sniffed as enum (pandas ≥2 infers 'str' dtype,
            # not object, for string columns)
            kinds.append(1)
        else:
            # datetime / TIME-ish / STR / mixed: pandas semantics
            return None
    try:
        with opener() as f:
            data = f.read()
        df = native_csv.parse_csv_native(data, names, kinds, sep=sep)
    except Exception:  # noqa: BLE001 — ANY native trouble means pandas decides
        return None
    if df is None:
        return None
    for c in int_named:
        v = df[c].to_numpy()
        if np.any(np.abs(v) >= 2**53):
            # f64 already rounded these — only pandas' int64 path is exact
            return None
        if not np.isnan(v).any() and np.all(v == np.floor(v)):
            df[c] = v.astype(np.int64)
    return df


def _sniff_sep(path: str) -> str:
    """Separator guessing on the first lines — ParseSetup's sep sniffing."""
    import gzip

    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt", errors="replace") as f:
        head = [line for _, line in zip(range(5), f)]
    if not head:
        return ","
    best, best_score = ",", -1
    for cand in (",", "\t", ";", "|"):
        counts = [line.count(cand) for line in head]
        score = min(counts) if min(counts) == max(counts) else 0
        if score > best_score:
            best, best_score = cand, score
    return best


def infer_kind(s: pd.Series) -> str:
    """Column type inference — ParseSetup's type-sniffing successor."""
    if pd.api.types.is_bool_dtype(s):
        return CAT
    if pd.api.types.is_datetime64_any_dtype(s):
        return TIME
    if isinstance(s.dtype, pd.CategoricalDtype):
        return CAT
    if pd.api.types.is_integer_dtype(s):
        return INT
    if pd.api.types.is_float_dtype(s):
        return NUM
    # object/string column: enum unless near-unique
    nz = s.dropna()
    if len(nz) == 0:
        return NUM
    # numeric-looking strings parse as numeric (CsvParser type coercion)
    coerced = pd.to_numeric(nz, errors="coerce")
    if coerced.notna().all():
        return NUM
    # date/time-looking strings parse as TIME (ParseSetup sniffs date formats)
    sample = nz.iloc[: 1000].astype(str)
    if sample.str.match(r"^\d{4}-\d{2}-\d{2}([ T].*)?$").all():
        try:
            pd.to_datetime(sample, format="ISO8601")
            return TIME
        except (ValueError, TypeError):
            pass
    nuniq = nz.nunique()
    if nuniq > _MAX_CAT_LEVELS or (len(nz) > 100 and nuniq > _MAX_CAT_FRACTION * len(nz)):
        return STR
    return CAT


def _series_to_host(s: pd.Series, kind: str, name: str):
    """Column → host-side (kind, values, domain, exact_time_copy) WITHOUT
    device placement, so :func:`dataframe_to_vecs` can batch all columns of
    one dtype into a single host→device transfer (a tunneled TPU pays ~66 ms
    per transfer; 28 per-column puts of a 10M-row frame were upload-bound)."""
    if kind == STR:
        vals = s.astype(object).where(s.notna(), None).to_numpy()
        return STR, vals, None, None
    if kind == CAT:
        if isinstance(s.dtype, pd.CategoricalDtype):
            cat = s.cat
            domain = [str(c) for c in cat.categories]
            codes = cat.codes.to_numpy().astype(np.int32)
        else:
            astr = s.astype(object).where(s.notna(), None)
            # H2O interns categorical levels in sorted order [UNVERIFIED]
            levels = sorted({str(v) for v in astr.dropna()})
            lut = {v: i for i, v in enumerate(levels)}
            codes = np.array(
                [lut[str(v)] if v is not None else -1 for v in astr], dtype=np.int32
            )
            domain = levels
        return CAT, codes, domain, None
    if kind == TIME:
        # epoch milliseconds UTC (H2O's time encoding); robust to the series'
        # datetime64 resolution (ns in classic pandas, us/s possible in 2.x)
        # and to timezone-aware inputs
        # errors="coerce": values the sniff sample missed (mixed formats, stray
        # strings past the first 1000 rows) become NA instead of crashing
        if pd.api.types.is_datetime64_any_dtype(s):
            dt = pd.to_datetime(s)
        elif pd.api.types.is_numeric_dtype(s):
            dt = pd.to_datetime(s, unit="ms", errors="coerce")  # epoch-ms input
        else:
            dt = pd.to_datetime(s, errors="coerce", format="ISO8601")
        if getattr(dt.dtype, "tz", None) is not None:
            dt = dt.dt.tz_convert("UTC").dt.tz_localize(None)
        vals = dt.astype("datetime64[ms]").astype("int64").to_numpy().astype(np.float64)
        vals = np.where(dt.isna().to_numpy(), np.nan, vals)
        return TIME, vals, None, np.asarray(vals, dtype=np.float64)
    vals = pd.to_numeric(s, errors="coerce").to_numpy(dtype=np.float64)
    return (INT if kind == INT else NUM), vals, None, None


def dataframe_to_vecs(df: pd.DataFrame, column_types: Mapping[str, str]) -> list[Vec]:
    """Columns → Vecs with BATCHED device placement: all columns of one
    device dtype ride a single host→device transfer and are sliced apart on
    device. Per-column ``device_put`` made a tunneled-TPU 10M×28 upload take
    minutes (one ~66 ms+ transfer per column, each bandwidth-fragmented);
    one (rows, k) matrix per dtype amortizes it to ≤3 transfers total."""
    from h2o3_tpu.parallel.mesh import pad_to_shards, shard_rows

    specs = []
    for name in df.columns:
        kind = column_types.get(str(name)) or infer_kind(df[name])
        if kind in ("numeric", "float", "double"):
            kind = NUM
        if kind in ("factor", "categorical"):
            kind = CAT
        specs.append((str(name), *_series_to_host(df[name], kind, str(name))))

    n = len(df)
    npad = pad_to_shards(n)
    vecs: list[Vec | None] = [None] * len(specs)
    groups: dict = {}  # device dtype -> [spec index]
    for i, (name, kind, arr, domain, exact) in enumerate(specs):
        if kind == STR:
            vecs[i] = Vec(arr, STR, name=name)
        else:
            dt, fill = Vec.device_dtype(kind, domain)
            groups.setdefault(dt.name, (dt, fill, []))[2].append(i)

    from h2o3_tpu.frame import chunkstore as _cs

    seed_mirror = _cs.streaming_enabled()
    for dt, fill, idxs in groups.values():
        mat = np.full((npad, len(idxs)), fill, dtype=dt)
        for j, i in enumerate(idxs):
            mat[:n, j] = specs[i][2].astype(dt, copy=False)
        dmat = shard_rows(mat)  # ONE transfer for the whole dtype group
        # the staging matrix is live device memory no Vec owns yet: claim
        # it in the devmem ledger under 'parse' until the per-column
        # slices (each its own device array) take over as frame_resident
        from h2o3_tpu.utils import devmem as _dm

        _dm.adjust("parse", dmat.nbytes)
        try:
            for j, i in enumerate(idxs):
                name, kind, _arr, domain, exact = specs[i]
                vecs[i] = Vec(dmat[:, j], kind, name=name, domain=domain,
                              nrow=n, host_exact=exact)
                if seed_mirror:
                    # an HBM window is configured: the ingest buffer already
                    # holds the padded column, so seed the spill-tier mirror
                    # now — a streaming build's host_values() then costs
                    # nothing instead of a device pull per column
                    vecs[i]._seed_host_mirror(mat[:, j])
        finally:
            _dm.adjust("parse", -dmat.nbytes)
    return vecs


def parse_setup(path: str, sep: str | None = None) -> dict:
    """Sniff a file — the ``POST /3/ParseSetup`` successor. Returns an
    editable setup dict accepted by :func:`parse`."""
    ext = os.path.splitext(path.removesuffix(".gz"))[1].lower()
    if sep is None and ext not in (".parquet", ".pq", ".orc", ".feather", ".arrow", ".xls", ".xlsx", ".svm", ".svmlight"):
        sep = _sniff_sep(path)
    head = _read_any(path, sep=sep, nrows=10_000)
    return {
        "source_frames": [path],
        "separator": sep or ",",
        "column_names": [str(c) for c in head.columns],
        "column_types": {str(c): infer_kind(head[c]) for c in head.columns},
        "rows_sniffed": len(head),
    }


_STREAM_CHUNK_ROWS = 1_000_000  # size threshold lives in config (H2O3_TPU_STREAM_BYTES)


def _is_csv_like(path: str) -> bool:
    ext = os.path.splitext(path.removesuffix(".gz"))[1].lower()
    return ext not in (
        ".parquet", ".pq", ".orc", ".feather", ".arrow", ".xls", ".xlsx",
        ".svm", ".svmlight",
    )


def parse_stream(
    paths: Sequence[str],
    column_types: Mapping[str, str],
    sep: str | None = None,
    destination_frame: str | None = None,
    chunk_rows: int = _STREAM_CHUNK_ROWS,
) -> Frame:
    """Chunked CSV ingest — the distributed-parse successor for files that
    should not be tokenized in one piece (upstream maps ``parseChunk`` over
    file blocks and unifies categorical domains in a second pass; here the
    chunked reader bounds tokenizer memory, categorical levels intern
    incrementally per chunk, and the cross-chunk code remap at the end is the
    single-process image of that second pass).
    """
    col_order: list[str] | None = None
    kinds: dict[str, str] = {}
    num_parts: dict[str, list[np.ndarray]] = {}
    cat_parts: dict[str, list[np.ndarray]] = {}
    str_parts: dict[str, list[np.ndarray]] = {}
    domains: dict[str, dict[str, int]] = {}
    # column types are fixed by the setup sniff (or the first chunk) — count
    # values later chunks silently coerce to NA so the drift is at least loud
    coerce_losses: dict[str, int] = {}

    for path in paths:
        reader = pd.read_csv(
            path, sep=sep or _sniff_sep(path), engine="c", chunksize=chunk_rows
        )
        for chunk in reader:
            if col_order is None:
                col_order = [str(c) for c in chunk.columns]
                for c in col_order:
                    k = column_types.get(c) or infer_kind(chunk[c])
                    if k in ("numeric", "float", "double"):
                        k = NUM
                    if k in ("factor", "categorical"):
                        k = CAT
                    kinds[c] = k
            for c in col_order:
                s = chunk[c]
                k = kinds[c]
                if k == CAT:
                    # C-speed interning: factorize the chunk, then remap the
                    # (small) chunk-local domain into the global LUT
                    local_codes, local_levels = pd.factorize(
                        s.astype(str).where(s.notna(), None)
                    )
                    lut = domains.setdefault(c, {})
                    remap = np.empty(len(local_levels) + 1, np.int32)
                    for li, lv in enumerate(local_levels):
                        remap[li] = lut.setdefault(str(lv), len(lut))
                    remap[-1] = -1  # factorize encodes NA as -1
                    cat_parts.setdefault(c, []).append(
                        remap[local_codes.astype(np.int64)]
                    )
                elif k == STR:
                    str_parts.setdefault(c, []).append(
                        s.astype(object).where(s.notna(), None).to_numpy()
                    )
                elif k == TIME:
                    dt = pd.to_datetime(s, errors="coerce", format="mixed", utc=True)
                    dt = dt.dt.tz_localize(None)
                    vals = (
                        dt.astype("datetime64[ms]").astype("int64").to_numpy()
                        .astype(np.float64)
                    )
                    vals = np.where(dt.isna().to_numpy(), np.nan, vals)
                    num_parts.setdefault(c, []).append(vals)
                else:
                    vals = pd.to_numeric(s, errors="coerce").to_numpy(np.float64)
                    lost = int((np.isnan(vals) & s.notna().to_numpy()).sum())
                    if lost:
                        coerce_losses[c] = coerce_losses.get(c, 0) + lost
                    num_parts.setdefault(c, []).append(vals)

    assert col_order is not None, "empty parse input"
    for c, lost in coerce_losses.items():
        Log.warn(
            f"stream parse: column {c!r} (typed {kinds[c]} from the sniff) had "
            f"{lost} non-numeric value(s) in later chunks coerced to NA — "
            "pass column_types to override the sniffed type"
        )
    vecs: list[Vec] = []
    for c in col_order:
        k = kinds[c]
        if k == CAT:
            codes = np.concatenate(cat_parts[c])
            # H2O interns levels in sorted order; remap insertion-order codes
            levels_ins = list(domains[c])
            order = sorted(range(len(levels_ins)), key=lambda i: levels_ins[i])
            remap = np.empty(len(levels_ins) + 1, np.int32)
            for new_i, old_i in enumerate(order):
                remap[old_i] = new_i
            remap[-1] = -1  # NA slot
            codes = remap[codes]  # -1 indexes the NA slot
            vecs.append(
                Vec.from_numpy(codes, CAT, name=c,
                               domain=[levels_ins[i] for i in order])
            )
        elif k == STR:
            vecs.append(Vec(np.concatenate(str_parts[c]), STR, name=c))
        else:
            vals = np.concatenate(num_parts[c])
            vecs.append(Vec.from_numpy(vals, INT if k == INT else NUM, name=c))
    fr = Frame(vecs, col_order, key=destination_frame, register=True)
    Log.info(f"Stream-parsed {fr.nrow} rows x {fr.ncol} cols into {fr.key}")
    return fr


def _data_line_offsets(path: str, wanted: set[int]) -> dict[int, int]:
    """Byte offsets where the requested 0-based DATA rows start (header is
    file-line 0). One streaming block scan, O(1) memory."""
    out: dict[int, int] = {}
    if not wanted:
        return out
    remaining = set(wanted)
    line = 0  # completed newlines so far == file-line index about to start
    pos = 0
    with open(path, "rb") as f:
        while remaining:
            block = f.read(1 << 22)
            if not block:
                break
            idx = 0
            while remaining:
                j = block.find(b"\n", idx)
                if j < 0:
                    break
                # data row (line) starts right after file-line `line` ends
                if line in remaining:
                    out[line] = pos + j + 1
                    remaining.discard(line)
                line += 1
                idx = j + 1
            pos += len(block)
    return out


def _read_rank_rows(path, sep, col_order, kinds, lo: int, hi: int, n: int):
    """This rank's data rows [lo, hi) as a DataFrame.

    Fast path: byte-range + native chunk parse. Locating the range is a
    streaming byte scan of the prefix (cheap: no tokenizing, ~GB/s); only
    the rank's own slice is TOKENIZED — the expensive part. The pandas
    ``skiprows`` fallback instead re-tokenizes the whole prefix on every
    rank; it remains the behavior-defining fallback for anything outside
    the native dialect. The caller (parse_sharded) has already rejected
    quoted files, so raw-newline row addressing == record addressing here.
    """
    from h2o3_tpu import config, native_csv

    if (
        hi > lo
        and config.get_bool("H2O3_TPU_NATIVE_PARSE")
        and native_csv.available()
    ):
        try:
            offs = _data_line_offsets(path, ({lo, hi} if hi < n else {lo}))
            start = offs.get(lo)
            end = offs.get(hi, os.path.getsize(path))
            if start is not None:
                with open(path, "rb") as f:
                    f.seek(start)
                    data = f.read(end - start)
                nat_kinds = [1 if kinds[c] == CAT else 0 for c in col_order]
                got = native_csv.parse_csv_native(
                    data, col_order, nat_kinds, sep=sep, has_header=False
                )
                if got is not None and len(got) == hi - lo:
                    return got
        except Exception:  # noqa: BLE001 — ANY native trouble (truncated
            # file mid-flight, decode, ...) must degrade to pandas, not
            # crash one rank and deadlock the others at the allgather
            pass
    return pd.read_csv(
        path, sep=sep,
        skiprows=range(1, lo + 1), nrows=max(hi - lo, 0),
        header=0, names=col_order,
    )


def parse_sharded(
    setup: dict, destination_frame: str | None = None
) -> Frame:
    """Distributed ingest — the ``MultiFileParseTask`` successor proper
    (``water/parser/ParseDataset.java`` [UNVERIFIED], SURVEY §2.1): on a
    multi-process cloud EVERY process parses only ITS OWN row range of the
    source and contributes its local device shards, so no single host ever
    materializes the whole table (Higgs-1B cannot pass through one host's
    pandas). Categorical domains are interned per-rank and unified in a
    second pass (an allgather of the small per-rank level sets), mirroring
    upstream's two-pass domain unification.

    v1 scope: one plain CSV path; numeric / enum / int columns (strings are
    host-resident and would defeat the point; TIME needs exact f64 host
    copies). Runs fine on a single process too (degenerate 1-range case).
    Must execute on every rank (spmd command or replicated section).
    """
    import pickle

    import jax

    from h2o3_tpu.parallel.mesh import get_mesh, pad_to_shards, row_sharding

    paths = setup["source_frames"]
    if len(paths) != 1 or not str(paths[0]).endswith(".csv"):
        raise ValueError("sharded parse v1 handles exactly one plain .csv")
    path = str(paths[0])
    P = jax.process_count()
    r = jax.process_index()

    # row count: one streaming newline scan (O(1) memory, every rank).
    # The SAME pass detects double quotes: a quoted field could hide an
    # embedded newline, which would make this raw-newline row count (and
    # any byte-offset row addressing) disagree with pandas' record
    # semantics — silently, and potentially DIFFERENTLY per rank. v1 scope
    # is plain CSV, so refuse deterministically on every rank instead.
    newlines = 0
    quotes = 0
    last = b"\n"
    with open(path, "rb") as f:
        while True:
            block = f.read(1 << 22)
            if not block:
                break
            newlines += block.count(b"\n")
            quotes += block.count(b'"')
            last = block[-1:]
    if quotes:
        raise ValueError(
            "sharded parse v1 requires unquoted CSV (a quoted field could "
            "embed a newline, breaking row addressing); re-export without "
            "quotes or use the single-host parse"
        )
    total_lines = newlines + (0 if last == b"\n" else 1)
    n = max(total_lines - 1, 0)  # minus header

    # identical sniff on every rank (deterministic kinds)
    sep = setup.get("separator") or _sniff_sep(path)
    sample = pd.read_csv(path, sep=sep, nrows=1000)
    col_order = [str(c) for c in sample.columns]
    ctypes = setup.get("column_types") or {}
    kinds = {}
    for c in col_order:
        k = ctypes.get(c) or infer_kind(sample[c])
        k = {"numeric": NUM, "float": NUM, "double": NUM,
             "factor": CAT, "categorical": CAT}.get(k, k)
        if k in (STR, TIME):
            raise ValueError(
                f"sharded parse v1 does not support {k} column {c!r} "
                "(host-resident / needs exact f64)"
            )
        kinds[c] = k

    npad = pad_to_shards(n)
    from h2o3_tpu.parallel.mesh import get_mesh as _gm

    mesh0 = _gm()
    flat = list(mesh0.devices.flat)
    rows_per_dev = npad // len(flat)
    positions = [i for i, d in enumerate(flat) if d.process_index == r]
    assert positions == list(range(positions[0], positions[-1] + 1)), (
        "sharded parse requires process-contiguous mesh devices"
    )
    per = len(positions) * rows_per_dev  # this rank's row block
    lo = min(positions[0] * rows_per_dev, n)
    hi = min(positions[0] * rows_per_dev + per, n)
    from h2o3_tpu import config as _cfg

    k_ranges = max(_cfg.get_int("H2O3_TPU_INGEST_SHARDS"), 0)
    if P == 1 and k_ranges > 1 and hi > lo:
        # coordinator-free single-process sharded lane (the pod ingest's
        # test/A-B form): split THIS range into k byte ranges, parse each
        # independently through the same byte-range reader a pod rank uses,
        # and concatenate — pinned byte-equal to the one-range parse
        bounds = [lo + (hi - lo) * j // k_ranges for j in range(k_ranges + 1)]
        parts = [
            _read_rank_rows(path, sep, col_order, kinds, a, b, n)
            for a, b in zip(bounds, bounds[1:]) if b > a
        ]
        local = pd.concat(parts, ignore_index=True)
    else:
        local = _read_rank_rows(path, sep, col_order, kinds, lo, hi, n)

    # per-rank categorical interning, then the global union pass
    local_domains: dict[str, list] = {}
    local_codes: dict[str, np.ndarray] = {}
    for c in col_order:
        if kinds[c] == CAT:
            codes, levels = pd.factorize(
                local[c].astype(str).where(local[c].notna(), None)
            )
            local_domains[c] = [str(v) for v in levels]
            local_codes[c] = codes.astype(np.int32)

    if P > 1:
        from jax.experimental import multihost_utils as mh

        raw = pickle.dumps(local_domains)
        cap = 1 << 20
        if len(raw) > cap:
            raise ValueError("sharded parse: categorical domains exceed 1MB")
        buf = np.zeros(cap + 4, np.uint8)
        buf[:4] = np.frombuffer(np.int32(len(raw)).tobytes(), np.uint8)
        buf[4 : 4 + len(raw)] = np.frombuffer(raw, np.uint8)
        gathered = np.asarray(mh.process_allgather(buf))
        all_domains = []
        for row in gathered:
            ln = int(np.frombuffer(row[:4].tobytes(), np.int32)[0])
            all_domains.append(pickle.loads(row[4 : 4 + ln].tobytes()))
    else:
        all_domains = [local_domains]

    union: dict[str, list] = {}
    for doms in all_domains:  # rank order → deterministic union on all ranks
        for c, levels in doms.items():
            seen = union.setdefault(c, [])
            have = set(seen)
            seen.extend(lv for lv in levels if lv not in have)
    for c in union:
        union[c] = sorted(union[c])  # H2O interns levels sorted

    mesh = mesh0
    sh = row_sharding(mesh)
    local_devs = [flat[i] for i in positions]
    dev_rows = rows_per_dev

    def _global_from_local(block: np.ndarray, dtype):
        block = np.asarray(block, dtype)
        parts = [
            jax.device_put(block[i * dev_rows : (i + 1) * dev_rows], d)
            for i, d in enumerate(local_devs)
        ]
        return jax.make_array_from_single_device_arrays((npad,), sh, parts)

    from h2o3_tpu.frame import chunkstore as _cs

    # ChunkStore lane: on a single process the local block IS the whole
    # padded column, so an out-of-core config (HBM window set) adopts it as
    # the spill-tier host mirror — a streaming build's host_values() then
    # costs nothing instead of a device pull per column. Multi-process
    # ranks hold only their slice; mirrors stay lazy there (documented).
    seed_mirror = P == 1 and _cs.streaming_enabled()
    vecs: list[Vec] = []
    for c in col_order:
        k = kinds[c]
        if k == CAT:
            lut = {lv: i for i, lv in enumerate(union[c])}
            # same narrowest-dtype rule as Vec.from_numpy so single- and
            # multi-process clouds store identical dtypes for the same data
            card = len(union[c])
            dt = np.int8 if card <= 127 else np.int16 if card <= 32767 else np.int32
            remap = np.array([lut[lv] for lv in local_domains[c]] or [0], dt)
            codes = np.full(per, -1, dt)
            lc = local_codes[c]
            codes[: len(lc)] = np.where(lc >= 0, remap[np.clip(lc, 0, None)], -1)
            data = _global_from_local(codes, dt)
            v = Vec(data, CAT, name=c, domain=tuple(union[c]), nrow=n)
            if seed_mirror:
                v._seed_host_mirror(codes)
            vecs.append(v)
        else:
            vals = np.full(per, np.nan, np.float32)
            got = pd.to_numeric(local[c], errors="coerce").to_numpy(np.float32)
            vals[: len(got)] = got
            data = _global_from_local(vals, np.float32)
            v = Vec(data, INT if k == INT else NUM, name=c, nrow=n)
            if seed_mirror:
                v._seed_host_mirror(vals)
            vecs.append(v)

    fr = Frame(vecs, col_order, key=destination_frame, register=True)
    Log.info(
        f"Shard-parsed {fr.nrow} rows x {fr.ncol} cols into {fr.key} "
        f"(rank {r}/{P} read rows [{lo}, {hi}))"
    )
    return fr


def parse(setup: dict, destination_frame: str | None = None) -> Frame:
    """Materialize a frame from a setup dict — the ``POST /3/Parse`` successor.

    Large CSV sources (or ``setup["stream"]=True``) take the chunked
    streaming path; everything else reads eagerly.
    """
    paths = setup["source_frames"]
    want_stream = bool(setup.get("stream"))
    if not want_stream and all(_is_csv_like(p) for p in paths):
        from h2o3_tpu import config

        try:
            total = sum(os.path.getsize(p) for p in paths)
            want_stream = total > config.get_int("H2O3_TPU_STREAM_BYTES")
        except OSError:
            pass
    if want_stream and all(_is_csv_like(p) for p in paths):
        return parse_stream(
            paths, setup.get("column_types") or {},
            sep=setup.get("separator"), destination_frame=destination_frame,
        )
    dfs = [_read_any(p, sep=setup.get("separator")) for p in paths]
    df = pd.concat(dfs, ignore_index=True) if len(dfs) > 1 else dfs[0]
    fr = Frame.from_pandas(
        df,
        destination_frame=destination_frame,
        column_types=setup.get("column_types"),
        register=True,
    )
    Log.info(f"Parsed {fr.nrow} rows x {fr.ncol} cols into {fr.key}")
    return fr


def import_file(
    path: str,
    destination_frame: str | None = None,
    col_types: Mapping[str, str] | None = None,
    sep: str | None = None,
    lazy: bool = False,
) -> Frame:
    """``h2o.import_file`` successor: sniff + parse in one call.

    ``lazy=True`` defers each column's device materialization to first
    touch (the FileVec successor — see frame/lazy.py).
    """
    if lazy:
        from h2o3_tpu.frame.lazy import import_file_lazy

        return import_file_lazy(
            path, destination_frame=destination_frame, col_types=col_types,
            sep=sep,
        )
    setup = parse_setup(path, sep=sep)
    if col_types:
        setup["column_types"].update(col_types)
    return parse(setup, destination_frame=destination_frame)


def upload_file(
    data: "str | pd.DataFrame | Mapping[str, Sequence]",
    destination_frame: str | None = None,
    col_types: Mapping[str, str] | None = None,
) -> Frame:
    """``h2o.upload_file`` successor; also accepts in-memory tabular data
    (the ``h2o.H2OFrame(python_obj)`` path)."""
    if isinstance(data, str):
        return import_file(data, destination_frame, col_types)
    df = data if isinstance(data, pd.DataFrame) else pd.DataFrame(data)
    return Frame.from_pandas(df, destination_frame, col_types or {}, register=True)
