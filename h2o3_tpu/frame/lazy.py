"""Vec flavors — successor of the upstream Vec zoo (``FileVec`` lazy
file-backed columns, ``CategoricalWrappedVec`` domain-remap views)
[UNVERIFIED upstream paths, SURVEY.md §2.1].

Upstream keeps cold columns on disk and materializes chunks on demand, and
wraps categorical vecs in remap views instead of rewriting codes. The TPU
analogs:

- :class:`LazyVec` — a column whose HBM materialization is deferred to
  first ``.data`` touch: the loader (a column read of the source file) runs
  once, pads, shards, caches. A wide file imported with ``lazy=True`` only
  ships the columns a model actually uses to the device — HBM is the scarce
  resource the upstream FileVec design protects on the JVM heap.
- :class:`WrappedCatVec` — a categorical remap view: shares the base vec's
  device codes and applies the (tiny) old→new code LUT lazily as one device
  gather on first touch, instead of rewriting the column eagerly.

Construction: ``h2o3_tpu.import_file(path, lazy=True)`` (CSV/Parquet).
"""

from __future__ import annotations

from typing import Callable

import numpy as np

from h2o3_tpu.frame.frame import CAT, STR, TIME, Frame, Vec
from h2o3_tpu.parallel.mesh import pad_to_shards


class LazyVec(Vec):
    """File-backed column; device materialization deferred to first touch."""

    def __init__(self, loader: Callable[[], np.ndarray], kind: str,
                 name: str, nrow: int, domain=None):
        # deliberately NOT calling Vec.__init__: `data` is a property here
        self.kind = kind
        self.name = name
        self.domain = tuple(domain) if domain is not None else None
        self.nrow = nrow
        self._loader = loader
        self._vec: Vec | None = None
        self._stats = None

    def _materialize(self) -> Vec:
        if self._vec is None:
            arr = self._loader()
            assert len(arr) == self.nrow, (
                f"lazy column {self.name!r}: loader returned {len(arr)} rows, "
                f"expected {self.nrow}"
            )
            if self.kind == CAT and self.domain is None:
                # intern now (sorted order, like the eager parser)
                vals = np.asarray(arr, dtype=object)
                levels = sorted({str(v) for v in vals if v is not None
                                 and v == v})
                lut = {v: i for i, v in enumerate(levels)}
                codes = np.asarray(
                    [lut.get(str(v), -1) if v is not None and v == v else -1
                     for v in vals], np.int32,
                )
                self.domain = tuple(levels)
                arr = codes
            self._vec = Vec.from_numpy(
                np.asarray(arr), self.kind, name=self.name, domain=self.domain
            )
            self._stats = None
            self._loader = None  # release the closure (may pin file handles)
        return self._vec

    # -- deferred surfaces ---------------------------------------------------
    @property
    def data(self):
        return self._materialize().data

    @data.setter
    def data(self, v) -> None:  # some internal paths assign; force through
        self._materialize().data = v

    @property
    def _host(self):
        return self._materialize()._host

    @_host.setter
    def _host(self, v) -> None:
        self._materialize()._host = v

    @property
    def npad(self) -> int:
        return pad_to_shards(self.nrow)

    @property
    def cardinality(self) -> int:
        if self.kind == CAT and self.domain is None:
            self._materialize()
        return len(self.domain) if self.domain else -1

    def levels(self) -> list[str]:
        if self.kind == CAT and self.domain is None:
            self._materialize()
        return list(self.domain) if self.domain else []

    @property
    def is_materialized(self) -> bool:
        return self._vec is not None

    def stats(self) -> dict:
        self._materialize()
        return super().stats()


class WrappedCatVec(Vec):
    """Domain-remap view over a categorical base vec (no eager rewrite)."""

    def __init__(self, base: Vec, new_domain, old_to_new: np.ndarray):
        assert base.is_categorical()
        self.kind = CAT
        self.name = base.name
        self.domain = tuple(new_domain)
        self.nrow = base.nrow
        self._base = base
        self._lut = np.asarray(old_to_new, np.int32)  # old code -> new code
        self._data = None
        self._stats = None

    @property
    def data(self):
        if self._data is None:
            import jax.numpy as jnp

            lut = jnp.asarray(np.append(self._lut, -1))  # -1 slot for NA
            self._data = lut[self._base.data]  # one device gather
        return self._data

    @data.setter
    def data(self, v) -> None:
        self._data = v

    @property
    def _host(self):
        return None

    @_host.setter
    def _host(self, v) -> None:
        pass

    @property
    def npad(self) -> int:
        return self._base.npad


def wrap_domain(base: Vec, new_domain) -> WrappedCatVec:
    """Remap a categorical vec onto ``new_domain`` as a lazy view (the
    CategoricalWrappedVec use case: aligning a test frame's levels to a
    train-time domain without rewriting the column)."""
    new_domain = list(new_domain)
    idx = {d: i for i, d in enumerate(new_domain)}
    old = list(base.domain or ())
    lut = np.asarray([idx.get(d, -1) for d in old], np.int32)
    return WrappedCatVec(base, new_domain, lut)


def import_file_lazy(
    path: str,
    destination_frame: str | None = None,
    col_types=None,
    sep: str | None = None,
) -> Frame:
    """``h2o.import_file(..., lazy=True)``: columns load on first touch."""
    import pandas as pd

    from h2o3_tpu.frame.parse import _read_any, infer_kind, parse_setup

    ext = path.removesuffix(".gz").rsplit(".", 1)[-1].lower()
    setup = parse_setup(path, sep=sep)
    types = dict(setup["column_types"])
    if col_types:
        types.update(col_types)
    names = setup["column_names"]

    # one cheap row-count pass (no tokenization of field contents)
    if ext in ("parquet", "pq"):
        import pyarrow.parquet as pq

        nrow = pq.ParquetFile(path).metadata.num_rows

        def make_loader(col: str, kind: str):
            def load():
                s = pd.read_parquet(path, columns=[col])[col]
                return _series_values(s, kind)

            return load
    else:
        # count rows the way pandas will parse them (quoted newlines, blank
        # trailing lines): tokenize once materializing only the first column.
        # Numeric first columns are cheap (8 B/row) — keep them to seed the
        # loader so the scan isn't wasted; object/string columns could pin
        # GBs for a column nobody may touch, so those are discarded.
        first_series = pd.read_csv(
            path, sep=setup.get("separator"), usecols=[names[0]], engine="c"
        )[names[0]]
        nrow = len(first_series)
        if not pd.api.types.is_numeric_dtype(first_series):
            first_series = None

        def make_loader(col: str, kind: str):
            if col == names[0] and first_series is not None:
                def load_first():
                    return _series_values(first_series, kind)

                return load_first

            def load():
                # usecols: the tokenizer still scans the file but only ONE
                # column's values are materialized (memory stays bounded)
                s = pd.read_csv(
                    path, sep=setup.get("separator"), usecols=[col],
                    engine="c",
                )[col]
                return _series_values(s, kind)

            return load

    vecs = []
    for name in names:
        kind = types.get(name, "real")
        kind = {"numeric": "real", "float": "real", "double": "real",
                "factor": "enum", "categorical": "enum"}.get(kind, kind)
        vecs.append(LazyVec(make_loader(name, kind), kind, name, nrow))
    return Frame(vecs, list(names), key=destination_frame, register=True)


def _series_values(s, kind: str) -> np.ndarray:
    import pandas as pd

    if kind == STR:
        return s.astype(object).where(s.notna(), None).to_numpy()
    if kind == CAT:
        return s.astype(object).where(s.notna(), None).to_numpy()
    if kind == TIME:
        dt = pd.to_datetime(s, errors="coerce", format="mixed", utc=True)
        dt = dt.dt.tz_localize(None)
        vals = dt.astype("datetime64[ms]").astype("int64").to_numpy().astype(np.float64)
        return np.where(dt.isna().to_numpy(), np.nan, vals)
    return pd.to_numeric(s, errors="coerce").to_numpy(np.float64)