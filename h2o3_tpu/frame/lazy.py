"""Vec flavors — successor of the upstream Vec zoo (``FileVec`` lazy
file-backed columns, ``CategoricalWrappedVec`` domain-remap views)
[UNVERIFIED upstream paths, SURVEY.md §2.1].

Upstream keeps cold columns on disk and materializes chunks on demand, and
wraps categorical vecs in remap views instead of rewriting codes. The TPU
analogs:

- :class:`LazyVec` — a column whose HBM materialization is deferred to
  first ``.data`` touch: the loader (a column read of the source file) runs
  once, pads, shards, caches. A wide file imported with ``lazy=True`` only
  ships the columns a model actually uses to the device — HBM is the scarce
  resource the upstream FileVec design protects on the JVM heap.
- :class:`WrappedCatVec` — a categorical remap view: shares the base vec's
  device codes and applies the (tiny) old→new code LUT lazily as one device
  gather on first touch, instead of rewriting the column eagerly.
- :class:`LazyExprVec` (ISSUE 20) — a column DEFINED by an elementwise
  expression graph instead of a loader: ``frame/ops.py`` binops/unops/
  ``ifelse`` under ``H2O3_TPU_MUNGE_FUSE`` return one of these, composing
  operand graphs, so a 10-op rapids chain materializes as ONE fused jitted
  dispatch (``munge_dispatches_total{op=expr_fuse}``) instead of ten eager
  kernels — the Rapids AST walk finally compiling the way H2O's hand-built
  AST nodes fused MRTask passes. When a ChunkStore window is configured the
  materialization streams leaf blocks through it (the PR-11 residency fix:
  no full device columns are pulled) and the result parks host-resident.

Construction: ``h2o3_tpu.import_file(path, lazy=True)`` (CSV/Parquet).
"""

from __future__ import annotations

from typing import Callable

import numpy as np

from h2o3_tpu.frame.frame import CAT, STR, TIME, Frame, Vec
from h2o3_tpu.parallel.mesh import pad_to_shards


class LazyVec(Vec):
    """File-backed column; device materialization deferred to first touch."""

    def __init__(self, loader: Callable[[], np.ndarray], kind: str,
                 name: str, nrow: int, domain=None):
        # deliberately NOT calling Vec.__init__: `data` is a property here
        self.kind = kind
        self.name = name
        self.domain = tuple(domain) if domain is not None else None
        self.nrow = nrow
        self._loader = loader
        self._vec: Vec | None = None
        self._stats = None

    def _materialize(self) -> Vec:
        if self._vec is None:
            arr = self._loader()
            assert len(arr) == self.nrow, (
                f"lazy column {self.name!r}: loader returned {len(arr)} rows, "
                f"expected {self.nrow}"
            )
            if self.kind == CAT and self.domain is None:
                # intern now (sorted order, like the eager parser)
                vals = np.asarray(arr, dtype=object)
                levels = sorted({str(v) for v in vals if v is not None
                                 and v == v})
                lut = {v: i for i, v in enumerate(levels)}
                codes = np.asarray(
                    [lut.get(str(v), -1) if v is not None and v == v else -1
                     for v in vals], np.int32,
                )
                self.domain = tuple(levels)
                arr = codes
            self._vec = Vec.from_numpy(
                np.asarray(arr), self.kind, name=self.name, domain=self.domain
            )
            self._stats = None
            self._loader = None  # release the closure (may pin file handles)
        return self._vec

    # -- deferred surfaces ---------------------------------------------------
    @property
    def data(self):
        return self._materialize().data

    @data.setter
    def data(self, v) -> None:  # some internal paths assign; force through
        self._materialize().data = v

    @property
    def _host(self):
        return self._materialize()._host

    @_host.setter
    def _host(self, v) -> None:
        self._materialize()._host = v

    @property
    def npad(self) -> int:
        return pad_to_shards(self.nrow)

    @property
    def cardinality(self) -> int:
        if self.kind == CAT and self.domain is None:
            self._materialize()
        return len(self.domain) if self.domain else -1

    def levels(self) -> list[str]:
        if self.kind == CAT and self.domain is None:
            self._materialize()
        return list(self.domain) if self.domain else []

    @property
    def is_materialized(self) -> bool:
        return self._vec is not None

    def stats(self) -> dict:
        self._materialize()
        return super().stats()


class WrappedCatVec(Vec):
    """Domain-remap view over a categorical base vec (no eager rewrite)."""

    def __init__(self, base: Vec, new_domain, old_to_new: np.ndarray):
        assert base.is_categorical()
        self.kind = CAT
        self.name = base.name
        self.domain = tuple(new_domain)
        self.nrow = base.nrow
        self._base = base
        self._lut = np.asarray(old_to_new, np.int32)  # old code -> new code
        self._data = None
        self._stats = None

    @property
    def data(self):
        if self._data is None:
            import jax.numpy as jnp

            lut = jnp.asarray(np.append(self._lut, -1))  # -1 slot for NA
            self._data = lut[self._base.data]  # one device gather
        return self._data

    @data.setter
    def data(self, v) -> None:
        self._data = v

    @property
    def _host(self):
        return None

    @_host.setter
    def _host(self, v) -> None:
        pass

    @property
    def npad(self) -> int:
        return self._base.npad


def wrap_domain(base: Vec, new_domain) -> WrappedCatVec:
    """Remap a categorical vec onto ``new_domain`` as a lazy view (the
    CategoricalWrappedVec use case: aligning a test frame's levels to a
    train-time domain without rewriting the column)."""
    new_domain = list(new_domain)
    idx = {d: i for i, d in enumerate(new_domain)}
    old = list(base.domain or ())
    lut = np.asarray([idx.get(d, -1) for d in old], np.int32)
    return WrappedCatVec(base, new_domain, lut)


def import_file_lazy(
    path: str,
    destination_frame: str | None = None,
    col_types=None,
    sep: str | None = None,
) -> Frame:
    """``h2o.import_file(..., lazy=True)``: columns load on first touch."""
    import pandas as pd

    from h2o3_tpu.frame.parse import _read_any, infer_kind, parse_setup

    ext = path.removesuffix(".gz").rsplit(".", 1)[-1].lower()
    setup = parse_setup(path, sep=sep)
    types = dict(setup["column_types"])
    if col_types:
        types.update(col_types)
    names = setup["column_names"]

    # one cheap row-count pass (no tokenization of field contents)
    if ext in ("parquet", "pq"):
        import pyarrow.parquet as pq

        nrow = pq.ParquetFile(path).metadata.num_rows

        def make_loader(col: str, kind: str):
            def load():
                s = pd.read_parquet(path, columns=[col])[col]
                return _series_values(s, kind)

            return load
    else:
        # count rows the way pandas will parse them (quoted newlines, blank
        # trailing lines): tokenize once materializing only the first column.
        # Numeric first columns are cheap (8 B/row) — keep them to seed the
        # loader so the scan isn't wasted; object/string columns could pin
        # GBs for a column nobody may touch, so those are discarded.
        first_series = pd.read_csv(
            path, sep=setup.get("separator"), usecols=[names[0]], engine="c"
        )[names[0]]
        nrow = len(first_series)
        if not pd.api.types.is_numeric_dtype(first_series):
            first_series = None

        def make_loader(col: str, kind: str):
            if col == names[0] and first_series is not None:
                def load_first():
                    return _series_values(first_series, kind)

                return load_first

            def load():
                # usecols: the tokenizer still scans the file but only ONE
                # column's values are materialized (memory stays bounded)
                s = pd.read_csv(
                    path, sep=setup.get("separator"), usecols=[col],
                    engine="c",
                )[col]
                return _series_values(s, kind)

            return load

    vecs = []
    for name in names:
        kind = types.get(name, "real")
        kind = {"numeric": "real", "float": "real", "double": "real",
                "factor": "enum", "categorical": "enum"}.get(kind, kind)
        vecs.append(LazyVec(make_loader(name, kind), kind, name, nrow))
    return Frame(vecs, list(names), key=destination_frame, register=True)


def _series_values(s, kind: str) -> np.ndarray:
    import pandas as pd

    if kind == STR:
        return s.astype(object).where(s.notna(), None).to_numpy()
    if kind == CAT:
        return s.astype(object).where(s.notna(), None).to_numpy()
    if kind == TIME:
        dt = pd.to_datetime(s, errors="coerce", format="mixed", utc=True)
        dt = dt.dt.tz_localize(None)
        vals = dt.astype("datetime64[ms]").astype("int64").to_numpy().astype(np.float64)
        return np.where(dt.isna().to_numpy(), np.nan, vals)
    return pd.to_numeric(s, errors="coerce").to_numpy(np.float64)

# ---------------------------------------------------------------------------
# Expression fusion (ISSUE 20): deferred elementwise graphs
# ---------------------------------------------------------------------------
#
# Node grammar (hashable tuples — the tuple IS the fused-program cache key):
#
#   ("leaf", i, is_cat)   i-th entry of ``_leaves``; CAT leaves apply the
#                         eager ``_codes_as_float`` NA cast inline
#   ("const", ci)         ci-th scalar, passed as a TRACED f32 argument so
#                         ``col + 1`` and ``col + 2`` share one compilation
#   ("bin", op, l, r)     ``frame/ops._BINOPS[op]`` + the ``_PRESERVE_NAN``
#                         NaN-reinsert rule + the trailing f32 cast
#   ("un", op, a)         ``frame/ops._UNOPS[op]`` + the "not" NaN rule
#   ("sel", t, y, n)      ifelse: where(t != 0, y, n), NaN where t is NaN
#
# Per-node evaluation calls the SAME jnp tables the eager kernels use and
# keeps the per-op f32 cast, so a fused chain is bit-identical to running
# the eager kernels back to back — tests/test_munge_fused.py pins it.

_EXPR_PROGS: dict = {}
_MAX_EXPR_NODES = 256  # beyond this, operands enter as materialized leaves


def _node_count(node) -> int:
    """Number of OPERATION nodes (bin/un/sel) in the graph."""
    tag = node[0]
    if tag == "bin":
        return 1 + _node_count(node[2]) + _node_count(node[3])
    if tag == "un":
        return 1 + _node_count(node[2])
    if tag == "sel":
        return 1 + sum(_node_count(c) for c in node[1:])
    return 0


def _eval_node(node, leaves, consts, one):
    import jax.numpy as jnp

    from h2o3_tpu.frame import ops as _ops

    tag = node[0]
    if tag == "leaf":
        x = leaves[node[1]]
        if node[2]:  # enum codes → float with NA (-1 → NaN), as _as_device
            return jnp.where(x < 0, jnp.nan, x.astype(jnp.float32))
        return x
    if tag == "const":
        return consts[node[1]]
    if tag == "bin":
        a = _eval_node(node[2], leaves, consts, one)
        b = _eval_node(node[3], leaves, consts, one)
        out = _ops._BINOPS[node[1]](a, b)
        if node[1] in _ops._PRESERVE_NAN:
            out = jnp.where(jnp.isnan(a) | jnp.isnan(b), jnp.nan, out)
        out = out.astype(jnp.float32)
        if node[1] == "*":
            # ``one`` is a RUNTIME 1.0: multiplying by it is a bitwise
            # identity the compiler cannot fold away, and its own FMA
            # contraction fma(t, 1, c) == t + c exactly. Without it LLVM
            # contracts this product into a consumer add (fused programs
            # only — eager kernels have a program boundary there), and the
            # fused chain would drift a ulp from the eager chain.
            out = out * one
        return out
    if tag == "un":
        a = _eval_node(node[2], leaves, consts, one)
        out = _ops._UNOPS[node[1]](a)
        if node[1] == "not":
            out = jnp.where(jnp.isnan(a), jnp.nan, out)
        return out.astype(jnp.float32)
    # "sel"
    t = _eval_node(node[1], leaves, consts, one)
    y = _eval_node(node[2], leaves, consts, one)
    n = _eval_node(node[3], leaves, consts, one)
    out = jnp.where(t != 0, y, n)
    return jnp.where(jnp.isnan(t), jnp.nan, out).astype(jnp.float32)


def _expr_program(struct):
    from h2o3_tpu.parallel.mesh import mesh_key

    key = (struct, mesh_key())
    prog = _EXPR_PROGS.get(key)
    if prog is None:
        import jax

        def run(leaves, consts, one):
            return _eval_node(struct, leaves, consts, one)

        prog = jax.jit(run)
        _EXPR_PROGS[key] = prog
    return prog


class LazyExprVec(Vec):
    """Deferred elementwise expression column (``H2O3_TPU_MUNGE_FUSE=1``).

    Holds the node graph plus references to its leaf Vecs; the fused jitted
    program runs once on first touch (``munge_dispatches_total{op=expr_fuse}``)
    — or streams leaf blocks through the ChunkStore window when one is
    configured, parking the result host-resident (``op=expr_stream``).
    """

    def __init__(self, node, leaves, consts, nrow: int, name: str = ""):
        # deliberately NOT calling Vec.__init__ (the LazyVec pattern):
        # `data`/`_host` are forwarding properties here
        self.kind = "real"
        self.name = name
        self.domain = None
        self.nrow = int(nrow)
        self._node = node
        self._leaves = list(leaves)
        self._consts = [float(c) for c in consts]
        self._vec: Vec | None = None
        self._stats = None

    def _materialize(self) -> Vec:
        if self._vec is None:
            self._vec = _materialize_expr(self)
            self._leaves = None  # release operand refs (may pin big columns)
            self._stats = None
        return self._vec

    # -- deferred surfaces ---------------------------------------------------
    @property
    def data(self):
        return self._materialize().data

    @data.setter
    def data(self, v) -> None:
        self._materialize().data = v

    @property
    def _host(self):
        return self._materialize()._host

    @_host.setter
    def _host(self, v) -> None:
        self._materialize()._host = v

    @property
    def npad(self) -> int:
        return pad_to_shards(self.nrow)

    @property
    def is_materialized(self) -> bool:
        return self._vec is not None

    def to_numpy(self) -> np.ndarray:
        return self._materialize().to_numpy()

    def host_values(self) -> np.ndarray:
        return self._materialize().host_values()

    def release_device(self):
        if self._vec is not None:
            return self._vec.release_device()
        return 0

    def stats(self) -> dict:
        self._materialize()
        return super().stats()


def _materialize_expr(lv: "LazyExprVec") -> Vec:
    from h2o3_tpu.frame import chunkstore as _cs
    from h2o3_tpu.frame import munge as _mg

    if _cs.streaming_enabled():
        out = _materialize_expr_streamed(lv)
        if out is not None:
            return out
    prog = _expr_program(lv._node)
    leaf_data = tuple(v.data for v in lv._leaves)
    consts = tuple(np.float32(c) for c in lv._consts)
    dev = _mg.run_munge(
        "expr_fuse", prog, (leaf_data, consts, np.float32(1.0)),
        ops=_node_count(lv._node), leaves=len(leaf_data),
    )
    return Vec(dev, "real", name=lv.name, nrow=lv.nrow)


def _materialize_expr_streamed(lv: "LazyExprVec") -> Vec | None:
    """Out-of-core materialization: leaf host mirrors stream through the
    ChunkStore window block by block (the PR-11 residency fix — no full
    device columns are pulled), transient result blocks are accounted to
    ``hbm_owned_bytes{owner=munge}``, and the result parks host-resident.
    Returns None when the planner says the frame fits resident."""
    from h2o3_tpu.frame import chunkstore as _cs
    from h2o3_tpu.frame import munge as _mg
    from h2o3_tpu.utils import devmem as _dm
    from h2o3_tpu.utils import jobacct as _ja
    from h2o3_tpu.utils.metrics import current_trace

    C = len(lv._leaves)
    npad = pad_to_shards(lv.nrow)
    store = _cs.ChunkStore.plan(npad, 4.0 * (C + 1))
    if store is None:
        return None
    try:
        names = []
        for i, v in enumerate(lv._leaves):
            buf = np.asarray(v.host_values())
            if buf.shape[0] != npad:  # mesh changed under the mirror
                return None
            store.add(f"l{i}", buf)
            names.append(f"l{i}")
        prog = _expr_program(lv._node)
        consts = tuple(np.float32(c) for c in lv._consts)
        outbuf = np.empty(npad, np.float32)

        def _run():
            for bi, blk in store.stream(names):
                lo, hi = store.span(bi)
                part = prog(tuple(blk[f"l{i}"] for i in range(C)), consts,
                            np.float32(1.0))
                _dm.adjust("munge", float(part.nbytes))
                try:
                    outbuf[lo:hi] = np.asarray(part)
                finally:
                    _dm.adjust("munge", -float(part.nbytes))

        _mg.run_munge("expr_stream", _run,
                      ops=_node_count(lv._node), blocks=store.n_blocks)
        _ja.on_window_bytes(current_trace(), store.peak_hbm)
    finally:
        store.close()
    out = Vec(None, "real", name=lv.name, nrow=lv.nrow)
    out._seed_host_mirror(outbuf)
    return out


# -- graph builders (called from frame/ops.py under fuse_on()) ---------------

def fusible_operand(x) -> bool:
    """Can ``x`` enter a fused graph? Mirrors ``_as_device``'s accepted
    operand set minus strings (which raise there too) — Frames are
    normalized to their single Vec by the caller."""
    if isinstance(x, Vec):
        return x.kind != STR
    return isinstance(x, (bool, int, float, np.integer, np.floating, np.bool_))


def _as_node(x, leaves, consts, leaf_ids, nrow):
    if isinstance(x, Vec):
        if (isinstance(x, LazyExprVec) and x._vec is None
                and _node_count(x._node) < _MAX_EXPR_NODES):
            assert x.nrow == nrow, "operand row counts differ"
            return _splice(x._node, x, leaves, consts, leaf_ids)
        assert x.nrow == nrow, "operand row counts differ"
        key = id(x)
        if key not in leaf_ids:
            leaf_ids[key] = len(leaves)
            leaves.append(x)
        return ("leaf", leaf_ids[key], x.kind == CAT)
    ci = len(consts)
    consts.append(float(x))
    return ("const", ci)


def _splice(node, src, leaves, consts, leaf_ids):
    """Graft ``src``'s graph into a new builder, remapping leaf/const slots
    (leaves dedup by identity so a column shared across operands ships once)."""
    tag = node[0]
    if tag == "leaf":
        v = src._leaves[node[1]]
        key = id(v)
        if key not in leaf_ids:
            leaf_ids[key] = len(leaves)
            leaves.append(v)
        return ("leaf", leaf_ids[key], node[2])
    if tag == "const":
        consts.append(src._consts[node[1]])
        return ("const", len(consts) - 1)
    if tag == "bin":
        return ("bin", node[1],
                _splice(node[2], src, leaves, consts, leaf_ids),
                _splice(node[3], src, leaves, consts, leaf_ids))
    if tag == "un":
        return ("un", node[1],
                _splice(node[2], src, leaves, consts, leaf_ids))
    return ("sel",) + tuple(_splice(c, src, leaves, consts, leaf_ids)
                            for c in node[1:])


def defer_binop(a: Vec, b, op: str, reflected: bool = False) -> LazyExprVec:
    leaves, consts, lid = [], [], {}
    na = _as_node(a, leaves, consts, lid, a.nrow)
    nb = _as_node(b, leaves, consts, lid, a.nrow)
    if reflected:
        na, nb = nb, na
    return LazyExprVec(("bin", op, na, nb), leaves, consts, a.nrow)


def defer_unop(a: Vec, op: str) -> LazyExprVec:
    leaves, consts, lid = [], [], {}
    na = _as_node(a, leaves, consts, lid, a.nrow)
    return LazyExprVec(("un", op, na), leaves, consts, a.nrow)


def defer_ifelse(test: Vec, yes, no) -> LazyExprVec:
    leaves, consts, lid = [], [], {}
    nt = _as_node(test, leaves, consts, lid, test.nrow)
    ny = _as_node(yes, leaves, consts, lid, test.nrow)
    nn = _as_node(no, leaves, consts, lid, test.nrow)
    return LazyExprVec(("sel", nt, ny, nn), leaves, consts, test.nrow)
