"""Distributed columnar Frame — successor of ``water.fvec.Frame`` / ``Vec`` /
``Chunk`` [UNVERIFIED upstream paths, SURVEY.md §0].

Design mapping (SURVEY.md §7 step 1):

- H2O ``Vec`` = one distributed column split into compressed ``Chunk``s homed
  across nodes → here one ``jax.Array`` sharded along the ``"rows"`` mesh
  axis. Chunk *alignment* (chunk *i* of every Vec on the same node) becomes
  *identical sharding* of every column — row-local compute by construction.
- H2O's chunk-compression zoo (``C1SChunk``…) existed to fit heaps and
  starve no core; on TPU the equivalents are narrow dtypes: numerics are
  ``float32`` (``bfloat16`` inside matmul kernels), categoricals ``int32``
  codes, booleans ``bool``. Binned tree features use ``uint8``/``int32``
  (:mod:`h2o3_tpu.models.tree.binning`), which is where C1Chunk-style 1-byte
  compression actually pays on device.
- Missing values: ``NaN`` for numerics, code ``-1`` for categoricals — H2O
  uses NA sentinels per chunk type.
- Rows are padded to a multiple of (shards × 8); padding is ``NaN``/``-1`` so
  NA-aware reductions ignore it, and :meth:`Frame.row_mask` gives an explicit
  validity mask for kernels that need one.
- String columns stay host-side (numpy object arrays) — SURVEY.md §7 "keep
  string ops host-side, don't chase CStrChunk on device".
"""

from __future__ import annotations

import math
from functools import partial
from typing import Iterable, Mapping, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import pandas as pd

from h2o3_tpu.cluster.registry import DKV
from h2o3_tpu.parallel.mesh import pad_to_shards, row_sharding, shard_rows

NUM, CAT, STR, TIME = "real", "enum", "string", "time"
INT = "int"  # integral-valued numeric; stored like NUM but reported as int


def _vec_gc(acct: dict) -> None:
    """weakref.finalize hook: return a dead Vec's remaining accounted bytes
    to the two-tier residency gauge (frame/chunkstore.py)."""
    try:
        from h2o3_tpu.frame import chunkstore as _cs

        for tier, amt in acct.items():
            if amt:
                _cs.account(tier, -amt)
                acct[tier] = 0.0
    except Exception:  # noqa: BLE001 — interpreter teardown must stay quiet
        pass


class Vec:
    """One column. Device-resident for num/cat/time; host-resident for str.

    TIME columns additionally keep an exact float64 epoch-millisecond copy on
    the host (``_host``): the device array is float32 (fine for model math,
    like H2O treating time as numeric), but f32 quantizes epoch-ms to ~2-minute
    steps, so materialization/round-trips use the exact copy.

    Two-tier residency (the out-of-core data plane, frame/chunkstore.py):
    ``data`` is a property over ``_data``. :meth:`release_device` parks the
    padded values as a host mirror (``_hostbuf``) and drops the device
    array; the property rebuilds it lazily — bit-identical, a device_get/
    device_put round trip of the same dtype — on next touch. Both tiers are
    accounted in the ``frame_bytes_resident{tier=hbm|host}`` gauge, and a
    finalizer returns a collected Vec's bytes so the gauge tracks LIVE
    residency, not cumulative traffic.
    """

    # class-level defaults so the Vec flavors that skip __init__ (LazyVec,
    # WrappedCatVec — frame/lazy.py) inherit working tier methods with
    # accounting as a no-op. _epoch None = "unmanaged": elastic re-sharding
    # (ISSUE 17) only applies to Vecs that recorded the topology epoch they
    # were padded under.
    _hostbuf: np.ndarray | None = None
    _acct: dict | None = None
    _data = None
    _epoch: int | None = None

    def __init__(
        self,
        data,
        kind: str,
        name: str = "",
        domain: tuple[str, ...] | None = None,
        nrow: int | None = None,
        host_exact: np.ndarray | None = None,
    ):
        import weakref

        self.kind = kind
        self.name = name
        self.domain = tuple(domain) if domain is not None else None
        self._acct = {"hbm": 0.0, "host": 0.0}
        self._hostbuf: np.ndarray | None = None
        self._data = None
        from h2o3_tpu.parallel.mesh import mesh_epoch

        self._epoch = mesh_epoch()
        weakref.finalize(self, _vec_gc, self._acct)
        if kind == STR:
            self._host = np.asarray(data, dtype=object)
            self.nrow = len(self._host) if nrow is None else nrow
        else:
            self._host = host_exact
            if self._host is not None:
                self._acct_add("host", self._host.nbytes)
            self.data = data  # padded, sharded jax array
            assert nrow is not None
            self.nrow = nrow
        self._stats: dict | None = None

    # -- two-tier residency --------------------------------------------------
    def _acct_add(self, tier: str, delta: float) -> None:
        if self._acct is None:  # Vec flavors that skip __init__
            return
        from h2o3_tpu.frame import chunkstore as _cs

        self._acct[tier] += delta
        _cs.account(tier, delta)

    def _maybe_reshard(self) -> None:
        """Elastic recovery (ISSUE 17): when the topology epoch moved past
        the one this Vec was padded under (``mesh.reform_mesh`` on a changed
        rows×cols shape), re-derive the padded width from the NEW shard
        counts and re-shard — real rows copied exactly, pad rows refilled
        with the NA sentinel, the device array rebuilt lazily on the new
        mesh. Same-shape reforms re-place the identical bits (a device
        round trip), so non-elastic recovery stays bit-for-bit."""
        from h2o3_tpu.parallel import mesh as _m

        if self._epoch is None or self._epoch == _m.mesh_epoch():
            return
        if self.kind == STR:
            self._epoch = _m.mesh_epoch()
            return
        if self._hostbuf is None and self._data is not None:
            import jax

            if not getattr(self._data, "is_fully_addressable", True):
                # a cross-process array of the DEAD formation cannot be
                # pulled rank-locally; the restarted rank re-ingests — keep
                # the stale placement and let the resume path replace it
                return
            self._hostbuf = np.ascontiguousarray(jax.device_get(self._data))
            self._acct_add("host", self._hostbuf.nbytes)
        if self._hostbuf is not None:
            from h2o3_tpu.parallel.mesh import pad_to_shards

            npad_new = pad_to_shards(self.nrow)
            if self._hostbuf.shape[0] != npad_new:
                old = self._hostbuf
                dt, fill = Vec.device_dtype(self.kind, self.domain)
                buf = np.full((npad_new,) + old.shape[1:], fill,
                              dtype=old.dtype)
                buf[: self.nrow] = old[: self.nrow]
                self._acct_add("host", buf.nbytes - old.nbytes)
                self._hostbuf = buf
        if self._data is not None:
            self.data = None  # stale-mesh placement; rebuilt lazily
        self._epoch = _m.mesh_epoch()

    @property
    def data(self):
        """Padded, sharded device array; rebuilt lazily from the host mirror
        after :meth:`release_device` (bit-identical values)."""
        self._maybe_reshard()
        if self._data is None and self._hostbuf is not None:
            from h2o3_tpu.parallel.mesh import shard_rows

            d = shard_rows(self._hostbuf)
            self._data = d
            self._acct_add("hbm", d.nbytes)
        return self._data

    @data.setter
    def data(self, v) -> None:
        if self._data is not None:
            self._acct_add("hbm", -self._data.nbytes)
        self._data = v
        if v is not None:
            self._acct_add("hbm", getattr(v, "nbytes", 0))

    def host_values(self) -> np.ndarray:
        """PADDED host mirror in the device dtype — the spill-tier copy the
        out-of-core block slicer reads. Cached; identical bits to the
        device array (a plain device_get)."""
        if self.kind == STR:
            return self._host
        self._maybe_reshard()
        if self._hostbuf is None:
            from h2o3_tpu.parallel.mesh import pull_to_host

            self._hostbuf = np.asarray(pull_to_host(self.data))
            self._acct_add("host", self._hostbuf.nbytes)
        return self._hostbuf

    def release_device(self) -> int:
        """Compressed residency: ensure the host mirror exists, then drop
        the device array (HBM freed; ``data`` rebuilds lazily). Returns the
        device bytes released."""
        if self.kind == STR or self._data is None:
            return 0
        self.host_values()
        freed = int(self._data.nbytes)
        self.data = None
        return freed

    def _seed_host_mirror(self, buf: np.ndarray) -> None:
        """Adopt an ingest-time padded host buffer as the spill-tier mirror
        (frame/parse.py batched upload): a later streaming build's
        ``host_values()`` then costs nothing instead of a device pull."""
        if self.kind == STR or self._hostbuf is not None:
            return
        self._hostbuf = np.ascontiguousarray(buf)
        self._acct_add("host", self._hostbuf.nbytes)

    def drop_host_mirror(self) -> int:
        """Release the spill-tier mirror (satellite of the double-residency
        fix: once a device copy exists again, the mirror is redundant and a
        long-lived frame should not pay host RAM for both tiers)."""
        if self._hostbuf is None:
            return 0
        freed = int(self._hostbuf.nbytes)
        self._acct_add("host", -freed)
        self._hostbuf = None
        return freed

    # -- construction -------------------------------------------------------
    @staticmethod
    def device_dtype(kind: str, domain=None):
        """(numpy dtype, NA fill) for a column's device storage — the single
        source of the chunk-compression ladder (upstream C1/C2/C4Chunk pick
        bytes per value; SURVEY §2.1): enums take the narrowest signed int
        that fits the domain (-1 stays the NA sentinel in every width, HBM
        drops 4x for <=127 levels, 2x for <=32767); everything else is f32
        with NaN NAs. Shared by :meth:`from_numpy` and the batched upload in
        frame/parse.py so the two placement routes cannot diverge."""
        if kind == CAT:
            card = len(domain or ())
            dt = np.int8 if card <= 127 else np.int16 if card <= 32767 else np.int32
            return np.dtype(dt), -1
        return np.dtype(np.float32), np.nan

    @staticmethod
    def from_numpy(arr: np.ndarray, kind: str, name: str = "", domain=None) -> "Vec":
        n = len(arr)
        if kind == STR:
            return Vec(arr, STR, name=name, nrow=n)
        npad = pad_to_shards(n)
        dt, fill = Vec.device_dtype(kind, domain)
        exact = np.asarray(arr, dtype=np.float64) if kind == TIME else None
        buf = np.full(npad, fill, dtype=dt)
        buf[:n] = np.asarray(arr, dtype=dt)
        return Vec(
            shard_rows(buf), kind, name=name, domain=domain, nrow=n, host_exact=exact
        )

    # -- basics --------------------------------------------------------------
    @property
    def npad(self) -> int:
        self._maybe_reshard()
        if self._data is not None:
            return self._data.shape[0]
        if self._hostbuf is not None:  # device-released: don't re-upload
            return self._hostbuf.shape[0]
        return len(self._host)

    def is_numeric(self) -> bool:
        return self.kind in (NUM, INT, TIME)

    def is_categorical(self) -> bool:
        return self.kind == CAT

    def to_numpy(self) -> np.ndarray:
        """Unpadded host copy. Cat → codes; use :meth:`levels` for strings."""
        if self.kind == STR:
            return self._host
        if self.kind == TIME and self._host is not None:
            return self._host
        if self._data is None and self._hostbuf is not None:
            return self._hostbuf[: self.nrow]  # device-released: host tier
        from h2o3_tpu.parallel.mesh import pull_to_host

        return np.asarray(pull_to_host(self.data))[: self.nrow]

    def levels(self) -> list[str]:
        return list(self.domain) if self.domain else []

    @property
    def cardinality(self) -> int:
        return len(self.domain) if self.domain else -1

    # -- rollup stats (successor of Vec rollups: mean/sigma/min/max/naCnt) ---
    def stats(self) -> dict:
        if self._stats is not None:
            return self._stats
        if self.kind == STR:
            nas = int(sum(1 for v in self._host if v is None))
            self._stats = {"naCnt": nas}
            return self._stats
        if self.kind == CAT:
            counts = _cat_counts(self.data, max(1, self.cardinality))
            counts = np.asarray(counts)
            nas = self.nrow - int(counts.sum())
            self._stats = {"naCnt": nas, "levelCounts": counts}
            return self._stats
        # Two-pass moments: f32 tree-reduce for a provisional mean, then
        # centered accumulation — keeps mean/sigma accurate at H2O row scales
        # without float64 (which TPUs emulate slowly). Count is exact int32.
        s = _num_stats(self.data)
        cnt = int(s["cnt"])
        mean0 = float(s["sum"]) / cnt if cnt else float("nan")
        c = _centered_stats(self.data, mean0)
        mean = mean0 + (float(c["dsum"]) / cnt if cnt else 0.0)
        var = (
            (float(c["dssq"]) - float(c["dsum"]) ** 2 / cnt) / cnt
            if cnt
            else float("nan")
        )
        self._stats = {
            "naCnt": self.nrow - cnt,
            "mean": mean,
            "sigma": math.sqrt(max(0.0, var) * (cnt / max(1.0, cnt - 1))),
            "min": float(s["min"]),
            "max": float(s["max"]),
        }
        return self._stats

    def mean(self) -> float:
        return self.stats()["mean"]

    def sigma(self) -> float:
        return self.stats()["sigma"]

    def min(self) -> float:
        return self.stats()["min"]

    def max(self) -> float:
        return self.stats()["max"]

    def na_count(self) -> int:
        return self.stats()["naCnt"]


@jax.jit
def _num_stats(col):
    ok = ~jnp.isnan(col)
    x = jnp.where(ok, col, 0.0)
    return {
        "cnt": ok.sum(dtype=jnp.int32),
        "sum": x.sum(dtype=jnp.float32),
        "min": jnp.where(ok, col, jnp.inf).min(),
        "max": jnp.where(ok, col, -jnp.inf).max(),
    }


@jax.jit
def _centered_stats(col, mean0):
    ok = ~jnp.isnan(col)
    d = jnp.where(ok, col - mean0, 0.0)
    return {"dsum": d.sum(dtype=jnp.float32), "dssq": (d * d).sum(dtype=jnp.float32)}


@partial(jax.jit, static_argnums=1)
def _cat_counts(codes, card):
    ok = codes >= 0
    return jnp.zeros(card, jnp.int32).at[jnp.where(ok, codes, 0)].add(
        ok.astype(jnp.int32)
    )


class Frame:
    """Named list of aligned Vecs — the ``water.fvec.Frame`` successor."""

    def __init__(
        self,
        vecs: Sequence[Vec] | None = None,
        names: Sequence[str] | None = None,
        key: str | None = None,
        register: bool | None = None,
    ):
        """``register=None`` registers in the DKV only when an explicit key is
        given — internal temporaries (column selections, splits) stay
        unregistered so device memory can be garbage-collected; user-facing
        entry points (parse/upload) pass ``register=True``.
        """
        vecs = list(vecs or [])
        if names is None:
            names = [v.name or f"C{i + 1}" for i, v in enumerate(vecs)]
        assert len(names) == len(vecs)
        nrows = {v.nrow for v in vecs}
        assert len(nrows) <= 1, f"misaligned vecs: {nrows}"
        self._vecs: list[Vec] = vecs
        self._names: list[str] = [str(n) for n in names]
        for v, n in zip(self._vecs, self._names):
            v.name = n
        if register is None:
            register = key is not None
        self.key = key or DKV.make_key("frame")
        if register:
            DKV.put(self.key, self)

    # -- construction --------------------------------------------------------
    @staticmethod
    def from_pandas(
        df: pd.DataFrame,
        destination_frame: str | None = None,
        column_types: Mapping[str, str] | None = None,
        register: bool | None = None,
    ) -> "Frame":
        from h2o3_tpu.frame.parse import dataframe_to_vecs

        vecs = dataframe_to_vecs(df, column_types or {})
        return Frame(vecs, list(df.columns), key=destination_frame, register=register)

    @staticmethod
    def from_arrays(
        cols: Mapping[str, np.ndarray],
        column_types: Mapping[str, str] | None = None,
        key: str | None = None,
    ) -> "Frame":
        return Frame.from_pandas(
            pd.DataFrame({k: np.asarray(v) for k, v in cols.items()}),
            destination_frame=key,
            column_types=column_types,
        )

    # -- shape & metadata ----------------------------------------------------
    @property
    def nrow(self) -> int:
        return self._vecs[0].nrow if self._vecs else 0

    @property
    def npad(self) -> int:
        return self._vecs[0].npad if self._vecs else 0

    @property
    def ncol(self) -> int:
        return len(self._vecs)

    @property
    def names(self) -> list[str]:
        return list(self._names)

    @property
    def types(self) -> dict[str, str]:
        return {n: v.kind for n, v in zip(self._names, self._vecs)}

    def vec(self, col: int | str) -> Vec:
        return self._vecs[self._index(col)]

    def _index(self, col: int | str) -> int:
        if isinstance(col, str):
            return self._names.index(col)
        return int(col)

    def __contains__(self, name: str) -> bool:
        return name in self._names

    def __repr__(self) -> str:
        return f"<Frame {self.key} {self.nrow}x{self.ncol} {self._names[:8]}>"

    # -- selection -----------------------------------------------------------
    def __getitem__(self, sel) -> "Frame":
        if isinstance(sel, (str, int)):
            sel = [sel]
        if isinstance(sel, (list, tuple)) and all(
            isinstance(s, (str, int)) for s in sel
        ):
            idx = [self._index(s) for s in sel]
            return Frame(
                [self._vecs[i] for i in idx], [self._names[i] for i in idx]
            )
        raise TypeError(f"unsupported selection {sel!r}")

    def drop(self, cols: str | Sequence[str]) -> "Frame":
        if isinstance(cols, str):
            cols = [cols]
        keep = [n for n in self._names if n not in set(cols)]
        return self[keep]

    def cbind(self, other: "Frame") -> "Frame":
        assert other.nrow == self.nrow
        return Frame(self._vecs + other._vecs, self._names + other._names)

    def rbind(self, other: "Frame") -> "Frame":
        """Row-append preserving kinds and unioning categorical domains
        (H2O unifies domains on rbind [UNVERIFIED])."""
        assert self._names == other._names, "rbind: column names differ"
        vecs = []
        for va, vb in zip(self._vecs, other._vecs):
            assert va.kind == vb.kind, f"rbind: kind mismatch on {va.name}"
            if va.kind == STR:
                vecs.append(Vec(np.concatenate([va._host, vb._host]), STR, name=va.name))
            elif va.kind == CAT:
                dom = list(va.domain or ())
                lut = {d: i for i, d in enumerate(dom)}
                remap = np.empty(len(vb.domain or ()) + 1, dtype=np.int32)
                remap[-1] = -1
                for j, d in enumerate(vb.domain or ()):
                    remap[j] = lut.setdefault(d, len(lut))
                    if remap[j] == len(dom):
                        dom.append(d)
                codes = np.concatenate([va.to_numpy(), remap[vb.to_numpy()]])
                vecs.append(Vec.from_numpy(codes, CAT, name=va.name, domain=dom))
            else:
                vals = np.concatenate([va.to_numpy(), vb.to_numpy()])
                vecs.append(Vec.from_numpy(vals, va.kind, name=va.name))
        return Frame(vecs, self._names)

    # -- two-tier residency (out-of-core data plane, frame/chunkstore.py) ----
    def spill_to_host(self, cols: Sequence[str] | None = None) -> int:
        """Release the device copies of (the named, default all) non-string
        columns to the host tier; ``Vec.data`` rebuilds lazily on next
        touch. No-op under ``H2O3_TPU_FRAME_COMPRESS=0``. Returns device
        bytes released."""
        from h2o3_tpu.frame import chunkstore as _cs

        names = list(cols) if cols is not None else self._names
        return _cs.release_frame_features(self, names)

    def resident_bytes(self) -> dict:
        """Per-tier bytes this frame's Vecs currently account."""
        out = {"hbm": 0.0, "host": 0.0}
        for v in self._vecs:
            for tier, amt in (v._acct or {}).items():
                out[tier] += amt
        return out

    # -- row mask ------------------------------------------------------------
    def row_mask(self):
        """float32 {0,1} validity mask over padded rows, row-sharded."""
        return _iota_mask(self.npad, self.nrow)

    # -- materialization -----------------------------------------------------
    def to_pandas(self) -> pd.DataFrame:
        out = {}
        for n, v in zip(self._names, self._vecs):
            if v.kind == STR:
                out[n] = v._host
            elif v.kind == TIME:
                # datetime column, like H2O's as_data_frame time handling —
                # keeps merge/round-trip through from_pandas unit-correct
                ms = v.to_numpy()
                out[n] = pd.to_datetime(pd.Series(ms), unit="ms")
            elif v.kind == CAT:
                codes = v.to_numpy()
                dom = np.asarray(v.domain, dtype=object)
                col = np.full(len(codes), None, dtype=object)
                ok = codes >= 0
                col[ok] = dom[codes[ok]]
                out[n] = col
            else:
                out[n] = v.to_numpy().astype(np.float64)
        return pd.DataFrame(out, columns=self._names)

    def head(self, n: int = 10) -> pd.DataFrame:
        return self.to_pandas().head(n)

    def tail(self, n: int = 10) -> pd.DataFrame:
        return self.to_pandas().tail(n)

    def describe(self) -> pd.DataFrame:
        rows = []
        for n, v in zip(self._names, self._vecs):
            s = v.stats()
            rows.append(
                {
                    "column": n,
                    "type": v.kind,
                    "missing": s.get("naCnt", 0),
                    "mean": s.get("mean"),
                    "sigma": s.get("sigma"),
                    "min": s.get("min"),
                    "max": s.get("max"),
                    "cardinality": v.cardinality if v.kind == CAT else None,
                }
            )
        return pd.DataFrame(rows)

    # -- munging (Rapids successors live in frame/ops.py; these are core) ----
    def subset_rows(self, rows: np.ndarray, key: str | None = None) -> "Frame":
        """New frame from a boolean mask or index array over rows.

        Domains, kinds, and TIME precision are preserved exactly (no pandas
        round-trip) — H2O likewise keeps the parent Vec domain on slices.
        Numeric/categorical columns are gathered ON DEVICE in one fused
        program (the former per-column to_numpy pulled every column to host).
        """
        rows = np.asarray(rows)
        if rows.dtype == bool:
            rows = np.flatnonzero(rows)
        # python-style negative indexing (numpy fancy-index semantics);
        # gather_rows itself reserves negatives for NA rows (joins)
        rows = np.where(rows < 0, rows + self.nrow, rows)
        return self.gather_rows(rows, key=key)

    def gather_rows(
        self, rows: np.ndarray, valid: np.ndarray | None = None, key: str | None = None
    ) -> "Frame":
        """Device row gather: output row i = input row ``rows[i]``; rows where
        ``valid`` is False (or ``rows < 0``) come out as NA. The workhorse of
        subset/sort/merge."""
        rows = np.asarray(rows)
        m = len(rows)
        if valid is None:
            valid = rows >= 0
        valid = np.asarray(valid, bool)
        idx_np = np.where(valid, rows, 0).astype(np.int64)
        npad_new = pad_to_shards(m)
        idx_pad = np.zeros(npad_new, np.int64)
        idx_pad[:m] = idx_np
        bad = np.ones(npad_new, bool)
        bad[:m] = ~valid

        dev_ids = [i for i, v in enumerate(self._vecs) if v.kind != STR]
        kinds = tuple(self._vecs[i].kind for i in dev_ids)
        gathered = ()
        if dev_ids:
            prog = _gather_program(kinds)
            gathered = prog(
                tuple(self._vecs[i].data for i in dev_ids),
                jnp.asarray(idx_pad),
                jnp.asarray(bad),
            )
            gathered = jax.device_put(gathered, row_sharding())

        vecs: list[Vec] = []
        gi = 0
        for i, v in enumerate(self._vecs):
            if v.kind == STR:
                out = np.full(m, None, dtype=object)
                out[valid] = v._host[idx_np[valid]]
                vecs.append(Vec(out, STR, name=v.name))
                continue
            exact = None
            if v._host is not None:  # TIME exactness preserved host-side
                exact = np.full(m, np.nan, np.float64)
                exact[valid] = v._host[idx_np[valid]]
            vecs.append(
                Vec(
                    gathered[gi], v.kind, name=v.name, domain=v.domain,
                    nrow=m, host_exact=exact,
                )
            )
            gi += 1
        return Frame(vecs, self._names, key=key)

    def split_frame(self, ratios: Sequence[float], seed: int = 1234) -> list["Frame"]:
        """Random row split — successor of ``h2o.split_frame`` (Rapids h2o.runif)."""
        rng = np.random.default_rng(seed)
        u = rng.random(self.nrow)
        edges = np.cumsum(list(ratios))
        assert edges[-1] <= 1.0 + 1e-9
        out = []
        lo = 0.0
        for e in list(edges) + ([1.0] if edges[-1] < 1.0 - 1e-9 else []):
            out.append(self.subset_rows((u >= lo) & (u < e)))
            lo = e
        return out


def _iota_mask(npad: int, nrow: int):
    return shard_rows((np.arange(npad) < nrow).astype(np.float32))


_GATHER_CACHE: dict = {}


def _gather_program(kinds: tuple):
    """Fused one-dispatch row gather for all non-string columns."""
    import jax as _jax

    key = (kinds, _jax.default_backend())
    prog = _GATHER_CACHE.get(key)
    if prog is None:

        def run(datas, idx, bad):
            outs = []
            for d, k in zip(datas, kinds):
                g = jnp.take(d, idx, axis=0)
                if k == CAT:
                    g = jnp.where(bad, -1, g)
                else:
                    g = jnp.where(bad, jnp.nan, g)
                outs.append(g)
            return tuple(outs)

        prog = _jax.jit(run)
        _GATHER_CACHE[key] = prog
    return prog
