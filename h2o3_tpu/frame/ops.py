"""Frame operations — successor of the Rapids DSL (``water.rapids.Rapids`` /
``ast/*`` / ``Merge.java`` [UNVERIFIED upstream paths, SURVEY.md §2.1]).

H2O clients build lazy expression trees that compile to Rapids strings
(``(+ (cols frame [0]) 1)``) shipped to the cluster and evaluated as MRTask
passes. The TPU-native shape of the same surface is direct: elementwise math
is a jitted device op over the row-sharded columns (XLA fuses chains of them
— the fusion H2O got from hand-written AST nodes falls out of the compiler);
group-by is a device segment-reduction; joins/sorts are host-coordinated over
columnar data. The public surface mirrors the Rapids op roster: arithmetic,
comparisons, boolean ops, unary math, ``ifelse``, group-by aggregation
(``ASTGroup``), ``merge`` (``ASTMerge`` radix join), ``quantile``, ``table``,
``cut``, ``unique``, string ops, time-component extraction, ``scale``,
cumulative ops, ``cor``/``var``.

Everything here attaches to :class:`Vec`/:class:`Frame` (operator overloads
+ named methods) when this module is imported, which ``h2o3_tpu/__init__``
does eagerly.
"""

from __future__ import annotations

from functools import partial
from typing import Mapping, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import pandas as pd

from h2o3_tpu.frame.frame import CAT, INT, NUM, STR, TIME, Frame, Vec

# ---------------------------------------------------------------------------
# elementwise kernels (cached by op name so jit caches hit across calls)
# ---------------------------------------------------------------------------

_BINOPS = {
    "+": jnp.add,
    "-": jnp.subtract,
    "*": jnp.multiply,
    "/": jnp.divide,
    "//": lambda a, b: jnp.floor(a / b),
    "%": jnp.mod,
    "**": jnp.power,
    "==": lambda a, b: (a == b).astype(jnp.float32),
    "!=": lambda a, b: (a != b).astype(jnp.float32),
    "<": lambda a, b: (a < b).astype(jnp.float32),
    "<=": lambda a, b: (a <= b).astype(jnp.float32),
    ">": lambda a, b: (a > b).astype(jnp.float32),
    ">=": lambda a, b: (a >= b).astype(jnp.float32),
    "&": lambda a, b: ((a != 0) & (b != 0)).astype(jnp.float32),
    "|": lambda a, b: ((a != 0) | (b != 0)).astype(jnp.float32),
    "min": jnp.minimum,
    "max": jnp.maximum,
}

_UNOPS = {
    "abs": jnp.abs,
    "sign": jnp.sign,
    "sqrt": jnp.sqrt,
    "exp": jnp.exp,
    "expm1": jnp.expm1,
    "log": jnp.log,
    "log1p": jnp.log1p,
    "log2": jnp.log2,
    "log10": jnp.log10,
    "floor": jnp.floor,
    "ceil": jnp.ceil,
    "round": jnp.round,
    "trunc": jnp.trunc,
    "cos": jnp.cos,
    "sin": jnp.sin,
    "tan": jnp.tan,
    "acos": jnp.arccos,
    "asin": jnp.arcsin,
    "atan": jnp.arctan,
    "cosh": jnp.cosh,
    "sinh": jnp.sinh,
    "tanh": jnp.tanh,
    "gamma": lambda x: jnp.exp(jax.scipy.special.gammaln(x)),
    "lgamma": jax.scipy.special.gammaln,
    "digamma": jax.scipy.special.digamma,
    "not": lambda x: (x == 0).astype(jnp.float32),
    "isna": lambda x: jnp.isnan(x).astype(jnp.float32),
}

# NA semantics: comparisons/boolean ops on NaN inputs yield NaN (H2O returns
# NA), so every non-arithmetic op re-inserts NaN where any input was NaN.
_PRESERVE_NAN = {"==", "!=", "<", "<=", ">", ">=", "&", "|"}


@partial(jax.jit, static_argnames=("op",))
def _binop_kernel(a, b, op: str):
    out = _BINOPS[op](a, b)
    if op in _PRESERVE_NAN:
        out = jnp.where(jnp.isnan(a) | jnp.isnan(b), jnp.nan, out)
    return out.astype(jnp.float32)


@partial(jax.jit, static_argnames=("op",))
def _unop_kernel(a, op: str):
    out = _UNOPS[op](a)
    if op == "not":
        out = jnp.where(jnp.isnan(a), jnp.nan, out)
    return out.astype(jnp.float32)


@jax.jit
def _codes_as_float(codes):
    """Enum codes → float with the NA sentinel (-1) mapped to NaN, so the
    module's NA semantics hold for enum operands too."""
    return jnp.where(codes < 0, jnp.nan, codes.astype(jnp.float32))


def _as_device(x, like: Vec):
    """Coerce operand to a device array aligned with ``like``'s padded rows."""
    if isinstance(x, Vec):
        if x.kind == STR:
            raise TypeError("arithmetic on string columns is not supported")
        assert x.nrow == like.nrow, "operand row counts differ"
        return _codes_as_float(x.data) if x.kind == CAT else x.data
    if isinstance(x, Frame):
        assert x.ncol == 1, "frame operand must have exactly one column"
        return _as_device(x.vec(0), like)
    return jnp.float32(x)  # scalar broadcasts over the padded column


def _binop(a: Vec, b, op: str, reflected: bool = False) -> Vec:
    if isinstance(b, str):
        return _binop_str(a, b, op)
    cross_enum = (
        isinstance(b, Vec)
        and a.kind == CAT
        and b.kind == CAT
        and a.domain != b.domain
    )
    if not cross_enum:
        from h2o3_tpu.frame import lazy as _lz
        from h2o3_tpu.frame import munge as _mg

        bb = b.vec(0) if isinstance(b, Frame) and b.ncol == 1 else b
        if _mg.fuse_on() and _lz.fusible_operand(a) and _lz.fusible_operand(bb):
            # defer: the op joins a LazyExprVec graph and compiles with its
            # whole chain on first touch (frame/lazy.py expression fusion)
            return _lz.defer_binop(a, bb, op, reflected)
        _mg.DISPATCHES.inc(op="elementwise")
    if cross_enum:
        # enums with different domains compare by LABEL: remap b's codes into
        # a's domain space (labels absent from a get distinct no-match codes)
        if op not in ("==", "!="):
            raise TypeError("ordering comparisons between enums with different domains")
        adom = list(a.domain or ())
        lut = {d: i for i, d in enumerate(adom)}
        remap = np.empty(len(b.domain or ()) + 1, dtype=np.float32)
        remap[-1] = np.nan
        for j, d in enumerate(b.domain or ()):
            remap[j] = lut.get(d, len(adom) + j)
        db = Vec.from_numpy(remap[b.to_numpy()], NUM).data
        out = _binop_kernel(_codes_as_float(a.data), db, op)
        return Vec(out, NUM, nrow=a.nrow)
    da = _as_device(a, a)
    db = _as_device(b, a)
    out = _binop_kernel(db, da, op) if reflected else _binop_kernel(da, db, op)
    return Vec(out, NUM, nrow=a.nrow)


def _binop_str(a: Vec, s: str, op: str) -> Vec:
    """``frame['col'] == 'level'`` — the standard H2O filter idiom. The level
    resolves to its code (no match → all-0 indicator with NA passthrough)."""
    if op not in ("==", "!="):
        raise TypeError(f"operator {op!r} not supported between a column and a string")
    from h2o3_tpu.frame import munge as _mg

    if a.kind == STR:
        _mg.fallback("string_op")  # host pass; stays eager under fusion
    else:
        _mg.DISPATCHES.inc(op="elementwise")
    if a.kind == CAT:
        try:
            code = (a.domain or ()).index(s)
        except ValueError:
            code = -2  # matches nothing, NA rows still yield NaN
        da = _codes_as_float(a.data)
        out = _binop_kernel(da, jnp.float32(code), op)
        return Vec(out, NUM, nrow=a.nrow)
    if a.kind == STR:
        vals = a.to_numpy()
        eq = np.array(
            [np.nan if v is None else float(v == s) for v in vals], dtype=np.float64
        )
        if op == "!=":
            eq = 1.0 - eq
        return Vec.from_numpy(eq, NUM, name=a.name)
    raise TypeError(f"cannot compare a {a.kind} column to a string")


def _unop(a: Vec, op: str) -> Vec:
    from h2o3_tpu.frame import lazy as _lz
    from h2o3_tpu.frame import munge as _mg

    if _mg.fuse_on() and _lz.fusible_operand(a):
        return _lz.defer_unop(a, op)
    _mg.DISPATCHES.inc(op="elementwise")
    return Vec(_unop_kernel(_as_device(a, a), op), NUM, nrow=a.nrow)


def ifelse(test: Vec, yes, no) -> Vec:
    """``ASTIfElse`` successor: elementwise select, NA where test is NA."""
    from h2o3_tpu.frame import lazy as _lz
    from h2o3_tpu.frame import munge as _mg

    yy = yes.vec(0) if isinstance(yes, Frame) and yes.ncol == 1 else yes
    nn = no.vec(0) if isinstance(no, Frame) and no.ncol == 1 else no
    if (_mg.fuse_on() and isinstance(test, Vec) and _lz.fusible_operand(test)
            and _lz.fusible_operand(yy) and _lz.fusible_operand(nn)):
        return _lz.defer_ifelse(test, yy, nn)
    _mg.DISPATCHES.inc(op="elementwise")
    t = _as_device(test, test)
    y = _as_device(yes, test)
    n = _as_device(no, test)
    out = _ifelse_kernel(t, y, n)
    return Vec(out, NUM, nrow=test.nrow)


@jax.jit
def _ifelse_kernel(t, y, n):
    out = jnp.where(t != 0, y, n)
    return jnp.where(jnp.isnan(t), jnp.nan, out).astype(jnp.float32)


# ---------------------------------------------------------------------------
# cumulative ops — host-side prefix pass (H2O's ASTCumu likewise runs a
# sequential two-pass chunk-prefix; a prefix scan is bandwidth-bound and has
# nothing for the MXU, so the host is the honest place for it)
# ---------------------------------------------------------------------------

_CUMOPS = ("cumsum", "cumprod", "cummin", "cummax")


def _cumulative(v: Vec, op: str) -> Vec:
    vals = v.to_numpy().astype(np.float64)
    out = {
        "cumsum": np.cumsum,
        "cumprod": np.cumprod,
        "cummin": np.minimum.accumulate,
        "cummax": np.maximum.accumulate,
    }[op](vals)
    return Vec.from_numpy(out, NUM)


def diff_lag1(v: Vec) -> Vec:
    """``ASTDiffLag1`` successor: x[i] - x[i-1], NA in row 0."""
    vals = v.to_numpy().astype(np.float64)
    return Vec.from_numpy(np.diff(vals, prepend=np.nan), NUM)


def fillna(v: Vec, method: str = "forward", maxlen: int = 0) -> Vec:
    """``h2o.fillna`` successor (axis=0): propagate the last (or next)
    observed value into NA runs, optionally capped at ``maxlen`` fills.

    Host prefix pass, like the cumulative ops above: a sequential
    carry has nothing for the MXU and is bandwidth-bound either way."""
    if method not in ("forward", "backward"):
        raise ValueError(f"fillna method must be forward/backward, got {method!r}")
    if not v.is_numeric():
        raise ValueError(f"fillna supports numeric/time columns, not {v.kind}")
    vals = v.to_numpy().astype(np.float64)
    if method == "backward":
        vals = vals[::-1]
    idx = np.arange(len(vals))
    valid = np.where(~np.isnan(vals), idx, -1)
    last = np.maximum.accumulate(valid)  # index of last non-NA at or before i
    dist = idx - last
    ok = last >= 0
    if maxlen and maxlen > 0:
        ok &= dist <= maxlen
    out = np.where(ok, vals[np.maximum(last, 0)], np.nan)
    if method == "backward":
        out = out[::-1]
    # keep the column kind: TIME must stay TIME (from_numpy re-derives the
    # exact f64 epoch-ms host copy; rebuilding as NUM would quantize ~2 min)
    return Vec.from_numpy(out, v.kind, name=v.name)


# ---------------------------------------------------------------------------
# group-by — successor of ``ASTGroup``
# ---------------------------------------------------------------------------


@partial(jax.jit, static_argnames=("ngroups",))
def _segment_aggregate(gid, x, ngroups: int):
    """Per-group {count, sum, sumsq, min, max} in one device pass."""
    ok = (gid >= 0) & ~jnp.isnan(x)
    g = jnp.where(ok, gid, 0)
    xz = jnp.where(ok, x, 0.0)
    cnt = jnp.zeros(ngroups, jnp.float32).at[g].add(ok.astype(jnp.float32))
    s = jnp.zeros(ngroups, jnp.float32).at[g].add(xz)
    ss = jnp.zeros(ngroups, jnp.float32).at[g].add(xz * xz)
    mn = jnp.full(ngroups, jnp.inf, jnp.float32).at[g].min(
        jnp.where(ok, x, jnp.inf)
    )
    mx = jnp.full(ngroups, -jnp.inf, jnp.float32).at[g].max(
        jnp.where(ok, x, -jnp.inf)
    )
    nas = jnp.zeros(ngroups, jnp.float32).at[jnp.where(gid >= 0, gid, 0)].add(
        (jnp.isnan(x) & (gid >= 0)).astype(jnp.float32)
    )
    return {"nrow": cnt, "sum": s, "sumsq": ss, "min": mn, "max": mx, "nacnt": nas}


class GroupBy:
    """``frame.group_by(cols).agg(...)`` — ASTGroup successor.

    Keys are factorized host-side (strings/enums need the host anyway); the
    numeric aggregations run as one device segment-reduction per column.
    """

    AGGS = ("count", "nrow", "sum", "mean", "min", "max", "var", "sd", "sumsq", "median", "mode", "first", "last")

    def __init__(self, frame: Frame, by: Sequence[str] | str):
        self.frame = frame
        self.by = [by] if isinstance(by, str) else list(by)
        cols = []
        for b in self.by:
            v = frame.vec(b)
            if v.kind == STR:
                cols.append(v.to_numpy())
            elif v.kind == CAT:
                dom = np.asarray(list(v.domain or ()) + [None], dtype=object)
                cols.append(dom[v.to_numpy()])
            else:
                cols.append(v.to_numpy())
        keys = pd.MultiIndex.from_arrays(cols) if len(cols) > 1 else pd.Index(cols[0])
        codes, uniques = pd.factorize(keys, sort=True)
        self._gid = codes.astype(np.int32)  # -1 for NA keys, matching H2O's NA group drop
        self._uniques = uniques
        self._ngroups = len(uniques)

    _DEV_AGGS = ("count", "nrow", "sum", "mean", "min", "max", "var", "sd", "sumsq")

    def agg(self, spec: Mapping[str, Sequence[str] | str]) -> Frame:
        from h2o3_tpu.frame import chunkstore as _cs
        from h2o3_tpu.frame import munge as _mg

        ngroups = self._ngroups
        items = [(c, [a] if isinstance(a, str) else list(a))
                 for c, a in spec.items()]
        dev_cols = [c for c, aggs in items
                    if any(a in self._DEV_AGGS for a in aggs)]
        fused = _mg.fuse_on() and dev_cols and ngroups > 0
        fused_stats: dict[str, dict] = {}
        if fused:
            # compiled lane: EVERY value column's segment stats in ONE
            # mesh-sharded dispatch (frame/munge.py) — streamed through the
            # ChunkStore window when one is configured, resident otherwise
            stats_list = None
            if _cs.streaming_enabled():
                host_cols = []
                for c in dev_cols:
                    v = self.frame.vec(c)
                    hv = np.asarray(v.host_values())
                    if v.kind == CAT:
                        hv = np.where(hv < 0, np.nan, hv.astype(np.float32))
                    host_cols.append(np.asarray(hv, np.float32))
                stats_list = _mg.groupby_stats_streamed(
                    self._gid, host_cols, ngroups)
            if stats_list is None:
                xs = []
                for c in dev_cols:
                    v = self.frame.vec(c)
                    xs.append(_codes_as_float(v.data) if v.kind == CAT
                              else v.data)
                stats_list = _mg.groupby_stats(self._gid, xs, ngroups)
            fused_stats = dict(zip(dev_cols, stats_list))
        else:
            gid_dev = Vec.from_numpy(self._gid, CAT, domain=[str(i) for i in range(max(1, ngroups))]).data
        out_cols: dict[str, np.ndarray] = {}
        # key columns
        if len(self.by) == 1:
            out_cols[self.by[0]] = np.asarray(self._uniques)
        else:
            for i, b in enumerate(self.by):
                out_cols[b] = np.asarray(self._uniques.get_level_values(i))
        for col, aggs in items:
            v = self.frame.vec(col)
            need_device = any(a in self._DEV_AGGS for a in aggs)
            stats = None
            if need_device:
                if fused:
                    stats = fused_stats[col]
                else:
                    x = _codes_as_float(v.data) if v.kind == CAT else v.data
                    stats = {k: np.asarray(s) for k, s in _segment_aggregate(gid_dev, x, ngroups).items()}
            if any(a in ("median", "mode", "first", "last") for a in aggs):
                _mg.fallback("host_agg")
            for a in aggs:
                name = f"{a}_{col}"
                if a in ("count", "nrow"):
                    out_cols[name] = stats["nrow"] + stats["nacnt"]
                elif a == "sum":
                    out_cols[name] = stats["sum"]
                elif a == "sumsq":
                    out_cols[name] = stats["sumsq"]
                elif a == "mean":
                    out_cols[name] = stats["sum"] / np.maximum(stats["nrow"], 1)
                elif a == "min":
                    out_cols[name] = stats["min"]
                elif a == "max":
                    out_cols[name] = stats["max"]
                elif a in ("var", "sd"):
                    n = stats["nrow"]
                    m = stats["sum"] / np.maximum(n, 1)
                    var = (stats["sumsq"] - n * m * m) / np.maximum(n - 1, 1)
                    var = np.maximum(var, 0.0)
                    out_cols[name] = np.sqrt(var) if a == "sd" else var
                elif a in ("median", "mode", "first", "last"):
                    vals = v.to_numpy()
                    if v.kind == CAT:  # NA sentinel -1 → NaN for the host aggs
                        vals = np.where(vals < 0, np.nan, vals.astype(np.float64))
                    out = np.full(ngroups, np.nan)
                    for g in range(ngroups):
                        gv = vals[self._gid == g]
                        if a in ("median",):
                            gv = gv[~pd.isna(gv)]
                            out[g] = np.median(gv) if len(gv) else np.nan
                        elif a == "mode":
                            gv = gv[~pd.isna(gv)]
                            out[g] = pd.Series(gv).mode().iloc[0] if len(gv) else np.nan
                        elif a == "first":
                            out[g] = gv[0] if len(gv) else np.nan
                        else:
                            out[g] = gv[-1] if len(gv) else np.nan
                    out_cols[name] = out
                else:
                    raise ValueError(f"unknown aggregation {a!r}")
        return Frame.from_pandas(pd.DataFrame(out_cols))


def group_by(frame: Frame, by) -> GroupBy:
    return GroupBy(frame, by)


# ---------------------------------------------------------------------------
# merge / sort — successor of ``ASTMerge`` (the distributed radix join,
# ``water/rapids/Merge.java`` [UNVERIFIED]) and ``ASTSort``. DEVICE-SIDE key
# matching: per-column int64 codes (numerics bitcast after -0/NaN
# canonicalization; enums remapped onto the union domain so the join is on
# LABELS), dense tuple group-ids via one lexsort over both sides' keys, then
# a sort-merge join (stable argsort + searchsorted). The host only expands
# the per-left-row match counts into (li, ri) index vectors (vectorized
# np.repeat — O(output rows)), and every payload column is gathered ON
# DEVICE in one fused program (``Frame.gather_rows``). STR and TIME keys
# fall back to the host (pandas) path: strings are host-resident anyway and
# TIME needs the exact f64 host values, not the f32 device copy.
# ---------------------------------------------------------------------------


def _domain_union(dom_a, dom_b):
    """Union of two enum domains, a-first order (shared by merge keys and
    join-key coalescing so the two can't drift)."""
    union = list(dom_a or ())
    seen = set(union)
    union += [d for d in (dom_b or ()) if d not in seen]
    return union


def _key_codes_device(v, union_pos: dict | None = None, padded: bool = False):
    """(nrow,) int32 device codes for one join/sort key column.

    Equal values get equal codes; NA is its own code (-1 for enums, the
    canonical-NaN bit pattern for numerics) so NA keys match NA keys, as the
    former pandas path behaved. int32 on purpose (JAX default x64-disabled
    mode truncates int64 anyway): group-id space caps at ~2^31 combined
    rows, beyond per-host frame sizes here. Returns None for kinds that
    need the host path (STR / TIME). ``padded=True`` keeps the full
    row-sharded padded column (the radix-exchange lane masks padding by
    row count instead of slicing)."""
    if v.kind in (STR, TIME):
        return None
    x = v.data if padded else v.data[: v.nrow]
    if v.kind == CAT:
        if union_pos is None:
            return x.astype(jnp.int32)
        lut = np.array(
            [union_pos[d] for d in (v.domain or ())] or [0], np.int32
        )
        return jnp.where(
            x >= 0, jnp.asarray(lut)[jnp.clip(x, 0, len(lut) - 1)], jnp.int32(-1)
        )
    xf = x.astype(jnp.float32)
    xf = jnp.where(xf == 0, jnp.float32(0.0), xf)  # -0.0 ≡ +0.0
    xf = jnp.where(jnp.isnan(xf), jnp.float32(np.nan), xf)  # canonical NaN
    return jax.lax.bitcast_convert_type(xf, jnp.int32)


def _tuple_gids(cols_l, cols_r):
    """Dense group ids for key TUPLES across both sides (device).

    One lexsort over the concatenated (n_l + n_r, K) key matrix; rows with
    equal tuples get equal ids — the collision-free successor of hashing."""
    Lk = jnp.stack(cols_l, axis=1)
    Rk = jnp.stack(cols_r, axis=1)
    allk = jnp.concatenate([Lk, Rk], axis=0)
    K = allk.shape[1]
    order = jnp.lexsort(tuple(allk[:, k] for k in range(K - 1, -1, -1)))
    skeys = allk[order]
    bump = jnp.any(skeys[1:] != skeys[:-1], axis=1).astype(jnp.int32)
    gid_sorted = jnp.concatenate([jnp.zeros(1, jnp.int32), jnp.cumsum(bump)])
    gid = jnp.zeros(allk.shape[0], jnp.int32).at[order].set(gid_sorted)
    return gid[: Lk.shape[0]], gid[Lk.shape[0] :]


def _join_stats(gl, gr, need_matched: bool):
    """Device sort-merge join statistics: for each left row the [lo, lo+m)
    range of matches in right-sorted order, the right permutation, and (only
    when ``need_matched`` — right/outer joins) the per-right-row matched
    mask. Stable argsort keeps equal right keys in right-frame order, so
    WITHIN a match group the output is in right-frame order like pandas;
    the groups themselves come out left-major (see ``merge``)."""
    rorder = jnp.argsort(gr, stable=True)
    rs = gr[rorder]
    lo = jnp.searchsorted(rs, gl, side="left")
    hi = jnp.searchsorted(rs, gl, side="right")
    n_l = gl.shape[0]
    if not need_matched or n_l == 0:
        matched_r = jnp.ones(gr.shape[0], bool) if not need_matched else jnp.zeros(gr.shape[0], bool)
    else:
        ls = jnp.sort(gl)
        pos = jnp.searchsorted(ls, gr, side="left")
        matched_r = (pos < n_l) & (ls[jnp.clip(pos, 0, n_l - 1)] == gr)
    return lo, hi - lo, rorder, matched_r


def _merge_keys_device(left, right, bx, bby):
    """(li, ri) row-index vectors via the device join, or None if any key
    column needs the host path."""
    cols_l, cols_r = [], []
    for cl, cr in zip(bx, bby):
        vl, vr = left.vec(cl), right.vec(cr)
        if vl.kind == CAT or vr.kind == CAT:
            if not (vl.kind == CAT and vr.kind == CAT):
                return None  # mixed enum/numeric key: host path decides
            union = _domain_union(vl.domain, vr.domain)
            pos = {d: i for i, d in enumerate(union)}
            kl, kr = _key_codes_device(vl, pos), _key_codes_device(vr, pos)
        else:
            kl, kr = _key_codes_device(vl), _key_codes_device(vr)
        if kl is None or kr is None:
            return None
        cols_l.append(kl)
        cols_r.append(kr)
    gl, gr = _tuple_gids(cols_l, cols_r)
    return gl, gr


def _exchange_gids(left, right, bx, bby):
    """Radix-partition ``all_to_all`` gid lane (frame/munge.py) for
    single-key joins on multi-device meshes. Returns (gl, gr) or None —
    the caller then takes the global-lexsort lane. Any injective gid
    relabeling yields the same join output (``_join_stats``'s stable
    right argsort keys on gid EQUALITY only), so the two lanes agree
    bit-for-bit on the merged frame."""
    from h2o3_tpu.frame import munge as _mg
    from h2o3_tpu.parallel.mesh import n_shards

    if len(bx) != 1 or n_shards() <= 1 or not left.nrow or not right.nrow:
        return None
    vl, vr = left.vec(bx[0]), right.vec(bby[0])
    if vl.kind in (STR, TIME) or vr.kind in (STR, TIME):
        return None
    if (vl.kind == CAT) != (vr.kind == CAT):
        return None  # mixed enum/numeric key: host path decides
    if vl.kind == CAT:
        union = _domain_union(vl.domain, vr.domain)
        pos = {d: i for i, d in enumerate(union)}
        klp = _key_codes_device(vl, pos, padded=True)
        krp = _key_codes_device(vr, pos, padded=True)
    else:
        klp = _key_codes_device(vl, padded=True)
        krp = _key_codes_device(vr, padded=True)
    return _mg.tuple_gids_exchange(klp, krp, left.nrow, right.nrow)


def merge(
    left: Frame,
    right: Frame,
    by: Sequence[str] | None = None,
    by_x: Sequence[str] | None = None,
    by_y: Sequence[str] | None = None,
    all_x: bool = False,
    all_y: bool = False,
) -> Frame:
    bx = list(by_x or by or [n for n in left.names if n in set(right.names)])
    bby = list(by_y or by or bx)

    from h2o3_tpu.frame import munge as _mg
    from h2o3_tpu.parallel.mesh import n_shards

    fused = _mg.fuse_on()
    dev = None
    if fused:
        dev = _exchange_gids(left, right, bx, bby)
        if dev is None and len(bx) > 1 and n_shards() > 1:
            _mg.fallback("join_multikey")
    if dev is None:
        dev = _merge_keys_device(left, right, bx, bby)
        if dev is None:
            _mg.fallback("host_keys")
    if dev is not None:
        # Output row order (device path): match groups in LEFT-frame order
        # (within a group, right-frame order), then — for right/outer joins —
        # unmatched right rows appended in right-frame order. H2O's own
        # ASTMerge returns key-sorted rows, so row order is an implementation
        # contract here, not pandas compatibility; the STR/TIME host
        # fallback below keeps pandas' native ordering.
        gl, gr = dev
        lo_d, m_d, rorder_d, matched_d = _join_stats(gl, gr, need_matched=all_y)
        if fused and left.nrow and right.nrow:
            # compiled expansion: the five np.repeat passes below as one
            # device searchsorted program (frame/munge.join_expand) —
            # identical (li, ri) bits by construction
            li, ri = _mg.join_expand(
                lo_d, m_d, rorder_d, matched_d, all_x, all_y, right.nrow)
            lvalid = li >= 0
        else:
            _mg.fallback("tiny_join")
            lo, m, rorder, matched_r = (
                np.asarray(lo_d, np.int64),
                np.asarray(m_d, np.int64),
                np.asarray(rorder_d, np.int64),
                np.asarray(matched_d, bool),
            )
            nr = right.nrow
            m_out = np.maximum(m, 1) if all_x else m
            li = np.repeat(np.arange(left.nrow, dtype=np.int64), m_out)
            off = np.repeat(np.cumsum(m_out) - m_out, m_out)
            within = np.arange(len(li), dtype=np.int64) - off
            has = np.repeat(m > 0, m_out)
            rpos = np.repeat(lo, m_out) + within
            ri = np.where(
                has, rorder[np.minimum(rpos, max(nr - 1, 0))] if nr else -1, -1
            ).astype(np.int64)
            if all_y and nr:
                extra = np.nonzero(~matched_r)[0].astype(np.int64)
                li = np.concatenate([li, np.full(len(extra), -1, np.int64)])
                ri = np.concatenate([ri, extra])
            lvalid = li >= 0
    else:
        how = (
            "outer" if (all_x and all_y) else "left" if all_x else "right" if all_y else "inner"
        )

        def _key_col(v):
            x = v.to_numpy()
            if v.kind == CAT:  # join on LABELS — codes are frame-local
                dom = np.asarray(list(v.domain or ()) + [None], dtype=object)
                return dom[np.where(x >= 0, x, len(dom) - 1).astype(np.int64)]
            return x

        lk = pd.DataFrame({c: _key_col(left.vec(c)) for c in bx})
        rk = pd.DataFrame({c: _key_col(right.vec(c)) for c in bby})
        lk["__li__"] = np.arange(left.nrow, dtype=np.int64)
        rk["__ri__"] = np.arange(right.nrow, dtype=np.int64)
        j = lk.merge(rk, left_on=bx, right_on=bby, how=how, suffixes=("", "__rk"))
        li = j["__li__"].to_numpy()
        ri = j["__ri__"].to_numpy()
        lvalid = ~pd.isna(li)
        rvalid = ~pd.isna(ri)
        li = np.where(lvalid, li, -1).astype(np.int64)
        ri = np.where(rvalid, ri, -1).astype(np.int64)

    lg = left.gather_rows(li)
    rcols = [n for n in right.names if n not in set(bby)]
    rg = right[rcols].gather_rows(ri) if rcols else None

    # join keys: take from whichever side has them (left wins; right-only
    # rows of an outer/right join fill from the right key columns)
    out_vecs, out_names = [], []
    for i, n in enumerate(lg.names):
        v = lg.vec(n)
        if n in set(bx) and (~lvalid).any():
            rkey = right.vec(bby[bx.index(n)]) if bby[bx.index(n)] in right else None
            if rkey is not None:
                patched = right[[bby[bx.index(n)]]].gather_rows(ri).vec(0)
                v = _coalesce_vec(v, patched, lvalid)
        out_vecs.append(v)
        out_names.append(n)
    if rg is not None:
        taken = set(out_names)
        for n in rg.names:
            out_vecs.append(rg.vec(n))
            out_names.append(n + "_y" if n in taken else n)
    return Frame(out_vecs, out_names)


def _coalesce_vec(a, b, use_a: np.ndarray):
    """a where use_a else b — for filling join keys of right-only rows."""
    import jax

    from h2o3_tpu.frame.frame import CAT, STR, Vec
    from h2o3_tpu.parallel.mesh import row_sharding

    if a.kind == STR:
        out = a._host.copy()
        out[~use_a] = b._host[~use_a]
        return Vec(out, STR, name=a.name)
    if a.kind == CAT and tuple(a.domain or ()) != tuple(b.domain or ()):
        # differing enum domains: rebuild over the union (host; key columns
        # of outer joins only — payload columns never coalesce)
        av, bv = a.to_numpy(), b.to_numpy()
        dom = _domain_union(a.domain, b.domain)
        lut_b = {d: i for i, d in enumerate(dom)}
        bmap = np.array([lut_b[d] for d in (b.domain or ())], np.int64)
        codes = np.where(
            use_a, av, np.where(bv >= 0, bmap[np.clip(bv, 0, None).astype(np.int64)], -1)
        )
        return Vec.from_numpy(codes.astype(np.int64), CAT, name=a.name, domain=tuple(dom))
    npad = a.data.shape[0]
    mask = np.zeros(npad, bool)
    mask[: len(use_a)] = use_a
    data = jax.device_put(
        jnp.where(jnp.asarray(mask), a.data, b.data), row_sharding()
    )
    return Vec(data, a.kind, name=a.name, domain=a.domain, nrow=a.nrow)


def sort(frame: Frame, by: Sequence[str] | str, ascending: bool | Sequence[bool] = True) -> Frame:
    by = [by] if isinstance(by, str) else list(by)
    asc = [ascending] * len(by) if isinstance(ascending, bool) else list(ascending)
    vs = [frame.vec(b) for b in by]
    from h2o3_tpu.frame import munge as _mg

    if all(v.kind not in (STR, TIME) for v in vs):
        if _mg.fuse_on():
            # one cached program: key prep (enum cast, descending negation)
            # + lexsort compiled together — same keys, same stable lexsort,
            # same order bits as the eager lane below
            order = _mg.sort_order(
                [v.data for v in vs], [v.kind for v in vs], asc, frame.nrow)
            return frame.gather_rows(order)
        # device multi-key stable lexsort (numerics sort NaN last either
        # direction, matching pandas na_position='last'; enums sort by code
        # with NA (-1) first ascending, exactly the former host behavior)
        keys = []
        for v, a in zip(vs, asc):
            k = v.data[: v.nrow]
            if v.kind == CAT:
                k = k.astype(jnp.float32)
            if not a:
                k = -k  # NaN stays NaN → still sorts last, like pandas
            keys.append(k)
        order = jnp.lexsort(tuple(reversed(keys)))  # np.lexsort: last = primary
        return frame.gather_rows(np.asarray(order))
    _mg.fallback("host_keys")
    df = pd.DataFrame({b: frame.vec(b).to_numpy() for b in by})
    order = df.sort_values(by=by, ascending=asc, kind="stable").index.to_numpy()
    return frame.gather_rows(order)


# ---------------------------------------------------------------------------
# quantile / table / unique / cut / impute
# ---------------------------------------------------------------------------


@jax.jit
def _sorted_valid(x):
    return jnp.sort(x), (~jnp.isnan(x)).sum(dtype=jnp.int32)


def quantile(frame_or_vec, prob: Sequence[float] = (0.001, 0.01, 0.1, 0.25, 0.333, 0.5, 0.667, 0.75, 0.9, 0.99, 0.999), weights: Vec | None = None) -> Frame:
    """``h2o.quantile`` successor (interpolation type 7, H2O's default).

    ``weights`` (a numeric Vec aligned with the input) switches to the
    weighted quantile with OBSERVATION-COUNT semantics, like the
    weights_column contract everywhere else in the framework: integer
    weights give exactly the quantiles of the row-replicated sample, and
    fractional weights interpolate that continuously. Consequently results
    are intentionally NOT invariant under uniform weight rescaling —
    halving all weights halves the implied sample size, exactly as
    de-duplicating rows would. Normalized weights (sum ~1) are degenerate
    under this reading and trigger a warning."""
    if isinstance(frame_or_vec, Vec):
        vecs = [frame_or_vec]
    else:
        vecs = [frame_or_vec.vec(n) for n in frame_or_vec.names if frame_or_vec.vec(n).is_numeric()]
    probs = np.asarray(prob, dtype=np.float64)
    out = {"Probs": probs}
    wall = None if weights is None else np.asarray(weights.to_numpy(), np.float64)
    warned = False
    for v in vecs:
        if wall is None:
            s, cnt = _sorted_valid(v.data)  # NaN sorts to the end
            s = np.asarray(s)[: int(cnt)]
        else:
            x = v.to_numpy().astype(np.float64)
            ok = ~np.isnan(x) & ~np.isnan(wall) & (wall > 0)
            order = np.argsort(x[ok], kind="mergesort")
            s = x[ok][order]
            sw = wall[ok][order]
        if len(s) == 0:
            out[v.name] = np.full(len(probs), np.nan)
            continue
        if wall is None:
            idx = (len(s) - 1) * probs
            lo = np.floor(idx).astype(int)
            hi = np.minimum(np.ceil(idx).astype(int), len(s) - 1)
            out[v.name] = s[lo] * (1 - (idx - lo)) + s[hi] * (idx - lo)
            continue
        # weighted type-7: the target position t = p*(W-1) on the
        # REPLICATED scale (element i occupies [left_i, left_i + w_i));
        # both brackets resolve through the cumulative weights, which makes
        # integer weights exactly equivalent to physically replicating rows
        cw = np.cumsum(sw)
        if cw[-1] < 2.0 and not warned:
            # per-COLUMN effective weight (rows where this column is NaN are
            # dropped, so a mostly-missing column can degenerate even when
            # the frame's total weight is large); warn once per call
            warned = True
            from h2o3_tpu.utils.log import Log

            Log.warn(
                "weighted quantile: effective total weight < 2 for column "
                f"{v.name!r} — weights are observation counts (replication "
                "semantics), not normalized fractions; results degenerate "
                "toward the minimum")
        t = probs * max(cw[-1] - 1.0, 0.0)
        k = np.floor(t)
        frac = t - k
        j1 = np.clip(np.searchsorted(cw, k, side="right"), 0, len(s) - 1)
        j2 = np.clip(np.searchsorted(cw, k + 1.0, side="right"), 0, len(s) - 1)
        out[v.name] = s[j1] * (1 - frac) + s[j2] * frac
    return Frame.from_pandas(pd.DataFrame(out))


def table(v1: Vec, v2: Vec | None = None, dense: bool = True) -> Frame:
    """``h2o.table`` successor: level counts for one or two columns."""

    def as_labels(v: Vec):
        if v.kind == CAT:
            dom = np.asarray(list(v.domain or ()) + [None], dtype=object)
            return dom[v.to_numpy()]
        return v.to_numpy()

    if v2 is None:
        s = pd.Series(as_labels(v1)).value_counts(sort=False).sort_index()
        df = pd.DataFrame({v1.name or "C1": s.index.to_numpy(), "Count": s.to_numpy()})
        return Frame.from_pandas(df)
    ct = pd.crosstab(pd.Series(as_labels(v1)), pd.Series(as_labels(v2)))
    rows = ct.stack().reset_index()
    rows.columns = [v1.name or "C1", v2.name or "C2", "Counts"]
    if dense:
        rows = rows[rows["Counts"] > 0]
    return Frame.from_pandas(rows)


def unique(v: Vec) -> Frame:
    if v.kind == CAT:
        dom = np.asarray(list(v.domain or ()), dtype=object)
        present = np.unique(v.to_numpy())
        present = present[present >= 0]
        vals = dom[present]
    else:
        vals = pd.unique(v.to_numpy())
        vals = vals[~pd.isna(vals)]
    return Frame.from_pandas(pd.DataFrame({v.name or "C1": vals}))


def match(v: Vec, table: Sequence, nomatch: float = float("nan"), start_index: int = 1) -> Vec:
    """``ASTMatch`` successor (R ``match`` / ``%in%``): position of each
    value in ``table`` (``start_index``-based, H2O default 1), ``nomatch``
    where absent. Enum vecs match on LABELS."""
    if v.kind == CAT:
        pos = {str(t): i for i, t in enumerate(table)}
        dom_map = np.full(max(len(v.domain or ()), 1), -1, np.int64)
        for i, d in enumerate(v.domain or ()):
            if str(d) in pos:
                dom_map[i] = pos[str(d)]
        codes = v.to_numpy()
        hit = np.where(codes >= 0, dom_map[np.clip(codes, 0, None).astype(np.int64)], -1)
    elif v.kind == STR:
        pos = {str(t): i for i, t in enumerate(table)}
        hit = np.array([pos.get(str(s), -1) if s is not None else -1 for s in v._host])
    else:
        # non-numeric table entries can never match a numeric vec: coerce to
        # NaN (NaN != x for all x) instead of crashing, like R's match
        tbl_np = pd.to_numeric(pd.Series(list(table)), errors="coerce").to_numpy(np.float32)
        tbl = jnp.asarray(tbl_np)
        x = v.data[: v.nrow]
        eq = x[:, None] == tbl[None, :]
        hit = np.asarray(jnp.where(eq.any(axis=1), jnp.argmax(eq, axis=1), -1))
    out = np.where(hit >= 0, hit + start_index, nomatch).astype(np.float64)
    return Vec.from_numpy(out, NUM, name=v.name)


def is_in(v: Vec, table: Sequence) -> Vec:
    """R ``%in%``: 1.0 where the value occurs in ``table`` else 0.0."""
    m = match(v, table, nomatch=0.0, start_index=1).to_numpy()
    return Vec.from_numpy((m > 0).astype(np.float64), NUM, name=v.name)


def which(v: Vec) -> Frame:
    """``ASTWhich`` successor: 0-based row indices where the vec is true
    (nonzero and non-NA), as a one-column frame — h2o.which semantics."""
    x = v.to_numpy()
    idx = np.flatnonzero(np.nan_to_num(x, nan=0.0) != 0)
    return Frame.from_pandas(pd.DataFrame({v.name or "which": idx.astype(np.float64)}))


def na_omit(frame: Frame) -> Frame:
    """``ASTNaOmit`` successor: drop every row containing an NA (device
    mask; payload gathered on device)."""
    import functools

    masks = []
    for n in frame.names:
        v = frame.vec(n)
        if v.kind == STR:
            masks.append(jnp.asarray(np.array([s is not None for s in v._host])))
        elif v.kind == CAT:
            masks.append(v.data[: v.nrow] >= 0)
        else:
            masks.append(~jnp.isnan(v.data[: v.nrow]))
    ok = np.asarray(functools.reduce(jnp.logical_and, masks))
    return frame.subset_rows(np.flatnonzero(ok))


def rank_within_group_by(
    frame: Frame,
    group_by_cols: Sequence[str],
    sort_cols: Sequence[str],
    ascending: Sequence[bool] | bool = True,
    new_col_name: str = "New_Rank_column",
    sort_cols_sorted: bool = False,
) -> Frame:
    """``ASTRankWithinGroupBy`` successor (h2o.rank_within_group_by): dense
    1-based rank of each row within its group, ordered by ``sort_cols``.

    Device lexsort over (group keys, sort keys); rank = position within the
    group run. NA sort-key rows keep rank NA like upstream. When
    ``sort_cols_sorted`` the output rows come back sorted by the group+sort
    order, else original row order."""
    from h2o3_tpu.frame import munge as _mg

    _mg.fallback("rank_within_group_by")  # eager lexsort lane for now
    gcols = list(group_by_cols)
    scols = list(sort_cols)
    asc = [ascending] * len(scols) if isinstance(ascending, bool) else list(ascending)
    keys = []
    n_gkeys = len(gcols)
    for n in gcols:
        k = _key_codes_device(frame.vec(n))
        if k is None:
            raise ValueError(f"rank_within_group_by: unsupported key column {n!r}")
        keys.append(k)  # int32 — f32 cannot represent bitcast codes exactly
    na_mask = jnp.zeros(frame.nrow, bool)
    for n, a in zip(scols, asc):
        v = frame.vec(n)
        k = v.data[: v.nrow]
        if v.kind == CAT:
            k = k.astype(jnp.float32)
            na_mask = na_mask | (k < 0)
        else:
            na_mask = na_mask | jnp.isnan(k)
        keys.append(k if a else -k)
    order = jnp.lexsort(tuple(reversed(keys)))  # last key = primary
    if n_gkeys:
        gsorted = jnp.stack([keys[i] for i in range(n_gkeys)], axis=1)[order]
        new_grp = jnp.concatenate(
            [jnp.ones(1, bool), jnp.any(gsorted[1:] != gsorted[:-1], axis=1)]
        )
    else:  # no grouping: one global group
        new_grp = jnp.zeros(frame.nrow, bool).at[0].set(True)
    pos = jnp.arange(frame.nrow, dtype=jnp.int32)
    # rank within group = position - position of the group's first row
    # (running max of group-start positions along the sorted order)
    grp_start_run = jax.lax.cummax(jnp.where(new_grp, pos, 0))
    rank_sorted = pos - grp_start_run + 1
    ranks = jnp.zeros(frame.nrow, jnp.float32).at[order].set(
        rank_sorted.astype(jnp.float32)
    )
    ranks = jnp.where(na_mask, jnp.float32(np.nan), ranks)
    rank_vec = Vec.from_numpy(np.asarray(ranks, np.float64), NUM, name=new_col_name)
    out = Frame(
        [frame.vec(n) for n in frame.names] + [rank_vec],
        list(frame.names) + [new_col_name],
    )
    if sort_cols_sorted:
        return out.gather_rows(np.asarray(order))
    return out


def pivot(frame: Frame, index: str, column: str, value: str) -> Frame:
    """``ASTPivot`` successor: long → wide. One output row per ``index``
    value, one output column per ``column`` enum level, cells = mean of
    ``value`` over the (index, level) pair (upstream averages duplicates)."""
    from h2o3_tpu.frame import munge as _mg

    cv = frame.vec(column)
    if cv.kind != CAT:
        raise ValueError("pivot: 'column' must be categorical")
    _mg.fallback("pivot")  # host long→wide reshape stays eager for now
    agg = group_by(frame, [index, column]).agg({value: "mean"})
    adf = agg.to_pandas()
    vcol = f"mean_{value}"  # group_by agg naming convention
    wide = adf.pivot(index=index, columns=column, values=vcol).reset_index()
    wide.columns = [str(c) for c in wide.columns]
    return Frame.from_pandas(wide)


def stratified_split(y: Vec, test_frac: float = 0.2, seed: int = -1) -> Vec:
    """``ASTStratifiedSplit`` successor (h2o.stratified_split): enum vec
    'train'/'test' with ~``test_frac`` of EACH response class in 'test'."""
    if y.kind != CAT:
        raise ValueError("stratified_split needs a categorical response")
    codes = y.to_numpy()
    rng = np.random.default_rng(seed if seed and seed > 0 else None)
    out = np.zeros(len(codes), np.int32)  # 0 = train, 1 = test
    for k in np.unique(codes):
        idx = np.flatnonzero(codes == k)
        n_test = int(round(len(idx) * test_frac))
        take = rng.permutation(len(idx))[:n_test]
        out[idx[take]] = 1
    out[codes < 0] = 0  # NA response rows go to train, like upstream
    return Vec.from_numpy(out, CAT, name="test_train_split", domain=("train", "test"))


def relevel(v: Vec, y: str) -> Vec:
    """``ASTRelevel`` successor (h2o.relevel): move level ``y`` to the front
    of the domain (the reference level for GLM one-hot drops)."""
    if v.kind != CAT:
        raise ValueError("relevel needs a categorical column")
    dom = list(v.domain or ())
    if y not in dom:
        raise ValueError(f"level {y!r} not in domain")
    new_dom = [y] + [d for d in dom if d != y]
    lut = np.array([new_dom.index(d) for d in dom], np.int32)
    codes = v.to_numpy()
    remapped = np.where(codes >= 0, lut[np.clip(codes, 0, None).astype(np.int64)], -1)
    return Vec.from_numpy(remapped, CAT, name=v.name, domain=new_dom)


def signif(v: Vec, digits: int = 6) -> Vec:
    """R ``signif``: round to significant digits (ASTSignif)."""
    x = v.to_numpy().astype(np.float64)
    with np.errstate(divide="ignore", invalid="ignore"):
        mag = np.where(x != 0, np.floor(np.log10(np.abs(x))), 0.0)
        factor = np.power(10.0, digits - 1 - mag)
        out = np.where(np.isfinite(x), np.round(x * factor) / factor, x)
    return Vec.from_numpy(out, NUM, name=v.name)


def cut(v: Vec, breaks: Sequence[float], labels: Sequence[str] | None = None,
        include_lowest: bool = False, right: bool = True) -> Vec:
    """``ASTCut`` successor: numeric → enum by interval."""
    got = pd.cut(v.to_numpy(), bins=list(breaks), labels=labels,
                 include_lowest=include_lowest, right=right)
    dom = [str(c) for c in got.categories]
    return Vec.from_numpy(got.codes.astype(np.int32), CAT, name=v.name, domain=dom)


def impute(frame: Frame, column: str, method: str = "mean",
           by: Sequence[str] | None = None) -> float | list:
    """``h2o.impute`` successor — fills NAs in place (returns fill value(s))."""
    v = frame.vec(column)
    if by:
        gb = GroupBy(frame, by)
        agg = "mean" if method == "mean" else "median" if method == "median" else "mode"
        if v.kind == CAT:
            agg = "mode"  # categorical columns can only take the group mode
        gfr = gb.agg({column: agg})
        fill_per_group = gfr.vec(f"{agg}_{column}").to_numpy()
        gid = gb._gid
        if v.kind == CAT:
            codes = v.to_numpy().astype(np.int64)
            na = (codes < 0) & (gid >= 0) & ~np.isnan(fill_per_group[np.clip(gid, 0, None)])
            codes[na] = fill_per_group[gid[na]].astype(np.int64)
            _replace_vec(frame, column, Vec.from_numpy(codes, CAT, name=column, domain=v.domain))
        else:
            vals = v.to_numpy().astype(np.float64)
            na = np.isnan(vals) & (gid >= 0)
            vals[na] = fill_per_group[gid[na]]
            _replace_vec(frame, column, Vec.from_numpy(vals, v.kind, name=column))
        return fill_per_group.tolist()
    if v.kind == CAT:
        codes = v.to_numpy()
        valid = codes[codes >= 0]
        fill = int(pd.Series(valid).mode().iloc[0]) if len(valid) else -1
        codes = np.where(codes < 0, fill, codes)
        _replace_vec(frame, column, Vec.from_numpy(codes, CAT, name=column, domain=v.domain))
        return float(fill)
    vals = v.to_numpy().astype(np.float64)
    if method == "median":
        fill = float(np.nanmedian(vals))
    elif method == "mode":
        fill = float(pd.Series(vals).mode().iloc[0])
    else:
        fill = float(np.nanmean(vals))
    vals = np.where(np.isnan(vals), fill, vals)
    _replace_vec(frame, column, Vec.from_numpy(vals, v.kind, name=column))
    return fill


def _replace_vec(frame: Frame, column: str, new: Vec) -> None:
    i = frame._index(column)
    frame._vecs[i] = new
    new.name = frame._names[i]


# ---------------------------------------------------------------------------
# scale / correlation / variance — device matmul over standardized columns
# ---------------------------------------------------------------------------


def scale(frame: Frame, center: bool = True, scale_: bool = True) -> Frame:
    vecs = []
    for n in frame.names:
        v = frame.vec(n)
        if not v.is_numeric():
            vecs.append(v)
            continue
        mu = v.mean() if center else 0.0
        sd = v.sigma() if scale_ else 1.0
        sd = sd if sd and np.isfinite(sd) and sd > 0 else 1.0
        vecs.append(Vec(_scale_kernel(v.data, jnp.float32(mu), jnp.float32(sd)), NUM, nrow=v.nrow))
    return Frame(vecs, frame.names)


@jax.jit
def _scale_kernel(x, mu, sd):
    return (x - mu) / sd


def cor(frame: Frame, use: str = "complete.obs") -> Frame:
    """Pearson correlation matrix over numeric columns (device Gram)."""
    names = [n for n in frame.names if frame.vec(n).is_numeric()]
    X = np.stack([frame.vec(n).to_numpy().astype(np.float64) for n in names], axis=1)
    if use == "complete.obs":
        X = X[~np.isnan(X).any(axis=1)]
    c = np.corrcoef(X, rowvar=False)
    df = pd.DataFrame(np.atleast_2d(c), columns=names)
    return Frame.from_pandas(df)


def var(frame: Frame) -> Frame:
    names = [n for n in frame.names if frame.vec(n).is_numeric()]
    X = np.stack([frame.vec(n).to_numpy().astype(np.float64) for n in names], axis=1)
    X = X[~np.isnan(X).any(axis=1)]
    c = np.cov(X, rowvar=False)
    return Frame.from_pandas(pd.DataFrame(np.atleast_2d(c), columns=names))


# ---------------------------------------------------------------------------
# string ops (host-side; on enum columns they rewrite the domain, like H2O)
# ---------------------------------------------------------------------------


def _str_apply(v: Vec, fn) -> Vec:
    if v.kind == CAT:
        dom = [fn(d) for d in (v.domain or ())]
        # collapsing domains (e.g. tolower making levels equal) → remap codes
        new_dom: list[str] = []
        lut: dict[str, int] = {}
        remap = np.empty(len(dom) + 1, dtype=np.int32)
        remap[-1] = -1
        for i, d in enumerate(dom):
            if d not in lut:
                lut[d] = len(new_dom)
                new_dom.append(d)
            remap[i] = lut[d]
        return Vec.from_numpy(remap[v.to_numpy()], CAT, name=v.name, domain=new_dom)
    if v.kind != STR:
        raise TypeError(f"string op on {v.kind} column")
    vals = np.array([fn(s) if s is not None else None for s in v.to_numpy()], dtype=object)
    return Vec(vals, STR, name=v.name)


def toupper(v: Vec) -> Vec:
    return _str_apply(v, str.upper)


def tolower(v: Vec) -> Vec:
    return _str_apply(v, str.lower)


def trim(v: Vec) -> Vec:
    return _str_apply(v, str.strip)


def sub(v: Vec, pattern: str, replacement: str) -> Vec:
    import re

    rx = re.compile(pattern)
    return _str_apply(v, lambda s: rx.sub(replacement, s, count=1))


def gsub(v: Vec, pattern: str, replacement: str) -> Vec:
    import re

    rx = re.compile(pattern)
    return _str_apply(v, lambda s: rx.sub(replacement, s))


def nchar(v: Vec) -> Vec:
    if v.kind == CAT:
        dom_len = np.array([len(d) for d in (v.domain or ())] + [np.nan], dtype=np.float64)
        return Vec.from_numpy(dom_len[v.to_numpy()], NUM, name=v.name)
    vals = np.array([len(s) if s is not None else np.nan for s in v.to_numpy()])
    return Vec.from_numpy(vals, NUM, name=v.name)


def substring(v: Vec, start: int, end: int | None = None) -> Vec:
    return _str_apply(v, lambda s: s[start:end])


def strsplit(v: Vec, pattern: str) -> Frame:
    import re

    rx = re.compile(pattern)
    if v.kind == CAT:
        vals = np.asarray(list(v.domain or ()) + [None], dtype=object)[v.to_numpy()]
    else:
        vals = v.to_numpy()
    parts = [rx.split(s) if s is not None else [] for s in vals]
    width = max((len(p) for p in parts), default=0)
    cols = {}
    for j in range(width):
        cols[f"C{j + 1}"] = np.array(
            [p[j] if j < len(p) else None for p in parts], dtype=object
        )
    df = pd.DataFrame(cols)
    return Frame.from_pandas(df, column_types={c: STR for c in cols})


def lstrip(v: Vec, chars: str | None = None) -> Vec:
    return _str_apply(v, lambda s: s.lstrip(chars))


def rstrip(v: Vec, chars: str | None = None) -> Vec:
    return _str_apply(v, lambda s: s.rstrip(chars))


def countmatches(v: Vec, patterns) -> Vec:
    """``ASTCountMatches`` successor: total occurrences of any of the
    substring patterns per row (NA rows stay NA)."""
    pats = [patterns] if isinstance(patterns, str) else list(patterns)

    def count(s: str) -> float:
        return float(sum(s.count(p) for p in pats))

    if v.kind == CAT:
        per_level = np.array([count(d) for d in (v.domain or ())] + [np.nan])
        return Vec.from_numpy(per_level[v.to_numpy()], NUM, name=v.name)
    vals = np.array([np.nan if s is None else count(s) for s in v.to_numpy()])
    return Vec.from_numpy(vals, NUM, name=v.name)


def entropy(v: Vec) -> Vec:
    """``ASTEntropy`` successor: per-string Shannon entropy over characters."""

    def ent(s: str) -> float:
        if not s:
            return 0.0
        _, counts = np.unique(list(s), return_counts=True)
        p = counts / counts.sum()
        return float(-(p * np.log2(p)).sum())

    if v.kind == CAT:
        per_level = np.array([ent(d) for d in (v.domain or ())] + [np.nan])
        return Vec.from_numpy(per_level[v.to_numpy()], NUM, name=v.name)
    vals = np.array([np.nan if s is None else ent(s) for s in v.to_numpy()])
    return Vec.from_numpy(vals, NUM, name=v.name)


def grep(v: Vec, pattern: str) -> Vec:
    """0/1 match indicator (H2O grep returns matching row indices; the
    indicator form composes with boolean masking)."""
    import re

    rx = re.compile(pattern)
    if v.kind == CAT:
        hit = np.array([1.0 if rx.search(d) else 0.0 for d in (v.domain or ())] + [np.nan])
        return Vec.from_numpy(hit[v.to_numpy()], NUM, name=v.name)
    vals = np.array(
        [np.nan if s is None else (1.0 if rx.search(s) else 0.0) for s in v.to_numpy()]
    )
    return Vec.from_numpy(vals, NUM, name=v.name)


# ---------------------------------------------------------------------------
# time-component ops (host, from the exact epoch-ms copy)
# ---------------------------------------------------------------------------


def _time_component(v: Vec, comp: str) -> Vec:
    ms = v.to_numpy().astype(np.float64)
    dt = pd.to_datetime(pd.Series(ms), unit="ms")
    if comp == "dayOfWeek":
        vals = dt.dt.dayofweek.to_numpy().astype(np.float64)  # Mon=0, like H2O
    elif comp == "week":
        vals = dt.dt.isocalendar().week.to_numpy().astype(np.float64)
    else:
        vals = getattr(dt.dt, comp).to_numpy().astype(np.float64)
    vals = np.where(np.isnan(ms), np.nan, vals)
    return Vec.from_numpy(vals, INT, name=v.name)


def year(v):
    return _time_component(v, "year")


def month(v):
    return _time_component(v, "month")


def day(v):
    return _time_component(v, "day")


def hour(v):
    return _time_component(v, "hour")


def minute(v):
    return _time_component(v, "minute")


def second(v):
    return _time_component(v, "second")


def day_of_week(v):
    return _time_component(v, "dayOfWeek")


def week(v):
    return _time_component(v, "week")


# ---------------------------------------------------------------------------
# type conversions
# ---------------------------------------------------------------------------


def interaction(frame, factors: list[str], pairwise: bool = False,
                max_factors: int = 100, min_occurrence: int = 1,
                destination_frame: str | None = None):
    """Factor-interaction columns — ``h2o.interaction`` / the Interaction
    handler successor [UNVERIFIED upstream path hex/Interaction.java].

    ``factors`` are categorical column names; one N-way interaction column
    (or all pairwise ones) is built whose levels are the observed
    ``a_b`` combinations. The ``max_factors`` most frequent levels are
    kept (ties by level order); everything else — including levels seen
    fewer than ``min_occurrence`` times — lumps into a catch-all
    ``other.values`` level, matching upstream's enforced-cap behavior.
    """
    from h2o3_tpu.frame.frame import CAT, Frame

    if len(factors) < 2:
        raise ValueError("interaction needs at least two factor columns")
    for f in factors:
        if not frame.vec(f).is_categorical():
            raise ValueError(f"interaction column {f!r} is not categorical")

    combos = (
        [(a, b) for i, a in enumerate(factors) for b in factors[i + 1:]]
        if pairwise else [tuple(factors)]
    )
    # one device->host pull per column, shared across pairwise combos
    col_codes = {f: frame.vec(f).to_numpy().astype(np.int64) for f in factors}
    vecs, names = [], []
    for combo in combos:
        cards = [len(frame.vec(f).domain) for f in combo]
        prod = 1
        for card in cards:
            prod *= max(card, 1)
            if prod > (1 << 62):
                raise ValueError(
                    "interaction cardinality product overflows the combined "
                    f"code space ({'x'.join(map(str, cards))})")
        codes = None
        for f, card in zip(combo, cards):
            c = col_codes[f]
            na = c < 0
            codes = c.copy() if codes is None else codes * card + c
            codes = np.where(na | (codes < 0), -1, codes)
        valid = codes >= 0
        uniq, counts = np.unique(codes[valid], return_counts=True)
        keep = uniq[counts >= max(min_occurrence, 1)]
        kcounts = counts[counts >= max(min_occurrence, 1)]
        if len(keep) > max(max_factors, 1):
            order = np.argsort(-kcounts, kind="stable")[: max(max_factors, 1)]
            keep = keep[np.sort(order)]  # stable level order like upstream
        doms = [frame.vec(f).domain for f in combo]

        def _label(code: int) -> str:
            parts = []
            for card, dom in zip(reversed(cards), reversed(doms)):
                parts.append(dom[code % card])
                code //= card
            return "_".join(reversed(parts))

        levels = [_label(int(u)) for u in keep]
        # map observed codes -> kept-level index by search over the SORTED
        # kept codes (dense-LUT-by-code-space would be O(prod cardinalities))
        catch_all = len(levels)
        if len(keep):
            pos = np.searchsorted(keep, codes)
            pos = np.minimum(pos, len(keep) - 1)
            hit = valid & (keep[pos] == codes)
        else:  # nothing survived min_occurrence: all rows -> catch-all
            pos = np.zeros_like(codes)
            hit = np.zeros_like(valid)
        mapped = np.where(hit, pos, np.where(valid, catch_all, -1))
        has_other = bool((valid & ~hit).any())
        if has_other:
            levels = levels + ["other.values"]
        name = "_".join(combo)
        names.append(name)
        vecs.append(Vec.from_numpy(mapped.astype(np.int32), CAT, name=name,
                                   domain=tuple(levels)))
    if destination_frame:
        return Frame(vecs, names, key=destination_frame, register=True)
    return Frame(vecs, names)


def asfactor(v: Vec) -> Vec:
    if v.kind == CAT:
        return v
    if v.kind == STR:
        vals = v.to_numpy()
        levels = sorted({str(s) for s in vals if s is not None})
        lut = {s: i for i, s in enumerate(levels)}
        codes = np.array([lut[str(s)] if s is not None else -1 for s in vals], dtype=np.int32)
        return Vec.from_numpy(codes, CAT, name=v.name, domain=levels)
    vals = v.to_numpy()
    uniq = np.unique(vals[~np.isnan(vals)])
    # integral numerics render without decimal point, like H2O's asfactor
    labels = [str(int(u)) if float(u).is_integer() else str(u) for u in uniq]
    lut = {u: i for i, u in enumerate(uniq)}
    codes = np.array([lut[x] if not np.isnan(x) else -1 for x in vals], dtype=np.int32)
    return Vec.from_numpy(codes, CAT, name=v.name, domain=labels)


def asnumeric(v: Vec) -> Vec:
    if v.is_numeric():
        return v
    if v.kind == CAT:
        # numeric-looking domains convert by value; otherwise by code (H2O)
        dom = list(v.domain or ())
        try:
            by_val = np.array([float(d) for d in dom] + [np.nan])
        except ValueError:
            by_val = np.array([float(i) for i in range(len(dom))] + [np.nan])
        return Vec.from_numpy(by_val[v.to_numpy()], NUM, name=v.name)
    vals = pd.to_numeric(pd.Series(v.to_numpy()), errors="coerce").to_numpy()
    return Vec.from_numpy(vals, NUM, name=v.name)


def ascharacter(v: Vec) -> Vec:
    if v.kind == STR:
        return v
    if v.kind == CAT:
        dom = np.asarray(list(v.domain or ()) + [None], dtype=object)
        return Vec(dom[v.to_numpy()], STR, name=v.name)
    vals = np.array([None if np.isnan(x) else str(x) for x in v.to_numpy()], dtype=object)
    return Vec(vals, STR, name=v.name)


# ---------------------------------------------------------------------------
# histogram of a numeric column (ASTHist successor)
# ---------------------------------------------------------------------------


def hist(v: Vec, breaks: int | Sequence[float] = 20) -> Frame:
    vals = v.to_numpy()
    vals = vals[~np.isnan(vals)]
    counts, edges = np.histogram(vals, bins=breaks)
    mids = (edges[:-1] + edges[1:]) / 2
    return Frame.from_pandas(pd.DataFrame({"breaks": edges[1:], "mids": mids, "counts": counts}))


# ---------------------------------------------------------------------------
# attach operators & methods to Vec / Frame
# ---------------------------------------------------------------------------


def _attach():
    def make_bin(op, reflected=False):
        def fn(self, other):
            v = self.vec(0) if isinstance(self, Frame) else self
            other = other.vec(0) if isinstance(other, Frame) else other
            return _binop(v, other, op, reflected=reflected)

        return fn

    for name, op in [
        ("__add__", "+"), ("__sub__", "-"), ("__mul__", "*"), ("__truediv__", "/"),
        ("__floordiv__", "//"), ("__mod__", "%"), ("__pow__", "**"),
        ("__eq__", "=="), ("__ne__", "!="), ("__lt__", "<"), ("__le__", "<="),
        ("__gt__", ">"), ("__ge__", ">="), ("__and__", "&"), ("__or__", "|"),
    ]:
        setattr(Vec, name, make_bin(op))
    for name, op in [
        ("__radd__", "+"), ("__rsub__", "-"), ("__rmul__", "*"), ("__rtruediv__", "/"),
        ("__rpow__", "**"), ("__rmod__", "%"),
    ]:
        setattr(Vec, name, make_bin(op, reflected=True))
    Vec.__hash__ = lambda self: id(self)
    Frame.__hash__ = lambda self: hash(self.key)

    for op in _UNOPS:
        name = {"not": "logical_not"}.get(op, op)
        setattr(Vec, name, (lambda o: lambda self: _unop(self, o))(op))
    for op in _CUMOPS:
        setattr(Vec, op, (lambda o: lambda self: _cumulative(self, o))(op))

    Vec.asfactor = asfactor
    Vec.asnumeric = asnumeric
    Vec.ascharacter = ascharacter
    Vec.toupper = toupper
    Vec.tolower = tolower
    Vec.trim = trim
    Vec.nchar = nchar
    Vec.sub_ = sub
    Vec.gsub = gsub
    Vec.substring = substring
    Vec.strsplit = strsplit
    Vec.grep = grep
    Vec.year = year
    Vec.month = month
    Vec.day = day
    Vec.hour = hour
    Vec.minute = minute
    Vec.second = second
    Vec.day_of_week = day_of_week
    Vec.week = week
    Vec.table = table
    Vec.unique = unique
    Vec.cut = cut
    Vec.quantile = quantile
    Vec.isna = lambda self: _unop(self, "isna")

    Frame.group_by = group_by
    Frame.merge = merge
    Frame.sort = sort
    Frame.quantile = quantile
    Frame.impute = impute
    Frame.scale = scale
    Frame.cor = cor
    Frame.var = var

    def frame_set(self, name, value):
        """``frame["col"] = vec`` — column add/replace."""
        if isinstance(value, Frame):
            value = value.vec(0)
        if isinstance(value, (int, float)):
            value = Vec.from_numpy(np.full(self.nrow, float(value)), NUM)
        if isinstance(value, np.ndarray):
            kind = STR if value.dtype == object else NUM
            value = Vec.from_numpy(value, kind) if kind != STR else Vec(value, STR)
        assert isinstance(value, Vec)
        assert value.nrow == self.nrow or self.ncol == 0
        value.name = str(name)
        if name in self._names:
            self._vecs[self._index(name)] = value
        else:
            self._names.append(str(name))
            self._vecs.append(value)

    Frame.__setitem__ = frame_set


_attach()
