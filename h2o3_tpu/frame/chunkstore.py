"""Out-of-core data plane — compressed device frames + a host-RAM chunk
spill tier with double-buffered host→device prefetch (the DKV-chunk
successor for datasets ≫ HBM; PAPER.md §1: frames are *compressed columnar
chunks* and compute moves to the data).

The resident frame layer keeps every numeric column device-resident as f32 —
Higgs-1B at f32×28 cols is ~112 GB and no pod bracket fits it. This module
is the piece that makes rows ≥ 10× device memory trainable through a FIXED
device footprint:

- **Compressed device residency** (``H2O3_TPU_FRAME_COMPRESS``, default on):
  tree features live on device as the uint8 bin codes the histogram kernels
  already consume (a 4× capacity win at zero accuracy cost — ``bins_u8`` is
  what the hist/split lane eats), categoricals as their narrow int8/int16
  codes (frame.Vec.device_dtype), and f32 materializes only at dispatch
  boundaries; streaming builds release the f32 device copies of binned
  feature columns to the host tier (``Vec.release_device``) and the ``data``
  property rebuilds them lazily on next touch.
- **Host-RAM chunk spill tier** (:class:`ChunkStore`): a training pipeline's
  per-row lanes (binned features, design-matrix blocks, targets, weights,
  running per-row state) partition into row-block chunks; an LRU device
  window bounded by ``H2O3_TPU_HBM_WINDOW_BYTES`` holds the blocks in
  flight, evicted chunks park as host numpy arrays.
- **Double-buffered prefetch** (:meth:`ChunkStore.stream`): block k+1's
  host→device transfer is issued while block k computes (``jax.device_put``
  is asynchronous), ``H2O3_TPU_PREFETCH_DEPTH`` deep.

The drivers (tree histogram loop, GLM IRLS Gram, DL epochs) become
block-accumulate outer loops around their EXISTING fused programs —
histogram accumulation is associative over row blocks, the Gram is a sum,
DL already minibatches — so the PR-6/PR-8 compiled pipelines and the PR-9
collective lanes run untouched inside each block. A frame that fits the
window takes the resident path unchanged (``plan`` returns None), which is
what pins bit-parity on small frames; ``H2O3_TPU_FRAME_COMPRESS=0``
disables the whole plane and restores today's resident behavior
bit-for-bit.

Observability: ``frame_bytes_resident{tier=hbm|host}`` (both tiers'
current residency), ``frame_chunk_evictions_total`` (LRU churn — the
oversized-frame smoke test counts eviction cycles here) and
``frame_prefetch_overlap_seconds`` (wall time each prefetched chunk's
transfer had to overlap compute before the consumer asked for it).
"""

from __future__ import annotations

import time
from collections import OrderedDict
from typing import Iterable, Sequence

import numpy as np

from h2o3_tpu.utils import devmem as _dm
from h2o3_tpu.utils import flightrec as _fr
from h2o3_tpu.utils import jobacct as _jobacct
from h2o3_tpu.utils import metrics as _mx

RESIDENT_BYTES = _mx.gauge(
    "frame_bytes_resident",
    "bytes of frame/lane data currently resident, by tier (hbm = device "
    "arrays owned by Vecs and chunk windows, host = spill-tier numpy "
    "mirrors and parked chunk lanes)", always=True)
EVICTIONS = _mx.counter(
    "frame_chunk_evictions_total",
    "out-of-core chunks evicted from the LRU device window back to the "
    "host tier", always=True)
PREFETCH_OVERLAP = _mx.counter(
    "frame_prefetch_overlap_seconds",
    "cumulative wall seconds between issuing a chunk's host->device "
    "prefetch and the consumer requesting it — the window in which the "
    "transfer overlapped compute", always=True)
WINDOW_PEAK = _mx.gauge(
    "frame_window_peak_bytes",
    "peak device bytes the most recently closed ChunkStore window held "
    "(published at close(); the --oocore-ab acceptance number — must be "
    "<= H2O3_TPU_HBM_WINDOW_BYTES)", always=True)
WINDOW_EVICTIONS = _mx.counter(
    "frame_window_evictions_total",
    "per-store eviction counts rolled into the registry at ChunkStore "
    "close() — the A/B-readable sum across finished streamed runs "
    "(frame_chunk_evictions_total is the same churn counted live)",
    always=True)


def account(tier: str, delta_bytes: float,
            owner: str = "frame_resident") -> None:
    """Adjust the two-tier residency gauge (tier = 'hbm' | 'host') and,
    for device bytes, the cross-plane devmem ledger under ``owner``
    (Vec residency defaults to 'frame_resident'; the ChunkStore window
    reports as 'frame_window')."""
    RESIDENT_BYTES.inc(float(delta_bytes), tier=tier)
    if tier == "hbm":
        _dm.adjust(owner, float(delta_bytes))


def compress_on() -> bool:
    """H2O3_TPU_FRAME_COMPRESS: the master switch of the out-of-core plane.
    '0' restores the fully-resident behavior bit-for-bit — no spill, no
    streaming, no device release — even when a window is configured."""
    from h2o3_tpu import config

    return config.get_bool("H2O3_TPU_FRAME_COMPRESS")


def window_bytes() -> int:
    """H2O3_TPU_HBM_WINDOW_BYTES (0 = unbounded -> everything resident)."""
    from h2o3_tpu import config

    return max(config.get_int("H2O3_TPU_HBM_WINDOW_BYTES"), 0)


def prefetch_depth() -> int:
    """H2O3_TPU_PREFETCH_DEPTH (1 = double buffering, 0 = synchronous)."""
    from h2o3_tpu import config

    return max(config.get_int("H2O3_TPU_PREFETCH_DEPTH"), 0)


def streaming_enabled() -> bool:
    """Whether ANY frame may stream: compress on AND a finite window set."""
    return compress_on() and window_bytes() > 0


# DEPRECATED alias: stats of the most recently closed ChunkStore (peak_hbm,
# window, n_blocks, block_rows, evictions). A bare module-global dict that
# concurrent/overlapping stores clobber — close() now publishes the same
# numbers through the registry (frame_window_peak_bytes gauge +
# frame_window_evictions_total counter), which is what the A/B tools read;
# the dict stays as a back-compat alias for existing callers/tests.
LAST_STORE_STATS: dict = {}


class ChunkStore:
    """Row-blocked two-tier store for one training pipeline's arrays.

    Lanes are full ``(npad, ...)`` host numpy arrays (the spill tier);
    blocks are contiguous row slices of every lane, sized so that one
    block's device bytes across the streamed lanes fit the LRU window's
    per-buffer share (window / (1 + prefetch_depth) — the prefetched
    block(s) need room beside the computing one). Device copies are cached
    per (lane, block) in an LRU bounded by the window; mutable lanes write
    back through :meth:`update`, which refreshes both tiers so an evicted
    chunk re-uploads the current values.
    """

    def __init__(self, npad: int, bytes_per_row: float, *,
                 window: int | None = None, prefetch: int | None = None):
        from h2o3_tpu.parallel.mesh import mesh_epoch, stream_block_rows

        # block geometry (block_rows, n_blocks) bakes the mesh's shard
        # count in — a store planned under a dead topology must never serve
        # blocks onto the re-formed one (ISSUE 17); fetch() checks this
        self._epoch = mesh_epoch()
        self.npad = int(npad)
        self.window = window_bytes() if window is None else int(window)
        self.depth = prefetch_depth() if prefetch is None else int(prefetch)
        budget_rows = int(
            self.window // max(bytes_per_row * (1 + self.depth), 1))
        self.block_rows = stream_block_rows(self.npad, budget_rows)
        self.n_blocks = -(-self.npad // self.block_rows)
        self._lanes: dict[str, np.ndarray] = {}
        # (lane, block) -> device array, in LRU order (oldest first)
        self._dev: OrderedDict[tuple[str, int], object] = OrderedDict()
        self._pinned: set[tuple[str, int]] = set()
        self._issued_at: dict[int, float] = {}  # block -> prefetch stamp
        self._hbm = 0
        self.peak_hbm = 0
        self.evictions = 0

    # -- planning -----------------------------------------------------------
    @staticmethod
    def plan(npad: int, bytes_per_row: float) -> "ChunkStore | None":
        """The ONE policy gate every driver uses: None (stay resident) when
        the plane is off or the frame's streamed lanes fit the budget whole
        — the resident path is bit-for-bit today's. Otherwise a store whose
        block geometry fits the window.

        The window comes from two places: the static operator knob
        (``H2O3_TPU_HBM_WINDOW_BYTES``), or — when no knob is set and the
        overload plane is on — ``overload.plan_window``'s measured-headroom
        share (the ISSUE-19 auto-route: a frame too big for resident
        streams instead of OOMing) and its degraded-retry halving. With the
        plane off (``H2O3_TPU_OVERLOAD=0``) only the static knob routes,
        exactly as before.

        Boundary fix (ISSUE 19): a frame OVER the window whose geometry
        rounded up to one block used to silently run fully resident —
        ``block_rows`` is quantized upward to the mesh shard multiple, so a
        frame a few rows past the window could land ``n_blocks == 1`` and
        skip the window entirely. An over-window frame now always streams:
        the geometry is re-clamped to at least two blocks (down to the
        one-quantum floor — a frame of a single shard quantum cannot split,
        but then its whole footprint IS one block and goes through the
        store's accounted window rather than the unbounded resident path).
        """
        if not compress_on():
            return None
        need = npad * bytes_per_row
        static = window_bytes()
        from h2o3_tpu.utils import overload as _ov

        ov_win = _ov.plan_window(need, static)
        if ov_win is not None:
            store = ChunkStore(npad, bytes_per_row, window=ov_win)
        elif static and need > static:
            store = ChunkStore(npad, bytes_per_row)
        else:
            return None
        if store.n_blocks <= 1:
            if need <= store.window:
                return None
            store._force_stream_geometry()
        return store

    def _force_stream_geometry(self) -> None:
        """Re-clamp block geometry so an over-window frame streams: halve
        the row budget until the frame splits into >= 2 blocks or the
        quantum floor is hit (a one-quantum frame stays one block but still
        runs through the store's accounted LRU window)."""
        from h2o3_tpu.parallel.mesh import stream_block_rows

        budget = max(self.npad // 2, 1)
        self.block_rows = stream_block_rows(self.npad, budget)
        self.n_blocks = -(-self.npad // self.block_rows)

    # -- lanes (host tier) --------------------------------------------------
    def add(self, name: str, arr: np.ndarray) -> np.ndarray:
        """Register a host lane (leading axis npad). Returns the lane so
        callers can fill it in place."""
        arr = np.ascontiguousarray(arr)
        assert arr.shape[0] == self.npad, (name, arr.shape, self.npad)
        old = self._lanes.get(name)
        if old is not None:
            account("host", -old.nbytes)
        self._lanes[name] = arr
        account("host", arr.nbytes)
        return arr

    def add_empty(self, name: str, shape: tuple, dtype, fill=0) -> np.ndarray:
        return self.add(name, np.full(shape, fill, dtype=dtype))

    def lane(self, name: str) -> np.ndarray:
        return self._lanes[name]

    def fill(self, name: str, value) -> None:
        """Reset a mutable lane on both tiers (drops stale device copies)."""
        self._lanes[name].fill(value)
        for bi in range(self.n_blocks):
            self._drop((name, bi))

    def span(self, bi: int) -> tuple[int, int]:
        lo = bi * self.block_rows
        return lo, min(lo + self.block_rows, self.npad)

    def rows(self, bi: int) -> int:
        lo, hi = self.span(bi)
        return hi - lo

    # -- device window ------------------------------------------------------
    def _drop(self, key: tuple[str, int], evict: bool = False) -> None:
        arr = self._dev.pop(key, None)
        if arr is not None:
            self._hbm -= arr.nbytes
            account("hbm", -arr.nbytes, owner="frame_window")
            if evict:
                self.evictions += 1
                EVICTIONS.inc()
                _fr.record("chunk_evict", lane=key[0], block=key[1],
                           bytes=int(arr.nbytes))

    def _evict_to(self, budget: int) -> None:
        for key in list(self._dev):
            if self._hbm <= budget:
                break
            if key in self._pinned:
                continue
            self._drop(key, evict=True)

    def fetch(self, bi: int, names: Sequence[str], pin: bool = False) -> dict:
        """Device arrays for block ``bi``'s named lanes, through the LRU
        window (misses upload from the host tier; the window evicts
        least-recently-used unpinned chunks past the budget)."""
        from h2o3_tpu.parallel.mesh import mesh_epoch, shard_rows

        if self._epoch != mesh_epoch():
            raise RuntimeError(
                "ChunkStore was planned under topology epoch "
                f"{self._epoch} but the mesh re-formed (epoch "
                f"{mesh_epoch()}); re-plan the store — resumed streamed "
                "builds re-derive block geometry from the new shard counts")

        lo, hi = self.span(bi)
        out = {}
        for name in names:
            key = (name, bi)
            arr = self._dev.get(key)
            if arr is None:
                lane = self._lanes[name][lo:hi]
                if self.window:
                    # evict BEFORE the upload so the window bounds the PEAK
                    # residency, not just the steady state (the bound can
                    # still exceed the window when the pinned in-flight
                    # blocks alone do — the documented one-quantum floor)
                    self._evict_to(max(self.window - lane.nbytes, 0))
                arr = shard_rows(lane)
                self._dev[key] = arr
                self._hbm += arr.nbytes
                account("hbm", arr.nbytes, owner="frame_window")
                # the plane ledger above knows "frame_window" spent it;
                # the job ledger charges the trace this fetch ran under
                _jobacct.on_window_bytes(_mx.current_trace(),
                                         int(arr.nbytes))
                self.peak_hbm = max(self.peak_hbm, self._hbm)
                _fr.record("chunk_fetch", lane=name, block=bi,
                           bytes=int(arr.nbytes))
            else:
                self._dev.move_to_end(key)
            if pin:
                self._pinned.add(key)
            out[name] = arr
        return out

    def update(self, bi: int, **arrays) -> None:
        """Write a block's new device values back: the host lane slice is
        refreshed (the spill tier stays current, so eviction loses nothing)
        and the device copy in the window is replaced in place."""
        import jax

        lo, hi = self.span(bi)
        for name, arr in arrays.items():
            self._lanes[name][lo:hi] = np.asarray(jax.device_get(arr)).reshape(
                self._lanes[name][lo:hi].shape)
            key = (name, bi)
            old = self._dev.pop(key, None)
            if old is not None:
                self._hbm -= old.nbytes
                account("hbm", -old.nbytes, owner="frame_window")
            if self.window:
                # same pre-insert eviction as fetch: the window bounds PEAK
                self._evict_to(max(self.window - arr.nbytes, 0))
            self._dev[key] = arr
            self._hbm += arr.nbytes
            account("hbm", arr.nbytes, owner="frame_window")
            self.peak_hbm = max(self.peak_hbm, self._hbm)

    def unpin(self, bi: int) -> None:
        self._pinned = {k for k in self._pinned if k[1] != bi}

    def stream(self, names: Sequence[str]):
        """Iterate ``(bi, {name: device_array})`` over every block with
        ``prefetch_depth`` blocks of lookahead: block k+1's upload is issued
        (pinned against eviction) before block k is yielded, so the
        transfer rides behind block k's compute. Each yielded block is a
        ``stream_block`` dispatch site: the time the CONSUMER holds the
        block (the per-block compute) lands in
        ``dispatch_device_seconds{site=stream_block}`` and the flight ring."""
        for bi in range(self.n_blocks):
            for j in range(bi + 1, min(bi + 1 + self.depth, self.n_blocks)):
                if j not in self._issued_at:
                    self._issued_at[j] = time.perf_counter()
                    self.fetch(j, names, pin=True)
            t0 = self._issued_at.pop(bi, None)
            if t0 is not None:
                PREFETCH_OVERLAP.inc(time.perf_counter() - t0)
            blk = self.fetch(bi, names)
            self.unpin(bi)
            with _fr.dispatch("stream_block", block=bi,
                              blocks=self.n_blocks):
                yield bi, blk
        self._issued_at.clear()

    def close(self) -> None:
        """Release both tiers (gauge returns to its prior level) and
        publish the run's stats through the REGISTRY — the
        ``frame_window_peak_bytes`` gauge and the cumulative
        ``frame_window_evictions_total`` counter are what the A/B harness
        and the oversized-frame smoke test read (/3/Metrics and bench
        artifacts agree by construction). :data:`LAST_STORE_STATS` stays
        as the deprecated dict alias."""
        WINDOW_PEAK.set(float(self.peak_hbm))
        if self.evictions:
            WINDOW_EVICTIONS.inc(float(self.evictions))
        LAST_STORE_STATS.update(
            peak_hbm=self.peak_hbm, window=self.window,
            n_blocks=self.n_blocks, block_rows=self.block_rows,
            evictions=self.evictions,
        )
        for key in list(self._dev):
            self._drop(key)
        self._pinned.clear()
        for name in list(self._lanes):
            account("host", -self._lanes.pop(name).nbytes)

    def __repr__(self) -> str:
        return (f"<ChunkStore {self.npad} rows x {len(self._lanes)} lanes, "
                f"{self.n_blocks} blocks of {self.block_rows}, "
                f"window {self.window} B, hbm {self._hbm} B>")


# ---------------------------------------------------------------------------
# frame helpers: host block sub-frames + compressed-residency release


def host_block_frame(frame, names: Iterable[str], lo: int, hi: int):
    """A block sub-frame over rows ``[lo, hi)`` of ``frame``'s PADDED host
    mirrors: each named column slices its host tier copy and ships one
    block-sized device array. ``hi - lo`` must divide the mesh
    (``mesh.block_quantum`` multiples do), so the sub-frame needs no extra
    padding rows and every elementwise transform (binning, DataInfo
    standardize/one-hot) yields EXACTLY the row slice of the full frame's
    transform — the bit-parity backbone of the streaming setup passes."""
    from h2o3_tpu.frame.frame import STR, Frame, Vec
    from h2o3_tpu.parallel.mesh import shard_rows

    nrow_blk = max(min(hi, frame.nrow) - lo, 0)
    vecs = []
    for name in names:
        v = frame.vec(name)
        assert v.kind != STR, "streaming lanes are numeric/categorical only"
        buf = v.host_values()[lo:hi]
        vecs.append(
            Vec(shard_rows(buf), v.kind, name=name, domain=v.domain,
                nrow=nrow_blk)
        )
    return Frame(vecs, list(names), register=False)


def reshard_host_mirrors(frame) -> int:
    """Elastic recovery (ISSUE 17): force every column of ``frame`` onto
    the CURRENT topology — host mirrors re-pad to the new shard counts (NA
    fill beyond ``nrow``, real rows copied exactly) and stale device
    placements drop so ``Vec.data`` rebuilds on the re-formed mesh. The
    per-Vec work is the same lazy ``_maybe_reshard`` the ``data``/
    ``host_values`` properties run on next touch; this helper is the eager
    form the resume path (and the elastic drill) calls so sharded/streamed
    ingest state survives the reshape at a known point instead of
    mid-dispatch. Returns the number of columns re-sharded."""
    from h2o3_tpu.frame.frame import STR

    n = 0
    for name in frame.names:
        v = frame.vec(name)
        if v.kind == STR or getattr(v, "_epoch", None) is None:
            continue
        before = v._epoch
        v._maybe_reshard()
        n += int(v._epoch != before)
    return n


def release_frame_features(frame, names: Iterable[str]) -> int:
    """Compressed device residency: drop the f32/int device copies of the
    named feature columns (their information lives on as bin codes /
    design-matrix lanes in a ChunkStore) — the host tier keeps the exact
    values and ``Vec.data`` rebuilds lazily on next touch. No-op (returns
    0) with H2O3_TPU_FRAME_COMPRESS=0. Returns bytes released."""
    if not compress_on():
        return 0
    freed = 0
    for name in names:
        v = frame.vec(name)
        freed += v.release_device()
    return freed
