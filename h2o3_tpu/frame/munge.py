"""Compiled sharded data-munging plane (ISSUE 20) — the ETL half of the
paper's platform, built the way the training lanes were built.

H2O's munging ops are MRTask passes over the DKV's compressed chunks; the
seed reproduced their SEMANTICS eagerly (frame/ops.py: one dispatch per
elementwise op, a single-device segment reduce per group-by column, a host
``np.repeat`` expansion inside ``merge``). This module is the compiled
successor:

- **group-by** runs as ONE mesh-sharded program per ``.agg()`` call: every
  value column's segment stats accumulate per row shard and reduce through
  the PR-9 collective wrappers (``ops/collectives.psum`` — the quant lane
  and the 2-D rows×cols stage-1-exact hierarchy apply unchanged; min/max
  ride the exact ``pmax``/``pmin`` lanes, extrema cannot quantize).
- **join** keeps the device sort-merge statistics and replaces the host
  ``np.repeat`` expansion with an on-device ``searchsorted`` expansion
  program; single-key joins on >1-device meshes additionally assign their
  dense key group-ids via a radix-partition ``all_to_all`` exchange
  (``ASTMerge``'s distributed radix join, on the mesh) instead of one
  global lexsort over both sides.
- **sort** compiles key preparation + ``lexsort`` into one cached program.
- **lazy expression fusion** (frame/lazy.py) dispatches through
  :func:`run_munge` so its one-fused-program claim is counter-proven.

Every dispatch lands in the flight recorder (``site=munge_*``), the per-job
ledger (utils/jobacct.py) and ``munge_dispatches_total{op}``; collective
bytes are captured at first trace and replayed per dispatch exactly like
the tree builder's ``_run_counted``. Paths that stay eager under
``H2O3_TPU_MUNGE_FUSE=1`` (string ops, STR/TIME join keys, pivot,
rank_within_group_by, host aggs) tally
``munge_fuse_fallbacks_total{reason}`` — the docs/MIGRATION.md fallback
matrix. ``H2O3_TPU_MUNGE_FUSE=0`` routes nothing here: every seed path
stays bit-for-bit.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.ops import collectives as coll
from h2o3_tpu.utils import flightrec as _fr
from h2o3_tpu.utils import jobacct as _ja
from h2o3_tpu.utils import metrics as _mx
from h2o3_tpu.utils.metrics import current_trace

DISPATCHES = _mx.counter(
    "munge_dispatches_total",
    "compiled munging-plane device dispatches by op (groupby / "
    "groupby_stream / join / join_exchange / sort / expr_fuse) plus the "
    "eager elementwise dispatches the fusion replaces (op=elementwise) — "
    "the expression-chain A/B reads the ratio", always=True)
FALLBACKS = _mx.counter(
    "munge_fuse_fallbacks_total",
    "munging calls that stayed on an eager/host path while the fused "
    "plane was on, by reason (string_op / host_keys / host_agg / pivot / "
    "rank_within_group_by / join_multikey / tiny_join / expr_ineligible)",
    always=True)
COLL_BYTES = _mx.counter(
    "munge_collective_bytes_total",
    "modeled cross-device payload bytes the compiled munging programs "
    "move, by phase (munge_groupby / munge_join_exchange) and lane — "
    "captured at first trace, replayed per dispatch like the tree "
    "builder's tally", always=True)


def fuse_on() -> bool:
    """H2O3_TPU_MUNGE_FUSE: read per call (tests toggle the env)."""
    from h2o3_tpu import config

    return config.get_bool("H2O3_TPU_MUNGE_FUSE")


def fallback(reason: str) -> None:
    """Tally an eager/host path taken WHILE the fused plane is on."""
    if fuse_on():
        FALLBACKS.inc(reason=reason)


# ---------------------------------------------------------------------------
# dispatch wrapper — the munging analog of shared_tree._run_counted: the
# first dispatch of a program traces under the collective tally; later
# dispatches replay the captured per-(phase, lane) bytes into the counter
# and the per-job ledger.

_PROG_COLL: dict = {}  # program cache key -> {(phase, lane): bytes}


def run_munge(op: str, fn, args=(), *, coll_key=None, **meta):
    DISPATCHES.inc(op=op)
    first = coll_key is not None and coll_key not in _PROG_COLL
    with _fr.dispatch(f"munge_{op}", **meta):
        if first:
            entries: list = []
            with coll.collective_tally(entries):
                out = fn(*args)
            agg: dict = {}
            for ph, lane, _grp, b in entries:
                agg[(ph, lane)] = agg.get((ph, lane), 0.0) + b
            _PROG_COLL[coll_key] = agg
        else:
            out = fn(*args)
    if coll_key is not None:
        job = current_trace()
        for (ph, lane), b in _PROG_COLL[coll_key].items():
            COLL_BYTES.inc(b, phase=ph)
            COLL_BYTES.inc(b, phase=ph, lane=lane)
            _ja.on_collective_bytes(job, b, lane=lane)
    return out


def _pow2(n: int, lo: int = 8) -> int:
    """Power-of-two ladder for compile-key dimensions (group counts,
    exchange bucket capacities, join output lengths) — unknown-cardinality
    shapes must not mint one executable per value."""
    p = lo
    while p < n:
        p <<= 1
    return p


def _shard_index(mesh):
    """Global row-shard index of this device inside a shard_map body —
    shard c*R + r sits on mesh.devices[r, c] (parallel/mesh.row_axes)."""
    from h2o3_tpu.parallel.mesh import COLS_AXIS, ROWS_AXIS, is_2d

    if is_2d(mesh):
        r = jax.lax.axis_index(ROWS_AXIS)
        c = jax.lax.axis_index(COLS_AXIS)
        return c * mesh.shape[ROWS_AXIS] + r
    return jax.lax.axis_index(ROWS_AXIS)


def _row_axis_names(mesh):
    from h2o3_tpu.parallel.mesh import row_axes

    ax = row_axes(mesh)
    return ax if len(ax) > 1 else ax[0]


# ---------------------------------------------------------------------------
# group-by: the sharded histogram machinery generalized to arbitrary
# aggregates — per-shard segment stats for EVERY value column of one
# ``.agg()`` call, reduced in one program.

_GB_PROGS: dict = {}

_STAT_ORDER = ("nrow", "sum", "sumsq", "nacnt", "min", "max")


def _segment_stats_local(gid, x, gpad: int):
    """One column's per-shard segment stats — the eager
    ``ops._segment_aggregate`` body verbatim (parity is an op-for-op
    argument, not a numeric accident): (4, gpad) sum lanes + (2, gpad)
    extrema lanes."""
    g = jnp.where(gid >= 0, gid, 0)
    ok = (gid >= 0) & ~jnp.isnan(x)
    xz = jnp.where(ok, x, 0.0)
    # count/sum/sumsq/nacnt ride ONE 4-wide scatter-add pass (XLA CPU/TPU
    # scatters are pass-bound, not payload-bound), extrema two more
    pay = jnp.stack(
        [ok.astype(jnp.float32), xz, xz * xz,
         (jnp.isnan(x) & (gid >= 0)).astype(jnp.float32)], axis=1)
    sums = jnp.zeros((gpad, 4), jnp.float32).at[g].add(pay)
    mn = jnp.full(gpad, jnp.inf, jnp.float32).at[g].min(
        jnp.where(ok, x, jnp.inf))
    mx = jnp.full(gpad, -jnp.inf, jnp.float32).at[g].max(
        jnp.where(ok, x, -jnp.inf))
    return sums.T, jnp.stack([mn, mx])


def _gb_program(npad: int, C: int, gpad: int):
    from jax.sharding import PartitionSpec as P

    from h2o3_tpu.parallel.mesh import (
        get_mesh, mesh_key, row_pspec, shard_map,
    )

    key = ("gb", mesh_key(), npad, C, gpad)
    prog = _GB_PROGS.get(key)
    if prog is not None:
        return key, prog
    mesh = get_mesh()
    nd = int(mesh.devices.size)

    def body(gid, *xs):
        sums, exts = jax.vmap(
            lambda x: _segment_stats_local(gid, x, gpad))(jnp.stack(xs))
        if nd > 1:
            sums = coll.psum(
                sums, n_dev=nd, phase="munge_groupby", mesh=mesh)
            mn = coll.exact_pmin(exts[:, 0], mesh, phase="munge_groupby")
            mx = coll.exact_pmax(exts[:, 1], mesh, phase="munge_groupby")
        else:
            mn, mx = exts[:, 0], exts[:, 1]
        return jnp.concatenate(
            [sums, mn[:, None], mx[:, None]], axis=1)  # (C, 6, gpad)

    spec = row_pspec(mesh)
    f = shard_map(
        body, mesh, in_specs=(spec,) * (C + 1), out_specs=P(),
        check_vma=False,
    )
    prog = jax.jit(f)
    _GB_PROGS[key] = prog
    return key, prog


def groupby_stats(gid: np.ndarray, xs_dev: list, ngroups: int) -> list:
    """Sharded segment aggregation of every value column in ONE dispatch.

    ``gid``: (nrow,) int32 host codes, -1 = NA key (dropped, matching the
    eager path); ``xs_dev``: padded (npad,) f32 device columns. Returns one
    eager-shaped stat dict per column (np arrays of length ``ngroups``)."""
    from h2o3_tpu.parallel.mesh import shard_rows

    npad = int(xs_dev[0].shape[0])
    gpad = _pow2(max(int(ngroups), 1))
    gp = np.full(npad, -1, np.int32)
    gp[: len(gid)] = gid
    gid_dev = shard_rows(gp)
    key, prog = _gb_program(npad, len(xs_dev), gpad)
    out = run_munge(
        "groupby", prog, (gid_dev, *xs_dev), coll_key=key,
        cols=len(xs_dev), groups=int(ngroups))
    r = np.asarray(out)[:, :, :ngroups]
    return [
        {name: r[i, j] for j, name in enumerate(_STAT_ORDER)}
        for i in range(r.shape[0])
    ]


# -- streamed variant: block-accumulate through the ChunkStore window so a
# group-by over a frame past the HBM window runs out-of-core. Blocks arrive
# row-sharded; the tiny (C, 6, gpad) accumulator stays device-resident.


@partial(jax.jit, static_argnames=("gpad",))
def _gb_block_kernel(gid, xs, gpad: int):
    sums, exts = jax.vmap(
        lambda x: _segment_stats_local(gid, x, gpad))(jnp.stack(xs))
    return jnp.concatenate(
        [sums, exts[:, 0][:, None], exts[:, 1][:, None]], axis=1)


@jax.jit
def _gb_merge(acc, part):
    return jnp.concatenate(
        [acc[:, :4] + part[:, :4],
         jnp.minimum(acc[:, 4:5], part[:, 4:5]),
         jnp.maximum(acc[:, 5:6], part[:, 5:6])], axis=1)


def groupby_stats_streamed(gid: np.ndarray, host_cols: list, ngroups: int):
    """Out-of-core group-by: stream (gid, value) row blocks through a
    ChunkStore window, accumulating the small per-group stat tensor on
    device. Returns eager-shaped stat dicts, or None when the planner says
    the frame fits resident (callers then take :func:`groupby_stats`)."""
    from h2o3_tpu.frame import chunkstore as _cs

    C = len(host_cols)
    npad = int(host_cols[0].shape[0])
    store = _cs.ChunkStore.plan(npad, 4.0 * (C + 1))
    if store is None:
        return None
    gpad = _pow2(max(int(ngroups), 1))
    gp = np.full(npad, -1, np.int32)
    gp[: len(gid)] = gid
    store.add("gid", gp)
    names = ["gid"]
    for i, cb in enumerate(host_cols):
        store.add(f"x{i}", np.asarray(cb, np.float32))
        names.append(f"x{i}")

    def _accumulate():
        acc = None
        for _bi, blk in store.stream(names):
            part = _gb_block_kernel(
                blk["gid"], tuple(blk[f"x{i}"] for i in range(C)), gpad)
            acc = part if acc is None else _gb_merge(acc, part)
        return acc

    try:
        out = run_munge(
            "groupby_stream", _accumulate, cols=C, groups=int(ngroups),
            blocks=store.n_blocks)
        _ja.on_window_bytes(current_trace(), store.peak_hbm)
    finally:
        store.close()
    r = np.asarray(out)[:, :, :ngroups]
    return [
        {name: r[i, j] for j, name in enumerate(_STAT_ORDER)}
        for i in range(r.shape[0])
    ]


# ---------------------------------------------------------------------------
# join: device expansion of the sort-merge statistics (replacing the host
# np.repeat path) + the radix-partition all_to_all gid exchange.


@jax.jit
def _join_cum_kernel(lo, m, rorder, matched_r, all_x_flag):
    m_out = jnp.where(all_x_flag, jnp.maximum(m, 1), m)
    cum = jnp.cumsum(m_out.astype(jnp.int32))
    return lo.astype(jnp.int32), m.astype(jnp.int32), cum, rorder, matched_r


@partial(jax.jit, static_argnames=("mpad",))
def _expand_kernel(lo, m, cum, rorder, mpad: int):
    """(li, ri) output index vectors from per-left-row match ranges —
    the eager path's five np.repeat passes as one device program."""
    n_l = lo.shape[0]
    n_r = rorder.shape[0]
    total = cum[-1] if n_l else jnp.int32(0)
    j = jnp.arange(mpad, dtype=jnp.int32)
    valid = j < total
    # searchsorted(cum, j, 'right') as scatter + prefix-sum: one mark per
    # left row at its output offset, cumsum turns marks into row indices —
    # O(n_l + mpad) vectorized vs the binary search's mpad*log(n_l) gathers
    marks = jnp.zeros(mpad, jnp.int32).at[cum].add(1, mode="drop")
    li = jnp.clip(jnp.cumsum(marks), 0, max(n_l - 1, 0)).astype(jnp.int32)
    m_out_li = jnp.where(li > 0, cum[li] - cum[jnp.maximum(li - 1, 0)], cum[li])
    start = cum[li] - m_out_li
    within = j - start
    has = m[li] > 0
    rpos = lo[li] + within
    ri = jnp.where(
        valid & has,
        rorder[jnp.clip(rpos, 0, max(n_r - 1, 0))].astype(jnp.int32)
        if n_r else jnp.int32(-1),
        -1,
    )
    li_out = jnp.where(valid, li, -1)
    return li_out, ri, total


def join_expand(lo_d, m_d, rorder_d, matched_d, all_x: bool, all_y: bool,
                n_r: int):
    """Device expansion lane of ``merge``: returns host (li, ri) int64
    index vectors with the exact eager-path ordering contract (match
    groups in left-frame order; unmatched right rows appended for
    right/outer joins)."""
    lo, m, cum, rorder, matched_r = _join_cum_kernel(
        lo_d, m_d, rorder_d, matched_d, jnp.bool_(all_x))
    total = int(np.asarray(cum[-1])) if int(lo.shape[0]) else 0
    mpad = _pow2(max(total, 1), lo=1024)
    li_d, ri_d, _ = run_munge(
        "join", _expand_kernel, (lo, m, cum, rorder, mpad),
        rows=total)
    li = np.asarray(li_d, np.int64)[:total]
    ri = np.asarray(ri_d, np.int64)[:total]
    if all_y and n_r:
        extra = np.nonzero(~np.asarray(matched_r, bool))[0].astype(np.int64)
        li = np.concatenate([li, np.full(len(extra), -1, np.int64)])
        ri = np.concatenate([ri, extra])
    return li, ri


# -- radix-partition gid exchange: dense key group-ids for single-key joins
# assigned DISTRIBUTEDLY — each device owns one hash partition, both sides'
# (key, row) pairs exchange over all_to_all, the owner ranks its partition's
# distinct keys locally, and gids (partition offset + local rank) ride the
# reverse exchange home. Replaces the global lexsort over the concatenated
# key matrix for the meshes where that sort is the join's dominant cost.

_JX_COUNT_PROGS: dict = {}
_JX_PROGS: dict = {}

def _jx_partition(key, valid, nd: int):
    # murmur3 finalizer: float-bitcast key codes differ mostly in LOW
    # mantissa bits, so the partition needs full avalanche (a bare
    # multiplicative hash clumps small-integer-valued floats into two
    # partitions and the skew guard then rejects every join)
    h = key.astype(jnp.uint32)
    h = h ^ (h >> jnp.uint32(16))
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> jnp.uint32(13))
    h = h * jnp.uint32(0xC2B2AE35)
    h = h ^ (h >> jnp.uint32(16))
    return jnp.where(valid, (h % jnp.uint32(nd)).astype(jnp.int32), nd)


def _jx_count_program(npad_l: int, npad_r: int):
    from jax.sharding import PartitionSpec as P

    from h2o3_tpu.parallel.mesh import (
        get_mesh, mesh_key, row_pspec, shard_map,
    )

    key = ("jxc", mesh_key(), npad_l, npad_r)
    prog = _JX_COUNT_PROGS.get(key)
    if prog is not None:
        return prog
    mesh = get_mesh()
    nd = int(mesh.devices.size)
    ax = _row_axis_names(mesh)

    def body(kl, kr, n_l, n_r):
        sh = _shard_index(mesh)

        def side_max(k, n):
            loc = k.shape[0]
            gidx = sh * loc + jnp.arange(loc, dtype=jnp.int32)
            p = _jx_partition(k, gidx < n, nd)
            cnt = jnp.zeros(nd, jnp.int32).at[p].add(
                1, mode="drop")
            return jnp.max(cnt)

        cap = jnp.maximum(side_max(kl, n_l), side_max(kr, n_r))
        return jax.lax.pmax(cap, ax)

    f = shard_map(
        body, mesh, in_specs=(row_pspec(mesh), row_pspec(mesh), P(), P()),
        out_specs=P(), check_vma=False)
    prog = jax.jit(f)
    _JX_COUNT_PROGS[key] = prog
    return prog


def _jx_program(npad_l: int, npad_r: int, cap: int):
    from jax.sharding import PartitionSpec as P

    from h2o3_tpu.parallel.mesh import (
        get_mesh, mesh_key, row_pspec, shard_map,
    )

    key = ("jx", mesh_key(), npad_l, npad_r, cap)
    prog = _JX_PROGS.get(key)
    if prog is not None:
        return key, prog
    mesh = get_mesh()
    nd = int(mesh.devices.size)
    ax = _row_axis_names(mesh)

    # Unfilled bucket slots carry the canonical-NaN bit pattern instead of a
    # separate validity plane: numeric NA keys already hold exactly those bits
    # (``_key_codes_device`` canonicalises), so empty slots merge into the NA
    # key group — gids only need EQUALITY consistency and an injective
    # labeling, never density, so one phantom group per partition is free.
    # This removes the two validity exchanges and the 2-key lexsort, and the
    # arrival-rank bookkeeping below replaces the per-side stable argsort.
    empty = jnp.int32(
        np.float32(np.nan).view(np.int32))  # == the canonical NA key code

    def scatter_side(k, n, sh):
        """Local rows → (nd, cap) exchange buckets + the (partition, slot)
        placement needed to route gids back. Slot = arrival rank within the
        partition, computed by a one-hot running count (no sort)."""
        loc = k.shape[0]
        gidx = sh * loc + jnp.arange(loc, dtype=jnp.int32)
        valid = gidx < n
        p = _jx_partition(k, valid, nd)  # nd for padding rows
        oh = (p[:, None] == jnp.arange(nd, dtype=jnp.int32)[None, :])
        within = jnp.take_along_axis(
            jnp.cumsum(oh.astype(jnp.int32), axis=0) - 1,
            jnp.clip(p, 0, nd - 1)[:, None], axis=1)[:, 0]
        keys_b = jnp.full((nd, cap), empty, jnp.int32).at[p, within].set(
            k, mode="drop")  # p=nd (padding) rows drop
        return keys_b, p, within

    def body(kl, kr, n_l, n_r):
        sh = _shard_index(mesh)
        kb_l, p_l, wi_l = scatter_side(kl, n_l, sh)
        kb_r, p_r, wi_r = scatter_side(kr, n_r, sh)
        # ONE exchange forward (both sides packed), one back with the gids:
        # partition p of every device lands on device p.
        got = coll.all_to_all_exchange(
            jnp.concatenate([kb_l, kb_r], axis=1), axis_name=ax,
            phase="munge_join_exchange")
        # local dense ranks over this partition's combined key set — raw
        # int32 bit order (key ORDER is irrelevant, only equality groups)
        bits = got.reshape(-1)
        order = jnp.argsort(bits)
        sb = bits[order]
        bump = (sb[1:] != sb[:-1]).astype(jnp.int32)
        rank_sorted = jnp.concatenate(
            [jnp.zeros(1, jnp.int32), jnp.cumsum(bump)])
        ranks = jnp.zeros(bits.shape[0], jnp.int32).at[order].set(rank_sorted)
        ucount = rank_sorted[-1] + 1
        uc_all = jax.lax.all_gather(ucount, ax, axis=0, tiled=False)
        uc_all = uc_all.reshape(-1)
        offset = (jnp.cumsum(uc_all) - uc_all)[sh]
        gb = coll.all_to_all_exchange(
            (offset + ranks).reshape(got.shape), axis_name=ax,
            phase="munge_join_exchange")
        gl = gb[:, :cap][jnp.clip(p_l, 0, nd - 1), wi_l]
        gr = gb[:, cap:][jnp.clip(p_r, 0, nd - 1), wi_r]
        return gl, gr

    spec = row_pspec(mesh)
    f = shard_map(
        body, mesh, in_specs=(spec, spec, P(), P()),
        out_specs=(spec, spec), check_vma=False)
    prog = jax.jit(f)
    _JX_PROGS[key] = prog
    return key, prog


def tuple_gids_exchange(klp, krp, n_l: int, n_r: int):
    """Distributed dense gid assignment for one int32 key column per side.

    ``klp``/``krp`` are the PADDED row-sharded device code columns (padding
    rows are masked by the row counts — numeric padding shares the NA code,
    so masking is load-bearing). Returns (gl, gr) sliced to the true row
    counts, or None when the mesh has one device (nothing to exchange)."""
    from h2o3_tpu.parallel.mesh import get_mesh, n_shards

    nd = n_shards()
    if nd <= 1:
        return None
    mesh = get_mesh()
    npad_l, npad_r = int(klp.shape[0]), int(krp.shape[0])
    counter = _jx_count_program(npad_l, npad_r)
    cap = int(np.asarray(counter(
        klp, krp, jnp.int32(n_l), jnp.int32(n_r))))
    cap = _pow2(max(cap, 1))
    if cap * nd * nd > 4 * max(npad_l + npad_r, 1):
        # degenerate skew: one partition holds ~everything — the exchange
        # buffers would dwarf the data. The lexsort lane is the right tool.
        fallback("join_skewed")
        return None
    key, prog = _jx_program(npad_l, npad_r, cap)
    gl, gr = run_munge(
        "join_exchange", prog,
        (klp, krp, jnp.int32(n_l), jnp.int32(n_r)),
        coll_key=key, rows_l=n_l, rows_r=n_r)
    return gl[:n_l], gr[:n_r]


# ---------------------------------------------------------------------------
# sort: key prep + lexsort as one cached program.


@partial(jax.jit, static_argnames=("kinds", "asc", "nrow"))
def _sort_kernel(vs, kinds, asc, nrow: int):
    keys = []
    for v, kd, a in zip(vs, kinds, asc):
        k = v[:nrow]
        if kd == "enum":
            k = k.astype(jnp.float32)
        if not a:
            k = -k  # NaN stays NaN → still sorts last, like pandas
        keys.append(k)
    return jnp.lexsort(tuple(reversed(keys)))


def sort_order(vs_data, kinds, asc, nrow: int) -> np.ndarray:
    """Row order of a multi-key sort in one compiled dispatch — key
    negation for descending and the lexsort fused (the eager lane runs one
    device op per descending key before its lexsort)."""
    out = run_munge(
        "sort", _sort_kernel,
        (tuple(vs_data), tuple(kinds), tuple(bool(a) for a in asc), nrow),
        keys=len(kinds), rows=nrow)
    return np.asarray(out)
