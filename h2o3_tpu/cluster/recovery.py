"""Supervised auto-recovery — the self-healing layer over the fail-stop
cloud (SURVEY.md §5.3; the ISSUE-10 tentpole).

The PR-2/PR-4 machinery detects every failure class — a dead mesh member
poisons the next collective and latches ``cloud.mark_degraded``, the spmd
watchdog trips on wedged commands, durable snapshots land at every scoring
interval — but each of those paths ends at an *operator* holding a
``checkpoint=`` flag. This module closes the loop:

- :func:`run_supervised` wraps a job launch. When the launch dies of a
  *cloud* failure (degraded latch, coordination-service death signature,
  stale generation) and recovery is enabled, it re-forms the cloud
  (:func:`reform`) and relaunches from the latest PR-2 snapshot in the
  job's ``export_checkpoints_dir`` — bounded by
  ``H2O3_TPU_RECOVERY_MAX_RESTARTS`` restarts with exponential backoff +
  deterministic jitter (``H2O3_TPU_RECOVERY_BACKOFF``). Deterministic
  command errors (bad params, a failing combo, :class:`faults.TrainAbort`)
  are NEVER retried — they would fail identically on the new cloud.
- :func:`reform` is the degraded → recovering → healthy transition: latch
  (if not already latched), rebuild the device mesh over the devices that
  are live now (``parallel/mesh.reform_mesh`` — on a multi-process cloud
  whose distributed runtime cannot re-initialize in-process, this shrinks
  to the surviving local mesh), then ``cloud.recover()`` which ticks the
  ``cloud_generation`` gauge. The generation tick is the correctness
  keystone: every replicated command is stamped with the generation it
  entered under (cluster/spmd.py), so a command from the failure epoch can
  never execute — or broadcast — into the re-formed cloud.
- :func:`install` starts the background supervisor thread (launch.py,
  coordinator only): it watches the degraded latch — wherever it came from
  (watchdog trip, death signature, operator) — and re-forms the cloud with
  backoff so the REST tier keeps serving and the serving circuit breakers
  (serving/batcher.py) get their half-open signal without an operator.

``H2O3_TPU_RECOVERY=0`` disables all of it: failures propagate exactly as
today (fail-stop; the degraded latch stays one-way until an operator acts).
"""

from __future__ import annotations

import glob
import os
import threading
import time
import zlib

from h2o3_tpu.utils import metrics
from h2o3_tpu.utils.log import Log

_ATTEMPTS = metrics.counter(
    "recovery_attempts_total",
    "supervised recovery attempts, by outcome: 'resumed' = the cloud was "
    "re-formed and the job relaunched from its latest snapshot, "
    "'exhausted' = the restart budget (H2O3_TPU_RECOVERY_MAX_RESTARTS) ran "
    "out and the failure surfaced, 'reform' = a background supervisor "
    "reform of the degraded latch with no job attached")
_SECONDS = metrics.histogram(
    "recovery_seconds",
    "wall seconds from failure detection to the relaunch dispatch of a "
    "supervised recovery (includes the backoff sleep and the cloud reform)",
    buckets=(0.1, 0.25, 0.5, 1, 2, 5, 10, 30, 60, 120, 300))


class RecoveryExhausted(RuntimeError):
    """The supervised restart budget ran out; the last failure is chained."""


def enabled() -> bool:
    """Supervised recovery on/off (``H2O3_TPU_RECOVERY``): '0' restores the
    pure fail-stop contract; 'auto'/'1' arm the supervisor wherever it is
    wired (REST builds with ``export_checkpoints_dir``, the launch.py
    watcher, :func:`run_supervised` callers)."""
    from h2o3_tpu import config

    return config.get("H2O3_TPU_RECOVERY").strip().lower() not in (
        "0", "false", "")


def _max_restarts() -> int:
    from h2o3_tpu import config

    return config.get_int("H2O3_TPU_RECOVERY_MAX_RESTARTS")


def _reset_secs() -> float:
    """``H2O3_TPU_RECOVERY_RESET_SECS``: a supervised job that runs healthy
    this long since its last relaunch gets its restart budget back
    (0 = never reset — the pre-ISSUE-17 lifetime budget)."""
    from h2o3_tpu import config

    return config.get_float("H2O3_TPU_RECOVERY_RESET_SECS")


def backoff_delay(attempt: int, key: str = "recovery") -> float:
    """Capped exponential backoff with DETERMINISTIC jitter (same scheme as
    persist.py / client.py: keyed on op+attempt, reproducible run-to-run,
    yet distinct supervisors desynchronize)."""
    from h2o3_tpu import config

    base = config.get_float("H2O3_TPU_RECOVERY_BACKOFF")
    delay = min(30.0, base * (2 ** attempt))
    frac = zlib.crc32(f"{key}:{attempt}".encode()) % 1000
    return delay * (1.0 + 0.5 * frac / 1000.0)


# signatures beyond spmd._DEATH_SIGNATURES that mark an exception as a
# CLOUD failure (recoverable by reform+resume) rather than a deterministic
# command failure (which would fail identically on the new cloud)
_CLOUD_FAILURE_MARKS = (
    "cloud is degraded (fail-stop)",
    "cloud re-formed (generation",
)


def is_cloud_failure(exc: BaseException) -> bool:
    """True when ``exc`` is a failure of the CLOUD, not of the command: the
    degraded latch is set, the exception carries a coordination-service
    death signature (``spmd._DEATH_SIGNATURES`` — matched on the repr/str
    because Job.join re-wraps worker exceptions with their traceback text),
    or it is a fail-stop / stale-generation error. ``faults.TrainAbort``
    (the simulated kill -9 of *this* process) is deliberately NOT a cloud
    failure: a process that died cannot supervise its own restart — the
    chaos suite's kill→restart→resume contract stays untouched."""
    from h2o3_tpu.cluster import cloud, spmd
    from h2o3_tpu.utils import faults

    if isinstance(exc, faults.TrainAbort):
        return False
    if isinstance(exc, spmd.StaleGeneration):
        return True
    if cloud.degraded_reason() is not None:
        return True
    msg = (repr(exc) + " " + str(exc)).lower()
    if any(m.lower() in msg for m in _CLOUD_FAILURE_MARKS):
        return True
    return any(sig.lower() in msg for sig in spmd._DEATH_SIGNATURES)


def _snapshot_progress(path: str) -> float:
    """Embedded progress counter of an interval snapshot: trees for GBM/DRF
    (``ntrees_actual``), epochs for DL (``epochs_trained``), the (lambda
    index, iteration) position for GLM (``irls_state``), folded into one
    orderable float. Raises on torn/unreadable/foreign files (the caller
    skips them); returns -1.0 for readable payloads with no recognizable
    counter, leaving the mtime tiebreak to decide."""
    import pickle

    from h2o3_tpu import persist

    blob = persist.read_bytes(path)
    if blob[: len(persist.FORMAT_MAGIC)] != persist.FORMAT_MAGIC:
        raise ValueError("not an h2o3_tpu model file")
    payload = pickle.loads(blob[len(persist.FORMAT_MAGIC):])
    out = (payload.get("state") or {}).get("output") or {}
    for k in ("ntrees_actual", "epochs_trained"):
        if out.get(k) is not None:
            return float(out[k])
    st = out.get("irls_state")
    if isinstance(st, dict):
        return float(int(st.get("li", 0)) * 1_000_000
                     + int(st.get("iters", st.get("it", 0))))
    return -1.0


def latest_snapshot(ckdir: str | None, algo: str | None) -> str | None:
    """Most-advanced PR-2 interval snapshot (``<algo>_ckpt_*``) in
    ``ckdir``, or None. This is the same file the ``/3/Jobs`` recovery
    block points at — the supervisor resumes from exactly what the runbook
    tells an operator to pass as ``checkpoint=``.

    Picking is by the EMBEDDED progress counter in the checkpoint payload
    (trees/epochs/IRLS position), with mtime only as tiebreak: clock skew
    or a restored volume can stamp a stale snapshot newest, and resuming
    from it would silently retrain finished work. Torn/unreadable files (a
    crash during a foreign copy, bit rot) are skipped with a warning
    instead of crashing the resume — the previous intact snapshot wins."""
    if not ckdir or not algo:
        return None
    best: tuple[tuple[float, float], str] | None = None
    for f in glob.glob(os.path.join(ckdir, f"{algo}_ckpt_*")):
        try:
            key = (_snapshot_progress(f), os.path.getmtime(f))
        except Exception as e:  # noqa: BLE001 — torn file, not a crash
            Log.warn(f"recovery: skipping torn/unreadable snapshot {f} "
                     f"({type(e).__name__}: {e})")
            continue
        if best is None or key > best[0]:
            best = (key, f)
    return best[1] if best else None


def reform(reason: str = "",
           topology: tuple[int, int] | str | None = None) -> int:
    """Re-form the cloud: degraded → recovering → healthy, returning the
    new generation. Ensures the latch is set first (so the transition
    counter and waiting commands observe the degraded epoch even when the
    failure surfaced as an exception without latching), rebuilds the device
    mesh over the currently-live devices, and ``cloud.recover()``s.

    Elastic recovery (ISSUE 17): topology is a RESUMABLE PARAMETER, not an
    invariant. ``topology=(rows, cols)`` (or ``"RxC"``) re-forms onto that
    explicit shape — the scale-down/scale-up resume path; ``topology=None``
    first consumes a pending induced reshape from the chaos harness
    (``faults.take_reshape`` — the ``reshape:RxC`` fault) and otherwise
    re-plans from the knob over every live device, exactly the same-shape
    behavior recovery has always had. Either way the topology epoch ticks,
    so frames padded for the old shard counts re-derive on next touch and
    GBM/GLM/DL resume re-shards carried state from the host pytree.

    Multi-process clouds: the JAX distributed runtime on current jaxlibs
    cannot re-initialize inside a poisoned process — a REAL member death
    still requires every rank to restart (the launch.py loop; the
    formation manifest in cluster/multihost.py lets the restarted ranks
    bootstrap into a CHANGED H2O3_TPU_NUM_PROCESSES). What reform gives
    the coordinator is a *survivor island*: a local mesh it can keep
    serving and resuming checkpointed jobs on while the pod reschedules."""
    from h2o3_tpu.cluster import cloud
    from h2o3_tpu.parallel import mesh as _mesh
    from h2o3_tpu.utils import faults, flightrec

    if topology is None:
        topology = faults.take_reshape()
    shape: tuple[int, int] | None = None
    if topology is not None:
        shape = (faults._parse_reshape(topology)
                 if isinstance(topology, str)
                 else (int(topology[0]), int(topology[1])))
    if cloud.degraded_reason() is None:
        cloud.mark_degraded(reason or "supervised reform")
    # freeze the evidence BEFORE the reform discards it (dedups with the
    # capture mark_degraded already made for this episode)
    flightrec.capture_incident(
        reason or "supervised reform", trigger="reform")
    try:
        m = _mesh.reform_mesh(shape) if shape is not None \
            else _mesh.reform_mesh()
        if shape is not None:
            Log.warn(f"recovery: cloud re-formed onto CHANGED topology "
                     f"{shape[0]}x{shape[1]} ({m.devices.size} device(s), "
                     f"epoch {_mesh.mesh_epoch()})")
            flightrec.record("reform_topology", rows=shape[0],
                             cols=shape[1], epoch=_mesh.mesh_epoch())
    except Exception as e:  # noqa: BLE001 — a dead backend must not stop the
        # state transition; the next dispatch surfaces the real error
        Log.warn(f"recovery: mesh rebuild failed ({e!r}); proceeding with "
                 "the recover transition — the next dispatch will retry it")
    return cloud.recover(reason)


def run_supervised(launch, *, ckdir: str | None = None, algo: str | None = None,
                   description: str = "job", max_restarts: int | None = None,
                   job=None):
    """Run ``launch(checkpoint)`` under the recovery supervisor.

    ``launch`` is called with ``None`` first; on a qualifying cloud failure
    (see :func:`is_cloud_failure`) the supervisor backs off, re-forms the
    cloud, and calls it again with the latest snapshot path from ``ckdir``
    (or the previous checkpoint when no newer snapshot landed). Anything
    that is not a cloud failure — or any failure when recovery is disabled
    — propagates unchanged, preserving today's fail-stop semantics
    bit-for-bit under ``H2O3_TPU_RECOVERY=0``.

    **OOM catch-and-degrade** (ISSUE 19): a ``RESOURCE_EXHAUSTED`` that the
    overload plane classified at a dispatch site is NOT a cloud failure —
    the formation is healthy, the job was just too big — so instead of a
    reform the job relaunches exactly ONCE under ``overload.degrade_scope``
    (``ChunkStore.plan`` streams the frame / halves the window) from its
    latest snapshot. ``oom_degrades_total{site,outcome}`` counts retried /
    recovered / exhausted; a second OOM while already degraded — and every
    OOM with the plane or recovery disabled — surfaces unchanged, keeping
    the deterministic-errors-never-retry contract."""
    if max_restarts is None:
        max_restarts = _max_restarts()
    attempt = 0
    ckpt: str | None = None
    oom_degraded: str | None = None  # OOM site once the degraded retry armed
    while True:
        launched_at = time.monotonic()
        try:
            from h2o3_tpu.utils import overload as _overload

            if oom_degraded is not None:
                with _overload.degrade_scope():
                    out = launch(ckpt)
                _overload.count_degrade(oom_degraded, "recovered")
                return out
            return launch(ckpt)
        except BaseException as e:  # noqa: BLE001 — classified below
            if enabled():
                from h2o3_tpu.utils import overload as _overload

                oom_at = _overload.oom_site(e)
                if oom_at is not None and oom_degraded is None:
                    # degrade ONCE: the cloud is healthy (no reform), the
                    # job was too big — relaunch streamed/halved from the
                    # latest snapshot. note_dispatch_error already froze
                    # the incident bundle naming the OOM dispatch.
                    oom_degraded = oom_at
                    _overload.count_degrade(oom_at, "retried")
                    snap = latest_snapshot(ckdir, algo)
                    from h2o3_tpu.utils import flightrec

                    flightrec.record(
                        "oom_degrade", job=description, site=oom_at,
                        error=type(e).__name__)
                    bundle = flightrec.last_incident()
                    if bundle is not None and job is not None:
                        info = dict(getattr(job, "recovery", None) or {})
                        info["incident_bundle"] = bundle
                        info["oom_degrade"] = {"site": oom_at}
                        if hasattr(job, "set_recovery"):
                            job.set_recovery(info)
                        else:
                            job.recovery = info
                    delay = backoff_delay(0, key=f"{description}-oom")
                    Log.warn(
                        f"recovery: {description} hit RESOURCE_EXHAUSTED at "
                        f"dispatch site {oom_at!r}; retrying ONCE degraded "
                        f"(streamed/halved window) in {delay:.2f}s"
                        + (f" from snapshot {snap}" if snap
                           else " from scratch"))
                    time.sleep(delay)
                    if snap is not None:
                        ckpt = snap
                    continue
                if oom_at is not None:
                    # second OOM while already degraded: out of degrade
                    # moves — surface it like any deterministic failure
                    _overload.count_degrade(oom_at, "exhausted")
            if not enabled() or not is_cloud_failure(e):
                raise
            healthy = time.monotonic() - launched_at
            reset_secs = _reset_secs()
            if attempt and reset_secs > 0 and healthy >= reset_secs:
                # the job ran healthy past the configured window since its
                # last restart: old restarts no longer predict the next
                # transient, so the budget resets instead of a days-long
                # job dying on its 3rd unrelated blip
                Log.info(
                    f"recovery: {description} ran healthy {healthy:.0f}s "
                    f">= H2O3_TPU_RECOVERY_RESET_SECS={reset_secs:.0f} — "
                    f"restart budget reset (was {attempt})")
                attempt = 0
            if attempt >= max_restarts:
                _ATTEMPTS.inc(outcome="exhausted")
                raise RecoveryExhausted(
                    f"supervised recovery of {description!r} gave up after "
                    f"{attempt} restart(s) "
                    f"(H2O3_TPU_RECOVERY_MAX_RESTARTS={max_restarts}); "
                    f"latest snapshot: {latest_snapshot(ckdir, algo)}"
                ) from e
            t0 = time.monotonic()
            snap = latest_snapshot(ckdir, algo)
            # the postmortem evidence, captured before the retry discards
            # it; the path surfaces in the job's recovery block so the
            # /3/Jobs poller (and the runbook) can find the bundle
            from h2o3_tpu.cluster import cloud
            from h2o3_tpu.utils import flightrec

            flightrec.record(
                "cloud_failure", job=description,
                error=type(e).__name__, generation=cloud.generation(),
                attempt=attempt + 1)
            bundle = flightrec.capture_incident(
                f"{description}: {type(e).__name__}: {e}", trigger="retry")
            if bundle is not None and job is not None:
                info = dict(getattr(job, "recovery", None) or {})
                info["incident_bundle"] = bundle
                if hasattr(job, "set_recovery"):
                    job.set_recovery(info)
                else:
                    job.recovery = info
            delay = backoff_delay(attempt, key=description)
            Log.warn(
                f"recovery: {description} died of a cloud failure "
                f"({type(e).__name__}); restart {attempt + 1}/{max_restarts} "
                f"in {delay:.2f}s"
                + (f" from snapshot {snap}" if snap else " from scratch")
            )
            time.sleep(delay)
            reform(f"supervised restart of {description} "
                   f"(attempt {attempt + 1}/{max_restarts})")
            if snap is not None:
                ckpt = snap
            attempt += 1
            if job is not None and hasattr(job, "restarts"):
                job.restarts = attempt
            _ATTEMPTS.inc(outcome="resumed")
            dt = time.monotonic() - t0
            _SECONDS.observe(dt)
            # recovery_seconds rides the flight recorder too, so an
            # incident bundle (or the pod-restart drill) shows failure →
            # relaunch latency next to the dispatches it interrupted
            flightrec.record(
                "recovery", seconds=round(dt, 3), outcome="resumed",
                job=description, generation=cloud.generation())


# ---------------------------------------------------------------------------
# background supervisor: the launch.py-installed watcher that re-forms the
# cloud when the degraded latch is set with no supervised job attached (a
# watchdog trip between jobs, a death signature on an unsupervised command).
# Without it, a coordinator whose cloud degraded while idle stays bricked
# until an operator calls clear_degraded — with it, the serving tier's
# circuit breakers half-open and checkpointed work becomes resumable again.

_WATCHER: threading.Thread | None = None
_WATCH_STOP = threading.Event()


def _watch_loop(poll: float) -> None:
    from h2o3_tpu.cluster import cloud

    consecutive = 0
    last_reform = 0.0
    while not _WATCH_STOP.wait(poll):
        if not enabled() or cloud.degraded_reason() is None:
            if consecutive and time.monotonic() - last_reform > 60.0:
                consecutive = 0  # a minute of health resets the backoff
            continue
        t0 = time.monotonic()
        delay = backoff_delay(min(consecutive, 6), key="latch-watch")
        if _WATCH_STOP.wait(delay):
            return
        if cloud.degraded_reason() is None:
            continue  # resolved (operator / job supervisor) while backing off
        gen = reform("background supervisor: degraded latch with no "
                     "supervised job attached")
        _ATTEMPTS.inc(outcome="reform")
        dt = time.monotonic() - t0
        _SECONDS.observe(dt)
        from h2o3_tpu.utils import flightrec

        flightrec.record("recovery", seconds=round(dt, 3),
                         outcome="reform", generation=gen)
        Log.warn(f"recovery: background reform complete (generation {gen})")
        consecutive += 1
        last_reform = time.monotonic()


def install(poll: float = 0.5) -> None:
    """Start the background latch watcher (idempotent; daemon thread). The
    loop no-ops while recovery is disabled, so installing it is always safe
    — launch.py installs it on the REST coordinator."""
    global _WATCHER
    if _WATCHER is not None and _WATCHER.is_alive():
        return
    _WATCH_STOP.clear()
    _WATCHER = threading.Thread(
        target=_watch_loop, args=(poll,), name="h2o3-recovery", daemon=True)
    _WATCHER.start()


def uninstall() -> None:
    """Stop the background watcher (tests)."""
    global _WATCHER
    _WATCH_STOP.set()
    if _WATCHER is not None:
        _WATCHER.join(timeout=5)
    _WATCHER = None
