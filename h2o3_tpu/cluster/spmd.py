"""SPMD command replication — the ``water.DTask``/RPC successor for
multi-host clouds (SURVEY.md §2.1 RPC/DTask row, §5.8).

Multi-controller JAX requires every process to execute the same device
program: a jit entered only on the REST coordinator would hang at its first
cross-process collective. H2O solves the equivalent problem by shipping a
serialized ``DTask`` to every node (``new RPC<>(node, dtask).call()``
[UNVERIFIED upstream path]); here the coordinator (process 0) broadcasts a
pickled ``(command, kwargs)`` through the jax coordination service and every
process — coordinator included — executes the SAME registered function.
Determinism of the shared execution (same frames from the same source, same
seeds, coordinator-chosen DKV keys carried in the command) is what keeps the
ranks' collective sequences aligned, exactly as H2O relies on every node
running the same jar.

Replicated commands: Parse (incl. sharded), model build, predict, grid
search, AutoML, Rapids eval, frame summary/download/export, and binary
model save/load. Grid/AutoML/Rapids replication rides the deterministic
key sequence ``DKV.make_key`` switches to inside replicated execution —
every rank names result frames and models identically without shipping
keys. Wall-clock budgets (``max_runtime_secs``) are rejected on
multi-process clouds: ranks' clocks diverge and would desynchronize the
collective sequence; use ``max_models``. Random Rapids ops (``h2o.runif``,
stratified split) demand an explicit seed for the same reason. File
writes (export, model save) pull collectively on every rank but write
from the coordinator only; file reads (model load) require the path to be
readable on every rank, the same contract as parse sources.

The broadcast payload is length-prefixed and padded to a power of two so the
number of distinct broadcast programs stays O(log max_payload).

Failure detection (SURVEY §5.3): the jax coordination service's heartbeat
IS the ``HeartBeatThread`` successor — a dead rank is detected by the
service, which poisons every other rank's next collective with a fatal
``PollForError`` (observed in the multihost test logs when a rank is
killed). The cloud is fail-stop on member death, exactly H2O's semantics
("a dead member makes the cluster unusable; restart is the recovery path");
durability comes from model checkpoints, not elasticity.
"""

from __future__ import annotations

import contextvars
import pickle
import threading
import time

import numpy as np

from h2o3_tpu.utils import metrics
from h2o3_tpu.utils.log import Log

_CMDS_TOTAL = metrics.counter(
    "spmd_commands_total", "replicated commands executed, by command")
_CMD_SECONDS = metrics.histogram(
    "spmd_command_seconds", "replicated command wall time, by command")
_BCAST_TOTAL = metrics.counter(
    "spmd_broadcasts_total", "coordination-service command broadcasts")
_COLLECTIVE_SECONDS = metrics.counter(
    "spmd_collective_seconds_total",
    "wall seconds inside command-broadcast collectives (the mesh "
    "communication overhead lever — invisible without a dedicated timer)")
_WATCHDOG_TRIPS = metrics.counter(
    "spmd_watchdog_trips_total",
    "replicated commands the collective watchdog presumed wedged "
    "(H2O3_TPU_SPMD_WATCHDOG_SECS exceeded → degraded latch), by command")

_LOCK = threading.RLock()  # serializes the coordinator's device-work commands
# ContextVar, not a process global: nested Job threads inherit it because
# Job.start runs the thread inside the creator's copied context (job.py),
# while unrelated coordinator REST threads see 0 — a process-global flag let
# a concurrent REST request mint from the replicated key sequence and drift
# the coordinator's keys ahead of the followers'.
_REPLICATED_VAR: contextvars.ContextVar[int] = contextvars.ContextVar(
    "spmd_replicated", default=0
)


def in_replicated() -> bool:
    """True while executing a replicated command (every rank in lockstep) —
    the only context where cross-process collectives are safe."""
    return _REPLICATED_VAR.get() > 0


import contextlib


@contextlib.contextmanager
def replicated_section():
    """Mark a region as replicated execution for library users driving their
    own multi-controller SPMD scripts (every rank must enter it together)."""
    token = _REPLICATED_VAR.set(_REPLICATED_VAR.get() + 1)
    try:
        yield
    finally:
        _REPLICATED_VAR.reset(token)


# -- collective watchdog -----------------------------------------------------
# A wedged collective (one rank stalled inside a cross-process program) hangs
# the coordinator's command thread forever while it holds _LOCK; every later
# spmd.run then blocks on the lock and the cloud goes from healthy to hung
# with nothing observable in between. The watchdog is the bounded-hang
# answer: commands register themselves while executing, a monitor thread
# latches cloud.mark_degraded once one exceeds its budget
# (H2O3_TPU_SPMD_WATCHDOG_SECS, read per command), and lock waiters poll the
# latch (bounded acquire below) so they fail-stop instead of queueing behind
# the wedge. Coordinator-side only — follower clocks diverge from the
# coordinator's, and followers already fail-stop through the coordination
# service — and disabled by default: only an operator who knows the
# workload's longest legitimate command should set a budget.

import itertools as _itertools

_WATCH_LOCK = threading.Lock()
_WATCH_ACTIVE: dict[int, dict] = {}
_WATCH_IDS = _itertools.count(1)
_WATCH_THREAD: threading.Thread | None = None


def _watchdog_budget() -> float:
    from h2o3_tpu import config

    try:
        return config.get_float("H2O3_TPU_SPMD_WATCHDOG_SECS")
    except (TypeError, ValueError):
        return 0.0


def _watchdog_pass(active: "list[tuple[int, dict]]") -> float:
    """One monitor sweep over a snapshot of the active commands; returns
    the sleep interval until the next sweep."""
    now = time.monotonic()
    interval = 0.2
    for wid, w in active:
        budget = w["budget"]
        interval = min(interval, max(budget / 4.0, 0.02))
        if now - w["t0"] <= budget or w["tripped"]:
            continue
        # Re-check under the lock before latching: the command may have
        # completed (and been popped by _watched's finally) between the
        # snapshot and now — tripping then would permanently degrade a
        # healthy cloud. Only a wid still registered is actually running.
        with _WATCH_LOCK:
            if (_WATCH_ACTIVE.get(wid) is not w or w["tripped"]
                    or time.monotonic() - w["t0"] <= budget):
                continue
            w["tripped"] = True
        _WATCHDOG_TRIPS.inc(cmd=w["cmd"])
        from h2o3_tpu.cluster import cloud
        from h2o3_tpu.utils import flightrec

        flightrec.record("watchdog_trip", cmd=w["cmd"],
                         budget_s=w["budget"],
                         running_s=round(time.monotonic() - w["t0"], 3))
        cloud.mark_degraded(
            f"spmd watchdog: replicated command {w['cmd']!r} still "
            f"running after its {budget}s budget — presumed wedged "
            "mid-collective (fail-stop; restart the cloud, recover "
            "models from checkpoints)"
        )
    return interval


def _watchdog_loop() -> None:
    while True:
        with _WATCH_LOCK:
            active = list(_WATCH_ACTIVE.items())
        time.sleep(_watchdog_pass(active))


@contextlib.contextmanager
def _watched(cmd: str):
    """Register ``cmd`` with the watchdog for the duration of its execution
    (no-op when the budget knob is 0/unset)."""
    budget = _watchdog_budget()
    if budget <= 0:
        yield
        return
    global _WATCH_THREAD
    wid = next(_WATCH_IDS)
    with _WATCH_LOCK:
        _WATCH_ACTIVE[wid] = {
            "cmd": cmd, "t0": time.monotonic(), "budget": budget,
            "tripped": False,
        }
        if _WATCH_THREAD is None or not _WATCH_THREAD.is_alive():
            _WATCH_THREAD = threading.Thread(
                target=_watchdog_loop, name="spmd-watchdog", daemon=True
            )
            _WATCH_THREAD.start()
    try:
        yield
    finally:
        with _WATCH_LOCK:
            _WATCH_ACTIVE.pop(wid, None)


def _failstop_if_degraded() -> None:
    from h2o3_tpu.cluster import cloud

    reason = cloud.degraded_reason()
    if reason is not None:
        raise RuntimeError(
            f"cloud is degraded (fail-stop): {reason} — "
            "restart the cloud; recover models from checkpoints"
        )


class StaleGeneration(RuntimeError):
    """A replicated command stamped under one cloud formation observed a
    reform (``cloud.recover``) to a newer generation before it could
    execute. The command fail-stops — it belongs to the failure epoch, and
    letting it run (or broadcast) could interleave its collectives with a
    wedged predecessor's on some rank. The supervisor's retry re-enters
    under the NEW generation."""


def _check_generation(entry_gen: int) -> None:
    from h2o3_tpu.cluster import cloud

    cur = cloud.generation()
    if cur != entry_gen:
        raise StaleGeneration(
            f"cloud re-formed (generation {entry_gen} -> {cur}) while this "
            "command waited (fail-stop): the command belongs to the failed "
            "formation — retry it against the new cloud"
        )


def _stale_reason(gen: int | None) -> str | None:
    """Follower-side fence: reject a command stamped with a generation OLDER
    than this rank's (a reform raced the broadcast — the command belongs to
    a pre-reform formation). A NEWER stamp is adopted: the coordinator
    re-formed and this rank learns the reform through the command stream,
    exactly how it learns everything else. Returns the rejection reason, or
    None when the command should execute."""
    if gen is None:  # legacy 2-tuple payload (no stamp): nothing to check
        return None
    from h2o3_tpu.cluster import cloud

    cur = cloud.generation()
    if gen < cur:
        return (f"stale-generation command (stamped {gen}, cloud is at "
                f"{cur}) rejected: it belongs to a pre-reform formation")
    if gen > cur:
        cloud.adopt_generation(gen)
    return None


def _acquire_command_lock(entry_gen: int) -> None:
    """Acquire ``_LOCK`` but keep polling the degraded latch AND the cloud
    generation: a caller queued behind a wedged command must fail-stop the
    moment the watchdog (or a death signature) latches — and must STAY
    fail-stopped if the supervisor re-forms the cloud while it waits. The
    generation poll is what drains pre-reform waiters: without it, a waiter
    that slept through the whole degraded window would acquire the lock on
    the re-formed cloud and execute a command from the failure epoch."""
    while not _LOCK.acquire(timeout=0.25):
        _failstop_if_degraded()
        _check_generation(entry_gen)


_IS_MULTI = False  # set once by cluster.cloud.init; read on hot paths


def mark_multi_process(flag: bool) -> None:
    global _IS_MULTI
    _IS_MULTI = bool(flag)


def multi_process() -> bool:
    if _IS_MULTI:
        return True
    import jax

    return jax.process_count() > 1


def is_coordinator() -> bool:
    import jax

    return jax.process_index() == 0


def _bcast_bytes(payload: bytes | None) -> bytes:
    """Broadcast a byte string from process 0 to all (collective: every
    process must call this — followers pass ``None``)."""
    from jax.experimental import multihost_utils as mh
    from h2o3_tpu.utils import faults

    faults.die_check("bcast")  # chaos: process death at a collective boundary
    t0 = time.perf_counter()
    n = len(payload) if payload is not None else 0
    n_arr = mh.broadcast_one_to_all(np.array([n], np.int32))
    n = int(n_arr[0])
    cap = 1 << max(10, (n - 1).bit_length())  # pow2 pad bounds compile count
    buf = np.zeros(cap, np.uint8)
    if payload is not None:
        buf[: len(payload)] = np.frombuffer(payload, np.uint8)
    data = mh.broadcast_one_to_all(buf)
    out = bytes(np.asarray(data[:n], np.uint8))
    _BCAST_TOTAL.inc()
    _COLLECTIVE_SECONDS.inc(time.perf_counter() - t0)
    return out


# -- command registry --------------------------------------------------------


def _exec_parse(setup: dict, dest: str):
    from h2o3_tpu.frame.parse import parse, parse_sharded

    if setup.pop("sharded", False):
        return parse_sharded(setup, destination_frame=dest)
    return parse(setup, destination_frame=dest)


def _exec_build(algo: str, kwargs: dict, x, y, train, valid, dest: str):
    from h2o3_tpu.api.server import _builder_cls
    from h2o3_tpu.cluster.registry import DKV

    model = _builder_cls(algo)(**kwargs).train(
        x=x, y=y, training_frame=train, validation_frame=valid
    )
    # every rank re-keys to the coordinator-chosen key so later commands
    # (predict, fetch) reference the same object on all ranks
    DKV.remove(model.key)
    model.key = dest
    DKV.put(dest, model)
    return model


def _exec_predict(model_key: str, frame_key: str, dest: str, option: str = "",
                  leaf_type: str = "Path"):
    from h2o3_tpu.cluster.registry import DKV

    model = DKV.get(model_key)
    fr = DKV.get(frame_key)
    if option == "contributions":
        out = model.predict_contributions(fr)
    elif option == "leaf_assignment":
        out = model.predict_leaf_node_assignment(fr, type=leaf_type)
    elif option == "reconstruction_error":
        out = model.anomaly(fr)
    else:
        out = model.predict(fr)
    DKV.put(dest, out)
    return out


def _exec_split_frame(frame_key: str, ratios, dests, seed: int):
    from h2o3_tpu.cluster.registry import DKV

    fr = DKV.get(frame_key)
    parts = fr.split_frame(list(ratios), seed=int(seed))
    # the host-side rng mask is seed-deterministic, so every rank computes
    # identical splits; rename each part onto its coordinator-chosen key
    out = []
    for p, d in zip(parts, dests):
        DKV.remove(p.key)
        p.key = d
        DKV.put(d, p)
        out.append(p)
    for p in parts[len(dests):]:  # unnamed remainder splits are dropped
        DKV.remove(p.key)
    return out


def _exec_interaction(frame_key: str, dest: str, factors, pairwise: bool,
                      max_factors: int, min_occurrence: int):
    from h2o3_tpu.cluster.registry import DKV
    from h2o3_tpu.frame import ops

    fr = DKV.get(frame_key)
    return ops.interaction(
        fr, list(factors), pairwise=bool(pairwise),
        max_factors=int(max_factors), min_occurrence=int(min_occurrence),
        destination_frame=dest,
    )


def _exec_create_frame(dest: str, spec: dict):
    """Synthetic frame generator (water/api/CreateFrameHandler successor
    [UNVERIFIED]): seed-deterministic host generation, identical on every
    rank."""
    import numpy as np
    import pandas as pd

    from h2o3_tpu.cluster.registry import DKV
    from h2o3_tpu.frame.frame import Frame

    rows = int(spec.get("rows", 10_000))
    cols = int(spec.get("cols", 10))
    # the coordinator resolves unseeded requests before broadcasting
    # (server.create_frame); a residual -1 here must still be deterministic
    # across ranks, so it maps to a fixed seed rather than OS entropy
    seed = int(spec.get("seed", -1))
    rng = np.random.default_rng(1234 if seed < 0 else seed)
    cat_frac = float(spec.get("categorical_fraction", 0.2))
    int_frac = float(spec.get("integer_fraction", 0.2))
    bin_frac = float(spec.get("binary_fraction", 0.1))
    missing = float(spec.get("missing_fraction", 0.0))
    factors = int(spec.get("factors", 100))
    real_range = float(spec.get("real_range", 100.0))
    int_range = int(spec.get("integer_range", 100))

    n_cat = int(round(cols * cat_frac))
    n_int = int(round(cols * int_frac))
    n_bin = int(round(cols * bin_frac))
    n_real = max(cols - n_cat - n_int - n_bin, 0)

    data = {}
    i = 0
    for _ in range(n_real):
        data[f"C{i + 1}"] = rng.uniform(-real_range, real_range, rows)
        i += 1
    for _ in range(n_int):
        data[f"C{i + 1}"] = rng.integers(-int_range, int_range + 1, rows).astype(np.float64)
        i += 1
    for _ in range(n_bin):
        data[f"C{i + 1}"] = rng.integers(0, 2, rows).astype(np.float64)
        i += 1
    for _ in range(n_cat):
        data[f"C{i + 1}"] = np.array(
            [f"c{int(v)}.l{int(v)}" for v in rng.integers(0, max(factors, 1), rows)]
        )
        i += 1
    df = pd.DataFrame(data)
    if missing > 0:
        mask = rng.random((rows, len(df.columns))) < missing
        df = df.mask(pd.DataFrame(mask, columns=df.columns))
    if spec.get("has_response"):
        rf = int(spec.get("response_factors", 2))
        if rf <= 1:
            df.insert(0, "response", rng.uniform(-real_range, real_range, rows))
        else:
            df.insert(0, "response", np.array(
                [f"resp{int(v)}" for v in rng.integers(0, rf, rows)]))
    fr = Frame.from_pandas(df)
    DKV.remove(fr.key)
    fr.key = dest
    DKV.put(dest, fr)
    return fr


class _JobShim:
    """Followers have no REST Job; grid/AutoML drivers only need these."""

    stop_requested = False
    progress = 0.0

    def update(self, p, *a, **k):
        self.progress = p


def _require_deterministic_budget(name: str, max_runtime) -> None:
    if multi_process() and max_runtime:
        raise ValueError(
            f"{name} with max_runtime_secs is not supported on a "
            "multi-process cloud: wall-clock budgets diverge across ranks "
            "and desynchronize the replicated collective sequence — use "
            "max_models (deterministic) instead"
        )


def _exec_grid(algo, hyper, criteria, grid_id, parallelism, kwargs, x, y,
               train, valid):
    from h2o3_tpu.api.server import _builder_cls
    from h2o3_tpu.cluster.registry import DKV
    from h2o3_tpu.models.grid import GridSearch

    criteria = dict(criteria or {})
    _require_deterministic_budget("Grid search", kwargs.get("max_runtime_secs")
                                  or criteria.get("max_runtime_secs"))
    if multi_process():
        # threads would interleave device programs differently per rank
        parallelism = 1
        if kwargs.get("export_checkpoints_dir"):
            raise ValueError(
                "export_checkpoints_dir is not supported on a multi-process "
                "cloud: per-rank manifest recovery/writes desynchronize the "
                "replicated sequence (and corrupt shared manifests)"
            )
        if (criteria.get("strategy") == "RandomDiscrete"
                and criteria.get("seed") in (None, -1)):
            raise ValueError(
                "RandomDiscrete grids on a multi-process cloud need an "
                "explicit search_criteria seed (ranks must draw the same "
                "combo sequence)"
            )
    gs = GridSearch(_builder_cls(algo), hyper, search_criteria=criteria or None,
                    grid_id=grid_id, parallelism=parallelism, **kwargs)
    gs._drive(_JobShim(), x, y, DKV.get(train),
              DKV.get(valid) if valid else None, {})
    return gs.grid


def _exec_automl(kwargs, y, train, dest):
    from h2o3_tpu.automl import AutoML
    from h2o3_tpu.cluster.registry import DKV

    _require_deterministic_budget("AutoML", kwargs.get("max_runtime_secs"))
    if multi_process():
        if kwargs.get("export_checkpoints_dir"):
            raise ValueError(
                "AutoML export_checkpoints_dir is not supported on a "
                "multi-process cloud: per-rank manifest recovery/writes "
                "desynchronize the replicated sequence (same rule as grids)"
            )
        # AutoMLSpec defaults max_runtime_secs to 3600 — a wall-clock budget
        # the ranks' clocks would apply differently; force it off and demand
        # the deterministic budget + seed instead
        kwargs = dict(kwargs, max_runtime_secs=0.0, max_runtime_secs_per_model=0.0)
        if not kwargs.get("max_models"):
            raise ValueError(
                "AutoML on a multi-process cloud needs max_models "
                "(wall-clock budgets diverge across ranks)"
            )
        if kwargs.get("seed") in (None, -1):
            raise ValueError(
                "AutoML on a multi-process cloud needs an explicit seed "
                "(its RandomDiscrete grid steps must draw identical combos "
                "on every rank)"
            )
    aml = AutoML(**kwargs)
    DKV.remove(aml.key)
    aml.key = dest  # coordinator-chosen, carried in the command
    DKV.put(dest, aml)  # registered BEFORE the run: clients poll mid-build
    aml._drive(_JobShim(), None, y, train, None, None)
    return aml


def _exec_rapids(ast: str, session):
    from h2o3_tpu.api.rapids import rapids_eval

    # every rank evaluates the same expression string against its copy of the
    # session; result keys come from DKV.make_key's replicated counter, so
    # ranks agree without shipping keys. Host pulls inside ops (quantile,
    # stratified_split, merge keys …) become collectives here.
    return rapids_eval(ast, session=session)


def _exec_frame_summary(key: str):
    from h2o3_tpu.cluster.registry import DKV

    fr = DKV.get(key)
    if fr is None:
        raise KeyError(f"Frame {key} not found")
    # describe() computes + caches per-Vec rollup stats — collective pulls —
    # on every rank; the route layer shapes the coordinator's copy
    return fr.describe()


def _exec_frame_pull(key: str):
    from h2o3_tpu.cluster.registry import DKV

    fr = DKV.get(key)
    if fr is None:
        raise KeyError(f"Frame {key} not found")
    return fr.to_pandas()


def _exec_frame_export(key: str, path: str, force: bool, format):
    from h2o3_tpu.cluster.registry import DKV
    from h2o3_tpu.persist import export_df

    fr = DKV.get(key)
    if fr is None:
        raise KeyError(f"Frame {key} not found")
    df = fr.to_pandas()  # collective pull on every rank …
    if is_coordinator():  # … but exactly one writer (shared-fs safe)
        return export_df(df, path, force=force, format=format)
    return path


def _exec_model_save(key: str, dir: str, force: bool):
    from h2o3_tpu.cluster.registry import DKV
    from h2o3_tpu.persist import (
        resolve_model_path,
        serialize_model,
        write_model_bytes,
    )

    model = DKV.get(key)
    # pulls FIRST on every rank, resolve/exists-check after and coordinator-
    # only: the exists/force answer depends on the coordinator's filesystem,
    # so followers cannot evaluate it identically — checking before the
    # collective pulls would let rank 0 bail while the others enter them.
    # A force=False collision wastes one pull; the cloud stays in lockstep.
    data = serialize_model(model)
    if is_coordinator():
        backend, p = resolve_model_path(dir, model.key, force)
        return write_model_bytes(data, backend, p, model.key)
    return None


def _exec_remove(key: str):
    from h2o3_tpu.cluster.registry import DKV

    # deletes must replicate or the ranks' DKVs diverge: a key deleted on the
    # coordinator alone would still resolve on followers, so a later rapids
    # command referencing it fails on rank 0 but RUNS on the others —
    # advancing their replicated key counters (permanent key skew) or
    # entering a collective alone (wedged cloud). No collectives inside.
    DKV.remove(key)


def _exec_model_load(dir: str):
    from h2o3_tpu.persist import load_model

    # the file must be on a path every rank can read (same contract as
    # parse sources); the model key is stored in the file, so ranks agree
    return load_model(dir)


def _exec_metrics_pod():
    from h2o3_tpu.cluster import federation

    # the snapshot allgather inside is the collective — every rank enters
    # it through this command, in lockstep with the rest of the stream
    return federation.pod_snapshot()


_COMMANDS = {
    "parse": _exec_parse,
    "build": _exec_build,
    "predict": _exec_predict,
    "grid": _exec_grid,
    "automl": _exec_automl,
    "rapids": _exec_rapids,
    "split_frame": _exec_split_frame,
    "create_frame": _exec_create_frame,
    "interaction": _exec_interaction,
    "frame_summary": _exec_frame_summary,
    "frame_pull": _exec_frame_pull,
    "frame_export": _exec_frame_export,
    "model_save": _exec_model_save,
    "model_load": _exec_model_load,
    "metrics_pod": _exec_metrics_pod,
    "remove": _exec_remove,
}

_SHUTDOWN = "__shutdown__"


_DEATH_SIGNATURES = (
    "coordination service", "PollForError", "heartbeat",
    "tasks are unhealthy", "jax_worker", "DEADLINE_EXCEEDED",
)


def _maybe_mark_dead_member(exc: BaseException) -> None:
    """A deterministic command error raises identically on every rank and
    the cloud stays usable; a coordination-service failure (dead member,
    severed coordinator) poisons every future collective — latch fail-stop
    so `/3/Cloud` and subsequent jobs report it instead of hanging.

    Only XLA-runtime errors are eligible: a user command failing on its own
    network IO (unreachable s3 endpoint, dead parse source) raises
    botocore/OSError types whose reprs can also say "connection" — those are
    deterministic command failures, not cloud death, and must not brick a
    healthy cloud behind the one-way latch."""
    if "xlaruntimeerror" not in type(exc).__name__.lower():
        import jax

        if not isinstance(exc, jax.errors.JaxRuntimeError):
            return
    msg = repr(exc)
    if any(sig.lower() in msg.lower() for sig in _DEATH_SIGNATURES):
        from h2o3_tpu.cluster import cloud

        cloud.mark_degraded(f"replicated command failed mid-collective: {msg[:300]}")


def run(cmd: str, **kwargs):
    """Execute ``cmd`` on every process of the cloud (coordinator API).

    Single-process clouds execute directly; multi-process clouds broadcast
    first so followers enter the same program. Holding the lock for the whole
    execution serializes device work — collective order must match on every
    rank, and concurrent jobs on the coordinator would interleave it.

    Every command is stamped with the cloud generation it entered under
    (``cloud.generation``): if a supervised reform (cluster/recovery.py)
    ticks the generation while the command waits on the lock, the command
    fail-stops with :class:`StaleGeneration` instead of executing against a
    formation it was never stamped for."""
    from h2o3_tpu.cluster import cloud
    from h2o3_tpu.utils import faults

    entry_gen = cloud.generation()
    if not multi_process():
        # the degraded latch fail-stops here too: single-host it can only be
        # set by the collective watchdog (a wedged device program), and a
        # wedged mesh is no more usable for the next command than a dead one
        _failstop_if_degraded()
        _check_generation(entry_gen)
        try:
            faults.death_check("spmd_run")  # chaos: synthetic dead member
            _CMDS_TOTAL.inc(cmd=cmd)
            t0 = time.perf_counter()
            with metrics.span(f"spmd.{cmd}"):
                try:
                    with _watched(cmd):
                        faults.stall_check("spmd_run")  # chaos: wedge
                        return _COMMANDS[cmd](**kwargs)
                finally:
                    _CMD_SECONDS.observe(time.perf_counter() - t0, cmd=cmd)
        except Exception as e:
            _maybe_mark_dead_member(e)  # runtime death signatures latch here too
            raise
    if not is_coordinator():  # pragma: no cover - followers use follower_loop
        raise RuntimeError("spmd.run is coordinator-only")
    # bounded acquire: waiters poll the degraded latch AND the generation so
    # a command wedged inside the lock (watchdog's case) fail-stops the
    # queue behind it — including waiters that outlive a supervised reform
    _acquire_command_lock(entry_gen)
    try:
        # degraded + generation checks INSIDE the lock: a job queued on the
        # lock while another latches the failure must not broadcast into the
        # dead cloud, and one that slept through a reform must not broadcast
        # a pre-reform command into the new one
        _failstop_if_degraded()
        _check_generation(entry_gen)
        try:
            faults.death_check("spmd_run")  # chaos: synthetic dead member
            _CMDS_TOTAL.inc(cmd=cmd)
            t0 = time.perf_counter()
            with metrics.span(f"spmd.{cmd}", replicated="1"):
                try:
                    with _watched(cmd):
                        faults.stall_check("spmd_run")  # chaos: wedge
                        _bcast_bytes(pickle.dumps((entry_gen, cmd, kwargs)))
                        with replicated_section():
                            return _COMMANDS[cmd](**kwargs)
                finally:
                    _CMD_SECONDS.observe(time.perf_counter() - t0, cmd=cmd)
        except Exception as e:
            _maybe_mark_dead_member(e)
            raise
    finally:
        _LOCK.release()


def shutdown_followers(timeout: float = 10.0) -> None:
    if multi_process() and is_coordinator():
        # bounded: a command wedged inside the lock (the watchdog's case)
        # must not turn shutdown/drain into a second hang — the followers
        # are stuck in the same dead collective anyway and die on restart
        if not _LOCK.acquire(timeout=timeout):
            Log.warn(
                f"shutdown_followers: command lock still held after "
                f"{timeout}s (wedged collective?) — skipping the shutdown "
                "broadcast"
            )
            return
        try:
            from h2o3_tpu.cluster import cloud

            _bcast_bytes(pickle.dumps((cloud.generation(), _SHUTDOWN, {})))
        finally:
            _LOCK.release()


def follower_loop() -> None:
    """Run on every non-coordinator process: execute the coordinator's
    command stream until shutdown.

    Deterministic command failures (bad path, bad params) raise IDENTICALLY
    on every rank — the coordinator's Job catches its copy, so the follower
    must survive too or one bad request would wedge the whole cloud. The
    exception is logged and the loop continues; genuinely divergent state
    (one rank fails mid-collective) surfaces as a collective mismatch and
    remains fail-stop."""
    Log.info(f"spmd follower loop up (process {__import__('jax').process_index()})")
    while True:
        try:
            payload = pickle.loads(_bcast_bytes(None))
        except Exception as e:  # dead coordinator/member: fail-stop the rank
            _maybe_mark_dead_member(e)
            raise
        if len(payload) == 3:
            gen, cmd, kwargs = payload
        else:  # legacy unstamped (cmd, kwargs) payload
            gen, (cmd, kwargs) = None, payload
        if cmd == _SHUTDOWN:
            Log.info("spmd follower shutdown")
            return
        stale = _stale_reason(gen)
        if stale is not None:
            # deterministic rejection: the coordinator's own generation
            # check raises the same epoch for its copy, so skipping here
            # keeps the ranks' replicated key/collective sequences aligned
            Log.err(f"spmd follower {stale}")
            continue
        Log.info(f"spmd follower executing {cmd}")
        try:
            with replicated_section():
                _COMMANDS[cmd](**kwargs)
        except Exception as e:
            import traceback

            _maybe_mark_dead_member(e)
            Log.err(
                "spmd follower command failed (coordinator job fails with "
                f"the same error):\n{traceback.format_exc()}"
            )
