"""SPMD command replication — the ``water.DTask``/RPC successor for
multi-host clouds (SURVEY.md §2.1 RPC/DTask row, §5.8).

Multi-controller JAX requires every process to execute the same device
program: a jit entered only on the REST coordinator would hang at its first
cross-process collective. H2O solves the equivalent problem by shipping a
serialized ``DTask`` to every node (``new RPC<>(node, dtask).call()``
[UNVERIFIED upstream path]); here the coordinator (process 0) broadcasts a
pickled ``(command, kwargs)`` through the jax coordination service and every
process — coordinator included — executes the SAME registered function.
Determinism of the shared execution (same frames from the same source, same
seeds, coordinator-chosen DKV keys carried in the command) is what keeps the
ranks' collective sequences aligned, exactly as H2O relies on every node
running the same jar.

v1 scope: Parse, model build, predict — the end-to-end REST training path.
Frame mutations via Rapids and grid/AutoML builds are coordinator-local and
raise on a multi-process cloud (documented limitation; both reduce to these
primitives and widen the same way).

The broadcast payload is length-prefixed and padded to a power of two so the
number of distinct broadcast programs stays O(log max_payload).

Failure detection (SURVEY §5.3): the jax coordination service's heartbeat
IS the ``HeartBeatThread`` successor — a dead rank is detected by the
service, which poisons every other rank's next collective with a fatal
``PollForError`` (observed in the multihost test logs when a rank is
killed). The cloud is fail-stop on member death, exactly H2O's semantics
("a dead member makes the cluster unusable; restart is the recovery path");
durability comes from model checkpoints, not elasticity.
"""

from __future__ import annotations

import pickle
import threading

import numpy as np

from h2o3_tpu.utils.log import Log

_LOCK = threading.RLock()  # serializes the coordinator's device-work commands
# process-global (not thread-local): builders spawn nested Job threads that
# must inherit the flag; replicated execution is serialized by _LOCK anyway
_REPLICATED = 0


def in_replicated() -> bool:
    """True while executing a replicated command (every rank in lockstep) —
    the only context where cross-process collectives are safe."""
    return _REPLICATED > 0


import contextlib


@contextlib.contextmanager
def replicated_section():
    """Mark a region as replicated execution for library users driving their
    own multi-controller SPMD scripts (every rank must enter it together)."""
    global _REPLICATED
    _REPLICATED += 1
    try:
        yield
    finally:
        _REPLICATED -= 1


def multi_process() -> bool:
    import jax

    return jax.process_count() > 1


def is_coordinator() -> bool:
    import jax

    return jax.process_index() == 0


def _bcast_bytes(payload: bytes | None) -> bytes:
    """Broadcast a byte string from process 0 to all (collective: every
    process must call this — followers pass ``None``)."""
    from jax.experimental import multihost_utils as mh

    n = len(payload) if payload is not None else 0
    n_arr = mh.broadcast_one_to_all(np.array([n], np.int32))
    n = int(n_arr[0])
    cap = 1 << max(10, (n - 1).bit_length())  # pow2 pad bounds compile count
    buf = np.zeros(cap, np.uint8)
    if payload is not None:
        buf[: len(payload)] = np.frombuffer(payload, np.uint8)
    data = mh.broadcast_one_to_all(buf)
    return bytes(np.asarray(data[:n], np.uint8))


# -- command registry --------------------------------------------------------


def _exec_parse(setup: dict, dest: str):
    from h2o3_tpu.frame.parse import parse, parse_sharded

    if setup.pop("sharded", False):
        return parse_sharded(setup, destination_frame=dest)
    return parse(setup, destination_frame=dest)


def _exec_build(algo: str, kwargs: dict, x, y, train, valid, dest: str):
    from h2o3_tpu.api.server import _builder_cls
    from h2o3_tpu.cluster.registry import DKV

    model = _builder_cls(algo)(**kwargs).train(
        x=x, y=y, training_frame=train, validation_frame=valid
    )
    # every rank re-keys to the coordinator-chosen key so later commands
    # (predict, fetch) reference the same object on all ranks
    DKV.remove(model.key)
    model.key = dest
    DKV.put(dest, model)
    return model


def _exec_predict(model_key: str, frame_key: str, dest: str):
    from h2o3_tpu.cluster.registry import DKV

    model = DKV.get(model_key)
    fr = DKV.get(frame_key)
    out = model.predict(fr)
    DKV.put(dest, out)
    return out


_COMMANDS = {
    "parse": _exec_parse,
    "build": _exec_build,
    "predict": _exec_predict,
}

_SHUTDOWN = "__shutdown__"


def run(cmd: str, **kwargs):
    """Execute ``cmd`` on every process of the cloud (coordinator API).

    Single-process clouds execute directly; multi-process clouds broadcast
    first so followers enter the same program. Holding the lock for the whole
    execution serializes device work — collective order must match on every
    rank, and concurrent jobs on the coordinator would interleave it."""
    if not multi_process():
        return _COMMANDS[cmd](**kwargs)
    if not is_coordinator():  # pragma: no cover - followers use follower_loop
        raise RuntimeError("spmd.run is coordinator-only")
    with _LOCK:
        _bcast_bytes(pickle.dumps((cmd, kwargs)))
        global _REPLICATED
        _REPLICATED += 1
        try:
            return _COMMANDS[cmd](**kwargs)
        finally:
            _REPLICATED -= 1


def shutdown_followers() -> None:
    if multi_process() and is_coordinator():
        with _LOCK:
            _bcast_bytes(pickle.dumps((_SHUTDOWN, {})))


def follower_loop() -> None:
    """Run on every non-coordinator process: execute the coordinator's
    command stream until shutdown.

    Deterministic command failures (bad path, bad params) raise IDENTICALLY
    on every rank — the coordinator's Job catches its copy, so the follower
    must survive too or one bad request would wedge the whole cloud. The
    exception is logged and the loop continues; genuinely divergent state
    (one rank fails mid-collective) surfaces as a collective mismatch and
    remains fail-stop."""
    Log.info(f"spmd follower loop up (process {__import__('jax').process_index()})")
    global _REPLICATED
    while True:
        cmd, kwargs = pickle.loads(_bcast_bytes(None))
        if cmd == _SHUTDOWN:
            Log.info("spmd follower shutdown")
            return
        Log.info(f"spmd follower executing {cmd}")
        _REPLICATED += 1
        try:
            _COMMANDS[cmd](**kwargs)
        except Exception:
            import traceback

            Log.err(
                "spmd follower command failed (coordinator job fails with "
                f"the same error):\n{traceback.format_exc()}"
            )
        finally:
            _REPLICATED -= 1
