from h2o3_tpu.cluster.registry import DKV
from h2o3_tpu.cluster.job import Job

__all__ = ["DKV", "Job"]
