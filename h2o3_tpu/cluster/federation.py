"""Pod-federated metrics — merge every rank's registry into one view
(the ISSUE-18 tentpole, piece c).

Each process keeps its own :data:`metrics.REGISTRY` (like H2O's per-node
logs), so on a multi-host pod `GET /3/Metrics` only ever showed the
coordinator's counters — a follower's dispatch seconds, HBM ledger and
collective bytes were invisible unless you could shell into the rank.
This module gathers per-rank snapshots with the same collective machinery
every other cross-rank exchange uses (length-prefix + pow2-padded
``process_allgather``, bounding the number of distinct gather programs at
O(log max_payload)) and merges them:

- **counters** sum across ranks per label-set (pod-total work);
- **histograms** merge bucket-by-bucket (cumulative counts, sums and
  counts all add — sum of cumulative prefixes is the cumulative prefix of
  the sum);
- **gauges** keep per-rank series under an added ``rank`` label (summing
  a gauge like ``hbm_owned_bytes`` across ranks would fabricate a device
  no rank has).

The gather is a collective: every rank must enter it in lockstep, so the
REST path dispatches it as the replicated ``metrics_pod`` spmd command
(single-process clouds merge the local snapshot directly as rank 0 — same
shape out, no collective, no command-lock wait).
"""

from __future__ import annotations

import json

from h2o3_tpu.utils import metrics as _mx


def _gather_bytes(payload: bytes) -> list[bytes]:
    """Allgather one byte string per rank (collective: every process must
    call this together). Returns the payloads in rank order."""
    import jax
    import numpy as np
    from jax.experimental import multihost_utils as mh

    n = len(payload)
    lens = np.asarray(mh.process_allgather(np.array([n], np.int32)))
    lens = lens.reshape(-1)
    cap = 1 << max(10, (int(lens.max()) - 1).bit_length())
    buf = np.zeros(cap, np.uint8)
    buf[:n] = np.frombuffer(payload, np.uint8)
    data = np.asarray(mh.process_allgather(buf)).reshape(
        jax.process_count(), cap)
    return [bytes(data[r, : int(lens[r])]) for r in range(data.shape[0])]


def merge(snaps: dict[int, dict]) -> dict:
    """Merge per-rank ``REGISTRY.snapshot()`` dicts into one snapshot-shaped
    dict (render with :func:`metrics.render_snapshot` or serve as JSON).

    ``snaps`` maps rank → snapshot. Counters/untyped sum per label-set,
    histograms merge buckets/sum/count per label-set, gauges gain a
    ``rank`` label so each rank's series survives side by side."""
    out: dict = {}
    agg_by_name: dict[str, dict] = {}
    for rank in sorted(snaps):
        for name, fam in snaps[rank].items():
            kind = fam.get("type", "untyped")
            if name not in out:
                out[name] = {"type": kind, "help": fam.get("help", ""),
                             "values": []}
                agg_by_name[name] = {}
            agg = agg_by_name[name]
            for val in fam.get("values", ()):
                labels = dict(val.get("labels", {}))
                if kind == "gauge":
                    labels["rank"] = str(rank)
                key = tuple(sorted(labels.items()))
                cur = agg.get(key)
                if "buckets" in val:
                    if cur is None:
                        agg[key] = {"labels": labels,
                                    "buckets": dict(val["buckets"]),
                                    "sum": float(val["sum"]),
                                    "count": int(val["count"])}
                    else:
                        for le, c in val["buckets"].items():
                            cur["buckets"][le] = cur["buckets"].get(le, 0) + c
                        cur["sum"] += float(val["sum"])
                        cur["count"] += int(val["count"])
                elif cur is None:
                    agg[key] = {"labels": labels,
                                "value": float(val["value"])}
                else:
                    cur["value"] += float(val["value"])
    for name, fam in out.items():
        agg = agg_by_name[name]
        fam["values"] = [agg[k] for k in sorted(agg)]
    return out


def pod_snapshot() -> dict:
    """Merged pod-wide snapshot. COLLECTIVE on multi-process clouds — every
    rank must call this in lockstep, which is why the REST layer reaches it
    through ``spmd.run("metrics_pod")``. Single-process: merges the local
    snapshot as rank 0 directly (same output shape, no collective)."""
    from h2o3_tpu.cluster import spmd

    local = _mx.REGISTRY.snapshot()
    if not spmd.multi_process():
        return merge({0: local})
    payloads = _gather_bytes(json.dumps(local).encode())
    return merge({r: json.loads(p.decode()) for r, p in enumerate(payloads)})
