"""Multihost pod runtime — the ``h2odriver``/``h2o-k8s`` bootstrap proper
(ISSUE 14 tentpole; SURVEY.md §2.3 launchers row).

``cluster/cloud.py`` owns the low-level ``jax.distributed.initialize`` call;
this module is the POD-SHAPED layer above it:

- :func:`pod_env` resolves the bootstrap triple (coordinator address,
  process count, process id) from environment knobs the k8s StatefulSet
  sets (``H2O3_TPU_COORDINATOR`` / ``H2O3_TPU_NUM_PROCESSES`` /
  ``H2O3_TPU_PROCESS_ID``), deriving the rank from the trailing pod
  ordinal (``pod-name-N``, the StatefulSet convention) when no explicit id
  is given — so the SAME container command works on every replica.
- :func:`bootstrap` runs env/args → ``cloud.init`` (distributed init,
  2-D mesh formation per ``H2O3_TPU_MESH_ROWS``) → :func:`formation`: a
  cross-process barrier plus per-host device enumeration — the
  ``water.Paxos`` cloud-lock analog: after it returns, every rank has
  agreed on the member list and the mesh shape, and the formation record
  lands in the flight recorder.
- :func:`probe_capability` is the runtime sibling of the PR-4 test probe:
  one bounded REAL cross-process collective, cached, so callers (and the
  two-process test fixture) can distinguish "this jaxlib refuses
  cross-process CPU collectives" from genuine cloud failures.
- :func:`install_pod_restart` closes the recovery loop on a REAL pod: the
  JAX runtime cannot re-initialize in-process, so a dead member leaves
  every surviving rank holding only the PR-10 survivor island. Under
  ``H2O3_TPU_POD_EXIT_DEGRADED=N`` a multi-process rank whose degraded
  latch persists N seconds EXITS (code 23); on k8s the restartPolicy
  brings every rank back, the cloud re-forms through this bootstrap, and
  the PR-10 supervisor resumes from the latest interval snapshot —
  ``recovery_seconds`` lands in the flight recorder and metrics
  (docs/RECOVERY.md "Pod restart").
"""

from __future__ import annotations

import os
import re
import threading
import time

from h2o3_tpu.utils.log import Log

#: exit code of the pod-restart path — distinct from crashes so operators
#: (and k8s events) can tell "deliberate restart-to-reform" from a bug
POD_RESTART_EXIT_CODE = 23


def pod_env() -> dict | None:
    """The env-driven bootstrap triple, or None when no coordinator is
    configured (single-host mode). Raises on a half-configured pod — a
    rank that silently boots single-host would hang the others at init."""
    from h2o3_tpu import config

    coordinator = config.get("H2O3_TPU_COORDINATOR").strip()
    if not coordinator:
        return None
    num = config.get_int("H2O3_TPU_NUM_PROCESSES")
    if num <= 0:
        raise ValueError(
            "H2O3_TPU_COORDINATOR is set but H2O3_TPU_NUM_PROCESSES is not "
            "— set it to the StatefulSet replica count")
    pid_raw = config.get("H2O3_TPU_PROCESS_ID").strip()
    if pid_raw:
        pid = int(pid_raw)
    else:
        pid = _ordinal_from_pod_name()
        if pid is None:
            raise ValueError(
                "H2O3_TPU_PROCESS_ID is unset and no trailing ordinal was "
                "found in H2O3_TPU_POD_NAME/POD_NAME/HOSTNAME — set one "
                "(the k8s StatefulSet convention is pod-name-N)")
    if not 0 <= pid < num:
        # elastic scale-down (ISSUE 17): when the formation manifest shows
        # this ordinal WAS a member of a previously larger formation, the
        # replica count shrank underneath a restart — the rank is RETIRED,
        # not misconfigured. Exit cleanly instead of crash-looping on a
        # ValueError the pod supervisor would restart forever.
        prev = read_manifest()
        if prev and pid < int(prev.get("processes", 0)):
            Log.warn(
                f"pod rank {pid} retired: formation scaled down from "
                f"{prev.get('processes')} to {num} process(es) "
                "(elastic transition) — exiting cleanly; the surviving "
                "ranks re-form and resume from the interval snapshots")
            from h2o3_tpu.utils import flightrec

            flightrec.record(
                "elastic_retired", rank=pid,
                prev_processes=int(prev.get("processes", 0)), processes=num)
            raise SystemExit(0)
        raise ValueError(
            f"process id {pid} out of range for {num} processes")
    return {"coordinator": coordinator, "num_processes": num,
            "process_id": pid}


def _ordinal_from_pod_name() -> int | None:
    """Trailing integer of the pod/host name — the StatefulSet ordinal."""
    for var in ("H2O3_TPU_POD_NAME", "POD_NAME", "HOSTNAME"):
        name = os.environ.get(var, "")
        m = re.search(r"-(\d+)$", name.strip())
        if m:
            return int(m.group(1))
    return None


# ---------------------------------------------------------------------------
# capability probe (the PR-4 auto-skip probe, runtime form)

_CAPABILITY: str | None = None  # None = not probed; "" = capable


def probe_capability(timeout: float = 30.0) -> str:
    """'' when this cloud can run REAL cross-process collectives; else the
    root-cause string (the auto-skip reason the tests surface). Single-
    process clouds are trivially capable. The probe is ONE bounded
    broadcast (every rank must call this at the same point — it is a
    collective) and the verdict is cached for the process lifetime."""
    global _CAPABILITY
    if _CAPABILITY is not None:
        return _CAPABILITY
    import jax

    if jax.process_count() <= 1:
        _CAPABILITY = ""
        return _CAPABILITY
    import numpy as np

    out: dict = {}

    def attempt():
        try:
            from jax.experimental import multihost_utils as mh

            got = mh.broadcast_one_to_all(np.array([7], np.int32))
            out["ok"] = int(np.asarray(got)[0]) == 7
        except Exception as e:  # noqa: BLE001 — the reason IS the result
            out["err"] = f"{type(e).__name__}: {e}"

    t = threading.Thread(target=attempt, daemon=True)
    t.start()
    t.join(timeout)
    if t.is_alive():
        _CAPABILITY = (f"cross-process collective probe timed out after "
                       f"{timeout:.0f}s")
    elif out.get("ok"):
        _CAPABILITY = ""
    else:
        _CAPABILITY = out.get(
            "err", "cross-process collective returned a wrong value")
    if _CAPABILITY:
        Log.warn(f"multihost capability probe: {_CAPABILITY}")
    return _CAPABILITY


# ---------------------------------------------------------------------------
# formation manifest (ISSUE 17, elastic recovery): the durable record of the
# last AGREED formation — member count + mesh shape. A restarted rank reads
# it before re-bootstrapping: a changed H2O3_TPU_NUM_PROCESSES is an ELASTIC
# TRANSITION (spot preemption shrank the pod; the autoscaler grew it), not an
# error — the rank boots into the NEW shape and the resumed job re-plans
# rows×cols from the surviving host set instead of barriering against the
# old count forever.


def _manifest_path() -> str | None:
    """Resolved H2O3_TPU_FORMATION_MANIFEST path, or None when disabled."""
    from h2o3_tpu import config

    v = config.get("H2O3_TPU_FORMATION_MANIFEST").strip()
    if v in ("0", "false", "off"):
        return None
    if v:
        return v
    import tempfile

    uid = getattr(os, "getuid", lambda: 0)()
    return os.path.join(tempfile.gettempdir(),
                        f"h2o3tpu_formation_{uid}.json")


def read_manifest() -> dict | None:
    """The last published formation record, or None (missing/disabled/
    torn — a torn manifest means no opinion, never a crash)."""
    path = _manifest_path()
    if not path:
        return None
    import json

    try:
        with open(path, encoding="utf-8") as f:
            rec = json.load(f)
        return rec if isinstance(rec, dict) else None
    except (OSError, ValueError):
        return None


def write_manifest(rec: dict) -> None:
    """Atomically publish the formation record (persist's temp+rename, so a
    crash mid-write never leaves a torn manifest for the next boot)."""
    path = _manifest_path()
    if not path:
        return
    import json

    from h2o3_tpu import persist

    try:
        persist.write_bytes(
            json.dumps(rec, sort_keys=True).encode("utf-8"), path)
    except Exception as e:  # noqa: BLE001 — the manifest is advisory
        Log.warn(f"formation manifest write failed ({e!r}); elastic "
                 "transitions will not be detected on the next restart")


def formation(barrier: bool = True) -> dict:
    """Cloud-formation record: barrier + per-host device enumeration.

    The barrier is the Paxos cloud-lock analog — after it, every rank has
    initialized its backend and agreed on membership (a rank that died
    during init fails the barrier instead of wedging the first real
    collective). The returned record (also pushed into the flight
    recorder) is what ``/3/Cloud`` cannot show: which DEVICES live on
    which HOST, and how the mesh factors over them."""
    import jax

    from h2o3_tpu.parallel import mesh as _mesh

    if barrier and jax.process_count() > 1 and not probe_capability():
        from jax.experimental import multihost_utils as mh

        mh.sync_global_devices("h2o3_tpu_formation")
    m = _mesh.get_mesh()
    hosts: dict[int, list] = {}
    for d in jax.devices():
        hosts.setdefault(int(d.process_index), []).append(int(d.id))
    rec = {
        "processes": int(jax.process_count()),
        "process_index": int(jax.process_index()),
        "local_devices": int(jax.local_device_count()),
        "devices": int(jax.device_count()),
        "platform": jax.devices()[0].platform,
        "mesh": dict(m.shape),
        "mesh_2d": _mesh.is_2d(m),
        "hosts": {str(k): sorted(v) for k, v in sorted(hosts.items())},
    }
    from h2o3_tpu.utils import flightrec

    flightrec.record(
        "formation", processes=rec["processes"],
        devices=rec["devices"], mesh=str(rec["mesh"]))
    # elastic transition detection (ISSUE 17): a previous manifest recording
    # a DIFFERENT member count or mesh shape means the topology changed
    # across a restart — record it loudly (the runbook's signal that resumed
    # jobs will re-plan rows×cols), then publish the new formation
    prev = read_manifest()
    if prev and (int(prev.get("processes", 0)) != rec["processes"]
                 or prev.get("mesh") != rec["mesh"]):
        Log.warn(
            f"elastic transition: formation changed from "
            f"{prev.get('processes')} process(es) mesh {prev.get('mesh')} "
            f"to {rec['processes']} process(es) mesh {rec['mesh']} — "
            "resumed jobs re-plan onto the new shape")
        flightrec.record(
            "elastic_transition",
            prev_processes=int(prev.get("processes", 0)),
            processes=rec["processes"],
            prev_mesh=str(prev.get("mesh")), mesh=str(rec["mesh"]))
    write_manifest(dict(rec, stamp=time.strftime(
        "%Y%m%dT%H%M%SZ", time.gmtime())))
    return rec


def bootstrap(coordinator: str | None = None, num_processes: int | None = None,
              process_id: int | None = None,
              log_level: str | None = None) -> dict:
    """env/args → ``jax.distributed`` init → barrier → formation record.

    Explicit args win; anything left None fills from :func:`pod_env`.
    Single-host (no coordinator anywhere) still boots a cloud — the
    degenerate 1-process pod — so one entrypoint serves laptops and pods."""
    env = pod_env() or {}
    coordinator = coordinator if coordinator is not None else env.get(
        "coordinator")
    if num_processes is None:
        num_processes = env.get("num_processes")
    if process_id is None:
        process_id = env.get("process_id")
    from h2o3_tpu.cluster import cloud

    cloud.init(
        coordinator=coordinator,
        num_processes=num_processes,
        process_id=process_id,
        log_level=log_level,
    )
    rec = formation()
    Log.info(
        f"pod formation: process {rec['process_index']}/{rec['processes']}, "
        f"{rec['devices']} device(s) over {len(rec['hosts'])} host(s), "
        f"mesh {rec['mesh']}")
    return rec


def bootstrap_from_env(log_level: str | None = None) -> dict | None:
    """The k8s entrypoint half of :func:`bootstrap`: None (do nothing) when
    no H2O3_TPU_COORDINATOR is configured, else the formation record."""
    if pod_env() is None:
        return None
    return bootstrap(log_level=log_level)


# ---------------------------------------------------------------------------
# pod-restart recovery loop

_EXIT_WATCHER: threading.Thread | None = None
_EXIT_STOP = threading.Event()


def _exit_grace() -> float:
    from h2o3_tpu import config

    return config.get_float("H2O3_TPU_POD_EXIT_DEGRADED")


def _exit_watch_loop(poll: float) -> None:
    import jax

    from h2o3_tpu.cluster import cloud

    latched_at: float | None = None
    while not _EXIT_STOP.wait(poll):
        grace = _exit_grace()
        if grace <= 0 or jax.process_count() <= 1:
            latched_at = None
            continue
        if cloud.degraded_reason() is None:
            latched_at = None  # recovered in-process (operator / supervisor)
            continue
        now = time.monotonic()
        if latched_at is None:
            latched_at = now
            continue
        if now - latched_at < grace:
            continue
        # the evidence is already frozen (mark_degraded captured an
        # incident bundle); flush checkpoints via the normal interval
        # machinery — they are already on durable storage — and restart
        Log.err(
            f"pod restart: degraded latch held {now - latched_at:.1f}s on a "
            f"{jax.process_count()}-process cloud (reason: "
            f"{cloud.degraded_reason()}); exiting with code "
            f"{POD_RESTART_EXIT_CODE} so the pod supervisor re-forms the "
            "cloud — resumable snapshots are in each job's "
            "export_checkpoints_dir")
        from h2o3_tpu.utils import flightrec

        flightrec.record("pod_restart_exit",
                         reason=str(cloud.degraded_reason())[:200])
        os._exit(POD_RESTART_EXIT_CODE)


def install_pod_restart(poll: float = 1.0) -> None:
    """Start the pod-restart watcher (idempotent daemon; no-op while
    H2O3_TPU_POD_EXIT_DEGRADED is 0 or the cloud is single-process).
    launch.py installs it on every rank of a multi-process pod."""
    global _EXIT_WATCHER
    if _EXIT_WATCHER is not None and _EXIT_WATCHER.is_alive():
        return
    _EXIT_STOP.clear()
    _EXIT_WATCHER = threading.Thread(
        target=_exit_watch_loop, args=(poll,), name="h2o3-pod-restart",
        daemon=True)
    _EXIT_WATCHER.start()


def uninstall_pod_restart() -> None:
    global _EXIT_WATCHER
    _EXIT_STOP.set()
    if _EXIT_WATCHER is not None:
        _EXIT_WATCHER.join(timeout=5)
    _EXIT_WATCHER = None
