"""Async job control — successor of ``water.Job`` [UNVERIFIED upstream path].

H2O's ``Job<T>`` is cancellable async work with 0..1 progress polled over
REST (SURVEY.md §2.1). Device compute here is synchronous XLA programs, so a
Job wraps the *host-side driver loop* (tree iterations, IRLS iterations,
AutoML steps) in a thread; cancellation stays cooperative, checked between
iterations — the same granularity H2O uses (between tree levels).
"""

from __future__ import annotations

import contextvars
import threading
import time
import traceback
from typing import Any, Callable

from h2o3_tpu.cluster.registry import DKV
from h2o3_tpu.utils import metrics
from h2o3_tpu.utils.log import Log

_JOBS_TOTAL = metrics.counter(
    "jobs_total", "jobs finished, by terminal status")
_JOBS_RUNNING = metrics.gauge("jobs_running", "jobs currently executing")


class JobCancelled(Exception):
    pass


# The job executing on the CURRENT thread (via the context Job.start copies
# into its worker). Nested Jobs — model_base.train's inner build job, CV
# fold jobs, grid/AutoML per-model jobs — link to it as their parent, so
# cancellation and deadlines set on the OUTER (REST-visible) job reach the
# builder loops polling the inner one, and recovery pointers set by the
# inner job surface on the outer key the client actually polls.
_CURRENT_JOB: contextvars.ContextVar["Job | None"] = contextvars.ContextVar(
    "h2o3_current_job", default=None
)


class Job:
    PENDING, RUNNING, DONE, FAILED, CANCELLED = (
        "PENDING",
        "RUNNING",
        "DONE",
        "FAILED",
        "CANCELLED",
    )

    def __init__(self, work: Callable[["Job"], Any], description: str = "job"):
        self.key = DKV.make_key("job")
        self.description = description
        self.status = Job.PENDING
        self.progress = 0.0
        self.exception: str | None = None
        self.result: Any = None
        self.start_time: float | None = None
        self.end_time: float | None = None
        self._error: BaseException | None = None
        self._work = work
        self._cancel_requested = threading.Event()
        self._thread: threading.Thread | None = None
        # soft deadline (epoch secs): work loops poll stop_requested and
        # truncate GRACEFULLY (partial model kept) — unlike cancel(), which
        # aborts via the JobCancelled raise in update()
        self.soft_deadline: float | None = None
        # the job this one was created inside (None at top level); deadlines
        # and cancellation are read through the chain, recovery writes walk up
        self.parent: Job | None = _CURRENT_JOB.get()
        # crash-recovery state: builders with export_checkpoints_dir record
        # their latest interval snapshot here, so a FAILED job still tells
        # operators (over /3/Jobs) where to resume from (docs/RECOVERY.md)
        self.recovery: dict | None = None
        # supervised-recovery restarts survived by this job (the recovery
        # supervisor bumps it on every reform+resume; /3/Jobs surfaces it)
        self.restarts: int = 0
        DKV.put(self.key, self)

    # -- driver-side API (the work callable calls these) --
    def update(self, progress: float) -> None:
        self.progress = min(1.0, max(self.progress, float(progress)))
        j: Job | None = self
        while j is not None:
            if j._cancel_requested.is_set():
                raise JobCancelled(self.key)
            j = j.parent

    @property
    def stop_requested(self) -> bool:
        now = time.time()
        j: Job | None = self
        while j is not None:  # an ancestor's cancel/deadline stops this job too
            if j._cancel_requested.is_set():
                return True
            if j.soft_deadline is not None and now > j.soft_deadline:
                return True
            j = j.parent
        return False

    def set_recovery(self, info: dict) -> None:
        """Record the latest resumable snapshot on this job AND its
        ancestors: clients poll the OUTER (REST) job key, so the pointer
        must surface there, not only on the nested builder job. MERGES
        into the existing block: a checkpoint update after a supervised
        restart must not drop the ``incident_bundle`` pointer the
        recovery loop attached (utils/flightrec.py)."""
        j: Job | None = self
        while j is not None:
            j.recovery = {**(j.recovery or {}), **info}
            j = j.parent

    # -- client-side API --
    def start(self) -> "Job":
        import contextvars

        # nested Jobs inherit the creator's context (e.g. the spmd
        # replicated-execution flag) — threads don't do this by default
        ctx = contextvars.copy_context()

        def run() -> None:
            _CURRENT_JOB.set(self)  # nested Jobs link here as their parent
            self.status = Job.RUNNING
            self.start_time = time.time()
            _JOBS_RUNNING.inc()
            try:
                # the job key IS the trace id: every span opened inside the
                # work body lands in this job's trace tree (/3/Jobs/{k}/trace).
                # A Job nested inside a replicated command joins the OUTER
                # job's trace — the one the client is polling.
                with metrics.trace(self.key), metrics.span(
                    "job", job=self.key, description=self.description
                ):
                    self.result = self._work(self)
                self.progress = 1.0
                self.status = Job.DONE
            except JobCancelled:
                self.status = Job.CANCELLED
            except Exception as e:
                self.exception = traceback.format_exc()
                self._error = e
                self.status = Job.FAILED
                Log.err(f"Job {self.key} failed:\n{self.exception}")
            finally:
                self.end_time = time.time()
                _JOBS_RUNNING.dec()
                _JOBS_TOTAL.inc(status=self.status)

        self._thread = threading.Thread(
            target=lambda: ctx.run(run), name=self.key, daemon=True
        )
        self._thread.start()
        return self

    def cancel(self) -> None:
        self._cancel_requested.set()

    def join(self, timeout: float | None = None) -> Any:
        if self._thread is not None:
            self._thread.join(timeout)
            if self._thread.is_alive():
                # still running: a silent partial/None return here let
                # callers mistake "not done yet" for "done with no result"
                raise TimeoutError(
                    f"Job {self.key} still running after {timeout}s "
                    f"(progress {self.progress:.0%}) — poll again or cancel()"
                )
        if self.status == Job.FAILED:
            from h2o3_tpu.utils import faults

            if isinstance(self._error, faults.TrainAbort):
                # simulated process death must keep its identity: the grid/
                # AutoML drivers re-raise it instead of logging a combo
                # failure (a real kill -9 gives them no chance either)
                raise self._error
            raise RuntimeError(f"Job {self.key} failed:\n{self.exception}")
        if self.status == Job.CANCELLED:
            raise JobCancelled(self.key)
        return self.result

    def wait(self, timeout: float | None = None) -> bool:
        """Wait (bounded) for the job to reach a terminal state WITHOUT
        raising on failure/cancel — the drain path's primitive: it only
        needs to know whether the worker thread is done flushing, not
        whether the job succeeded. Returns True when terminal."""
        if self._thread is not None:
            self._thread.join(timeout)
        return self.status not in (Job.PENDING, Job.RUNNING)

    def run_sync(self) -> Any:
        """Run inline on the calling thread (used by tests and local API)."""
        self.start()
        return self.join()

    @property
    def duration_ms(self) -> int | None:
        """Elapsed ms: live for a RUNNING job, frozen at end_time once the
        job reaches a terminal state (stable across polls)."""
        if self.start_time is None:
            return None
        end = self.end_time if self.end_time is not None else time.time()
        return int((end - self.start_time) * 1000)

    def to_dict(self) -> dict:
        from h2o3_tpu.utils import jobacct

        ledger = jobacct.snapshot(self.key)
        return {
            "key": self.key,
            "description": self.description,
            "status": self.status,
            "progress": self.progress,
            "exception": self.exception,
            "start_time": self.start_time,
            "end_time": self.end_time,
            "started_at": self.start_time,
            "duration_ms": self.duration_ms,
            "span_summary": metrics.trace_summary(self.key),
            # the per-job resource ledger (utils/jobacct.py): device-seconds,
            # dispatch counts, collective/window bytes attributed to THIS
            # job's trace — the budget signal the fleet scheduler reads
            **({"ledger": ledger} if ledger else {}),
            **({"recovery": self.recovery} if self.recovery else {}),
            **({"restarts": self.restarts} if self.restarts else {}),
        }
