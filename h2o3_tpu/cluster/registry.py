"""Object registry — successor of H2O's DKV (``water.DKV`` / ``water.Key`` /
``water.Lockable`` [UNVERIFIED upstream paths, SURVEY.md §0]).

H2O's DKV is a cluster-wide hash map with consistent-hash home nodes and
cache invalidation, because model/frame state lives scattered across JVM
heaps. In the TPU rebuild the *data plane* (columns) already lives in device
HBM as sharded ``jax.Array``s managed by the JAX runtime; only the *control
plane* needs a key→object map, and a coordinator-side dict with RW locks is
the idiomatic replacement. Keys keep H2O's string-key surface so the REST
layer and clients feel identical.
"""

from __future__ import annotations

import fnmatch
import threading
import uuid
from typing import Any, Iterable


class _RWLock:
    """Reader-writer lock — successor of ``water.Lockable`` semantics."""

    def __init__(self) -> None:
        self._cond = threading.Condition()
        self._readers = 0
        self._writer = False

    def acquire_read(self) -> None:
        with self._cond:
            while self._writer:
                self._cond.wait()
            self._readers += 1

    def release_read(self) -> None:
        with self._cond:
            self._readers -= 1
            if self._readers == 0:
                self._cond.notify_all()

    def acquire_write(self) -> None:
        with self._cond:
            while self._writer or self._readers:
                self._cond.wait()
            self._writer = True

    def release_write(self) -> None:
        with self._cond:
            self._writer = False
            self._cond.notify_all()


class DKV:
    """Process-wide key→value store for Frames, Models, Jobs, Grids."""

    _store: dict[str, Any] = {}
    _locks: dict[str, _RWLock] = {}
    _mutex = threading.Lock()

    @classmethod
    def make_key(cls, prefix: str = "obj") -> str:
        # Inside replicated SPMD execution every rank runs the same code in
        # the same (serialized) order, so a counter yields IDENTICAL keys on
        # every rank — which is what lets whole grids/AutoML runs replicate
        # without carrying each model key in the command (cluster/spmd.py).
        # _IS_MULTI is a plain module bool set once at cloud init: no jax
        # import (or exception swallowing) on this hot path.
        from h2o3_tpu.cluster import spmd

        if spmd._IS_MULTI and spmd.in_replicated():
            with cls._mutex:
                cls._replicated_seq = getattr(cls, "_replicated_seq", 0) + 1
                return f"{prefix}_r{cls._replicated_seq:08d}"
        return f"{prefix}_{uuid.uuid4().hex[:12]}"

    @classmethod
    def put(cls, key: str, value: Any) -> str:
        with cls._mutex:
            cls._store[key] = value
            cls._locks.setdefault(key, _RWLock())
        return key

    @classmethod
    def get(cls, key: str, default: Any = None) -> Any:
        with cls._mutex:
            return cls._store.get(key, default)

    @classmethod
    def remove(cls, key: str) -> None:
        with cls._mutex:
            cls._store.pop(key, None)
            cls._locks.pop(key, None)

    @classmethod
    def remove_all(cls) -> None:
        with cls._mutex:
            cls._store.clear()
            cls._locks.clear()

    @classmethod
    def keys(cls, pattern: str = "*") -> list[str]:
        with cls._mutex:
            return sorted(k for k in cls._store if fnmatch.fnmatch(k, pattern))

    @classmethod
    def values_of_type(cls, typ: type) -> Iterable[Any]:
        with cls._mutex:
            return [v for v in cls._store.values() if isinstance(v, typ)]

    @classmethod
    def lock(cls, key: str) -> _RWLock:
        with cls._mutex:
            return cls._locks.setdefault(key, _RWLock())


# --- convenience surface mirrored into the top-level package (h2o.ls etc.) ---

def get_frame(key: str):
    from h2o3_tpu.frame.frame import Frame

    v = DKV.get(key)
    return v if isinstance(v, Frame) else None


def get_model(key: str):
    try:
        from h2o3_tpu.models.model_base import Model
    except ImportError:  # models package not built yet
        return None
    v = DKV.get(key)
    return v if isinstance(v, Model) else None


def ls() -> list[str]:
    return DKV.keys()


def remove(key: str) -> None:
    DKV.remove(key)


def remove_all() -> None:
    DKV.remove_all()
