"""Cloud lifecycle — successor of ``water.H2O`` main / ``water.Paxos`` cloud
formation / ``HeartBeatThread`` [UNVERIFIED upstream paths, SURVEY.md §0].

H2O boots a JVM per node, gossips membership, and locks the cloud at the
first job. The TPU-native cloud is the JAX runtime itself:

- single host: ``init()`` just builds the device mesh;
- multi-host: ``init(coordinator=...)`` calls ``jax.distributed.initialize``
  — the JAX coordination service replaces Paxos + heartbeats (it performs
  liveness detection and fail-stop, matching H2O's no-elastic-recovery
  semantics, SURVEY.md §5.3).

``cluster_info()`` is the ``GET /3/Cloud`` analog.
"""

from __future__ import annotations

import os
import time

import jax

from h2o3_tpu.parallel import mesh as _mesh
from h2o3_tpu.utils import metrics
from h2o3_tpu.utils.log import Log

_started_at: float | None = None


def _distributed_initialized() -> bool:
    """jax-compat: ``jax.distributed.is_initialized`` only exists on newer
    jax; older releases expose the same fact through ``global_state.client``.
    This container's jax has the latter shape — without the probe, every
    multi-host ``init`` dies on AttributeError before forming the cloud."""
    is_init = getattr(jax.distributed, "is_initialized", None)
    if is_init is not None:
        try:
            return bool(is_init())
        except Exception:  # noqa: BLE001 — treat a broken probe as "not yet"
            return False
    state = getattr(jax.distributed, "global_state", None)
    return bool(getattr(state, "client", None))

# cluster health as gauges: a scraper sees the degraded latch / probe
# failures without polling /3/Cloud JSON, and the transition counter
# preserves flap history a point-in-time gauge cannot show
_G_DEGRADED = metrics.gauge(
    "cloud_degraded", "1 while the fail-stop degraded latch is set")
_G_HEALTHY = metrics.gauge(
    "cloud_healthy", "1 while every probed local device passes health checks")
_G_GENERATION = metrics.gauge(
    "cloud_generation",
    "cloud formation epoch: starts at 0 and ticks on every supervised "
    "recover() reform (cluster/recovery.py). Replicated spmd commands are "
    "stamped with the generation they entered under and fail-stop if the "
    "cloud re-formed while they waited — a retried collective can never "
    "interleave with a wedged predecessor")
_C_TRANSITIONS = metrics.counter(
    "cloud_health_transitions_total", "health state changes, by target state")
_C_CACHE_HITS = metrics.counter(
    "compile_cache_hits_total",
    "persistent XLA compilation-cache hits (jax monitoring event "
    "'/jax/compilation_cache/cache_hits') — a warm scoring replica or a "
    "same-shape-bucket rebuild should count only hits here and compile "
    "zero new programs")

_CACHE_LISTENER_INSTALLED = False


def _install_cache_hit_listener() -> None:
    """Bridge jax's compilation-cache monitoring events into the registry
    so operators can watch cross-process cache effectiveness (replica
    cold-start, AutoML same-bucket rebuilds) from /3/Metrics. Best-effort:
    the monitoring module is jax-internal and absent on some versions."""
    global _CACHE_LISTENER_INSTALLED
    if _CACHE_LISTENER_INSTALLED:
        return
    try:
        from jax._src import monitoring as _mon

        def _on_event(event, **kw):
            if "compilation_cache" in event and "cache_hits" in event:
                _C_CACHE_HITS.inc()

        _mon.register_event_listener(_on_event)
        _CACHE_LISTENER_INSTALLED = True
    except Exception as e:  # noqa: BLE001 — telemetry only, never fatal
        Log.debug(f"compile-cache hit listener unavailable: {e!r}")


def init(
    coordinator: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    mesh=None,
    log_level: str | None = None,
) -> dict:
    """Bring up (or attach to) the cloud and build the row mesh.

    Mirrors ``h2o.init()``: idempotent, returns cluster status. For
    multi-host pods pass the coordinator address (maps to
    ``jax.distributed.initialize``, the Paxos/flatfile successor).
    ``log_level`` defaults from the H2O3_TPU_LOG_LEVEL knob (config.py).
    """
    global _started_at
    from h2o3_tpu import config

    Log.set_level(log_level or config.get("H2O3_TPU_LOG_LEVEL"))
    # Honor an explicit JAX_PLATFORMS=cpu env even when a site hook has
    # already overridden the jax_platforms CONFIG (observed: the axon
    # sitecustomize forces "axon,cpu", after which the env var alone is
    # ignored and any backend touch tries to init the tunnel backend —
    # which HANGS, not fails, when the tunnel is wedged). Must run before
    # the first jax.devices()/process_count() call below.
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu" and str(
        jax.config.jax_platforms or ""
    ).lower() != "cpu":
        jax.config.update("jax_platforms", "cpu")
    # Persistent XLA compilation cache (SURVEY.md §7: compile-latency
    # amortization across the many small jit programs of AutoML/tree loops).
    # ACCELERATOR BACKENDS ONLY: XLA:CPU cache entries are AOT-compiled with
    # the builder machine's exact CPU features; loading them on a host with
    # a different feature set is a documented SIGILL/segfault hazard (the
    # cpu_aot_loader "machine type mismatch" error), observed crashing the
    # test suite inside cache (de)serialization. CPU compiles are fast
    # enough to skip caching entirely.
    _install_cache_hit_listener()
    cache_dir = config.get("H2O3_TPU_COMPILE_CACHE")
    if not cache_dir:
        pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        cache_dir = os.path.join(pkg_root, ".jax_cache")
    try:
        # decide from the DECLARED platform, not jax.default_backend() —
        # touching the backend here would break the later
        # jax.distributed.initialize() (must run before any backend init).
        # Only an explicit cpu declaration disables the cache (auto-detected
        # accelerators keep it; our test/driver cpu runs always declare).
        plat = (os.environ.get("JAX_PLATFORMS") or str(
            jax.config.jax_platforms or "")).lower()
        if plat == "cpu":
            Log.debug("compile cache skipped on XLA:CPU (AOT feature-"
                      "mismatch SIGILL hazard)")
        else:
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception as e:  # cache is an optimization, never fatal — but say so
        Log.warn(f"compilation cache disabled: {e}")
    if coordinator is not None and not _distributed_initialized():
        # Must run before any backend use (jax.devices() etc.).
        # heartbeat_timeout bounds dead-member detection (SURVEY §5.3): the
        # coordination service's heartbeat IS the HeartBeatThread successor;
        # jax's default 100 s is tunable down for tests/latency-sensitive ops
        import inspect

        kw = {}
        if "heartbeat_timeout_seconds" in inspect.signature(
            jax.distributed.initialize
        ).parameters:  # older jax has no tunable heartbeat — default applies
            kw["heartbeat_timeout_seconds"] = config.get_int(
                "H2O3_TPU_HEARTBEAT_TIMEOUT"
            )
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id,
            **kw,
        )
    from h2o3_tpu.utils import telemetry

    telemetry.install()
    from h2o3_tpu.cluster import spmd

    spmd.mark_multi_process(jax.process_count() > 1)  # hot-path flag (DKV keys)
    if mesh is not None:
        _mesh.set_mesh(mesh)
    m = _mesh.get_mesh()
    if _started_at is None:
        _started_at = time.time()
        Log.info(
            f"h2o3_tpu cloud up: {len(jax.devices())} device(s) "
            f"({jax.devices()[0].platform}), {jax.process_count()} process(es), "
            f"mesh axes {dict(m.shape)}"
        )
    return cluster_info()


_degraded: str | None = None
_generation = 0


def generation() -> int:
    """Current cloud formation epoch (see the ``cloud_generation`` gauge).
    Moves ONLY through :func:`recover` — ``clear_degraded`` (the manual
    escape hatch) leaves it alone, so a cloud that never reforms keeps
    generation 0 forever and the spmd generation fence stays inert."""
    return _generation


def adopt_generation(gen: int) -> None:
    """Fast-forward this rank's generation to a NEWER one observed on the
    replicated command stream (a follower learning the coordinator's
    reform). Never moves backwards — the fence against pre-reform commands
    stays intact."""
    global _generation
    if gen > _generation:
        Log.warn(f"cloud generation adopted from command stream: "
                 f"{_generation} -> {gen}")
        _generation = gen
        _G_GENERATION.set(_generation)


def mark_degraded(reason: str) -> None:
    """Latch the cloud unhealthy (fail-stop semantics, SURVEY §5.3): called
    when a replicated command dies with a coordination-service failure
    signature — a dead member makes the cloud unusable; restart is the
    recovery path, durability comes from checkpoints. `/3/Cloud` surfaces it.

    The latch instant is when the flight-recorder ring still holds the
    dying dispatch, so the incident bundle captures HERE — before any
    supervisor reform/retry (or operator restart) discards the evidence."""
    global _degraded
    if _degraded is None:
        _degraded = reason
        _G_DEGRADED.set(1)
        _C_TRANSITIONS.inc(to="degraded")
        Log.err(f"cloud degraded (fail-stop): {reason}")
        from h2o3_tpu.utils import flightrec

        flightrec.record("degraded", reason=str(reason)[:200],
                         generation=_generation)
        flightrec.capture_incident(reason, trigger="degraded")


def degraded_reason() -> str | None:
    return _degraded


def recover(reason: str = "") -> int:
    """The SINGLE supervised un-latch transition (degraded → recovering →
    healthy): tick the cloud generation and release the latch. Only the
    recovery supervisor (cluster/recovery.py) should call this — ticking
    the generation is what fences every command stamped under the old
    formation out of the re-formed cloud, which is the invariant that makes
    auto-restart safe. ``clear_degraded()`` remains the manual escape hatch
    (no generation tick: the operator is asserting the OLD cloud is fine).
    No-op (returns the current generation) when the latch is not set."""
    global _degraded, _generation
    if _degraded is None:
        return _generation
    _C_TRANSITIONS.inc(to="recovering")
    _generation += 1
    _G_GENERATION.set(_generation)
    Log.warn(
        f"cloud recovering (generation {_generation - 1} -> {_generation}; "
        f"was degraded: {_degraded})"
        + (f" — {reason}" if reason else "")
    )
    _degraded = None
    _G_DEGRADED.set(0)
    _C_TRANSITIONS.inc(to="healthy")
    from h2o3_tpu.utils import flightrec

    flightrec.record("generation", generation=_generation,
                     was=_generation - 1)
    return _generation


def clear_degraded() -> None:
    """Un-latch the degraded flag. The latch is one-way BY DESIGN in
    production (restart is the recovery path) — this exists for the chaos
    test suite and for an operator who has verified every rank restarted
    clean and wants the coordinator process reusable."""
    global _degraded
    if _degraded is not None:
        Log.warn(f"cloud degraded latch cleared (was: {_degraded})")
        _C_TRANSITIONS.inc(to="healthy")
    _degraded = None
    _G_DEGRADED.set(0)


def cluster_info() -> dict:
    from h2o3_tpu.utils import devmem

    m = _mesh.get_mesh()
    # per-device health (the /3/Cloud node-table analog), read through the
    # devmem ledger's rate-limited poller — the ONE memory_stats reader in
    # the process (the node table may be up to H2O3_TPU_DEVMEM_POLL_SECS
    # old; a device that errors on the probe reports unhealthy instead of
    # killing the route). Only addressable devices are probed: remote
    # hosts' devices reject memory_stats and must not mark a healthy
    # multi-host cloud unhealthy.
    nodes = []
    healthy = True
    for d in devmem.device_stats():
        node = {"id": d["id"], "platform": d["platform"],
                "process": d["process"], "healthy": d["error"] is None}
        if "in_use" in d:
            node["mem_in_use"] = d["in_use"]
        if "limit" in d:
            node["mem_limit"] = d["limit"]
        if not node["healthy"]:
            healthy = False
        nodes.append(node)
    out_degraded = degraded_reason()
    if out_degraded is not None:
        healthy = False
    _G_HEALTHY.set(1 if healthy else 0)
    return {
        "version": "h2o3_tpu",
        "cloud_healthy": healthy,
        **({"degraded": out_degraded} if out_degraded else {}),
        "generation": _generation,
        "cloud_size": len(jax.devices()),
        "processes": jax.process_count(),
        "platform": jax.devices()[0].platform,
        "mesh": dict(m.shape),
        "nodes": nodes,
        "uptime_ms": int((time.time() - _started_at) * 1e3) if _started_at else 0,
    }


def shutdown() -> None:
    """Drop all state (the process keeps running; devices are managed by JAX)."""
    from h2o3_tpu.cluster.registry import DKV
    from h2o3_tpu.cluster import spmd

    spmd.shutdown_followers()  # release any follower_loop ranks first
    DKV.remove_all()
