// Native chunked CSV parser — the ParseDataset tokenizer analog
// (SURVEY.md §2.1: upstream's parser is a native multi-chunk subsystem;
// here the chunk-parallel tokenize/coerce stage runs in C++ threads and
// Python keeps orchestration, type setup and every non-fast-path format).
//
// Scope (the FAST path; anything outside it returns an error and the
// caller falls back to the pandas reader, so behavior never diverges):
//   - single-char separator, no quoted fields (a '"' anywhere bails)
//   - columns pre-typed by the caller's sample: numeric (f64 out) or enum
//     (int32 codes + interned domain out)
//   - NA = empty field / NA / N/A / nan / NaN / null / NULL
//   - ragged rows or a numeric-parse failure bail (rc < 0) rather than
//     guess — parity with pandas' column-type flip is handled by falling
//     back, not by re-implementing it
//
// Parallel design mirrors upstream's chunk scheme: the buffer splits into
// T byte-ranges aligned to row boundaries; each thread tokenizes and
// type-coerces its range into private buffers (per-thread enum intern
// maps); a merge phase remaps thread-local enum codes onto the global
// domain (first-seen order, like upstream's categorical interning) and
// concatenates columns in row order.

#include <atomic>
#include <cctype>
#include <cerrno>
#include <cmath>
#include <charconv>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

struct ColChunk {
  std::vector<double> nums;          // numeric column slice
  std::vector<int32_t> codes;        // enum column slice (thread-local ids)
};

struct ThreadChunk {
  std::vector<ColChunk> cols;
  std::vector<std::string> local_domains;  // flattened per enum col below
  // per enum col: thread-local id -> level string
  std::vector<std::vector<std::string>> domains;
  int64_t rows = 0;
  int error = 0;  // 1 ragged, 2 numeric parse failure
};

struct Parsed {
  int ncols = 0;
  int64_t nrows = 0;
  std::vector<int> kinds;  // 0 numeric, 1 enum
  std::vector<std::vector<double>> nums;
  std::vector<std::vector<int32_t>> codes;
  std::vector<std::vector<std::string>> domains;
};

// EXACTLY pandas' default na_values set — the two paths must agree on
// what is NA, or enum columns silently diverge (e.g. pandas treats 'None'
// as NA but NOT 'NAN').
inline bool is_na(const char* b, size_t n) {
  if (n == 0) return true;
  static const char* kNA[] = {
      "#N/A", "#N/A N/A", "#NA", "-1.#IND", "-1.#QNAN", "-NaN", "-nan",
      "1.#IND", "1.#QNAN", "<NA>", "N/A", "NA", "NULL", "NaN", "None",
      "n/a", "nan", "null",
  };
  for (const char* cand : kNA) {
    size_t cn = std::strlen(cand);
    if (cn == n && !std::memcmp(b, cand, n)) return true;
  }
  return false;
}

// trim the \r of a \r\n line ending. ONLY valid for the final field of a
// row (the caller gates on at_end): pandas' C parser treats a lone '\r' as
// a line terminator, so any '\r' not followed by '\n' means the two paths
// would tokenize different rows — fastcsv_parse prescans and bails to the
// pandas path for such buffers (rc -5) instead of guessing.
inline void trim_cr(const char*& b, size_t& n) {
  if (n && b[n - 1] == '\r') --n;
}

// Whole-field double parse, from_chars{general} semantics: no leading
// whitespace or '+', no hex, entire field consumed. libstdc++ < 11 ships
// no floating-point std::from_chars, so older toolchains fall back to
// glibc strtod (correctly rounded, same result bits) with the laxer
// strtod acceptances rejected up front. strtod reads LC_NUMERIC's decimal
// point — embedding interpreters leave it "C" unless the host app calls
// setlocale, which is outside this parser's contract either way.
inline bool parse_f64(const char* fb, size_t fn, double& v) {
#if defined(__cpp_lib_to_chars) && __cpp_lib_to_chars >= 201611L
  auto [p, ec] = std::from_chars(fb, fb + fn, v);
  return ec == std::errc() && p == fb + fn;
#else
  if (fn == 0) return false;
  const unsigned char c0 = static_cast<unsigned char>(fb[0]);
  if (fb[0] == '+' || std::isspace(c0)) return false;
  const size_t d = (fb[0] == '-') ? 1 : 0;
  if (fn > d + 1 && fb[d] == '0' && (fb[d + 1] == 'x' || fb[d + 1] == 'X'))
    return false;
  std::string tmp(fb, fn);  // strtod needs NUL termination
  errno = 0;
  char* endp = nullptr;
  v = std::strtod(tmp.c_str(), &endp);
  if (endp != tmp.c_str() + fn) return false;
  if (errno == ERANGE && (v == HUGE_VAL || v == -HUGE_VAL))
    return false;  // overflow -> pandas decides (from_chars errors here too)
  return true;
#endif
}

void parse_range(const char* buf, int64_t begin, int64_t end, char sep,
                 int ncols, const int* kinds, ThreadChunk* out) {
  out->cols.resize(ncols);
  out->domains.resize(ncols);
  std::vector<std::unordered_map<std::string, int32_t>> intern(ncols);
  int64_t pos = begin;
  while (pos < end) {
    int64_t eol = pos;
    while (eol < end && buf[eol] != '\n') ++eol;
    // blank lines are SKIPPED (pandas skip_blank_lines=True default)
    if (eol == pos || (eol == pos + 1 && buf[pos] == '\r')) {
      pos = eol + 1;
      continue;
    }
    // tokenize one row
    int col = 0;
    int64_t f0 = pos;
    for (int64_t i = pos; i <= eol && col < ncols + 1; ++i) {
      const bool at_end = (i == eol);
      if (at_end || buf[i] == sep) {
        if (col >= ncols) { out->error = 1; return; }
        const char* fb = buf + f0;
        size_t fn = static_cast<size_t>(i - f0);
        if (at_end) trim_cr(fb, fn);  // only the field ending at EOL owns \r
        if (kinds[col] == 0) {
          double v;
          if (is_na(fb, fn)) {
            v = std::nan("");
          } else if (!parse_f64(fb, fn, v)) {
            // tolerate leading '+' which from_chars-style parsing rejects
            if (!(fn > 1 && fb[0] == '+' && parse_f64(fb + 1, fn - 1, v))) {
              out->error = 2;
              return;
            }
          }
          out->cols[col].nums.push_back(v);
        } else {
          if (is_na(fb, fn)) {
            out->cols[col].codes.push_back(-1);
          } else {
            std::string key(fb, fn);
            auto it = intern[col].find(key);
            int32_t id;
            if (it == intern[col].end()) {
              id = static_cast<int32_t>(out->domains[col].size());
              intern[col].emplace(std::move(key), id);
              out->domains[col].push_back(std::string(fb, fn));
            } else {
              id = it->second;
            }
            out->cols[col].codes.push_back(id);
          }
        }
        ++col;
        f0 = i + 1;
      }
    }
    if (col != ncols) { out->error = 1; return; }
    ++out->rows;
    pos = eol + 1;
  }
}

}  // namespace

extern "C" {

// Parse the whole buffer. Returns an opaque handle (call fastcsv_free), or
// nullptr with *rc set: -1 quote found, -2 ragged row, -3 numeric parse
// failure, -4 bad args, -5 stray \r outside a \r\n line ending.
void* fastcsv_parse(const char* buf, int64_t len, char sep, int skip_header,
                    int ncols, const int* kinds, int n_threads, int* rc) {
  *rc = 0;
  if (ncols <= 0 || len < 0) { *rc = -4; return nullptr; }
  if (std::memchr(buf, '"', static_cast<size_t>(len)) != nullptr) {
    *rc = -1;  // quoted dialect -> pandas
    return nullptr;
  }
  // stray '\r' (not part of a \r\n ending): pandas' C parser treats a lone
  // \r as a line terminator, which would split rows differently than the
  // \n-scan below — bail to pandas rather than silently keeping the byte
  // inside a field (or mis-trimming it from a non-final field).
  {
    const char* p = buf;
    const char* bend = buf + len;
    while ((p = static_cast<const char*>(
                std::memchr(p, '\r', static_cast<size_t>(bend - p)))) != nullptr) {
      if (p + 1 >= bend || p[1] != '\n') { *rc = -5; return nullptr; }
      ++p;
    }
  }
  int64_t begin = 0;
  if (skip_header) {
    while (begin < len && buf[begin] != '\n') ++begin;
    if (begin < len) ++begin;
  }
  if (n_threads < 1) n_threads = 1;
  // split on row boundaries
  std::vector<int64_t> starts{begin};
  for (int t = 1; t < n_threads; ++t) {
    int64_t p = begin + (len - begin) * t / n_threads;
    while (p < len && buf[p] != '\n') ++p;
    if (p < len) ++p;
    starts.push_back(p);
  }
  starts.push_back(len);

  std::vector<ThreadChunk> chunks(n_threads);
  std::vector<std::thread> threads;
  for (int t = 0; t < n_threads; ++t) {
    threads.emplace_back(parse_range, buf, starts[t], starts[t + 1], sep,
                         ncols, kinds, &chunks[t]);
  }
  for (auto& th : threads) th.join();
  for (auto& c : chunks) {
    if (c.error) { *rc = c.error == 1 ? -2 : -3; return nullptr; }
  }

  auto* out = new Parsed();
  out->ncols = ncols;
  out->kinds.assign(kinds, kinds + ncols);
  out->nums.resize(ncols);
  out->codes.resize(ncols);
  out->domains.resize(ncols);
  for (auto& c : chunks) out->nrows += c.rows;

  for (int col = 0; col < ncols; ++col) {
    if (kinds[col] == 0) {
      auto& dst = out->nums[col];
      dst.reserve(static_cast<size_t>(out->nrows));
      for (auto& c : chunks)
        dst.insert(dst.end(), c.cols[col].nums.begin(), c.cols[col].nums.end());
    } else {
      // merge thread-local domains in thread order (== first-seen row
      // order within each chunk; global order is deterministic for a
      // given buffer + thread count)
      std::unordered_map<std::string, int32_t> global;
      auto& dom = out->domains[col];
      auto& dst = out->codes[col];
      dst.reserve(static_cast<size_t>(out->nrows));
      for (auto& c : chunks) {
        std::vector<int32_t> remap(c.domains[col].size());
        for (size_t i = 0; i < c.domains[col].size(); ++i) {
          auto it = global.find(c.domains[col][i]);
          if (it == global.end()) {
            int32_t id = static_cast<int32_t>(dom.size());
            global.emplace(c.domains[col][i], id);
            dom.push_back(c.domains[col][i]);
            remap[i] = id;
          } else {
            remap[i] = it->second;
          }
        }
        for (int32_t code : c.cols[col].codes)
          dst.push_back(code < 0 ? -1 : remap[static_cast<size_t>(code)]);
      }
    }
  }
  return out;
}

int64_t fastcsv_nrows(void* h) { return static_cast<Parsed*>(h)->nrows; }

void fastcsv_get_numeric(void* h, int col, double* out) {
  auto* p = static_cast<Parsed*>(h);
  std::memcpy(out, p->nums[col].data(), p->nums[col].size() * sizeof(double));
}

void fastcsv_get_codes(void* h, int col, int32_t* out) {
  auto* p = static_cast<Parsed*>(h);
  std::memcpy(out, p->codes[col].data(), p->codes[col].size() * sizeof(int32_t));
}

int64_t fastcsv_domain_size(void* h, int col) {
  return static_cast<int64_t>(static_cast<Parsed*>(h)->domains[col].size());
}

// total bytes needed for the \n-joined domain blob of one column
int64_t fastcsv_domain_bytes(void* h, int col) {
  auto* p = static_cast<Parsed*>(h);
  int64_t total = 0;
  for (auto& s : p->domains[col]) total += static_cast<int64_t>(s.size()) + 1;
  return total;
}

void fastcsv_get_domain(void* h, int col, char* out) {
  auto* p = static_cast<Parsed*>(h);
  for (auto& s : p->domains[col]) {
    std::memcpy(out, s.data(), s.size());
    out += s.size();
    *out++ = '\n';
  }
}

void fastcsv_free(void* h) { delete static_cast<Parsed*>(h); }

}  // extern "C"
