// Native tmojo scoring runtime — successor of the h2o-genmodel scoring
// core (`hex.genmodel.easy.EasyPredictModelWrapper` / `CompressedTree.score0`)
// [UNVERIFIED upstream paths, SURVEY.md §2.3]: the offline, cluster-free,
// jax-free tree-forest scorer, in C++ for deployment surfaces where the
// Python/numpy replay (h2o3_tpu/genmodel.py) is too slow or unavailable.
//
// Design: the Python loader (h2o3_tpu/native.py) flattens the tmojo level
// arrays into contiguous buffers once; this library walks trees row-major
// with per-row early exit — each row touches only the nodes on its own
// root->leaf path, unlike the level-synchronous numpy replay that streams
// every level array over all rows. Plain C ABI so ctypes can bind it with
// no build-time Python dependency.
//
// Layout contract (all buffers little-endian, C-contiguous):
//   bins        (n_rows, n_cols) uint8 — bin codes, 0 = NA
//   For every (tree t, class k), levels are consecutive entries in the
//   global level table:  tk_level_start[t*K+k] .. +tk_level_count[t*K+k].
//   Level L's nodes live at node offset lvl_node_off[L] in the node arrays;
//   cat_mask is (node, B) flattened.
//
// Build: g++ -O3 -shared -fPIC [-fopenmp] tmojo_score.cpp -o libtmojo.so

#include <cstdint>
#include <cstring>

extern "C" {

// Score the whole forest: out (n_rows, K) += sum over trees of leaf values.
void tmojo_score_forest(
    const uint8_t* bins, int64_t n_rows, int64_t n_cols,
    int64_t n_trees, int64_t K,
    const int64_t* tk_level_start,   // (n_trees*K)
    const int64_t* tk_level_count,   // (n_trees*K)
    const int64_t* lvl_node_off,     // (total_levels)
    const int32_t* split_col,
    const int32_t* split_bin,
    const uint8_t* is_cat,
    const uint8_t* cat_mask, int64_t B,
    const uint8_t* na_left,
    const uint8_t* leaf_now,
    const float* leaf_val,
    const int32_t* child_base,
    double* out)                      // (n_rows, K), caller-zeroed
{
#ifdef _OPENMP
#pragma omp parallel for schedule(static)
#endif
    for (int64_t r = 0; r < n_rows; ++r) {
        const uint8_t* row = bins + r * n_cols;
        double* orow = out + r * K;
        for (int64_t t = 0; t < n_trees; ++t) {
            for (int64_t k = 0; k < K; ++k) {
                const int64_t lv0 = tk_level_start[t * K + k];
                const int64_t nlv = tk_level_count[t * K + k];
                int64_t nid = 0;
                for (int64_t l = 0; l < nlv; ++l) {
                    const int64_t off = lvl_node_off[lv0 + l] + nid;
                    if (leaf_now[off]) {
                        orow[k] += (double)leaf_val[off];
                        break;
                    }
                    const uint8_t b = row[split_col[off]];
                    bool left;
                    if (b == 0) {
                        left = na_left[off] != 0;
                    } else if (is_cat[off]) {
                        left = cat_mask[off * B + b] != 0;
                    } else {
                        left = (int32_t)b <= split_bin[off];
                    }
                    nid = (int64_t)child_base[off] + (left ? 0 : 1);
                }
            }
        }
    }
}

// Bin numeric features exactly like the device path: float32 values against
// float32 right-open edges (searchsorted side="left"), code 0 for NaN.
void tmojo_bin_numeric(
    const float* x, int64_t n, const float* edges, int64_t n_edges,
    uint8_t* out)
{
    for (int64_t i = 0; i < n; ++i) {
        const float v = x[i];
        if (v != v) { out[i] = 0; continue; }  // NaN
        // branchless-ish binary search: first edge >= v
        int64_t lo = 0, hi = n_edges;
        while (lo < hi) {
            const int64_t mid = (lo + hi) >> 1;
            if (edges[mid] < v) lo = mid + 1; else hi = mid;
        }
        out[i] = (uint8_t)(lo + 1);
    }
}

}  // extern "C"
