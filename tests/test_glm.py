"""GLM tests — modeled on upstream ``hex/glm/GLMBasicTest*.java`` scenarios
[UNVERIFIED upstream path]: fit against known references (sklearn / closed
form) on the 8-device CPU mesh."""

import numpy as np
import pandas as pd
import pytest

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.glm import GLM


def _reg_data(n=4000, p=5, seed=0, noise=0.1):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, p))
    beta = np.arange(1, p + 1, dtype=np.float64)
    y = X @ beta + 2.5 + noise * rng.normal(size=n)
    df = pd.DataFrame(X, columns=[f"x{i}" for i in range(p)])
    df["y"] = y
    return df, beta


def test_gaussian_recovers_coefficients():
    df, beta = _reg_data()
    fr = Frame.from_pandas(df)
    m = GLM(family="gaussian", lambda_=0.0).train(y="y", training_frame=fr)
    coef = m.coef
    for i, b in enumerate(beta):
        assert coef[f"x{i}"] == pytest.approx(b, abs=0.02)
    assert coef["Intercept"] == pytest.approx(2.5, abs=0.02)
    assert m.training_metrics.r2 > 0.99


def test_gaussian_matches_sklearn_ridge():
    from sklearn.linear_model import Ridge

    df, _ = _reg_data(noise=1.0)
    fr = Frame.from_pandas(df)
    lam = 0.1
    m = GLM(family="gaussian", alpha=0.0, lambda_=lam, standardize=False).train(
        y="y", training_frame=fr
    )
    n = len(df)
    sk = Ridge(alpha=lam * n, fit_intercept=True).fit(df.drop(columns="y"), df["y"])
    for i in range(5):
        assert m.coef[f"x{i}"] == pytest.approx(sk.coef_[i], abs=5e-3)


def test_binomial_matches_sklearn():
    from sklearn.linear_model import LogisticRegression

    rng = np.random.default_rng(1)
    n = 6000
    X = rng.normal(size=(n, 4))
    eta = X @ np.array([1.0, -2.0, 0.5, 0.0]) - 0.3
    y = (rng.random(n) < 1 / (1 + np.exp(-eta))).astype(int)
    df = pd.DataFrame(X, columns=list("abcd"))
    df["y"] = np.where(y == 1, "yes", "no")
    fr = Frame.from_pandas(df)
    m = GLM(family="binomial", lambda_=0.0, standardize=False).train(
        y="y", training_frame=fr
    )
    sk = LogisticRegression(penalty=None, max_iter=500).fit(X, y)
    for i, c in enumerate("abcd"):
        assert m.coef[c] == pytest.approx(sk.coef_[0][i], abs=2e-2)
    assert m.training_metrics.auc == pytest.approx(
        _sk_auc(y, sk.predict_proba(X)[:, 1]), abs=2e-3
    )


def _sk_auc(y, p):
    from sklearn.metrics import roc_auc_score

    return roc_auc_score(y, p)


def test_poisson_family():
    rng = np.random.default_rng(2)
    n = 5000
    x = rng.normal(size=n)
    mu = np.exp(0.5 + 0.8 * x)
    y = rng.poisson(mu)
    fr = Frame.from_pandas(pd.DataFrame({"x": x, "y": y.astype(float)}))
    m = GLM(family="poisson", lambda_=0.0, standardize=False).train(
        y="y", training_frame=fr
    )
    assert m.coef["x"] == pytest.approx(0.8, abs=0.05)
    assert m.coef["Intercept"] == pytest.approx(0.5, abs=0.05)


def test_lasso_sparsifies():
    rng = np.random.default_rng(3)
    n, p = 3000, 20
    X = rng.normal(size=(n, p))
    y = X[:, 0] * 3.0 + X[:, 1] * -2.0 + 0.05 * rng.normal(size=n)
    df = pd.DataFrame(X, columns=[f"x{i}" for i in range(p)])
    df["y"] = y
    fr = Frame.from_pandas(df)
    m = GLM(family="gaussian", alpha=1.0, lambda_=0.05).train(y="y", training_frame=fr)
    coef = m.coef_norm()
    nz = [k for k, v in coef.items() if abs(v) > 1e-6 and k != "Intercept"]
    assert set(nz) == {"x0", "x1"}


def test_lambda_search_path():
    df, _ = _reg_data(n=2000, noise=0.5)
    fr = Frame.from_pandas(df)
    m = GLM(family="gaussian", lambda_search=True, nlambdas=20, alpha=0.5).train(
        y="y", training_frame=fr
    )
    path = m.output["regularization_path"]
    assert len(path) >= 2
    assert path[0]["lambda"] > path[-1]["lambda"]
    assert m.training_metrics.r2 > 0.95


def test_categorical_predictors():
    rng = np.random.default_rng(4)
    n = 4000
    g = rng.choice(["a", "b", "c"], n)
    eff = {"a": 0.0, "b": 1.0, "c": -2.0}
    y = np.array([eff[v] for v in g]) + 0.1 * rng.normal(size=n)
    fr = Frame.from_pandas(pd.DataFrame({"g": g, "y": y}))
    m = GLM(family="gaussian", lambda_=0.0).train(y="y", training_frame=fr)
    # reference level 'a' dropped; effects relative to it
    assert m.coef["g.b"] == pytest.approx(1.0, abs=0.02)
    assert m.coef["g.c"] == pytest.approx(-2.0, abs=0.02)


def test_multinomial():
    rng = np.random.default_rng(5)
    n = 3000
    X = rng.normal(size=(n, 3))
    logits = X @ rng.normal(size=(3, 3)) * 2
    y = logits.argmax(axis=1)
    df = pd.DataFrame(X, columns=list("abc"))
    df["y"] = np.array(["c0", "c1", "c2"])[y]
    fr = Frame.from_pandas(df)
    m = GLM(family="multinomial", lambda_=1e-4).train(y="y", training_frame=fr)
    mm = m.training_metrics
    assert mm.classification_error < 0.08
    assert mm.logloss < 0.35
    pred = m.predict(fr)
    assert pred.names == ["predict", "c0", "c1", "c2"]


def test_weights_column():
    # duplicate-rows-vs-weight-2 equivalence, an H2O GLM test classic
    rng = np.random.default_rng(6)
    n = 1000
    x = rng.normal(size=n)
    y = 2 * x + rng.normal(size=n) * 0.1
    df1 = pd.DataFrame({"x": np.r_[x, x], "y": np.r_[y, y]})
    df2 = pd.DataFrame({"x": x, "y": y, "w": np.full(n, 2.0)})
    m1 = GLM(family="gaussian", lambda_=0.0).train(y="y", training_frame=Frame.from_pandas(df1))
    m2 = GLM(family="gaussian", lambda_=0.0, weights_column="w").train(
        y="y", training_frame=Frame.from_pandas(df2), x=["x"]
    )
    assert m1.coef["x"] == pytest.approx(m2.coef["x"], abs=1e-4)


def test_p_values():
    df, _ = _reg_data(n=2000, noise=1.0)
    fr = Frame.from_pandas(df)
    m = GLM(family="gaussian", lambda_=0.0, compute_p_values=True, standardize=False).train(
        y="y", training_frame=fr
    )
    pv = m.output["p_values"]
    assert (pv[:5] < 1e-6).all()  # true effects significant


def test_validation_frame_and_predict():
    df, _ = _reg_data(n=3000, noise=0.5)
    fr = Frame.from_pandas(df)
    tr, te = fr.split_frame([0.8], seed=1)
    m = GLM(family="gaussian").train(y="y", training_frame=tr, validation_frame=te)
    assert m.validation_metrics is not None
    assert m.validation_metrics.r2 > 0.9
    pred = m.predict(te)
    assert pred.nrow == te.nrow
    perf = m.model_performance(te)
    assert perf.rmse == pytest.approx(m.validation_metrics.rmse, rel=1e-6)


# ---------------------------------------------------------------------------
# ordinal family + L_BFGS solver (round 3)


def test_glm_ordinal_recovers_proportional_odds():
    from scipy import optimize as spo

    rng = np.random.default_rng(2)
    n = 4000
    x0, x1 = rng.normal(size=(2, n))
    eta = 1.5 * x0 - x1
    lat = eta + rng.logistic(size=n)
    yo = np.digitize(lat, [-1.0, 0.5])  # classes 0 < 1 < 2
    df = pd.DataFrame({"x0": x0, "x1": x1, "y": yo.astype(str)})
    fr = Frame.from_pandas(df, column_types={"y": "enum"})
    m = GLM(family="ordinal", standardize=False).train(y="y", training_frame=fr)
    beta = np.array([m.coef["x0"], m.coef["x1"]])
    theta = np.asarray(m.output["theta"])
    # independent numpy/scipy fit of the same likelihood
    X = np.stack([x0, x1], axis=1)

    def nll(params):
        b, t1, dt = params[:2], params[2], params[3]
        th = np.array([t1, t1 + np.exp(dt)])
        e = X @ b
        cum = 1 / (1 + np.exp(-(th[None, :] - e[:, None])))
        pk = np.diff(
            np.concatenate(
                [np.zeros((n, 1)), cum, np.ones((n, 1))], axis=1
            ), axis=1,
        )
        return -np.log(np.clip(pk[np.arange(n), yo], 1e-12, 1)).sum()

    ref = spo.minimize(nll, np.zeros(4), method="Nelder-Mead",
                       options={"maxiter": 4000, "fatol": 1e-10})
    rb = ref.x[:2]
    rt = np.array([ref.x[2], ref.x[2] + np.exp(ref.x[3])])
    np.testing.assert_allclose(beta, rb, atol=0.05)
    np.testing.assert_allclose(theta, rt, atol=0.05)
    # parameters near the generating truth
    np.testing.assert_allclose(beta, [1.5, -1.0], atol=0.15)
    np.testing.assert_allclose(theta, [-1.0, 0.5], atol=0.15)
    # predicted class probs are proper
    P = m._predict_raw(fr)
    np.testing.assert_allclose(P.sum(axis=1), 1.0, atol=1e-6)


def test_glm_lbfgs_matches_irlsm():
    rng = np.random.default_rng(5)
    n = 3000
    x0, x1 = rng.normal(size=(2, n))
    eta = 1.2 * x0 - 0.7 * x1 + 0.3
    y = (rng.random(n) < 1 / (1 + np.exp(-eta))).astype(int)
    fr = Frame.from_pandas(
        pd.DataFrame({"x0": x0, "x1": x1, "y": y.astype(str)}),
        column_types={"y": "enum"},
    )
    a = GLM(family="binomial", lambda_=0.0).train(y="y", training_frame=fr)
    b = GLM(family="binomial", lambda_=0.0, solver="L_BFGS").train(
        y="y", training_frame=fr
    )
    for k in a.coef:
        np.testing.assert_allclose(a.coef[k], b.coef[k], atol=2e-3)
    # poisson too (different link/deviance path through the same objective)
    lam = np.exp(0.5 * x0)
    yp = rng.poisson(lam)
    frp = Frame.from_pandas(pd.DataFrame({"x0": x0, "y": yp.astype(float)}))
    c = GLM(family="poisson", lambda_=0.0).train(y="y", training_frame=frp)
    d = GLM(family="poisson", lambda_=0.0, solver="L_BFGS").train(
        y="y", training_frame=frp
    )
    np.testing.assert_allclose(c.coef["x0"], d.coef["x0"], atol=2e-3)


def test_hglm_recovers_variance_components():
    from h2o3_tpu.models import HGLM

    rng = np.random.default_rng(7)
    n, q = 8000, 40
    grp = rng.integers(0, q, n)
    u_true = rng.normal(0, 1.5, q)  # sigma_u^2 = 2.25
    x = rng.normal(size=n)
    y = 2.0 + 3.0 * x + u_true[grp] + rng.normal(0, 1.0, n)  # sigma_e^2 = 1
    df = pd.DataFrame({"x": x, "g": [f"g{i:02d}" for i in grp], "y": y})
    fr = Frame.from_pandas(df, column_types={"g": "enum"})
    m = HGLM(random_columns=["g"]).train(y="y", x=["x", "g"], training_frame=fr)
    assert abs(m.coef["x"] - 3.0) < 0.05
    assert abs(m.coef["Intercept"] - 2.0) < 0.6  # absorbs group mean shift
    assert abs(m.output["sigma_e2"] - 1.0) < 0.1
    assert abs(m.output["sigma_u2"]["g"] - 2.25) < 0.8
    blups = m.coefs_random("g")
    corr = np.corrcoef([blups[f"g{i:02d}"] for i in range(q)], u_true)[0, 1]
    assert corr > 0.99  # BLUPs track the true random effects
    # shrinkage: BLUP variance below raw group-mean variance
    assert np.var(list(blups.values())) < np.var(u_true) * 1.5
    # scoring uses the BLUPs: r2 well above the fixed-effect-only fit
    assert m.training_metrics.value("r2") > 0.9


def test_hglm_validation():
    from h2o3_tpu.models import HGLM

    rng = np.random.default_rng(8)
    df = pd.DataFrame({"x": rng.normal(size=100), "y": rng.normal(size=100)})
    fr = Frame.from_pandas(df)
    with pytest.raises(Exception, match="random_columns"):
        HGLM().train(y="y", training_frame=fr)
    with pytest.raises(Exception, match="categorical"):
        HGLM(random_columns=["x"]).train(y="y", training_frame=fr)


def test_glm_ordinal_standardized_coefs_consistent():
    # standardize=True must yield the same class probabilities and the same
    # ORIGINAL-scale slopes as standardize=False (review: the intercept
    # destandardization used to clobber the last coefficient)
    rng = np.random.default_rng(3)
    n = 3000
    x0 = rng.normal(2.0, 3.0, n)  # non-trivial mean/sigma
    x1 = rng.normal(-1.0, 0.5, n)
    lat = 0.8 * x0 + 1.1 * x1 + rng.logistic(size=n)
    yo = np.digitize(lat, [0.0, 2.5])
    df = pd.DataFrame({"x0": x0, "x1": x1, "y": yo.astype(str)})
    fr = Frame.from_pandas(df, column_types={"y": "enum"})
    ms = GLM(family="ordinal", standardize=True).train(y="y", training_frame=fr)
    mu = GLM(family="ordinal", standardize=False).train(y="y", training_frame=fr)
    np.testing.assert_allclose(
        [ms.coef["x0"], ms.coef["x1"]], [mu.coef["x0"], mu.coef["x1"]],
        atol=0.03,
    )
    np.testing.assert_allclose(
        ms.output["theta_orig"], mu.output["theta"], atol=0.08
    )
    Ps = ms._predict_raw(fr)
    Pu = mu._predict_raw(fr)
    np.testing.assert_allclose(Ps, Pu, atol=0.02)


def test_hglm_two_random_columns():
    from h2o3_tpu.models import HGLM

    rng = np.random.default_rng(9)
    n = 6000
    g1 = rng.integers(0, 25, n)
    g2 = rng.integers(0, 8, n)
    u1 = rng.normal(0, 1.0, 25)
    u2 = rng.normal(0, 2.0, 8)
    x = rng.normal(size=n)
    y = 1.0 + 2.0 * x + u1[g1] + u2[g2] + rng.normal(0, 0.7, n)
    df = pd.DataFrame({"x": x, "g1": [f"a{i}" for i in g1],
                       "g2": [f"b{i}" for i in g2], "y": y})
    fr = Frame.from_pandas(df, column_types={"g1": "enum", "g2": "enum"})
    m = HGLM(random_columns=["g1", "g2"]).train(
        y="y", x=["x", "g1", "g2"], training_frame=fr
    )
    assert abs(m.coef["x"] - 2.0) < 0.05
    s = m.output["sigma_u2"]
    assert 0.5 < s["g1"] < 2.0  # true 1.0
    assert 1.5 < s["g2"] < 12.0  # true 4.0, only 8 levels -> wide
    assert abs(m.output["sigma_e2"] - 0.49) < 0.1
    c1 = np.corrcoef([m.coefs_random("g1")[f"a{i}"] for i in range(25)], u1)[0, 1]
    assert c1 > 0.99


def test_glm_interactions_recover_products(tmp_path):
    import os

    from h2o3_tpu.genmodel import MojoModel
    from h2o3_tpu.models.export import export_mojo

    rng = np.random.default_rng(3)
    n = 4000
    x1, x2 = rng.normal(size=(2, n))
    g = rng.choice(["a", "b"], n)
    slope = np.where(g == "a", 1.0, -2.0)
    y = 0.5 * x1 + 3.0 * x1 * x2 + slope * x2 + 0.1 * rng.normal(size=n)
    df = pd.DataFrame({"x1": x1, "x2": x2, "g": g, "y": y})
    fr = Frame.from_pandas(df)
    m0 = GLM(lambda_=0.0).train(y="y", x=["x1", "x2", "g"], training_frame=fr)
    m1 = GLM(lambda_=0.0, interaction_pairs=[("x1", "x2"), ("g", "x2")]).train(
        y="y", x=["x1", "x2", "g"], training_frame=fr
    )
    assert m0.training_metrics.value("r2") < 0.2  # additive model can't fit
    assert m1.training_metrics.value("r2") > 0.99
    c = m1.coef
    assert abs(c["x1:x2"] - 3.0) < 0.05  # product coefficient recovered
    assert abs(c["g.b:x2"] - (-3.0)) < 0.05  # slope delta b vs baseline a
    # export round-trips the interaction design
    p = os.path.join(str(tmp_path), "inter.zip")
    export_mojo(m1, p)
    off = MojoModel.load(p).predict(df.drop(columns="y"))["predict"]
    live = m1.predict(fr).vec("predict").to_numpy()
    np.testing.assert_allclose(off, live, atol=1e-4)
    # `interactions` list form = all pairwise
    m2 = GLM(lambda_=0.0, interactions=["x1", "x2"]).train(
        y="y", x=["x1", "x2"], training_frame=fr
    )
    assert "x1:x2" in m2.coef
    # cat x cat: combined-factor interaction (upstream enum-by-enum)
    g2 = rng.choice(["u", "v"], n)
    bump = np.where((g == "a") & (g2 == "u"), 2.5, 0.0)
    y3 = 0.5 * x1 + bump + 0.1 * rng.normal(size=n)
    df3 = pd.DataFrame({"x1": x1, "g": g, "g2": g2, "y": y3})
    fr3 = Frame.from_pandas(df3)
    # tiny ridge: with main effects present the cross indicators are exactly
    # collinear (a_v+b_v == g2.v), so lambda=0 would leave beta non-unique
    # and the live-vs-offline comparison numerically fragile
    m3 = GLM(lambda_=1e-4, alpha=0.0, interaction_pairs=[("g", "g2")]).train(
        y="y", x=["x1", "g", "g2"], training_frame=fr3
    )
    assert m3.training_metrics.value("r2") > 0.95
    assert any(k.startswith("g:g2.") for k in m3.coef)
    # scoring a fresh frame exercises the combined-code remap path
    pred = m3.predict(fr3).vec("predict").to_numpy()[:n]
    assert float(np.sqrt(np.mean((pred - y3) ** 2))) < 0.2
    # MOJO export must carry the combined-factor spec (offline == live)
    p3 = os.path.join(str(tmp_path), "catcat.zip")
    export_mojo(m3, p3)
    off3 = MojoModel.load(p3).predict(df3.drop(columns="y"))["predict"]
    np.testing.assert_allclose(off3, pred, atol=1e-4)


def test_glm_lbfgs_accepts_explicit_l1():
    """L_BFGS fits elastic net exactly now (bound-constrained split) —
    explicit alpha>0 with lambda>0 trains instead of erroring."""
    rng = np.random.default_rng(7)
    n = 500
    x0 = rng.normal(size=n)
    y = (rng.random(n) < 1 / (1 + np.exp(-x0))).astype(int)
    fr = Frame.from_pandas(
        pd.DataFrame({"x0": x0, "y": y.astype(str)}), column_types={"y": "enum"}
    )
    m = GLM(family="binomial", solver="L_BFGS", alpha=0.5, lambda_=0.01).train(
        y="y", training_frame=fr)
    assert 0.5 < float(m.training_metrics.auc) <= 1.0


def test_lbfgs_elastic_net_matches_irlsm():
    """L_BFGS now honors the L1 part of elastic net (bound-constrained
    split): coefficients track the IRLSM/ADMM solution of the same
    objective, and strong L1 produces the same sparsity pattern."""
    rng = np.random.default_rng(4)
    n, k = 3000, 8
    X = rng.normal(size=(n, k))
    beta_true = np.array([2.0, -1.5, 1.0, 0, 0, 0, 0, 0])
    y = X @ beta_true + rng.normal(size=n) * 0.5
    df = pd.DataFrame(X, columns=[f"x{i}" for i in range(k)])
    df["y"] = y
    fr = Frame.from_pandas(df)

    kw = dict(family="gaussian", alpha=0.9, lambda_=0.05)
    m_ir = GLM(solver="IRLSM", **kw).train(y="y", training_frame=fr)
    m_lb = GLM(solver="L_BFGS", **kw).train(y="y", training_frame=fr)
    c_ir = np.array([m_ir.coef[f"x{i}"] for i in range(k)])
    c_lb = np.array([m_lb.coef[f"x{i}"] for i in range(k)])
    np.testing.assert_allclose(c_lb, c_ir, atol=0.02)
    # noise coefficients are driven to (near) zero by the L1 part
    assert np.all(np.abs(c_lb[3:]) < 0.02)
    assert np.all(np.abs(c_lb[:3]) > 0.5)


def test_lbfgs_lambda_search_path():
    """lambda_search now works under L_BFGS: a warm-started geometric path
    with a regularization_path output and a best-lambda pick."""
    rng = np.random.default_rng(11)
    n, k = 1500, 6
    X = rng.normal(size=(n, k))
    y = X[:, 0] * 1.5 - X[:, 1] + rng.normal(size=n) * 0.5
    df = pd.DataFrame(X, columns=[f"x{i}" for i in range(k)])
    df["y"] = y
    fr = Frame.from_pandas(df)
    m = GLM(solver="L_BFGS", family="gaussian", alpha=0.95,
            lambda_search=True, nlambdas=20).train(y="y", training_frame=fr)
    path = m.output["regularization_path"]
    assert 2 <= len(path) <= 20
    lams = [r["lambda"] for r in path]
    assert lams == sorted(lams, reverse=True)  # descending sequence
    # deviance improves monotonically-ish down the path; best is recorded
    assert m.output["lambda_best"] == min(
        path, key=lambda r: r["deviance"])["lambda"]
    assert float(m.training_metrics.r2) > 0.6


def test_lbfgs_lambda_search_with_offset_does_not_early_stop():
    """The path early-stop uses an OFFSET-AWARE null deviance: an offset
    explaining most of the response must not terminate the path at
    lambda_max with a maximally-penalized model."""
    rng = np.random.default_rng(13)
    n = 1200
    off = rng.normal(size=n) * 3.0          # dominant known component
    x0 = rng.normal(size=n)
    y = off + 0.8 * x0 + rng.normal(size=n) * 0.3
    fr = Frame.from_pandas(pd.DataFrame({"x0": x0, "off": off, "y": y}))
    m = GLM(solver="L_BFGS", family="gaussian", alpha=1.0,
            lambda_search=True, nlambdas=12, offset_column="off",
            standardize=False).train(y="y", x=["x0"], training_frame=fr)
    path = m.output["regularization_path"]
    assert len(path) > 1, "path stopped at lambda_max (offset-blind null)"
    assert abs(m.coef["x0"] - 0.8) < 0.1
