"""R client EXECUTION coverage (VERDICT r4 missing #7 / SURVEY §4 runits).

The R surface (`r/h2o3tpu.R` + generated `r/estimators_gen.R`) is
codegen-pinned by test_bindings_gen.py, but pinning proves freshness, not
that the code runs. This test drives the real client against a live server
— import → train → predict — whenever an R runtime exists.

Environment note (kept honest): the build image used through round 5 ships
NO ``Rscript`` (verified `which Rscript R` → nothing), so there this test
SKIPS with that reason rather than silently passing. The test body is
complete and runs wherever R + jsonlite are installed.
"""

import shutil
import subprocess

import numpy as np
import pandas as pd
import pytest

import h2o3_tpu
from h2o3_tpu.api.server import start_server

RSCRIPT = shutil.which("Rscript")

R_SMOKE = """
source(file.path("{repo}", "r", "h2o3tpu.R"))
h2o.init("{url}")
info <- h2o.clusterInfo()
stopifnot(info$cloud_healthy)
fr <- h2o.importFile("{csv}")
m <- h2o.gbm(y = "label", training_frame = fr, ntrees = 3, max_depth = 3,
             min_rows = 2, seed = 1)
p <- h2o.predict(m, fr)
stopifnot(nrow(p) == 120)
perf <- h2o.performance(m)
stopifnot(perf$auc > 0.5)
cat("R_SMOKE_OK\\n")
"""


@pytest.mark.skipif(
    RSCRIPT is None,
    reason="no Rscript in this image (verified absent in the round-5 "
    "environment) — R execution coverage runs wherever R + jsonlite exist; "
    "codegen freshness is still pinned by test_bindings_gen.py",
)
def test_r_client_smoke(tmp_path):
    import os

    rng = np.random.default_rng(5)
    n = 120
    df = pd.DataFrame({"a": rng.normal(size=n), "b": rng.normal(size=n)})
    df["label"] = np.where(rng.random(n) < 1 / (1 + np.exp(-df["a"])), "y", "n")
    csv = tmp_path / "smoke.csv"
    df.to_csv(csv, index=False)

    srv = start_server(port=0)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = tmp_path / "smoke.R"
    script.write_text(
        R_SMOKE.format(repo=repo, url=f"http://127.0.0.1:{srv.port}", csv=csv)
    )
    r = subprocess.run(
        [RSCRIPT, "--vanilla", str(script)],
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert r.returncode == 0, f"Rscript failed:\n{r.stdout}\n{r.stderr}"
    assert "R_SMOKE_OK" in r.stdout
