"""Out-of-core data plane (ISSUE 11, frame/chunkstore.py): compressed
device frames + streaming block epochs for datasets past the HBM window.

The acceptance pins:
- a frame that FITS the window takes the resident path unchanged
  (``ChunkStore.plan`` returns None → bit-parity by construction, asserted
  byte-equal), and ``H2O3_TPU_FRAME_COMPRESS=0`` restores the resident
  behavior bit-for-bit even with a window configured;
- a frame FORCED through a multi-block window trains GBM with the SAME
  split decisions as the resident build (gains differ only by f32
  block-summation order) and 1e-6-level predictions, GLM to matching
  coefficients, DL to a working model — across 1/2/8-device meshes;
- an oversized frame (tiny forced window) trains correctly through >= 4
  eviction cycles with the peak device residency bounded by the window;
- kill-and-resume (PR-10 / PR-2 recovery) survives mid-stream at 1e-6.
"""

import contextlib
import os

import numpy as np
import pandas as pd
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from h2o3_tpu.frame import chunkstore as cs
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.parallel import mesh as pm
from h2o3_tpu.utils import metrics as mx


@contextlib.contextmanager
def _use_mesh(k: int):
    devs = jax.devices("cpu")
    assert len(devs) >= k, "8-device conftest pin did not land"
    old = pm._mesh
    pm.set_mesh(Mesh(np.array(devs[:k]), (pm.ROWS_AXIS,)))
    try:
        yield
    finally:
        pm.set_mesh(old)


@contextlib.contextmanager
def _env(**kv):
    old = {k: os.environ.get(k) for k in kv}
    os.environ.update({k: str(v) for k, v in kv.items()})
    try:
        yield
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _frame(n=4000, c=8, seed=0, regression=False):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, c)).astype(np.float32)
    eta = X[:, 0] - 0.5 * X[:, 1] + 0.25 * X[:, 2]
    df = pd.DataFrame(X, columns=[f"x{i}" for i in range(c)])
    if regression:
        df["label"] = (eta + 0.3 * rng.normal(size=n)).astype(np.float32)
    else:
        y = rng.random(n) < 1.0 / (1.0 + np.exp(-eta))
        df["label"] = np.where(y, "s", "b")
    return Frame.from_pandas(df)


def _p1(model, fr):
    pf = model.predict(fr)
    return pf.vec(pf.names[-1]).to_numpy()


def _tree_decisions(model):
    out = []
    for group in model.output["trees"]:
        for t in group:
            h = t.to_host()
            out.append([(np.asarray(lv.split_col), np.asarray(lv.split_bin),
                         np.asarray(lv.leaf_now)) for lv in h.levels])
    return out


# ---------------------------------------------------------------------------
# ChunkStore unit behavior


def test_plan_gates():
    # no window -> resident
    with _env(H2O3_TPU_HBM_WINDOW_BYTES="0"):
        assert cs.ChunkStore.plan(10_000, 32) is None
    # fits the window -> resident
    with _env(H2O3_TPU_HBM_WINDOW_BYTES=str(10_000 * 32 + 1)):
        assert cs.ChunkStore.plan(10_000, 32) is None
    # compress off -> resident even with a window
    with _env(H2O3_TPU_HBM_WINDOW_BYTES="4096", H2O3_TPU_FRAME_COMPRESS="0"):
        assert cs.ChunkStore.plan(10_000, 32) is None
    # past the window -> streams with >1 block
    with _env(H2O3_TPU_HBM_WINDOW_BYTES="65536"):
        st = cs.ChunkStore.plan(100_000, 32)
        assert st is not None and st.n_blocks > 1
        q = pm.block_quantum()
        assert st.block_rows % q == 0


def test_plan_overwindow_single_block_still_streams():
    # Boundary pin (ISSUE 19): block geometry is quantized to the shard
    # multiple, so a one-quantum frame can never split into two blocks — a
    # window smaller than its footprint used to silently fall back to the
    # unbounded resident path. It must stream through the store's
    # accounted window instead, as a single quantum-floor block.
    q = pm.block_quantum()
    bpr = 32
    need = q * bpr
    with _env(H2O3_TPU_HBM_WINDOW_BYTES=str(need // 4)):
        st = cs.ChunkStore.plan(q, bpr)
        assert st is not None
        assert st.n_blocks == 1 and st.block_rows == q
        assert st.window == need // 4  # the accounted LRU budget, not need
    # the same geometry WITH room for the whole frame stays resident
    with _env(H2O3_TPU_HBM_WINDOW_BYTES=str(need * 4)):
        assert cs.ChunkStore.plan(q, bpr) is None


def test_store_lru_eviction_updates_and_gauges():
    h0 = mx.counter_value("frame_bytes_resident", tier="host")
    d0 = mx.counter_value("frame_bytes_resident", tier="hbm")
    e0 = mx.counter_value("frame_chunk_evictions_total")
    st = cs.ChunkStore(1024, 16, window=4096, prefetch=1)
    st.add_empty("x", (1024, 4), np.float32)
    st.lane("x")[:] = np.arange(1024 * 4, dtype=np.float32).reshape(1024, 4)
    assert mx.counter_value("frame_bytes_resident", tier="host") - h0 == \
        st.lane("x").nbytes
    for bi, blk in st.stream(("x",)):
        lo, hi = st.span(bi)
        assert np.array_equal(np.asarray(blk["x"]), st.lane("x")[lo:hi])
    assert st.evictions > 0
    assert mx.counter_value("frame_chunk_evictions_total") > e0
    # peak bounded by the window (pre-upload eviction)
    assert st.peak_hbm <= st.window
    # update writes through to the host tier and the window copy
    st.update(0, x=jnp.zeros((st.rows(0), 4), jnp.float32))
    assert (st.lane("x")[: st.rows(0)] == 0).all()
    got = st.fetch(0, ("x",))["x"]
    assert (np.asarray(got) == 0).all()
    st.close()
    assert mx.counter_value("frame_bytes_resident", tier="host") == \
        pytest.approx(h0)
    assert mx.counter_value("frame_bytes_resident", tier="hbm") == \
        pytest.approx(d0)
    assert cs.LAST_STORE_STATS["peak_hbm"] <= cs.LAST_STORE_STATS["window"]


def test_vec_release_rebuild_bit_equal():
    fr = _frame(500, 4, seed=3)
    v = fr.vec("x1")
    before = np.asarray(v.data)
    hbm0 = mx.counter_value("frame_bytes_resident", tier="hbm")
    freed = v.release_device()
    assert freed > 0
    assert mx.counter_value("frame_bytes_resident", tier="hbm") == \
        pytest.approx(hbm0 - freed)
    assert v._data is None and v.npad == len(before)
    after = np.asarray(v.data)  # lazy rebuild
    assert before.tobytes() == after.tobytes()
    # frame-level spill is a no-op under COMPRESS=0
    with _env(H2O3_TPU_FRAME_COMPRESS="0"):
        assert fr.spill_to_host() == 0


# ---------------------------------------------------------------------------
# GBM streaming parity


@pytest.mark.parametrize("n_dev", [1, 2, 8])
def test_gbm_streaming_matches_resident(n_dev):
    with _use_mesh(n_dev):
        fr = _frame(3000, 6, seed=7)
        kw = dict(ntrees=4, max_depth=4, seed=11, score_tree_interval=2)
        from h2o3_tpu.models.tree import GBM

        m_res = GBM(**kw).train(y="label", training_frame=fr)
        with _env(H2O3_TPU_HBM_WINDOW_BYTES=str(48 * 1024)):
            fr2 = _frame(3000, 6, seed=7)
            m_str = GBM(**kw).train(y="label", training_frame=fr2)
        assert cs.LAST_STORE_STATS["n_blocks"] > 1  # really streamed
        dres, dstr = _tree_decisions(m_res), _tree_decisions(m_str)
        assert len(dres) == len(dstr)
        for tr, ts in zip(dres, dstr):
            assert len(tr) == len(ts)
            for (c1, b1, l1), (c2, b2, l2) in zip(tr, ts):
                # identical split decisions: the streamed histogram differs
                # from the resident one only by f32 block-summation order
                assert np.array_equal(l1, l2)
                live = ~l1
                assert np.array_equal(c1[live], c2[live])
                assert np.array_equal(b1[live], b2[live])
        np.testing.assert_allclose(_p1(m_res, fr), _p1(m_str, fr), atol=1e-6)
        np.testing.assert_allclose(
            m_res.training_metrics.logloss, m_str.training_metrics.logloss,
            atol=1e-6)


def test_gbm_small_frame_fits_window_stays_resident_byte_equal():
    fr = _frame(2000, 6, seed=5)
    from h2o3_tpu.models.tree import GBM

    kw = dict(ntrees=3, max_depth=3, seed=2)
    m0 = GBM(**kw).train(y="label", training_frame=fr)
    # a window the frame fits: plan() declines, the resident programs run
    with _env(H2O3_TPU_HBM_WINDOW_BYTES=str(1 << 30)):
        from h2o3_tpu.frame import chunkstore as _cs

        assert _cs.ChunkStore.plan(fr.npad, 6 + 28) is None
        m1 = GBM(**kw).train(y="label", training_frame=fr)
    assert _p1(m0, fr).tobytes() == _p1(m1, fr).tobytes()


def test_compress_off_restores_resident_bit_for_bit():
    fr = _frame(2500, 6, seed=9)
    from h2o3_tpu.models.tree import GBM

    kw = dict(ntrees=3, max_depth=3, seed=4)
    m0 = GBM(**kw).train(y="label", training_frame=fr)
    e0 = mx.counter_value("frame_chunk_evictions_total")
    with _env(H2O3_TPU_HBM_WINDOW_BYTES="32768", H2O3_TPU_FRAME_COMPRESS="0"):
        m1 = GBM(**kw).train(y="label", training_frame=fr)
    assert mx.counter_value("frame_chunk_evictions_total") == e0
    assert _p1(m0, fr).tobytes() == _p1(m1, fr).tobytes()


# ---------------------------------------------------------------------------
# GLM / DL streaming parity


@pytest.mark.parametrize("n_dev", [1, 8])
def test_glm_streaming_coef_parity(n_dev):
    with _use_mesh(n_dev):
        fr = _frame(4000, 8, seed=13)
        from h2o3_tpu.models.glm import GLM

        kw = dict(family="binomial", lambda_=1e-4, max_iterations=15, seed=1)
        m_res = GLM(**kw).train(y="label", training_frame=fr)
        with _env(H2O3_TPU_HBM_WINDOW_BYTES=str(96 * 1024)):
            fr2 = _frame(4000, 8, seed=13)
            m_str = GLM(**kw).train(y="label", training_frame=fr2)
        assert cs.LAST_STORE_STATS["n_blocks"] > 1
        delta = max(abs(m_res.coef[k] - m_str.coef[k]) for k in m_res.coef)
        assert delta < 2e-5
        np.testing.assert_allclose(
            m_res.training_metrics.logloss, m_str.training_metrics.logloss,
            atol=1e-6)


def test_glm_streaming_gaussian_and_elastic_net():
    fr = _frame(4000, 8, seed=17, regression=True)
    from h2o3_tpu.models.glm import GLM

    kw = dict(family="gaussian", alpha=0.5, lambda_=1e-3, max_iterations=12,
              seed=1)
    m_res = GLM(**kw).train(y="label", training_frame=fr)
    with _env(H2O3_TPU_HBM_WINDOW_BYTES=str(96 * 1024)):
        fr2 = _frame(4000, 8, seed=17, regression=True)
        m_str = GLM(**kw).train(y="label", training_frame=fr2)
    delta = max(abs(m_res.coef[k] - m_str.coef[k]) for k in m_res.coef)
    assert delta < 2e-5


def test_dl_streaming_trains():
    from h2o3_tpu.models.deeplearning import DeepLearning

    with _env(H2O3_TPU_HBM_WINDOW_BYTES=str(96 * 1024)):
        fr = _frame(4000, 8, seed=21)
        m = DeepLearning(hidden=[16, 16], epochs=2, mini_batch_size=64,
                         seed=3).train(y="label", training_frame=fr)
    assert cs.LAST_STORE_STATS["n_blocks"] > 1
    assert m.output["epochs_trained"] == 2
    assert all(np.isfinite(e["loss"]) for e in m.scoring_history)
    assert float(m.training_metrics.auc) > 0.6


# ---------------------------------------------------------------------------
# oversized-frame smoke + chaos


def test_oversized_frame_trains_through_eviction_cycles():
    """Tiny forced window: rows x lanes >> window, >= 4 eviction cycles,
    peak device residency bounded by the window, model still correct."""
    from h2o3_tpu.models.tree import GBM

    e0 = mx.counter_value("frame_chunk_evictions_total")
    with _env(H2O3_TPU_HBM_WINDOW_BYTES=str(24 * 1024)):
        fr = _frame(6000, 6, seed=23)
        # frame lanes ~ 6000 * 34 B ~ 200 KiB >> 24 KiB window
        m = GBM(ntrees=4, max_depth=4, seed=5).train(
            y="label", training_frame=fr)
    stats = cs.LAST_STORE_STATS
    assert stats["n_blocks"] >= 4
    assert stats["evictions"] >= 4
    assert mx.counter_value("frame_chunk_evictions_total") - e0 >= 4
    assert stats["peak_hbm"] <= stats["window"]
    assert float(m.training_metrics.auc) > 0.7
    assert mx.counter_value("frame_prefetch_overlap_seconds") > 0


def test_gbm_streaming_kill_and_resume_matches_uninterrupted(tmp_path):
    """PR-10/PR-2 recovery mid-stream: abort at an interval boundary,
    resume from the interval snapshot, land within 1e-6 of the
    uninterrupted streamed run."""
    from h2o3_tpu.models.tree import GBM
    from h2o3_tpu.utils import faults

    ckdir = str(tmp_path)
    kw = dict(max_depth=3, seed=6, score_tree_interval=2)
    with _env(H2O3_TPU_HBM_WINDOW_BYTES=str(48 * 1024)):
        fr = _frame(3000, 6, seed=29)
        full = GBM(ntrees=6, **kw).train(y="label", training_frame=fr)
        assert cs.LAST_STORE_STATS["n_blocks"] > 1
        with faults.inject(abort={"gbm": 4}):
            with pytest.raises(faults.TrainAbort):
                GBM(ntrees=6, export_checkpoints_dir=ckdir, **kw).train(
                    y="label", training_frame=fr)
        snaps = [f for f in os.listdir(ckdir) if f.startswith("gbm_ckpt")]
        assert snaps, "no interval snapshot was exported mid-stream"
        from h2o3_tpu import persist

        prior = persist.load_model(os.path.join(ckdir, snaps[0]))
        assert prior.output["ntrees_actual"] == 4
        resumed = GBM(ntrees=6, checkpoint=prior.key, **kw).train(
            y="label", training_frame=fr)
    assert resumed.output["ntrees_actual"] == 6
    np.testing.assert_allclose(
        resumed.training_metrics.logloss, full.training_metrics.logloss,
        atol=1e-6)
    np.testing.assert_allclose(_p1(resumed, fr), _p1(full, fr), atol=1e-6)
