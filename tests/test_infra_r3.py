"""Round-3 infra: streaming CSV parse, grid parallelism, persist schemes."""

import io
import os

import numpy as np
import pandas as pd
import pytest

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.frame.parse import parse, parse_setup


def _write_csv(tmp_path, n=5000):
    rng = np.random.default_rng(0)
    df = pd.DataFrame(
        {
            "num": rng.normal(size=n),
            "int": rng.integers(0, 100, n).astype(float),
            "cat": rng.choice(["red", "green", "blue"], n),
            "txt": [f"id_{i}" for i in range(n)],
        }
    )
    df.loc[::97, "num"] = np.nan
    df.loc[::101, "cat"] = None
    p = os.path.join(tmp_path, "data.csv")
    df.to_csv(p, index=False)
    return p, df


def test_stream_parse_matches_eager(tmp_path):
    p, df = _write_csv(str(tmp_path))
    setup = parse_setup(p)
    eager = parse(dict(setup), destination_frame="eager_fr")
    setup["stream"] = True
    stream = parse(dict(setup), destination_frame="stream_fr")

    assert stream.nrow == eager.nrow == len(df)
    assert stream.names == eager.names
    np.testing.assert_allclose(
        stream.vec("num").to_numpy(), eager.vec("num").to_numpy(), equal_nan=True
    )
    assert stream.vec("cat").domain == eager.vec("cat").domain
    np.testing.assert_array_equal(
        stream.vec("cat").to_numpy(), eager.vec("cat").to_numpy()
    )


def test_stream_parse_multichunk_domain_union(tmp_path):
    # levels that only appear in later chunks must land in the global domain
    n = 3000
    df = pd.DataFrame({"c": ["early"] * (n // 2) + ["late"] * (n // 2),
                       "v": np.arange(n, dtype=float)})
    p = os.path.join(str(tmp_path), "chunks.csv")
    df.to_csv(p, index=False)
    from h2o3_tpu.frame.parse import parse_stream

    fr = parse_stream([p], {}, chunk_rows=500)
    assert list(fr.vec("c").domain) == ["early", "late"]
    codes = fr.vec("c").to_numpy()
    assert (codes[: n // 2] == 0).all() and (codes[n // 2:] == 1).all()


def test_grid_parallelism_matches_sequential():
    from h2o3_tpu.models import GBM
    from h2o3_tpu.models.grid import GridSearch

    rng = np.random.default_rng(1)
    n = 1200
    X = rng.normal(size=(n, 4))
    y = (X[:, 0] + X[:, 1] ** 2 > 0.5).astype(int)
    df = pd.DataFrame(X, columns=list("abcd"))
    df["y"] = np.where(y == 1, "Y", "N")
    fr = Frame.from_pandas(df)
    hyper = {"max_depth": [2, 3], "ntrees": [5, 10]}

    seq = GridSearch(GBM, hyper, seed=5).train(y="y", training_frame=fr)
    par = GridSearch(GBM, hyper, parallelism=3, seed=5).train(
        y="y", training_frame=fr
    )
    assert len(par.models) == len(seq.models) == 4
    # same hyper combos built (order may differ in completion-order mode)
    key = lambda hv: (hv["max_depth"], hv["ntrees"])
    assert sorted(map(key, par.hyper_values)) == sorted(map(key, seq.hyper_values))
    # identical data + seed -> identical leaderboard AUCs per combo
    seq_by = {key(hv): m.training_metrics.value("auc")
              for hv, m in zip(seq.hyper_values, seq.models)}
    par_by = {key(hv): m.training_metrics.value("auc")
              for hv, m in zip(par.hyper_values, par.models)}
    for k in seq_by:
        np.testing.assert_allclose(seq_by[k], par_by[k], rtol=1e-5)


def test_grid_parallel_respects_max_models():
    from h2o3_tpu.models import GBM
    from h2o3_tpu.models.grid import GridSearch

    rng = np.random.default_rng(2)
    df = pd.DataFrame(rng.normal(size=(400, 3)), columns=list("abc"))
    df["y"] = rng.normal(size=400)
    fr = Frame.from_pandas(df)
    g = GridSearch(
        GBM, {"max_depth": [2, 3, 4], "ntrees": [3, 5]},
        search_criteria={"strategy": "RandomDiscrete", "max_models": 3, "seed": 7},
        parallelism=2,
    ).train(y="y", training_frame=fr)
    assert len(g.models) == 3


def test_persist_missing_cloud_sdk_is_clean():
    from h2o3_tpu.persist import _backend_for

    has_boto = True
    try:
        import boto3  # noqa: F401
    except ImportError:
        has_boto = False
    if has_boto:
        pytest.skip("boto3 present in image; gate untestable")
    with pytest.raises(ValueError, match="s3"):
        _backend_for("s3://bucket/key")


def test_persist_custom_backend_roundtrip():
    from h2o3_tpu import persist
    from h2o3_tpu.models import GLM

    store: dict[str, bytes] = {}

    class MemBackend(persist.PersistBackend):
        def open_read(self, path):
            return io.BytesIO(store[path])

        def open_write(self, path):
            class _W(io.BytesIO):
                def close(s):
                    store[path] = s.getvalue()
                    io.BytesIO.close(s)

                def __exit__(s, *a):
                    s.close()

            return _W()

    persist.register_backend("mem", MemBackend())
    rng = np.random.default_rng(3)
    df = pd.DataFrame({"x": rng.normal(size=300)})
    df["y"] = 2 * df["x"] + 0.1 * rng.normal(size=300)
    fr = Frame.from_pandas(df)
    m = GLM(lambda_=0.0).train(y="y", training_frame=fr)
    persist.save_model(m, "mem://models/m1")
    m2 = persist.load_model("mem://models/m1")
    p1 = m.predict(fr).vec("predict").to_numpy()
    p2 = m2.predict(fr).vec("predict").to_numpy()
    np.testing.assert_allclose(p1, p2)


def test_sklearn_proba_aligns_with_classes_for_numeric_labels():
    from sklearn.metrics import log_loss

    from h2o3_tpu.sklearn import H2OGradientBoostingClassifier

    rng = np.random.default_rng(9)
    n = 1200
    X = rng.normal(size=(n, 3))
    y = np.where(X[:, 0] > 0, 10, 2)  # lexicographic order '10' < '2'
    m = H2OGradientBoostingClassifier(ntrees=30, max_depth=3, seed=1).fit(X, y)
    assert list(m.classes_) == [10, 2]  # domain order, not numeric order
    proba = m.predict_proba(X)
    # column i must be P(classes_[i]): the class-10 column dominates when
    # x0>0 (alignment is the property under test, not calibration)
    i10 = list(m.classes_).index(10)
    i2 = list(m.classes_).index(2)
    assert (proba[X[:, 0] > 0.5, i10] > proba[X[:, 0] > 0.5, i2]).all()
    # sklearn's log_loss sorts its labels; feed columns in that sorted order
    srt = np.argsort(m.classes_)
    aligned = log_loss(y, proba[:, srt], labels=sorted(m.classes_))
    flipped = log_loss(y, proba[:, srt[::-1]], labels=sorted(m.classes_))
    assert aligned < 0.3 < flipped  # misalignment would flip these


def test_native_scorer_bit_identical_to_numpy():
    import os
    import tempfile

    from h2o3_tpu import native
    from h2o3_tpu.genmodel import MojoModel
    from h2o3_tpu.models import GBM
    from h2o3_tpu.models.export import export_mojo

    if not native.available():
        pytest.skip("no C++ toolchain in this environment")
    rng = np.random.default_rng(4)
    n = 5000
    X = rng.normal(size=(n, 6)).astype(np.float32)
    cat = rng.choice(list("abc"), n)
    eta = X[:, 0] * 2 + X[:, 1] ** 2 + (cat == "b") - 1
    df = pd.DataFrame(X, columns=[f"f{i}" for i in range(6)])
    df["cat"] = cat
    df["y"] = np.where(rng.random(n) < 1 / (1 + np.exp(-eta)), "Y", "N")
    fr = Frame.from_pandas(df)
    m = GBM(ntrees=10, max_depth=4, seed=2).train(y="y", training_frame=fr)
    p = tempfile.mktemp(suffix=".zip")
    export_mojo(m, p)
    mojo = MojoModel.load(p)
    table = mojo._rows_to_table(df.drop(columns="y"))
    old = os.environ.get("H2O3_TPU_NATIVE")
    try:
        os.environ["H2O3_TPU_NATIVE"] = "0"
        ref = np.asarray(mojo.score_raw(table))
        os.environ["H2O3_TPU_NATIVE"] = "1"
        got = np.asarray(mojo.score_raw(table))
    finally:
        if old is None:
            os.environ.pop("H2O3_TPU_NATIVE", None)
        else:
            os.environ["H2O3_TPU_NATIVE"] = old
    np.testing.assert_array_equal(ref, got)
    os.unlink(p)


@pytest.mark.slow
def test_automl_exploitation_step():
    from h2o3_tpu.automl.automl import AutoML

    rng = np.random.default_rng(12)
    n = 800
    X = rng.normal(size=(n, 3))
    df = pd.DataFrame(X, columns=list("abc"))
    df["y"] = np.where(X[:, 0] + X[:, 1] ** 2 > 0.5, "Y", "N")
    fr = Frame.from_pandas(df)
    aml = AutoML(
        max_models=3, nfolds=0, seed=3, exploitation_ratio=0.1,
        include_algos=["GBM"], max_runtime_secs=600.0,
    )
    aml.train(y="y", training_frame=fr)
    stages = [e["stage"] for e in aml.event_log]
    assert "exploit" in stages  # the lr-annealing refinement ran
    # the refined model really uses annealed settings
    exploit_msg = next(e for e in aml.event_log if e["stage"] == "exploit")
    assert "exploit_gbm_lr_annealing" in exploit_msg["message"]


def test_max_runtime_secs_truncates_gracefully():
    from h2o3_tpu.models import GBM

    rng = np.random.default_rng(13)
    n = 20000
    X = rng.normal(size=(n, 8))
    df = pd.DataFrame(X, columns=[f"f{i}" for i in range(8)])
    df["y"] = X[:, 0] * 2 + rng.normal(size=n)
    fr = Frame.from_pandas(df)
    m = GBM(ntrees=500, max_depth=5, seed=1, max_runtime_secs=2.0,
            score_tree_interval=1).train(y="y", training_frame=fr)
    # the budget truncates the forest but the partial model is kept + scored
    assert 1 <= m.output["ntrees_actual"] < 500
    assert np.isfinite(m.training_metrics.value("rmse"))
