"""Round-3 infra: streaming CSV parse, grid parallelism, persist schemes."""

import io
import os

import numpy as np
import pandas as pd
import pytest

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.frame.parse import parse, parse_setup


def _write_csv(tmp_path, n=5000):
    rng = np.random.default_rng(0)
    df = pd.DataFrame(
        {
            "num": rng.normal(size=n),
            "int": rng.integers(0, 100, n).astype(float),
            "cat": rng.choice(["red", "green", "blue"], n),
            "txt": [f"id_{i}" for i in range(n)],
        }
    )
    df.loc[::97, "num"] = np.nan
    df.loc[::101, "cat"] = None
    p = os.path.join(tmp_path, "data.csv")
    df.to_csv(p, index=False)
    return p, df


def test_stream_parse_matches_eager(tmp_path):
    p, df = _write_csv(str(tmp_path))
    setup = parse_setup(p)
    eager = parse(dict(setup), destination_frame="eager_fr")
    setup["stream"] = True
    stream = parse(dict(setup), destination_frame="stream_fr")

    assert stream.nrow == eager.nrow == len(df)
    assert stream.names == eager.names
    np.testing.assert_allclose(
        stream.vec("num").to_numpy(), eager.vec("num").to_numpy(), equal_nan=True
    )
    assert stream.vec("cat").domain == eager.vec("cat").domain
    np.testing.assert_array_equal(
        stream.vec("cat").to_numpy(), eager.vec("cat").to_numpy()
    )


def test_stream_parse_multichunk_domain_union(tmp_path):
    # levels that only appear in later chunks must land in the global domain
    n = 3000
    df = pd.DataFrame({"c": ["early"] * (n // 2) + ["late"] * (n // 2),
                       "v": np.arange(n, dtype=float)})
    p = os.path.join(str(tmp_path), "chunks.csv")
    df.to_csv(p, index=False)
    from h2o3_tpu.frame.parse import parse_stream

    fr = parse_stream([p], {}, chunk_rows=500)
    assert list(fr.vec("c").domain) == ["early", "late"]
    codes = fr.vec("c").to_numpy()
    assert (codes[: n // 2] == 0).all() and (codes[n // 2:] == 1).all()


def test_grid_parallelism_matches_sequential():
    from h2o3_tpu.models import GBM
    from h2o3_tpu.models.grid import GridSearch

    rng = np.random.default_rng(1)
    n = 1200
    X = rng.normal(size=(n, 4))
    y = (X[:, 0] + X[:, 1] ** 2 > 0.5).astype(int)
    df = pd.DataFrame(X, columns=list("abcd"))
    df["y"] = np.where(y == 1, "Y", "N")
    fr = Frame.from_pandas(df)
    hyper = {"max_depth": [2, 3], "ntrees": [5, 10]}

    seq = GridSearch(GBM, hyper, seed=5).train(y="y", training_frame=fr)
    par = GridSearch(GBM, hyper, parallelism=3, seed=5).train(
        y="y", training_frame=fr
    )
    assert len(par.models) == len(seq.models) == 4
    # same hyper combos built (order may differ in completion-order mode)
    key = lambda hv: (hv["max_depth"], hv["ntrees"])
    assert sorted(map(key, par.hyper_values)) == sorted(map(key, seq.hyper_values))
    # identical data + seed -> identical leaderboard AUCs per combo
    seq_by = {key(hv): m.training_metrics.value("auc")
              for hv, m in zip(seq.hyper_values, seq.models)}
    par_by = {key(hv): m.training_metrics.value("auc")
              for hv, m in zip(par.hyper_values, par.models)}
    for k in seq_by:
        np.testing.assert_allclose(seq_by[k], par_by[k], rtol=1e-5)


def test_grid_parallel_respects_max_models():
    from h2o3_tpu.models import GBM
    from h2o3_tpu.models.grid import GridSearch

    rng = np.random.default_rng(2)
    df = pd.DataFrame(rng.normal(size=(400, 3)), columns=list("abc"))
    df["y"] = rng.normal(size=400)
    fr = Frame.from_pandas(df)
    g = GridSearch(
        GBM, {"max_depth": [2, 3, 4], "ntrees": [3, 5]},
        search_criteria={"strategy": "RandomDiscrete", "max_models": 3, "seed": 7},
        parallelism=2,
    ).train(y="y", training_frame=fr)
    assert len(g.models) == 3


def test_persist_missing_cloud_sdk_is_clean():
    from h2o3_tpu.persist import _backend_for

    has_boto = True
    try:
        import boto3  # noqa: F401
    except ImportError:
        has_boto = False
    if has_boto:
        pytest.skip("boto3 present in image; gate untestable")
    with pytest.raises(ValueError, match="s3"):
        _backend_for("s3://bucket/key")


def test_persist_custom_backend_roundtrip():
    from h2o3_tpu import persist
    from h2o3_tpu.models import GLM

    store: dict[str, bytes] = {}

    class MemBackend(persist.PersistBackend):
        def open_read(self, path):
            return io.BytesIO(store[path])

        def open_write(self, path):
            class _W(io.BytesIO):
                def close(s):
                    store[path] = s.getvalue()
                    io.BytesIO.close(s)

                def __exit__(s, *a):
                    s.close()

            return _W()

    persist.register_backend("mem", MemBackend())
    rng = np.random.default_rng(3)
    df = pd.DataFrame({"x": rng.normal(size=300)})
    df["y"] = 2 * df["x"] + 0.1 * rng.normal(size=300)
    fr = Frame.from_pandas(df)
    m = GLM(lambda_=0.0).train(y="y", training_frame=fr)
    persist.save_model(m, "mem://models/m1")
    m2 = persist.load_model("mem://models/m1")
    p1 = m.predict(fr).vec("predict").to_numpy()
    p2 = m2.predict(fr).vec("predict").to_numpy()
    np.testing.assert_allclose(p1, p2)


def test_sklearn_proba_aligns_with_classes_for_numeric_labels():
    from sklearn.metrics import log_loss

    from h2o3_tpu.sklearn import H2OGradientBoostingClassifier

    rng = np.random.default_rng(9)
    n = 1200
    X = rng.normal(size=(n, 3))
    y = np.where(X[:, 0] > 0, 10, 2)  # lexicographic order '10' < '2'
    m = H2OGradientBoostingClassifier(ntrees=10, max_depth=3, seed=1).fit(X, y)
    assert list(m.classes_) == [10, 2]  # domain order, not numeric order
    proba = m.predict_proba(X)
    # column i must be P(classes_[i]): the class-10 column is high when x0>0
    i10 = list(m.classes_).index(10)
    assert proba[X[:, 0] > 1.0, i10].mean() > 0.9
    assert log_loss(y, proba, labels=list(m.classes_)) < 0.3
