"""DeepLearning tests — upstream ``hex/deeplearning`` scenario style
[UNVERIFIED upstream path]; sync-SGD successor of the Hogwild trainer."""

import numpy as np
import pandas as pd
import pytest

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.deeplearning import DeepLearning


def test_dl_classification_learns_xor():
    rng = np.random.default_rng(0)
    n = 4000
    X = rng.uniform(-1, 1, size=(n, 2))
    y = (X[:, 0] * X[:, 1] > 0).astype(int)
    df = pd.DataFrame(X, columns=["a", "b"])
    df["y"] = np.where(y == 1, "pos", "neg")
    fr = Frame.from_pandas(df)
    m = DeepLearning(
        hidden=(32, 32), epochs=60, mini_batch_size=256, seed=1
    ).train(y="y", training_frame=fr)
    assert m.training_metrics.auc > 0.95  # XOR is not linearly separable
    pred = m.predict(fr)
    assert pred.names == ["predict", "neg", "pos"]


def test_dl_regression():
    rng = np.random.default_rng(1)
    n = 3000
    X = rng.normal(size=(n, 3))
    y = X[:, 0] ** 2 + np.sin(X[:, 1]) + 0.1 * rng.normal(size=n)
    df = pd.DataFrame(X, columns=list("abc"))
    df["y"] = y
    fr = Frame.from_pandas(df)
    m = DeepLearning(hidden=(64, 64), epochs=40, mini_batch_size=256, seed=2).train(
        y="y", training_frame=fr
    )
    assert m.training_metrics.r2 > 0.8


def test_dl_reproducible():
    rng = np.random.default_rng(2)
    df = pd.DataFrame(
        {"a": rng.normal(size=500), "y": rng.normal(size=500)}
    )
    fr = Frame.from_pandas(df)
    kw = dict(hidden=(8,), epochs=3, mini_batch_size=64, seed=7)
    m1 = DeepLearning(**kw).train(y="y", training_frame=fr)
    m2 = DeepLearning(**kw).train(y="y", training_frame=fr)
    np.testing.assert_allclose(
        m1._predict_raw(fr), m2._predict_raw(fr), rtol=1e-6
    )


def test_dl_multiclass_and_l2():
    rng = np.random.default_rng(3)
    n = 3000
    X = rng.normal(size=(n, 2))
    y = (np.arctan2(X[:, 1], X[:, 0]) // (2 * np.pi / 3 + 1e-9) + 1).astype(int)
    df = pd.DataFrame(X, columns=["a", "b"])
    df["y"] = np.array(["c0", "c1", "c2"])[np.clip(y, 0, 2)]
    fr = Frame.from_pandas(df)
    m = DeepLearning(hidden=(32,), epochs=30, mini_batch_size=256, l2=1e-5, seed=4).train(
        y="y", training_frame=fr
    )
    assert m.training_metrics.classification_error < 0.2
