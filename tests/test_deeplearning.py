"""DeepLearning tests — upstream ``hex/deeplearning`` scenario style
[UNVERIFIED upstream path]; sync-SGD successor of the Hogwild trainer."""

import numpy as np
import pandas as pd
import pytest

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.deeplearning import DeepLearning


def test_dl_classification_learns_xor():
    rng = np.random.default_rng(0)
    n = 4000
    X = rng.uniform(-1, 1, size=(n, 2))
    y = (X[:, 0] * X[:, 1] > 0).astype(int)
    df = pd.DataFrame(X, columns=["a", "b"])
    df["y"] = np.where(y == 1, "pos", "neg")
    fr = Frame.from_pandas(df)
    m = DeepLearning(
        hidden=(32, 32), epochs=60, mini_batch_size=256, seed=1
    ).train(y="y", training_frame=fr)
    assert m.training_metrics.auc > 0.95  # XOR is not linearly separable
    pred = m.predict(fr)
    assert pred.names == ["predict", "neg", "pos"]


def test_dl_regression():
    rng = np.random.default_rng(1)
    n = 3000
    X = rng.normal(size=(n, 3))
    y = X[:, 0] ** 2 + np.sin(X[:, 1]) + 0.1 * rng.normal(size=n)
    df = pd.DataFrame(X, columns=list("abc"))
    df["y"] = y
    fr = Frame.from_pandas(df)
    m = DeepLearning(hidden=(64, 64), epochs=40, mini_batch_size=256, seed=2).train(
        y="y", training_frame=fr
    )
    assert m.training_metrics.r2 > 0.8


def test_dl_reproducible():
    rng = np.random.default_rng(2)
    df = pd.DataFrame(
        {"a": rng.normal(size=500), "y": rng.normal(size=500)}
    )
    fr = Frame.from_pandas(df)
    kw = dict(hidden=(8,), epochs=3, mini_batch_size=64, seed=7)
    m1 = DeepLearning(**kw).train(y="y", training_frame=fr)
    m2 = DeepLearning(**kw).train(y="y", training_frame=fr)
    np.testing.assert_allclose(
        m1._predict_raw(fr), m2._predict_raw(fr), rtol=1e-6
    )


def test_dl_multiclass_and_l2():
    rng = np.random.default_rng(3)
    n = 3000
    X = rng.normal(size=(n, 2))
    y = (np.arctan2(X[:, 1], X[:, 0]) // (2 * np.pi / 3 + 1e-9) + 1).astype(int)
    df = pd.DataFrame(X, columns=["a", "b"])
    df["y"] = np.array(["c0", "c1", "c2"])[np.clip(y, 0, 2)]
    fr = Frame.from_pandas(df)
    m = DeepLearning(hidden=(32,), epochs=30, mini_batch_size=256, l2=1e-5, seed=4).train(
        y="y", training_frame=fr
    )
    assert m.training_metrics.classification_error < 0.2


def test_autoencoder_learns_structure_and_scores_anomalies():
    """Autoencoder (upstream autoencoder=true / H2OAutoEncoderEstimator):
    reconstruction improves with training, and rows OFF the training
    manifold score higher Reconstruction.MSE than rows on it."""
    from h2o3_tpu.estimators import H2OAutoEncoderEstimator

    rng = np.random.default_rng(8)
    n = 2000
    # 2-D latent structure embedded in 6 dims
    z = rng.normal(size=(n, 2))
    W = rng.normal(size=(2, 6))
    X = z @ W + rng.normal(size=(n, 6)) * 0.05
    df = pd.DataFrame(X, columns=[f"f{i}" for i in range(6)])
    fr = Frame.from_pandas(df)

    ae = H2OAutoEncoderEstimator(hidden=(8, 2, 8), epochs=30,
                                 mini_batch_size=64, seed=4)
    ae.train(training_frame=fr)
    mse_trained = ae.mse()
    assert np.isfinite(mse_trained) and mse_trained < 0.5  # standardized scale

    # anomalies: rows far off the latent plane reconstruct worse
    X_out = rng.normal(size=(200, 6)) * 3.0
    df_out = pd.DataFrame(X_out, columns=df.columns)
    a_in = ae.anomaly(fr).vec("Reconstruction.MSE").to_numpy()
    a_out = ae.anomaly(Frame.from_pandas(df_out)).vec("Reconstruction.MSE").to_numpy()
    assert np.median(a_out) > 4 * np.median(a_in)

    # predict() returns the reconstruction columns, upstream layout
    rec = ae.predict(fr)
    assert rec.names == [f"reconstr_{c}" for c in ae.model.output["expanded_names"]]
    assert rec.nrow == n


def test_autoencoder_anomaly_over_rest():
    import json as _json
    import urllib.request as _rq

    from h2o3_tpu.api.server import start_server
    from h2o3_tpu.cluster.registry import DKV
    from h2o3_tpu.estimators import H2OAutoEncoderEstimator

    rng = np.random.default_rng(3)
    df = pd.DataFrame(rng.normal(size=(300, 4)), columns=list("abcd"))
    fr = Frame.from_pandas(df)
    DKV.put("ae_fr", fr)
    ae = H2OAutoEncoderEstimator(hidden=(4,), epochs=2, seed=1)
    ae.train(training_frame=fr)
    s = start_server(port=0)
    body = _json.dumps({"reconstruction_error": True}).encode()
    r = _rq.Request(
        f"{s.url}/3/Predictions/models/{ae.model_id}/frames/ae_fr",
        data=body, headers={"Content-Type": "application/json"}, method="POST")
    out = _json.loads(_rq.urlopen(r).read())
    key = out["predictions_frame"]["name"]
    got = _json.loads(_rq.urlopen(f"{s.url}/3/Frames/{key}").read())
    assert [c["label"] for c in got["frames"][0]["columns"]] == ["Reconstruction.MSE"]


def test_autoencoder_checkpoint_and_tiny_frame():
    """AE checkpoint continuation works like supervised DL, tiny frames
    (nrow < mini_batch_size) train without over-counting row 0, and
    model_performance on an AE returns reconstruction metrics instead of
    crashing on the missing response."""
    from h2o3_tpu.cluster.registry import DKV
    from h2o3_tpu.models.deeplearning import DeepLearning

    rng = np.random.default_rng(2)
    df = pd.DataFrame(rng.normal(size=(20, 3)), columns=list("abc"))
    fr = Frame.from_pandas(df)
    m1 = DeepLearning(autoencoder=True, hidden=(4,), epochs=3, seed=6,
                      mini_batch_size=32).train(training_frame=fr)
    assert np.isfinite(m1.training_metrics.mse)
    perf = m1.model_performance(fr)
    assert abs(perf.mse - m1.training_metrics.mse) < 1e-9

    m2 = DeepLearning(autoencoder=True, hidden=(4,), epochs=6, seed=6,
                      mini_batch_size=32, checkpoint=m1.key,
                      ).train(training_frame=fr)
    uninterrupted = DeepLearning(autoencoder=True, hidden=(4,), epochs=6,
                                 seed=6, mini_batch_size=32,
                                 ).train(training_frame=fr)
    assert abs(m2.training_metrics.mse - uninterrupted.training_metrics.mse) < 1e-6
    with pytest.raises(RuntimeError, match="cross-validation"):
        DeepLearning(autoencoder=True, nfolds=3).train(training_frame=fr)
    DKV.remove(m1.key); DKV.remove(m2.key)


def test_dl_model_summary_layer_table():
    rng = np.random.default_rng(1)
    df = pd.DataFrame({"a": rng.normal(size=300), "b": rng.normal(size=300)})
    df["y"] = np.where(df.a > 0, "x", "z")
    fr = Frame.from_pandas(df)
    from h2o3_tpu.models.deeplearning import DeepLearning

    m = DeepLearning(hidden=(7, 5), epochs=1, seed=2).train(
        y="y", training_frame=fr)
    rows = m.model_summary()
    assert [r["units"] for r in rows] == [2, 7, 5, 2]
    assert rows[0]["type"] == "Input" and rows[-1]["type"] == "Softmax"
