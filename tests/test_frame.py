"""Frame/Vec core tests — modeled on upstream ``water/fvec/FrameTest.java``
scenarios [UNVERIFIED upstream path] recast for the sharded-array frame."""

import numpy as np
import pandas as pd
import pytest

import h2o3_tpu
from h2o3_tpu.frame.frame import CAT, INT, NUM, STR, Frame


def _toy_df(n=1000, seed=0):
    rng = np.random.default_rng(seed)
    return pd.DataFrame(
        {
            "x": rng.normal(size=n),
            "i": rng.integers(0, 100, size=n),
            "c": rng.choice(["a", "b", "c"], size=n),
            "y": rng.choice(["yes", "no"], size=n),
        }
    )


def test_from_pandas_shapes_and_types():
    df = _toy_df(1000)
    fr = Frame.from_pandas(df)
    assert fr.nrow == 1000
    assert fr.ncol == 4
    assert fr.types == {"x": NUM, "i": INT, "c": CAT, "y": CAT}
    assert fr.npad % 8 == 0 and fr.npad >= 1000
    assert fr.vec("c").domain == ("a", "b", "c")


def test_roundtrip_to_pandas():
    df = _toy_df(500)
    fr = Frame.from_pandas(df)
    back = fr.to_pandas()
    np.testing.assert_allclose(back["x"].to_numpy(), df["x"].to_numpy(), rtol=1e-6)
    assert (back["c"] == df["c"]).all()


def test_missing_values():
    df = pd.DataFrame({"x": [1.0, np.nan, 3.0, np.nan], "c": ["a", None, "b", "a"]})
    fr = Frame.from_pandas(df)
    assert fr.vec("x").na_count() == 2
    assert fr.vec("c").na_count() == 1
    codes = fr.vec("c").to_numpy()
    assert codes[1] == -1


def test_rollup_stats_match_numpy():
    df = _toy_df(2000, seed=3)
    fr = Frame.from_pandas(df)
    v = fr.vec("x")
    x = df["x"].to_numpy()
    assert v.mean() == pytest.approx(x.mean(), rel=1e-5)
    assert v.sigma() == pytest.approx(x.std(ddof=1), rel=1e-4)
    assert v.min() == pytest.approx(x.min(), rel=1e-6)
    assert v.max() == pytest.approx(x.max(), rel=1e-6)


def test_cat_level_counts():
    df = _toy_df(1200, seed=5)
    fr = Frame.from_pandas(df)
    counts = fr.vec("c").stats()["levelCounts"]
    expected = df["c"].value_counts().reindex(["a", "b", "c"]).to_numpy()
    np.testing.assert_array_equal(np.asarray(counts), expected)


def test_selection_and_cbind_drop():
    fr = Frame.from_pandas(_toy_df(100))
    sub = fr[["x", "c"]]
    assert sub.names == ["x", "c"]
    assert sub.nrow == 100
    d = fr.drop("y")
    assert d.names == ["x", "i", "c"]
    cb = sub.cbind(fr[["y"]])
    assert cb.names == ["x", "c", "y"]


def test_split_frame():
    fr = Frame.from_pandas(_toy_df(5000, seed=7))
    tr, te = fr.split_frame([0.8], seed=99)
    assert tr.nrow + te.nrow == 5000
    assert abs(tr.nrow / 5000 - 0.8) < 0.03
    assert tr.types == fr.types


def test_row_mask_counts_rows():
    fr = Frame.from_pandas(_toy_df(777))
    m = np.asarray(fr.row_mask())
    assert m.sum() == 777
    assert len(m) == fr.npad


def test_registry_roundtrip():
    fr = Frame.from_pandas(_toy_df(10), destination_frame="myframe")
    assert h2o3_tpu.get_frame("myframe") is fr
    assert "myframe" in h2o3_tpu.ls()
    h2o3_tpu.remove("myframe")
    assert h2o3_tpu.get_frame("myframe") is None


def test_sharding_is_row_partitioned():
    import jax

    fr = Frame.from_pandas(_toy_df(4000))
    arr = fr.vec("x").data
    assert len(arr.sharding.device_set) == 8


def test_subset_preserves_domain():
    df = pd.DataFrame({"c": ["a", "b", "c", "a", "b", "c"] * 10, "x": np.arange(60.0)})
    fr = Frame.from_pandas(df)
    # subset containing no "a": domain must survive
    sub = fr.subset_rows(np.array([1, 2, 4, 5]))
    assert sub.vec("c").domain == ("a", "b", "c")
    np.testing.assert_array_equal(sub.vec("c").to_numpy(), [1, 2, 1, 2])


def test_rbind_unions_domains():
    a = Frame.from_pandas(pd.DataFrame({"c": ["a", "b"], "x": [1.0, 2.0]}))
    b = Frame.from_pandas(pd.DataFrame({"c": ["c", "b"], "x": [3.0, 4.0]}))
    ab = a.rbind(b)
    assert ab.nrow == 4
    assert ab.vec("c").domain == ("a", "b", "c")
    np.testing.assert_array_equal(ab.vec("c").to_numpy(), [0, 1, 2, 1])


def test_time_column_exact_roundtrip():
    ts = pd.to_datetime(["2024-01-01 12:34:56.789", "2025-06-30 01:02:03.004"])
    df = pd.DataFrame({"t": ts})
    fr = Frame.from_pandas(df)
    assert fr.types["t"] == "time"
    ms = fr.vec("t").to_numpy()
    np.testing.assert_allclose(
        ms, ts.astype("datetime64[ms]").astype("int64").to_numpy(), rtol=0, atol=0.5
    )
    sub = fr.subset_rows(np.array([1]))
    np.testing.assert_allclose(sub.vec("t").to_numpy(), [ms[1]], atol=0.5)


def test_temporaries_not_registered():
    import h2o3_tpu

    before = set(h2o3_tpu.ls())
    fr = Frame.from_pandas(_toy_df(100))
    _ = fr[["x"]]
    _ = fr.split_frame([0.5])
    assert set(h2o3_tpu.ls()) == before


def test_big_column_count_exact():
    # int32 count path: no phantom NAs from f32 accumulation
    n = 1_000_000
    fr = Frame.from_pandas(pd.DataFrame({"x": np.ones(n, dtype=np.float32)}))
    assert fr.vec("x").na_count() == 0
    assert fr.vec("x").mean() == pytest.approx(1.0, abs=1e-6)


# ---------------------------------------------------------------------------
# Vec flavors (FileVec / CategoricalWrappedVec successors)


def test_lazy_import_defers_materialization(tmp_path):
    import os

    import pandas as pd

    import h2o3_tpu
    from h2o3_tpu.frame.lazy import LazyVec

    rng = np.random.default_rng(0)
    n = 4000
    df = pd.DataFrame(
        {"a": rng.normal(size=n), "b": rng.normal(size=n),
         "c": rng.choice(["x", "y"], n), "unused": rng.normal(size=n)}
    )
    p = os.path.join(str(tmp_path), "wide.csv")
    df.to_csv(p, index=False)
    fr = h2o3_tpu.import_file(p, lazy=True)
    assert all(isinstance(fr.vec(nm), LazyVec) for nm in fr.names)
    assert not any(fr.vec(nm).is_materialized for nm in fr.names)
    # touching one column materializes ONLY that column
    a = fr.vec("a").to_numpy()
    np.testing.assert_allclose(a, df["a"], rtol=1e-6)
    assert fr.vec("a").is_materialized
    assert not fr.vec("unused").is_materialized
    # categorical domain resolves on demand
    assert fr.vec("c").levels() == ["x", "y"]
    assert fr.vec("c").is_materialized


def test_lazy_frame_trains_a_model(tmp_path):
    import os

    import pandas as pd

    import h2o3_tpu
    from h2o3_tpu.models import GLM

    rng = np.random.default_rng(1)
    n = 2000
    df = pd.DataFrame({"x": rng.normal(size=n), "junk": rng.normal(size=n)})
    df["y"] = 3 * df["x"] + 0.1 * rng.normal(size=n)
    p = os.path.join(str(tmp_path), "lz.csv")
    df.to_csv(p, index=False)
    fr = h2o3_tpu.import_file(p, lazy=True)
    m = GLM(lambda_=0.0).train(y="y", x=["x"], training_frame=fr)
    assert abs(m.coef["x"] - 3.0) < 0.05
    assert not fr.vec("junk").is_materialized  # untouched column stayed cold


def test_wrapped_cat_vec_remaps_domain():
    import pandas as pd

    from h2o3_tpu.frame.frame import Frame
    from h2o3_tpu.frame.lazy import wrap_domain

    df = pd.DataFrame({"c": ["b", "a", "c", "a", None]})
    fr = Frame.from_pandas(df, column_types={"c": "enum"})
    base = fr.vec("c")
    assert list(base.domain) == ["a", "b", "c"]
    w = wrap_domain(base, ["c", "b", "a", "zzz"])
    codes = np.asarray(w.data)[: w.nrow]
    # b->1, a->2, c->0, NA stays -1
    np.testing.assert_array_equal(codes, [1, 2, 0, 2, -1])
    assert w.cardinality == 4


def test_enum_codes_use_narrowest_dtype():
    """Chunk-compression-zoo analog: enum device storage picks the
    narrowest signed int that fits the domain, NA (-1) preserved."""
    import pandas as pd

    small = h2o3_tpu.upload_file(pd.DataFrame({"g": ["a", "b", None, "a"]}))
    v = small.vec("g")
    assert v.data.dtype == np.int8
    assert v.to_numpy().tolist() == [0, 1, -1, 0]

    wide = h2o3_tpu.upload_file(
        pd.DataFrame({"g": [f"lvl{i:04d}" for i in range(300)] * 3})
    )
    assert wide.vec("g").data.dtype == np.int16
    assert wide.vec("g").to_numpy().max() == 299
