"""h2o.explain successor: PDP/ICE/varimp/SHAP-summary/residuals artifacts."""

import numpy as np
import pandas as pd

from h2o3_tpu import explain as ex
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models import GBM, GLM


def _frame(n=1500, seed=0):
    rng = np.random.default_rng(seed)
    x0 = rng.normal(size=n)
    x1 = rng.normal(size=n)
    noise = rng.normal(size=n)
    y = 2 * np.sin(x0) + x1 + 0.1 * noise
    return Frame.from_pandas(pd.DataFrame({"x0": x0, "x1": x1, "y": y}))


def test_varimp_and_heatmap():
    fr = _frame()
    g = GBM(ntrees=15, max_depth=4, seed=1).train(y="y", training_frame=fr)
    l = GLM(lambda_=0.0).train(y="y", training_frame=fr)
    vg = ex.varimp(g)
    assert set(vg) == {"x0", "x1"}
    assert max(vg.values()) == 1.0  # normalized
    hm = ex.varimp_heatmap([g, l])
    assert hm["matrix"].shape == (2, 2)
    assert hm["features"] == ["x0", "x1"]


def test_pdp_recovers_shape():
    fr = _frame()
    g = GBM(ntrees=25, max_depth=4, seed=2).train(y="y", training_frame=fr)
    pdp = ex.partial_dependence(g, fr, "x0", nbins=9)
    vals = np.asarray(pdp["values"])
    mr = np.asarray(pdp["mean_response"])
    # 2*sin(x) is increasing then decreasing on [-2, 2]: the PDP must rise
    # from the left edge to the middle region
    assert mr[np.argmin(np.abs(vals - 1.4))] > mr[0] + 0.5
    ic = ex.ice(g, fr, "x0", nbins=5, sample_rows=10)
    assert ic["curves"].shape == (10, 5)


def test_shap_summary_and_residuals():
    fr = _frame()
    g = GBM(ntrees=15, max_depth=4, seed=3).train(y="y", training_frame=fr)
    ss = ex.shap_summary(g, fr)
    assert ss["features"][0] == "x0"  # dominant feature leads
    assert ss["contributions"].shape[0] == fr.nrow
    ra = ex.residual_analysis(g, fr)
    assert ra["rmse"] < 0.6
    assert len(ra["residuals"]) == fr.nrow


def test_explain_driver_end_to_end():
    fr = _frame()
    g = GBM(ntrees=10, max_depth=3, seed=4).train(y="y", training_frame=fr)
    l = GLM(lambda_=0.0).train(y="y", training_frame=fr)
    out = ex.explain([g, l], fr)
    assert "varimp" in out and "pdp" in out
    assert "model_correlation" in out
    corr = out["model_correlation"]["correlation"]
    assert corr[0, 1] > 0.7  # both models learn the same signal
    assert "residual_analysis" in out


def test_plot_surface_renders(tmp_path):
    """Every plotting wrapper renders a Figure headlessly and saves a PNG
    (the h2o-py varimp_plot/pd_plot/roc_plot/learning_curve_plot surface)."""
    from h2o3_tpu import explain as ex
    from h2o3_tpu.models import GBM

    rng = np.random.default_rng(3)
    n = 600
    df = pd.DataFrame({
        "a": rng.normal(size=n),
        "b": rng.choice(["u", "v", "w"], n),
    })
    df["y"] = np.where(df.a + (df.b == "u") > 0.3, "T", "F")
    fr = Frame.from_pandas(df)
    m = GBM(ntrees=5, max_depth=3, seed=1).train(y="y", training_frame=fr)

    for name, call in {
        "vi.png": lambda p: ex.varimp_plot(m, save=p),
        "pd_num.png": lambda p: ex.pd_plot(m, fr, "a", nbins=6, save=p),
        "pd_cat.png": lambda p: ex.pd_plot(m, fr, "b", save=p),
        "roc.png": lambda p: ex.roc_plot(m, save=p),
        "lc.png": lambda p: ex.learning_curve_plot(m, save=p),
        "shap.png": lambda p: ex.shap_summary_plot(m, fr, save=p),
    }.items():
        p = str(tmp_path / name)
        fig = call(p)
        assert fig is not None
        assert (tmp_path / name).stat().st_size > 2000, name


def test_roc_plot_without_validation_metrics_errors_clearly():
    import pytest

    from h2o3_tpu import explain as ex
    from h2o3_tpu.models import GBM

    rng = np.random.default_rng(5)
    df = pd.DataFrame({"a": rng.normal(size=200)})
    df["y"] = np.where(df.a > 0, "x", "z")
    m = GBM(ntrees=2, max_depth=2, seed=1).train(
        y="y", training_frame=Frame.from_pandas(df))
    with pytest.raises(ValueError, match="validation"):
        ex.roc_plot(m, valid=True)
