"""h2o.explain successor: PDP/ICE/varimp/SHAP-summary/residuals artifacts."""

import numpy as np
import pandas as pd

from h2o3_tpu import explain as ex
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models import GBM, GLM


def _frame(n=1500, seed=0):
    rng = np.random.default_rng(seed)
    x0 = rng.normal(size=n)
    x1 = rng.normal(size=n)
    noise = rng.normal(size=n)
    y = 2 * np.sin(x0) + x1 + 0.1 * noise
    return Frame.from_pandas(pd.DataFrame({"x0": x0, "x1": x1, "y": y}))


def test_varimp_and_heatmap():
    fr = _frame()
    g = GBM(ntrees=15, max_depth=4, seed=1).train(y="y", training_frame=fr)
    l = GLM(lambda_=0.0).train(y="y", training_frame=fr)
    vg = ex.varimp(g)
    assert set(vg) == {"x0", "x1"}
    assert max(vg.values()) == 1.0  # normalized
    hm = ex.varimp_heatmap([g, l])
    assert hm["matrix"].shape == (2, 2)
    assert hm["features"] == ["x0", "x1"]


def test_pdp_recovers_shape():
    fr = _frame()
    g = GBM(ntrees=25, max_depth=4, seed=2).train(y="y", training_frame=fr)
    pdp = ex.partial_dependence(g, fr, "x0", nbins=9)
    vals = np.asarray(pdp["values"])
    mr = np.asarray(pdp["mean_response"])
    # 2*sin(x) is increasing then decreasing on [-2, 2]: the PDP must rise
    # from the left edge to the middle region
    assert mr[np.argmin(np.abs(vals - 1.4))] > mr[0] + 0.5
    ic = ex.ice(g, fr, "x0", nbins=5, sample_rows=10)
    assert ic["curves"].shape == (10, 5)


def test_shap_summary_and_residuals():
    fr = _frame()
    g = GBM(ntrees=15, max_depth=4, seed=3).train(y="y", training_frame=fr)
    ss = ex.shap_summary(g, fr)
    assert ss["features"][0] == "x0"  # dominant feature leads
    assert ss["contributions"].shape[0] == fr.nrow
    ra = ex.residual_analysis(g, fr)
    assert ra["rmse"] < 0.6
    assert len(ra["residuals"]) == fr.nrow


def test_explain_driver_end_to_end():
    fr = _frame()
    g = GBM(ntrees=10, max_depth=3, seed=4).train(y="y", training_frame=fr)
    l = GLM(lambda_=0.0).train(y="y", training_frame=fr)
    out = ex.explain([g, l], fr)
    assert "varimp" in out and "pdp" in out
    assert "model_correlation" in out
    corr = out["model_correlation"]["correlation"]
    assert corr[0, 1] > 0.7  # both models learn the same signal
    assert "residual_analysis" in out
