"""REST API + Rapids tests — successor of upstream REST/pyunit coverage
(``water.api`` handler tests, Rapids pyunits) [UNVERIFIED upstream paths,
SURVEY.md §4]. A real server on a real port, driven by urllib — no mocks,
matching H2O's "real stack, local topology" strategy."""

import json
import time
import urllib.error
import urllib.parse
import urllib.request

import numpy as np
import pandas as pd
import pytest

import h2o3_tpu
from h2o3_tpu.api.server import start_server
from h2o3_tpu.frame.frame import Frame


@pytest.fixture(scope="module")
def server():
    return start_server(port=0)


def _get(server, path):
    with urllib.request.urlopen(server.url + path) as r:
        return json.loads(r.read())


def _post(server, path, payload=None, as_json=False):
    if as_json:
        data = json.dumps(payload or {}).encode()
        req = urllib.request.Request(
            server.url + path, data=data,
            headers={"Content-Type": "application/json"}, method="POST",
        )
    else:
        data = urllib.parse.urlencode(payload or {}).encode()
        req = urllib.request.Request(server.url + path, data=data, method="POST")
    with urllib.request.urlopen(req) as r:
        return json.loads(r.read())


def _wait_job(server, job_key, timeout=120.0):
    t0 = time.time()
    while time.time() - t0 < timeout:
        j = _get(server, f"/3/Jobs/{job_key}")["jobs"][0]
        if j["status"] in ("DONE", "FAILED", "CANCELLED"):
            return j
        time.sleep(0.2)
    raise TimeoutError(job_key)


def _upload_frame(n=800, seed=0, key="rest_train"):
    rng = np.random.default_rng(seed)
    df = pd.DataFrame({
        "a": rng.normal(size=n),
        "b": rng.normal(size=n),
        "y": np.where(rng.random(n) < 0.5, "dog", "cat"),
    })
    return Frame.from_pandas(df, destination_frame=key)


def test_cloud_and_ping(server):
    c = _get(server, "/3/Cloud")
    assert c["cloud_healthy"] and c["cloud_size"] >= 1
    assert _get(server, "/3/Ping")["ok"]


def test_parse_roundtrip(server, tmp_path):
    df = pd.DataFrame({"x": [1.0, 2.0, np.nan], "s": ["a", "b", "a"]})
    p = tmp_path / "t.csv"
    df.to_csv(p, index=False)
    setup = _post(server, "/3/ParseSetup", {"source_frames": str(p)})
    assert setup["source_frames"] == [str(p)]
    resp = _post(server, "/3/Parse", {"source_frames": str(p), "destination_frame": "rest_parsed"})
    _wait_job(server, resp["job"]["key"]["name"])
    fr = _get(server, "/3/Frames/rest_parsed")["frames"][0]
    assert fr["rows"] == 3
    assert fr["column_count"] == 2
    types = {c["label"]: c["type"] for c in fr["columns"]}
    assert types["s"] == "enum"
    nas = {c["label"]: c["missing_count"] for c in fr["columns"]}
    assert nas["x"] == 1


def test_model_build_predict_over_rest(server):
    _upload_frame(key="rest_train")
    resp = _post(server, "/3/ModelBuilders/glm", {
        "training_frame": "rest_train", "response_column": "y",
        "family": "binomial", "lambda_": 1e-4,
    })
    job = _wait_job(server, resp["job"]["key"]["name"])
    assert job["status"] == "DONE", job
    model_key = job["dest"]["name"]
    m = _get(server, f"/3/Models/{model_key}")["models"][0]
    assert m["algo"] == "glm"
    assert m["output"]["model_category"] == "Binomial"
    assert m["output"]["training_metrics"]["auc"] > 0.3

    pred = _post(server, f"/3/Predictions/models/{model_key}/frames/rest_train", {})
    pkey = pred["predictions_frame"]["name"]
    pfr = _get(server, f"/3/Frames/{pkey}")["frames"][0]
    assert pfr["rows"] == 800
    labels = [c["label"] for c in pfr["columns"]]
    assert labels == ["predict", "cat", "dog"]

    mm = _post(server, f"/3/ModelMetrics/models/{model_key}/frames/rest_train", {})
    assert 0.0 <= mm["model_metrics"][0]["auc"] <= 1.0


def test_model_builders_listing_and_errors(server):
    mb = _get(server, "/3/ModelBuilders")
    assert "gbm" in mb["model_builders"]
    # unknown algo -> 404 with H2O-style error body
    try:
        _post(server, "/3/ModelBuilders/nope", {"training_frame": "x"})
        assert False, "expected 404"
    except urllib.error.HTTPError as e:
        body = json.loads(e.read())
        assert body["http_status"] == 404


def test_automl_over_rest(server):
    _upload_frame(n=600, seed=3, key="rest_aml")
    resp = _post(server, "/99/AutoMLBuilder", {
        "build_control": {"stopping_criteria": {"max_models": 2, "seed": 1},
                          "nfolds": 3, "project_name": "t"},
        "input_spec": {"training_frame": {"name": "rest_aml"},
                       "response_column": {"column_name": "y"}},
        "build_models": {"include_algos": ["GLM", "StackedEnsemble"]},
    }, as_json=True)
    job = _wait_job(server, resp["job"]["key"]["name"], timeout=300)
    assert job["status"] == "DONE", job
    aml = _get(server, f"/99/AutoML/{resp['automl_id']['name']}")
    assert len(aml["leaderboard_table"]) >= 1
    assert aml["leader"] is not None


def test_rapids_eval(server):
    fr = _upload_frame(n=100, seed=5, key="rapids_fr")
    # scalar: mean of column a
    out = _post(server, "/99/Rapids", {"ast": "(mean (cols_py rapids_fr 'a'))"})
    expect = float(np.nanmean(fr.vec("a").to_numpy()))
    assert out["scalar"] == pytest.approx(expect, rel=1e-5)
    # frame op: new derived column, assigned to a temp key
    out = _post(server, "/99/Rapids",
                {"ast": "(tmp= rap_tmp (* (cols_py rapids_fr 'a') 2))"})
    assert out["key"]["name"] == "rap_tmp"
    doubled = h2o3_tpu.get_frame("rap_tmp").vec(0).to_numpy()
    np.testing.assert_allclose(doubled, fr.vec("a").to_numpy() * 2, rtol=1e-6)
    # group-by through rapids
    out = _post(server, "/99/Rapids",
                {"ast": "(GB rapids_fr ['y'] mean 'a' 'all')"})
    g = h2o3_tpu.get_frame(out["key"]["name"])
    assert g.nrow == 2 and "mean_a" in g.names


def test_rapids_parse_errors_are_4xx(server):
    try:
        _post(server, "/99/Rapids", {"ast": "(nosuchop 1 2)"})
        assert False
    except urllib.error.HTTPError as e:
        assert e.code == 400


def test_wave3_algos_build_over_rest(server):
    """List-valued params (gam_columns, random_columns) coerce correctly
    through the REST schema layer for the round-3 builders."""
    rng = np.random.default_rng(11)
    n = 1200
    x = rng.normal(size=n)
    g = rng.choice(["a", "b", "c"], n)
    Frame.from_pandas(
        pd.DataFrame({"x": x, "g": g,
                      "y": np.sin(2 * x) + rng.normal(0, 0.1, n)}),
        column_types={"g": "enum"}, destination_frame="w3fr", register=True,
    )
    cases = [
        ("gam", {"gam_columns": ["x"]}),
        ("rulefit", {"rule_generation_ntrees": 6}),
        ("hglm", {"random_columns": ["g"]}),
        ("modelselection", {"mode": "forward", "max_predictor_number": 2}),
    ]
    for algo, extra in cases:
        res = _post(server, f"/3/ModelBuilders/{algo}",
                    {"training_frame": "w3fr", "response_column": "y", **extra},
                    as_json=True)
        jj = _wait_job(server, res["job"]["key"]["name"])
        assert jj["status"] == "DONE", f"{algo}: {jj.get('exception')}"
    # the flow page serves and lists the new builders
    with urllib.request.urlopen(server.url + "/") as r:
        assert b"h2o3-tpu Flow" in r.read()
    mb = _get(server, "/3/ModelBuilders")["model_builders"]
    for algo, _ in cases:
        assert algo in mb


def test_models_bin_save_load_roundtrip(server, tmp_path):
    """/99/Models.bin save + load (upstream ModelsHandler binary persistence
    routes the R client's h2o.saveModel/h2o.loadModel speak)."""
    import urllib.parse
    import urllib.request

    base = server.url
    rng = np.random.default_rng(2)
    df = pd.DataFrame({
        "a": rng.normal(size=500), "b": rng.normal(size=500),
    })
    df["y"] = np.where(df.a + df.b > 0, "t", "f")
    p = tmp_path / "mb.csv"
    df.to_csv(p, index=False)

    def req(method, path, data=None):
        body = urllib.parse.urlencode(data).encode() if data else None
        r = urllib.request.Request(base + path, data=body, method=method)
        return json.loads(urllib.request.urlopen(r, timeout=120).read())

    req("POST", "/3/ImportFiles", {"path": str(p)})
    pj = req("POST", "/3/Parse", {"source_frames": str(p), "destination_frame": "mbf"})
    import time as _t
    pjid = pj["job"]["key"]["name"]
    while req("GET", f"/3/Jobs/{pjid}")["jobs"][0]["status"] not in ("DONE", "FAILED"):
        _t.sleep(0.2)
    job = req("POST", "/3/ModelBuilders/gbm",
              {"training_frame": "mbf", "response_column": "y",
               "ntrees": "3", "max_depth": "2", "seed": "1"})
    jid = (job.get("job") or job)["key"]["name"]
    while True:
        j = req("GET", f"/3/Jobs/{jid}")["jobs"][0]
        if j["status"] in ("DONE", "FAILED"):
            break
        _t.sleep(0.3)
    assert j["status"] == "DONE"
    mkey = j["dest"]["name"]
    saved = req("POST", f"/99/Models.bin/{mkey}?dir={tmp_path}")
    assert saved["dir"]
    # delete then load back
    req("DELETE", f"/3/Models/{mkey}")
    loaded = req("POST", f"/99/Models.bin?dir={urllib.parse.quote(saved['dir'])}")
    m = loaded["models"][0]
    assert m["output"]["training_metrics"]["auc"] > 0.7


def test_profiler_route(server):
    """/3/Profiler returns per-thread stacks (JProfile/JStack successor)."""
    out = _get(server, "/3/Profiler?depth=5")
    prof = out["nodes"][0]["profile"]
    assert any("MainThread" in p["thread"] for p in prof)
    assert all(p["stack"] for p in prof)
    assert all(len(p["stack"]) <= 5 for p in prof)


class TestNodePersistentStorage:
    """/3/NodePersistentStorage — the Flow notebook save/load store
    (upstream water/api/NodePersistentStorageHandler [UNVERIFIED])."""

    def test_roundtrip_list_delete(self, server, monkeypatch, tmp_path):
        monkeypatch.setenv("H2O3_TPU_NPS_DIR", str(tmp_path))
        assert _get(server, "/3/NodePersistentStorage/configured")["configured"]
        flow = json.dumps([{"type": "md", "text": "# hi"}])
        _post(server, "/3/NodePersistentStorage/notebook/my%20flow",
              {"value": flow}, as_json=True)
        got = _get(server, "/3/NodePersistentStorage/notebook/my%20flow")
        assert got["value"] == flow
        entries = _get(server, "/3/NodePersistentStorage/notebook")["entries"]
        assert [e["name"] for e in entries] == ["my flow"]
        assert entries[0]["size"] == len(flow)
        req = urllib.request.Request(
            server.url + "/3/NodePersistentStorage/notebook/my%20flow",
            method="DELETE")
        with urllib.request.urlopen(req) as r:
            json.loads(r.read())
        assert _get(server, "/3/NodePersistentStorage/notebook")["entries"] == []

    def test_rejects_path_traversal(self, server, monkeypatch, tmp_path):
        monkeypatch.setenv("H2O3_TPU_NPS_DIR", str(tmp_path))
        for bad in ("..%2F..%2Fetc", ".hidden", "a%2Fb"):
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post(server, f"/3/NodePersistentStorage/notebook/{bad}",
                      {"value": "x"}, as_json=True)
            assert ei.value.code in (400, 404)

    def test_get_missing_is_404(self, server, monkeypatch, tmp_path):
        monkeypatch.setenv("H2O3_TPU_NPS_DIR", str(tmp_path))
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(server, "/3/NodePersistentStorage/notebook/nope")
        assert ei.value.code == 404


def test_flow_page_serves_notebook(server):
    """Flow page smoke: served at / and /flow, carries the notebook cell
    engine, and its script's bracket nesting is balanced (no JS parser in
    the image; this catches truncated-template regressions)."""
    import urllib.request as _rq

    with _rq.urlopen(server.url + "/flow") as r:
        html = r.read().decode()
    assert "Notebook" in html and "nbRunAll" in html
    assert "/3/NodePersistentStorage/notebook/" in html
    js = html.split("<script>")[1].split("</script>")[0]
    for o, c in ("()", "{}", "[]"):
        assert js.count(o) == js.count(c)


def test_predict_options_over_rest(server):
    """predict_contributions / leaf_node_assignment predict options
    (upstream PredictV3 surface) return their special frames."""
    _upload_frame(n=300, seed=9, key="rest_popt")
    resp = _post(server, "/3/ModelBuilders/gbm", {
        "training_frame": "rest_popt", "response_column": "y",
        "ntrees": 2, "max_depth": 3, "seed": 4,
    })
    job = _wait_job(server, resp["job"]["key"]["name"])
    assert job["status"] == "DONE", job
    mk = job["dest"]["name"]

    c = _post(server, f"/3/Predictions/models/{mk}/frames/rest_popt",
              {"predict_contributions": True}, as_json=True)
    cfr = _get(server, f"/3/Frames/{c['predictions_frame']['name']}")["frames"][0]
    assert [x["label"] for x in cfr["columns"]] == ["a", "b", "BiasTerm"]

    la = _post(server, f"/3/Predictions/models/{mk}/frames/rest_popt",
               {"leaf_node_assignment": True}, as_json=True)
    lfr = _get(server, f"/3/Frames/{la['predictions_frame']['name']}")["frames"][0]
    assert [x["label"] for x in lfr["columns"]] == ["T1.C1", "T2.C1"]

    # unsupported model (GLM) -> 400
    resp = _post(server, "/3/ModelBuilders/glm", {
        "training_frame": "rest_popt", "response_column": "y",
        "family": "binomial",
    })
    job = _wait_job(server, resp["job"]["key"]["name"])
    glm_key = job["dest"]["name"]
    try:
        _post(server, f"/3/Predictions/models/{glm_key}/frames/rest_popt",
              {"predict_contributions": True}, as_json=True)
        assert False, "expected 400"
    except urllib.error.HTTPError as e:
        assert json.loads(e.read())["http_status"] == 400


def test_split_and_create_frame_routes(server):
    """/3/SplitFrame and /3/CreateFrame (upstream frame-utility handlers)."""
    _upload_frame(n=1000, seed=13, key="rest_split_src")
    out = _post(server, "/3/SplitFrame", {
        "dataset": "rest_split_src", "ratios": [0.75],
        "destination_frames": ["sf_train", "sf_test"], "seed": 7,
    }, as_json=True)
    assert [d["name"] for d in out["destination_frames"]] == ["sf_train", "sf_test"]
    a = _get(server, "/3/Frames/sf_train")["frames"][0]["rows"]
    b = _get(server, "/3/Frames/sf_test")["frames"][0]["rows"]
    assert a + b == 1000 and 650 <= a <= 850

    cf = _post(server, "/3/CreateFrame", {
        "dest": "cf1", "rows": 500, "cols": 10, "seed": 3,
        "categorical_fraction": 0.3, "integer_fraction": 0.2,
        "missing_fraction": 0.05, "factors": 5,
        "has_response": True, "response_factors": 2,
    }, as_json=True)
    assert cf["rows"] == 500 and cf["cols"] == 11  # +response
    fr = _get(server, "/3/Frames/cf1")["frames"][0]
    labels = [c["label"] for c in fr["columns"]]
    assert labels[0] == "response"
    # ratio errors are 400s
    try:
        _post(server, "/3/SplitFrame", {"dataset": "rest_split_src",
                                        "ratios": [0.9, 0.9]}, as_json=True)
        assert False, "expected 400"
    except urllib.error.HTTPError as e:
        assert e.code == 400


def test_split_frame_validates_destination_count(server):
    _upload_frame(n=200, seed=17, key="rest_split_v")
    for dests in (["a", "b", "c"], ["only_one"]):
        try:
            _post(server, "/3/SplitFrame", {
                "dataset": "rest_split_v", "ratios": [0.75],
                "destination_frames": dests}, as_json=True)
            assert False, "expected 400"
        except urllib.error.HTTPError as e:
            assert e.code == 400
    # bad scalar params are 400s, not 500s
    try:
        _post(server, "/3/CreateFrame", {"rows": "abc"}, as_json=True)
        assert False, "expected 400"
    except urllib.error.HTTPError as e:
        assert e.code == 400


def test_pojo_download_route(server):
    """GET /3/Models/{id}/pojo serves the standalone scoring script."""
    _upload_frame(n=200, seed=21, key="rest_pojo")
    resp = _post(server, "/3/ModelBuilders/gbm", {
        "training_frame": "rest_pojo", "response_column": "y",
        "ntrees": 1, "max_depth": 2, "seed": 1})
    job = _wait_job(server, resp["job"]["key"]["name"])
    mk = job["dest"]["name"]
    with urllib.request.urlopen(server.url + f"/3/Models/{mk}/pojo") as r:
        body = r.read().decode()
        assert r.headers.get("Content-Type", "").startswith("text/x-python")
    assert "MODEL" in body and "numpy" in body


def test_interaction_route(server):
    """/3/Interaction builds factor-interaction columns (hex/Interaction)."""
    rng = np.random.default_rng(23)
    n = 300
    df = pd.DataFrame({
        "c1": rng.choice(["a", "b"], n), "c2": rng.choice(["u", "v", "w"], n),
        "y": rng.normal(size=n),
    })
    fr = h2o3_tpu.upload_file(df)
    from h2o3_tpu.cluster.registry import DKV
    DKV.put("rest_inter", DKV.get(fr.key)); fr.key = "rest_inter"
    out = _post(server, "/3/Interaction", {
        "source_frame": "rest_inter", "factor_columns": ["c1", "c2"],
        "dest": "inter1"}, as_json=True)
    assert out["destination_frame"]["name"] == "inter1"
    got = _get(server, "/3/Frames/inter1")["frames"][0]
    assert [c["label"] for c in got["columns"]] == ["c1_c2"]
    assert got["columns"][0]["type"] == "enum"
    assert len(got["columns"][0]["domain"]) == 6


def test_make_metrics_and_partial_dependence_routes(server):
    """/3/ModelMetrics/predictions_frame/... (h2o.make_metrics) and
    /3/PartialDependence."""
    fr = _upload_frame(n=400, seed=31, key="rest_mm")
    resp = _post(server, "/3/ModelBuilders/gbm", {
        "training_frame": "rest_mm", "response_column": "y",
        "ntrees": 3, "max_depth": 3, "seed": 2})
    job = _wait_job(server, resp["job"]["key"]["name"])
    mk = job["dest"]["name"]
    pred = _post(server, f"/3/Predictions/models/{mk}/frames/rest_mm", {})
    pk = pred["predictions_frame"]["name"]

    # make_metrics from the dog-probability column vs the actual labels:
    # must agree with the model's own training AUC
    r = _post(server, "/99/Rapids",
              {"ast": f"(tmp= rest_mm_p (cols_py {pk} ['dog']))"}, as_json=True)
    r = _post(server, "/99/Rapids",
              {"ast": "(tmp= rest_mm_y (cols_py rest_mm ['y']))"}, as_json=True)
    mm = _post(server,
               "/3/ModelMetrics/predictions_frame/rest_mm_p/actuals_frame/rest_mm_y",
               {"domain": ["cat", "dog"]}, as_json=True)
    auc = mm["model_metrics"][0]["auc"]
    m = _get(server, f"/3/Models/{mk}")["models"][0]
    assert abs(auc - m["output"]["training_metrics"]["auc"]) < 1e-6

    pd_out = _post(server, "/3/PartialDependence", {
        "model_id": mk, "frame_id": "rest_mm", "cols": ["a"], "nbins": 5,
    }, as_json=True)
    t = pd_out["partial_dependence_data"][0]
    assert t["column"] == "a" and len(t["values"]) == 5
    assert len(t["mean_response"]) == 5


def test_make_metrics_na_and_domain_order():
    import numpy as np

    import h2o3_tpu
    from h2o3_tpu.frame.frame import CAT, Frame, Vec

    rng = np.random.default_rng(4)
    n = 1000
    y = rng.integers(0, 2, n)
    p = np.clip(rng.normal(0.4 + 0.2 * y, 0.25, n), 0.001, 0.999)
    base = h2o3_tpu.make_metrics(p, y.astype(float), domain=("a", "b"))

    # NA actuals (code -1) must be dropped, not folded in as y=-1
    codes = y.astype(np.int32).copy()
    codes[:50] = -1
    va = Vec.from_numpy(codes, CAT, name="y", domain=("a", "b"))
    mm_na = h2o3_tpu.make_metrics(p, va, domain=("a", "b"))
    ref = h2o3_tpu.make_metrics(p[50:], y[50:].astype(float), domain=("a", "b"))
    assert abs(mm_na.auc - ref.auc) < 1e-12
    assert abs(mm_na.value("logloss") - ref.value("logloss")) < 1e-12

    # a categorical actuals vec whose LEVEL ORDER differs from the given
    # domain must remap by label, not reuse raw codes
    flipped = Vec.from_numpy((1 - y).astype(np.int32), CAT, name="y",
                             domain=("b", "a"))  # same labels, swapped codes
    mm_fl = h2o3_tpu.make_metrics(p, flipped, domain=("a", "b"))
    assert abs(mm_fl.auc - base.auc) < 1e-12


def test_typeahead_and_metadata_routes(server, tmp_path):
    (tmp_path / "data_a.csv").write_text("x\n1\n")
    (tmp_path / "data_b.csv").write_text("x\n2\n")
    (tmp_path / "datadir").mkdir()
    j = _get(server, "/3/Typeahead/files?src="
             + urllib.parse.quote(str(tmp_path / "data")))
    assert j["matches"] == [str(tmp_path / "data_a.csv"),
                            str(tmp_path / "data_b.csv"),
                            str(tmp_path / "datadir") + "/"]

    md = _get(server, "/3/Metadata/schemas")
    names = {s["algo"] for s in md["schemas"]}
    assert {"gbm", "glm", "deeplearning", "xgboost"} <= names
    gbm = next(s for s in md["schemas"] if s["algo"] == "gbm")
    assert any(f["name"] == "ntrees" for f in gbm["fields"])
    assert any(r["url_pattern"].endswith("ModelBuilders/([^/]+)")
               for r in md["routes"])


def test_wait_job_failure_includes_job_key(server):
    """client.wait_job on a FAILED job raises with the JOB KEY in the
    message (not just the traceback text)."""
    from h2o3_tpu.client import H2OClientError, H2OConnection

    conn = H2OConnection(server.url)
    resp = _post(server, "/3/Parse", {
        "source_frames": "/definitely/not/here.csv",
        "destination_frame": "nope_fr"})
    jkey = resp["job"]["key"]["name"]
    with pytest.raises(H2OClientError) as ei:
        conn.wait_job(jkey)
    assert jkey in str(ei.value)


def test_job_deadline_knob_surfaces_on_jobs(server, monkeypatch):
    """H2O3_TPU_JOB_DEADLINE_SECS stamps a deadline on REST-created jobs and
    /3/Jobs propagates it to the client."""
    monkeypatch.setenv("H2O3_TPU_JOB_DEADLINE_SECS", "120")
    t0 = time.time()
    resp = _post(server, "/3/CreateFrame",
                 {"dest": "deadline_fr", "rows": 50, "cols": 2, "seed": 1},
                 as_json=True)
    j = resp["job"]
    assert "deadline" in j, j
    assert 30 < j["deadline"] - t0 <= 200


def test_job_queue_bound_sheds_503(server, monkeypatch):
    """Job-creating routes beyond H2O3_TPU_MAX_QUEUED_JOBS are shed with
    503 + Retry-After instead of queueing unboundedly."""
    import threading

    from h2o3_tpu.api import server as S

    monkeypatch.setenv("H2O3_TPU_MAX_QUEUED_JOBS", "1")
    release = threading.Event()
    occupier = S._start_job(lambda j: release.wait(20), "queue occupier")
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(server, "/3/CreateFrame",
                  {"rows": 10, "cols": 2, "seed": 1}, as_json=True)
        assert ei.value.code == 503
        assert float(ei.value.headers.get("Retry-After")) > 0
        body = json.loads(ei.value.read())
        assert "queue full" in body["msg"]
    finally:
        release.set()
        assert occupier.wait(20)


def test_shed_response_not_cached_under_idempotency_key(server, monkeypatch):
    """Regression: a 503 shed (queue full) must NOT be cached under the
    request's Idempotency-Key — the client retries 429/503 with the SAME
    key, so a cached shed would replay the rejection forever."""
    import threading

    from h2o3_tpu.api import server as S

    monkeypatch.setenv("H2O3_TPU_MAX_QUEUED_JOBS", "1")
    release = threading.Event()
    occupier = S._start_job(lambda j: release.wait(20), "idem shed occupier")
    key = "idem-shed-regression"

    def _keyed_post():
        data = json.dumps({"dest": "idem_shed_fr", "rows": 10, "cols": 2,
                           "seed": 1}).encode()
        req = urllib.request.Request(
            server.url + "/3/CreateFrame", data=data, method="POST",
            headers={"Content-Type": "application/json",
                     "Idempotency-Key": key})
        with urllib.request.urlopen(req) as r:
            return json.loads(r.read()), r.headers

    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _keyed_post()
        assert ei.value.code == 503
    finally:
        release.set()
        assert occupier.wait(20)
    # retry with the SAME key once the shed clears: the mutation must RUN
    # (fresh job), not replay the stored 503
    resp, headers = _keyed_post()
    assert headers.get("Idempotency-Replayed") is None
    assert "job" in resp
    _wait_job(server, resp["job"]["key"]["name"])


def test_idem_eviction_never_drops_inflight_key():
    """Regression: when the idempotency cache is at capacity, eviction must
    skip in-flight (_IDEM_PENDING) entries — evicting one would let a retry
    of that key re-run the mutation a second time, concurrently."""
    from h2o3_tpu.api import server as S

    with S._IDEM_LOCK:
        saved = dict(S._IDEM_CACHE)
        S._IDEM_CACHE.clear()
    try:
        assert S._idem_begin("pending-key") is None  # in flight, unfinished
        for i in range(S._IDEM_MAX + 8):  # sustained eviction pressure
            k = f"done-{i}"
            assert S._idem_begin(k) is None
            S._idem_finish(k, 200, {"i": i})
        with S._IDEM_LOCK:
            assert S._IDEM_CACHE.get("pending-key") is S._IDEM_PENDING
        # a duplicate of the in-flight key is still serialized behind the
        # owner (409 path), never admitted as a new owner
        assert S._idem_begin("pending-key") is S._IDEM_PENDING
    finally:
        with S._IDEM_LOCK:
            S._IDEM_CACHE.clear()
            S._IDEM_CACHE.update(saved)


def test_job_queue_cap_exact_under_concurrency(monkeypatch):
    """Regression: the prune+count+append sequence in _start_job is one
    critical section — concurrent creates can never exceed the cap."""
    import threading

    from h2o3_tpu.api import server as S

    with S._JOBS_LOCK:
        S._REST_JOBS[:] = [j for j in S._REST_JOBS
                           if j.status in (S.Job.PENDING, S.Job.RUNNING)]
        live0 = len(S._REST_JOBS)
    cap = live0 + 3
    monkeypatch.setenv("H2O3_TPU_MAX_QUEUED_JOBS", str(cap))
    release = threading.Event()
    admitted, shed = [], []
    seen = threading.Lock()
    start = threading.Barrier(12)

    def _create():
        start.wait(5)
        try:
            j = S._start_job(lambda job: release.wait(20), "cap hammer")
            with seen:
                admitted.append(j)
        except S.ApiError as e:
            with seen:
                shed.append(e.status)

    threads = [threading.Thread(target=_create) for _ in range(12)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(10)
    try:
        assert len(admitted) == 3  # exactly up to the cap, never beyond
        assert len(shed) == 9 and all(s == 503 for s in shed)
    finally:
        release.set()
        for j in admitted:
            assert j.wait(20)


def test_admission_gate_healthy_path_overhead(server):
    """Acceptance bound: the admission gate costs ≤ 2% of serving-path
    latency on the healthy path. Measured directly: per-call gate cost
    (enter+exit) vs the median round-trip of the CHEAPEST real route."""
    import timeit

    from h2o3_tpu.api import server as S

    n = 5000
    per_call = timeit.timeit(
        lambda: (S._admission_enter("POST", "/3/Parse"), S._admission_exit()),
        number=n) / n
    # median of real /3/Ping round-trips (the lightest handler there is —
    # every mutating route does strictly more work than this)
    times = []
    for _ in range(30):
        t0 = time.perf_counter()
        _get(server, "/3/Ping")
        times.append(time.perf_counter() - t0)
    ping_median = sorted(times)[len(times) // 2]
    assert per_call < 0.02 * ping_median, (per_call, ping_median)
    assert per_call < 50e-6  # absolute sanity: microseconds, not millis


def test_weighted_quantile_over_rapids(server):
    rng = np.random.default_rng(7)
    x = rng.normal(size=300)
    w = rng.integers(1, 4, 300).astype(float)
    fr = h2o3_tpu.upload_file(pd.DataFrame({"x": x, "w": w}))
    from h2o3_tpu.cluster.registry import DKV
    DKV.put("rq_fr", DKV.get(fr.key)); fr.key = "rq_fr"
    _post(server, "/99/Rapids",
          {"ast": "(tmp= rq_out (quantile rq_fr [0.25 0.5] 'interpolate' 'w'))"},
          as_json=True)
    got = h2o3_tpu.get_frame("rq_out").vec("x").to_numpy()
    rep = np.repeat(x, w.astype(int))
    # frame storage is f32 — compare at that precision
    np.testing.assert_allclose(got, np.quantile(rep, [0.25, 0.5]), rtol=1e-6)
    # weights column is excluded from the quantile output columns
    assert "w" not in h2o3_tpu.get_frame("rq_out").names
    # misspelled weights column errors instead of silently unweighting
    try:
        _post(server, "/99/Rapids",
              {"ast": "(quantile rq_fr [0.5] 'interpolate' 'nope')"},
              as_json=True)
        assert False, "expected 400"
    except urllib.error.HTTPError as e:
        assert e.code == 400
