"""Compute-fabric tests — modeled on upstream ``water/MRTaskTest.java``
scenarios [UNVERIFIED upstream path]: associative map/reduce over the row
shards must match a host-side reference."""

import jax.numpy as jnp
import numpy as np
import pandas as pd

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.parallel import map_only, map_reduce


def test_map_reduce_sum():
    x = np.arange(8000, dtype=np.float32)
    fr = Frame.from_pandas(pd.DataFrame({"x": x}))
    out = map_reduce(lambda c: {"s": jnp.nansum(c), "n": (~jnp.isnan(c)).sum()}, fr.vec("x").data)
    assert float(out["s"]) == x.sum()
    assert int(out["n"]) == 8000


def test_map_reduce_multi_column_gram():
    rng = np.random.default_rng(0)
    a = rng.normal(size=4096).astype(np.float32)
    b = rng.normal(size=4096).astype(np.float32)
    fr = Frame.from_pandas(pd.DataFrame({"a": a, "b": b}))

    def gram(ca, cb):
        X = jnp.stack([jnp.nan_to_num(ca), jnp.nan_to_num(cb)], axis=1)
        return X.T @ X

    out = np.asarray(map_reduce(gram, fr.vec("a").data, fr.vec("b").data))
    X = np.stack([a, b], axis=1)
    np.testing.assert_allclose(out, X.T @ X, rtol=2e-3)


def test_map_only_preserves_sharding():
    x = np.arange(2048, dtype=np.float32)
    fr = Frame.from_pandas(pd.DataFrame({"x": x}))
    y = map_only(lambda c: c * 2.0 + 1.0, fr.vec("x").data)
    np.testing.assert_allclose(np.asarray(y)[:2048], x * 2 + 1)
    assert len(y.sharding.device_set) == 8
