"""Device-memory ledger + incident flight recorder (ISSUE 13,
utils/devmem.py + utils/flightrec.py).

The acceptance pins:
- ledger attribution sums stay consistent under CONCURRENT frame-stream +
  serving-paging load (each owner's claim returns to its prior level, the
  window claim never exceeds the window, no owner goes negative);
- the ring is bounded and ordered under multithreaded append, and its
  append stays O(µs) (the ≤2% fused-tree span-overhead contract is a bench
  pin; the per-event cost bound here is its unit-level guard);
- an injected cloud death (faults ``die:`` at a collective boundary)
  produces an incident bundle containing the dying dispatch and the
  failing generation, with the bundle path surfaced in the job's recovery
  block — and the supervised run still heals;
- ``H2O3_TPU_METRICS=0`` keeps the ring recording and bundles writing
  (the histogram alone goes quiet);
- the attribution identity Σ owned + unattributed = in_use holds when the
  backend reports memory_stats (synthetic stats on the CPU proxy);
- ChunkStore stats land in the REGISTRY at close() (the LAST_STORE_STATS
  clobber fix) and /3/FlightRecorder serves the ring + devmem snapshot.
"""

import contextlib
import json
import os
import threading
import time
import urllib.request

import numpy as np
import pandas as pd
import pytest

from h2o3_tpu.cluster import cloud, recovery
from h2o3_tpu.frame import chunkstore as cs
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.parallel.mesh import pad_to_shards
from h2o3_tpu.utils import devmem, faults, flightrec
from h2o3_tpu.utils import metrics as mx


@pytest.fixture(autouse=True)
def _clean(monkeypatch, tmp_path):
    monkeypatch.setenv("H2O3_TPU_INCIDENT_DIR", str(tmp_path / "incidents"))
    monkeypatch.setenv("H2O3_TPU_RECOVERY", "1")
    monkeypatch.setenv("H2O3_TPU_RECOVERY_BACKOFF", "0.01")
    flightrec._reset_incidents_for_tests()
    cloud.clear_degraded()
    yield
    faults.reset()
    cloud.clear_degraded()


@contextlib.contextmanager
def _env(**kv):
    old = {k: os.environ.get(k) for k in kv}
    os.environ.update({k: str(v) for k, v in kv.items()})
    try:
        yield
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _df(n=1500, seed=3):
    rng = np.random.default_rng(seed)
    df = pd.DataFrame({
        "a": rng.normal(size=n),
        "b": rng.normal(size=n),
        "c": rng.normal(size=n),
    })
    eta = df["a"] * 1.5 - df["b"]
    df["y"] = np.where(eta + rng.normal(size=n) > 0, "p", "n")
    return df


class _FakeScorer:
    """Minimal pageable-payload scorer for ResidencyManager tests."""

    def __init__(self, key: str, kb: int = 8):
        self.model_key = key
        self._host_args = {"w": np.ones(kb * 256, np.float32)}


# ---------------------------------------------------------------------------
# the owner ledger


def test_adjust_tracks_live_and_peak():
    o0 = devmem.owned().get("frame_resident", 0.0)
    devmem.adjust("frame_resident", 5000)
    devmem.adjust("frame_resident", -2000)
    assert devmem.owned()["frame_resident"] == pytest.approx(o0 + 3000)
    assert devmem.peaks()["frame_resident"] >= o0 + 5000
    assert mx.counter_value("hbm_owned_bytes", owner="frame_resident") == (
        pytest.approx(o0 + 3000))
    devmem.adjust("frame_resident", -3000)


def test_ledger_attribution_under_concurrent_load():
    """Frame streaming (ChunkStore window) and serving paging
    (ResidencyManager LRU) hammer the ledger from two threads: the window
    claim stays <= the window the whole time, the serving claim stays
    <= the device-LRU total, and both return their bytes at the end."""
    from h2o3_tpu.serving.residency import ResidencyManager

    base_win = devmem.owned().get("frame_window", 0.0)
    base_srv = devmem.owned().get("serving", 0.0)
    window = 16 * 1024
    npad = pad_to_shards(4096)
    errs: list = []
    over: list = []

    def _stream():
        try:
            store = cs.ChunkStore(npad, 8.0, window=window, prefetch=1)
            store.add("x", np.zeros((npad,), np.float32))
            store.add("n", np.zeros((npad,), np.int32))
            for _ in range(3):
                for _bi, blk in store.stream(("x", "n")):
                    claim = devmem.owned().get("frame_window", 0.0)
                    if claim - base_win > window + 1:
                        over.append(claim)
            store.close()
        except Exception as e:  # noqa: BLE001 — surfaced below
            errs.append(e)

    def _page():
        try:
            mgr = ResidencyManager()
            scorers = [_FakeScorer(f"m{i}") for i in range(6)]
            with _env(H2O3_TPU_SERVE_HBM_BYTES=str(3 * 8 * 1024)):
                for _ in range(4):
                    for s in scorers:
                        with mgr.hold(s):
                            pass
            for s in scorers:
                mgr.release(s)
        except Exception as e:  # noqa: BLE001 — surfaced below
            errs.append(e)

    ts = [threading.Thread(target=_stream), threading.Thread(target=_page)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=120)
    assert not errs, errs
    assert not over, f"frame_window claim exceeded the window: {over[:3]}"
    # both planes returned their bytes: the ledger is live residency
    assert devmem.owned().get("frame_window", 0.0) == pytest.approx(
        base_win, abs=1.0)
    assert devmem.owned().get("serving", 0.0) == pytest.approx(
        base_srv, abs=1.0)
    # and the gauges never went negative
    for owner, v in devmem.owned().items():
        assert v >= -1.0, (owner, v)


def test_attribution_identity_with_synthetic_stats(monkeypatch):
    """Sigma owned + unattributed = in_use (the CPU proxy's devices report
    memory_stats()=None, so the identity is pinned with injected stats)."""
    devmem.adjust("serving", 10_000)
    try:
        owned_total = sum(devmem.owned().values())
        fake = {"bytes_in_use": int(owned_total + 70_000),
                "peak_bytes_in_use": int(owned_total + 90_000),
                "bytes_limit": int(owned_total + 1_000_000)}
        monkeypatch.setattr(devmem, "_stats_fn",
                            lambda d: fake if d.id == 0 else None)
        devmem.poll(force=True)
        s = devmem.status()
        assert s["in_use_bytes"] == fake["bytes_in_use"]
        assert s["unattributed_bytes"] == pytest.approx(70_000, abs=1)
        assert s["unattributed_bytes"] + s["owned_total_bytes"] == (
            s["in_use_bytes"])
        assert mx.counter_value(
            "hbm_owned_bytes", owner="unattributed") == pytest.approx(
                70_000, abs=1)
        assert mx.counter_value(
            "device_hbm_bytes", device="0", kind="in_use") == (
                fake["bytes_in_use"])
        assert devmem.headroom() == pytest.approx(
            fake["bytes_limit"] - fake["bytes_in_use"], abs=1)
    finally:
        devmem.adjust("serving", -10_000)
        monkeypatch.undo()
        devmem.poll(force=True)


def test_cluster_info_routes_through_devmem(monkeypatch):
    """/3/Cloud's node table reads the ledger's cached poll — ONE
    memory_stats reader — and keeps the probe-failure health semantics."""
    calls = []

    def _probe(d):
        calls.append(d.id)
        if d.id == 1:
            raise RuntimeError("probe died")
        return {"bytes_in_use": 11, "bytes_limit": 22}

    monkeypatch.setattr(devmem, "_stats_fn", _probe)
    devmem.poll(force=True)
    n_calls = len(calls)
    info = cloud.cluster_info()
    # served from the cache: cluster_info itself did not re-probe
    assert len(calls) == n_calls
    nodes = {n["id"]: n for n in info["nodes"]}
    assert nodes[0]["healthy"] and nodes[0]["mem_in_use"] == 11
    assert not nodes[1]["healthy"]
    assert not info["cloud_healthy"]
    monkeypatch.undo()
    devmem.poll(force=True)


# ---------------------------------------------------------------------------
# the ring


def test_ring_bounded_and_ordered_under_multithreaded_append():
    flightrec.reset()
    n_threads, per = 8, 1500

    def _spam(tid):
        for i in range(per):
            flightrec.record("spam", tid=tid, i=i)

    ts = [threading.Thread(target=_spam, args=(t,)) for t in range(n_threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=60)
    evs = flightrec.events()
    assert len(evs) <= flightrec._SIZE
    seqs = [e["seq"] for e in evs]
    assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
    # the newest events survived (it is a ring, not a sieve)
    assert evs[-1]["kind"] == "spam"
    st = flightrec.ring_status()
    assert st["next_seq"] >= n_threads * per
    assert st["dropped"] >= n_threads * per - flightrec._SIZE


def test_ring_append_stays_microseconds():
    """The O(µs) hot-path budget, unit level (the end-to-end ≤2%
    fused-tree overhead bound is the bench contract)."""
    n = 20_000
    t0 = time.perf_counter()
    for i in range(n):
        flightrec.record("bench", i=i)
    per_event = (time.perf_counter() - t0) / n
    assert per_event < 100e-6, f"{per_event * 1e6:.1f}µs per append"


def test_dispatch_feeds_histogram_and_ring():
    flightrec.reset()
    fam = mx.REGISTRY.histogram("dispatch_device_seconds")
    before = sum(n for _l, _c, _s, n in fam.samples()
                 if _l.get("site") == "probe_site")
    with flightrec.dispatch("probe_site", program="k1", block=2):
        time.sleep(0.002)
    evs = flightrec.events()
    kinds = [e["kind"] for e in evs]
    assert "dispatch_start" in kinds and "dispatch_end" in kinds
    end = [e for e in evs if e["kind"] == "dispatch_end"][-1]
    assert end["site"] == "probe_site" and end["dur_ms"] >= 1.0
    after = sum(n for _l, _c, _s, n in fam.samples()
                if _l.get("site") == "probe_site")
    assert after == before + 1


def test_training_dispatches_land_in_ring_and_histogram():
    """The wired hot sites: a GBM train stamps ``site=tree`` dispatch
    events (program key included) and the dispatch_device_seconds series."""
    from h2o3_tpu.models.tree import GBM

    flightrec.reset()
    fr = Frame.from_pandas(_df())
    fam = mx.REGISTRY.histogram("dispatch_device_seconds")
    before = sum(n for _l, _c, _s, n in fam.samples()
                 if _l.get("site") == "tree")
    GBM(ntrees=3, max_depth=3, seed=7).train(y="y", training_frame=fr)
    tree_evs = [e for e in flightrec.events(kind="dispatch_end")
                if e["site"] == "tree"]
    assert tree_evs, "no tree dispatch events recorded"
    starts = [e for e in flightrec.events(kind="dispatch_start")
              if e["site"] == "tree"]
    assert any("program" in e for e in starts)
    after = sum(n for _l, _c, _s, n in fam.samples()
                if _l.get("site") == "tree")
    assert after > before


# ---------------------------------------------------------------------------
# incident bundles


class _JobShim:
    def __init__(self):
        self.recovery = None
        self.restarts = 0

    def set_recovery(self, info):
        self.recovery = {**(self.recovery or {}), **info}


def test_incident_bundle_on_injected_cloud_death(tmp_path):
    """The recovery drill with forensics: a die: fault mid-GBM produces a
    bundle whose ring holds the dying dispatch and the failing
    generation, the bundle path lands in the job's recovery block, the
    bundle was written atomically through persist, and the supervised
    run still heals to the uninterrupted result."""
    flightrec.reset()
    fr = Frame.from_pandas(_df())
    kw = dict(max_depth=3, seed=11, learn_rate=0.2, score_tree_interval=2)
    from h2o3_tpu.models.tree import GBM

    full = GBM(ntrees=6, **kw).train(y="y", training_frame=fr)
    ckdir = str(tmp_path / "heal")
    g0 = cloud.generation()
    job = _JobShim()

    def _launch(ckpt):
        kw2 = dict(kw, export_checkpoints_dir=ckdir)
        if ckpt:
            kw2["checkpoint"] = ckpt
        return GBM(ntrees=6, **kw2).train(y="y", training_frame=fr)

    with faults.inject(die={"gbm"}):
        healed = recovery.run_supervised(
            _launch, ckdir=ckdir, algo="gbm", description="forensics drill",
            job=job)
    # healed (the PR-10 contract holds with forensics attached)
    np.testing.assert_allclose(
        healed.training_metrics.logloss, full.training_metrics.logloss,
        atol=1e-6)
    assert cloud.generation() == g0 + 1
    # the bundle path surfaced in the recovery block — and survived the
    # post-resume checkpoint updates (set_recovery merges)
    assert job.recovery and "incident_bundle" in job.recovery
    path = job.recovery["incident_bundle"]
    assert os.path.exists(path)
    assert path == flightrec.last_incident()
    with open(path) as f:
        bundle = json.load(f)
    # captured BEFORE the reform: the failing generation, not the new one
    assert bundle["generation"] == g0
    kinds = {e["kind"] for e in bundle["events"]}
    # the dying dispatch is in the ring...
    assert any(e["kind"] == "dispatch_start" and e["site"] == "tree"
               for e in bundle["events"])
    # ...with the failing episode's generation marker
    assert "cloud_failure" in kinds
    cf = [e for e in bundle["events"] if e["kind"] == "cloud_failure"][-1]
    assert cf["generation"] == g0
    # the full forensics payload is present
    assert bundle["devmem"]["owned_bytes"] is not None
    assert isinstance(bundle["metrics"], dict) and bundle["metrics"]
    assert isinstance(bundle["log_tail"], list)
    assert mx.counter_value("incident_bundles_total", trigger="retry") >= 1


def test_incident_capture_dedups_per_episode():
    flightrec._reset_incidents_for_tests()
    p1 = flightrec.capture_incident("first failure", trigger="degraded")
    p2 = flightrec.capture_incident("same episode", trigger="reform")
    assert p1 is not None and p2 == p1  # one bundle per degraded episode


def test_metrics_off_keeps_ring_and_bundles(tmp_path):
    """H2O3_TPU_METRICS=0 contract: the ring keeps recording (always-on),
    bundles still write; only the gated histogram goes quiet."""
    mx.set_enabled(False)
    try:
        flightrec.reset()
        flightrec._reset_incidents_for_tests()
        fam = mx.REGISTRY.histogram("dispatch_device_seconds")
        before = sum(n for _l, _c, _s, n in fam.samples())
        with flightrec.dispatch("gated_site"):
            pass
        evs = flightrec.events()
        assert [e["kind"] for e in evs[-2:]] == [
            "dispatch_start", "dispatch_end"]
        after = sum(n for _l, _c, _s, n in fam.samples())
        assert after == before  # the histogram IS gated
        path = flightrec.capture_incident("metrics-off incident")
        assert path is not None and os.path.exists(path)
    finally:
        mx.set_enabled(True)


# ---------------------------------------------------------------------------
# ChunkStore registry stats (the LAST_STORE_STATS clobber fix)


def test_chunkstore_close_publishes_registry_stats():
    npad = pad_to_shards(4096)
    window = 16 * 1024
    ev0 = mx.counter_value("frame_window_evictions_total")
    store = cs.ChunkStore(npad, 8.0, window=window, prefetch=1)
    store.add("x", np.zeros((npad,), np.float32))
    store.add("n", np.zeros((npad,), np.int32))
    for _bi, _blk in store.stream(("x", "n")):
        pass
    store.close()
    assert mx.counter_value("frame_window_peak_bytes") == (
        store.peak_hbm)
    assert mx.counter_value("frame_window_peak_bytes") <= window
    assert mx.counter_value("frame_window_evictions_total") - ev0 == (
        store.evictions)
    # the deprecated dict alias still mirrors the same run
    assert cs.LAST_STORE_STATS["peak_hbm"] == store.peak_hbm
    # chunk fetch/evict traffic reached the ring
    assert flightrec.events(kind="chunk_fetch")
    # and the window returned its ledger claim
    assert devmem.owned().get("frame_window", 0.0) == pytest.approx(
        0.0, abs=1.0)


def test_oversized_streamed_train_bounds_ledger_claims():
    """The acceptance geometry on the proxy: an oversized streamed GBM
    concurrent with serving paging keeps hbm_owned_bytes{frame_window}
    <= the window and {serving} <= the serve budget while both run."""
    from h2o3_tpu.models.tree import GBM
    from h2o3_tpu.serving.residency import ResidencyManager

    window = 24 * 1024
    serve_budget = 3 * 8 * 1024
    base_win = devmem.owned().get("frame_window", 0.0)
    base_srv = devmem.owned().get("serving", 0.0)
    samples: list = []
    stop = threading.Event()
    errs: list = []

    def _serve():
        try:
            mgr = ResidencyManager()
            scorers = [_FakeScorer(f"ov{i}") for i in range(6)]
            with _env(H2O3_TPU_SERVE_HBM_BYTES=str(serve_budget)):
                while not stop.is_set():
                    for s in scorers:
                        with mgr.hold(s):
                            pass
                    samples.append((
                        devmem.owned().get("frame_window", 0.0) - base_win,
                        devmem.owned().get("serving", 0.0) - base_srv,
                    ))
            for s in scorers:
                mgr.release(s)
        except Exception as e:  # noqa: BLE001 — surfaced below
            errs.append(e)

    t = threading.Thread(target=_serve)
    t.start()
    try:
        with _env(H2O3_TPU_HBM_WINDOW_BYTES=str(window)):
            fr = _frame_oversized()
            m = GBM(ntrees=3, max_depth=3, seed=5).train(
                y="label", training_frame=fr)
    finally:
        stop.set()
        t.join(timeout=120)
    assert not errs, errs
    assert cs.LAST_STORE_STATS["n_blocks"] > 1  # really streamed
    assert samples, "no concurrent samples taken"
    for win_claim, srv_claim in samples:
        assert win_claim <= window + 1
        assert srv_claim <= serve_budget + 1
    assert float(m.training_metrics.auc) > 0.6


def _frame_oversized(n=6000, c=6, seed=23):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, c)).astype(np.float32)
    eta = X[:, 0] - 0.5 * X[:, 1]
    df = pd.DataFrame(X, columns=[f"x{i}" for i in range(c)])
    y = rng.random(n) < 1.0 / (1.0 + np.exp(-eta))
    df["label"] = np.where(y, "s", "b")
    return Frame.from_pandas(df)


# ---------------------------------------------------------------------------
# the REST surface


def test_flight_recorder_route():
    from h2o3_tpu.api import server as srv_mod

    existing = srv_mod._SERVER
    srv = srv_mod.start_server(port=0)
    try:
        flightrec.record("route_probe", x=1)
        with urllib.request.urlopen(
                srv.url + "/3/FlightRecorder?n=64", timeout=10) as r:
            out = json.loads(r.read())
        assert out["ring"]["size"] == flightrec._SIZE
        assert any(e["kind"] == "route_probe" for e in out["events"])
        assert "owned_bytes" in out["devmem"]
        with urllib.request.urlopen(
                srv.url + "/3/FlightRecorder?kind=route_probe",
                timeout=10) as r:
            filt = json.loads(r.read())
        assert filt["events"] and all(
            e["kind"] == "route_probe" for e in filt["events"])
    finally:
        if existing is None:
            srv.stop()
