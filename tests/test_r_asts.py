"""Executable pin for the R client's munging verbs.

No R runtime exists in this image (see test_r_client.py), so the R surface
is pinned from the other side of the wire: every Rapids AST template that
``r/h2o3tpu.R``'s munging verbs sprintf together is replayed here through
the same REST route R uses (POST /99/Rapids), asserting the response carries
the exact field each R wrapper reads (frame ``key`` / ``scalar`` /
``string``). A template drift between the R file and the Rapids dialect
breaks this test, not an R user."""

import json
import urllib.request

import numpy as np
import pandas as pd
import pytest

import h2o3_tpu
from h2o3_tpu.api.server import start_server


@pytest.fixture(scope="module")
def server():
    return start_server(port=0)


@pytest.fixture(scope="module")
def fr(server):
    df = pd.DataFrame(
        {
            "g": pd.Categorical(["a", "b", "a", "b", "a"]),
            "x": [1.0, 2.0, 3.0, 4.0, np.nan],
            "s": ["Hi", " lo ", "Mid", "X", "y"],
        }
    )
    return h2o3_tpu.upload_file(df, destination_frame="r_ast_fr")


def _rapids(server, ast: str) -> dict:
    req = urllib.request.Request(
        server.url + "/99/Rapids",
        data=json.dumps({"ast": ast}).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req) as r:
        return json.loads(r.read())


# (verb, AST exactly as the R wrapper emits it, response field it reads)
R_VERB_ASTS = [
    ("h2o.group_by", "(GB r_ast_fr ['g'] mean 'x' 'all' nrow 'x' 'all')", "key"),
    ("h2o.cbind", "(cbind r_ast_fr r_ast_fr)", "key"),
    ("h2o.rbind", "(rbind r_ast_fr r_ast_fr)", "key"),
    ("h2o.ifelse", "(ifelse (cols r_ast_fr 'x') 1 0)", "key"),
    ("h2o.cut", "(cut (cols r_ast_fr 'x') [0 2 4] null FALSE TRUE)", "key"),
    ("h2o.cut+labels", "(cut (cols r_ast_fr 'x') [0 2 4] ['lo' 'hi'] TRUE TRUE)", "key"),
    ("h2o.scale", "(scale r_ast_fr TRUE TRUE)", "key"),
    ("h2o.cor", "(cor r_ast_fr)", "key"),
    ("h2o.hist", "(hist (cols r_ast_fr 'x') 4)", "key"),
    ("h2o.levels", "(levels (cols r_ast_fr 'g'))", "string"),
    ("h2o.asfactor", "(as.factor (cols r_ast_fr 'x'))", "key"),
    ("h2o.asnumeric", "(as.numeric (cols r_ast_fr 'g'))", "key"),
    ("h2o.round", "(round (cols r_ast_fr 'x') 0)", "key"),
    ("h2o.signif", "(signif (cols r_ast_fr 'x') 2)", "key"),
    ("h2o.toupper", "(toupper (cols r_ast_fr 's'))", "key"),
    ("h2o.tolower", "(tolower (cols r_ast_fr 's'))", "key"),
    ("h2o.trim", "(trim (cols r_ast_fr 's'))", "key"),
    ("h2o.nchar", "(nchar (cols r_ast_fr 's'))", "key"),
    ("h2o.gsub", "(gsub 'i' 'I' (cols r_ast_fr 's'))", "key"),
    ("h2o.sub", "(sub 'i' 'I' (cols r_ast_fr 's'))", "key"),
    ("h2o.substring", "(substring (cols r_ast_fr 's') 0 2)", "key"),
    ("h2o.mean", "(mean (cols r_ast_fr 'x'))", "scalar"),
    ("h2o.sum", "(sum (cols r_ast_fr 'x'))", "scalar"),
    ("h2o.sd", "(sd (cols r_ast_fr 'x'))", "scalar"),
    ("h2o.var", "(var (cols r_ast_fr 'x'))", "scalar"),
    ("h2o.median", "(median (cols r_ast_fr 'x'))", "scalar"),
]


@pytest.mark.parametrize("verb,ast,field", R_VERB_ASTS, ids=[v for v, _, _ in R_VERB_ASTS])
def test_r_verb_ast(server, fr, verb, ast, field):
    out = _rapids(server, ast)
    assert out.get("http_status", 200) < 400, out
    assert out.get(field) is not None, (verb, ast, out)


def test_r_verb_semantics(server, fr):
    """Spot-check values, not just shape, for a few verbs."""
    out = _rapids(server, "(mean (cols r_ast_fr 'x'))")
    assert float(out["scalar"]) == pytest.approx(2.5)
    out = _rapids(server, "(levels (cols r_ast_fr 'g'))")
    assert "a" in out["string"] and "b" in out["string"]
    gb = _rapids(server, "(GB r_ast_fr ['g'] mean 'x' 'all')")
    key = gb["key"]["name"]
    fr2 = h2o3_tpu.get_frame(key)
    got = fr2.to_pandas().sort_values("g")
    # group a: mean(1,3,nan->skip)=2.0; group b: mean(2,4)=3.0
    assert got["mean_x"].tolist() == pytest.approx([2.0, 3.0])


def test_r_cbind_duplicate_names_suffixed(server, fr):
    """cbind with overlapping names must WIDEN, not overwrite (h2o.cbind)."""
    out = _rapids(server, "(cbind r_ast_fr r_ast_fr)")
    fr2 = h2o3_tpu.get_frame(out["key"]["name"])
    assert fr2.ncol == 6  # 3 + 3 suffixed, none dropped
    assert len(set(fr2.names)) == 6


def test_r_levels_from_frame_metadata(server, fr):
    """h2o.levels reads /3/Frames column domains (structured JSON), so
    levels with commas/quotes survive — pin the metadata shape it reads."""
    req = urllib.request.Request(server.url + "/3/Frames/r_ast_fr")
    with urllib.request.urlopen(req) as r:
        meta = json.loads(r.read())
    cols = meta["frames"][0]["columns"]
    dom = next(c["domain"] for c in cols if c["label"] == "g")
    assert dom == ["a", "b"]
