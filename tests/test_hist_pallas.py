"""Regression net for the Pallas TPU histogram kernel (ops/hist_pallas.py) —
the gpu_hist-successor the project is named for. Runs the kernel in the
Pallas interpreter (CPU CI) against the exact scatter reference over an
adversarial shape grid: tile boundaries, NA bin occupancy, categorical
codes, ragged row counts, retired rows, and the 2-term bf16 split's
accuracy bound."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from h2o3_tpu.ops.hist_pallas import NODE_TILE, ROW_TILE, hist_pallas_local
from h2o3_tpu.ops.histogram import _hist_scatter_local


def _make_case(n, c, n_nodes, n_bins, seed, na_frac=0.1, retired_frac=0.1,
               zero_w_frac=0.1):
    rng = np.random.default_rng(seed)
    bins = rng.integers(1, n_bins, size=(n, c)).astype(np.uint8)
    bins[rng.random((n, c)) < na_frac] = 0  # NA bin 0 occupied
    nid = rng.integers(0, n_nodes, size=n).astype(np.int32)
    nid[rng.random(n) < retired_frac] = -1  # retired rows
    w = rng.random(n).astype(np.float32)
    w[rng.random(n) < zero_w_frac] = 0.0  # sampled-out rows
    t = rng.normal(size=n).astype(np.float32)
    wy = w * t
    wh = w * rng.random(n).astype(np.float32)
    # production GBM shape: 3 stat lanes (w, wy, wh); the kernel is
    # S-generic and the uplift case below covers S=4
    stats = np.stack([w, wy, wh], axis=1)
    # retired rows must arrive pre-masked (histogram_in_jit's contract)
    stats[nid < 0] = 0.0
    return (jnp.asarray(bins), jnp.asarray(nid), jnp.asarray(stats))


CASES = [
    # (n_rows, n_cols, n_nodes, n_bins) — each probes a distinct boundary
    pytest.param(1000, 4, 8, 256, id="rows-not-row-tile-multiple"),
    pytest.param(ROW_TILE, 3, 1, 256, id="single-node-exact-tile"),
    pytest.param(700, 5, NODE_TILE + 16, 256, id="nodes-over-node-tile"),
    pytest.param(1300, 11, 8, 64, id="cols-over-col-tile-small-bins"),
    pytest.param(257, 2, 4, 17, id="odd-bins-lane-padding"),
]


@pytest.mark.parametrize("n,c,n_nodes,n_bins", CASES)
def test_pallas_matches_scatter(n, c, n_nodes, n_bins):
    args = _make_case(n, c, n_nodes, n_bins, seed=n + c)
    got = hist_pallas_local(*args, n_nodes, n_bins, interpret=True)
    ref = jax.jit(
        _hist_scatter_local, static_argnums=(3, 4)
    )(*args, n_nodes, n_bins)
    assert got.shape == (c, n_nodes * n_bins, 3)
    # bf16 2-term split: ~16 mantissa bits on the stats operand; the
    # contraction then accumulates in f32. Bound the relative error by the
    # per-(node,col) mass actually present (measured ~1.5e-5; single-pass
    # bf16 — the regression this guards — is ~2e-3).
    scale = np.maximum(np.abs(np.asarray(ref)), 1.0)
    err = np.abs(np.asarray(got) - np.asarray(ref)) / scale
    assert err.max() < 5e-5, f"max rel err {err.max():.2e}"


def test_pallas_f64_accuracy_bound():
    """The kernel's result tracks a float64 scatter reference to ≤5e-5 rel
    (measured ~1.5e-5) — the accuracy envelope of the 2-term bf16 MXU
    split."""
    args = _make_case(4096, 6, 32, 256, seed=9)
    got = np.asarray(hist_pallas_local(*args, 32, 256, interpret=True))
    bins, nid, stats = (np.asarray(a) for a in args)
    ref = np.zeros((6, 32 * 256, 3), np.float64)
    stats = stats.astype(np.float64)
    active = nid >= 0
    for col in range(6):
        idx = nid[active] * 256 + bins[active, col]
        np.add.at(ref[col], idx, stats[active])
    scale = np.maximum(np.abs(ref), 1.0)
    err = np.abs(got - ref) / scale
    assert err.max() < 5e-5, f"max rel err vs f64 {err.max():.2e}"


def test_pallas_retired_rows_contribute_nothing():
    args = list(_make_case(800, 3, 4, 64, seed=3, retired_frac=0.0))
    # retire every row -> histogram must be exactly zero
    args[1] = jnp.full(800, -1, jnp.int32)
    got = hist_pallas_local(*args, 4, 64, interpret=True)
    np.testing.assert_array_equal(np.asarray(got), 0.0)


def test_pallas_zero_stat_rows_contribute_nothing():
    """Sampled-out rows keep a valid nid but carry all-zero stats (the
    builder zeroes w/wy/wy²/wh); their cells must match a reference built
    with those rows removed entirely."""
    args = list(
        _make_case(800, 3, 4, 64, seed=3, retired_frac=0.0, zero_w_frac=0.0)
    )
    mask = np.zeros(800, bool)
    mask[::5] = True
    stats = np.asarray(args[2]).copy()
    stats[mask] = 0.0
    args[2] = jnp.asarray(stats)
    got = hist_pallas_local(*args, 4, 64, interpret=True)
    kept = [jnp.asarray(np.asarray(a)[~mask]) for a in args]
    ref = jax.jit(_hist_scatter_local, static_argnums=(3, 4))(*kept, 4, 64)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=1e-3)


def test_pallas_categorical_codes_roundtrip():
    """Categorical bins are plain codes 1..K; every (node, code) cell mass
    must land exactly where the scatter reference puts it."""
    rng = np.random.default_rng(4)
    n, k = 1536, 7  # 7 levels -> bins 1..7
    bins = rng.integers(1, k + 1, size=(n, 1)).astype(np.uint8)
    nid = rng.integers(0, 3, size=n).astype(np.int32)
    w = np.ones(n, np.float32)
    z = np.zeros(n, np.float32)
    # S=4 here on purpose: the kernel is stat-lane-generic (uplift runs 4)
    args = (jnp.asarray(bins), jnp.asarray(nid),
            jnp.asarray(np.stack([w, w, z, w], axis=1)))
    got = hist_pallas_local(*args, 3, k + 1, interpret=True)
    ref = jax.jit(_hist_scatter_local, static_argnums=(3, 4))(*args, 3, k + 1)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=1e-3)
    # every row accounted for: total w mass equals n
    assert abs(float(np.asarray(got)[0, :, 0].sum()) - n) < 1e-3


class TestBinAdaptivity:
    """Per-level bin coarsening (DHistogram re-binning analog) — the
    coarsened histogram must equal the coarsened full histogram, and the
    adaptive tree must match the full-bin tree's quality with full-res
    recorded thresholds."""

    def test_coarsen_hist_matches_hist_of_coarse_bins(self):
        import jax.numpy as jnp

        from h2o3_tpu.models.tree.shared_tree import (
            _coarse_nbins, _coarsen_bins, _coarsen_hist,
        )
        from h2o3_tpu.ops.histogram import histogram_in_jit

        rng = np.random.default_rng(0)
        n, c, nb = 4096, 3, 255
        bins = jnp.asarray(rng.integers(0, nb, (n, c)).astype(np.uint8))
        nid = jnp.asarray(rng.integers(0, 4, n).astype(np.int32))
        w = jnp.ones(n, jnp.float32)
        wy = jnp.asarray(rng.normal(size=n).astype(np.float32))
        full = histogram_in_jit(bins, nid, (w, wy, w), 4, nb)
        for s in (1, 2):
            nb_c = _coarse_nbins(nb, s)
            direct = histogram_in_jit(
                _coarsen_bins(bins, s), nid, (w, wy, w), 4, nb_c
            )
            via = _coarsen_hist(full, s)
            np.testing.assert_allclose(
                np.asarray(via), np.asarray(direct), rtol=1e-5, atol=1e-4
            )

    @pytest.mark.slow  # ~40 s; adaptivity is default-off (measured slower on
    # v5e) so the quality scenario runs nightly-style, the cheap coarsen
    # equivalence below stays in the default tier
    def test_adaptive_tree_quality_and_full_res_thresholds(self, monkeypatch):
        import jax
        import jax.numpy as jnp

        from h2o3_tpu.models.tree import shared_tree as st
        from h2o3_tpu.models.tree.distributions import grad_hess

        rng = np.random.default_rng(1)
        n, c = 8192, 6
        X = rng.normal(size=(n, c)).astype(np.float32)
        y = (X[:, 0] + 0.6 * X[:, 1] ** 2 + 0.3 * rng.normal(size=n) > 0.4)
        # quantile-ish binning to 255 data bins
        bins = np.zeros((n, c), np.uint8)
        for j in range(c):
            q = np.quantile(X[:, j], np.linspace(0, 1, 255)[1:-1])
            bins[:, j] = np.searchsorted(q, X[:, j]) + 1
        bins_d = jnp.asarray(bins)
        w = jnp.ones(n, jnp.float32)
        yy = jnp.asarray(y.astype(np.float32))

        def auc_of(preds):
            from sklearn.metrics import roc_auc_score

            return roc_auc_score(y, np.asarray(preds))

        def train(adapt):
            monkeypatch.setenv("H2O3_TPU_BIN_ADAPT", "1" if adapt else "0")
            st._STEP_CACHE.clear()
            F, vi, stacked = st.build_trees_scanned(
                bins_d, w, yy, jnp.zeros(n, jnp.float32),
                jnp.zeros(c, jnp.float32), jax.random.PRNGKey(0), 10,
                grad_fn=lambda F_, y_, w_: grad_hess("bernoulli", F_, y_, w_, 0.0),
                grad_key=("adapt_test", adapt),
                sample_rate=1.0, n_bins=255, is_cat_cols=np.zeros(c, bool),
                max_depth=6, min_rows=10.0, min_split_improvement=1e-5,
                learn_rates=np.full(10, 0.3, np.float32),
                max_abs_leaf=float("inf"),
                col_sample_rate=1.0, col_sample_rate_per_tree=1.0,
            )
            trees = st.trees_from_stacked(stacked, 10)
            return np.asarray(F), trees

        try:
            f_off, _ = train(False)
            f_on, trees_on = train(True)
        finally:
            st._STEP_CACHE.clear()
        a_off, a_on = auc_of(f_off), auc_of(f_on)
        assert a_on > a_off - 0.01, (a_on, a_off)
        # recorded thresholds are FULL-resolution: replaying the adaptive
        # trees against the full-res bins reproduces the training scores
        preds = jnp.zeros(n, jnp.float32)
        for t in trees_on:
            _, preds = t.replay(bins_d, jnp.zeros(n, jnp.int32), preds)
        np.testing.assert_allclose(np.asarray(preds), f_on, rtol=1e-5, atol=1e-5)


def test_scatter_chunked_matches_unchunked(monkeypatch):
    """The lax.scan row-chunked scatter (memory bound for big shards) must
    agree with the single-chunk path it replaces. Chunk forced tiny so the
    test exercises padding + multi-chunk accumulation."""
    from h2o3_tpu.ops import histogram as H

    rng = np.random.default_rng(3)
    n, c, n_nodes, n_bins = 1000, 5, 8, 16
    bins = jnp.asarray(rng.integers(0, n_bins, (n, c)).astype(np.uint8))
    nid = jnp.asarray(rng.integers(-1, n_nodes, n).astype(np.int32))
    w = np.asarray(rng.random(n).astype(np.float32))
    wy = np.asarray(rng.normal(size=n).astype(np.float32))
    stats = np.stack([w, wy, w], axis=1)
    stats[np.asarray(nid) < 0] = 0.0  # pre-masked, per the local-impl contract
    stats = jnp.asarray(stats)
    ref = H._hist_scatter_local(bins, nid, stats, n_nodes, n_bins)
    monkeypatch.setattr(H, "_SCATTER_ROW_CHUNK", 96)  # 1000 -> 11 chunks + pad
    out = H._hist_scatter_local(bins, nid, stats, n_nodes, n_bins)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-6, atol=1e-6)
