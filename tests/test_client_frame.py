"""Lazy client-side H2OFrame (expr.py successor) against a live server."""

import numpy as np
import pandas as pd
import pytest

from h2o3_tpu.client import H2OConnection
from h2o3_tpu.client_frame import H2OFrame
from h2o3_tpu.frame.frame import Frame


@pytest.fixture(scope="module")
def server():
    from h2o3_tpu.api.server import H2OServer

    srv = H2OServer(port=54381)
    srv.start()
    yield H2OConnection("http://127.0.0.1:54381")
    srv.stop()


@pytest.fixture(scope="module")
def data(server):
    rng = np.random.default_rng(0)
    n = 2000
    df = pd.DataFrame(
        {"age": rng.integers(18, 80, n).astype(float),
         "income": rng.normal(50, 12, n),
         "grp": rng.choice(["a", "b"], n)}
    )
    Frame.from_pandas(df, destination_frame="lazy_src", register=True)
    return df


def test_lazy_is_lazy_then_evaluates(server, data):
    fr = H2OFrame.from_key(server, "lazy_src")
    expr = (fr["income"] + 10) / 2
    assert expr._key is None  # nothing sent yet
    got = expr.mean()
    want = float((data["income"] + 10).mean() / 2)
    assert abs(got - want) < 1e-4


def test_lazy_filter_rows(server, data):
    fr = H2OFrame.from_key(server, "lazy_src")
    old = fr[fr["age"] > 50]
    n_old, ncol = old.shape
    assert n_old == int((data["age"] > 50).sum())
    assert ncol == 3
    m = old["income"].mean()
    want = float(data.loc[data["age"] > 50, "income"].mean())
    assert abs(m - want) < 1e-3


def test_lazy_to_pandas_roundtrip(server, data):
    fr = H2OFrame.from_key(server, "lazy_src")
    sub = fr[["age", "income"]]
    pdf = sub.to_pandas()
    assert list(pdf.columns) == ["age", "income"]
    assert len(pdf) == len(data)
    np.testing.assert_allclose(
        np.sort(pdf["age"]), np.sort(data["age"]), rtol=1e-6
    )


def test_lazy_group_by(server, data):
    fr = H2OFrame.from_key(server, "lazy_src")
    agg = fr.group_by("grp", income="mean").to_pandas()
    want = data.groupby("grp")["income"].mean()
    got = dict(zip(agg.iloc[:, 0], agg.iloc[:, 1]))
    for g in ("a", "b"):
        assert abs(got[g] - want[g]) < 1e-3


def test_lazy_ifelse_and_reuse(server, data):
    fr = H2OFrame.from_key(server, "lazy_src")
    flag = (fr["age"] > 50).ifelse(1.0, 0.0)
    s = flag.sum()
    assert s == int((data["age"] > 50).sum())
    # refresh() materializes once; later ops reference the temp key
    flag.refresh()
    assert flag._key is not None
    assert flag.sum() == s


def test_lazy_match_in_na_omit(server, data):
    fr = H2OFrame.from_key(server, "lazy_src")
    m = fr["grp"].match(["b", "a"])  # default nomatch=NaN must render
    got = m.to_pandas().iloc[:, 0]
    want = data["grp"].map({"b": 1, "a": 2})
    assert (got.fillna(-1) == want.fillna(-1)).all()
    flags = fr["grp"].isin(["a"]).to_pandas().iloc[:, 0]
    assert (flags == (data["grp"] == "a").astype(float)).all()
    no = fr.na_omit()
    assert no.to_pandas().shape[0] <= len(data)


def test_lazy_round4_breadth(server, data):
    """Round-4 lazy surface: cum/diff/fillna/round, moment + boolean
    reductions, string helpers — all ship as Rapids ASTs."""
    fr = H2OFrame.from_key(server, "lazy_src")
    inc = fr["income"]

    cs = inc.cumsum().to_pandas().iloc[:, 0].to_numpy()
    np.testing.assert_allclose(cs[:5], np.cumsum(data["income"])[:5], rtol=1e-5)

    d = inc.difflag1().to_pandas().iloc[:, 0].to_numpy()
    assert np.isnan(d[0])
    np.testing.assert_allclose(d[1:4], np.diff(data["income"])[:3], rtol=1e-4)

    r = inc.round(1).to_pandas().iloc[:, 0].to_numpy()
    np.testing.assert_allclose(r[:5], np.round(data["income"][:5], 1), atol=0.06)

    sk = inc.skewness()
    x = data["income"].to_numpy()
    m, s = x.mean(), x.std()
    assert abs(sk - ((x - m) ** 3).mean() / s**3) < 1e-6
    assert fr["age"].anyna() is False
    assert (fr["age"] > 17).all() is True

    up = fr["grp"].toupper().to_pandas().iloc[:, 0].tolist()
    assert set(up[:10]) <= {"A", "B"}


def test_client_split_frame(server, data):
    fr = H2OFrame.from_key(server, "lazy_src")
    tr, te = fr.split_frame([0.7], seed=9)
    n_tr, _ = tr.shape
    n_te, _ = te.shape
    assert n_tr + n_te == len(data)
    assert 0.55 * len(data) < n_tr < 0.85 * len(data)
    # split parts are real server frames usable in further expressions
    assert abs(tr["income"].mean() - data["income"].mean()) < data["income"].std()
