"""Fake-multiprocess recovery harness (ISSUE 10 satellite; ROADMAP item 2
asks for this explicitly): a subprocess-based TWO-PROCESS cloud pytest
fixture that drives the degraded latch, generation fencing, and supervised
recovery across a real ``jax.distributed`` process boundary.

Reuses the PR-4 bounded capability probe from test_multihost: jaxlib builds
that refuse cross-process CPU collectives (this CI container among them)
auto-skip with the root cause instead of carrying environmental failures as
red — the tests run for real on any host whose jaxlib allows it.
"""

import json
import os
import socket
import subprocess
import sys
import time
import urllib.error
import urllib.parse
import urllib.request

import pytest

from test_multihost import _skip_unless_two_process_capable


@pytest.fixture()
def two_process_cloud(tmp_path):
    """Boot a 2-process launch.py cloud (2 CPU devices per process) with a
    synthetic dead-member fault armed on the first replicated command and
    the recovery supervisor enabled. Yields the coordinator's REST base URL;
    tears both processes down (and dumps log tails) afterwards."""
    _skip_unless_two_process_capable()
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        coord_port = s.getsockname()[1]
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        rest_port = s.getsockname()[1]
    env = dict(
        os.environ,
        XLA_FLAGS="--xla_force_host_platform_device_count=2",
        JAX_PLATFORMS="cpu",
        # the coordinator's first replicated command dies with a
        # coordination-service signature (one-shot) — the degraded-latch
        # driver; followers never call spmd.run, so only rank 0 raises
        H2O3_TPU_FAULTS="death:spmd_run",
        H2O3_TPU_RECOVERY="1",
        # keep the launch.py background watcher's auto-reform far away
        # (30 s backoff): the test drives the reform explicitly through
        # POST /3/Recover so the latched window is observable first
        H2O3_TPU_RECOVERY_BACKOFF="30",
    )
    env.pop("PALLAS_AXON_POOL_IPS", None)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    logs = [open(tmp_path / f"rproc{i}.log", "wb") for i in range(2)]
    procs = [
        subprocess.Popen(
            [sys.executable, "-m", "h2o3_tpu.launch",
             "--coordinator", f"127.0.0.1:{coord_port}",
             "--num-processes", "2", "--process-id", str(i),
             "--ip", "127.0.0.1", "--port", str(rest_port)],
            stdout=logs[i], stderr=subprocess.STDOUT, cwd=repo, env=env,
        )
        for i in range(2)
    ]
    base = f"http://127.0.0.1:{rest_port}"
    try:
        deadline = time.time() + 120
        up = False
        while time.time() < deadline and not up:
            if any(p.poll() is not None for p in procs):
                break
            try:
                _req(base, "GET", "/3/Ping", timeout=5)
                up = True
            except Exception:
                time.sleep(1.0)
        assert up, "coordinator REST never came up"
        yield base
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for p in procs:
            try:
                p.wait(timeout=20)
            except subprocess.TimeoutExpired:
                p.kill()
        for f in logs:
            f.close()
        for i in range(2):
            sys.stderr.write(f"--- rproc{i} log tail ---\n")
            tail = (tmp_path / f"rproc{i}.log").read_bytes()[-2000:]
            sys.stderr.write(tail.decode(errors="replace") + "\n")


def _req(base, method, path, data=None, timeout=60):
    body = urllib.parse.urlencode(data).encode() if data else None
    r = urllib.request.Request(base + path, data=body, method=method)
    return json.loads(urllib.request.urlopen(r, timeout=timeout).read())


@pytest.mark.slow
def test_cross_process_latch_recover_and_fenced_commands(two_process_cloud):
    """The full cross-process self-healing sequence on a REAL two-process
    cloud: (1) the armed death signature latches the degraded fail-stop on
    the coordinator's first replicated command and /3/Cloud reports it;
    (2) a queued command fail-stops instead of broadcasting into the dead
    cloud; (3) POST /3/Recover re-forms — generation 0 -> 1; (4) a fresh
    replicated command carries the new stamp, the FOLLOWER adopts the
    generation through the command stream, and the command executes on both
    ranks (the CreateFrame result proves follower participation: replicated
    commands hang without it)."""
    base = two_process_cloud

    # (1) first replicated command dies with the death signature → latch
    with pytest.raises(urllib.error.HTTPError) as ei:
        _req(base, "POST", "/3/CreateFrame",
             {"dest": "mp0", "rows": "100", "cols": "2", "seed": "1"})
    assert ei.value.code >= 500
    cloud = _req(base, "GET", "/3/Cloud")
    assert cloud["cloud_healthy"] is False
    assert "degraded" in cloud and cloud["generation"] == 0

    # (2) queued commands fail-stop at admission, never broadcast
    with pytest.raises(urllib.error.HTTPError) as ei:
        _req(base, "POST", "/3/CreateFrame",
             {"dest": "mp1", "rows": "100", "cols": "2", "seed": "1"})
    assert ei.value.code >= 500

    # (3) supervised reform over REST: degraded → recovering → healthy
    out = _req(base, "POST", "/3/Recover", {})
    assert out["recovered"] is True and out["generation"] == 1
    cloud = _req(base, "GET", "/3/Cloud")
    assert cloud["cloud_healthy"] is True and cloud["generation"] == 1

    # (4) post-reform replicated command: the follower adopts generation 1
    # from the command stamp and executes — cross-process again
    cf = _req(base, "POST", "/3/CreateFrame",
              {"dest": "mp2", "rows": "300", "cols": "3", "seed": "2",
               "has_response": "true"}, timeout=120)
    assert cf["rows"] == 300
    fr = _req(base, "GET", "/3/Frames/mp2")["frames"][0]
    assert fr["rows"] == 300
