"""Multi-host + observability smoke (SURVEY.md §4 CI strategy row, §5.1/§5.8).

The 2-process jax.distributed test backs the multi-host claim in
cluster/cloud.py: two OS processes form a cloud through the coordination
service (the Paxos successor) and run a psum across both processes' devices.
"""

import os
import socket
import subprocess
import sys
import textwrap

import pytest


@pytest.mark.slow
def test_two_process_jax_distributed_psum(tmp_path):
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    prog = textwrap.dedent(f"""
        import os, sys
        os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ.pop("PALLAS_AXON_POOL_IPS", None)
        import jax
        jax.config.update("jax_platforms", "cpu")
        import numpy as np
        import jax.numpy as jnp
        from jax.sharding import Mesh, PartitionSpec as P

        pid = int(sys.argv[1])
        import h2o3_tpu
        info = h2o3_tpu.init(coordinator="127.0.0.1:{port}", num_processes=2,
                             process_id=pid)
        assert info["processes"] == 2, info
        assert info["cloud_size"] == 4, info  # 2 procs x 2 local cpu devices

        # a psum over the GLOBAL mesh — the MRTask.reduce successor crossing
        # the process boundary. The global array is assembled from each
        # process's addressable shards of one logical numpy array.
        from jax.sharding import NamedSharding
        mesh = Mesh(np.array(jax.devices()).reshape(4), ("rows",))
        sharding = NamedSharding(mesh, P("rows"))
        np_global = np.arange(8.0)
        x = jax.make_array_from_callback((8,), sharding, lambda idx: np_global[idx])
        def body(x):
            return jax.lax.psum(jnp.sum(x), "rows")
        out = jax.jit(jax.shard_map(body, mesh=mesh, in_specs=P("rows"),
                                    out_specs=P()))(x)
        total = float(np.asarray(jax.device_get(out.addressable_shards[0].data)))
        assert total == 28.0, total  # sum(0..7)
        print(f"proc {{pid}} OK total={{total}}")
    """)
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", prog, str(i)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        for i in range(2)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=180)
        outs.append(out.decode())
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} failed:\n{out[-3000:]}"
        assert f"proc {i} OK total=28.0" in out


def test_timeline_records_compiles():
    import jax.numpy as jnp
    import jax

    import h2o3_tpu
    from h2o3_tpu.utils import telemetry

    h2o3_tpu.init()
    # force a fresh compile with a unique shape
    jax.jit(lambda x: x * 3 + 1)(jnp.ones(173)).block_until_ready()
    tl = telemetry.timeline()
    assert tl["compile_count"] >= 1
    assert any(e["kind"] == "compile" for e in tl["events"])


def test_profiler_writes_trace(tmp_path):
    import jax.numpy as jnp

    import h2o3_tpu

    h2o3_tpu.init()
    logdir = str(tmp_path / "prof")
    with h2o3_tpu.profiler(logdir):
        (jnp.ones(64) * 2).block_until_ready()
    import glob

    assert glob.glob(logdir + "/**/*.xplane.pb", recursive=True)
