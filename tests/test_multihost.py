"""Multi-host + observability smoke (SURVEY.md §4 CI strategy row, §5.1/§5.8).

The 2-process jax.distributed test backs the multi-host claim in
cluster/cloud.py: two OS processes form a cloud through the coordination
service (the Paxos successor) and run a psum across both processes' devices.
"""

import os
import socket
import subprocess
import sys
import textwrap

import pytest

# ---------------------------------------------------------------------------
# environment probe: some jaxlib builds (including this CI container's)
# accept jax.distributed.initialize but then refuse CROSS-PROCESS
# computations on the CPU backend ("Multiprocess computations aren't
# implemented on the CPU backend"). The two-process tests below cannot pass
# there for environmental reasons — probe ONCE (bounded) and auto-skip with
# the real reason instead of carrying known-environmental failures as red.

_TWO_PROC_REASON: str | None = None  # None = not probed; "" = capable


def _two_process_blocker() -> str:
    global _TWO_PROC_REASON
    if _TWO_PROC_REASON is not None:
        return _TWO_PROC_REASON
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    prog = textwrap.dedent(f"""
        import os, sys
        os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ.pop("PALLAS_AXON_POOL_IPS", None)
        import jax
        jax.config.update("jax_platforms", "cpu")
        jax.distributed.initialize(coordinator_address="127.0.0.1:{port}",
                                   num_processes=2,
                                   process_id=int(sys.argv[1]))
        assert jax.device_count() == 4, jax.device_count()
        # the real capability test: an actual cross-process collective
        import numpy as np
        from jax.experimental import multihost_utils as mh
        out = mh.broadcast_one_to_all(np.array([7], np.int32))
        assert int(out[0]) == 7, out
        print("PROBE OK", sys.argv[1])
    """)
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", prog, str(i)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        )
        for i in range(2)
    ]
    outs, timed_out = [], False
    for p in procs:
        try:
            out, _ = p.communicate(timeout=90)
            outs.append(out.decode(errors="replace"))
        except subprocess.TimeoutExpired:
            p.kill()
            timed_out = True
            outs.append("")
    if timed_out:
        _TWO_PROC_REASON = "2-process jax.distributed probe timed out (90s)"
    elif all(p.returncode == 0 for p in procs):
        _TWO_PROC_REASON = ""
    else:
        # surface the root-cause line when recognizable, else the tail
        joined = "\n".join(outs)
        reason = next(
            (ln.strip() for ln in joined.splitlines()
             if "Error" in ln or "error" in ln), joined[-300:])
        _TWO_PROC_REASON = reason[-300:]
    return _TWO_PROC_REASON


def _skip_unless_two_process_capable() -> None:
    reason = _two_process_blocker()
    if reason:
        pytest.skip(
            "two-process jax.distributed is unavailable in this environment "
            f"(auto-skip, pre-existing environmental limitation): {reason}"
        )


@pytest.mark.slow
def test_two_process_jax_distributed_psum(tmp_path):
    _skip_unless_two_process_capable()
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    prog = textwrap.dedent(f"""
        import os, sys
        os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ.pop("PALLAS_AXON_POOL_IPS", None)
        import jax
        jax.config.update("jax_platforms", "cpu")
        import numpy as np
        import jax.numpy as jnp
        from jax.sharding import Mesh, PartitionSpec as P

        pid = int(sys.argv[1])
        import h2o3_tpu
        info = h2o3_tpu.init(coordinator="127.0.0.1:{port}", num_processes=2,
                             process_id=pid)
        assert info["processes"] == 2, info
        assert info["cloud_size"] == 4, info  # 2 procs x 2 local cpu devices

        # a psum over the GLOBAL mesh — the MRTask.reduce successor crossing
        # the process boundary. The global array is assembled from each
        # process's addressable shards of one logical numpy array.
        from jax.sharding import NamedSharding
        mesh = Mesh(np.array(jax.devices()).reshape(4), ("rows",))
        sharding = NamedSharding(mesh, P("rows"))
        np_global = np.arange(8.0)
        x = jax.make_array_from_callback((8,), sharding, lambda idx: np_global[idx])
        def body(x):
            return jax.lax.psum(jnp.sum(x), "rows")
        out = jax.jit(jax.shard_map(body, mesh=mesh, in_specs=P("rows"),
                                    out_specs=P()))(x)
        total = float(np.asarray(jax.device_get(out.addressable_shards[0].data)))
        assert total == 28.0, total  # sum(0..7)
        print(f"proc {{pid}} OK total={{total}}")
    """)
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", prog, str(i)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        for i in range(2)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=180)
        outs.append(out.decode())
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} failed:\n{out[-3000:]}"
        assert f"proc {i} OK total=28.0" in out


def test_timeline_records_compiles():
    import jax.numpy as jnp
    import jax

    import h2o3_tpu
    from h2o3_tpu.utils import telemetry

    h2o3_tpu.init()
    # force a fresh compile with a unique shape
    jax.jit(lambda x: x * 3 + 1)(jnp.ones(173)).block_until_ready()
    tl = telemetry.timeline()
    assert tl["compile_count"] >= 1
    assert any(e["kind"] == "compile" for e in tl["events"])


def test_profiler_writes_trace(tmp_path):
    import jax.numpy as jnp

    import h2o3_tpu

    h2o3_tpu.init()
    logdir = str(tmp_path / "prof")
    with h2o3_tpu.profiler(logdir):
        (jnp.ones(64) * 2).block_until_ready()
    import glob

    assert glob.glob(logdir + "/**/*.xplane.pb", recursive=True)


def test_launch_rest_train_across_two_processes(tmp_path):
    """End-to-end multi-host: two launch.py processes form a cloud; a GBM
    trains THROUGH REST with the spmd command replication executing the same
    device programs on both ranks (VERDICT r3 item 3 / SURVEY §4 multi-node
    row). Default tier: tiny shapes, 2 CPU devices per process."""
    _skip_unless_two_process_capable()
    import json
    import time
    import urllib.error
    import urllib.request

    import numpy as np
    import pandas as pd

    rng = np.random.default_rng(1)
    n = 400
    X = rng.normal(size=(n, 3))
    y = (X[:, 0] + 0.5 * X[:, 1] > 0)
    df = pd.DataFrame(X, columns=["a", "b", "c"])
    df["label"] = np.where(y, "p", "n")
    csv = tmp_path / "mh.csv"
    df.to_csv(csv, index=False)

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        coord_port = s.getsockname()[1]
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        rest_port = s.getsockname()[1]

    env = dict(
        os.environ,
        XLA_FLAGS="--xla_force_host_platform_device_count=2",
        JAX_PLATFORMS="cpu",
    )
    env.pop("PALLAS_AXON_POOL_IPS", None)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    logs = [open(tmp_path / f"proc{i}.log", "wb") for i in range(2)]
    procs = [
        subprocess.Popen(
            [sys.executable, "-m", "h2o3_tpu.launch",
             "--coordinator", f"127.0.0.1:{coord_port}",
             "--num-processes", "2", "--process-id", str(i),
             "--ip", "127.0.0.1", "--port", str(rest_port)],
            stdout=logs[i], stderr=subprocess.STDOUT, cwd=repo, env=env,
        )
        for i in range(2)
    ]

    base = f"http://127.0.0.1:{rest_port}"

    def req(method, path, data=None, timeout=60):
        import urllib.parse

        body = urllib.parse.urlencode(data).encode() if data else None
        r = urllib.request.Request(base + path, data=body, method=method)
        return json.loads(urllib.request.urlopen(r, timeout=timeout).read())

    try:
        # wait for the coordinator's REST to come up
        deadline = time.time() + 120
        cloud = None
        while time.time() < deadline:
            if any(p.poll() is not None for p in procs):
                break
            try:
                cloud = req("GET", "/3/Cloud", timeout=5)
                break
            except Exception:
                time.sleep(1.0)
        assert cloud is not None, "REST coordinator never came up"
        assert cloud["cloud_size"] == 4  # 2 procs x 2 devices

        req("POST", "/3/ImportFiles", {"path": str(csv)})
        req("POST", "/3/Parse", {"source_frames": str(csv),
                                 "destination_frame": "mh"})
        job = req("POST", "/3/ModelBuilders/gbm",
                  {"training_frame": "mh", "response_column": "label",
                   "ntrees": "3", "max_depth": "3", "seed": "7"})
        jid = (job.get("job") or job)["key"]["name"]
        deadline = time.time() + 240
        status = None
        while time.time() < deadline:
            j = req("GET", f"/3/Jobs/{jid}")["jobs"][0]
            status = j["status"]
            if status in ("DONE", "FAILED", "CANCELLED"):
                break
            time.sleep(1.0)
        assert status == "DONE", f"build ended {status}: {j.get('exception')}"
        mkey = j["dest"]["name"]
        mm = req("GET", f"/3/Models/{mkey}")["models"][0]
        auc = mm["output"]["training_metrics"]["auc"]
        assert auc > 0.8, auc

        pred = req("POST", f"/3/Predictions/models/{mkey}/frames/mh", {})
        assert pred["predictions_frame"]["name"]

        # -- spmd v3 surfaces on the SAME live cloud (boot is the expensive
        # part): Rapids eval, frame summary, CSV download, export, and
        # binary model save + load all replicate across both ranks --------
        r = req("POST", "/99/Rapids",
                {"ast": "(tmp= mh_sub (cols_py mh ['a' 'b']))"})
        assert r["num_cols"] == 2 and r["num_rows"] == 400, r
        r = req("POST", "/99/Rapids", {"ast": "(mean (cols_py mh 'a'))"})
        assert "scalar" in r or "key" in r, r

        s = req("GET", "/3/Frames/mh/summary")
        assert s["summary"], s
        # the replicated describe cached rollups: plain frame GET now serves
        # real per-column stats even on the multi-process cloud
        fg = req("GET", "/3/Frames/mh")["frames"][0]
        acol = next(c for c in fg["columns"] if c["label"] == "a")
        assert acol["mean"] is not None

        raw = urllib.request.urlopen(
            f"{base}/3/DownloadDataset?frame_id=mh", timeout=60).read()
        assert raw.decode().count("\n") >= 400

        out_csv = tmp_path / "mh_export.csv"
        req("POST", "/3/Frames/mh/export",
            {"path": str(out_csv), "force": "true"})
        assert out_csv.exists() and out_csv.stat().st_size > 1000

        sv = req("POST", f"/99/Models.bin/{mkey}", {"dir": str(tmp_path)})
        assert sv["dir"], sv
        lr = req("POST", "/99/Models.bin", {"dir": sv["dir"]})
        assert lr["models"][0]["model_id"]["name"] == mkey
        pred2 = req("POST", f"/3/Predictions/models/{mkey}/frames/mh", {})
        assert pred2["predictions_frame"]["name"]

        # unseeded random ops must be rejected (cross-rank divergence)
        try:
            req("POST", "/99/Rapids", {"ast": "(tmp= rnd (h2o.runif mh -1))"})
            raise AssertionError("unseeded h2o.runif should 4xx on a "
                                 "multi-process cloud")
        except urllib.error.HTTPError as e:
            assert e.code in (400, 412), e.code
        r = req("POST", "/99/Rapids", {"ast": "(tmp= rnd (h2o.runif mh 42))"})
        assert r["num_rows"] == 400, r

        # frame-utility commands replicate on the same live cloud:
        # SplitFrame (seeded), CreateFrame (coordinator-drawn seed),
        # Interaction — then a model trains on a replicated product
        sp = req("POST", "/3/SplitFrame",
                 {"dataset": "mh", "ratios": "[0.75]",
                  "destination_frames": '["mh_tr", "mh_te"]', "seed": "5"})
        tr_rows = req("GET", "/3/Frames/mh_tr")["frames"][0]["rows"]
        te_rows = req("GET", "/3/Frames/mh_te")["frames"][0]["rows"]
        assert tr_rows + te_rows == 400, (tr_rows, te_rows)
        cf = req("POST", "/3/CreateFrame",
                 {"dest": "mh_cf", "rows": "300", "cols": "4",
                  "categorical_fraction": "0.5", "factors": "3",
                  "has_response": "true"})
        assert cf["rows"] == 300, cf
        it = req("POST", "/3/Interaction",
                 {"source_frame": "mh_cf", "factor_columns": '["C3", "C4"]'})
        ikey = it["destination_frame"]["name"]
        ifr = req("GET", f"/3/Frames/{ikey}")["frames"][0]
        assert ifr["columns"][0]["type"] == "enum", ifr
        job2 = req("POST", "/3/ModelBuilders/gbm",
                   {"training_frame": "mh_tr", "response_column": "label",
                    "ntrees": "2", "max_depth": "2", "seed": "3"})
        jid2 = job2["job"]["key"]["name"]
        deadline = time.time() + 180
        while time.time() < deadline:
            j2 = req("GET", f"/3/Jobs/{jid2}")["jobs"][0]
            if j2["status"] in ("DONE", "FAILED", "CANCELLED"):
                break
            time.sleep(1.0)
        assert j2["status"] == "DONE", j2.get("exception")
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=20)
            except subprocess.TimeoutExpired:
                p.kill()
        for f in logs:
            f.close()
        for i in range(2):
            sys.stderr.write(f"--- proc{i} log tail ---\n")
            tail = (tmp_path / f"proc{i}.log").read_bytes()[-2000:]
            sys.stderr.write(tail.decode(errors="replace") + "\n")


def test_sharded_parse_single_process(tmp_path):
    """parse_sharded degenerates to a plain parse on one process — values,
    domains and NA placement must match the eager reader."""
    import numpy as np
    import pandas as pd

    import h2o3_tpu
    from h2o3_tpu.frame.parse import parse, parse_sharded

    rng = np.random.default_rng(3)
    n = 3001  # deliberately not a shard multiple
    df = pd.DataFrame({
        "x": rng.normal(size=n),
        "g": rng.choice(["u", "v", "w"], n),
        "i": rng.integers(0, 9, n),
    })
    df.loc[::13, "x"] = np.nan
    csv = tmp_path / "s.csv"
    df.to_csv(csv, index=False)
    a = parse({"source_frames": [str(csv)]}, destination_frame="sp_a")
    b = parse_sharded({"source_frames": [str(csv)]}, destination_frame="sp_b")
    assert b.nrow == a.nrow == n
    np.testing.assert_allclose(
        b.vec("x").to_numpy(), a.vec("x").to_numpy(), rtol=1e-6
    )
    assert tuple(b.vec("g").domain) == tuple(a.vec("g").domain)
    np.testing.assert_array_equal(b.vec("g").to_numpy(), a.vec("g").to_numpy())


def test_sharded_parse_two_processes(tmp_path):
    """Each rank parses ONLY its own row range (ParseDataset distributed
    ingest successor) and the global frame is correct: per-rank host reads
    are asserted disjoint and the global sums match the full-file truth."""
    _skip_unless_two_process_capable()
    import numpy as np
    import pandas as pd

    rng = np.random.default_rng(9)
    n = 5000
    df = pd.DataFrame({
        "x": rng.normal(size=n),
        "g": rng.choice(["aa", "bb", "cc", "dd"], n),
    })
    csv = tmp_path / "mh2.csv"
    df.to_csv(csv, index=False)
    want_sum = float(np.nansum(df["x"]))

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    prog = textwrap.dedent(f"""
        import os, sys
        os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ.pop("PALLAS_AXON_POOL_IPS", None)
        import jax
        jax.config.update("jax_platforms", "cpu")
        import numpy as np
        pid = int(sys.argv[1])
        import h2o3_tpu
        h2o3_tpu.init(coordinator="127.0.0.1:{port}", num_processes=2, process_id=pid)
        import pandas as pd
        reads = {{}}
        orig = pd.read_csv
        def spy(path, *a, **k):
            out = orig(path, *a, **k)
            if str(path).endswith("mh2.csv"):
                reads.setdefault("rows", []).append(len(out))
            return out
        pd.read_csv = spy
        from h2o3_tpu.frame.parse import parse_sharded
        from h2o3_tpu.cluster import spmd
        fr = parse_sharded({{"source_frames": [{str(csv)!r}]}}, destination_frame="mh2")
        assert fr.nrow == {n}, fr.nrow
        # the big read this rank did must be ONLY its range (< 60% of rows)
        big = max(reads["rows"])
        assert big <= 0.6 * {n}, big
        with spmd.replicated_section():
            x = fr.vec("x").to_numpy()
            g = fr.vec("g").to_numpy()
        assert abs(float(np.nansum(x)) - {want_sum!r}) < 1e-3
        assert g.min() >= 0 and tuple(fr.vec("g").domain) == ("aa", "bb", "cc", "dd")
        print(f"proc {{pid}} OK sharded ingest")
    """)
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", prog, str(i)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        for i in range(2)
    ]
    outs = [p.communicate(timeout=240)[0].decode() for p in procs]
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} failed:\n{out[-3000:]}"
        assert f"proc {i} OK sharded ingest" in out


@pytest.mark.slow
def test_grid_over_rest_across_two_processes(tmp_path):
    """Grid search replicates as ONE spmd command: the deterministic key
    sequence keeps every rank's grid-model keys aligned (registry.make_key
    replicated mode), so /99/Grids and predictions work afterwards."""
    _skip_unless_two_process_capable()
    import json
    import time
    import urllib.parse
    import urllib.request

    import numpy as np
    import pandas as pd

    rng = np.random.default_rng(4)
    n = 400
    X = rng.normal(size=(n, 3))
    df = pd.DataFrame(X, columns=["a", "b", "c"])
    df["label"] = np.where(X[:, 0] + 0.5 * X[:, 1] > 0, "p", "n")
    csv = tmp_path / "grid.csv"
    df.to_csv(csv, index=False)

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        coord_port = s.getsockname()[1]
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        rest_port = s.getsockname()[1]
    env = dict(os.environ, XLA_FLAGS="--xla_force_host_platform_device_count=2",
               JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    logs = [open(tmp_path / f"gproc{i}.log", "wb") for i in range(2)]
    procs = [
        subprocess.Popen(
            [sys.executable, "-m", "h2o3_tpu.launch",
             "--coordinator", f"127.0.0.1:{coord_port}",
             "--num-processes", "2", "--process-id", str(i),
             "--ip", "127.0.0.1", "--port", str(rest_port)],
            stdout=logs[i], stderr=subprocess.STDOUT, cwd=repo, env=env,
        )
        for i in range(2)
    ]
    base = f"http://127.0.0.1:{rest_port}"

    def req(method, path, data=None, as_json=False, timeout=60):
        if as_json:
            body = json.dumps(data).encode()
            r = urllib.request.Request(base + path, data=body, method=method,
                                       headers={"Content-Type": "application/json"})
        else:
            body = urllib.parse.urlencode(data).encode() if data else None
            r = urllib.request.Request(base + path, data=body, method=method)
        return json.loads(urllib.request.urlopen(r, timeout=timeout).read())

    try:
        deadline = time.time() + 120
        up = False
        while time.time() < deadline and not up:
            try:
                req("GET", "/3/Ping", timeout=5)
                up = True
            except Exception:
                time.sleep(1.0)
        assert up, "coordinator REST never came up"

        req("POST", "/3/ImportFiles", {"path": str(csv)})
        pj = req("POST", "/3/Parse", {"source_frames": str(csv),
                                      "destination_frame": "gfr"})
        pjid = pj["job"]["key"]["name"]
        while req("GET", f"/3/Jobs/{pjid}")["jobs"][0]["status"] not in ("DONE", "FAILED"):
            time.sleep(0.5)

        g = req("POST", "/99/Grid/gbm", {
            "training_frame": "gfr", "response_column": "label",
            "ntrees": 3, "max_depth": 2, "seed": 3,
            "hyper_parameters": {"learn_rate": [0.1, 0.3]},
        }, as_json=True)
        gid = g["grid_id"]["name"]
        jid = g["job"]["key"]["name"]
        deadline = time.time() + 300
        while time.time() < deadline:
            j = req("GET", f"/3/Jobs/{jid}")["jobs"][0]
            if j["status"] in ("DONE", "FAILED", "CANCELLED"):
                break
            time.sleep(1.0)
        assert j["status"] == "DONE", j.get("exception")
        grid = req("GET", f"/99/Grids/{gid}")["grids"][0]
        ids = [m["name"] for m in grid.get("model_ids", [])]
        assert len(ids) == 2, grid
        # the grid's models are predictable cross-rank (keys aligned)
        pred = req("POST", f"/3/Predictions/models/{ids[0]}/frames/gfr", {})
        assert pred["predictions_frame"]["name"]
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=20)
            except subprocess.TimeoutExpired:
                p.kill()
        for f in logs:
            f.close()
        for i in range(2):
            sys.stderr.write(f"--- gproc{i} tail ---\n")
            sys.stderr.write((tmp_path / f"gproc{i}.log").read_bytes()[-1500:]
                             .decode(errors="replace") + "\n")


@pytest.mark.slow
def test_dead_rank_fails_stop(tmp_path):
    """SURVEY §5.3 failure semantics: killing a member kills the CLOUD within
    the heartbeat bound — the jax distributed runtime aborts every surviving
    process when a task stops heartbeating (observed: "Terminating process
    because the JAX distributed service detected fatal errors"). That is
    exactly H2O's fail-stop contract (a dead node makes the cluster
    unusable; restart + checkpoints are the recovery path). The assertion is
    BOUNDED DEATH, not survival: the coordinator must exit, not hang."""
    _skip_unless_two_process_capable()
    import json
    import signal
    import time
    import urllib.parse
    import urllib.request

    import numpy as np
    import pandas as pd

    rng = np.random.default_rng(2)
    df = pd.DataFrame(rng.normal(size=(300, 3)), columns=["a", "b", "c"])
    df["label"] = np.where(df["a"] + df["b"] > 0, "p", "n")
    csv = tmp_path / "dead.csv"
    df.to_csv(csv, index=False)

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        coord_port = s.getsockname()[1]
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        rest_port = s.getsockname()[1]
    env = dict(os.environ,
               XLA_FLAGS="--xla_force_host_platform_device_count=2",
               JAX_PLATFORMS="cpu",
               H2O3_TPU_HEARTBEAT_TIMEOUT="10")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    logs = [open(tmp_path / f"dproc{i}.log", "wb") for i in range(2)]
    procs = [
        subprocess.Popen(
            [sys.executable, "-m", "h2o3_tpu.launch",
             "--coordinator", f"127.0.0.1:{coord_port}",
             "--num-processes", "2", "--process-id", str(i),
             "--ip", "127.0.0.1", "--port", str(rest_port)],
            stdout=logs[i], stderr=subprocess.STDOUT, cwd=repo, env=env,
        )
        for i in range(2)
    ]
    base = f"http://127.0.0.1:{rest_port}"

    def req(method, path, data=None, timeout=30):
        body = urllib.parse.urlencode(data).encode() if data else None
        r = urllib.request.Request(base + path, data=body, method=method)
        return json.loads(urllib.request.urlopen(r, timeout=timeout).read())

    try:
        deadline = time.time() + 120
        up = False
        while time.time() < deadline and not up:
            try:
                req("GET", "/3/Ping", timeout=5)
                up = True
            except Exception:
                time.sleep(1.0)
        assert up, "coordinator REST never came up"

        # a healthy cloud first: parse succeeds across both ranks
        req("POST", "/3/ImportFiles", {"path": str(csv)})
        req("POST", "/3/Parse", {"source_frames": str(csv),
                                 "destination_frame": "dfr"})
        time.sleep(5)

        procs[1].send_signal(signal.SIGKILL)  # kill the follower
        procs[1].wait(timeout=10)

        # fail-stop, bounded by the 10 s heartbeat (+ polling margin): the
        # surviving coordinator must DIE, not hang serving a broken cloud
        deadline = time.time() + 90
        while time.time() < deadline and procs[0].poll() is None:
            time.sleep(2.0)
        assert procs[0].poll() is not None, (
            "coordinator still alive 90 s after member death — fail-stop "
            "violated (hung cloud)"
        )
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for p in procs:
            try:
                p.wait(timeout=20)
            except subprocess.TimeoutExpired:
                p.kill()
        for f in logs:
            f.close()
    tail = (tmp_path / "dproc0.log").read_bytes()[-3000:].decode(errors="replace")
    assert ("unhealthy" in tail or "heartbeat" in tail
            or "distributed service detected fatal errors" in tail), tail
