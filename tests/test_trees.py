"""GBM/DRF tests — modeled on upstream ``hex/tree/gbm/GBMTest.java`` scenario
style [UNVERIFIED upstream path]: accuracy pinned against sklearn references,
structural invariants on the recorded trees."""

import numpy as np
import pandas as pd
import pytest

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.tree import DRF, GBM


def _friedman(n=3000, seed=0, noise=0.3):
    rng = np.random.default_rng(seed)
    X = rng.random((n, 5))
    y = (
        10 * np.sin(np.pi * X[:, 0] * X[:, 1])
        + 20 * (X[:, 2] - 0.5) ** 2
        + 10 * X[:, 3]
        + 5 * X[:, 4]
        + noise * rng.normal(size=n)
    )
    df = pd.DataFrame(X, columns=[f"x{i}" for i in range(5)])
    df["y"] = y
    return df


def _binary_df(n=4000, seed=1):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 4))
    eta = X[:, 0] * 2 + X[:, 1] ** 2 - X[:, 2] - 1
    y = (rng.random(n) < 1 / (1 + np.exp(-eta))).astype(int)
    df = pd.DataFrame(X, columns=list("abcd"))
    df["y"] = np.where(y == 1, "Y", "N")
    return df, y


def test_gbm_stump_finds_optimal_split():
    # single depth-1 tree on perfectly separable step data
    x = np.linspace(0, 1, 1000)
    y = np.where(x < 0.5, 1.0, 3.0)
    fr = Frame.from_pandas(pd.DataFrame({"x": x, "y": y}))
    m = GBM(ntrees=1, max_depth=1, learn_rate=1.0, min_rows=1.0).train(
        y="y", training_frame=fr
    )
    pred = m.predict(fr).vec("predict").to_numpy()
    # histogram trees can be off by one bin (~n/nbins rows) at the boundary
    assert np.mean(np.abs(pred - y) > 0.5) < 0.03  # rows on the wrong side
    assert pred[:450] == pytest.approx(1.0, abs=0.05)
    assert pred[550:] == pytest.approx(3.0, abs=0.05)


def test_gbm_regression_beats_baseline_and_tracks_sklearn():
    from sklearn.ensemble import GradientBoostingRegressor

    df = _friedman()
    fr = Frame.from_pandas(df)
    m = GBM(ntrees=30, max_depth=4, learn_rate=0.2, min_rows=5.0, score_tree_interval=100).train(
        y="y", training_frame=fr
    )
    r2 = m.training_metrics.r2
    sk = GradientBoostingRegressor(
        n_estimators=30, max_depth=4, learning_rate=0.2
    ).fit(df.drop(columns="y"), df["y"])
    from sklearn.metrics import r2_score

    sk_r2 = r2_score(df["y"], sk.predict(df.drop(columns="y")))
    assert r2 > 0.9
    assert r2 > sk_r2 - 0.05  # within striking distance of sklearn exact-split GBM


def test_gbm_binomial_auc():
    from sklearn.ensemble import GradientBoostingClassifier
    from sklearn.metrics import roc_auc_score

    df, ybin = _binary_df()
    fr = Frame.from_pandas(df)
    m = GBM(ntrees=30, max_depth=3, learn_rate=0.2, score_tree_interval=100).train(
        y="y", training_frame=fr
    )
    auc = m.training_metrics.auc
    sk = GradientBoostingClassifier(n_estimators=30, max_depth=3, learning_rate=0.2).fit(
        df[list("abcd")], ybin
    )
    sk_auc = roc_auc_score(ybin, sk.predict_proba(df[list("abcd")])[:, 1])
    assert auc > 0.85
    assert auc > sk_auc - 0.03
    # prediction frame layout
    pred = m.predict(fr)
    assert pred.names == ["predict", "N", "Y"]
    p = pred.vec("Y").to_numpy()
    assert 0 <= p.min() and p.max() <= 1


def test_gbm_multinomial():
    rng = np.random.default_rng(3)
    n = 3000
    X = rng.normal(size=(n, 3))
    y = (X[:, 0] + 0.5 * X[:, 1] > 0.5).astype(int) + (X[:, 2] > 0.8).astype(int)
    df = pd.DataFrame(X, columns=list("abc"))
    df["y"] = np.array(["lo", "mid", "hi"])[y]
    fr = Frame.from_pandas(df)
    m = GBM(ntrees=15, max_depth=3, learn_rate=0.3, score_tree_interval=100).train(
        y="y", training_frame=fr
    )
    assert m.training_metrics.classification_error < 0.1
    pred = m.predict(fr)
    assert pred.names == ["predict", "hi", "lo", "mid"]


def test_gbm_categorical_feature():
    rng = np.random.default_rng(4)
    n = 3000
    g = rng.choice(list("pqrs"), n)
    eff = {"p": 0.0, "q": 5.0, "r": -3.0, "s": 1.0}
    y = np.array([eff[v] for v in g]) + 0.1 * rng.normal(size=n)
    fr = Frame.from_pandas(pd.DataFrame({"g": g, "y": y}))
    m = GBM(ntrees=5, max_depth=2, learn_rate=0.8, min_rows=5.0).train(
        y="y", training_frame=fr
    )
    pred = m.predict(fr).vec("predict").to_numpy()
    for v, e in eff.items():
        sel = g == v
        assert pred[sel].mean() == pytest.approx(e, abs=0.2)


def test_gbm_handles_missing_values():
    rng = np.random.default_rng(5)
    n = 2000
    x = rng.normal(size=n)
    y = np.where(np.isnan(x := np.where(rng.random(n) < 0.2, np.nan, x)), 5.0, 2 * x)
    fr = Frame.from_pandas(pd.DataFrame({"x": x, "y": y}))
    m = GBM(ntrees=10, max_depth=3, learn_rate=0.5, min_rows=5.0).train(
        y="y", training_frame=fr
    )
    assert m.training_metrics.r2 > 0.95  # NA direction must be learned
    pred = m.predict(fr).vec("predict").to_numpy()
    assert np.isnan(pred).sum() == 0


def test_gbm_poisson():
    rng = np.random.default_rng(6)
    n = 3000
    x = rng.normal(size=n)
    y = rng.poisson(np.exp(0.3 + 0.7 * x)).astype(float)
    fr = Frame.from_pandas(pd.DataFrame({"x": x, "y": y}))
    m = GBM(ntrees=20, max_depth=3, distribution="poisson", score_tree_interval=100).train(
        y="y", training_frame=fr
    )
    pred = m.predict(fr).vec("predict").to_numpy()
    assert (pred > 0).all()  # log link keeps predictions positive
    assert m.training_metrics.mean_residual_deviance < 1.5


def test_gbm_early_stopping():
    df = _friedman(n=2000, noise=2.0)
    fr = Frame.from_pandas(df)
    tr, va = fr.split_frame([0.7], seed=3)
    m = GBM(
        ntrees=200,
        max_depth=3,
        learn_rate=0.5,
        stopping_rounds=2,
        stopping_tolerance=1e-3,
        score_tree_interval=5,
    ).train(y="y", training_frame=tr, validation_frame=va)
    assert m.output["ntrees_actual"] < 200
    # scoring history carries both training and validation series
    assert "validation_rmse" in m.scoring_history[0]


def test_gbm_varimp_ranks_informative_feature():
    rng = np.random.default_rng(7)
    n = 2000
    df = pd.DataFrame(
        {
            "signal": rng.normal(size=n),
            "noise1": rng.normal(size=n),
            "noise2": rng.normal(size=n),
        }
    )
    df["y"] = 3 * df["signal"] + 0.1 * rng.normal(size=n)
    fr = Frame.from_pandas(df)
    m = GBM(ntrees=10, max_depth=3).train(y="y", training_frame=fr)
    vi = m.varimp()
    assert vi[0]["variable"] == "signal"
    assert vi[0]["percentage"] > 0.9


def test_gbm_sampling_reproducible():
    df = _friedman(n=1500)
    fr = Frame.from_pandas(df)
    kw = dict(ntrees=10, max_depth=3, sample_rate=0.7, col_sample_rate=0.8, seed=42)
    m1 = GBM(**kw).train(y="y", training_frame=fr)
    m2 = GBM(**kw).train(y="y", training_frame=fr)
    np.testing.assert_allclose(
        m1.predict(fr).vec("predict").to_numpy(),
        m2.predict(fr).vec("predict").to_numpy(),
        rtol=1e-6,
    )


@pytest.mark.slow
def test_drf_classification():
    df, ybin = _binary_df(n=3000)
    fr = Frame.from_pandas(df)
    m = DRF(ntrees=20, max_depth=10, score_tree_interval=100, seed=1).train(
        y="y", training_frame=fr
    )
    assert m.training_metrics.auc > 0.9  # in-bag training AUC is optimistic; sanity bound
    pred = m.predict(fr)
    p1 = pred.vec("Y").to_numpy()
    assert 0 <= p1.min() and p1.max() <= 1


@pytest.mark.slow
def test_drf_regression():
    df = _friedman(n=2500)
    fr = Frame.from_pandas(df)
    m = DRF(ntrees=25, max_depth=12, score_tree_interval=100, seed=2).train(
        y="y", training_frame=fr
    )
    assert m.training_metrics.r2 > 0.85


def test_drf_multinomial():
    rng = np.random.default_rng(9)
    n = 2500
    X = rng.normal(size=(n, 3))
    y = (X[:, 0] > 0).astype(int) + (X[:, 1] > 0.5).astype(int)
    df = pd.DataFrame(X, columns=list("abc"))
    df["y"] = np.array(["A", "B", "C"])[y]
    fr = Frame.from_pandas(df)
    m = DRF(ntrees=15, max_depth=8, score_tree_interval=100, seed=3).train(
        y="y", training_frame=fr
    )
    assert m.training_metrics.classification_error < 0.15
    P = m._predict_raw(fr)
    np.testing.assert_allclose(P.sum(axis=1), 1.0, atol=1e-5)


def test_gbm_predict_on_new_frame_with_unseen_level():
    rng = np.random.default_rng(10)
    n = 1000
    g = rng.choice(["a", "b"], n)
    y = np.where(g == "a", 1.0, 2.0) + 0.01 * rng.normal(size=n)
    fr = Frame.from_pandas(pd.DataFrame({"g": g, "y": y}))
    m = GBM(ntrees=3, max_depth=1, learn_rate=1.0, min_rows=1.0).train(
        y="y", training_frame=fr
    )
    test = Frame.from_pandas(pd.DataFrame({"g": ["a", "b", "zz"], "y": [0.0, 0.0, 0.0]}))
    pred = m.predict(test).vec("predict").to_numpy()
    assert pred[0] == pytest.approx(1.0, abs=0.05)
    assert pred[1] == pytest.approx(2.0, abs=0.05)
    assert np.isfinite(pred[2])  # unseen level routes through the NA path


def test_scanned_chunk_builder_matches_loop_quality():
    """The lax.scan chunked builder (the TPU dispatch-amortization path) must
    produce trees of the same quality as the per-tree loop on CPU."""
    import jax
    import jax.numpy as jnp

    from h2o3_tpu.models.tree.binning import bin_frame, fit_bins
    from h2o3_tpu.models.tree.shared_tree import (
        build_trees_scanned,
        replay_batch,
        scan_chunk_cap,
        trees_from_stacked,
    )

    df, yarr = _binary_df(n=3000, seed=5)
    fr = Frame.from_pandas(df)
    cols = [c for c in fr.names if c != "y"]
    spec = fit_bins(fr, cols)
    bins = bin_frame(spec, fr)
    npad = bins.shape[0]
    ybuf = np.zeros(npad, np.float32)
    ybuf[: fr.nrow] = yarr
    y01 = jnp.asarray(ybuf)
    w = jnp.asarray((np.arange(npad) < fr.nrow).astype(np.float32))

    from h2o3_tpu.models.tree.distributions import grad_hess, init_score

    f0 = init_score("bernoulli", np.asarray(y01)[: fr.nrow], np.ones(fr.nrow), 0.0)
    F = jnp.full(npad, f0, jnp.float32)
    varimp = jnp.zeros(len(cols), jnp.float32)

    n_trees = 8
    assert scan_chunk_cap(4, spec.max_bins) >= n_trees
    F2, varimp2, stacked = build_trees_scanned(
        bins, w, y01, F, varimp, jax.random.PRNGKey(3), n_trees,
        grad_fn=lambda F_, y_, w_: grad_hess("bernoulli", F_, y_, w_, 0.0),
        grad_key=("gbm", "bernoulli", 0.0),
        sample_rate=0.8,
        n_bins=spec.max_bins,
        is_cat_cols=spec.is_cat,
        max_depth=4,
        min_rows=5.0,
        min_split_improvement=1e-5,
        learn_rates=np.full(n_trees, 0.1, np.float32),
        max_abs_leaf=float("inf"),
        col_sample_rate=1.0,
        col_sample_rate_per_tree=1.0,
    )
    trees = trees_from_stacked(stacked, n_trees)
    assert len(trees) == n_trees and all(len(t.levels) == 5 for t in trees)

    # replay of the stacked records reproduces the carried F exactly
    F_replay = replay_batch(bins, stacked, jnp.full(npad, f0, jnp.float32))
    np.testing.assert_allclose(
        np.asarray(F_replay), np.asarray(F2), rtol=0, atol=1e-5
    )

    # quality: training AUC from the scanned ensemble clearly beats chance
    p1 = 1.0 / (1.0 + np.exp(-np.asarray(F2)[: fr.nrow]))
    from sklearn.metrics import roc_auc_score

    yv = np.asarray(y01)[: fr.nrow]
    assert roc_auc_score(yv, p1) > 0.8


def test_hist_subtraction_matches_direct(monkeypatch):
    """The fused builder's sibling-subtraction scheme (build the lighter
    child's histogram, derive the other as parent − built; terminal level
    from recorded split stats) must reproduce the direct per-node-histogram
    scheme: same splits, same leaf structure, near-identical predictions."""
    import jax
    import jax.numpy as jnp

    from h2o3_tpu.models.tree.binning import bin_frame, fit_bins
    from h2o3_tpu.models.tree.distributions import grad_hess, init_score
    from h2o3_tpu.models.tree.shared_tree import (
        build_trees_scanned,
        trees_from_stacked,
    )

    rng = np.random.default_rng(11)
    n = 4000
    df = pd.DataFrame(
        {
            "a": rng.normal(size=n),
            "b": rng.normal(size=n),
            "cat": rng.choice(list("uvwxyz"), size=n),
            "c": rng.normal(size=n),
        }
    )
    df.loc[rng.random(n) < 0.05, "a"] = np.nan  # exercise the NA bin
    eta = 2 * df["a"].fillna(0) + (df["cat"].isin(["u", "v"])) * 1.5 - df["c"]
    yarr = (rng.random(n) < 1 / (1 + np.exp(-eta))).astype(np.float32)
    df["y"] = yarr

    fr = Frame.from_pandas(df)
    cols = ["a", "b", "cat", "c"]
    spec = fit_bins(fr, cols)
    bins = bin_frame(spec, fr)
    npad = bins.shape[0]
    ybuf = np.zeros(npad, np.float32)
    ybuf[: fr.nrow] = yarr
    y01 = jnp.asarray(ybuf)
    w = jnp.asarray((np.arange(npad) < fr.nrow).astype(np.float32))
    f0 = init_score("bernoulli", yarr, np.ones(fr.nrow), 0.0)

    def run():
        F = jnp.full(npad, f0, jnp.float32)
        varimp = jnp.zeros(len(cols), jnp.float32)
        F2, vi, stacked = build_trees_scanned(
            bins, w, y01, F, varimp, jax.random.PRNGKey(7), 4,
            grad_fn=lambda F_, y_, w_: grad_hess("bernoulli", F_, y_, w_, 0.0),
            grad_key=("test", "bernoulli"),
            sample_rate=0.9,
            n_bins=spec.max_bins,
            is_cat_cols=spec.is_cat,
            max_depth=4,
            min_rows=5.0,
            min_split_improvement=1e-5,
            learn_rates=np.full(4, 0.2, np.float32),
            max_abs_leaf=float("inf"),
            col_sample_rate=1.0,
            col_sample_rate_per_tree=1.0,
        )
        return np.asarray(F2), np.asarray(vi), trees_from_stacked(stacked, 4)

    monkeypatch.setenv("H2O3_TPU_HIST_SUBTRACT", "1")
    F_sub, vi_sub, trees_sub = run()
    monkeypatch.setenv("H2O3_TPU_HIST_SUBTRACT", "0")
    F_dir, vi_dir, trees_dir = run()

    np.testing.assert_allclose(F_sub, F_dir, rtol=0, atol=2e-4)
    np.testing.assert_allclose(vi_sub, vi_dir, rtol=1e-3, atol=1e-3)
    for ts, td in zip(trees_sub, trees_dir):
        for ls, ld in zip(ts.levels, td.levels):
            np.testing.assert_array_equal(
                np.asarray(ls.split_col), np.asarray(ld.split_col)
            )
            np.testing.assert_array_equal(
                np.asarray(ls.leaf_now), np.asarray(ld.leaf_now)
            )
            np.testing.assert_allclose(
                np.asarray(ls.leaf_val), np.asarray(ld.leaf_val),
                rtol=0, atol=2e-5,
            )


def test_calibrate_model_platt_and_isotonic():
    """calibrate_model/calibration_frame: cal_p columns appear and
    materially fix an overconfident (overfit) GBM's probabilities."""
    from sklearn.metrics import log_loss

    rng = np.random.default_rng(2)
    n = 6000
    X = rng.normal(size=(n, 5))
    eta = 0.8 * X[:, 0] - 0.5 * X[:, 1]
    y = (rng.random(n) < 1 / (1 + np.exp(-eta))).astype(int)
    df = pd.DataFrame(X, columns=list("abcde"))
    df["y"] = np.where(y == 1, "Y", "N")
    tr = Frame.from_pandas(df.iloc[:1500].reset_index(drop=True))
    cal = Frame.from_pandas(df.iloc[1500:3000].reset_index(drop=True))
    te = df.iloc[3000:].reset_index(drop=True)
    tef = Frame.from_pandas(te)
    yte = (te["y"] == "Y").astype(int)

    # deliberately overfit: probabilities pushed toward 0/1
    kw = dict(ntrees=150, max_depth=6, learn_rate=0.3, seed=1)
    raw = GBM(**kw).train(y="y", training_frame=tr).predict(tef).vec("Y").to_numpy()
    m = GBM(**kw, calibrate_model=True, calibration_frame=cal).train(
        y="y", training_frame=tr
    )
    out = m.predict(tef)
    assert out.names[-2:] == ["cal_p0", "cal_p1"]
    cp1 = out.vec("cal_p1").to_numpy()
    cp0 = out.vec("cal_p0").to_numpy()
    np.testing.assert_allclose(cp0 + cp1, 1.0, atol=1e-9)
    assert m.output["calibration"]["a"] < 0.8  # shrinks overconfident scores
    ll_raw = log_loss(yte, np.clip(raw, 1e-9, 1 - 1e-9))
    ll_cal = log_loss(yte, np.clip(cp1, 1e-9, 1 - 1e-9))
    assert ll_cal < ll_raw - 0.1  # material improvement

    iso = GBM(**kw, calibrate_model=True, calibration_frame=cal,
              calibration_method="IsotonicRegression").train(
        y="y", training_frame=tr
    ).predict(tef).vec("cal_p1").to_numpy()
    assert log_loss(yte, np.clip(iso, 1e-9, 1 - 1e-9)) < ll_raw - 0.1

    with pytest.raises(Exception, match="calibration_frame"):
        GBM(**kw, calibrate_model=True).train(y="y", training_frame=tr)


def test_calibration_survives_mojo_export(tmp_path):
    import os

    from h2o3_tpu.genmodel import MojoModel
    from h2o3_tpu.models.export import export_mojo

    rng = np.random.default_rng(4)
    n = 3000
    X = rng.normal(size=(n, 4))
    y = (rng.random(n) < 1 / (1 + np.exp(-X[:, 0]))).astype(int)
    df = pd.DataFrame(X, columns=list("abcd"))
    df["y"] = np.where(y == 1, "Y", "N")
    tr = Frame.from_pandas(df.iloc[:1000].reset_index(drop=True))
    cal = Frame.from_pandas(df.iloc[1000:2000].reset_index(drop=True))
    te = df.iloc[2000:].reset_index(drop=True)
    m = GBM(ntrees=40, max_depth=5, learn_rate=0.3, seed=2,
            calibrate_model=True, calibration_frame=cal).train(
        y="y", training_frame=tr
    )
    p = os.path.join(str(tmp_path), "calm.zip")
    export_mojo(m, p)
    off = MojoModel.load(p).predict(te.drop(columns="y"))
    assert "cal_p1" in off
    live = m.predict(Frame.from_pandas(te)).vec("cal_p1").to_numpy()
    np.testing.assert_allclose(off["cal_p1"], live, atol=1e-6)


@pytest.mark.slow
def test_monotone_constraints_enforced():
    """monotone_constraints: per-tree split rejection + bound propagation
    makes predictions monotone in the constrained feature at any slice."""
    rng = np.random.default_rng(1)
    n = 5000
    x = rng.uniform(-3, 3, n)
    z = rng.normal(size=n)
    y = x + 0.8 * np.sin(3 * x) + 0.5 * z + 0.2 * rng.normal(size=n)
    fr = Frame.from_pandas(pd.DataFrame({"x": x, "z": z, "y": y}))
    kw = dict(ntrees=40, max_depth=4, learn_rate=0.2, seed=1)
    m0 = GBM(**kw).train(y="y", training_frame=fr)
    m1 = GBM(**kw, monotone_constraints={"x": 1}).train(y="y", training_frame=fr)
    xs = np.linspace(-3, 3, 300)
    for zv in (-1.0, 0.0, 1.5):
        gf = Frame.from_pandas(pd.DataFrame({"x": xs, "z": np.full(300, zv)}))
        p0 = m0.predict(gf).vec("predict").to_numpy()
        p1 = m1.predict(gf).vec("predict").to_numpy()
        if zv == 0.0:
            assert (np.diff(p0) < -1e-9).sum() > 0  # wiggles without it
        assert (np.diff(p1) < -1e-9).sum() == 0  # monotone with it
    # quality stays close
    assert m1.training_metrics.value("r2") > m0.training_metrics.value("r2") - 0.05
    # decreasing constraint on -y
    fr2 = Frame.from_pandas(pd.DataFrame({"x": x, "z": z, "y": -y}))
    m2 = GBM(**kw, monotone_constraints={"x": -1}).train(y="y", training_frame=fr2)
    gf = Frame.from_pandas(pd.DataFrame({"x": xs, "z": np.zeros(300)}))
    p2 = m2.predict(gf).vec("predict").to_numpy()
    assert (np.diff(p2) > 1e-9).sum() == 0  # non-increasing

    # binary margin monotonicity (bernoulli)
    yb = (rng.random(n) < 1 / (1 + np.exp(-(x + np.sin(2 * x))))).astype(int)
    frb = Frame.from_pandas(pd.DataFrame(
        {"x": x, "z": z, "y": np.where(yb == 1, "Y", "N")}))
    mb = GBM(ntrees=30, max_depth=3, learn_rate=0.3, seed=2,
             monotone_constraints={"x": 1}).train(y="y", training_frame=frb)
    pb = mb.predict(Frame.from_pandas(
        pd.DataFrame({"x": xs, "z": np.zeros(300)}))).vec("Y").to_numpy()
    assert (np.diff(pb) < -1e-9).sum() == 0

    # validation errors
    with pytest.raises(Exception, match="categorical|unknown"):
        g = rng.choice(["a", "b"], n)
        frc = Frame.from_pandas(pd.DataFrame(
            {"x": x, "g": g, "y": y}))
        GBM(ntrees=5, monotone_constraints={"g": 1}).train(
            y="y", training_frame=frc
        )
    with pytest.raises(Exception, match="distributions"):
        GBM(ntrees=5, distribution="poisson",
            monotone_constraints={"x": 1}).train(
            y="y", training_frame=Frame.from_pandas(
                pd.DataFrame({"x": x, "y": np.abs(y)})))


@pytest.mark.slow
def test_fused_whole_tree_deep_matches_per_level(monkeypatch):
    """Depth beyond the old 12-level fused cap (VERDICT r3 weak #7): the
    unrolled whole-tree program at depth 13 must equal the per-level
    dispatch loop bit-for-bit (same inputs, same keys)."""
    import jax
    import jax.numpy as jnp

    from h2o3_tpu.models.tree import shared_tree as st

    rng = np.random.default_rng(5)
    n, c = 4096, 5
    bins = jnp.asarray(rng.integers(1, 32, (n, c)).astype(np.uint8))
    w = jnp.ones(n, jnp.float32)
    t = jnp.asarray(rng.normal(size=n).astype(np.float32))
    h = jnp.ones(n, jnp.float32)
    key = jax.random.PRNGKey(3)
    depth = 13

    def run(force_per_level: bool):
        preds = jnp.zeros(n, jnp.float32)
        vi = jnp.zeros(c, jnp.float32)
        if force_per_level:
            nid = jnp.zeros(n, jnp.int32)
            tree = st.Tree()
            for d in range(depth + 1):
                n_pad = min(1 << d, 2048)
                n_pad_next = min(2 * n_pad, 2048)
                step = st._level_step(n_pad, n_pad_next, 32, d == depth, ())
                nid, preds, vi, n_split, rec = step(
                    bins, nid, preds, vi, w, w * t, h,
                    jax.random.fold_in(key, d),
                    jnp.ones(c, jnp.float32), jnp.zeros(c, bool),
                    jnp.float32(10.0), jnp.float32(1e-5), jnp.float32(0.1),
                    jnp.float32(np.inf), jnp.float32(1.0), None,
                )
                tree.levels.append(st.TreeLevel(**rec))
            return preds, vi
        prog = st._tree_program(depth, 32, 2048, ())
        _, preds, vi, _ = prog(
            bins, preds, vi, w, w * t, h, key,
            jnp.ones(c, jnp.float32), jnp.zeros(c, bool),
            jnp.float32(10.0), jnp.float32(1e-5), jnp.float32(0.1),
            jnp.float32(np.inf), jnp.float32(1.0), None,
        )
        return preds, vi

    # per-level builds every histogram from scratch at full bins; the fused
    # program uses sibling subtraction and bin adaptivity — equality must
    # hold exactly when both are OFF
    monkeypatch.setenv("H2O3_TPU_HIST_SUBTRACT", "0")
    monkeypatch.setenv("H2O3_TPU_BIN_ADAPT", "0")
    st._STEP_CACHE.clear()
    try:
        p1, v1 = run(force_per_level=False)
        p2, v2 = run(force_per_level=True)
        np.testing.assert_array_equal(np.asarray(p1), np.asarray(p2))
        np.testing.assert_array_equal(np.asarray(v1), np.asarray(v2))
    finally:
        st._STEP_CACHE.clear()  # drop subtract=False programs for later tests


def test_gains_lift_and_ks_match_reference():
    """Gains/lift + KS on both metric paths, pinned against a direct
    numpy computation and basic invariants."""
    import numpy as np

    from h2o3_tpu.models.metrics import binomial_metrics

    rng = np.random.default_rng(17)
    n = 4000
    y = rng.integers(0, 2, n).astype(np.float64)
    p = np.clip(rng.normal(0.35 + 0.3 * y, 0.2, n), 0.001, 0.999)
    mm = binomial_metrics(y, p, domain=("n", "p"))
    rows = mm.gains_lift()
    assert rows and len(rows) == 16
    # cumulative columns are monotone; the final row covers everything
    ccr = [r["cumulative_capture_rate"] for r in rows]
    assert all(b >= a - 1e-12 for a, b in zip(ccr, ccr[1:]))
    assert abs(ccr[-1] - 1.0) < 1e-9
    assert abs(rows[-1]["cumulative_data_fraction"] - 1.0) < 1e-9
    assert abs(rows[-1]["cumulative_lift"] - 1.0) < 1e-9
    # top group must beat baseline on this signal
    assert rows[0]["lift"] > 1.2
    # KS == max |TPR - FPR| computed directly
    order = np.argsort(-p, kind="mergesort")
    ys = y[order]
    tpr = np.cumsum(ys) / ys.sum()
    fpr = np.cumsum(1 - ys) / (1 - ys).sum()
    assert abs(mm.kolmogorov_smirnov() - np.max(np.abs(tpr - fpr))) < 1e-9


def test_gains_lift_device_path_close_to_host():
    import jax.numpy as jnp
    import numpy as np

    from h2o3_tpu.models.metrics import binomial_metrics

    rng = np.random.default_rng(3)
    n = 20000
    y = rng.integers(0, 2, n).astype(np.float64)
    p = np.clip(rng.normal(0.35 + 0.3 * y, 0.2, n), 0.001, 0.999)
    host = binomial_metrics(y, p, domain=("n", "p"))
    dev = binomial_metrics(jnp.asarray(y, jnp.float32), jnp.asarray(p, jnp.float32),
                           domain=("n", "p"))
    assert abs(host.kolmogorov_smirnov() - dev.kolmogorov_smirnov()) < 0.02
    hr, dr = host.gains_lift(), dev.gains_lift()
    assert dr and abs(hr[0]["cumulative_lift"] - dr[0]["cumulative_lift"]) < 0.1


def test_ks_zero_for_constant_predictor_any_row_order():
    """Tied scores collapse to one threshold: a constant predictor has
    KS 0 regardless of input row order (was order-dependent up to 1.0)."""
    from h2o3_tpu.models.metrics import binomial_metrics

    y_sorted = np.array([1.0] * 50 + [0.0] * 50)
    p = np.full(100, 0.5)
    mm1 = binomial_metrics(y_sorted, p, domain=("n", "p"))
    rng = np.random.default_rng(0)
    mm2 = binomial_metrics(rng.permutation(y_sorted), p, domain=("n", "p"))
    assert abs(mm1.kolmogorov_smirnov()) < 1e-12
    assert abs(mm2.kolmogorov_smirnov()) < 1e-12


def test_nbins_cats_groups_tail_levels():
    """nbins_cats caps categorical bins: levels past the cap share the last
    bin (upstream's high-cardinality grouping), and the model still trains."""
    from h2o3_tpu.models import GBM
    from h2o3_tpu.models.tree.binning import fit_bins

    rng = np.random.default_rng(2)
    n = 2000
    cat = np.array([f"lvl{i:03d}" for i in rng.integers(0, 50, n)])
    ybin = np.where((rng.random(n) < 0.3) ^ (cat < "lvl025"), "a", "b")
    df = pd.DataFrame({"c": cat, "x": rng.normal(size=n), "y": ybin})
    fr = Frame.from_pandas(df)

    spec = fit_bins(fr, ["c", "x"], nbins_cats=8)
    ci = spec.names.index("c")
    assert spec.nbins[ci] == 8  # 50 levels -> 8 bins, tail grouped
    spec_full = fit_bins(fr, ["c", "x"])
    assert spec_full.nbins[ci] == 50
    # upstream semantics: nbins_cats is INDEPENDENT of the numeric nbins —
    # a low nbins must not silently crush categorical resolution
    spec_low = fit_bins(fr, ["c", "x"], nbins=20)
    assert spec_low.nbins[ci] == 50

    m = GBM(ntrees=3, max_depth=3, nbins_cats=8, seed=1).train(
        y="y", training_frame=fr)
    assert float(m.training_metrics.auc) > 0.5


def test_model_summary_tree_table():
    """model_summary (upstream table): tree counts and depth/leaf ranges."""
    from h2o3_tpu.models import GBM

    rng = np.random.default_rng(6)
    df = pd.DataFrame({"a": rng.normal(size=800), "b": rng.normal(size=800)})
    df["y"] = np.where(df.a - df.b > 0, "p", "q")
    fr = Frame.from_pandas(df)
    m = GBM(ntrees=4, max_depth=3, seed=2).train(y="y", training_frame=fr)
    s = m.model_summary()
    assert s["number_of_trees"] == 4 and s["number_of_internal_trees"] == 4
    assert 1 <= s["min_depth"] <= s["max_depth"] <= 3
    assert 2 <= s["min_leaves"] <= s["max_leaves"] <= 2 ** 3
    assert s["mean_leaves"] >= s["min_leaves"]
