"""Algorithm wave 3 — RuleFit, UpliftDRF, GAM, ModelSelection, ANOVA-GLM,
Aggregator, Infogram, PSVM (SURVEY.md §2.2 rows C28/C32), pinned against
sklearn / analytic references where a counterpart exists."""

import numpy as np
import pandas as pd
import pytest

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models import (
    ANOVAGLM,
    GAM,
    PSVM,
    Aggregator,
    Infogram,
    ModelSelection,
    RuleFit,
    UpliftDRF,
)


# ---------------------------------------------------------------------------
# ModelSelection


def _lin_frame(n=2000, seed=1):
    rng = np.random.default_rng(seed)
    x0, x1, x2 = rng.normal(size=(3, n))
    cat = rng.choice(list("abc"), size=n)
    ce = {"a": 0.0, "b": 1.0, "c": -1.0}
    y = 2 * x0 - 1.5 * x1 + np.vectorize(ce.get)(cat) + 0.1 * rng.normal(size=n)
    df = pd.DataFrame({"x0": x0, "x1": x1, "x2": x2, "cat": cat, "y": y})
    return Frame.from_pandas(df), df


def test_modelselection_maxr_picks_true_predictors():
    fr, _ = _lin_frame()
    m = ModelSelection(mode="maxr", max_predictor_number=3).train(
        y="y", training_frame=fr
    )
    subs = m.get_best_model_predictors()
    assert subs[0] == ["x0"]
    assert set(subs[1]) == {"x0", "x1"}
    assert set(subs[2]) == {"x0", "x1", "cat"}  # noise col x2 excluded
    r2 = m.get_best_r2_values()
    assert all(b >= a - 1e-9 for a, b in zip(r2, r2[1:]))  # monotone in size
    assert r2[2] > 0.99


def test_modelselection_allsubsets_agrees_with_maxr():
    fr, _ = _lin_frame()
    a = ModelSelection(mode="allsubsets", max_predictor_number=2).train(
        y="y", training_frame=fr
    )
    b = ModelSelection(mode="maxr", max_predictor_number=2).train(
        y="y", training_frame=fr
    )
    assert [set(s) for s in a.get_best_model_predictors()] == [
        set(s) for s in b.get_best_model_predictors()
    ]
    np.testing.assert_allclose(
        a.get_best_r2_values(), b.get_best_r2_values(), rtol=1e-9
    )


def test_modelselection_forward_backward():
    fr, _ = _lin_frame()
    f = ModelSelection(mode="forward", max_predictor_number=4).train(
        y="y", training_frame=fr
    )
    assert f.get_best_model_predictors()[0] == ["x0"]
    b = ModelSelection(mode="backward", min_predictor_number=2).train(
        y="y", training_frame=fr
    )
    # x2 (pure noise) must be eliminated first -> absent from the size-3 set
    assert "x2" not in b.get_best_model_predictors()[-1]


def test_modelselection_r2_matches_numpy_ols():
    fr, df = _lin_frame()
    m = ModelSelection(mode="allsubsets", max_predictor_number=1).train(
        y="y", x=["x0", "x1", "x2"], training_frame=fr
    )
    # best single predictor is x0; compare R2 to a direct OLS fit
    X = np.stack([df["x0"], np.ones(len(df))], axis=1)
    beta, *_ = np.linalg.lstsq(X, df["y"], rcond=None)
    resid = df["y"] - X @ beta
    r2_np = 1 - np.sum(resid**2) / np.sum((df["y"] - df["y"].mean()) ** 2)
    assert abs(m.get_best_r2_values()[0] - r2_np) < 1e-3


# ---------------------------------------------------------------------------
# ANOVA GLM


def test_anovaglm_flags_true_effects():
    fr, _ = _lin_frame()
    m = ANOVAGLM(highest_interaction_term=2).train(
        y="y", x=["x0", "cat", "x2"], training_frame=fr
    )
    tab = {r["term"]: r for r in m.anova_table()}
    assert tab["x0"]["p_value"] < 1e-10
    assert tab["cat"]["p_value"] < 1e-10
    assert tab["x2"]["p_value"] > 0.01  # pure noise
    assert tab["x0:x2"]["p_value"] > 0.01  # no interaction in truth
    # SS decomposition sanity: every SS nonnegative, residual df plausible
    assert all(r["ss"] >= 0 for r in m.anova_table())


def test_anovaglm_gaussian_f_matches_direct_computation():
    rng = np.random.default_rng(7)
    n = 500
    a = rng.normal(size=n)
    b = rng.normal(size=n)
    y = a + 0.5 * b + rng.normal(size=n)
    fr = Frame.from_pandas(pd.DataFrame({"a": a, "b": b, "y": y}))
    m = ANOVAGLM(highest_interaction_term=1, standardize=False).train(
        y="y", x=["a", "b"], training_frame=fr
    )
    # direct type-III F for 'a': RSS(b) - RSS(a,b)
    X_full = np.stack([a, b, np.ones(n)], axis=1)
    X_red = np.stack([b, np.ones(n)], axis=1)
    rss = lambda X: np.sum(
        (y - X @ np.linalg.lstsq(X, y, rcond=None)[0]) ** 2
    )
    ss_a = rss(X_red) - rss(X_full)
    tab = {r["term"]: r for r in m.anova_table()}
    np.testing.assert_allclose(tab["a"]["ss"], ss_a, rtol=1e-3)


def test_anovaglm_binomial():
    rng = np.random.default_rng(9)
    n = 1500
    a = rng.normal(size=n)
    b = rng.normal(size=n)
    eta = 1.5 * a
    y = (rng.random(n) < 1 / (1 + np.exp(-eta))).astype(int)
    df = pd.DataFrame({"a": a, "b": b, "y": [str(v) for v in y]})
    fr = Frame.from_pandas(df, column_types={"y": "enum"})
    m = ANOVAGLM(highest_interaction_term=1).train(
        y="y", x=["a", "b"], training_frame=fr
    )
    tab = {r["term"]: r for r in m.anova_table()}
    assert tab["a"]["p_value"] < 1e-8
    assert tab["b"]["p_value"] > 0.01


# ---------------------------------------------------------------------------
# GAM


def test_gam_beats_linear_on_nonlinear_signal():
    rng = np.random.default_rng(3)
    n = 2500
    x0 = rng.normal(size=n)
    x1 = rng.normal(size=n)
    y = np.sin(2 * x0) + 0.5 * x1 + 0.1 * rng.normal(size=n)
    fr = Frame.from_pandas(pd.DataFrame({"x0": x0, "x1": x1, "y": y}))
    g = GAM(gam_columns=["x0"]).train(y="y", training_frame=fr)
    from h2o3_tpu.models import GLM

    lin = GLM(lambda_=0.0).train(y="y", training_frame=fr)
    r2_gam = g.training_metrics.value("r2")
    r2_lin = lin.training_metrics.value("r2")
    assert r2_gam > 0.95
    assert r2_gam > r2_lin + 0.2  # the spline must capture sin(2x)


def test_gam_predict_consistency_and_smoothing():
    rng = np.random.default_rng(4)
    n = 1500
    x = rng.uniform(-2, 2, n)
    y = x**2 + 0.1 * rng.normal(size=n)
    fr = Frame.from_pandas(pd.DataFrame({"x": x, "y": y}))
    g = GAM(gam_columns=["x"], num_knots=[8]).train(y="y", training_frame=fr)
    p1 = g.predict(fr).vec("predict").to_numpy()
    p2 = g.predict(fr).vec("predict").to_numpy()
    np.testing.assert_allclose(p1, p2)  # deterministic scoring
    # very strong smoothing must flatten the fit
    g2 = GAM(gam_columns=["x"], num_knots=[8], scale=[1e9]).train(
        y="y", training_frame=fr
    )
    assert g2.training_metrics.value("r2") < g.training_metrics.value("r2")


def test_gam_binomial():
    rng = np.random.default_rng(5)
    n = 2500
    x = rng.normal(size=n)
    eta = np.sin(2 * x) * 2
    y = (rng.random(n) < 1 / (1 + np.exp(-eta))).astype(int)
    df = pd.DataFrame({"x": x, "y": [str(v) for v in y]})
    fr = Frame.from_pandas(df, column_types={"y": "enum"})
    g = GAM(gam_columns=["x"], family="binomial").train(y="y", training_frame=fr)
    assert g.training_metrics.value("auc") > 0.75


# ---------------------------------------------------------------------------
# RuleFit


def test_rulefit_recovers_rules():
    rng = np.random.default_rng(0)
    n = 4000
    X = rng.normal(size=(n, 5)).astype(np.float32)
    y = (
        ((X[:, 0] > 0.3) & (X[:, 1] < 0.5)).astype(float) * 2.0
        + (X[:, 2] > 0) * 1.0
        + 0.1 * rng.normal(size=n)
    )
    df = pd.DataFrame(X, columns=[f"x{i}" for i in range(5)])
    df["y"] = y
    fr = Frame.from_pandas(df)
    m = RuleFit(
        rule_generation_ntrees=20, min_rule_length=2, max_rule_length=3, seed=42
    ).train(y="y", training_frame=fr)
    assert m.training_metrics.value("r2") > 0.9
    imp = m.rule_importance()
    assert len(imp) > 0
    top = imp[0]["rule"]
    assert "x0" in top and "x1" in top  # the generating interaction
    # scoring a fresh frame round-trips through rule evaluation
    pred = m.predict(fr).vec("predict").to_numpy()
    assert np.corrcoef(pred, y)[0, 1] ** 2 > 0.9


def test_rulefit_binomial_and_linear_only():
    rng = np.random.default_rng(2)
    n = 2500
    X = rng.normal(size=(n, 4))
    eta = 2 * ((X[:, 0] > 0) & (X[:, 1] > 0)) - 1
    y = (rng.random(n) < 1 / (1 + np.exp(-2 * eta))).astype(int)
    df = pd.DataFrame(X, columns=list("abcd"))
    df["y"] = np.where(y == 1, "Y", "N")
    fr = Frame.from_pandas(df)
    m = RuleFit(rule_generation_ntrees=16, seed=3).train(y="y", training_frame=fr)
    assert m.training_metrics.value("auc") > 0.75
    lin = RuleFit(model_type="linear", seed=3).train(y="y", training_frame=fr)
    assert all(r["variable"].startswith("linear.") for r in lin.rule_importance())


# ---------------------------------------------------------------------------
# UpliftDRF


def _uplift_frame(n=6000, seed=3):
    rng = np.random.default_rng(seed)
    x0 = rng.normal(size=n)
    x1 = rng.normal(size=n)
    treat = rng.integers(0, 2, n)
    p = 0.3 + 0.3 * treat * (x0 > 0)
    y = (rng.random(n) < p).astype(int)
    df = pd.DataFrame(
        {"x0": x0, "x1": x1,
         "treatment": np.where(treat, "treatment", "control"),
         "y": y.astype(str)}
    )
    return (
        Frame.from_pandas(df, column_types={"y": "enum", "treatment": "enum"}),
        x0,
    )


@pytest.mark.parametrize("metric", ["KL", "Euclidean", "ChiSquared"])
def test_upliftdrf_recovers_heterogeneous_effect(metric):
    fr, x0 = _uplift_frame()
    m = UpliftDRF(
        ntrees=16, max_depth=4, treatment_column="treatment",
        uplift_metric=metric, seed=11,
    ).train(y="y", training_frame=fr)
    u = m._predict_raw(fr)
    assert u[x0 > 0].mean() > 0.2  # true uplift 0.3
    assert u[x0 <= 0].mean() < 0.1  # true uplift 0
    mm = m.training_metrics
    assert mm.value("qini") > 0  # better than random targeting
    assert 0.1 < mm.value("ate") < 0.2  # overall ATE ~ 0.15


def test_upliftdrf_validation_errors():
    fr, _ = _uplift_frame(n=500)
    with pytest.raises(Exception, match="2-level factor"):
        UpliftDRF(treatment_column="x0").train(y="y", training_frame=fr)
    with pytest.raises(Exception, match="uplift_metric"):
        UpliftDRF(treatment_column="treatment", uplift_metric="bogus").train(
            y="y", training_frame=fr
        )


# ---------------------------------------------------------------------------
# Aggregator


def test_aggregator_reduces_with_exact_count_conservation():
    rng = np.random.default_rng(8)
    n = 20000
    df = pd.DataFrame(
        {"a": rng.normal(size=n), "b": rng.normal(size=n),
         "c": rng.choice(list("xyz"), n)}
    )
    fr = Frame.from_pandas(df)
    m = Aggregator(target_num_exemplars=500).train(training_frame=fr)
    agg = m.aggregated_frame
    counts = agg.vec("counts").to_numpy()
    assert int(counts.sum()) == n  # every row accounted for
    ne = m.output["num_exemplars"]
    assert ne <= 500 * 1.5 + 1
    assert ne >= 10
    assert agg.nrow == ne
    # mapping covers all rows and points at real exemplars
    mapping = m.output["mapping"]
    assert mapping.shape == (n,)
    assert mapping.min() >= 0 and mapping.max() < ne


# ---------------------------------------------------------------------------
# Infogram


def test_infogram_core_ranks_signal_over_noise():
    rng = np.random.default_rng(6)
    n = 1500
    x0 = rng.normal(size=n)
    x1 = rng.normal(size=n)
    x2 = rng.normal(size=n)  # noise
    y = 2 * x0 + x1 + 0.05 * rng.normal(size=n)
    fr = Frame.from_pandas(pd.DataFrame({"x0": x0, "x1": x1, "x2": x2, "y": y}))
    m = Infogram(ntrees=10, max_depth=3).train(y="y", training_frame=fr)
    tab = {r["column"]: r for r in m.get_admissible_score_frame()}
    assert tab["x0"]["total_information"] > tab["x2"]["total_information"]
    assert tab["x0"]["net_information"] > tab["x2"]["net_information"]
    assert "x0" in m.get_admissible_features()
    assert "x2" not in m.get_admissible_features()


def test_infogram_fair_flags_proxy():
    rng = np.random.default_rng(10)
    n = 1500
    protected = rng.normal(size=n)
    proxy = protected + 0.1 * rng.normal(size=n)  # near-copy of protected
    clean = rng.normal(size=n)
    y = protected + clean + 0.1 * rng.normal(size=n)
    df = pd.DataFrame({"prot": protected, "proxy": proxy, "clean": clean, "y": y})
    fr = Frame.from_pandas(df)
    m = Infogram(
        protected_columns=["prot"], ntrees=10, max_depth=3
    ).train(y="y", training_frame=fr)
    tab = {r["column"]: r for r in m.get_admissible_score_frame()}
    assert tab["clean"]["safety_index"] > tab["proxy"]["safety_index"]
    assert "clean" in m.get_admissible_features()
    assert "proxy" not in m.get_admissible_features()


# ---------------------------------------------------------------------------
# PSVM


def test_psvm_nonlinear_boundary():
    rng = np.random.default_rng(5)
    n = 3000
    x0 = rng.normal(size=n)
    x1 = rng.normal(size=n)
    yc = ((x0**2 + x1**2) < 1.2).astype(int)  # circle: linearly inseparable
    df = pd.DataFrame({"x0": x0, "x1": x1, "y": [str(v) for v in yc]})
    fr = Frame.from_pandas(df, column_types={"y": "enum"})
    m = PSVM(hyper_param=1.0, seed=7).train(y="y", training_frame=fr)
    assert m.training_metrics.value("auc") > 0.97
    assert 0 < m.output["svs_count"] < n
    # decisions reproduce on re-scoring
    d1 = m._decision(fr)
    d2 = m._decision(fr)
    np.testing.assert_allclose(d1, d2)


def test_psvm_tracks_sklearn_svc():
    from sklearn.metrics import roc_auc_score
    from sklearn.svm import SVC

    rng = np.random.default_rng(12)
    n = 1500
    X = rng.normal(size=(n, 3))
    yc = ((X[:, 0] * X[:, 1] + X[:, 2]) > 0).astype(int)
    df = pd.DataFrame(X, columns=["a", "b", "c"])
    df["y"] = [str(v) for v in yc]
    fr = Frame.from_pandas(df, column_types={"y": "enum"})
    m = PSVM(hyper_param=1.0, seed=2, max_iterations=300).train(
        y="y", training_frame=fr
    )
    ours = roc_auc_score(yc, m._decision(fr))
    Xs = (X - X.mean(0)) / X.std(0)
    sk = roc_auc_score(
        yc, SVC(C=1.0, gamma=1.0 / 3).fit(Xs, yc).decision_function(Xs)
    )
    assert ours > sk - 0.05  # within 5 AUC points of exact kernel SVC


def test_gam_no_intercept():
    rng = np.random.default_rng(21)
    n = 1200
    x = rng.normal(size=n)
    y = np.sin(x) * 2 + 0.05 * rng.normal(size=n)
    fr = Frame.from_pandas(pd.DataFrame({"x": x, "y": y}))
    g = GAM(gam_columns=["x"], intercept=False).train(y="y", training_frame=fr)
    assert "Intercept" not in g.output["coef_names"]
    assert len(g.output["coef_names"]) == len(g.output["beta"])
    assert g.training_metrics.value("r2") > 0.9  # centered signal still fits


def test_modelselection_coef_size_lookup_backward():
    fr, _ = _lin_frame()
    m = ModelSelection(mode="backward", min_predictor_number=2).train(
        y="y", training_frame=fr
    )
    sizes = [len(s) for s in m.get_best_model_predictors()]
    assert min(sizes) == 2  # no size-1 model exists in this run
    c = m.coef(size=min(sizes))
    assert isinstance(c, dict) and c
    with pytest.raises(ValueError, match="available sizes"):
        m.coef(size=1)
