"""Whole-program GLM IRLS + DeepLearning epoch fusion (ISSUE 8): the fused
lanes (H2O3_TPU_GLM_FUSE, H2O3_TPU_DL_EPOCH_CHUNK, H2O3_TPU_DL_GRAD_SHARD)
must be coefficient-equivalent to the per-iteration/per-epoch paths —
bit-exact where the math is unchanged (DL epoch chunking, the sharded Gram
blocks vs the replicated einsum, shape-bucket padding), f32-envelope where
the solve moved on-device — while dropping host dispatches from
O(iterations|epochs) to O(.../K), reporting into the PR-5 collective
counters, and keeping PR-2 checkpoint kill-and-resume pinned.
"""

import contextlib
import os

import numpy as np
import pandas as pd
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.deeplearning import DeepLearning
from h2o3_tpu.models.glm import GLM
from h2o3_tpu.parallel import mesh as pm
from h2o3_tpu.utils import faults
from h2o3_tpu.utils import metrics as mx


@contextlib.contextmanager
def _use_mesh(k: int):
    """Run under a k-device sub-mesh of the 8-device CPU test cloud."""
    devs = jax.devices("cpu")
    assert len(devs) >= k, "8-device conftest pin did not land"
    old = pm._mesh
    pm.set_mesh(Mesh(np.array(devs[:k]), (pm.ROWS_AXIS,)))
    try:
        yield
    finally:
        pm.set_mesh(old)


@contextlib.contextmanager
def _env(**kv):
    old = {k: os.environ.get(k) for k in kv}
    for k, v in kv.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = str(v)
    try:
        yield
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _df(n=1200, c=6, seed=0, classify=True):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, c)).astype(np.float32)
    eta = X[:, 0] - 0.5 * X[:, 1] + 0.25 * X[:, 2]
    df = pd.DataFrame(X, columns=[f"x{i}" for i in range(c)])
    if classify:
        y = rng.random(n) < 1.0 / (1.0 + np.exp(-eta))
        df["y"] = np.where(y, "a", "b")
    else:
        df["y"] = (eta + 0.3 * rng.normal(size=n)).astype(np.float32)
    return df


def _coefs(m):
    return np.array([m.coef[k] for k in sorted(m.coef)])


# ---------------------------------------------------------------------------
# sharded Gram blocks vs the replicated einsum (mesh sweep)


@pytest.mark.parametrize("k", [1, 2, 8])
def test_sharded_gram_matches_replicated_einsum(k):
    """psum_scatter'd contiguous G row blocks + one all_gather must equal
    the replicated-einsum Gram bit-for-bit on the same mesh (XLA:CPU sums
    per-device partials in the same order either way)."""
    from h2o3_tpu.ops.gram import weighted_gram, weighted_gram_sharded

    with _use_mesh(k):
        n = pm.pad_to_shards(2000)
        p = pm.pad_cols_to_shards(8)
        rng = np.random.default_rng(1)
        X = pm.shard_rows(jnp.asarray(rng.normal(size=(n, p)).astype(np.float32)))
        w = pm.shard_rows(jnp.asarray(
            np.abs(rng.normal(size=n)).astype(np.float32)))
        z = pm.shard_rows(jnp.asarray(rng.normal(size=n).astype(np.float32)))
        Gr, br, swr = jax.jit(weighted_gram)(X, w, z)
        Gs, bs, sws = jax.jit(
            lambda X, w, z: weighted_gram_sharded(X, w, z))(X, w, z)
        np.testing.assert_array_equal(np.asarray(Gr), np.asarray(Gs))
        np.testing.assert_array_equal(np.asarray(br), np.asarray(bs))
        np.testing.assert_allclose(
            float(swr), float(sws), rtol=1e-6)


def test_device_solvers_match_host():
    """The on-device jitter-ladder Cholesky and ADMM reproduce the host
    float64 solutions within the f32 envelope, including the unit pad
    diagonal keeping padded columns at exactly zero."""
    from h2o3_tpu.ops.gram import (
        admm_elastic_net, admm_elastic_net_device, cho_solve_jitter_device,
        solve_cholesky)

    rng = np.random.default_rng(2)
    p, pad = 10, 2
    A = rng.normal(size=(40, p))
    G = A.T @ A + 0.1 * np.eye(p)
    b = rng.normal(size=p)
    Gp = np.zeros((p + pad, p + pad))
    Gp[:p, :p] = G
    bp = np.concatenate([b, np.zeros(pad)])
    pad_diag = (np.arange(p + pad) >= p).astype(np.float32)

    xh = solve_cholesky(G, b)
    xd, ok = jax.jit(cho_solve_jitter_device)(
        jnp.asarray(Gp, jnp.float32), jnp.asarray(bp, jnp.float32),
        jnp.asarray(pad_diag))
    assert bool(ok)
    np.testing.assert_array_equal(np.asarray(xd)[p:], 0.0)
    np.testing.assert_allclose(np.asarray(xd)[:p], xh, rtol=2e-4, atol=2e-4)

    zh = admm_elastic_net(G, b, l1=0.8, l2=0.4, intercept_idx=p - 1)
    zd, ok = admm_elastic_net_device(
        jnp.asarray(Gp, jnp.float32), jnp.asarray(bp, jnp.float32),
        jnp.float32(0.8), jnp.float32(0.4), jnp.int32(p - 1),
        jnp.asarray(pad_diag), jnp.float32(p))
    assert bool(ok)
    np.testing.assert_array_equal(np.asarray(zd)[p:], 0.0)
    np.testing.assert_allclose(np.asarray(zd)[:p], zh, atol=5e-4)


# ---------------------------------------------------------------------------
# GLM fused lane


def test_glm_fused_matches_unfused_elastic_net():
    """Fused (on-device ADMM) vs unfused (host f64 ADMM) coefficient parity
    on the elastic-net lane, plus the dispatch contract: O(iters/K) fused
    vs O(iters) unfused."""
    fr = Frame.from_pandas(_df(seed=3))
    kw = dict(family="binomial", lambda_=1e-4, max_iterations=20, seed=1)
    d0 = mx.counter_value("glm_dispatches_total")
    i0 = mx.counter_value("glm_irls_iterations_total")
    m_f = GLM(**kw).train(y="y", training_frame=fr)
    d1 = mx.counter_value("glm_dispatches_total")
    i1 = mx.counter_value("glm_irls_iterations_total")
    with _env(H2O3_TPU_GLM_FUSE="0"):
        m_u = GLM(**kw).train(y="y", training_frame=fr)
    d2 = mx.counter_value("glm_dispatches_total")
    i2 = mx.counter_value("glm_irls_iterations_total")

    np.testing.assert_allclose(_coefs(m_f), _coefs(m_u), atol=1e-4)
    fused_disp, fused_iters = d1 - d0, i1 - i0
    unfused_disp, unfused_iters = d2 - d1, i2 - i1
    assert unfused_disp == unfused_iters  # one host dispatch per iteration
    assert fused_disp <= -(-fused_iters // 8) + 1  # chunks of K=8
    pf = m_f.predict(fr)
    pu = m_u.predict(fr)
    np.testing.assert_allclose(
        pf.vec(pf.names[-1]).to_numpy(), pu.vec(pu.names[-1]).to_numpy(),
        atol=1e-4)


def test_glm_fused_matches_unfused_cholesky_lane():
    """lambda=0 routes the solve through the device Cholesky jitter ladder
    (no ADMM); gaussian + binomial both stay in the f32 envelope."""
    for fam, classify in (("gaussian", False), ("binomial", True)):
        fr = Frame.from_pandas(_df(seed=4, classify=classify))
        kw = dict(family=fam, lambda_=0.0, alpha=0.0, max_iterations=15,
                  seed=1)
        m_f = GLM(**kw).train(y="y", training_frame=fr)
        with _env(H2O3_TPU_GLM_FUSE="0"):
            m_u = GLM(**kw).train(y="y", training_frame=fr)
        np.testing.assert_allclose(_coefs(m_f), _coefs(m_u), atol=2e-4)


@pytest.mark.parametrize("k", [2, 8])
def test_glm_fused_mesh_sweep_and_gram_counters(k):
    """The fused lane on 2- and 8-device sub-meshes: coefficients match the
    1-device fused run, and the gram_reduce/gram_gather collective phases
    tally (replication-volume model; a 1-device mesh moves nothing)."""
    df = _df(seed=5)
    kw = dict(family="binomial", lambda_=1e-4, max_iterations=10, seed=1)
    with _use_mesh(1):
        m1 = GLM(**kw).train(y="y", training_frame=Frame.from_pandas(df))
    with _use_mesh(k):
        g0 = mx.counter_value("tree_collective_bytes_total",
                              phase="gram_reduce")
        a0 = mx.counter_value("tree_collective_bytes_total",
                              phase="gram_gather")
        mk = GLM(**kw).train(y="y", training_frame=Frame.from_pandas(df))
        assert mx.counter_value(
            "tree_collective_bytes_total", phase="gram_reduce") > g0
        assert mx.counter_value(
            "tree_collective_bytes_total", phase="gram_gather") > a0
    np.testing.assert_allclose(_coefs(m1), _coefs(mk), atol=2e-4)


def test_glm_bucketed_padding_is_inert():
    """Shape-bucketed design columns (zero columns + unit solve diagonal)
    must not move the coefficients beyond XLA reduction-order rounding: the
    padded Gram's real block contracts the same products, but XLA may tile
    the einsum differently at the padded shape, so the pin is the f32
    reduction envelope, not bit-equality (the padded COEFFICIENTS
    themselves are exactly zero — asserted via the solver unit test)."""
    df = _df(seed=6)  # 6 features + intercept = 7 -> pads to 8
    kw = dict(family="binomial", lambda_=1e-4, max_iterations=10, seed=1)
    with _use_mesh(1):
        m_b = GLM(**kw).train(y="y", training_frame=Frame.from_pandas(df))
        with _env(H2O3_TPU_SHAPE_BUCKETS="0"):
            m_e = GLM(**kw).train(y="y", training_frame=Frame.from_pandas(df))
    np.testing.assert_allclose(_coefs(m_b), _coefs(m_e), atol=1e-5)


def test_glm_same_bucket_rebuild_zero_new_compiles():
    """The PR-1 ladder applied to GLM program keys: a rebuild on a frame
    whose design width lands in the SAME 4-column bucket (and same row
    bucket) must compile ZERO new fused chunk programs."""
    df_a = _df(seed=7, c=6)   # 6 + intercept = 7 -> bucket 8
    df_b = _df(seed=8, c=7)   # 7 + intercept = 8 -> bucket 8
    kw = dict(family="binomial", lambda_=1e-4, max_iterations=6, seed=1)
    GLM(**kw).train(y="y", training_frame=Frame.from_pandas(df_a))
    c0 = mx.counter_value("glm_programs_compiled_total")
    h0 = mx.counter_value("glm_program_cache_hits_total")
    GLM(**kw).train(y="y", training_frame=Frame.from_pandas(df_b))
    assert mx.counter_value("glm_programs_compiled_total") == c0
    assert mx.counter_value("glm_program_cache_hits_total") > h0


def test_glm_fused_checkpoint_kill_and_resume_bit_exact(tmp_path):
    """PR-2's exact-trajectory contract under the fused lane: with
    export_checkpoints_dir set the chunk clamps to K=1 (irls_state
    snapshots land at every iteration boundary), and a killed run resumed
    from the snapshot reproduces the uninterrupted FUSED trajectory
    bit-for-bit."""
    from h2o3_tpu.persist import load_model

    fr = Frame.from_pandas(_df(seed=9))
    kw = dict(family="binomial", max_iterations=25, seed=1)
    with _env(H2O3_TPU_GLM_FUSE="8"):
        full = GLM(**kw).train(y="y", training_frame=fr)
        ckdir = str(tmp_path / "glm_ck")
        with faults.inject(abort={"glm": 3}):
            with pytest.raises(faults.TrainAbort):
                GLM(export_checkpoints_dir=ckdir, **kw).train(
                    y="y", training_frame=fr)
        snaps = [f for f in os.listdir(ckdir) if "glm_ckpt" in f]
        assert snaps
        prior = load_model(os.path.join(ckdir, snaps[0]))
        # checkpoints-on clamps the chunk: the snapshot position is an
        # exact iteration boundary
        assert prior.output["irls_state"]["it"] <= 3
        resumed = GLM(checkpoint=prior.key, **kw).train(
            y="y", training_frame=fr)
    np.testing.assert_array_equal(
        np.asarray(resumed.output["beta_std"]),
        np.asarray(full.output["beta_std"]))


def _free_compile_state():
    """Drop in-memory compiled executables after a compile-heavy test —
    the ISSUE-15 suites add dozens of programs (fused multinomial on three
    sub-meshes, dropout lanes) to a long-lived tier-1 process that this
    jaxlib's CPU backend can otherwise crash compiling into (see the
    test_split_pallas twin of this helper); later tests re-read the
    persistent compile cache, so the wall cost is small."""
    jax.clear_caches()


def _df_multinomial(n=1200, c=5, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, c)).astype(np.float32)
    eta = np.stack([X[:, 0], -X[:, 1], 0.5 * X[:, 2]], 1)
    pm_ = np.exp(eta)
    pm_ /= pm_.sum(1, keepdims=True)
    yk = np.array([rng.choice(3, p=pr) for pr in pm_])
    df = pd.DataFrame(X, columns=[f"x{i}" for i in range(c)])
    df["y"] = np.array(["a", "b", "c"])[yk]
    return df


def test_glm_fused_multinomial_parity_and_dispatches():
    """ISSUE-15 closure (b): the K-class cycling IRLS runs as ONE fused
    program (lax.scan over classes inside one while_loop). Coefficient
    parity <= 2e-3 vs the host f64 cycling loop at equal iteration count,
    and dispatches/model drop >= 3x (counter-pinned: the host loop pays
    one dispatch per (iteration, class))."""
    fr = Frame.from_pandas(_df_multinomial(seed=21))
    # objective_epsilon=0 pins both lanes to the FULL iteration budget so
    # the dispatch ratio compares equal work
    kw = dict(family="multinomial", max_iterations=8, seed=1,
              objective_epsilon=0.0)
    d0 = mx.counter_value("glm_dispatches_total")
    m_f = GLM(**kw).train(y="y", training_frame=fr)
    d1 = mx.counter_value("glm_dispatches_total")
    with _env(H2O3_TPU_GLM_FUSE="0"):
        m_u = GLM(**kw).train(y="y", training_frame=fr)
    d2 = mx.counter_value("glm_dispatches_total")
    fused_disp, unfused_disp = d1 - d0, d2 - d1
    assert unfused_disp == 8 * 3  # one per (iteration, class)
    assert unfused_disp >= 3 * fused_disp, (unfused_disp, fused_disp)
    Bf = np.asarray(m_f.output["beta_multinomial_std"])
    Bu = np.asarray(m_u.output["beta_multinomial_std"])
    np.testing.assert_allclose(Bf, Bu, atol=2e-3)
    pf = m_f.predict(fr)
    pu = m_u.predict(fr)
    np.testing.assert_allclose(
        pf.vec(pf.names[-1]).to_numpy(), pu.vec(pu.names[-1]).to_numpy(),
        atol=1e-4)
    _free_compile_state()


@pytest.mark.parametrize("k", [2, 8])
def test_glm_fused_multinomial_mesh_sweep(k):
    """The fused multinomial's sharded Gram (per-class psum_scatter +
    gather) on 2/8-device sub-meshes matches the 1-device fused run."""
    df = _df_multinomial(seed=22)
    kw = dict(family="multinomial", max_iterations=6, seed=1)
    with _use_mesh(1):
        m1 = GLM(**kw).train(y="y", training_frame=Frame.from_pandas(df))
    with _use_mesh(k):
        mk = GLM(**kw).train(y="y", training_frame=Frame.from_pandas(df))
    np.testing.assert_allclose(
        np.asarray(m1.output["beta_multinomial_std"]),
        np.asarray(mk.output["beta_multinomial_std"]), atol=2e-3)
    _free_compile_state()


def test_glm_fused_multinomial_kill_and_resume_bit_exact(tmp_path):
    """Multinomial irls_state (NEW in ISSUE 15): with
    export_checkpoints_dir the fused chunk clamps to one outer iteration,
    snapshots carry (it, ll_prev, Beta), and a killed run resumed from the
    snapshot reproduces the uninterrupted FUSED trajectory bit-for-bit."""
    from h2o3_tpu.persist import load_model

    fr = Frame.from_pandas(_df_multinomial(seed=23))
    kw = dict(family="multinomial", max_iterations=8, seed=1,
              objective_epsilon=0.0)
    full = GLM(**kw).train(y="y", training_frame=fr)
    ckdir = str(tmp_path / "glm_mn_ck")
    with faults.inject(abort={"glm": 3}):
        with pytest.raises(faults.TrainAbort):
            GLM(export_checkpoints_dir=ckdir, **kw).train(
                y="y", training_frame=fr)
    snaps = [f for f in os.listdir(ckdir) if "glm_ckpt" in f]
    assert snaps
    prior = load_model(os.path.join(ckdir, snaps[0]))
    st_ = prior.output["irls_state"]
    assert st_["multinomial"] and st_["it"] <= 3
    resumed = GLM(checkpoint=prior.key, **kw).train(y="y", training_frame=fr)
    np.testing.assert_array_equal(
        np.asarray(resumed.output["beta_multinomial_std"]),
        np.asarray(full.output["beta_multinomial_std"]))
    _free_compile_state()


def test_glm_fused_ordinal_matches_host_driver():
    """The fused on-device BFGS ordinal fit converges to the host
    L-BFGS-B optimum (the NLL is convex in this parameterization);
    predictions within the f32 optimization envelope."""
    rng = np.random.default_rng(24)
    n, c = 1000, 4
    X = rng.normal(size=(n, c)).astype(np.float32)
    lat = X[:, 0] - 0.7 * X[:, 1] + 0.5 * rng.normal(size=n)
    yk = np.digitize(lat, [-0.7, 0.7])
    df = pd.DataFrame(X, columns=[f"x{i}" for i in range(c)])
    df["y"] = np.array(["lo", "mid", "hi"])[yk]
    fr = Frame.from_pandas(df)
    m_f = GLM(family="ordinal", seed=1).train(y="y", training_frame=fr)
    with _env(H2O3_TPU_GLM_FUSE="0"):
        m_h = GLM(family="ordinal", seed=1).train(y="y", training_frame=fr)
    np.testing.assert_allclose(
        np.asarray(m_f.output["beta_std"]),
        np.asarray(m_h.output["beta_std"]), atol=2e-3)
    np.testing.assert_allclose(
        np.asarray(m_f.output["theta"]),
        np.asarray(m_h.output["theta"]), atol=2e-3)
    pf = m_f.predict(fr)
    ph = m_h.predict(fr)
    np.testing.assert_allclose(
        pf.vec(pf.names[-1]).to_numpy(), ph.vec(ph.names[-1]).to_numpy(),
        atol=2e-3)


def test_glm_fallback_counter_p_values_quiet():
    """compute_p_values rides the fused IRLS lane (ISSUE 16): the
    glm_fuse_fallbacks_total{reason=p_values} counter stays quiet and the
    fused chunk program compiles/hits like any other fit."""
    fr = Frame.from_pandas(_df(seed=25))
    f0 = mx.counter_value("glm_fuse_fallbacks_total", reason="p_values")
    c0 = mx.counter_value("glm_programs_compiled_total")
    h0 = mx.counter_value("glm_program_cache_hits_total")
    m = GLM(family="binomial", lambda_=0.0, alpha=0.0, compute_p_values=True,
            max_iterations=5, seed=1).train(y="y", training_frame=fr)
    assert "p_values" in m.output
    assert mx.counter_value(
        "glm_fuse_fallbacks_total", reason="p_values") == f0
    assert (mx.counter_value("glm_programs_compiled_total") > c0
            or mx.counter_value("glm_program_cache_hits_total") > h0)


def test_glm_p_values_fused_parity():
    """Fused-lane p-values (covariance from the final device Gram at the
    converged beta) must match the unfused per-iteration path within the
    f32 trajectory envelope."""
    fr = Frame.from_pandas(_df(seed=10))
    m_f = GLM(family="binomial", lambda_=0.0, alpha=0.0,
              compute_p_values=True, max_iterations=10, seed=1).train(
        y="y", training_frame=fr)
    with _env(H2O3_TPU_GLM_FUSE="0"):
        m_u = GLM(family="binomial", lambda_=0.0, alpha=0.0,
                  compute_p_values=True, max_iterations=10, seed=1).train(
            y="y", training_frame=fr)
    np.testing.assert_allclose(
        np.asarray(m_f.output["p_values"], dtype=np.float64),
        np.asarray(m_u.output["p_values"], dtype=np.float64), atol=1e-6)


# ---------------------------------------------------------------------------
# DL fused lanes


def test_dl_epoch_chunk_bit_identical_and_dispatches():
    """Folding K epochs into one program (donated carry, host-side
    permutation RNG, threaded dropout key) must reproduce the per-epoch
    trajectory BIT-identically, with O(epochs/K) dispatches."""
    fr = Frame.from_pandas(_df(seed=11))
    kw = dict(hidden=[16], epochs=4, mini_batch_size=64, seed=7)
    with _env(H2O3_TPU_DL_GRAD_SHARD="0"):
        d0 = mx.counter_value("dl_dispatches_total")
        m_c = DeepLearning(**kw).train(y="y", training_frame=fr)
        d1 = mx.counter_value("dl_dispatches_total")
        with _env(H2O3_TPU_DL_EPOCH_CHUNK="1"):
            m_1 = DeepLearning(**kw).train(y="y", training_frame=fr)
        d2 = mx.counter_value("dl_dispatches_total")
    assert d1 - d0 == 1     # 4 epochs, one chunk
    assert d2 - d1 == 4     # per-epoch control
    pc = m_c.predict(fr)
    p1 = m_1.predict(fr)
    np.testing.assert_array_equal(
        pc.vec(pc.names[-1]).to_numpy(), p1.vec(p1.names[-1]).to_numpy())
    # per-epoch history is preserved under chunking
    assert [h["epoch"] for h in m_c.scoring_history] == [1, 2, 3, 4]
    np.testing.assert_allclose(
        [h["loss"] for h in m_c.scoring_history],
        [h["loss"] for h in m_1.scoring_history], rtol=1e-5)


def test_dl_grad_shard_parity_and_counters():
    """The sharded gradient reduction (flat psum_scatter + per-shard
    optimizer + params all_gather) stays within the reduction-order
    envelope of the replicated lane and tallies dl_grad_reduce /
    dl_param_gather."""
    fr = Frame.from_pandas(_df(seed=12))
    kw = dict(hidden=[16], epochs=4, mini_batch_size=64, seed=7)
    g0 = mx.counter_value("tree_collective_bytes_total",
                          phase="dl_grad_reduce")
    a0 = mx.counter_value("tree_collective_bytes_total",
                          phase="dl_param_gather")
    m_s = DeepLearning(**kw).train(y="y", training_frame=fr)
    assert mx.counter_value(
        "tree_collective_bytes_total", phase="dl_grad_reduce") > g0
    assert mx.counter_value(
        "tree_collective_bytes_total", phase="dl_param_gather") > a0
    with _env(H2O3_TPU_DL_GRAD_SHARD="0"):
        m_r = DeepLearning(**kw).train(y="y", training_frame=fr)
    ps = m_s.predict(fr)
    pr = m_r.predict(fr)
    np.testing.assert_allclose(
        ps.vec(ps.names[-1]).to_numpy(), pr.vec(pr.names[-1]).to_numpy(),
        atol=1e-4)


@pytest.mark.parametrize("k", [2, 8])
def test_dl_mesh_sweep_chunk_invariance(k):
    """Chunked-vs-per-epoch bit-identity holds on every sub-mesh size
    (the sharded grad lane is active on >1-device meshes)."""
    df = _df(seed=13)
    kw = dict(hidden=[8], epochs=3, mini_batch_size=64, seed=4)
    with _use_mesh(k):
        fr = Frame.from_pandas(df)
        m_c = DeepLearning(**kw).train(y="y", training_frame=fr)
        with _env(H2O3_TPU_DL_EPOCH_CHUNK="1"):
            m_1 = DeepLearning(**kw).train(y="y", training_frame=fr)
        pc = m_c.predict(fr)
        p1 = m_1.predict(fr)
        np.testing.assert_array_equal(
            pc.vec(pc.names[-1]).to_numpy(), p1.vec(p1.names[-1]).to_numpy())


def test_dl_bucketed_input_bit_identical():
    """Input-width bucketing (zero-padded first kernel rows) must be
    bit-inert: the padded rows start at zero, receive zero gradients, and
    the real-weight trajectory is unchanged."""
    df = _df(seed=14, c=6)  # D=6 -> pads to 8
    kw = dict(hidden=[8], epochs=3, mini_batch_size=64, seed=4)
    fr = Frame.from_pandas(df)
    m_b = DeepLearning(**kw).train(y="y", training_frame=fr)
    assert int(m_b.output["input_pad"]) == 2
    k0 = np.asarray(m_b.output["params"]["params"]["Dense_0"]["kernel"])
    np.testing.assert_array_equal(k0[6:], 0.0)  # pad rows stayed zero
    with _env(H2O3_TPU_SHAPE_BUCKETS="0"):
        m_e = DeepLearning(**kw).train(y="y", training_frame=fr)
    assert int(m_e.output["input_pad"]) == 0
    pb = m_b.predict(fr)
    pe = m_e.predict(fr)
    # padded rows contribute exact zeros to every dot product; the only
    # permissible deviation is XLA re-tiling the wider matmul
    np.testing.assert_allclose(
        pb.vec(pb.names[-1]).to_numpy(), pe.vec(pe.names[-1]).to_numpy(),
        atol=1e-6)


def test_dl_same_bucket_rebuild_zero_new_compiles():
    """A rebuild on a frame in the same input-width bucket (and row
    bucket) must compile ZERO new epoch-chunk programs."""
    kw = dict(hidden=[8], epochs=2, mini_batch_size=64, seed=4)
    DeepLearning(**kw).train(
        y="y", training_frame=Frame.from_pandas(_df(seed=15, c=6)))
    c0 = mx.counter_value("dl_programs_compiled_total")
    h0 = mx.counter_value("dl_program_cache_hits_total")
    # 7 features -> same 8-wide bucket as 6; rows unchanged -> same npad;
    # the minibatch trip count is a DYNAMIC argument, so a different row
    # count inside the bucket would not recompile either
    DeepLearning(**kw).train(
        y="y", training_frame=Frame.from_pandas(_df(seed=16, c=7)))
    assert mx.counter_value("dl_programs_compiled_total") == c0
    assert mx.counter_value("dl_program_cache_hits_total") > h0


def test_dl_dropout_trains_on_sharded_lane_with_ctl_parity():
    """ISSUE-15 closure (c): dropout no longer gates the sharded-gradient
    lane — each device folds its shard index into the minibatch dropout
    key. The H2O3_TPU_DL_GRAD_SHARD=ctl lane is the replicated control
    drawing the SAME masks (per-chunk folds): trajectory parity pinned at
    1e-4 preds. The old replicated lane (full-batch masks) must genuinely
    DIFFER — proving the dropout actually fires — and GRAD_SHARD=0 still
    restores it."""
    fr = Frame.from_pandas(_df(seed=26))
    kw = dict(hidden=[16], epochs=4, mini_batch_size=64, seed=7,
              activation="RectifierWithDropout",
              hidden_dropout_ratios=[0.3], input_dropout_ratio=0.1)
    g0 = mx.counter_value("tree_collective_bytes_total",
                          phase="dl_grad_reduce")
    m_s = DeepLearning(**kw).train(y="y", training_frame=fr)
    assert mx.counter_value(
        "tree_collective_bytes_total", phase="dl_grad_reduce") > g0, \
        "dropout training no longer engaged the sharded lane"
    with _env(H2O3_TPU_DL_GRAD_SHARD="ctl"):
        m_c = DeepLearning(**kw).train(y="y", training_frame=fr)
    with _env(H2O3_TPU_DL_GRAD_SHARD="0"):
        m_r = DeepLearning(**kw).train(y="y", training_frame=fr)
    ps = m_s.predict(fr)
    pc = m_c.predict(fr)
    pr = m_r.predict(fr)
    a = ps.vec(ps.names[-1]).to_numpy()
    b = pc.vec(pc.names[-1]).to_numpy()
    c = pr.vec(pr.names[-1]).to_numpy()
    np.testing.assert_allclose(a, b, atol=1e-4)  # the trajectory-parity pin
    # full-batch masks are a DIFFERENT dropout stream: if these matched,
    # the parity above would be vacuous (dropout never fired)
    assert np.max(np.abs(a - c)) > 1e-3
    _free_compile_state()


def test_dl_shard_fallback_counter_reasons():
    """dl_shard_fallbacks_total{reason}: batch indivisibility and
    non-elementwise optimizer state still fall back — and tally."""
    fr = Frame.from_pandas(_df(seed=27))
    b0 = mx.counter_value("dl_shard_fallbacks_total",
                          reason="batch_indivisible")
    # 63 % 8 != 0 on the 8-device mesh -> replicated + counter
    DeepLearning(hidden=[8], epochs=2, mini_batch_size=63, seed=4).train(
        y="y", training_frame=fr)
    assert mx.counter_value(
        "dl_shard_fallbacks_total", reason="batch_indivisible") > b0
    o0 = mx.counter_value("dl_shard_fallbacks_total", reason="opt_state")
    # momentum SGD carries a schedule step counter -> non-elementwise
    DeepLearning(hidden=[8], epochs=2, mini_batch_size=64, seed=4,
                 adaptive_rate=False, rate=0.01, rate_decay=0.9,
                 momentum_start=0.5).train(y="y", training_frame=fr)
    assert mx.counter_value(
        "dl_shard_fallbacks_total", reason="opt_state") > o0


def test_dl_chunked_checkpoint_resume_matches_full():
    """Key-based continuation into the chunked driver: the RNG fast-forward
    keeps the resumed trajectory identical to an uninterrupted chunked
    run."""
    fr = Frame.from_pandas(_df(seed=17))
    kw = dict(hidden=[8], seed=4, mini_batch_size=64)
    full = DeepLearning(epochs=5, **kw).train(y="y", training_frame=fr)
    part = DeepLearning(epochs=2, **kw).train(y="y", training_frame=fr)
    resumed = DeepLearning(epochs=5, checkpoint=part.key, **kw).train(
        y="y", training_frame=fr)
    assert resumed.output["epochs_trained"] == 5
    assert len(resumed.scoring_history) == 3  # only the 3 new epochs ran
    pf = full.predict(fr)
    pr = resumed.predict(fr)
    np.testing.assert_array_equal(
        pf.vec(pf.names[-1]).to_numpy(), pr.vec(pr.names[-1]).to_numpy())
