"""Rapids-successor frame ops (h2o3_tpu/frame/ops.py) against pandas truth."""

import numpy as np
import pandas as pd
import pytest

import h2o3_tpu
from h2o3_tpu.frame.frame import Frame, Vec
from h2o3_tpu.frame import ops


@pytest.fixture()
def fr(rng):
    n = 500
    df = pd.DataFrame(
        {
            "a": rng.normal(size=n),
            "b": rng.normal(size=n) + 2.0,
            "g": rng.choice(["x", "y", "z"], n),
            "s": [f"row_{i}" for i in range(n)],
            "t": pd.date_range("2020-01-01", periods=n, freq="h"),
        }
    )
    df.loc[5, "a"] = np.nan
    return h2o3_tpu.upload_file(df), df


def col(v):
    return np.asarray(v.to_numpy(), dtype=np.float64)


class TestArithmetic:
    def test_binary_ops(self, fr):
        f, df = fr
        a, b = f.vec("a"), f.vec("b")
        np.testing.assert_allclose(col(a + b), (df.a + df.b), rtol=1e-5)
        np.testing.assert_allclose(col(a - b), (df.a - df.b), rtol=1e-5)
        np.testing.assert_allclose(col(a * 2), df.a * 2, rtol=1e-5)
        np.testing.assert_allclose(col(1 / b), 1 / df.b, rtol=1e-5)
        np.testing.assert_allclose(col(2 - a), 2 - df.a, rtol=1e-5)

    def test_comparisons_na(self, fr):
        f, df = fr
        gt = col(f.vec("a") > 0)
        want = (df.a > 0).astype(float).where(df.a.notna(), np.nan)
        np.testing.assert_allclose(gt, want, rtol=1e-6)
        assert np.isnan(gt[5])

    def test_unary(self, fr):
        f, df = fr
        np.testing.assert_allclose(
            col(f.vec("b").log()), np.log(df.b), rtol=1e-5, atol=1e-5
        )
        np.testing.assert_allclose(col(f.vec("a").abs()), np.abs(df.a), rtol=1e-5)
        isna = col(f.vec("a").isna())
        assert isna[5] == 1.0 and isna.sum() == 1.0

    def test_ifelse(self, fr):
        f, df = fr
        got = col(ops.ifelse(f.vec("a") > 0, f.vec("b"), 0.0))
        want = np.where(df.a > 0, df.b, 0.0)
        want = np.where(df.a.isna(), np.nan, want)
        np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_cumsum(self, fr):
        f, df = fr
        got = col(f.vec("b").cumsum())
        np.testing.assert_allclose(got, np.cumsum(df.b), rtol=1e-4)


class TestGroupBy:
    def test_agg_matches_pandas(self, fr):
        f, df = fr
        out = f.group_by("g").agg({"a": ["mean", "sum", "min", "max", "sd"], "b": "count"}).to_pandas()
        want = df.groupby("g").agg(
            mean_a=("a", "mean"), sum_a=("a", "sum"), min_a=("a", "min"),
            max_a=("a", "max"), sd_a=("a", "std"), count_b=("b", "size"),
        ).reset_index()
        out = out.sort_values("g").reset_index(drop=True)
        for c in ("mean_a", "sum_a", "min_a", "max_a", "sd_a", "count_b"):
            np.testing.assert_allclose(
                out[c].astype(float), want[c].astype(float), rtol=1e-4, err_msg=c
            )

    def test_median_numeric_key(self, fr):
        f, df = fr
        f2 = h2o3_tpu.upload_file(pd.DataFrame({"k": [1, 1, 2, 2, 2], "v": [1.0, 3.0, 2.0, 4.0, 6.0]}))
        out = f2.group_by("k").agg({"v": "median"}).to_pandas().sort_values("k")
        np.testing.assert_allclose(out["median_v"], [2.0, 4.0])


class TestMergeSort:
    def test_inner_merge(self):
        left = h2o3_tpu.upload_file(pd.DataFrame({"k": ["a", "b", "c"], "x": [1.0, 2.0, 3.0]}))
        right = h2o3_tpu.upload_file(pd.DataFrame({"k": ["b", "c", "d"], "y": [20.0, 30.0, 40.0]}))
        out = ops.merge(left, right).to_pandas()
        assert sorted(out["k"]) == ["b", "c"]
        assert out.loc[out.k == "b", "y"].iloc[0] == 20.0

    def test_left_merge(self):
        left = h2o3_tpu.upload_file(pd.DataFrame({"k": ["a", "b"], "x": [1.0, 2.0]}))
        right = h2o3_tpu.upload_file(pd.DataFrame({"k": ["b"], "y": [9.0]}))
        out = ops.merge(left, right, all_x=True).to_pandas()
        assert len(out) == 2 and np.isnan(out.loc[out.k == "a", "y"].iloc[0])

    def test_sort(self, fr):
        f, df = fr
        out = ops.sort(f, "b").to_pandas()
        assert (np.diff(out["b"]) >= 0).all()


class TestQuantileTable:
    def test_quantile(self, fr):
        f, df = fr
        q = ops.quantile(f.vec("b"), prob=[0.25, 0.5, 0.75]).to_pandas()
        want = np.quantile(df.b, [0.25, 0.5, 0.75])
        np.testing.assert_allclose(q["b"], want, rtol=1e-4)

    def test_table(self, fr):
        f, df = fr
        t = ops.table(f.vec("g")).to_pandas()
        want = df.g.value_counts()
        for _, row in t.iterrows():
            assert row["Count"] == want[row["g"]]

    def test_unique(self, fr):
        f, df = fr
        u = ops.unique(f.vec("g")).to_pandas()
        assert sorted(u.iloc[:, 0]) == sorted(df.g.unique())

    def test_cut(self, fr):
        f, df = fr
        v = ops.cut(f.vec("b"), breaks=[-10, 0, 2, 10])
        assert v.kind == "enum" and v.cardinality == 3


class TestImputeScale:
    def test_impute_mean(self):
        f = h2o3_tpu.upload_file(pd.DataFrame({"x": [1.0, np.nan, 3.0]}))
        fill = ops.impute(f, "x", method="mean")
        assert fill == pytest.approx(2.0)
        np.testing.assert_allclose(f.vec("x").to_numpy(), [1, 2, 3])

    def test_impute_by_group(self):
        f = h2o3_tpu.upload_file(
            pd.DataFrame({"g": ["a", "a", "b", "b"], "x": [1.0, np.nan, 10.0, np.nan]})
        )
        ops.impute(f, "x", method="mean", by=["g"])
        np.testing.assert_allclose(f.vec("x").to_numpy(), [1, 1, 10, 10])

    def test_scale(self, fr):
        f, df = fr
        out = ops.scale(f[["b"]]).to_pandas()
        assert abs(out["b"].mean()) < 1e-4 and abs(out["b"].std() - 1) < 1e-2

    def test_cor(self, fr):
        f, df = fr
        c = ops.cor(f[["a", "b"]]).to_pandas()
        want = df[["a", "b"]].dropna().corr()
        np.testing.assert_allclose(c.values, want.values, atol=1e-4)


class TestStringsTime:
    def test_string_ops(self, fr):
        f, _ = fr
        up = f.vec("s").toupper()
        assert up.to_numpy()[0] == "ROW_0"
        assert f.vec("s").nchar().to_numpy()[0] == 5.0
        g2 = f.vec("s").gsub("row", "R")
        assert g2.to_numpy()[0] == "R_0"

    def test_string_ops_on_enum_rewrite_domain(self, fr):
        f, _ = fr
        up = f.vec("g").toupper()
        assert up.kind == "enum" and set(up.levels()) == {"X", "Y", "Z"}

    def test_strsplit(self, fr):
        f, _ = fr
        parts = f.vec("s").strsplit("_").to_pandas()
        assert parts.iloc[0, 0] == "row" and parts.iloc[0, 1] == "0"

    def test_time_components(self, fr):
        f, df = fr
        assert (f.vec("t").year().to_numpy() == 2020).all()
        np.testing.assert_allclose(f.vec("t").hour().to_numpy(), df.t.dt.hour)
        np.testing.assert_allclose(f.vec("t").day_of_week().to_numpy(), df.t.dt.dayofweek)


class TestConversions:
    def test_asfactor_roundtrip(self, fr):
        f, df = fr
        v = h2o3_tpu.upload_file(pd.DataFrame({"x": [1.0, 2.0, 1.0]})).vec("x").asfactor()
        assert v.kind == "enum" and v.levels() == ["1", "2"]
        back = v.asnumeric()
        np.testing.assert_allclose(back.to_numpy(), [1, 2, 1])

    def test_ascharacter(self, fr):
        f, _ = fr
        s = f.vec("g").ascharacter()
        assert s.kind == "string"

    def test_setitem(self, fr):
        f, df = fr
        f["a2"] = f.vec("a") * 2
        np.testing.assert_allclose(col(f.vec("a2")), df.a * 2, rtol=1e-5)
        assert "a2" in f.names


class TestReviewRegressions:
    """Fixes confirmed by the pre-commit review: NA enum semantics, string
    comparisons, TIME round-trips through merge, tz-aware ingest."""

    def test_merge_preserves_time(self):
        left = h2o3_tpu.upload_file(
            pd.DataFrame({"k": ["a", "b"], "t": pd.to_datetime(["2020-01-01", "2021-06-30"])})
        )
        right = h2o3_tpu.upload_file(pd.DataFrame({"k": ["a", "b"], "y": [1.0, 2.0]}))
        out = ops.merge(left, right)
        assert out.types["t"] == "time"
        ms = out.vec("t").to_numpy()
        assert abs(ms[0] - 1577836800000.0) < 1  # 2020-01-01 epoch-ms

    def test_tz_aware_ingest(self):
        f = h2o3_tpu.upload_file(
            pd.DataFrame({"t": pd.date_range("2020-01-01", periods=3, tz="US/Pacific")})
        )
        assert f.types["t"] == "time"
        # 2020-01-01 00:00 Pacific = 08:00 UTC
        assert abs(f.vec("t").to_numpy()[0] - 1577865600000.0) < 1

    def test_enum_na_comparison(self):
        f = h2o3_tpu.upload_file(
            pd.DataFrame({"g": ["x", None, "y"], "h": ["x", None, "z"]})
        )
        eq = (f.vec("g") == f.vec("h")).to_numpy()
        assert eq[0] == 1.0 and np.isnan(eq[1]) and eq[2] == 0.0

    def test_enum_eq_string_literal(self):
        f = h2o3_tpu.upload_file(pd.DataFrame({"g": ["x", None, "y"]}))
        eq = (f.vec("g") == "x").to_numpy()
        assert eq[0] == 1.0 and np.isnan(eq[1]) and eq[2] == 0.0
        ne = (f.vec("g") != "x").to_numpy()
        assert ne[0] == 0.0 and np.isnan(ne[1]) and ne[2] == 1.0
        nomatch = (f.vec("g") == "zzz").to_numpy()
        assert nomatch[0] == 0.0 and np.isnan(nomatch[1])

    def test_groupby_enum_excludes_na_codes(self):
        f = h2o3_tpu.upload_file(pd.DataFrame({"k": ["a", "a"], "c": ["u", None]}))
        out = f.group_by("k").agg({"c": ["min", "mode"]}).to_pandas()
        assert out["min_c"].iloc[0] == 0.0  # code of 'u', not the -1 sentinel
        assert out["mode_c"].iloc[0] == 0.0

    def test_impute_categorical_by_group(self):
        f = h2o3_tpu.upload_file(
            pd.DataFrame({"g": ["a", "a", "a"], "c": ["u", "u", None]})
        )
        ops.impute(f, "c", method="mode", by=["g"])
        assert f.vec("c").to_numpy().tolist() == [0, 0, 0]


def test_merge_and_sort_avoid_full_frame_host_roundtrip(monkeypatch):
    """merge/sort must compute permutations from KEY columns only and gather
    payload on device — to_pandas on the inputs is the former slow path."""
    n = 4000
    rng = np.random.default_rng(0)
    left = h2o3_tpu.upload_file(pd.DataFrame({
        "k": rng.integers(0, 500, n), "x": rng.normal(size=n),
        "c": rng.choice(["u", "v"], n)}))
    right = h2o3_tpu.upload_file(pd.DataFrame({
        "k": rng.integers(0, 500, n), "y": rng.normal(size=n)}))

    def boom(self):
        raise AssertionError("to_pandas called during merge/sort")

    monkeypatch.setattr(Frame, "to_pandas", boom)
    out = ops.merge(left, right, by=["k"])
    srt = ops.sort(left, "k")
    monkeypatch.undo()

    # correctness vs pandas reference
    ldf = pd.DataFrame({"k": left.vec("k").to_numpy(), "x": left.vec("x").to_numpy()})
    rdf = pd.DataFrame({"k": right.vec("k").to_numpy(), "y": right.vec("y").to_numpy()})
    ref = ldf.merge(rdf, on="k", how="inner")
    assert out.nrow == len(ref)
    assert abs(float(np.nansum(out.vec("y").to_numpy())) - float(ref["y"].sum())) < 1e-3
    assert (np.diff(srt.vec("k").to_numpy()) >= 0).all()


class TestDeviceJoin:
    """Device-side merge/sort (ASTMerge radix-join successor): the key path
    must be pandas-free, and must agree with a pandas reference on every
    join flavor including duplicate keys (cartesian groups), NaN keys,
    multi-key joins and enum keys with differing domains."""

    def _frames(self, n=3000, seed=5):
        rng = np.random.default_rng(seed)
        ldf = pd.DataFrame({
            "k": rng.integers(0, 200, n).astype(np.float64),
            "k2": rng.integers(0, 4, n).astype(np.float64),
            "x": rng.normal(size=n),
        })
        rdf = pd.DataFrame({
            "k": rng.integers(0, 300, n // 2).astype(np.float64),
            "k2": rng.integers(0, 4, n // 2).astype(np.float64),
            "y": rng.normal(size=n // 2),
        })
        ldf.loc[::37, "k"] = np.nan  # NaN keys must match NaN keys
        rdf.loc[::53, "k"] = np.nan
        return ldf, rdf

    def _check(self, how, all_x, all_y):
        ldf, rdf = self._frames()
        left, right = h2o3_tpu.upload_file(ldf), h2o3_tpu.upload_file(rdf)
        out = ops.merge(left, right, by=["k"], all_x=all_x, all_y=all_y)
        ref = ldf[["k", "x"]].merge(rdf[["k", "y"]], on="k", how=how)
        assert out.nrow == len(ref)
        for c in ("x", "y"):
            got = np.nansum(out.vec(c).to_numpy())
            want = ref[c].sum()
            assert abs(got - want) < 1e-6 * max(1, abs(want)), (how, c)

    def test_inner_duplicates_and_nan(self):
        self._check("inner", False, False)

    def test_left(self):
        self._check("left", True, False)

    def test_right(self):
        self._check("right", False, True)

    def test_outer(self):
        self._check("outer", True, True)

    def test_multi_key(self):
        ldf, rdf = self._frames()
        left, right = h2o3_tpu.upload_file(ldf), h2o3_tpu.upload_file(rdf)
        out = ops.merge(left, right, by=["k", "k2"])
        ref = ldf.merge(rdf, on=["k", "k2"], how="inner")
        assert out.nrow == len(ref)
        want = ref["y"].sum()
        assert abs(np.nansum(out.vec("y").to_numpy()) - want) < 1e-6 * max(1, abs(want))

    def test_enum_keys_differing_domains(self):
        ldf = pd.DataFrame({"g": ["a", "b", "c", "a"], "x": [1.0, 2, 3, 4]})
        rdf = pd.DataFrame({"g": ["c", "a", "d"], "y": [10.0, 20, 30]})
        out = ops.merge(
            h2o3_tpu.upload_file(ldf), h2o3_tpu.upload_file(rdf), by=["g"]
        ).to_pandas()
        ref = ldf.merge(rdf, on="g")
        assert len(out) == len(ref)
        assert sorted(out["y"]) == sorted(ref["y"])

    def test_join_is_pandas_free(self, monkeypatch):
        ldf, rdf = self._frames(512)
        left, right = h2o3_tpu.upload_file(ldf), h2o3_tpu.upload_file(rdf)

        def boom(*a, **k):
            raise AssertionError("pandas merge/sort called on device key path")

        monkeypatch.setattr(pd.DataFrame, "merge", boom)
        monkeypatch.setattr(pd.DataFrame, "sort_values", boom)
        out = ops.merge(left, right, by=["k"], all_x=True, all_y=True)
        srt = ops.sort(left, ["k", "k2"], ascending=[True, False])
        monkeypatch.undo()
        assert out.nrow > 0 and srt.nrow == left.nrow

    def test_sort_multi_key_desc_matches_pandas(self):
        ldf, _ = self._frames()
        left = h2o3_tpu.upload_file(ldf)
        srt = ops.sort(left, ["k2", "k"], ascending=[False, True])
        ref = ldf.sort_values(["k2", "k"], ascending=[False, True], kind="stable")
        np.testing.assert_allclose(
            srt.vec("x").to_numpy(), ref["x"].to_numpy(), atol=0
        )

    def test_sort_enum_and_desc_numeric(self):
        df = pd.DataFrame({
            "g": ["b", None, "a", "b", "a"], "v": [1.0, 2, np.nan, 4, 0]
        })
        fr = h2o3_tpu.upload_file(df)
        srt = ops.sort(fr, "g").to_pandas()
        # NA enum (-1 code) first, then label-order codes — former host behavior
        assert srt["v"].tolist()[0] == 2.0
        srtd = ops.sort(fr, "v", ascending=False)
        v = srtd.vec("v").to_numpy()
        assert np.isnan(v[-1]) and v[0] == 4.0  # NaN last even descending


class TestRapidsWave4:
    """match/%in%/which/na.omit/rank_within_groupby/pivot/stratified_split —
    the round-4 Rapids breadth additions (upstream ast/** classes)."""

    def test_match_and_in(self):
        df = pd.DataFrame({"g": ["a", "b", "c", "a", None], "v": [1.0, 2, 3, 2, 5]})
        fr = h2o3_tpu.upload_file(df)
        m = ops.match(fr.vec("g"), ["b", "a"]).to_numpy()
        assert m[0] == 2 and m[1] == 1 and m[3] == 2  # 1-based positions
        assert np.isnan(m[2]) and np.isnan(m[4])
        i = ops.is_in(fr.vec("v"), [2, 5]).to_numpy()
        assert i.tolist() == [0, 1, 0, 1, 1]

    def test_which(self):
        fr = h2o3_tpu.upload_file(pd.DataFrame({"v": [0.0, 1, 0, 2, np.nan, 3]}))
        w = ops.which(fr.vec("v")).to_pandas().iloc[:, 0].tolist()
        assert w == [1, 3, 5]

    def test_na_omit(self):
        df = pd.DataFrame({
            "a": [1.0, np.nan, 3, 4], "g": ["x", "y", None, "x"], "s": ["p", "q", "r", None]
        })
        fr = h2o3_tpu.upload_file(df)
        out = ops.na_omit(fr)
        assert out.nrow == 1
        assert out.vec("a").to_numpy()[0] == 1.0

    def test_rank_within_group_by(self):
        df = pd.DataFrame({
            "g": ["a", "a", "b", "b", "a", "b"],
            "v": [3.0, 1, 2, np.nan, 2, 1],
        })
        fr = h2o3_tpu.upload_file(df)
        out = ops.rank_within_group_by(fr, ["g"], ["v"], new_col_name="rk")
        rk = out.vec("rk").to_numpy()
        # group a: v=3->3, v=1->1, v=2->2 ; group b: v=2->2, NaN->NA, v=1->1
        assert rk[0] == 3 and rk[1] == 1 and rk[4] == 2
        assert rk[2] == 2 and rk[5] == 1 and np.isnan(rk[3])

    def test_pivot(self):
        df = pd.DataFrame({
            "id": [1.0, 1, 2, 2, 1],
            "k": ["x", "y", "x", "y", "x"],
            "v": [1.0, 2, 3, 4, 5],
        })
        fr = h2o3_tpu.upload_file(df)
        out = ops.pivot(fr, "id", "k", "v").to_pandas().sort_values("id")
        assert out[out.id == 1]["x"].iloc[0] == 3.0  # mean(1, 5)
        assert out[out.id == 2]["y"].iloc[0] == 4.0

    def test_stratified_split(self):
        rng = np.random.default_rng(0)
        y = np.where(rng.random(1000) < 0.1, "pos", "neg")
        fr = h2o3_tpu.upload_file(pd.DataFrame({"y": y}))
        sp = ops.stratified_split(fr.vec("y"), test_frac=0.25, seed=7)
        codes = sp.to_numpy()
        assert tuple(sp.domain) == ("train", "test")
        for cls in ("pos", "neg"):
            mask = y == cls
            frac = (codes[mask] == 1).mean()
            assert abs(frac - 0.25) < 0.02, cls

    def test_rapids_strings(self):
        from h2o3_tpu.api.rapids import rapids_eval
        from h2o3_tpu.cluster.registry import DKV

        df = pd.DataFrame({"g": ["a", "b", "a", "c"], "v": [1.0, 2, 3, 4]})
        fr = h2o3_tpu.upload_file(df)
        DKV.put("rw4", fr)
        out = rapids_eval(f"(tmp= rw4_w (which (%in% (cols rw4 'g') ['a'])))")
        w = DKV.get("rw4_w").to_pandas().iloc[:, 0].tolist()
        assert w == [0, 2]
        out2 = rapids_eval("(tmp= rw4_no (na.omit rw4))")
        assert DKV.get("rw4_no").nrow == 4


def test_relevel_and_signif():
    df = pd.DataFrame({"g": ["b", "c", "a", None, "b"], "v": [123456.0, 0.0012349, -9.87654e5, np.nan, 0.0]})
    fr = h2o3_tpu.upload_file(df)
    rv = ops.relevel(fr.vec("g"), "c")
    assert rv.levels()[0] == "c"
    # values preserved: decode both and compare labels
    dom_old = fr.vec("g").levels()
    dom_new = rv.levels()
    old = [dom_old[int(c)] if c >= 0 else None for c in fr.vec("g").to_numpy()]
    new = [dom_new[int(c)] if c >= 0 else None for c in rv.to_numpy()]
    assert old == new
    sg = ops.signif(fr.vec("v"), 3).to_numpy()
    np.testing.assert_allclose(sg[0], 123000.0)
    np.testing.assert_allclose(sg[1], 0.00123)
    np.testing.assert_allclose(sg[2], -988000.0)
    assert np.isnan(sg[3]) and sg[4] == 0.0


def test_cumulative_diff_fillna_rapids():
    """Round-4 Rapids breadth: cum*, difflag1, h2o.fillna, round with digits
    (upstream ast ops ASTCumu/ASTDiffLag1/ASTFillNA/ASTRound successors)."""
    from h2o3_tpu.api.rapids import rapids_eval
    from h2o3_tpu.cluster.registry import DKV

    x = np.array([2.0, np.nan, 3.0, 1.0, np.nan])
    fr = h2o3_tpu.upload_file(pd.DataFrame({"x": x}))
    DKV.put("rc4", fr)

    rapids_eval("(tmp= rc4_cs (cumsum (cols rc4 'x')))")
    cs = DKV.get("rc4_cs").vec(0).to_numpy()
    assert cs[0] == 2.0 and np.isnan(cs[1:]).all()  # NaN poisons the tail

    rapids_eval("(tmp= rc4_cm (cummax (cols rc4 'x')))")
    cm = DKV.get("rc4_cm").vec(0).to_numpy()
    assert cm[0] == 2.0

    rapids_eval("(tmp= rc4_d (difflag1 (cols rc4 'x')))")
    d = DKV.get("rc4_d").vec(0).to_numpy()
    assert np.isnan(d[0]) and np.isnan(d[1]) and np.isnan(d[2]) and d[3] == -2.0

    rapids_eval("(tmp= rc4_f (h2o.fillna rc4 'forward' 0 0))")
    f = DKV.get("rc4_f").vec(0).to_numpy()
    np.testing.assert_array_equal(f, [2.0, 2.0, 3.0, 1.0, 1.0])

    rapids_eval("(tmp= rc4_b (h2o.fillna rc4 'backward' 0 1))")
    b = DKV.get("rc4_b").vec(0).to_numpy()
    assert b[1] == 3.0 and np.isnan(b[4])  # maxlen=1: trailing NA unreachable

    rapids_eval("(tmp= rc4_r (round (cols rc4 'x') 0))")
    r = DKV.get("rc4_r").vec(0).to_numpy()
    assert r[0] == 2.0 and r[3] == 1.0


def test_fillna_ops_direct():
    v = Vec.from_numpy(np.array([np.nan, 1.0, np.nan, np.nan, 5.0]), "real")
    f = ops.fillna(v, "forward").to_numpy()
    np.testing.assert_array_equal(f, [np.nan, 1.0, 1.0, 1.0, 5.0])
    fb = ops.fillna(v, "backward").to_numpy()
    np.testing.assert_array_equal(fb, [1.0, 1.0, 5.0, 5.0, 5.0])
    fm = ops.fillna(v, "forward", maxlen=1).to_numpy()
    assert fm[2] == 1.0 and np.isnan(fm[3])
    with pytest.raises(ValueError):
        ops.fillna(v, "sideways")


def test_string_extras_and_moment_aggs():
    from h2o3_tpu.api.rapids import rapids_eval
    from h2o3_tpu.cluster.registry import DKV

    df = pd.DataFrame({"s": ["  ab  ", "aab", None, "bbb"],
                       "x": [1.0, 2.0, 3.0, 10.0]})
    fr = Frame.from_pandas(df, column_types={"s": "string"})
    DKV.put("rs4", fr)

    ls = ops.lstrip(fr.vec("s")).to_numpy()
    assert ls[0] == "ab  " and ls[2] is None
    rs = ops.rstrip(fr.vec("s")).to_numpy()
    assert rs[0] == "  ab"

    cm = ops.countmatches(fr.vec("s"), ["ab", "b"]).to_numpy()
    assert cm[1] == 2 and np.isnan(cm[2])  # "aab": one "ab" + one "b"
    assert cm[3] == 3  # "bbb": three "b"

    en = ops.entropy(fr.vec("s")).to_numpy()
    assert abs(en[3]) < 1e-12  # "bbb" has zero entropy
    assert en[1] > 0

    sk = rapids_eval("(skewness (cols rs4 'x'))")["scalar"]
    x = df["x"].to_numpy()
    m, s = x.mean(), x.std()
    assert abs(sk - ((x - m) ** 3).mean() / s**3) < 1e-9
    assert rapids_eval("(anyNA (cols rs4 'x'))")["scalar"] == 0.0
    assert rapids_eval("(any (> (cols rs4 'x') 5))")["scalar"] == 1.0
    assert rapids_eval("(all (> (cols rs4 'x') 5))")["scalar"] == 0.0
    assert rapids_eval("(is.numeric (cols rs4 'x'))")["scalar"] == 1.0
    assert rapids_eval("(is.character (cols rs4 's'))")["scalar"] == 1.0
    # new unop exposure: tanh on device matches numpy
    rapids_eval("(tmp= rs4_t (tanh (cols rs4 'x')))")
    np.testing.assert_allclose(DKV.get("rs4_t").vec(0).to_numpy(),
                               np.tanh(x), rtol=1e-6)


class TestInteraction:
    """h2o.interaction successor (hex/Interaction.java [UNVERIFIED])."""

    def _fr(self):
        import pandas as pd

        df = pd.DataFrame({
            "a": ["x", "x", "y", "y", "x", "y", "x", "x"],
            "b": ["u", "v", "u", "v", "u", "u", None, "u"],
            "n": [1.0] * 8,
        })
        return Frame.from_pandas(df)

    def test_two_way_levels_and_codes(self):
        fr = self._fr()
        out = ops.interaction(fr, ["a", "b"])
        assert out.names == ["a_b"]
        v = out.vec("a_b")
        labels = np.asarray(v.levels())
        codes = v.to_numpy().astype(int)
        got = [labels[c] if c >= 0 else None for c in codes]
        assert got == ["x_u", "x_v", "y_u", "y_v", "x_u", "y_u", None, "x_u"]

    def test_max_factors_catch_all_and_min_occurrence(self):
        fr = self._fr()
        out = ops.interaction(fr, ["a", "b"], max_factors=1)
        v = out.vec("a_b")
        labels = list(v.levels())
        assert labels == ["x_u", "other.values"]  # x_u is most frequent (3)
        codes = v.to_numpy().astype(int)
        assert (codes == 0).sum() == 3 and (codes == 1).sum() == 4
        out2 = ops.interaction(fr, ["a", "b"], min_occurrence=2)
        assert list(out2.vec("a_b").levels()) == ["x_u", "y_u", "other.values"]

    def test_pairwise_three_columns(self):
        import pandas as pd

        df = pd.DataFrame({
            "a": ["x", "y"] * 4, "b": ["u", "v"] * 4, "c": ["p", "q"] * 4,
        })
        fr = Frame.from_pandas(df)
        out = ops.interaction(fr, ["a", "b", "c"], pairwise=True)
        assert out.names == ["a_b", "a_c", "b_c"]

    def test_non_categorical_rejected(self):
        fr = self._fr()
        with pytest.raises(ValueError, match="not categorical"):
            ops.interaction(fr, ["a", "n"])

    def test_cardinality_overflow_rejected(self):
        """Domains whose cardinality product would overflow the int64
        combined-code space must error, not wrap silently to NA."""
        import pandas as pd

        fr = Frame.from_pandas(pd.DataFrame({"a": ["x"], "b": ["y"]}))

        class _Dom:  # claims a huge cardinality without materializing it
            def __init__(self, n): self.n = n
            def __len__(self): return self.n
            def __getitem__(self, i): return "L"

        class _FakeVec:
            def __init__(self, v): self._v = v; self.domain = _Dom(1 << 32)
            def is_categorical(self): return True
            def to_numpy(self): return self._v.to_numpy()

        class _FakeFrame:
            def __init__(self, fr): self._fr = fr
            def vec(self, n): return _FakeVec(self._fr.vec(n))

        with pytest.raises(ValueError, match="overflows"):
            ops.interaction(_FakeFrame(fr), ["a", "b"])


def test_weighted_quantile_matches_replication_and_unit_weights():
    """Weighted quantile: all-ones weights == unweighted; integer weights
    == row replication (the defining property)."""
    import pandas as pd

    rng = np.random.default_rng(5)
    x = rng.normal(size=200)
    w = rng.integers(1, 5, 200).astype(float)
    fr = Frame.from_pandas(pd.DataFrame({"x": x}))
    wv = Frame.from_pandas(pd.DataFrame({"w": w})).vec("w")
    probs = [0.1, 0.25, 0.5, 0.75, 0.9]

    unw = ops.quantile(fr.vec("x"), probs).vec("x").to_numpy()
    ones = Frame.from_pandas(pd.DataFrame({"w": np.ones(200)})).vec("w")
    unit = ops.quantile(fr.vec("x"), probs, weights=ones).vec("x").to_numpy()
    np.testing.assert_allclose(unit, unw, rtol=1e-12)

    rep = np.repeat(x, w.astype(int))
    frr = Frame.from_pandas(pd.DataFrame({"x": rep}))
    expect = ops.quantile(frr.vec("x"), probs).vec("x").to_numpy()
    got = ops.quantile(fr.vec("x"), probs, weights=wv).vec("x").to_numpy()
    np.testing.assert_allclose(got, expect, rtol=1e-9)
