"""Feature hashing (DataInfo ``hash_buckets``) — the sparse-chunk /
sparse-DMatrix successor for Criteo-class cardinalities (upstream
``water/fvec/CXIChunk.java`` sparse chunks, ``h2o-ext-xgboost`` sparse
DMatrix conversion [UNVERIFIED: reference mount empty]; SURVEY §2.1).

The TPU-first answer to 10^6-level categoricals is a FIXED-width hashed
indicator block: the design matrix stays dense and MXU-friendly but its
width is bounded by the bucket count, not the cardinality."""

import numpy as np
import pandas as pd
import pytest

import h2o3_tpu
from h2o3_tpu.models.datainfo import SKIP, DataInfo, _hash_codes


def _frame(levels, x=None):
    df = pd.DataFrame({"c": pd.Categorical(levels)})
    if x is not None:
        df["x"] = x
    return h2o3_tpu.upload_file(df)


def test_hash_block_bounded_and_stable():
    levels = [f"L{i}" for i in range(40)]
    fr = _frame(levels)
    di = DataInfo.fit(fr, ["c"], standardize=False, hash_buckets=8)
    assert di.ncols_expanded == 8
    assert di.columns[0].kind == "hash"
    assert di.coef_names() == [f"c.hash{i}" for i in range(8)]

    X, valid = di.transform(fr)
    Xn = np.asarray(X)[:40]
    # exactly one bucket lights per row
    assert (Xn.sum(axis=1) == 1.0).all()

    # a scoring frame with a DIFFERENT domain (subset, reordered, plus an
    # unseen level) must land identical levels in identical buckets — the
    # hash sees the level string, not the frame-local code
    fr2 = _frame(["L7", "L0", "ZZZ_unseen", "L39"])
    X2 = np.asarray(di.transform(fr2)[0])[:4]
    assert (X2[0] == Xn[7]).all()
    assert (X2[1] == Xn[0]).all()
    assert (X2[3] == Xn[39]).all()
    assert X2[2].sum() == 1.0  # unseen levels hash somewhere, not to NA


def test_hash_seeded_per_column():
    # same level strings in two columns should bucket independently
    df = pd.DataFrame(
        {"a": pd.Categorical([f"L{i}" for i in range(32)]),
         "b": pd.Categorical([f"L{i}" for i in range(32)])}
    )
    fr = h2o3_tpu.upload_file(df)
    di = DataInfo.fit(fr, ["a", "b"], standardize=False, hash_buckets=8)
    X = np.asarray(di.transform(fr)[0])[:32]
    assert not (X[:, :8] == X[:, 8:]).all()


def test_hash_below_cap_stays_exact():
    fr = _frame(["a", "b", "c"] * 5)
    di = DataInfo.fit(fr, ["c"], hash_buckets=8)
    # cardinality 3 <= 8 buckets: ordinary exact one-hot, no hashing
    assert di.columns[0].kind == "cat"


def test_hash_buckets_zero_or_negative_disables():
    fr = _frame([f"L{i}" for i in range(40)])
    for hb in (0, -3, None):
        di = DataInfo.fit(fr, ["c"], hash_buckets=hb)
        assert di.columns[0].kind == "cat"
        assert di.columns[0].width == 40


def test_hash_reference_level_dropped():
    import zlib

    fr = _frame([f"L{i}" for i in range(40)])
    di = DataInfo.fit(
        fr, ["c"], standardize=False, use_all_factor_levels=False,
        hash_buckets=8,
    )
    # bucket 0 is the reference level: 7 columns, so the block cannot be
    # collinear with an intercept (unregularized Gram stays full-rank)
    assert di.ncols_expanded == 7
    X = np.asarray(di.transform(fr)[0])[:40]
    b0 = [
        i for i in range(40)
        if zlib.crc32(b"c\x00" + f"L{i}".encode()) % 8 == 0
    ]
    assert b0, "expected some levels in bucket 0 for this domain"
    assert (X[b0].sum(axis=1) == 0.0).all()
    rest = [i for i in range(40) if i not in b0]
    assert (X[rest].sum(axis=1) == 1.0).all()


def test_hash_na_handling():
    levels = pd.Categorical(
        [f"L{i}" for i in range(20)] + [None], categories=[f"L{i}" for i in range(20)]
    )
    fr = _frame(levels)
    di = DataInfo.fit(fr, ["c"], standardize=False, hash_buckets=4)
    X, valid = di.transform(fr)
    assert np.asarray(X)[20].sum() == 0.0  # NA row: all-zero block

    di_skip = DataInfo.fit(
        fr, ["c"], standardize=False, hash_buckets=4, missing_handling=SKIP
    )
    _, valid = di_skip.transform(fr)
    v = np.asarray(valid)
    assert v[20] == 0.0 and v[:20].all()


def test_hash_codes_match_crc32():
    import zlib

    fr = _frame([f"L{i}" for i in range(10)])
    buckets = np.asarray(_hash_codes(fr.vec("c"), "c", 4))[:10]
    want = [zlib.crc32(b"c\x00" + f"L{i}".encode()) % 4 for i in range(10)]
    assert buckets.tolist() == want


def test_glm_trains_on_hashed_column():
    rng = np.random.default_rng(3)
    n, card, hot = 4000, 500, 10
    # hot levels carry the signal; the tail is near-uniform noise
    is_hot = rng.random(n) < 0.8
    code = np.where(is_hot, rng.integers(0, hot, n), rng.integers(hot, card, n))
    x = rng.normal(size=n)
    eta = 1.0 * x + np.where(is_hot & (code % 2 == 0), 1.2, -0.4)
    y = rng.random(n) < 1 / (1 + np.exp(-eta))
    df = pd.DataFrame(
        {
            "c": pd.Categorical.from_codes(
                code, categories=[f"v{i}" for i in range(card)]
            ),
            "x": x,
            "y": pd.Categorical(np.where(y, "yes", "no")),
        }
    )
    fr = h2o3_tpu.upload_file(df)

    from h2o3_tpu.models.glm import GLM

    m = GLM(family="binomial", lambda_=1e-4, hash_buckets=64,
            max_iterations=20).train(y="y", training_frame=fr)
    assert np.isfinite(m.training_metrics.logloss)
    assert m.training_metrics.auc > 0.62  # hashed hot levels carry signal
    # GLM fits use_all_factor_levels=False: bucket 0 is the reference level
    # (a full block would be collinear with the intercept), + x + intercept
    assert len(m.coef) == (64 - 1) + 2
    # scoring a frame with a sub-domain must work without remap errors
    preds = m.predict(fr).to_pandas()
    assert len(preds) == n
