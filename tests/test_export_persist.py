"""MOJO-export parity + binary save/load tests — the MOJO/POJO parity
regression net of upstream (``pyunit_*mojo*``; SURVEY.md §4): train → export
→ score offline with the numpy genmodel → assert row-wise equality with the
in-cluster predictions."""

import numpy as np
import pandas as pd
import pytest

import h2o3_tpu
import h2o3_tpu.models.export  # noqa: F401 — attaches Model.download_mojo
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.genmodel import MojoModel
from h2o3_tpu.models import DRF, GBM, GLM, DeepLearning, KMeans


def _df(n=1500, seed=0, classification=True):
    rng = np.random.default_rng(seed)
    df = pd.DataFrame({
        "num1": rng.normal(size=n),
        "num2": rng.random(n) * 10,
        "cat1": rng.choice(["a", "b", "c"], n),
    })
    df.loc[rng.choice(n, 50, replace=False), "num1"] = np.nan
    eta = df["num1"].fillna(0) + (df["cat1"] == "a") * 2 - 0.3 * df["num2"]
    if classification:
        df["y"] = np.where(eta + rng.normal(size=n) > 0, "pos", "neg")
    else:
        df["y"] = eta + 0.1 * rng.normal(size=n)
    return df


def _parity(model, df, tmp_path, prob_col, tol=1e-5):
    fr = Frame.from_pandas(df)
    path = str(tmp_path / f"{model.algo}.zip")
    model.download_mojo(path)
    mojo = MojoModel.load(path)

    incluster = model.predict(fr)
    offline = mojo.predict(df.drop(columns=["y"]))
    if prob_col is not None:
        a = incluster.vec(prob_col).to_numpy()
        b = offline[prob_col]
    else:
        a = incluster.vec("predict").to_numpy()
        b = offline["predict"]
    np.testing.assert_allclose(
        np.asarray(a, np.float64), np.asarray(b, np.float64), atol=tol, rtol=0
    )
    return mojo


def test_bin_code_equality_device_vs_mojo(tmp_path):
    """Device prebinning and the offline scorer must produce IDENTICAL bin
    codes (atol=0) — the root cause of two rounds of parity failures was an
    f32/f64 searchsorted mismatch between the two paths."""
    df = _df(seed=11, classification=False)
    fr = Frame.from_pandas(df)
    m = GBM(ntrees=2, max_depth=3, seed=3, distribution="gaussian").train(
        y="y", training_frame=fr
    )
    path = str(tmp_path / "bins.zip")
    m.download_mojo(path)
    mojo = MojoModel.load(path)

    from h2o3_tpu.models.tree.binning import bin_frame

    dev = np.asarray(bin_frame(m.output["bin_spec"], fr))[: fr.nrow]
    off = mojo._bin_features(mojo._rows_to_table(df.drop(columns=["y"])))
    np.testing.assert_array_equal(dev.astype(np.int64), off)


def test_gbm_mojo_parity(tmp_path):
    df = _df()
    fr = Frame.from_pandas(df)
    m = GBM(ntrees=10, max_depth=4, seed=3).train(y="y", training_frame=fr)
    _parity(m, df, tmp_path, "pos")


def test_gbm_regression_mojo_parity(tmp_path):
    df = _df(classification=False)
    fr = Frame.from_pandas(df)
    m = GBM(ntrees=10, max_depth=3, seed=3, distribution="gaussian").train(
        y="y", training_frame=fr
    )
    _parity(m, df, tmp_path, None, tol=1e-4)


def test_drf_mojo_parity(tmp_path):
    df = _df(seed=4)
    fr = Frame.from_pandas(df)
    m = DRF(ntrees=10, max_depth=6, seed=3).train(y="y", training_frame=fr)
    _parity(m, df, tmp_path, "pos")


def test_glm_mojo_parity(tmp_path):
    df = _df(seed=5)
    fr = Frame.from_pandas(df)
    m = GLM(family="binomial", lambda_=1e-4).train(y="y", training_frame=fr)
    _parity(m, df, tmp_path, "pos")


def test_glm_hashed_mojo_parity(tmp_path):
    """Export→score round trip for a feature-HASHED GLM: the artifact ships
    hash_buckets (no domain — the point of hashing is that the train domain
    may be Criteo-sized) and the offline scorer re-derives each bucket from
    the raw level string via crc32(col \\0 level) % hash_buckets, including
    the bucket-0 reference-level drop (GLM fits use_all_factor_levels=False).
    Scoring rows include levels NEVER seen in training — hashing must bucket
    them identically on both paths, not NA them."""
    rng = np.random.default_rng(9)
    n, card = 2000, 200
    code = rng.integers(0, card, n)
    df = pd.DataFrame({
        "c": pd.Categorical.from_codes(
            code, categories=[f"v{i}" for i in range(card)]
        ),
        "num1": rng.normal(size=n),
    })
    eta = df["num1"] + np.where(code % 2 == 0, 1.0, -1.0)
    df["y"] = np.where(eta + rng.normal(size=n) > 0, "pos", "neg")
    fr = Frame.from_pandas(df)
    m = GLM(family="binomial", lambda_=1e-4, hash_buckets=16,
            max_iterations=20).train(y="y", training_frame=fr)
    assert m.output["datainfo"].hash_buckets == 16  # hashing actually on
    mojo = _parity(m, df, tmp_path, "pos")
    assert mojo.meta["datainfo"]["hash_buckets"] == 16
    # unseen level: identical buckets (hence probabilities) on both paths
    df2 = df.head(8).copy()
    df2["c"] = [f"unseen{i}" for i in range(8)]
    fr2 = Frame.from_pandas(df2)
    a = np.asarray(m.predict(fr2).vec("pos").to_numpy(), np.float64)
    b = np.asarray(mojo.predict(df2.drop(columns=["y"]))["pos"], np.float64)
    np.testing.assert_allclose(a, b, atol=1e-5, rtol=0)


def test_deeplearning_mojo_parity(tmp_path):
    df = _df(seed=6)
    fr = Frame.from_pandas(df)
    m = DeepLearning(hidden=[16], epochs=3, seed=3).train(y="y", training_frame=fr)
    _parity(m, df, tmp_path, "pos", tol=1e-3)


def test_kmeans_mojo_clusters(tmp_path):
    df = _df(seed=7).drop(columns=["y"])
    fr = Frame.from_pandas(df)
    m = KMeans(k=3, seed=3).train(training_frame=fr)
    path = str(tmp_path / "kmeans.zip")
    m.download_mojo(path)
    mojo = MojoModel.load(path)
    offline = mojo.predict(df)["cluster"]
    incluster = m.predict(fr).vec(0).to_numpy()
    assert (offline == incluster).mean() > 0.99


def test_single_row_easypredict(tmp_path):
    df = _df(seed=8)
    fr = Frame.from_pandas(df)
    m = GBM(ntrees=5, max_depth=3, seed=3).train(y="y", training_frame=fr)
    path = str(tmp_path / "m.zip")
    m.download_mojo(path)
    mojo = MojoModel.load(path)
    row = {"num1": 0.5, "num2": 3.0, "cat1": "a"}
    out = mojo.predict(row)
    assert out["predict"][0] in ("pos", "neg")
    assert out["pos"][0] + out["neg"][0] == pytest.approx(1.0, abs=1e-6)
    # unseen categorical level routes like NA, not a crash
    out2 = mojo.predict({"num1": 0.5, "num2": 3.0, "cat1": "ZZZ"})
    assert out2["pos"][0] >= 0.0


@pytest.mark.parametrize("builder,kw", [
    (GBM, dict(ntrees=5, max_depth=3, seed=2)),
    (GLM, dict(family="binomial", lambda_=1e-4)),
    (DeepLearning, dict(hidden=[8], epochs=2, seed=2)),
])
def test_binary_save_load_roundtrip(tmp_path, builder, kw):
    df = _df(seed=9)
    fr = Frame.from_pandas(df)
    m = builder(**kw).train(y="y", training_frame=fr)
    before = m.predict(fr).vec("pos").to_numpy()
    p = h2o3_tpu.save_model(m, str(tmp_path) + "/")
    h2o3_tpu.remove(m.key)
    m2 = h2o3_tpu.load_model(p)
    assert m2.key == m.key
    assert h2o3_tpu.get_model(m.key) is m2
    after = m2.predict(fr).vec("pos").to_numpy()
    np.testing.assert_allclose(before, after, atol=1e-6)


def test_generic_model_reimport_scores_live(tmp_path):
    """hex.generic successor: a tmojo zip re-imported as a live model
    predicts identically to the original in-cluster model."""
    df = _df(seed=14)
    fr = Frame.from_pandas(df)
    m = GBM(ntrees=5, max_depth=3, seed=3).train(y="y", training_frame=fr)
    path = str(tmp_path / "g.zip")
    m.download_mojo(path)

    g = h2o3_tpu.import_mojo(path, model_id="generic_test")
    assert h2o3_tpu.get_model("generic_test") is g
    pa, pb = m.predict(fr), g.predict(fr)
    np.testing.assert_allclose(
        pa.vec("pos").to_numpy(), pb.vec("pos").to_numpy(), atol=1e-5
    )
    la = pa.vec("predict").to_numpy()
    lb = pb.vec("predict").to_numpy()
    assert (la == lb).mean() > 0.999  # labels use the carried F1 threshold
    assert g.output["source_algo"] == "gbm"


def test_pojo_standalone_scoring(tmp_path):
    """POJO-successor: a single generated .py scores with numpy only, in a
    bare subprocess with no h2o3_tpu/jax on the path."""
    import os
    import subprocess
    import sys

    from h2o3_tpu.models import GBM
    from h2o3_tpu.models.export import export_pojo

    rng = np.random.default_rng(0)
    n = 2000
    X = rng.normal(size=(n, 5)).astype(np.float32)
    df = pd.DataFrame(X, columns=[f"f{i}" for i in range(5)])
    df["y"] = np.where(X[:, 0] * 2 + X[:, 1] ** 2 > 1, "Y", "N")
    fr = Frame.from_pandas(df)
    m = GBM(ntrees=10, max_depth=4, seed=1).train(y="y", training_frame=fr)
    pojo = os.path.join(str(tmp_path), "model.py")
    export_pojo(m, pojo)
    csv = os.path.join(str(tmp_path), "rows.csv")
    df.drop(columns="y").to_csv(csv, index=False)
    r = subprocess.run(
        [sys.executable, pojo, csv], capture_output=True, text=True,
        env={"PATH": os.environ["PATH"]}, timeout=300,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    import io as _io

    out = pd.read_csv(_io.StringIO(r.stdout))
    ours = m.predict(fr).vec("Y").to_numpy()
    np.testing.assert_allclose(out["Y"].to_numpy(), ours, atol=1e-5)


def test_ordinal_glm_mojo_parity(tmp_path):
    from h2o3_tpu.genmodel import MojoModel
    from h2o3_tpu.models import GLM
    from h2o3_tpu.models.export import export_mojo

    rng = np.random.default_rng(6)
    n = 2000
    x0 = rng.normal(1.0, 2.0, n)
    x1 = rng.normal(size=n)
    yo = np.digitize(0.9 * x0 - x1 + rng.logistic(size=n), [0.0, 2.0])
    df = pd.DataFrame({"x0": x0, "x1": x1, "y": yo.astype(str)})
    fr = Frame.from_pandas(df, column_types={"y": "enum"})
    m = GLM(family="ordinal").train(y="y", training_frame=fr)
    p = str(tmp_path / "ordinal.zip")
    export_mojo(m, p)
    mojo = MojoModel.load(p)
    offline = mojo.score_raw(mojo._rows_to_table(df.drop(columns="y")))
    live = m._predict_raw(fr)
    np.testing.assert_allclose(offline, live, atol=1e-5)
    assert offline.shape == (n, 3)


def test_mojo_leaf_node_assignment_parity(tmp_path):
    """Offline scorer's leaf assignment == in-cluster
    predict_leaf_node_assignment, both types."""
    df = _df(500, seed=6)
    fr = Frame.from_pandas(df)
    m = GBM(ntrees=3, max_depth=3, seed=8).train(y="y", training_frame=fr)
    path = str(tmp_path / "leafmojo.zip")
    m.download_mojo(path)
    mojo = MojoModel.load(path)
    table = {c: df[c].to_numpy() for c in df.columns if c != "y"}

    ids_cluster = m.predict_leaf_node_assignment(fr, type="Node_ID")
    paths_cluster = m.predict_leaf_node_assignment(fr, type="Path")
    ids_mojo = mojo.leaf_node_assignment(table, type="Node_ID")
    paths_mojo = mojo.leaf_node_assignment(table, type="Path")
    for c in ids_cluster.names:
        np.testing.assert_array_equal(
            ids_cluster.vec(c).to_numpy().astype(int), ids_mojo[c])
        pv = paths_cluster.vec(c)
        s = np.asarray(pv.levels())[pv.to_numpy().astype(int)]
        np.testing.assert_array_equal(s.astype(str), paths_mojo[c].astype(str))
