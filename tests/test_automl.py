"""AutoML tests — modeled on upstream ``h2o-py/tests/testdir_algos/automl``
pyunit scenarios [UNVERIFIED upstream path, SURVEY.md §4]."""

import numpy as np
import pytest
import pandas as pd

from h2o3_tpu.automl import AutoML
from h2o3_tpu.frame.frame import Frame


def _binary_frame(n=1500, seed=2):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 4))
    eta = X[:, 0] * 2 - X[:, 1] + 0.5 * X[:, 2]
    y = (rng.random(n) < 1 / (1 + np.exp(-eta))).astype(int)
    df = pd.DataFrame(X, columns=list("abcd"))
    df["y"] = np.where(y == 1, "yes", "no")
    return Frame.from_pandas(df)


@pytest.mark.slow
def test_automl_builds_leaderboard_with_ensembles():
    fr = _binary_frame()
    aml = AutoML(
        max_models=4,
        nfolds=3,
        seed=7,
        # generous: the scenario asserts ensembles + leaderboard ordering,
        # not wall-clock (a loaded 2-core box measured ~19 min for one
        # depth-20 preset; the per-model remaining-budget cap keeps real
        # budgets honest and has its own test below)
        max_runtime_secs=3000.0,
        exclude_algos=["DeepLearning"],
    )
    leader = aml.train(y="y", training_frame=fr)
    lb = aml.leaderboard
    assert leader is not None
    assert len(lb.models) >= 4
    # ensembles run even after max_models is hit
    algos = {m.algo for m in lb.models}
    assert "stackedensemble" in algos
    # leaderboard is sorted on AUC descending
    aucs = [r["auc"] for r in lb.as_table()]
    assert aucs == sorted(aucs, reverse=True)
    assert aucs[0] > 0.75
    # every non-SE model was cross-validated for stacking
    assert all(
        m.cv_predictions is not None for m in lb.models if m.algo != "stackedensemble"
    )
    # events log recorded the plan execution
    stages = {e["stage"] for e in aml.event_log}
    assert {"init", "model", "done"} <= stages


@pytest.mark.slow
def test_automl_regression_and_exclusions():
    rng = np.random.default_rng(4)
    X = rng.random((1200, 3))
    df = pd.DataFrame(X, columns=list("abc"))
    df["y"] = 2 * X[:, 0] + np.sin(5 * X[:, 1]) + 0.05 * rng.normal(size=1200)
    fr = Frame.from_pandas(df)
    aml = AutoML(
        max_models=3,
        nfolds=3,
        seed=7,
        include_algos=["GBM", "GLM"],
        max_runtime_secs=400.0,
    )
    aml.train(y="y", training_frame=fr)
    algos = {m.algo for m in aml.leaderboard.models}
    assert algos <= {"gbm", "glm", "stackedensemble"}
    assert "drf" not in algos
    # regression leaderboard sorted ascending on deviance
    vals = [aml.leaderboard._metric_of(m) for m in aml.leaderboard.models]
    assert vals == sorted(vals)


def test_automl_runs_xgboost_steps_first():
    """Upstream AutoML's plan opens with its XGBoost defaults; ours mirrors
    that — with max_models=2 the leaderboard's trained base models are the
    first two plan steps, i.e. algo == 'xgboost'; excluding XGBoost drops
    them."""
    fr = _binary_frame(n=800, seed=5)
    aml = AutoML(max_models=2, nfolds=0, seed=5,
                 exclude_algos=["DeepLearning", "StackedEnsemble"])
    aml.train(y="y", training_frame=fr)
    algos = [m.algo for m in aml.leaderboard.models]
    assert algos and all(a == "xgboost" for a in algos), algos

    aml2 = AutoML(max_models=2, nfolds=0, seed=6,
                  exclude_algos=["XGBoost", "DeepLearning", "StackedEnsemble"])
    aml2.train(y="y", training_frame=fr)
    algos2 = {m.algo for m in aml2.leaderboard.models}
    assert "xgboost" not in algos2 and algos2, algos2


def test_automl_budget_caps_each_model():
    """A single step must not blow the whole wall-clock budget: every
    builder is launched with max_runtime_secs <= the REMAINING AutoML
    budget (the upstream time-allocation contract; a depth-20 preset once
    overshot a 600 s budget to 1127 s)."""
    fr = _binary_frame(n=800, seed=5)
    aml = AutoML(max_models=3, nfolds=0, seed=3, max_runtime_secs=40.0,
                 include_algos=["GBM", "GLM"])
    launched: list[float] = []
    orig = AutoML._builder

    def spy(self, algo, params):
        launched.append(params.get("max_runtime_secs"))
        return orig(self, algo, params)

    AutoML._builder = spy
    try:
        aml.train(y="y", training_frame=fr)
    finally:
        AutoML._builder = orig
    assert launched, "no models launched"
    assert all(cap is not None and cap <= 40.0 for cap in launched), launched
    # caps shrink as budget is consumed
    assert launched[-1] <= launched[0]


def test_get_leaderboard_extra_columns():
    from h2o3_tpu.automl import get_leaderboard

    fr = _binary_frame(n=600, seed=9)
    aml = AutoML(max_models=2, nfolds=0, seed=1, max_runtime_secs=120.0,
                 include_algos=["GLM", "GBM"])
    aml.train(y="y", training_frame=fr)
    rows = get_leaderboard(aml, extra_columns="ALL")
    assert rows and all("training_time_ms" in r for r in rows)
    assert all(r["training_time_ms"] >= 0 for r in rows)
    plain = get_leaderboard(aml)
    assert all("training_time_ms" not in r for r in plain)
