"""Chaos suite — deterministic fault injection (utils/faults.py) proving the
crash-durability layer: atomic persist publish, retry-with-backoff, the
degraded fail-stop latch, and kill→restart→resume reproducing uninterrupted
runs (the ISSUE-2 acceptance pins). Everything here is fast and runs in
tier-1 (``pytest -m chaos`` selects just this layer)."""

import glob
import json
import os
import time

import numpy as np
import pandas as pd
import pytest

import h2o3_tpu
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models import GBM, GLM, DeepLearning
from h2o3_tpu.persist import (
    PersistBackend,
    PersistFS,
    load_model,
    register_backend,
    resolve_model_path,
    save_model,
    write_bytes,
)
from h2o3_tpu.utils import faults

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _fast_backoff(monkeypatch):
    monkeypatch.setenv("H2O3_TPU_PERSIST_BACKOFF", "0.01")
    monkeypatch.setenv("H2O3_TPU_PERSIST_RETRIES", "4")
    yield
    faults.reset()


def _df(n=1500, seed=3):
    rng = np.random.default_rng(seed)
    df = pd.DataFrame({
        "a": rng.normal(size=n),
        "b": rng.normal(size=n),
        "c": rng.choice(["x", "y", "z"], n),
    })
    eta = df["a"] * 1.5 + (df["c"] == "x") * 2 - df["b"]
    df["y"] = np.where(eta + rng.normal(size=n) > 0, "p", "n")
    return df


# ---------------------------------------------------------------------------
# durable persist: atomic publish + retry/backoff


def test_fs_crash_mid_write_leaves_no_partial_file(tmp_path):
    tgt = str(tmp_path / "model.bin")
    fs = PersistFS()
    with pytest.raises(RuntimeError):
        with fs.open_write(tgt) as f:
            f.write(b"partial bytes")
            raise RuntimeError("simulated crash mid-write")
    assert not os.path.exists(tgt)
    assert os.listdir(tmp_path) == []  # temp cleaned up too
    # and a clean write does publish
    with fs.open_write(tgt) as f:
        f.write(b"whole")
    with open(tgt, "rb") as f:
        assert f.read() == b"whole"


def test_transient_write_failure_retried_within_budget(tmp_path):
    tgt = str(tmp_path / "retry.bin")
    with faults.inject(fail={"persist_write": 2}):
        write_bytes(b"payload", tgt)
        attempts = faults.counts()["persist_write"]
    assert attempts == 3  # 2 injected failures + the success
    with open(tgt, "rb") as f:
        assert f.read() == b"payload"


def test_retry_budget_exhausted_surfaces_error_and_no_partial(tmp_path, monkeypatch):
    monkeypatch.setenv("H2O3_TPU_PERSIST_RETRIES", "2")
    tgt = str(tmp_path / "never.bin")
    with faults.inject(fail={"persist_write": 99}):
        with pytest.raises(faults.InjectedIOError):
            write_bytes(b"payload", tgt)
        assert faults.counts()["persist_write"] == 3  # 1 + 2 retries
    assert not os.path.exists(tgt)


def test_deterministic_error_fails_fast(tmp_path):
    blocker = tmp_path / "iam_a_file"
    blocker.write_bytes(b"x")
    t0 = time.time()
    with faults.inject(fail={"persist_write": 0}):  # armed → counts attempts
        with pytest.raises((NotADirectoryError, FileExistsError)):
            write_bytes(b"x", str(blocker / "child.bin"))
        assert faults.counts().get("persist_write", 0) == 1  # no retries
    assert time.time() - t0 < 1.0  # no backoff sleeps burned


def test_transient_read_failure_retried(tmp_path):
    df = _df(200, seed=9)
    fr = Frame.from_pandas(df)
    m = GBM(ntrees=2, max_depth=2, seed=1).train(y="y", training_frame=fr)
    path = save_model(m, str(tmp_path))
    h2o3_tpu.remove(m.key)
    with faults.inject(fail={"persist_read": 2}):
        m2 = load_model(path)
        assert faults.counts()["persist_read"] == 3
    assert m2.output["ntrees_actual"] == 2


# ---------------------------------------------------------------------------
# persist satellites: scheme-correct probes, corrupt files, nested qualnames


def test_resolve_model_path_uses_backend_probes_not_local_fs(tmp_path):
    class Mem(PersistBackend):
        store = {"mem0://bucket/models/m1": b"x"}

        def exists(self, p):
            return p in self.store

    register_backend("mem0", Mem())
    # collision detected on the BACKEND's namespace (local fs knows nothing)
    with pytest.raises(FileExistsError):
        resolve_model_path("mem0://bucket/models/m1", "m1", force=False)
    # trailing slash means directory-append, object-store style
    _, p = resolve_model_path("mem0://bucket/models/", "m2", force=False)
    assert p == "mem0://bucket/models/m2"


def test_load_model_corrupt_file_names_path(tmp_path):
    from h2o3_tpu.persist import FORMAT_MAGIC

    bad = tmp_path / "truncated.bin"
    bad.write_bytes(FORMAT_MAGIC + b"\x80\x05not really a pickle")
    with pytest.raises(ValueError, match="corrupt or truncated") as ei:
        load_model(str(bad))
    assert "truncated.bin" in str(ei.value)  # the error names the path
    notours = tmp_path / "foreign.bin"
    notours.write_bytes(b"GARBAGE!")
    with pytest.raises(ValueError, match="not an h2o3_tpu model file"):
        load_model(str(notours))


class _Outer:
    class InnerModel(h2o3_tpu.models.model_base.Model):
        algo = "innertest"

        def __init__(self):  # pragma: no cover - never constructed normally
            pass


def test_load_model_resolves_nested_class_qualnames(tmp_path):
    import pickle

    from h2o3_tpu.persist import FORMAT_MAGIC

    payload = {
        "cls_module": __name__,
        "cls_name": "_Outer.InnerModel",
        "algo": "innertest",
        "state": {"key": "inner_1", "output": {}, "params": None},
    }
    path = tmp_path / "nested.bin"
    path.write_bytes(FORMAT_MAGIC + pickle.dumps(payload))
    m = load_model(str(path))
    assert type(m) is _Outer.InnerModel
    assert m.key == "inner_1"
    h2o3_tpu.remove("inner_1")


# ---------------------------------------------------------------------------
# Job satellites


def test_job_join_timeout_raises():
    from h2o3_tpu.cluster.job import Job

    release = []

    def work(j):
        while not release:
            time.sleep(0.01)
        return "done"

    job = Job(work, "sleepy").start()
    with pytest.raises(TimeoutError, match="still running"):
        job.join(timeout=0.05)
    release.append(1)
    assert job.join(timeout=5.0) == "done"


# ---------------------------------------------------------------------------
# degraded latch (fail-stop) — the _maybe_mark_dead_member contract


@pytest.fixture()
def _clean_latch():
    from h2o3_tpu.cluster import cloud

    cloud.clear_degraded()
    yield
    cloud.clear_degraded()


def test_synthetic_death_signature_latches_degraded(_clean_latch):
    from h2o3_tpu.cluster import cloud, spmd

    # a deterministic command error must NOT latch (healthy cloud stays up)
    spmd._maybe_mark_dead_member(ValueError("bad parse path: connection"))
    assert cloud.degraded_reason() is None
    # a death-signature XlaRuntimeError latches, one way
    spmd._maybe_mark_dead_member(faults.make_death_error())
    assert cloud.degraded_reason() is not None
    assert cloud.cluster_info()["cloud_healthy"] is False
    # /3/Cloud surfaces it
    from h2o3_tpu.api.server import Endpoints

    resp = Endpoints().cloud({})
    assert resp["cloud_healthy"] is False
    assert "degraded" in resp


def test_degraded_cloud_failstops_queued_spmd_run(_clean_latch, monkeypatch):
    from h2o3_tpu.cluster import cloud, spmd

    cloud.mark_degraded("test: member died")
    monkeypatch.setattr(spmd, "_IS_MULTI", True)
    monkeypatch.setattr(spmd, "is_coordinator", lambda: True)
    with pytest.raises(RuntimeError, match="fail-stop"):
        spmd.run("remove", key="whatever")


def test_injected_death_in_spmd_run_latches_via_real_path(_clean_latch, monkeypatch):
    from h2o3_tpu.cluster import cloud, spmd

    monkeypatch.setattr(spmd, "_IS_MULTI", True)
    monkeypatch.setattr(spmd, "is_coordinator", lambda: True)
    with faults.inject(death={"spmd_run"}):
        with pytest.raises(faults.XlaRuntimeError):
            spmd.run("remove", key="whatever")
    assert cloud.degraded_reason() is not None
    # the latch now fail-stops the NEXT command before it broadcasts
    with pytest.raises(RuntimeError, match="restart the cloud"):
        spmd.run("remove", key="whatever")


# ---------------------------------------------------------------------------
# kill → restart → resume (the acceptance pins)


def _latest_snapshot(ckdir: str, prefix: str) -> str:
    files = glob.glob(os.path.join(ckdir, f"{prefix}_ckpt_*"))
    assert files, f"no {prefix} snapshot written to {ckdir}"
    return max(files, key=os.path.getmtime)


def test_gbm_kill_and_resume_matches_uninterrupted(tmp_path):
    fr = Frame.from_pandas(_df())
    kw = dict(max_depth=3, seed=11, learn_rate=0.2, score_tree_interval=2)

    full = GBM(ntrees=8, **kw).train(y="y", training_frame=fr)

    ckdir = str(tmp_path / "gbm_ck")
    with faults.inject(abort={"gbm": 4}):
        with pytest.raises(faults.TrainAbort):
            GBM(ntrees=8, export_checkpoints_dir=ckdir, **kw).train(
                y="y", training_frame=fr
            )
    prior = load_model(_latest_snapshot(ckdir, "gbm"))
    assert prior.output["ntrees_actual"] == 4  # snapshot at the armed interval
    resumed = GBM(ntrees=8, checkpoint=prior.key, **kw).train(
        y="y", training_frame=fr
    )
    assert resumed.output["ntrees_actual"] == 8
    np.testing.assert_allclose(
        resumed.training_metrics.logloss, full.training_metrics.logloss, atol=1e-6
    )
    pa = full.predict(fr).vec("p").to_numpy()
    pb = resumed.predict(fr).vec("p").to_numpy()
    np.testing.assert_allclose(pa, pb, atol=1e-5)


def test_glm_irls_kill_and_resume_matches_uninterrupted(tmp_path):
    fr = Frame.from_pandas(_df(seed=5))
    kw = dict(family="binomial", max_iterations=25, seed=1)

    full = GLM(**kw).train(y="y", training_frame=fr)

    ckdir = str(tmp_path / "glm_ck")
    with faults.inject(abort={"glm": 3}):
        with pytest.raises(faults.TrainAbort):
            GLM(export_checkpoints_dir=ckdir, **kw).train(y="y", training_frame=fr)
    snap = _latest_snapshot(ckdir, "glm")
    # resume straight from the FILE path — the post-restart runbook shape
    resumed = GLM(checkpoint=snap, **kw).train(y="y", training_frame=fr)
    # the restored loop position replays the identical IRLS trajectory
    np.testing.assert_array_equal(
        np.asarray(resumed.output["beta_std"]), np.asarray(full.output["beta_std"])
    )
    np.testing.assert_allclose(
        resumed.training_metrics.logloss, full.training_metrics.logloss, atol=1e-6
    )


def test_deeplearning_kill_and_resume_matches_uninterrupted(tmp_path):
    fr = Frame.from_pandas(_df(seed=9))
    kw = dict(hidden=[8], seed=4, mini_batch_size=64)

    full = DeepLearning(epochs=4, **kw).train(y="y", training_frame=fr)

    ckdir = str(tmp_path / "dl_ck")
    with faults.inject(abort={"deeplearning": 2}):
        with pytest.raises(faults.TrainAbort):
            DeepLearning(epochs=4, export_checkpoints_dir=ckdir, **kw).train(
                y="y", training_frame=fr
            )
    prior = load_model(_latest_snapshot(ckdir, "deeplearning"))
    assert prior.output["epochs_trained"] == 2
    resumed = DeepLearning(epochs=4, checkpoint=prior.key, **kw).train(
        y="y", training_frame=fr
    )
    assert resumed.output["epochs_trained"] == 4
    pa = full.predict(fr).vec("p").to_numpy()
    pb = resumed.predict(fr).vec("p").to_numpy()
    np.testing.assert_allclose(pa, pb, atol=1e-5)


def test_automl_kill_and_resume_matches_uninterrupted(tmp_path, monkeypatch):
    import h2o3_tpu.automl.automl as A

    fr = Frame.from_pandas(_df(600, seed=7))
    tiny = [
        A._Step("s_gbm1", "model", "gbm",
                dict(ntrees=6, max_depth=3, score_tree_interval=3)),
        A._Step("s_glm", "model", "glm", dict()),
        A._Step("s_gbm2", "model", "gbm",
                dict(ntrees=6, max_depth=2, score_tree_interval=3)),
    ]
    monkeypatch.setattr(
        A, "_default_plan",
        lambda: [A._Step(s.name, s.kind, s.algo, dict(s.params),
                         dict(s.hyper), s.weight) for s in tiny],
    )
    spec = dict(max_models=3, nfolds=2, seed=11, max_runtime_secs=0.0,
                project_name="chaosml")

    def lb_table(aml):
        return sorted(
            (r["model_id"].split("_")[0], round(float(r["auc"]), 10))
            for r in aml.leaderboard.as_table()
        )

    full = A.AutoML(**spec)
    full.train(y="y", training_frame=fr)

    ckdir = str(tmp_path / "aml_ck")
    with faults.inject(abort={"automl": 2}):
        with pytest.raises(faults.TrainAbort):
            A.AutoML(export_checkpoints_dir=ckdir, **spec).train(
                y="y", training_frame=fr
            )
    manifest = json.load(open(glob.glob(os.path.join(ckdir, "*.automl.json"))[0]))
    assert len(manifest["steps"]) == 2  # two finished steps recorded
    # cold recovery: drop the aborted run's models from the registry
    for keys in manifest["steps"].values():
        for k in keys:
            h2o3_tpu.remove(k)

    resumed = A.AutoML(export_checkpoints_dir=ckdir, **spec)
    resumed.train(y="y", training_frame=fr)
    assert "recover" in {e["stage"] for e in resumed.event_log}
    assert lb_table(resumed) == lb_table(full)


# ---------------------------------------------------------------------------
# overload-safe serving (ISSUE 4): admission control, collective watchdog,
# graceful drain — the shed/bound/drain acceptance pins


def _rest_post(url, path, payload, headers=None, timeout=30):
    import urllib.parse
    import urllib.request

    data = urllib.parse.urlencode(payload or {}).encode()
    req = urllib.request.Request(url + path, data=data,
                                 headers=headers or {}, method="POST")
    return json.loads(urllib.request.urlopen(req, timeout=timeout).read())


def _rest_get(url, path, timeout=30):
    import urllib.request

    return json.loads(urllib.request.urlopen(url + path, timeout=timeout).read())


def test_overload_shed_and_client_backoff_retry(tmp_path, monkeypatch):
    """The full overload story: with the in-flight gate at 1 and a
    fault-injected slow handler holding the slot, excess mutating requests
    are shed 429 + Retry-After (never queued), GETs keep serving, the shed
    counter moves, and the client's capped-backoff retry eventually lands."""
    import threading
    import urllib.error
    import urllib.request

    from h2o3_tpu.api.server import start_server
    from h2o3_tpu.client import H2OConnection
    from h2o3_tpu.utils import metrics as mx

    monkeypatch.setenv("H2O3_TPU_MAX_INFLIGHT", "1")
    srv = start_server(port=0)
    csv = tmp_path / "ov.csv"
    csv.write_text("x\n1\n2\n")
    before = mx.counter_value(
        "rest_rejected_total", method="POST", route="/3/ImportFiles",
        reason="inflight_full")

    with faults.inject(slow={"rest": 0.8}):
        def _blocker():
            _rest_post(srv.url, "/3/ImportFiles", {"path": str(csv)})

        t = threading.Thread(target=_blocker)
        t.start()
        time.sleep(0.25)  # the blocker now owns the single in-flight slot
        # a direct POST is shed with the Retry-After contract, instantly
        t0 = time.time()
        with pytest.raises(urllib.error.HTTPError) as ei:
            _rest_post(srv.url, "/3/ImportFiles", {"path": str(csv)})
        assert ei.value.code == 429
        assert float(ei.value.headers.get("Retry-After")) > 0
        assert time.time() - t0 < 0.5  # rejected at admission, not queued
        # GETs pass the gate even under overload: the cloud stays observable
        assert _rest_get(srv.url, "/3/Ping")["ok"]
        # a client with backoff-retry rides out the overload
        conn = H2OConnection(srv.url, retries=10, retry_backoff=0.1)
        out = conn.post("/3/ImportFiles", {"path": str(csv)})
        assert out["files"] == [str(csv)]
        t.join(timeout=10)
    after = mx.counter_value(
        "rest_rejected_total", method="POST", route="/3/ImportFiles",
        reason="inflight_full")
    assert after > before


def test_idempotent_retried_post_trains_once():
    """A retried POST carrying the same Idempotency-Key replays the first
    response instead of double-training: same job key, no second job."""
    import urllib.parse
    import urllib.request

    from h2o3_tpu.api.server import start_server
    from h2o3_tpu.client import H2OConnection
    from h2o3_tpu.cluster.job import Job
    from h2o3_tpu.cluster.registry import DKV

    srv = start_server(port=0)
    Frame.from_pandas(_df(300, seed=21), destination_frame="idem_fr")
    conn = H2OConnection(srv.url)
    body = {"training_frame": "idem_fr", "response_column": "y",
            "ntrees": 2, "max_depth": 2, "seed": 1}
    key = "chaos-idem-1"
    # count ROOT builds only: the builder spawns a nested "gbm build" job
    # asynchronously under the REST job (parent set), so counting children
    # would race the build thread
    def _root_builds():
        return sum(1 for j in DKV.values_of_type(Job)
                   if j.description == "gbm build" and j.parent is None)

    r1 = conn.post("/3/ModelBuilders/gbm", body, idempotency_key=key)
    jkey = r1["job"]["key"]["name"]
    n_jobs = _root_builds()
    # duplicate while (possibly) still running AND after completion: both
    # replay the original response
    r2 = conn.post("/3/ModelBuilders/gbm", body, idempotency_key=key)
    assert r2["job"]["key"]["name"] == jkey
    conn.wait_job(jkey)
    data = urllib.parse.urlencode(body).encode()
    req = urllib.request.Request(
        srv.url + "/3/ModelBuilders/gbm", data=data, method="POST",
        headers={"Idempotency-Key": key})
    with urllib.request.urlopen(req, timeout=30) as r:
        r3 = json.loads(r.read())
        assert r.headers.get("Idempotency-Replayed") == "true"
    assert r3["job"]["key"]["name"] == jkey
    assert _root_builds() == n_jobs  # exactly one train


def test_watchdog_latches_on_stalled_command(_clean_latch, monkeypatch):
    """A stall-injected replicated command exceeding its watchdog budget
    trips the degraded latch; the NEXT command fail-stops instead of
    entering the wedged mesh."""
    from h2o3_tpu.cluster import cloud, spmd
    from h2o3_tpu.utils import metrics as mx

    monkeypatch.setenv("H2O3_TPU_SPMD_WATCHDOG_SECS", "0.15")
    before = mx.counter_value("spmd_watchdog_trips_total", cmd="remove")
    with faults.inject(stall={"spmd_run": 0.7}):
        spmd.run("remove", key="watchdog_nope")  # stalls past the budget
    reason = cloud.degraded_reason()
    assert reason is not None and "watchdog" in reason
    assert mx.counter_value("spmd_watchdog_trips_total", cmd="remove") == before + 1
    with pytest.raises(RuntimeError, match="fail-stop"):
        spmd.run("remove", key="watchdog_nope2")


def test_watchdog_stale_snapshot_does_not_trip(_clean_latch):
    """Regression: a command that completed (and was popped) after the
    watchdog snapshotted it must NOT latch degraded — the monitor re-checks
    registration under _WATCH_LOCK before tripping, so only a still-running
    command can degrade the cloud."""
    from h2o3_tpu.cluster import cloud, spmd

    wid = 10**9  # never collides with real _WATCH_IDS
    w = {"cmd": "stale", "t0": time.monotonic() - 99.0, "budget": 0.05,
         "tripped": False}
    # stale snapshot: over budget, but no longer registered (completed)
    spmd._watchdog_pass([(wid, w)])
    assert cloud.degraded_reason() is None
    assert not w["tripped"]
    # the same entry while still registered DOES trip, one way
    with spmd._WATCH_LOCK:
        spmd._WATCH_ACTIVE[wid] = w
    try:
        spmd._watchdog_pass([(wid, w)])
        assert w["tripped"]
        assert cloud.degraded_reason() is not None
    finally:
        with spmd._WATCH_LOCK:
            spmd._WATCH_ACTIVE.pop(wid, None)


def test_degraded_latch_unblocks_lock_waiters(_clean_latch, monkeypatch):
    """A caller queued on spmd._LOCK behind a wedged command fail-stops the
    moment the latch is set — no indefinite block on the lock."""
    import threading

    from h2o3_tpu.cluster import cloud, spmd

    monkeypatch.setattr(spmd, "_IS_MULTI", True)
    monkeypatch.setattr(spmd, "is_coordinator", lambda: True)
    outcome = []
    assert spmd._LOCK.acquire(timeout=1)  # stand-in for the wedged command
    try:
        def _caller():
            try:
                spmd.run("remove", key="lock_wait")
                outcome.append(None)
            except Exception as e:  # noqa: BLE001 — captured for assert
                outcome.append(e)

        t = threading.Thread(target=_caller)
        t.start()
        time.sleep(0.6)
        assert t.is_alive() and not outcome  # genuinely waiting on the lock
        cloud.mark_degraded("test: wedged collective holds the lock")
        t.join(timeout=5)
        assert not t.is_alive()
    finally:
        spmd._LOCK.release()
    assert isinstance(outcome[0], RuntimeError)
    assert "fail-stop" in str(outcome[0])


def test_drain_flushes_resumable_checkpoint(tmp_path, monkeypatch):
    """stop(drain=True) via POST /3/Shutdown?drain=true during a running
    GBM job: mutating admits stop instantly (503 + Retry-After), the job
    truncates gracefully at the next interval and flushes a checkpoint,
    and resuming from it reproduces the uninterrupted run at 1e-6 (the PR 2
    harness contract). Then the listener closes."""
    import urllib.error
    import urllib.request

    from h2o3_tpu.api import server as S
    from h2o3_tpu.cluster.job import Job
    from h2o3_tpu.cluster.registry import DKV

    fr = Frame.from_pandas(_df(), destination_frame="drain_fr")
    kw = dict(max_depth=3, seed=11, learn_rate=0.2, score_tree_interval=2)
    full = GBM(ntrees=8, **kw).train(y="y", training_frame=fr)

    srv = S.start_server(port=0)
    url = srv.url
    ckdir = str(tmp_path / "drain_ck")
    with faults.inject(slow={"gbm": 0.5}):
        resp = _rest_post(url, "/3/ModelBuilders/gbm", {
            "training_frame": "drain_fr", "response_column": "y",
            "ntrees": 8, "export_checkpoints_dir": ckdir, **kw,
        })
        jkey = resp["job"]["key"]["name"]
        # wait for the first interval snapshot (the /3/Jobs recovery block)
        deadline = time.time() + 120
        j = None
        while time.time() < deadline:
            j = _rest_get(url, f"/3/Jobs/{jkey}")["jobs"][0]
            if j.get("recovery") or j["status"] != "RUNNING":
                break
            time.sleep(0.02)
        assert j and j["status"] == "RUNNING" and j.get("recovery"), j

        out = _rest_post(url, "/3/Shutdown?drain=true", {})
        assert out["drain"] is True
        # draining: mutating work is shed while the job flushes...
        with pytest.raises(urllib.error.HTTPError) as ei:
            _rest_post(url, "/3/ImportFiles", {"path": "/nope.csv"})
        assert ei.value.code == 503
        assert ei.value.headers.get("Retry-After")
        # ...then the listener closes
        deadline = time.time() + 60
        while time.time() < deadline:
            try:
                _rest_get(url, "/3/Ping", timeout=2)
                time.sleep(0.1)
            except Exception:
                break
        else:
            raise AssertionError("listener still up 60s after drain")

    job = DKV.get(jkey)
    assert isinstance(job, Job) and job.status == Job.DONE
    partial = job.result
    # truncated mid-build, on an interval boundary, never empty
    assert 2 <= partial.output["ntrees_actual"] < 8
    prior = load_model(_latest_snapshot(ckdir, "gbm"))
    resumed = GBM(ntrees=8, checkpoint=prior.key, **kw).train(
        y="y", training_frame=fr
    )
    assert resumed.output["ntrees_actual"] == 8
    np.testing.assert_allclose(
        resumed.training_metrics.logloss, full.training_metrics.logloss,
        atol=1e-6,
    )


def test_grid_abort_preserves_manifest_and_recovers(tmp_path):
    from h2o3_tpu.models.grid import GridSearch

    fr = Frame.from_pandas(_df(600, seed=10))
    ckdir = str(tmp_path / "grid_ck")
    mk = dict(grid_id="g_chaos", seed=2, ntrees=3, export_checkpoints_dir=ckdir)

    with faults.inject(abort={"grid": 2}):
        with pytest.raises(faults.TrainAbort):
            GridSearch(GBM, {"max_depth": [2, 3, 4]}, **mk).train(
                y="y", training_frame=fr
            )
    # the manifest records exactly the finished combos
    manifest = json.load(open(os.path.join(ckdir, "g_chaos.grid.json")))
    assert len(manifest["built"]) == 2
    for k in manifest["built"].values():
        h2o3_tpu.remove(k)
    g2 = GridSearch(GBM, {"max_depth": [2, 3, 4]}, **mk).train(
        y="y", training_frame=fr
    )
    assert len(g2.models) == 3
    assert sorted(manifest["built"].values()) == sorted(
        m.key for m in g2.models[:2]
    )
