"""Native chunked CSV parser (native/fastcsv.cpp + native_csv.py) — the
ParseDataset tokenizer analog (SURVEY §2.1). Contract under test: the fast
path is bit-exact against the correctly-rounded reference parse, and EVERY
out-of-dialect input falls back to pandas (returns None) instead of
guessing."""

import gzip
import io

import numpy as np
import pandas as pd
import pytest

from h2o3_tpu import native_csv
from h2o3_tpu.frame import parse as P

pytestmark = pytest.mark.skipif(
    not native_csv.available(), reason="no g++ toolchain to build libfastcsv"
)


def _csv(tmp_path, text, name="t.csv"):
    p = tmp_path / name
    p.write_text(text)
    return str(p)


def test_mixed_frame_parity(tmp_path):
    rng = np.random.default_rng(1)
    n = 50_000
    df = pd.DataFrame(
        {
            "x": rng.normal(size=n),
            "i": rng.integers(-1000, 1000, n),
            "g": rng.choice(["red", "green", "blue"], n),
            "y": rng.normal(size=n) * 1e12,
        }
    )
    df.loc[rng.random(n) < 0.03, "x"] = np.nan
    path = str(tmp_path / "m.csv")
    df.to_csv(path, index=False)

    got = P._try_native_csv(path, ",")
    assert got is not None
    ref = pd.read_csv(path, float_precision="round_trip")
    # float64 parse is bit-exact vs the correctly-rounded reference
    # (pandas' DEFAULT parser is the one that's off by an ulp)
    assert (np.nan_to_num(got["x"].to_numpy(), nan=-9e9)
            == np.nan_to_num(ref["x"].to_numpy(), nan=-9e9)).all()
    assert (got["y"].to_numpy() == ref["y"].to_numpy()).all()
    assert got["i"].dtype == np.int64 and (got["i"] == ref["i"]).all()
    assert (got["g"].astype(str) == ref["g"].astype(str)).all()


def test_na_spellings_and_crlf(tmp_path):
    path = _csv(tmp_path, "a,g\r\n1.5,x\r\nNA,null\r\n,NaN\r\n+3.25,x\r\n")
    got = P._try_native_csv(path, ",")
    assert got is not None
    a = got["a"].to_numpy()
    assert a[0] == 1.5 and np.isnan(a[1]) and np.isnan(a[2]) and a[3] == 3.25
    g = got["g"]
    assert str(g.iloc[0]) == "x" and pd.isna(g.iloc[1]) and pd.isna(g.iloc[2])


def test_stray_cr_bails_to_pandas(tmp_path):
    """A '\\r' outside a \\r\\n line ending must NOT be silently trimmed from
    (or kept inside) a field: pandas' C parser treats a lone \\r as a line
    terminator, so the native path declines and the import goes through
    pandas — both paths then see the same rows."""
    # interior \r inside a non-final enum field, and one ending a non-final
    # field — historically trim_cr stripped the latter, diverging from pandas
    for text in ("a,g\n1.5,x\ry\n2.5,z\n", "a,g\n1.5,w\r,z\n"):
        path = _csv(tmp_path, text)
        assert P._try_native_csv(path, ",") is None
    # \r\n endings (every \r followed by \n) stay ON the fast path, and the
    # final field comes out \r-free
    path = _csv(tmp_path, "a,g\r\n1.5,x\r\n2.5,y\r\n")
    got = P._try_native_csv(path, ",")
    assert got is not None
    assert [str(v) for v in got["g"]] == ["x", "y"]
    ref = pd.read_csv(path)
    assert list(ref["g"]) == ["x", "y"]


def test_na_set_matches_pandas_exactly(tmp_path):
    """'None' IS pandas-NA; 'NAN' is NOT — both paths must agree."""
    path = _csv(tmp_path, "g\na\nNone\nNAN\nb\n")
    got = P._try_native_csv(path, ",")
    assert got is not None
    ref = pd.read_csv(path)
    assert pd.isna(got["g"].iloc[1]) and pd.isna(ref["g"].iloc[1])
    assert str(got["g"].iloc[2]) == "NAN" == str(ref["g"].iloc[2])
    # domains come out SORTED, exactly like the pandas-path interning
    assert list(got["g"].cat.categories) == sorted(["a", "NAN", "b"])


def test_blank_lines_skipped_like_pandas(tmp_path):
    path = _csv(tmp_path, "a\n1\n\n2\n")
    got = P._try_native_csv(path, ",")
    assert got is not None
    assert got["a"].tolist() == [1, 2]  # pandas skip_blank_lines default


def test_big_int64_ids_fall_back(tmp_path):
    # values past 2^53 cannot round-trip through f64; only pandas' int64
    # path is exact, so the native path must decline
    path = _csv(tmp_path, "id\n9007199254740993\n9007199254740995\n")
    assert P._try_native_csv(path, ",") is None


def test_no_trailing_newline(tmp_path):
    path = _csv(tmp_path, "a,b\n1,2\n3,4")
    got = P._try_native_csv(path, ",")
    assert got is not None
    assert got["a"].tolist() == [1, 3] and got["b"].tolist() == [2, 4]


def test_gz_supported(tmp_path):
    p = tmp_path / "z.csv.gz"
    with gzip.open(p, "wt") as f:
        f.write("a\n1.25\n2.5\n")
    got = P._try_native_csv(str(p), ",")
    assert got is not None and got["a"].tolist() == [1.25, 2.5]


def test_quoted_dialect_falls_back(tmp_path):
    path = _csv(tmp_path, 'a,g\n1,"x,y"\n2,z\n')
    assert P._try_native_csv(path, ",") is None  # pandas handles quoting


def test_numeric_surprise_falls_back(tmp_path):
    # sample says numeric; a stray token deep in the column must NOT guess
    rows = "\n".join(["%d" % i for i in range(3000)])
    path = _csv(tmp_path, f"a\n{rows}\noops\n")
    assert P._try_native_csv(path, ",") is None


def test_ragged_row_falls_back(tmp_path):
    path = _csv(tmp_path, "a,b\n1,2\n3,4,5\n")
    assert P._try_native_csv(path, ",") is None


def test_time_like_column_falls_back(tmp_path):
    path = _csv(tmp_path, "t\n2024-01-01\n2024-01-02\n")
    assert P._try_native_csv(path, ",") is None  # TIME stays pandas-typed


def test_duplicate_headers_match_pandas_mangling(tmp_path):
    # the eligibility sample is read by pandas, which already mangles
    # duplicates ('a', 'a.1') — so the native path sees unique names and
    # produces the same columns the pandas path would
    path = _csv(tmp_path, "a,a\n1,2\n")
    got = P._try_native_csv(path, ",")
    if got is not None:
        assert list(got.columns) == list(pd.read_csv(path).columns)


def test_import_file_uses_same_values_either_path(tmp_path, monkeypatch):
    """End-to-end: the Frame built through import_file carries identical
    values whether the native fast path or pandas parsed the file."""
    import h2o3_tpu

    rng = np.random.default_rng(7)
    n = 5_000
    df = pd.DataFrame(
        {
            "x": rng.normal(size=n),
            "g": rng.choice(["a", "b", "c"], n),
            "label": rng.choice(["yes", "no"], n),
        }
    )
    path = str(tmp_path / "e2e.csv")
    df.to_csv(path, index=False)

    fr_native = h2o3_tpu.import_file(path, destination_frame="ncsv_native")
    monkeypatch.setenv("H2O3_TPU_NATIVE_PARSE", "0")
    fr_pandas = h2o3_tpu.import_file(path, destination_frame="ncsv_pandas")

    a = fr_native.to_pandas()
    b = fr_pandas.to_pandas()
    assert list(a.columns) == list(b.columns)
    assert (a["x"].to_numpy() == b["x"].to_numpy()).all()
    assert (a["g"].astype(str) == b["g"].astype(str)).all()
    assert (a["label"].astype(str) == b["label"].astype(str)).all()


def test_thread_count_invariance():
    """Row order, values AND enum domains are independent of the thread
    split (the merge remaps thread-local codes onto sorted global levels)."""
    rng = np.random.default_rng(3)
    n = 10_000
    lines = ["x,g"] + [
        f"{rng.normal():.6g},{rng.choice(['u', 'v', 'w'])}" for _ in range(n)
    ]
    data = ("\n".join(lines) + "\n").encode()
    ref = native_csv.parse_csv_native(data, ["x", "g"], [0, 1], n_threads=1)
    assert ref is not None
    for t in (2, 3, 7):
        df = native_csv.parse_csv_native(data, ["x", "g"], [0, 1], n_threads=t)
        assert df is not None, t
        assert (df["x"] == ref["x"]).all()
        assert list(df["g"].cat.categories) == list(ref["g"].cat.categories)
        assert (df["g"].astype(str) == ref["g"].astype(str)).all()


def test_rank_rows_byte_range_matches_pandas(tmp_path):
    """The sharded-parse per-rank reader: native byte-range slice == the
    pandas skiprows read, for interior, first and tail ranges."""
    from h2o3_tpu.frame.parse import CAT, NUM, _read_rank_rows

    rng = np.random.default_rng(11)
    n = 1000
    df = pd.DataFrame(
        {"x": rng.normal(size=n).round(4),
         "g": rng.choice(["aa", "bb", "cc"], n)}
    )
    path = str(tmp_path / "r.csv")
    df.to_csv(path, index=False)
    kinds = {"x": NUM, "g": CAT}
    for lo, hi in ((0, 250), (250, 700), (700, 1000), (0, 1000), (990, 1000)):
        got = _read_rank_rows(path, ",", ["x", "g"], kinds, lo, hi, n)
        ref = pd.read_csv(path, skiprows=range(1, lo + 1), nrows=hi - lo,
                          header=0, names=["x", "g"])
        assert len(got) == hi - lo
        assert (got["x"].to_numpy() == ref["x"].to_numpy()).all(), (lo, hi)
        assert (got["g"].astype(str) == ref["g"].astype(str)).all(), (lo, hi)


def test_rank_rows_fallback_outside_dialect(tmp_path):
    from h2o3_tpu.frame.parse import CAT, NUM, _read_rank_rows

    path = str(tmp_path / "q.csv")
    with open(path, "w") as f:
        f.write('x,g\n1.0,"a,b"\n2.0,c\n')
    got = _read_rank_rows(path, ",", ["x", "g"], {"x": NUM, "g": CAT}, 0, 2, 2)
    assert len(got) == 2 and str(got["g"].iloc[0]) == "a,b"  # pandas path


def test_sharded_parse_refuses_quoted_csv(tmp_path):
    """Raw-newline row addressing is only sound without quoted fields; the
    v1 sharded parse must refuse deterministically (same answer on every
    rank), not silently mis-shard."""
    from h2o3_tpu.frame.parse import parse_sharded

    path = str(tmp_path / "q.csv")
    with open(path, "w") as f:
        f.write('x,g\n1.0,"a,b"\n2.0,c\n')
    with pytest.raises(ValueError, match="unquoted"):
        parse_sharded({"source_frames": [path]})
