"""Compiled sharded munging plane (ISSUE 20, frame/munge.py + frame/lazy.py
expression fusion + frame/ops.py routing).

The acceptance pins:
- group-by / join / sort parity vs the eager seed path on 1/2/8-device
  meshes and on the 2x4 mesh (join and sort BIT-equal; group-by float sums
  allclose — per-shard accumulation + psum reorders f32 addition — with
  count/min/max exact);
- a 10-op rapids-style expression chain materializes as ONE fused dispatch
  (>= 5x dispatch reduction, counter-proven) with bit-identical values;
- streamed (ChunkStore window) == resident results with the peak window
  bytes held under the configured window;
- ``H2O3_TPU_MUNGE_FUSE=0`` runs the seed code paths: zero munge-plane
  dispatches and byte-identical outputs.
"""

import contextlib
import os

import numpy as np
import pytest

import jax
from jax.sharding import Mesh

from h2o3_tpu.frame import chunkstore as cs
from h2o3_tpu.frame import lazy as lz
from h2o3_tpu.frame import munge as mg
from h2o3_tpu.frame import ops as OPS
from h2o3_tpu.frame.frame import CAT, NUM, Frame, Vec
from h2o3_tpu.parallel import mesh as pm
from h2o3_tpu.utils.metrics import counter_value


@contextlib.contextmanager
def _use_mesh(k: int):
    devs = jax.devices("cpu")
    assert len(devs) >= k, "8-device conftest pin did not land"
    old = pm._mesh
    pm.set_mesh(Mesh(np.array(devs[:k]), (pm.ROWS_AXIS,)))
    try:
        yield
    finally:
        pm.set_mesh(old)


@contextlib.contextmanager
def _use_mesh_2d(r: int, c: int):
    devs = jax.devices("cpu")
    assert len(devs) >= r * c
    old = pm._mesh
    pm.set_mesh(pm.make_mesh_2d(r, c, devs))
    try:
        yield
    finally:
        pm.set_mesh(old)


@contextlib.contextmanager
def _env(**kv):
    old = {k: os.environ.get(k) for k in kv}
    os.environ.update({k: str(v) for k, v in kv.items()})
    try:
        yield
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _frame(n=1000, seed=0, ngroups=13):
    rng = np.random.default_rng(seed)
    a = rng.normal(size=n)
    a[::17] = np.nan
    b = rng.normal(size=n)
    g = rng.integers(0, ngroups, size=n)
    return Frame(
        [
            Vec.from_numpy(a, NUM, name="a"),
            Vec.from_numpy(b, NUM, name="b"),
            Vec.from_numpy(
                g.astype(np.int64), CAT, name="g",
                domain=[str(i) for i in range(ngroups)],
            ),
        ],
        ["a", "b", "g"],
    )


def _join_frames(seed=1, nl=400, nr=300, nkeys=50):
    rng = np.random.default_rng(seed)
    L = Frame(
        [
            Vec.from_numpy(
                rng.integers(0, nkeys, size=nl).astype(np.float64), NUM,
                name="k"),
            Vec.from_numpy(rng.normal(size=nl), NUM, name="x"),
        ],
        ["k", "x"],
    )
    R = Frame(
        [
            Vec.from_numpy(
                rng.integers(0, nkeys, size=nr).astype(np.float64), NUM,
                name="k"),
            Vec.from_numpy(rng.normal(size=nr), NUM, name="y"),
        ],
        ["k", "y"],
    )
    return L, R


def _frames_equal(fa, fb, *, float_close=(), rtol=1e-5, atol=1e-4):
    """Bit-equality column-wise, except columns in ``float_close`` which
    get allclose (accumulation-order differences)."""
    assert list(fa.columns) == list(fb.columns)
    assert fa.shape == fb.shape
    for c in fa.columns:
        xa, xb = fa[c].to_numpy(), fb[c].to_numpy()
        if xa.dtype == object:
            assert list(xa) == list(xb), c
        elif c in float_close:
            assert np.allclose(xa, xb, rtol=rtol, atol=atol, equal_nan=True), c
        else:
            assert np.array_equal(xa, xb, equal_nan=True), c


GB_SPEC = {"a": ["sum", "mean", "min", "max", "count", "var", "sd"],
           "b": ["sum", "nrow"]}
GB_CLOSE = ("sum_a", "mean_a", "var_a", "sd_a", "sum_b")


@pytest.mark.parametrize("ndev", [1, 2, 8])
def test_groupby_parity_meshes(ndev):
    with _use_mesh(ndev):
        with _env(H2O3_TPU_MUNGE_FUSE="0"):
            eager = OPS.group_by(_frame(), "g").agg(GB_SPEC).to_pandas()
        with _env(H2O3_TPU_MUNGE_FUSE="1"):
            fused = OPS.group_by(_frame(), "g").agg(GB_SPEC).to_pandas()
    _frames_equal(eager, fused, float_close=GB_CLOSE)


def test_groupby_parity_mesh2d():
    with _use_mesh_2d(2, 4):
        with _env(H2O3_TPU_MUNGE_FUSE="0"):
            eager = OPS.group_by(_frame(), "g").agg(GB_SPEC).to_pandas()
        with _env(H2O3_TPU_MUNGE_FUSE="1"):
            fused = OPS.group_by(_frame(), "g").agg(GB_SPEC).to_pandas()
    _frames_equal(eager, fused, float_close=GB_CLOSE)


@pytest.mark.parametrize("ndev", [1, 2, 8])
@pytest.mark.parametrize("how", [(False, False), (True, False),
                                 (False, True), (True, True)])
def test_join_bit_parity_meshes(ndev, how):
    all_x, all_y = how
    with _use_mesh(ndev):
        with _env(H2O3_TPU_MUNGE_FUSE="0"):
            L, R = _join_frames()
            eager = OPS.merge(L, R, by=["k"], all_x=all_x, all_y=all_y).to_pandas()
        with _env(H2O3_TPU_MUNGE_FUSE="1"):
            L, R = _join_frames()
            fused = OPS.merge(L, R, by=["k"], all_x=all_x, all_y=all_y).to_pandas()
    _frames_equal(eager, fused)  # BIT-equal: same expansion contract


def test_join_exchange_lane_runs_and_matches():
    """On the 8-dev mesh the radix all_to_all gid exchange must actually
    engage (counter-proven) and still produce the bit-identical join."""
    with _use_mesh(8):
        with _env(H2O3_TPU_MUNGE_FUSE="0"):
            L, R = _join_frames(seed=7)
            eager = OPS.merge(L, R, by=["k"]).to_pandas()
        d0 = counter_value("munge_dispatches_total", op="join_exchange")
        with _env(H2O3_TPU_MUNGE_FUSE="1"):
            L, R = _join_frames(seed=7)
            fused = OPS.merge(L, R, by=["k"]).to_pandas()
        d1 = counter_value("munge_dispatches_total", op="join_exchange")
    assert d1 - d0 >= 1, "exchange lane did not run"
    _frames_equal(eager, fused)


def test_join_enum_keys_mesh2d():
    with _use_mesh_2d(2, 4):
        def mk():
            rng = np.random.default_rng(3)
            L = Frame(
                [Vec.from_numpy(rng.integers(0, 5, 120).astype(np.int64),
                                CAT, name="k", domain=list("abcde")),
                 Vec.from_numpy(rng.normal(size=120), NUM, name="x")],
                ["k", "x"])
            R = Frame(
                [Vec.from_numpy(rng.integers(0, 6, 90).astype(np.int64),
                                CAT, name="k", domain=list("abcdef")),
                 Vec.from_numpy(rng.normal(size=90), NUM, name="y")],
                ["k", "y"])
            return L, R
        with _env(H2O3_TPU_MUNGE_FUSE="0"):
            L, R = mk()
            eager = OPS.merge(L, R, by=["k"], all_x=True).to_pandas()
        with _env(H2O3_TPU_MUNGE_FUSE="1"):
            L, R = mk()
            fused = OPS.merge(L, R, by=["k"], all_x=True).to_pandas()
    _frames_equal(eager, fused)


@pytest.mark.parametrize("ndev", [1, 2, 8])
def test_sort_bit_parity_meshes(ndev):
    with _use_mesh(ndev):
        with _env(H2O3_TPU_MUNGE_FUSE="0"):
            eager = OPS.sort(_frame(), ["g", "a"],
                             ascending=[True, False]).to_pandas()
        with _env(H2O3_TPU_MUNGE_FUSE="1"):
            fused = OPS.sort(_frame(), ["g", "a"],
                             ascending=[True, False]).to_pandas()
    _frames_equal(eager, fused)


def test_sort_bit_parity_mesh2d():
    with _use_mesh_2d(2, 4):
        with _env(H2O3_TPU_MUNGE_FUSE="0"):
            eager = OPS.sort(_frame(), ["b"]).to_pandas()
        with _env(H2O3_TPU_MUNGE_FUSE="1"):
            fused = OPS.sort(_frame(), ["b"]).to_pandas()
    _frames_equal(eager, fused)


def _chain(fr):
    """10 elementwise ops, the rapids-AST shape: arithmetic + compare +
    boolean + ifelse + unary."""
    va, vb = fr.vec("a"), fr.vec("b")
    c = (va * 2.0 + vb) / 3.0          # 3
    d = (c > 0) & (vb < 1.0)           # +3 = 6
    e = OPS.ifelse(d, c, va - vb)      # +2 = 8
    return (e * e + 1.0)               # +2 = 10


def test_expr_chain_fuses_to_one_dispatch_bit_equal():
    fr = _frame()
    with _env(H2O3_TPU_MUNGE_FUSE="0"):
        e0 = counter_value("munge_dispatches_total", op="elementwise")
        eager = _chain(fr).to_numpy()
        e1 = counter_value("munge_dispatches_total", op="elementwise")
    with _env(H2O3_TPU_MUNGE_FUSE="1"):
        f0 = counter_value("munge_dispatches_total", op="expr_fuse")
        out = _chain(fr)
        assert isinstance(out, lz.LazyExprVec) and not out.is_materialized
        fused = out.to_numpy()
        f1 = counter_value("munge_dispatches_total", op="expr_fuse")
    n_eager, n_fused = e1 - e0, f1 - f0
    assert n_eager == 10
    assert n_fused == 1
    assert n_eager / n_fused >= 5  # the acceptance ratio
    assert np.array_equal(eager, fused, equal_nan=True)


def test_expr_streamed_matches_resident_and_holds_window():
    n = 50000
    rng = np.random.default_rng(5)
    a = rng.normal(size=n)
    a[::31] = np.nan
    b = rng.normal(size=n)

    def build():
        return Frame(
            [Vec.from_numpy(a, NUM, name="a"),
             Vec.from_numpy(b, NUM, name="b")], ["a", "b"])

    window = 64 * 1024
    with _env(H2O3_TPU_MUNGE_FUSE="1"):
        fr = build()
        resident = ((fr.vec("a") * 2.0 + fr.vec("b")) / 3.0).to_numpy()
        with _env(H2O3_TPU_FRAME_COMPRESS="1",
                  H2O3_TPU_HBM_WINDOW_BYTES=str(window)):
            s0 = counter_value("munge_dispatches_total", op="expr_stream")
            fr2 = build()
            out = (fr2.vec("a") * 2.0 + fr2.vec("b")) / 3.0
            streamed = out.to_numpy()
            s1 = counter_value("munge_dispatches_total", op="expr_stream")
    assert s1 - s0 == 1
    assert np.array_equal(resident, streamed, equal_nan=True)
    # residency fix: the streamed result parks host-side, no device column
    assert out._materialize()._data is None
    assert cs.LAST_STORE_STATS["peak_hbm"] <= window


def test_groupby_streamed_matches_resident():
    n = 50000
    spec = {"a": ["sum", "min", "max", "count"]}
    with _env(H2O3_TPU_MUNGE_FUSE="1"):
        resident = OPS.group_by(_frame(n=n, seed=9, ngroups=100),
                                "g").agg(spec).to_pandas()
        with _env(H2O3_TPU_FRAME_COMPRESS="1",
                  H2O3_TPU_HBM_WINDOW_BYTES=str(64 * 1024)):
            g0 = counter_value("munge_dispatches_total", op="groupby_stream")
            streamed = OPS.group_by(_frame(n=n, seed=9, ngroups=100),
                                    "g").agg(spec).to_pandas()
            g1 = counter_value("munge_dispatches_total", op="groupby_stream")
    assert g1 - g0 == 1
    # counts/extrema exact; sums reorder f32 accumulation across blocks
    _frames_equal(resident, streamed, float_close=("sum_a",))


def test_fuse_off_runs_zero_munge_dispatches_byte_identical():
    """MUNGE_FUSE=0 is the seed path: no munge-plane dispatches at all, and
    outputs byte-identical to the fused lanes where bits are pinned."""
    with _env(H2O3_TPU_MUNGE_FUSE="0"):
        tracked = ("groupby", "groupby_stream", "join", "join_exchange",
                   "sort", "expr_fuse", "expr_stream")
        before = {op: counter_value("munge_dispatches_total", op=op)
                  for op in tracked}
        fr = _frame()
        _ = _chain(fr).to_numpy()
        _ = OPS.group_by(fr, "g").agg({"a": "sum"}).to_pandas()
        L, R = _join_frames()
        _ = OPS.merge(L, R, by=["k"]).to_pandas()
        _ = OPS.sort(fr, ["a"]).to_pandas()
        after = {op: counter_value("munge_dispatches_total", op=op)
                 for op in tracked}
    assert before == after, "fuse=0 must never enter the munge plane"


def test_fallback_counters_tally():
    with _env(H2O3_TPU_MUNGE_FUSE="1"):
        b0 = counter_value("munge_fuse_fallbacks_total", reason="host_agg")
        _ = OPS.group_by(_frame(), "g").agg({"a": ["median"]}).to_pandas()
        b1 = counter_value("munge_fuse_fallbacks_total", reason="host_agg")
    assert b1 - b0 >= 1


def test_deferred_vec_is_transparent():
    """A LazyExprVec behaves as a Vec across the frame surface: stats,
    frame insertion, row filtering, gather."""
    with _env(H2O3_TPU_MUNGE_FUSE="1"):
        fr = _frame()
        v = fr.vec("a") * 2.0 + 1.0
        assert v.nrow == fr.nrow
        st = v.stats()
        assert np.isfinite(st["mean"])
        fr2 = Frame(fr._vecs + [v], fr.names + ["c"])
        got = fr2.vec("c").to_numpy()
    with _env(H2O3_TPU_MUNGE_FUSE="0"):
        fr2 = _frame()
        want = (fr2.vec("a") * 2.0 + 1.0).to_numpy()
    assert np.array_equal(got, want, equal_nan=True)
