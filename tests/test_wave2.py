"""Tree kernel wave 2 (ISSUE 16): GOSS row sampling, exclusive feature
bundling, u8-code-native binned frames, int16 histogram lanes, and
leaf-wise (lossguide) growth. Every lever ships with a forced-off control
that must reproduce today's path bit-for-bit, and every fast path must
stay inside its documented accuracy envelope."""

import contextlib
import os

import numpy as np
import pandas as pd
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.tree import GBM
from h2o3_tpu.models.tree import shared_tree as st
from h2o3_tpu.models.tree.binning import bin_frame, fit_bins, fit_efb
from h2o3_tpu.parallel import mesh as pm
from h2o3_tpu.utils import metrics as mx


@contextlib.contextmanager
def _use_mesh(k: int):
    """Run under a k-device sub-mesh of the 8-device CPU test cloud."""
    devs = jax.devices("cpu")
    assert len(devs) >= k, "8-device conftest pin did not land"
    old = pm._mesh
    pm.set_mesh(Mesh(np.array(devs[:k]), (pm.ROWS_AXIS,)))
    try:
        yield
    finally:
        pm.set_mesh(old)


@contextlib.contextmanager
def _env(**kv):
    old = {k: os.environ.get(k) for k in kv}
    for k, v in kv.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = str(v)
    try:
        yield
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _dense_df(n=3000, seed=0, c=5):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, c))
    df = pd.DataFrame(X, columns=[f"x{i}" for i in range(c)])
    df["y"] = X[:, 0] * 2 - X[:, 1] + 0.3 * rng.normal(size=n)
    return df


def _onehot_df(n=2400, seed=1, levels=8, dense=2):
    """EFB-friendly design: one-hot indicator columns (mutually exclusive
    by construction — zero conflicts) plus a couple of dense columns."""
    rng = np.random.default_rng(seed)
    g = rng.integers(0, levels, n)
    cols = {f"oh{j}": (g == j).astype(np.float32) for j in range(levels)}
    for j in range(dense):
        cols[f"d{j}"] = rng.normal(size=n).astype(np.float32)
    df = pd.DataFrame(cols)
    df["y"] = (
        0.7 * (g % 3) + df["d0"] - 0.5 * df["d1"]
        + 0.2 * rng.normal(size=n)
    )
    return df


def _cls_df(n=4000, seed=2, c=6):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, c))
    eta = X[:, 0] * 1.5 - X[:, 1] + 0.5 * X[:, 2] * X[:, 3]
    df = pd.DataFrame(X, columns=[f"x{i}" for i in range(c)])
    df["y"] = np.where(rng.random(n) < 1 / (1 + np.exp(-eta)), "a", "b")
    return df, (df["y"] == "a").to_numpy()


def _train(fr, **kw):
    params = dict(ntrees=8, max_depth=4, seed=7, distribution="gaussian")
    params.update(kw)
    return GBM(**params).train(y="y", training_frame=fr)


def _pred(m, fr, col="predict"):
    p = m.predict(fr)
    return p.vec(col if col in p.names else p.names[-1]).to_numpy()


# ---------------------------------------------------------------------------
# GOSS (H2O3_TPU_TREE_GOSS)


def test_goss_factor_amplification_pin():
    """The sampling factor itself: top-a rows by |gradient| keep weight
    1.0 exactly, kept rest rows get exactly (1-a)/b, dropped rows get 0,
    and invalid (sampled-out) rows stay out."""
    rng = np.random.default_rng(0)
    n = 4096
    w = np.ones(n, np.float32)
    w[:100] = 0.0  # already sampled out
    wy = rng.normal(size=n).astype(np.float32) * w
    a, b = 0.2, 0.1
    f = np.asarray(st._goss_factor(
        jnp.asarray(w), jnp.asarray(wy), jax.random.PRNGKey(3), a, b))
    n_valid = int((w > 0).sum())
    k = int(round(a * n_valid))
    amp = (1.0 - a) / b
    assert set(np.unique(f)).issubset({0.0, 1.0, np.float32(amp)})
    assert (f[w == 0] == 0).all()
    # the top-k |gradient| rows are exactly the factor-1.0 rows
    order = np.argsort(-np.abs(wy))
    top = order[:k]
    assert (f[top] == 1.0).all()
    # expected kept-rest count: Binomial(n_valid - k, b/(1-a))
    kept_rest = int((f == np.float32(amp)).sum())
    exp = (n_valid - k) * b / (1 - a)
    assert abs(kept_rest - exp) < 4 * np.sqrt(exp)


def test_goss_ab_parsing_and_validation():
    with _env(H2O3_TPU_TREE_GOSS="0.2,0.1"):
        assert st._goss_ab() == (0.2, 0.1)
    with _env(H2O3_TPU_TREE_GOSS=""):
        assert st._goss_ab() is None
    for bad in ("0.2", "1.1,0.1", "0.5,0.6", "0.2,0", "-0.1,0.5"):
        with _env(H2O3_TPU_TREE_GOSS=bad):
            with pytest.raises(ValueError):
                st._goss_ab()


@pytest.mark.slow
def test_goss_auc_envelope_and_counter():
    """GOSS at (a=0.2, b=0.1) trains on ~30% of rows per tree yet must
    stay inside a tight AUC envelope of the full-data build, and the
    modeled rows-sampled counter must tally exactly (a+b)*npad*ntrees."""
    from sklearn.metrics import roc_auc_score

    df, y = _cls_df()
    fr = Frame.from_pandas(df)
    kw = dict(ntrees=20, max_depth=4, seed=7, distribution="bernoulli")
    base = GBM(**kw).train(y="y", training_frame=fr)
    auc_base = roc_auc_score(y, _pred(base, fr, "a"))
    c0 = mx.counter_value("tree_rows_sampled_total")
    with _env(H2O3_TPU_TREE_GOSS="0.2,0.1"):
        goss = GBM(**kw).train(y="y", training_frame=fr)
    auc_goss = roc_auc_score(y, _pred(goss, fr, "a"))
    assert auc_goss > auc_base - 0.03
    dc = mx.counter_value("tree_rows_sampled_total") - c0
    assert dc == pytest.approx(0.3 * fr.npad * 20, rel=1e-6)


def test_goss_off_bit_identical():
    """The forced-off control: H2O3_TPU_TREE_GOSS='' must reproduce the
    unset-knob build bit-for-bit."""
    fr = Frame.from_pandas(_dense_df(seed=3))
    p0 = _pred(_train(fr), fr)
    with _env(H2O3_TPU_TREE_GOSS=""):
        p1 = _pred(_train(fr), fr)
    np.testing.assert_array_equal(p0, p1)


def test_goss_composes_with_sample_rate():
    """GOSS draws only among rows the per-tree bagging kept (w>0), so the
    two samplers compose rather than clobber each other."""
    fr = Frame.from_pandas(_dense_df(seed=4))
    with _env(H2O3_TPU_TREE_GOSS="0.2,0.1"):
        m = _train(fr, sample_rate=0.7)
    p = _pred(m, fr)
    assert np.isfinite(p).all()
    y = _dense_df(seed=4)["y"].to_numpy()
    assert np.corrcoef(p, y)[0, 1] > 0.8


# ---------------------------------------------------------------------------
# EFB (H2O3_TPU_TREE_EFB)


def test_efb_plan_shrinks_onehot_columns():
    """8 mutually-exclusive one-hot columns + 2 dense must bundle into far
    fewer histogram columns (>= 1.5x shrink, the acceptance floor)."""
    df = _onehot_df()
    fr = Frame.from_pandas(df)
    cols = [c for c in df.columns if c != "y"]
    spec = fit_bins(fr, cols)
    bins = bin_frame(spec, fr)
    plan = fit_efb(spec, bins, nrow=fr.nrow)
    assert plan is not None
    assert plan.n_cols == len(cols)
    assert plan.n_cols / plan.n_cols_b >= 1.5


def _split_structure(m):
    """(col, bin, leaf, na_left) arrays over the REAL node slots of every
    level of every tree — the split-decision fingerprint EFB must not
    perturb."""
    out = []
    for it in m.output["trees"]:
        for t in it:
            h = t.to_host()
            for lv, mask in zip(h.levels, h.real_level_masks()):
                out.append((
                    np.asarray(lv.split_col)[mask],
                    np.asarray(lv.split_bin)[mask],
                    np.asarray(lv.leaf_now)[mask],
                    np.asarray(lv.na_left)[mask],
                ))
    return out


def _integer_onehot_df(n=2400, seed=5, levels=8):
    """Integer-exact EFB parity suite: one-hot features and an integer,
    exactly-zero-mean response. With unit weights the stat lanes stay
    small in-range integers, so f32 sums are exact everywhere and EFB's
    default-cell reconstruction (node_total - sum of non-default) is
    bit-exact — the regime where 'bit-equal splits' is a theorem, not a
    tie-break accident."""
    rng = np.random.default_rng(seed)
    g = rng.integers(0, levels, n // 2)
    y_half = (g % 3 - 1).astype(np.float32)  # in {-1, 0, 1}
    g = np.concatenate([g, g])
    y = np.concatenate([y_half, -y_half])  # integer sum == exactly 0
    cols = {f"oh{j}": (g == j).astype(np.float32) for j in range(levels)}
    cols["flip"] = np.repeat([0.0, 1.0], n // 2).astype(np.float32)
    # one dense column so the BinSpec's code space (max_bins) is wide
    # enough to pack the one-hot columns' ~3-code ranges into one bundle —
    # an all-binary frame caps max_bins at ~5 and no bundle has room.
    # Dense FEATURE values may be float: the stat lanes (unit w, integer y)
    # are what exactness needs
    x = rng.normal(size=n // 2).astype(np.float32)
    cols["dense"] = np.concatenate([x, x])
    df = pd.DataFrame(cols)
    df["y"] = y
    return df


@pytest.mark.parametrize("k", [1, 2, 8])
def test_efb_bit_equal_splits_across_meshes(k):
    """EFB on integer-exact stat lanes must reproduce the unbundled build
    BIT-for-bit — split structure and predictions — on 1-, 2- and 8-device
    meshes, and the bundled-columns counter must tally the C shrink."""
    df = _integer_onehot_df()
    with _use_mesh(k):
        fr = Frame.from_pandas(df)
        kw = dict(ntrees=1, max_depth=4)
        m0 = _train(fr, **kw)
        p0 = _pred(m0, fr)
        c0 = mx.counter_value("tree_cols_bundled_total")
        with _env(H2O3_TPU_TREE_EFB="1"):
            m1 = _train(fr, **kw)
        p1 = _pred(m1, fr)
        for s0, s1 in zip(_split_structure(m0), _split_structure(m1)):
            for a0, a1 in zip(s0, s1):
                np.testing.assert_array_equal(a0, a1)
        np.testing.assert_array_equal(p0, p1)
        assert mx.counter_value("tree_cols_bundled_total") > c0


@pytest.mark.slow
def test_efb_float_gradients_quality_envelope():
    """On float gradient lanes the default-cell reconstruction carries an
    f32-associativity envelope: equal-gain threshold ties may break
    differently, but predictions must stay within a tight envelope of the
    unbundled build."""
    fr = Frame.from_pandas(_onehot_df(seed=5))
    p0 = _pred(_train(fr), fr)
    with _env(H2O3_TPU_TREE_EFB="1"):
        p1 = _pred(_train(fr), fr)
    np.testing.assert_allclose(p0, p1, atol=1e-4)


def test_efb_off_is_default():
    """The knob defaults off: no bundling work, counter quiet."""
    fr = Frame.from_pandas(_onehot_df(seed=6))
    c0 = mx.counter_value("tree_cols_bundled_total")
    _train(fr)
    assert mx.counter_value("tree_cols_bundled_total") == c0


def test_efb_skips_dense_frames():
    """All-dense designs have nothing to bundle: fit_efb declines and the
    build takes the ordinary path (knob on, counter quiet)."""
    fr = Frame.from_pandas(_dense_df(seed=7))
    p0 = _pred(_train(fr), fr)
    c0 = mx.counter_value("tree_cols_bundled_total")
    with _env(H2O3_TPU_TREE_EFB="1"):
        p1 = _pred(_train(fr), fr)
    np.testing.assert_array_equal(p0, p1)
    assert mx.counter_value("tree_cols_bundled_total") == c0


# ---------------------------------------------------------------------------
# int16 histogram lanes (H2O3_TPU_HIST_I16)


def _hist_case(n=3000, c=4, n_nodes=4, n_bins=16, seed=8, integer=True):
    rng = np.random.default_rng(seed)
    bins = rng.integers(0, n_bins, size=(n, c)).astype(np.uint8)
    nid = rng.integers(0, n_nodes, size=n).astype(np.int32)
    if integer:
        s = rng.integers(-5, 6, size=(n, 3)).astype(np.float32)
    else:
        s = rng.normal(size=(n, 3)).astype(np.float32)
    # histogram_in_jit takes stats as a sequence of (n,) lanes
    lanes = tuple(jnp.asarray(s[:, i]) for i in range(3))
    return jnp.asarray(bins), jnp.asarray(nid), lanes


def test_i16_exact_on_integer_stats():
    """Small-integer stat lanes (|v| <= 127, integral — the w/count lanes)
    hit the scale-1 EXACT path: the i16 histogram equals the f32 one
    bit-for-bit."""
    from h2o3_tpu.ops.histogram import build_histograms

    bins, nid, lanes = _hist_case()
    h_f32 = np.asarray(build_histograms(bins, nid, lanes, 4, 16))
    with _env(H2O3_TPU_HIST_I16="1"):
        h_i16 = np.asarray(build_histograms(bins, nid, lanes, 4, 16))
    np.testing.assert_array_equal(h_f32, h_i16)


def test_i16_float_stats_envelope():
    """Float lanes quantize at absmax/127 per (node, lane): the histogram
    must match f32 within the 1/254 relative-cell envelope."""
    from h2o3_tpu.ops.histogram import build_histograms

    bins, nid, lanes = _hist_case(seed=9, integer=False)
    h_f32 = np.asarray(build_histograms(bins, nid, lanes, 4, 16))
    with _env(H2O3_TPU_HIST_I16="1"):
        h_i16 = np.asarray(build_histograms(bins, nid, lanes, 4, 16))
    # per-cell error bound: (rows in cell) * scale/2 — bound globally by
    # the max |stat| row count via a loose but safe envelope
    scale = max(float(jnp.abs(s).max()) for s in lanes) / 127.0
    ones = tuple(jnp.ones_like(s) for s in lanes)
    rows_per_cell = np.asarray(build_histograms(bins, nid, ones, 4, 16))
    np.testing.assert_allclose(
        h_i16, h_f32, atol=float(scale) * (rows_per_cell.max() / 2 + 1))


def test_i16_overflow_latch_recomputes_f32():
    """A cell whose quantized sum exceeds +/-32767 trips the latch: the
    counter tallies and the pass recomputes in f32 — output bit-equal to
    the knob-off histogram."""
    from h2o3_tpu.ops.histogram import build_histograms

    # the latch is SHARD-local (the rescale happens before the cross-device
    # reduce), so the per-shard cell must overflow: on the 8-device mesh
    # 4800 rows put 600 q=127 codes in each shard's bin-0 cell (76200 >
    # 32767), tripping every shard's latch
    n = 4800
    bins = np.zeros((n, 2), np.uint8)  # every row in bin 0 of both cols
    nid = np.zeros(n, np.int32)
    lane = jnp.full(n, 127.0, jnp.float32)  # q=127 each
    args = (jnp.asarray(bins), jnp.asarray(nid), (lane, lane, lane))
    h_f32 = np.asarray(build_histograms(*args, 1, 4))
    c0 = mx.counter_value("tree_hist_i16_overflows_total")
    with _env(H2O3_TPU_HIST_I16="1"):
        h_i16 = np.asarray(build_histograms(*args, 1, 4))
    jax.effects_barrier()  # flush the debug.callback carrying the tally
    np.testing.assert_array_equal(h_f32, h_i16)
    assert mx.counter_value("tree_hist_i16_overflows_total") > c0


@pytest.mark.slow
def test_i16_gbm_trains_inside_envelope():
    """End-to-end: quantized histograms perturb near-tie split choices, so
    individual trees diverge across boosting rounds — the MODEL QUALITY
    envelope is the contract: the i16 build's training RMSE must stay
    within 10% of the f32 build's, and the forced-off control must be
    bit-for-bit."""
    df = _dense_df(seed=10)
    y = df["y"].to_numpy()
    fr = Frame.from_pandas(df)
    p0 = _pred(_train(fr), fr)
    with _env(H2O3_TPU_HIST_I16="1"):
        p1 = _pred(_train(fr), fr)
    with _env(H2O3_TPU_HIST_I16="0"):
        p2 = _pred(_train(fr), fr)
    rmse0 = float(np.sqrt(np.mean((p0 - y) ** 2)))
    rmse1 = float(np.sqrt(np.mean((p1 - y) ** 2)))
    assert rmse1 <= rmse0 * 1.10
    np.testing.assert_array_equal(p0, p2)


# ---------------------------------------------------------------------------
# leaf-wise growth (grow_policy=lossguide)


@pytest.mark.slow
def test_lossguide_honors_max_leaves():
    fr = Frame.from_pandas(_dense_df(seed=11))
    m = _train(fr, max_depth=6, grow_policy="lossguide", max_leaves=8)
    for it in m.output["trees"]:
        for t in it:
            assert t.n_leaves <= 8
    # depthwise at the same depth grows far past 8 leaves on this data
    d = _train(fr, max_depth=6)
    assert max(t.n_leaves for it in d.output["trees"] for t in it) > 8


def test_lossguide_huge_budget_matches_depthwise():
    """With max_leaves >= 2^depth the budget never binds: lossguide must
    reproduce the depthwise build bit-for-bit (same splits, same order of
    stat accumulation)."""
    fr = Frame.from_pandas(_dense_df(seed=12))
    p_d = _pred(_train(fr), fr)
    p_l = _pred(
        _train(fr, grow_policy="lossguide", max_leaves=2 ** 4), fr)
    np.testing.assert_array_equal(p_d, p_l)


def test_lossguide_validation():
    fr = Frame.from_pandas(_dense_df(n=500, seed=13))
    with pytest.raises(Exception, match="max_leaves"):
        _train(fr, grow_policy="lossguide")
    with pytest.raises(Exception, match="grow_policy"):
        _train(fr, grow_policy="bogus")


# ---------------------------------------------------------------------------
# u8-code-native frames (H2O3_TPU_TREE_U8CACHE)


def test_u8_cache_returns_same_buffer():
    """Second bin_frame over the same (spec, frame) must be a cache hit:
    the IDENTICAL device buffer, and zero new rebin HBM traffic."""
    df = _dense_df(seed=14)
    fr = Frame.from_pandas(df)
    cols = [c for c in df.columns if c != "y"]
    spec = fit_bins(fr, cols)
    b0 = bin_frame(spec, fr)
    r0 = mx.counter_value("tree_hist_hbm_bytes_total", path="rebin")
    b1 = bin_frame(spec, fr)
    assert b1 is b0
    assert mx.counter_value(
        "tree_hist_hbm_bytes_total", path="rebin") == r0
    with _env(H2O3_TPU_TREE_U8CACHE="0"):
        b2 = bin_frame(spec, fr)
    assert b2 is not b0
    np.testing.assert_array_equal(np.asarray(b2), np.asarray(b0))
    assert mx.counter_value(
        "tree_hist_hbm_bytes_total", path="rebin") > r0


def test_u8_cache_off_bit_identical():
    """The forced-off control: cache disabled must score identically."""
    fr = Frame.from_pandas(_dense_df(seed=15))
    p0 = _pred(_train(fr), fr)
    with _env(H2O3_TPU_TREE_U8CACHE="0"):
        p1 = _pred(_train(fr), fr)
    np.testing.assert_array_equal(p0, p1)


def test_u8_cache_saves_rebin_traffic_across_builds():
    """Two same-spec builds over one frame: the second must add no rebin
    bytes (the wave-2 A/B's >=2x frame-traffic cut comes from here)."""
    fr = Frame.from_pandas(_dense_df(seed=16))
    _train(fr)
    r1 = mx.counter_value("tree_hist_hbm_bytes_total", path="rebin")
    _train(fr)
    assert mx.counter_value(
        "tree_hist_hbm_bytes_total", path="rebin") == r1


# ---------------------------------------------------------------------------
# uplift through the fused whole-tree program (satellite a)


def _uplift_frame(n=4000, seed=17):
    rng = np.random.default_rng(seed)
    x0 = rng.normal(size=n)
    x1 = rng.normal(size=n)
    treat = rng.integers(0, 2, n)
    p = 0.3 + 0.3 * treat * (x0 > 0)
    y = (rng.random(n) < p).astype(int)
    df = pd.DataFrame(
        {"x0": x0, "x1": x1,
         "treatment": np.where(treat, "treatment", "control"),
         "y": y.astype(str)})
    return Frame.from_pandas(
        df, column_types={"y": "enum", "treatment": "enum"})


def test_uplift_fused_fallback_quiet():
    """Uplift's 4-lane scan now rides the fused whole-tree program: the
    tree_fused_fallbacks_total{reason=uplift} counter must stay quiet."""
    from h2o3_tpu.models import UpliftDRF

    fr = _uplift_frame()
    f0 = mx.counter_value("tree_fused_fallbacks_total", reason="uplift")
    UpliftDRF(ntrees=4, max_depth=3, treatment_column="treatment",
              uplift_metric="KL", seed=11).train(y="y", training_frame=fr)
    assert mx.counter_value(
        "tree_fused_fallbacks_total", reason="uplift") == f0


def test_uplift_fused_matches_legacy_loop():
    """Fused whole-tree uplift must reproduce the per-level legacy loop's
    predictions bit-for-bit (the loop early-breaks, the program pads with
    inert all-leaf levels — same trees either way)."""
    from h2o3_tpu.models import UpliftDRF

    fr = _uplift_frame(seed=18)
    kw = dict(ntrees=4, max_depth=3, treatment_column="treatment",
              uplift_metric="KL", seed=11)
    u_fused = UpliftDRF(**kw).train(y="y", training_frame=fr)._predict_raw(fr)
    with _env(H2O3_TPU_WHOLE_TREE="0"):
        u_legacy = UpliftDRF(**kw).train(
            y="y", training_frame=fr)._predict_raw(fr)
    np.testing.assert_array_equal(np.asarray(u_fused), np.asarray(u_legacy))
