"""One end-to-end 'airlines demo' scenario — the classic upstream workflow
(import a messy CSV with dates/enums/NAs, munge, split, train several
families, compare, export, score offline) run against this framework
exactly as a migrating H2O user would write it. Upstream analog: the
airlines pyunit/demo family [UNVERIFIED, SURVEY.md §4]."""

import numpy as np
import pandas as pd
import pytest

import h2o3_tpu


def _airline_csv(path, n=4000, seed=0):
    rng = np.random.default_rng(seed)
    dep_time = rng.integers(0, 2400, n)
    distance = rng.integers(100, 3000, n).astype(float)
    carrier = rng.choice(["AA", "UA", "DL", "WN", "B6"], n)
    origin = rng.choice(["SFO", "JFK", "ORD", "ATL", "DEN", "LAX"], n)
    dow = rng.integers(1, 8, n)
    date = pd.to_datetime("2008-01-01") + pd.to_timedelta(
        rng.integers(0, 365, n), unit="D"
    )
    # delay depends on carrier, hour, distance — learnable signal
    eta = (
        (carrier == "WN") * 0.8
        + (dep_time / 2400.0) * 1.5
        - (distance / 3000.0)
        + (dow >= 6) * 0.4
        + rng.normal(size=n) * 0.8
    )
    delayed = np.where(eta > 0.6, "YES", "NO")
    df = pd.DataFrame({
        "Date": date.strftime("%Y-%m-%d"),
        "DepTime": dep_time.astype(float),
        "UniqueCarrier": carrier,
        "Origin": origin,
        "DayOfWeek": dow.astype(float),
        "Distance": distance,
        "IsDepDelayed": delayed,
    })
    # realistic mess: missing values in numeric + enum columns
    df.loc[rng.choice(n, 200, replace=False), "DepTime"] = np.nan
    df.loc[rng.choice(n, 150, replace=False), "Origin"] = None
    df.to_csv(path, index=False)
    return df


@pytest.mark.slow
def test_airline_end_to_end(tmp_path):
    csv = tmp_path / "allyears_tiny.csv"
    _airline_csv(csv)

    # -- import + inspect ---------------------------------------------------
    fr = h2o3_tpu.import_file(str(csv))
    assert fr.nrow == 4000 and fr.ncol == 7
    assert fr.vec("UniqueCarrier").is_categorical()
    assert fr.vec("IsDepDelayed").is_categorical()
    assert fr.vec("Distance").is_numeric()

    # -- munge: filter + derived column via the ops surface -----------------
    night = (fr.vec("DepTime") >= 2200) | (fr.vec("DepTime") <= 500)
    assert 0 < float(np.nansum(night.to_numpy())) < 4000

    # -- split + train three families ---------------------------------------
    train, test = fr.split_frame([0.8], seed=42)
    feats = ["DepTime", "UniqueCarrier", "Origin", "DayOfWeek", "Distance"]
    from h2o3_tpu.estimators import (
        H2OGeneralizedLinearEstimator,
        H2OGradientBoostingEstimator,
        H2ORandomForestEstimator,
    )

    models = {}
    gbm = H2OGradientBoostingEstimator(ntrees=20, max_depth=4, seed=1)
    gbm.train(x=feats, y="IsDepDelayed", training_frame=train,
              validation_frame=test)
    models["gbm"] = gbm
    glm = H2OGeneralizedLinearEstimator(family="binomial", lambda_=1e-4)
    glm.train(x=feats, y="IsDepDelayed", training_frame=train,
              validation_frame=test)
    models["glm"] = glm
    drf = H2ORandomForestEstimator(ntrees=20, max_depth=8, seed=1)
    drf.train(x=feats, y="IsDepDelayed", training_frame=train,
              validation_frame=test)
    models["drf"] = drf

    # every family learns the signal out of sample
    for name, m in models.items():
        auc = m.auc(valid=True)
        assert auc > 0.65, (name, auc)
    # trees should beat the linear model on this nonlinear signal
    assert max(models["gbm"].auc(valid=True), models["drf"].auc(valid=True)) \
        >= models["glm"].auc(valid=True) - 0.02

    # -- varimp names come from the original columns ------------------------
    vi_cols = {r["variable"].split(".")[0] for r in gbm.varimp()}
    assert vi_cols <= set(feats)

    # -- predict + threshold metrics on held-out data -----------------------
    pred = gbm.predict(test)
    assert pred.names[0] == "predict" and pred.nrow == test.nrow
    perf = gbm.model_performance(test)
    assert 0.0 < perf.value("logloss") < 1.0
    assert perf.gains_lift() and perf.gains_lift()[0]["lift"] > 1.0

    # -- offline scoring round-trip (the deployment contract) ---------------
    mojo_path = str(tmp_path / "airline_gbm.zip")
    gbm.download_mojo(mojo_path)
    from h2o3_tpu.genmodel import MojoModel

    mojo = MojoModel.load(mojo_path)
    tdf = pd.read_csv(csv).iloc[:500]
    offline = mojo.predict({c: tdf[c].to_numpy() for c in feats})
    online = gbm.predict(fr)
    on_lab = online.vec("predict")
    on_500 = np.asarray(on_lab.levels())[on_lab.to_numpy().astype(int)[:500]]
    agree = float(np.mean(offline["predict"][:500] == on_500))
    assert agree > 0.999, agree
