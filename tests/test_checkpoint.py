"""Checkpoint / continuation tests — SURVEY.md §5.4: GBM/DRF continue with
more trees, DL with more epochs, grids recover from export_checkpoints_dir,
frames export. The kill-and-resume contract: an interrupted-then-continued
run must reproduce the uninterrupted run's final metrics."""

import numpy as np
import pandas as pd
import pytest

import h2o3_tpu
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models import DRF, GBM, DeepLearning
from h2o3_tpu.models.grid import GridSearch, load_grid


def _df(n=2500, seed=3):
    rng = np.random.default_rng(seed)
    df = pd.DataFrame({
        "a": rng.normal(size=n),
        "b": rng.normal(size=n),
        "c": rng.choice(["x", "y", "z"], n),
    })
    eta = df["a"] * 1.5 + (df["c"] == "x") * 2 - df["b"]
    df["y"] = np.where(eta + rng.normal(size=n) > 0, "p", "n")
    return df


def test_gbm_checkpoint_resume_identical_to_uninterrupted():
    df = _df()
    fr = Frame.from_pandas(df)
    kw = dict(max_depth=3, seed=11, learn_rate=0.2, score_tree_interval=100)

    full = GBM(ntrees=10, **kw).train(y="y", training_frame=fr)
    part = GBM(ntrees=4, **kw).train(y="y", training_frame=fr)
    resumed = GBM(ntrees=10, checkpoint=part.key, **kw).train(y="y", training_frame=fr)

    assert resumed.output["ntrees_actual"] == 10
    np.testing.assert_allclose(
        resumed.training_metrics.logloss, full.training_metrics.logloss, atol=1e-6
    )
    # predictions agree row-wise, not just in aggregate
    pa = full.predict(fr).vec("p").to_numpy()
    pb = resumed.predict(fr).vec("p").to_numpy()
    np.testing.assert_allclose(pa, pb, atol=1e-5)


def test_gbm_checkpoint_with_sampling_resumes_exactly():
    df = _df(seed=5)
    fr = Frame.from_pandas(df)
    kw = dict(max_depth=3, seed=17, sample_rate=0.7, score_tree_interval=100)
    full = GBM(ntrees=8, **kw).train(y="y", training_frame=fr)
    part = GBM(ntrees=3, **kw).train(y="y", training_frame=fr)
    resumed = GBM(ntrees=8, checkpoint=part.key, **kw).train(y="y", training_frame=fr)
    np.testing.assert_allclose(
        resumed.training_metrics.logloss, full.training_metrics.logloss, atol=1e-6
    )


def test_drf_checkpoint_adds_trees():
    df = _df(seed=7)
    fr = Frame.from_pandas(df)
    kw = dict(max_depth=6, seed=9, score_tree_interval=100)
    part = DRF(ntrees=3, **kw).train(y="y", training_frame=fr)
    resumed = DRF(ntrees=7, checkpoint=part.key, **kw).train(y="y", training_frame=fr)
    assert resumed.output["ntrees_actual"] == 7
    full = DRF(ntrees=7, **kw).train(y="y", training_frame=fr)
    np.testing.assert_allclose(
        resumed.training_metrics.auc, full.training_metrics.auc, atol=1e-6
    )


def test_checkpoint_validation_rejects_changed_params():
    df = _df(seed=8)
    fr = Frame.from_pandas(df)
    part = GBM(ntrees=3, max_depth=3, seed=1).train(y="y", training_frame=fr)
    with pytest.raises(Exception, match="max_depth"):
        GBM(ntrees=6, max_depth=5, seed=1, checkpoint=part.key).train(
            y="y", training_frame=fr
        )
    with pytest.raises(Exception, match="ntrees"):
        GBM(ntrees=2, max_depth=3, seed=1, checkpoint=part.key).train(
            y="y", training_frame=fr
        )


def test_deeplearning_checkpoint_continues_epochs():
    df = _df(seed=9)
    fr = Frame.from_pandas(df)
    kw = dict(hidden=[8], seed=4, mini_batch_size=64)
    part = DeepLearning(epochs=2, **kw).train(y="y", training_frame=fr)
    resumed = DeepLearning(epochs=5, checkpoint=part.key, **kw).train(
        y="y", training_frame=fr
    )
    assert resumed.output["epochs_trained"] == 5
    assert len(resumed.scoring_history) == 3  # only the 3 new epochs ran
    assert resumed.training_metrics.logloss <= part.training_metrics.logloss + 0.05


def test_grid_checkpoint_dir_resume(tmp_path):
    df = _df(seed=10)
    fr = Frame.from_pandas(df)
    ckdir = str(tmp_path / "grid_ck")

    gs1 = GridSearch(
        GBM, {"max_depth": [2, 3]}, grid_id="g_ck", seed=2, ntrees=3,
        export_checkpoints_dir=ckdir,
    )
    g1 = gs1.train(y="y", training_frame=fr)
    assert len(g1.models) == 2

    # wipe the in-memory registry, rebuild the same grid: everything recovers
    built_keys = [m.key for m in g1.models]
    for k in built_keys:
        h2o3_tpu.remove(k)
    gs2 = GridSearch(
        GBM, {"max_depth": [2, 3]}, grid_id="g_ck", seed=2, ntrees=3,
        export_checkpoints_dir=ckdir,
    )
    g2 = gs2.train(y="y", training_frame=fr)
    assert sorted(m.key for m in g2.models) == sorted(built_keys)

    # cold reload via load_grid
    for k in built_keys:
        h2o3_tpu.remove(k)
    g3 = load_grid(ckdir, "g_ck")
    assert len(g3.models) == 2
    assert g3.best_model() is not None


def test_frame_export_roundtrip(tmp_path):
    df = _df(seed=12)
    fr = Frame.from_pandas(df)
    csv = str(tmp_path / "out.csv")
    pq = str(tmp_path / "out.parquet")
    h2o3_tpu.export_file(fr, csv)
    h2o3_tpu.export_file(fr, pq)
    back = pd.read_csv(csv)
    assert len(back) == fr.nrow and list(back.columns) == fr.names
    backp = pd.read_parquet(pq)
    np.testing.assert_allclose(
        backp["a"].to_numpy(), fr.vec("a").to_numpy(), atol=1e-6
    )
    with pytest.raises(FileExistsError):
        h2o3_tpu.export_file(fr, csv)
