"""XGBoost param-surface tests — SURVEY §7 step 9 / §2.4: the hist engine is
the ``h2o-ext-xgboost`` successor; these pin the translation onto GBM."""

import numpy as np
import pandas as pd
import pytest

import h2o3_tpu
from h2o3_tpu.models.tree.gbm import GBM
from h2o3_tpu.models.tree.xgboost import XGBoost, XGBoostParams


@pytest.fixture(scope="module")
def bin_frame():
    rng = np.random.default_rng(7)
    X = rng.normal(size=(3000, 5)).astype(np.float32)
    y = X[:, 0] + 0.6 * X[:, 1] ** 2 + rng.normal(size=3000) * 0.4 > 0.4
    df = pd.DataFrame(X, columns=[f"f{i}" for i in range(5)])
    df["label"] = np.where(y, "y", "n")
    return h2o3_tpu.upload_file(df)


def test_alias_translation():
    b = XGBoost(
        eta=0.2, subsample=0.8, colsample_bytree=0.7, min_child_weight=3,
        max_bin=64, gamma=0.01, n_estimators=7, response_column="label",
    )
    p = b.params
    assert p.learn_rate == 0.2
    assert p.sample_rate == 0.8
    assert p.col_sample_rate_per_tree == 0.7
    assert p.min_rows == 3
    assert p.nbins == 64
    assert p.min_split_improvement == 0.01
    assert p.ntrees == 7


def test_alias_conflict_rejected():
    with pytest.raises(ValueError, match="aliases"):
        XGBoost(eta=0.2, learn_rate=0.3)


def test_xgboost_defaults_differ_from_gbm():
    p = XGBoostParams()
    assert p.learn_rate == 0.3 and p.max_depth == 6 and p.min_rows == 1.0
    assert p.reg_lambda == 1.0 and p.reg_alpha == 0.0


def test_booster_and_grow_policy_validation():
    with pytest.raises(ValueError, match="gbtree"):
        XGBoost(booster="gblinear")
    with pytest.raises(ValueError, match="lossguide"):
        XGBoost(grow_policy="lossguide")
    with pytest.raises(ValueError, match="tree_method"):
        XGBoost(tree_method="gpu_hist_nope")
    # exact/approx warn but construct
    XGBoost(tree_method="exact")


def test_max_bin_clamped():
    b = XGBoost(max_bin=4096)
    assert b.params.nbins == 255


def test_unregularized_xgboost_equals_gbm(bin_frame):
    """λ=0, α=0 and matched params ⇒ identical trees to GBM (same engine)."""
    shared = dict(
        ntrees=5, max_depth=4, min_rows=10.0, seed=11,
        min_split_improvement=1e-5,
    )
    g = GBM(learn_rate=0.3, **shared).train(y="label", training_frame=bin_frame)
    x = XGBoost(eta=0.3, reg_lambda=0.0, reg_alpha=0.0, **shared).train(
        y="label", training_frame=bin_frame
    )
    pg = g.predict(bin_frame).vec("y").to_numpy()
    px = x.predict(bin_frame).vec("y").to_numpy()
    np.testing.assert_allclose(px, pg, rtol=0, atol=0)


def test_reg_lambda_shrinks_leaves(bin_frame):
    kw = dict(ntrees=5, max_depth=4, seed=11, reg_alpha=0.0)
    m0 = XGBoost(reg_lambda=0.0, **kw).train(y="label", training_frame=bin_frame)
    m5 = XGBoost(reg_lambda=50.0, **kw).train(y="label", training_frame=bin_frame)
    p0 = m0.predict(bin_frame).vec("y").to_numpy()
    p5 = m5.predict(bin_frame).vec("y").to_numpy()
    # heavier L2 pulls scores toward the prior: less spread
    assert np.std(p5) < np.std(p0)
    assert m5.training_metrics.auc > 0.6  # still learns


def test_reg_alpha_large_kills_leaves(bin_frame):
    m = XGBoost(
        ntrees=3, max_depth=3, seed=11, reg_lambda=0.0, reg_alpha=1e9
    ).train(y="label", training_frame=bin_frame)
    p = m.predict(bin_frame).vec("y").to_numpy()
    # soft-threshold wipes every leaf: predictions collapse to the init score
    assert float(np.ptp(p)) < 1e-6


def test_scale_pos_weight(bin_frame):
    m1 = XGBoost(ntrees=5, max_depth=3, seed=3).train(
        y="label", training_frame=bin_frame
    )
    m5 = XGBoost(ntrees=5, max_depth=3, seed=3, scale_pos_weight=5.0).train(
        y="label", training_frame=bin_frame
    )
    p1 = m1.predict(bin_frame).vec("y").to_numpy()
    p5 = m5.predict(bin_frame).vec("y").to_numpy()
    # up-weighting positives raises predicted positive probability on average
    assert p5.mean() > p1.mean()


def test_estimator_and_rest_surface(bin_frame):
    from h2o3_tpu.estimators import H2OXGBoostEstimator

    est = H2OXGBoostEstimator(ntrees=3, max_depth=3, eta=0.3, seed=1)
    est.train(y="label", training_frame=bin_frame)
    assert est.model.algo == "xgboost"
    assert est.model_performance().auc > 0.6
    # REST: algo registered
    from h2o3_tpu.api.server import _ALGOS

    assert "xgboost" in _ALGOS


def test_mojo_parity(bin_frame, tmp_path):
    m = XGBoost(ntrees=3, max_depth=3, seed=5).train(
        y="label", training_frame=bin_frame
    )
    path = m.download_mojo(str(tmp_path / "xgb.zip"))
    from h2o3_tpu.genmodel import MojoModel

    scorer = MojoModel.load(path)
    df = pd.DataFrame(
        {f"f{i}": np.random.default_rng(0).normal(size=50) for i in range(5)}
    )
    server_pred = m.predict(h2o3_tpu.upload_file(df)).vec("y").to_numpy()
    offline = scorer.predict(df)  # dict[str, np.ndarray]
    np.testing.assert_allclose(offline["y"], server_pred, atol=1e-5)


def test_max_delta_step_zero_means_unlimited():
    b = XGBoost(max_delta_step=0.0)
    assert b.params.max_abs_leafnode_pred == float("inf")
    b = XGBoost(max_delta_step=0.7)
    assert b.params.max_abs_leafnode_pred == 0.7
    with pytest.raises(ValueError, match=">= 0"):
        XGBoost(max_delta_step=-1.0)


def test_scale_pos_weight_validation():
    with pytest.raises(ValueError, match="scale_pos_weight"):
        XGBoost(scale_pos_weight=0.0)


def test_checkpoint_freezes_regularization(bin_frame):
    m1 = XGBoost(ntrees=3, max_depth=3, seed=2, reg_lambda=1.0).train(
        y="label", training_frame=bin_frame
    )
    with pytest.raises(RuntimeError, match="reg_lambda"):
        XGBoost(
            ntrees=6, max_depth=3, seed=2, reg_lambda=100.0, checkpoint=m1
        ).train(y="label", training_frame=bin_frame)


def test_rest_alias_parsing():
    from h2o3_tpu.api.server import Endpoints
    from h2o3_tpu.models.tree.xgboost import XGBoost as XGB

    kwargs, x, y, tk, vk = Endpoints._parse_build_params(
        None, XGB, {"eta": "0.05", "max_bin": "64", "response_column": "label"}
    )
    b = XGB(**kwargs)
    assert b.params.learn_rate == 0.05
    assert b.params.nbins == 64
