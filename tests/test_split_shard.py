"""Column-sharded split pipeline (ISSUE 5): the histogram reduce-scatter +
blockwise split scan + per-block winner merge must be INDISTINGUISHABLE from
the replicated path — split decisions, predictions and varimp bit-equal on
1-, 2- and 8-device meshes, including under adversarial exact ties where the
merge's tie-break must reproduce ``jnp.argmax``'s lowest-global-index rule.
"""

import contextlib
import os

import numpy as np
import pandas as pd
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from h2o3_tpu.models.tree import shared_tree as st
from h2o3_tpu.parallel import mesh as pm


@contextlib.contextmanager
def _use_mesh(k: int):
    """Run under a k-device sub-mesh of the 8-device CPU test cloud."""
    devs = jax.devices("cpu")
    assert len(devs) >= k, "8-device conftest pin did not land"
    old = pm._mesh
    pm.set_mesh(Mesh(np.array(devs[:k]), (pm.ROWS_AXIS,)))
    try:
        yield
    finally:
        pm.set_mesh(old)


@contextlib.contextmanager
def _env(**kv):
    old = {k: os.environ.get(k) for k in kv}
    os.environ.update({k: str(v) for k, v in kv.items()})
    try:
        yield
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _bits(a) -> bytes:
    return np.ascontiguousarray(np.asarray(a)).tobytes()


def _tree_fields(tree: st.Tree) -> list[dict]:
    host = tree.to_host()
    return [
        {
            "split_col": lv.split_col, "split_bin": lv.split_bin,
            "is_cat": lv.is_cat, "cat_mask": lv.cat_mask,
            "na_left": lv.na_left, "leaf_now": lv.leaf_now,
            "leaf_val": lv.leaf_val, "child_base": lv.child_base,
            "gain": lv.gain, "node_w": lv.node_w,
        }
        for lv in host.levels
    ]


def _assert_trees_bit_equal(a: st.Tree, b: st.Tree, what: str):
    fa, fb = _tree_fields(a), _tree_fields(b)
    assert len(fa) == len(fb), what
    for li, (la, lb) in enumerate(zip(fa, fb)):
        for k in la:
            assert _bits(la[k]) == _bits(lb[k]), (
                f"{what}: level {li} field {k} diverged between sharded and "
                f"replicated split pipelines"
            )


def _build_one(bins_np, t_np, *, split_shard: int, max_depth=3, n_bins=16,
               node_cap=2048, min_rows=1.0, env=None, is_cat=None, seed=5):
    """build_tree under the given H2O3_TPU_SPLIT_SHARD, on the CURRENT mesh."""
    n, C = bins_np.shape
    with _env(H2O3_TPU_SPLIT_SHARD=split_shard, **(env or {})):
        bins = pm.shard_rows(jnp.asarray(bins_np))
        w = pm.shard_rows(jnp.ones(n, jnp.float32))
        t = pm.shard_rows(jnp.asarray(t_np, dtype=jnp.float32))
        h = pm.shard_rows(jnp.ones(n, jnp.float32))
        preds = pm.shard_rows(jnp.zeros(n, jnp.float32))
        tree, preds, varimp = st.build_tree(
            bins, w, t, h,
            n_bins=n_bins,
            is_cat_cols=(np.zeros(C, bool) if is_cat is None else is_cat),
            max_depth=max_depth,
            min_rows=min_rows,
            min_split_improvement=0.0,
            learn_rate=0.1,
            preds=preds,
            key=jax.random.PRNGKey(seed),
            varimp=jnp.zeros(C, jnp.float32),
            node_cap=node_cap,
        )
        return tree, np.asarray(preds), np.asarray(varimp)


def _pad_rows(n_raw: int) -> int:
    return pm.pad_to_shards(n_raw)


def _tie_data(n_pad: int, C: int, n_bins: int, dup_all: bool, seed=0):
    """Adversarial exact-tie data: every weight is 1.0 and every target is
    integer-valued, so histogram sums are exact in f32 and candidate gains
    that tie mathematically tie BIT-exactly. ``dup_all=True`` additionally
    duplicates one column into every column — identical gains in every
    block, so only the lowest-global-index tie-break can pick the winner."""
    rng = np.random.default_rng(seed)
    base = rng.integers(1, n_bins, n_pad).astype(np.uint8)
    if dup_all:
        bins = np.tile(base[:, None], (1, C))
    else:
        bins = rng.integers(1, n_bins, (n_pad, C)).astype(np.uint8)
        bins[:, C // 2:] = bins[:, : C - C // 2]  # mirror block-spanning dups
    t = np.ones(n_pad, np.float32)  # constant target: EVERY candidate gain
    # is exactly 0.0 (wy == w, sums exact) — maximal tie pressure
    return bins, t


@pytest.mark.parametrize("k", [1, 2, 8])
def test_tie_break_constant_target_all_columns_tie(k):
    """Constant target: every (col, bin) candidate's gain is exactly 0.0 in
    every block. jnp.argmax resolves to the lowest bin of the lowest column;
    the sharded merge must land on the identical choice on any mesh."""
    with _use_mesh(k):
        n_pad = _pad_rows(960)
        bins, t = _tie_data(n_pad, C=13, n_bins=16, dup_all=True)
        t1, p1, v1 = _build_one(bins, t, split_shard=1)
        t0, p0, v0 = _build_one(bins, t, split_shard=0)
        _assert_trees_bit_equal(t1, t0, f"ties/{k}dev")
        assert _bits(p1) == _bits(p0)
        assert _bits(v1) == _bits(v0)
        # the replicated argmax picks global column 0 when everything ties;
        # a merge that preferred a later block (or a local index without the
        # block offset) would record a different column
        assert int(np.asarray(t1.levels[0].split_col)[0]) == 0


@pytest.mark.parametrize("k", [2, 8])
def test_tie_break_duplicated_columns_nonzero_gains(k):
    """Duplicated columns with a real signal: identical NON-zero best gains
    appear in several blocks at once; the winner must be the lowest global
    column index (bit-exact vs the replicated scan)."""
    with _use_mesh(k):
        n_pad = _pad_rows(960)
        rng = np.random.default_rng(3)
        bins, _ = _tie_data(n_pad, C=16, n_bins=16, dup_all=True, seed=3)
        t = (rng.integers(0, 2, n_pad) * 2 - 1).astype(np.float32)
        t1, p1, v1 = _build_one(bins, t, split_shard=1, max_depth=4)
        t0, p0, v0 = _build_one(bins, t, split_shard=0, max_depth=4)
        _assert_trees_bit_equal(t1, t0, f"dup-cols/{k}dev")
        assert _bits(p1) == _bits(p0) and _bits(v1) == _bits(v0)
        # every split must sit on column 0 — all 16 columns are copies
        masks = t0.real_level_masks()
        for lv, m in zip(t0.levels, masks):
            split = ~np.asarray(lv.leaf_now) & m
            assert (np.asarray(lv.split_col)[split] == 0).all()


@pytest.mark.parametrize("subtract", ["1", "0"])
def test_parity_both_force_leaf_paths(subtract):
    """Both terminal-level regimes: subtract=1 derives the last level's leaf
    stats from the parents' chosen splits (no histogram at all); subtract=0
    builds a terminal histogram and force-leafs from its totals."""
    n_pad = _pad_rows(700)
    rng = np.random.default_rng(7)
    bins = rng.integers(0, 16, (n_pad, 7)).astype(np.uint8)  # 7 % 8 != 0
    t = rng.normal(size=n_pad).astype(np.float32)
    env = {"H2O3_TPU_HIST_SUBTRACT": subtract}
    t1, p1, v1 = _build_one(bins, t, split_shard=1, env=env)
    t0, p0, v0 = _build_one(bins, t, split_shard=0, env=env)
    _assert_trees_bit_equal(t1, t0, f"force-leaf/subtract={subtract}")
    assert _bits(p1) == _bits(p0) and _bits(v1) == _bits(v0)


def test_parity_coarsened_saturated_levels():
    """Deep tree with a small node_cap and bin adaptivity on: the saturated
    while_loop region runs at COARSENED bins — the sharded scan must stay
    bit-equal through the coarsen + sibling-subtraction carry."""
    n_pad = _pad_rows(600)
    rng = np.random.default_rng(11)
    bins = rng.integers(0, 255, (n_pad, 6)).astype(np.uint8)
    t = rng.normal(size=n_pad).astype(np.float32)
    env = {"H2O3_TPU_BIN_ADAPT": "1", "H2O3_TPU_SHAPE_BUCKETS": "0"}
    kw = dict(max_depth=8, n_bins=255, node_cap=8)
    t1, p1, v1 = _build_one(bins, t, split_shard=1, env=env, **kw)
    t0, p0, v0 = _build_one(bins, t, split_shard=0, env=env, **kw)
    # the saturated region must actually exist for this shape, or the test
    # is not exercising the coarsened while_loop at all
    shifts = st._bin_shifts(8, 255, ())
    assert st._sat_region(8, 8, shifts)[1] >= 2
    _assert_trees_bit_equal(t1, t0, "coarsened-sat")
    assert _bits(p1) == _bits(p0) and _bits(v1) == _bits(v0)


def test_parity_categorical_and_model_level():
    """End-to-end GBM with categorical columns: predictions, varimp and the
    canonical records are bit-equal between the pipelines."""
    rng = np.random.default_rng(0)
    n = 2000
    X = rng.normal(size=(n, 5))
    df = pd.DataFrame(X, columns=[f"x{i}" for i in range(5)])
    df["c0"] = pd.Categorical(rng.choice(list("abcdefg"), n))
    df["c1"] = pd.Categorical(rng.choice(list("uvwxyz"), n))
    df["y"] = (
        X[:, 0] * 2 - X[:, 1]
        + (df["c0"].cat.codes.to_numpy() % 3)
        + 0.3 * rng.normal(size=n)
    )

    def run(shard):
        with _env(H2O3_TPU_SPLIT_SHARD=shard):
            from h2o3_tpu.frame.frame import Frame
            from h2o3_tpu.models.tree import GBM

            fr = Frame.from_pandas(df)
            m = GBM(
                ntrees=4, max_depth=4, seed=7, distribution="gaussian",
                col_sample_rate=0.7, sample_rate=0.8,
            ).train(y="y", training_frame=fr)
            p = np.asarray(m.predict(fr).vec("predict").to_numpy())
            vi = [
                (r["variable"], float(r["relative_importance"]))
                for r in m.varimp()
            ]
            return p, vi

    p1, v1 = run(1)
    p0, v0 = run(0)
    assert _bits(p1.astype(np.float64)) == _bits(p0.astype(np.float64))
    assert v1 == v0


def test_collective_byte_counters_measure_the_claim():
    """tree_collective_bytes_total{phase}: the sharded pipeline's
    hist-reduce volume must undercut the replicated one >= 2x (it is 1/P
    by construction), and the winner gather must be accounted (nonzero)
    yet small next to the histogram traffic it replaces."""
    from h2o3_tpu.utils import metrics as mx

    n_pad = _pad_rows(700)
    rng = np.random.default_rng(19)
    bins = rng.integers(0, 32, (n_pad, 28)).astype(np.uint8)  # bench C=28
    t = rng.normal(size=n_pad).astype(np.float32)

    def bytes_for(shard):
        before_h = mx.counter_value(
            "tree_collective_bytes_total", phase="hist_reduce")
        before_w = mx.counter_value(
            "tree_collective_bytes_total", phase="winner_gather")
        _build_one(bins, t, split_shard=shard, n_bins=32, seed=23)
        return (
            mx.counter_value(
                "tree_collective_bytes_total", phase="hist_reduce") - before_h,
            mx.counter_value(
                "tree_collective_bytes_total", phase="winner_gather") - before_w,
        )

    h1, w1 = bytes_for(1)
    h0, w0 = bytes_for(0)
    assert h0 > 0 and h1 > 0
    assert w0 == 0  # replicated path has no winner gather
    assert w1 > 0
    assert h0 >= 2 * (h1 + w1), (h0, h1, w1)


def test_hist_override_scatter_reaches_scatter_impl():
    from h2o3_tpu.ops import histogram as hg

    with _env(H2O3_TPU_HIST="scatter"):
        assert hg._select_local() is hg._hist_scatter_local
    with _env(H2O3_TPU_HIST="matmul"):
        assert hg._select_local() is hg._hist_matmul_local


def test_sharded_histogram_bit_equal_and_padded():
    """histogram_in_jit(col_sharded=True): each column block is bit-equal to
    the replicated psum's slice; divisibility padding columns are all-zero
    (C=7 on an 8-device mesh exercises C < P block padding)."""
    from h2o3_tpu.ops.histogram import histogram_in_jit

    rng = np.random.default_rng(2)
    n, C, N, B = _pad_rows(2000), 7, 8, 16
    bins = pm.shard_rows(jnp.asarray(rng.integers(0, B, (n, C)), jnp.uint8))
    nid = pm.shard_rows(jnp.asarray(rng.integers(-1, N, n), jnp.int32))
    w = pm.shard_rows(jnp.asarray(rng.random(n), jnp.float32))
    wy = pm.shard_rows(jnp.asarray(rng.normal(size=n), jnp.float32))
    rep = jax.jit(
        lambda b, i, *s: histogram_in_jit(b, i, s, N, B)
    )(bins, nid, w, wy, w)
    shd = jax.jit(
        lambda b, i, *s: histogram_in_jit(b, i, s, N, B, col_sharded=True)
    )(bins, nid, w, wy, w)
    rep, shd = np.asarray(rep), np.asarray(shd)
    Cp = pm.pad_cols_to_shards(C)
    assert shd.shape[1] == Cp
    assert _bits(rep) == _bits(shd[:, :C])
    assert not shd[:, C:].any()
