"""Stored-expectation accuracy regression suite — ``h2o-test-accuracy/``
successor (SURVEY.md §4): flagship algos on fixed seeded datasets compared
against checked-in expected metrics, with NO runtime sklearn dependency.

On drift: either a bug crept in (fix it) or an intentional algorithm change
moved metrics — then regenerate with ``python tools/gen_accuracy_expectations.py``
and review the JSON diff.
"""

import json
import pathlib

import pytest

from accuracy_cases import TOLERANCES, run_cases

EXPECT = pathlib.Path(__file__).parent / "accuracy_expectations.json"


@pytest.fixture(scope="module")
def results():
    return run_cases()


def _expected():
    return json.loads(EXPECT.read_text())


def test_expectation_file_exists():
    assert EXPECT.exists(), "regenerate with tools/gen_accuracy_expectations.py"


@pytest.mark.parametrize("case", sorted(_expected()))
def test_case_matches_expectation(results, case):
    expected = _expected()[case]
    assert case in results, f"case {case} no longer produced"
    for metric, want in expected.items():
        got = results[case][metric]
        tol = TOLERANCES[metric]
        assert got == pytest.approx(want, abs=tol), (
            f"{case}.{metric}: got {got:.6f}, expected {want:.6f} ±{tol} — "
            "if intentional, regenerate tests/accuracy_expectations.json"
        )


def test_no_unexpected_cases(results):
    # a case added to accuracy_cases.py must also be captured in the JSON
    assert set(results) == set(_expected())
