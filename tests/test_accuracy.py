"""Stored-expectation accuracy regression suite — ``h2o-test-accuracy/``
successor (SURVEY.md §4): flagship algos on fixed seeded datasets compared
against checked-in expected metrics, with NO runtime sklearn dependency.

Default tier runs the fast flagship subset; the slow tier covers every case.
On drift: either a bug crept in (fix it) or an intentional algorithm change
moved metrics — then regenerate with ``python
tools/gen_accuracy_expectations.py`` and review the JSON diff.
"""

import json
import pathlib

import pytest

from accuracy_cases import TOLERANCES, run_cases

EXPECT = pathlib.Path(__file__).parent / "accuracy_expectations.json"
FAST_CASES = ("gbm_binomial", "glm_binomial", "kmeans")


def _expected():
    return json.loads(EXPECT.read_text()) if EXPECT.exists() else {}


def _check(results, case):
    expected = _expected()[case]
    assert case in results, f"case {case} no longer produced"
    for metric, want in expected.items():
        got = results[case][metric]
        tol = TOLERANCES[metric]
        assert got == pytest.approx(want, abs=tol), (
            f"{case}.{metric}: got {got:.6f}, expected {want:.6f} ±{tol} — "
            "if intentional, regenerate tests/accuracy_expectations.json"
        )


def test_expectation_file_exists():
    assert EXPECT.exists(), "regenerate with tools/gen_accuracy_expectations.py"


@pytest.fixture(scope="module")
def fast_results():
    return run_cases(cases=FAST_CASES)


@pytest.mark.parametrize("case", [c for c in sorted(_expected()) if c in FAST_CASES])
def test_fast_case_matches_expectation(fast_results, case):
    _check(fast_results, case)


@pytest.mark.slow
class TestFullAccuracy:
    @pytest.fixture(scope="class")
    def results(self):
        return run_cases()

    def test_all_cases(self, results):
        exp = _expected()
        assert set(results) == set(exp)
        for case in sorted(exp):
            _check(results, case)
