"""Overload-survival plane tests (ISSUE 19): memory-aware admission with
per-job HBM reservations, streamed-lane auto-routing, the REST memory gate
and admission storm behavior, RESOURCE_EXHAUSTED catch-and-degrade, the
dispatch hang watchdog, and the H2O3_TPU_OVERLOAD=0 pre-overload pin.

The CPU proxy's devices report no ``memory_stats``, so every headroom-
dependent check injects synthetic stats through ``devmem._stats_fn`` (the
one real call site) and force-polls — no mocks of the plane itself."""

import contextlib
import json
import os
import threading
import time
import urllib.error
import urllib.parse
import urllib.request

import numpy as np
import pandas as pd
import pytest

from h2o3_tpu.cluster import cloud, recovery
from h2o3_tpu.frame import chunkstore as cs
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models import GBM
from h2o3_tpu.utils import devmem, faults, flightrec, overload
from h2o3_tpu.utils import metrics as mx

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _clean(monkeypatch, tmp_path):
    monkeypatch.setenv("H2O3_TPU_INCIDENT_DIR", str(tmp_path / "incidents"))
    monkeypatch.setenv("H2O3_TPU_RECOVERY", "1")
    monkeypatch.setenv("H2O3_TPU_RECOVERY_BACKOFF", "0.01")
    monkeypatch.setenv("H2O3_TPU_OVERLOAD", "1")
    flightrec._reset_incidents_for_tests()
    overload._reset_for_tests()
    cloud.clear_degraded()
    yield
    faults.reset()
    overload._reset_for_tests()
    flightrec._HUNG_SPANS.clear()  # synthetic ring spans must not leak into
    for k in list(devmem.reservations()):  # the live span-id sequence
        devmem.release(k)
    cloud.clear_degraded()


@contextlib.contextmanager
def _env(**kv):
    old = {k: os.environ.get(k) for k in kv}
    os.environ.update({k: str(v) for k, v in kv.items()})
    try:
        yield
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


@contextlib.contextmanager
def _synthetic_stats(in_use, limit):
    """Route devmem's one memory_stats call site through synthetic numbers
    (per local device), force-poll, and restore the proxy's honest None."""
    orig = devmem._stats_fn
    devmem._stats_fn = lambda d: {"bytes_in_use": int(in_use),
                                  "bytes_limit": int(limit)}
    devmem.poll(force=True)
    try:
        yield
    finally:
        devmem._stats_fn = orig
        devmem.poll(force=True)


def _df(n=800, seed=3):
    rng = np.random.default_rng(seed)
    df = pd.DataFrame({
        "a": rng.normal(size=n),
        "b": rng.normal(size=n),
        "c": rng.choice(["x", "y", "z"], n),
    })
    eta = df["a"] * 1.5 + (df["c"] == "x") * 2 - df["b"]
    df["y"] = np.where(eta + rng.normal(size=n) > 0, "p", "n")
    return df


# ---------------------------------------------------------------------------
# admission preflight: resident / streamed / shed routing + reservations


def test_capacity_model_shapes():
    # the admission preflight and tools/tpu_mem_analysis.py share one model
    assert overload.per_row_device_bytes(32, "gbm", compressed=True) == \
        32 + overload.STATE_BYTES
    assert overload.per_row_device_bytes(32, "gbm", compressed=False) == \
        32 * 5 + overload.STATE_BYTES
    assert overload.per_row_device_bytes(10, "glm") == (10 + 3) * 4
    fr = Frame.from_pandas(_df(200))
    est = overload.estimate_build_bytes(fr, "gbm")
    assert est >= fr.npad  # at least one byte/row of binned codes


def test_admit_routes_and_reservation_ledger():
    # per device: limit 1 GiB, in_use 0.5 GiB -> 8 x 0.5 GiB = 4 GiB headroom
    with _synthetic_stats(in_use=1 << 29, limit=1 << 30):
        head = devmem.headroom()
        assert head == pytest.approx(8 * (1 << 29))
        avail = head * 0.7
        # small footprint: resident, full-footprint reservation
        assert overload.admit("job_small", 1 << 20, "gbm") == "resident"
        assert devmem.reservations()["job_small"] == float(1 << 20)
        # huge footprint + compression on: streamed with a headroom window
        with _env(H2O3_TPU_FRAME_COMPRESS="1"):
            assert overload.admit("job_big", 100 << 30, "gbm") == "streamed"
        win = devmem.reservations()["job_big"]
        assert 4 << 20 <= win <= avail
        # reservation gauge publishes per-job series
        snap = mx.REGISTRY.snapshot()["hbm_reserved_bytes"]
        jobs = {v["labels"].get("job") for v in snap["values"]}
        assert {"job_small", "job_big"} <= jobs
        # fits nowhere (streaming unavailable): shed with honest Retry-After
        with _env(H2O3_TPU_FRAME_COMPRESS="0"):
            with pytest.raises(overload.Shed) as ei:
                overload.admit("job_doomed", 100 << 30, "gbm")
        assert ei.value.retry_after >= 1.0
        assert "job_doomed" not in devmem.reservations()
        # release: sums return to zero and the gauge series disappear
        overload.finish("job_small")
        overload.finish("job_big")
        overload.finish("job_big")  # idempotent
        assert devmem.reservations() == {}
        assert devmem.reserved_total() == 0.0
        snap = mx.REGISTRY.snapshot()["hbm_reserved_bytes"]
        assert not [v for v in snap["values"]
                    if v["labels"].get("job") in ("job_small", "job_big")]


def test_admit_unmeasured_headroom_still_reserves():
    # CPU proxy devices report no stats: admitted resident, but the
    # reservation (and so the hold-time estimator) still works
    assert devmem.headroom() is None
    assert overload.admit("job_cpu", 123456, "gbm") == "resident"
    assert devmem.reservations() == {"job_cpu": 123456.0}
    with overload.job_scope("job_other"):
        pass  # scope releases on exit
    overload.finish("job_cpu")
    assert devmem.reservations() == {}


def test_retry_after_scales_with_queue_depth():
    # no completed holds yet: the 5 s prior, clamped to >= 1
    assert overload.retry_after_estimate() == pytest.approx(5.0)
    # finish() feeds the measured hold time into the estimator
    overload._reserve("held", 1)
    time.sleep(0.02)
    overload.finish("held")
    with overload._HOLD_LOCK:
        assert len(overload._HOLDS) == 1 and overload._HOLDS[0] >= 0.02
        overload._HOLDS[0] = 2.0  # deterministic mean for the math below
    assert overload.retry_after_estimate() == pytest.approx(2.0)
    # a deeper live reservation queue means a longer advertised wait
    devmem.reserve("q1", 1)
    devmem.reserve("q2", 1)
    devmem.reserve("q3", 1)
    assert overload.retry_after_estimate() == pytest.approx(6.0)
    # and the estimate clamps into [1, 120]
    with overload._HOLD_LOCK:
        overload._HOLDS[0] = 90.0
    assert overload.retry_after_estimate() == pytest.approx(120.0)
    for k in ("q1", "q2", "q3"):
        devmem.release(k)


def test_job_scope_releases_on_error():
    with pytest.raises(RuntimeError):
        with overload.job_scope("job_err"):
            devmem.reserve("job_err", 7)
            raise RuntimeError("boom")
    assert "job_err" not in devmem.reservations()


# ---------------------------------------------------------------------------
# streamed-lane routing: plan_window + ChunkStore.plan


def test_plan_window_autoroutes_and_excludes_own_reservation():
    with _synthetic_stats(in_use=1 << 29, limit=1 << 30):
        head = devmem.headroom()
        avail = head * 0.7
        # fits the usable share: no override, resident lane
        assert overload.plan_window(avail * 0.5, 0) is None
        # exceeds it: headroom-derived window, at least the 4 MiB floor
        win = overload.plan_window(avail * 4, 0)
        assert win is not None and win >= 4 << 20 and win <= avail
        # an operator window always wins over the auto-route
        assert overload.plan_window(avail * 4, 8 << 20) is None
        # another job's reservation shrinks the share ...
        devmem.reserve("hog", int(avail))
        assert overload.plan_window(avail * 0.5, 0) is not None
        # ... but a job's OWN reservation must not push it to streaming
        with overload.job_scope("hog"):
            assert overload.plan_window(avail * 0.5, 0) is None
        assert devmem.reservations() == {}  # job_scope released "hog"


def test_plan_window_degrade_scope_halves():
    need = 100 << 20
    with overload.degrade_scope():
        assert overload.degrade_active()
        # previously streaming: half the static window
        assert overload.plan_window(need, 8 << 20) == 4 << 20
        # previously resident: half the frame's own footprint
        assert overload.plan_window(need, 0) == need // 2
    assert not overload.degrade_active()
    # outside the scope, no headroom measured: legacy static policy
    assert overload.plan_window(need, 8 << 20) is None


def test_chunkstore_plan_consults_overload_window():
    with _synthetic_stats(in_use=1 << 29, limit=1 << 30):
        avail = devmem.headroom() * 0.7
        npad = 1 << 20
        bpr = max(int(avail * 4 // npad), 8)  # footprint ~4x the usable share
        with _env(H2O3_TPU_HBM_WINDOW_BYTES="0", H2O3_TPU_FRAME_COMPRESS="1"):
            # no static knob: the auto-route streams through a measured-
            # headroom window instead of OOMing resident
            st = cs.ChunkStore.plan(npad, bpr)
            assert st is not None and st.n_blocks > 1
            assert st.window <= avail
            # plane off: the same frame runs resident, exactly as before
            with _env(H2O3_TPU_OVERLOAD="0"):
                assert cs.ChunkStore.plan(npad, bpr) is None


def test_plan_window_disabled_pins_legacy():
    with _env(H2O3_TPU_OVERLOAD="0"):
        assert overload.admit("job_off", 1 << 40, "gbm") == "off"
        assert devmem.reservations() == {}
        with _synthetic_stats(in_use=1 << 29, limit=1 << 30):
            assert overload.plan_window(1 << 40, 0) is None
        with overload.degrade_scope():
            assert overload.plan_window(1 << 40, 0) is None
        assert overload.watchdog_pass() == []


# ---------------------------------------------------------------------------
# REST admission: inflight storm + the memory gate


def _post_status(url, path, payload):
    """POST form-encoded; return (status, retry_after, reason)."""
    data = urllib.parse.urlencode(payload or {}).encode()
    req = urllib.request.Request(url + path, data=data, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=60) as r:
            return r.status, None, None
    except urllib.error.HTTPError as e:
        ra = e.headers.get("Retry-After")
        try:
            reason = json.loads(e.read()).get("reason")
        except Exception:  # noqa: BLE001 — status is the assertion target
            reason = None
        return e.code, (float(ra) if ra else None), reason


def test_rest_admission_storm_sheds_and_recovers():
    from h2o3_tpu.api.server import start_server

    srv = start_server(port=0)
    with _env(H2O3_TPU_MAX_INFLIGHT="2"):
        faults.configure(slow={"rest": 0.6})
        try:
            results = []
            bar = threading.Barrier(6)

            def _one(i):
                bar.wait()
                results.append(_post_status(
                    srv.url, "/3/CreateFrame",
                    {"dest": f"ovst_{i}", "rows": 50, "cols": 2, "seed": i}))

            ts = [threading.Thread(target=_one, args=(i,)) for i in range(6)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=60)
        finally:
            faults.reset()
        statuses = [s for s, _, _ in results]
        assert statuses.count(200) >= 1      # capacity serves
        shed = [r for r in results if r[0] != 200]
        assert shed                           # excess is shed, not queued
        for s, ra, reason in shed:
            assert s in (429, 503)
            assert ra is not None and ra >= 1.0
            assert reason in ("inflight_full", "queue_full", "memory",
                              "draining", "job_queue_full")
    # the storm leaves no reservation behind and the server still serves
    assert devmem.reservations() == {}
    s, _, _ = _post_status(srv.url, "/3/CreateFrame",
                           {"dest": "ovst_after", "rows": 50, "cols": 2})
    assert s == 200


def test_rest_memory_gate_closes_and_reopens():
    from h2o3_tpu.api.server import start_server

    srv = start_server(port=0)
    payload = {"dest": "ovmem", "rows": 50, "cols": 2}
    with _env(H2O3_TPU_ADMIT_MIN_HEADROOM_BYTES=str(64 << 20)):
        # zero measured headroom: every mutating request sheds 503 "memory"
        with _synthetic_stats(in_use=8 << 30, limit=8 << 30):
            s, ra, reason = _post_status(srv.url, "/3/CreateFrame", payload)
            assert s == 503 and reason == "memory"
            assert ra is not None and ra >= 1.0
            assert mx.counter_value("rest_rejected_total", method="POST",
                                    route="/3/CreateFrame",
                                    reason="memory") >= 1
        # stats gone (unmeasured headroom): the gate must not trip on stale
        # numbers — the CPU proxy is never memory-gated
        s, _, _ = _post_status(srv.url, "/3/CreateFrame", payload)
        assert s == 200


def test_client_retries_memory_shed_with_retry_after_floor():
    from h2o3_tpu.api.server import start_server
    from h2o3_tpu.client import H2OClientError, H2OConnection

    srv = start_server(port=0)
    # a short measured hold keeps the computed Retry-After at the 1 s clamp
    with overload._HOLD_LOCK:
        overload._HOLDS.append(0.5)
    orig = devmem._stats_fn
    with _env(H2O3_TPU_ADMIT_MIN_HEADROOM_BYTES=str(64 << 20)):
        devmem._stats_fn = lambda d: {"bytes_in_use": 8 << 30,
                                      "bytes_limit": 8 << 30}
        devmem.poll(force=True)
        try:
            # the machine-readable shed surfaces on a no-retry client
            conn = H2OConnection(srv.url, retries=0)
            with pytest.raises(H2OClientError) as ei:
                conn.post("/3/CreateFrame",
                          {"dest": "cm0", "rows": 50, "cols": 2})
            err = ei.value
            assert err.status == 503 and err.reason == "memory"
            assert err.retry_after is not None and err.retry_after >= 1.0
            # the computed Retry-After floors the client's tiny backoff
            conn.retries = 8
            conn.retry_backoff = 0.01
            assert conn._backoff_delay("/x", 0,
                                       err.retry_after) >= err.retry_after
            # gate reopens while the client backs off: the retry lands
            def _reopen():
                time.sleep(0.3)
                devmem._stats_fn = orig
                devmem.poll(force=True)

            threading.Thread(target=_reopen, daemon=True).start()
            out = conn.post("/3/CreateFrame",
                            {"dest": "cm1", "rows": 50, "cols": 2})
            assert out.get("key") or out.get("job")  # served post-reopen
        finally:
            devmem._stats_fn = orig
            devmem.poll(force=True)


# ---------------------------------------------------------------------------
# OOM catch-and-degrade: one supervised retry under the degrade scope


def test_oom_degrades_once_and_matches_clean_run(tmp_path):
    fr = Frame.from_pandas(_df())
    kw = dict(max_depth=3, seed=11, learn_rate=0.2, score_tree_interval=2)
    full = GBM(ntrees=6, **kw).train(y="y", training_frame=fr)

    ckdir = str(tmp_path / "oomck")
    g0 = cloud.generation()
    retried0 = mx.counter_value("oom_degrades_total", site="tree",
                                outcome="retried")
    recovered0 = mx.counter_value("oom_degrades_total", site="tree",
                                  outcome="recovered")

    def _launch(ckpt):
        kw2 = dict(kw, export_checkpoints_dir=ckdir)
        if ckpt:
            kw2["checkpoint"] = ckpt
        return GBM(ntrees=6, **kw2).train(y="y", training_frame=fr)

    with faults.inject(oom={"tree"}):
        healed = recovery.run_supervised(_launch, ckdir=ckdir, algo="gbm",
                                         description="oom degrade drill")
    # degrade-once, NOT a reform: generation must not tick
    assert cloud.generation() == g0
    assert cloud.degraded_reason() is None
    assert healed.output["ntrees_actual"] == 6
    np.testing.assert_allclose(healed.training_metrics.logloss,
                               full.training_metrics.logloss, atol=1e-6)
    pa = full.predict(fr).vec("p").to_numpy()
    pb = healed.predict(fr).vec("p").to_numpy()
    np.testing.assert_allclose(pa, pb, atol=1e-5)
    assert mx.counter_value("oom_degrades_total", site="tree",
                            outcome="retried") == retried0 + 1
    assert mx.counter_value("oom_degrades_total", site="tree",
                            outcome="recovered") == recovered0 + 1
    # the incident bundle froze the dying state and names the OOM site
    path = flightrec.last_incident()
    assert path and os.path.exists(path)
    with open(path) as f:
        bundle = json.load(f)
    assert bundle["trigger"] == "oom"
    assert "'tree'" in bundle["reason"]
    # the ring kept the classification and the degrade record
    assert [e for e in flightrec.events(kind="oom") if e["site"] == "tree"]
    assert [e for e in flightrec.events(kind="oom_degrade")
            if e.get("site") == "tree"]


def test_oom_disabled_plane_surfaces_error(tmp_path):
    fr = Frame.from_pandas(_df(300, seed=9))

    def _launch(ckpt):
        return GBM(ntrees=4, max_depth=2, seed=1,
                   score_tree_interval=2).train(y="y", training_frame=fr)

    with _env(H2O3_TPU_OVERLOAD="0"):
        with faults.inject(oom={"tree"}):
            with pytest.raises(Exception, match="RESOURCE_EXHAUSTED"):
                recovery.run_supervised(_launch, description="oom off")
    assert cloud.degraded_reason() is None  # no latch: plain job failure


def test_is_oom_classification():
    assert overload.is_oom(RuntimeError("RESOURCE_EXHAUSTED: out of memory"))
    assert overload.is_oom(RuntimeError("Resource_Exhausted allocating"))
    assert not overload.is_oom(RuntimeError("invalid argument"))
    assert overload.oom_site(RuntimeError("invalid argument")) is None


# ---------------------------------------------------------------------------
# dispatch hang watchdog: ring-driven trips with an injectable clock


def _seed_site(site, n, dur_ms, span0=0):
    for i in range(n):
        flightrec.record("dispatch_start", site=site, span=span0 + i)
        flightrec.record("dispatch_end", site=site, span=span0 + i,
                         dur_ms=dur_ms)


def test_watchdog_trips_overdue_dispatch_once():
    overload.uninstall_watchdog()  # the ring walk below owns the clock
    flightrec.reset()
    _seed_site("wd_site", 3, dur_ms=100.0)       # baseline mean 0.1 s
    flightrec.record("dispatch_start", site="wd_site", span=991)
    with _env(H2O3_TPU_HANG_MIN_SECS="0.5", H2O3_TPU_HANG_FACTOR="8"):
        hangs0 = mx.counter_value("dispatch_hangs_total", site="wd_site")
        trips = overload.watchdog_pass(now=time.time() + 5.0)
        assert len(trips) == 1
        t = trips[0]
        assert t["site"] == "wd_site" and t["span"] == 991
        assert t["budget_s"] == pytest.approx(0.8, abs=0.01)  # 8 x 0.1 s
        assert t["age_s"] > t["budget_s"]
        # the trip's full blast radius: counter, gauge, ring, latch, bundle
        assert mx.counter_value("dispatch_hangs_total",
                                site="wd_site") == hangs0 + 1
        snap = mx.REGISTRY.snapshot()["dispatch_hung"]
        hung = {v["labels"].get("site"): v["value"] for v in snap["values"]}
        assert hung["wd_site"] > 0
        assert [e for e in flightrec.events(kind="watchdog_trip")
                if e["site"] == "wd_site"]
        reason = cloud.degraded_reason()
        assert reason and "wd_site" in reason and "wedged" in reason
        with open(flightrec.last_incident()) as f:
            assert json.load(f)["trigger"] == "hang"
        # same pass again: the span trips exactly once
        assert overload.watchdog_pass(now=time.time() + 6.0) == []
        assert mx.counter_value("dispatch_hangs_total",
                                site="wd_site") == hangs0 + 1
        # the span closes (late unwedge): the hung gauge clears to 0
        flightrec.record("dispatch_end", site="wd_site", span=991,
                         dur_ms=5000.0, error="RuntimeError")
        overload.watchdog_pass(now=time.time() + 7.0)
        snap = mx.REGISTRY.snapshot()["dispatch_hung"]
        hung = {v["labels"].get("site"): v["value"] for v in snap["values"]}
        assert hung["wd_site"] == 0.0
    flightrec.reset()


def test_watchdog_floor_guards_first_compile():
    overload.uninstall_watchdog()  # the ring walk below owns the clock
    flightrec.reset()
    # < 3 completed dispatches: the rolling mean is untrusted — only the
    # floor applies, so a legitimately long first compile never false-trips
    _seed_site("wd_new", 2, dur_ms=10.0)
    flightrec.record("dispatch_start", site="wd_new", span=992)
    with _env(H2O3_TPU_HANG_MIN_SECS="120", H2O3_TPU_HANG_FACTOR="8"):
        assert overload.watchdog_pass(now=time.time() + 60.0) == []
        # a seasoned site with the same tiny baseline WOULD have tripped,
        # but still not before the floor
        _seed_site("wd_old", 3, dur_ms=10.0, span0=100)
        flightrec.record("dispatch_start", site="wd_old", span=993)
        assert overload.watchdog_pass(now=time.time() + 60.0) == []
        # past the floor both trip — the floor is the young site's only guard
        trips = overload.watchdog_pass(now=time.time() + 125.0)
        assert {t["site"] for t in trips} == {"wd_old", "wd_new"}
    flightrec.reset()


def test_hung_span_fail_stops_at_dispatch_exit():
    # a dispatch the watchdog declared wedged must not return its late
    # result: the exit raises the degraded fail-stop the supervisor owns
    d = flightrec.dispatch("wd_failstop")
    with pytest.raises(RuntimeError, match="fail-stop"):
        with d:
            flightrec.mark_span_hung(d._span)
    ends = [e for e in flightrec.events(kind="dispatch_end")
            if e["site"] == "wd_failstop"]
    assert ends  # the span still closed in the ring


def test_watchdog_thread_install_uninstall_idempotent():
    overload.install_watchdog()
    overload.install_watchdog()
    names = [t.name for t in threading.enumerate()]
    assert names.count("h2o3-hang-watchdog") == 1
    overload.uninstall_watchdog()
    overload.uninstall_watchdog()
    assert "h2o3-hang-watchdog" not in [t.name for t in threading.enumerate()]


# ---------------------------------------------------------------------------
# the overload metric families bypass the H2O3_TPU_METRICS gate


def test_overload_metrics_record_while_metrics_disabled():
    gated = mx.counter("overload_test_gated", "a normal gated counter")
    mx.set_enabled(False)
    try:
        gated.inc(k="v")
        overload.count_degrade("mx_site", "retried")
        devmem.reserve("mx_job", 42)
        snap = mx.REGISTRY.snapshot()
        # the gated counter recorded nothing while disabled ...
        assert all(v["value"] == 0.0
                   for v in snap["overload_test_gated"]["values"])
        # ... while the always-on overload families kept counting
        assert mx.counter_value("oom_degrades_total", site="mx_site",
                                outcome="retried") == 1
        res = {v["labels"].get("job"): v["value"]
               for v in snap["hbm_reserved_bytes"]["values"]}
        assert res["mx_job"] == 42.0
    finally:
        mx.set_enabled(True)
        devmem.release("mx_job")
