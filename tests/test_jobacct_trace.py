"""Job-scoped tracing + accounting plane tests (ISSUE 18): trace context
through REST ingress and the coalescing batcher, the per-job ledger
against the dispatch spans it mirrors, pod-federated metric merging, and
the METRICS=0 contract (trace ids are attribution, not telemetry — they
stay on when the registry is gated).
"""

import threading
import time
import urllib.request

import numpy as np
import pandas as pd
import pytest

from h2o3_tpu.cluster import federation
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models import GBM
from h2o3_tpu.utils import flightrec
from h2o3_tpu.utils import jobacct
from h2o3_tpu.utils import metrics as _mx


@pytest.fixture(scope="module")
def score_model():
    rng = np.random.default_rng(11)
    n = 600
    df = pd.DataFrame({
        "a": rng.normal(size=n),
        "b": rng.normal(size=n),
        "y": np.where(rng.random(n) < 0.5, "dog", "cat"),
    })
    fr = Frame.from_pandas(df, destination_frame="jobacct_train")
    return GBM(ntrees=5, max_depth=3, seed=1).train(y="y",
                                                   training_frame=fr)


# ---------------------------------------------------------------------------
# span trees: a coalesced request keeps ITS trace; the shared batch
# dispatch is cross-referenced, not stolen


def test_coalesced_request_keeps_own_span_tree(score_model, monkeypatch):
    """N concurrent traced requests coalesce into one batch dispatch. Each
    request's trace must still carry its OWN queue_wait span, and that
    span's batch_span id must resolve to a serving_batch dispatch — the
    shared dispatch parents under the batch span, never under any single
    request."""
    from h2o3_tpu import serving

    monkeypatch.setenv("H2O3_TPU_SCORE_BATCH_WINDOW_MS", "60")
    flightrec.reset()
    errors = []

    def worker(i):
        try:
            with _mx.trace(f"req-span-{i}", kind="request"), \
                    _mx.span("rest.request", route="/3/Predictions/rows"):
                serving.score_rows(score_model, [{"a": 0.1 * i, "b": -0.5}])
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert not errors

    evs = flightrec.events()
    waits = {e["trace"]: e for e in evs if e["kind"] == "queue_wait"
             and str(e.get("trace", "")).startswith("req-span-")}
    assert len(waits) == 6  # every request got its own queue_wait span
    batch_parents = {e.get("parent") for e in evs
                     if e["kind"] == "dispatch_end"
                     and e.get("site") == "serving_batch"}
    for i in range(6):
        w = waits[f"req-span-{i}"]
        assert w.get("span") is not None
        assert w.get("dur_ms") is not None and w["dur_ms"] >= 0
        # the cross-reference: this request's batch dispatched under the
        # shared batch span, and the dispatch span parents under it
        assert w.get("batch_span") in batch_parents
        # the request's registry span tree is its own (the shared dispatch
        # never appears inside any single request's trace)
        names = {s["name"] for s in _mx.trace_events(f"req-span-{i}")}
        assert "rest.request" in names


def test_rest_ingress_assigns_and_echoes_trace():
    """REST ingress starts a request trace (client X-Request-Id wins, else
    rest-{n}) and echoes the id back as X-H2O3-Trace."""
    from h2o3_tpu.api.server import start_server

    server = start_server(port=0)
    req = urllib.request.Request(server.url + "/3/Ping",
                                 headers={"X-Request-Id": "my-req-77"})
    with urllib.request.urlopen(req) as r:
        assert r.headers.get("X-H2O3-Trace") == "my-req-77"
    names = {s["name"] for s in _mx.trace_events("my-req-77")}
    assert "rest.request" in names
    with urllib.request.urlopen(server.url + "/3/Ping") as r:
        assigned = r.headers.get("X-H2O3-Trace")
    assert assigned and assigned.startswith("rest-")


# ---------------------------------------------------------------------------
# the ledger against the spans it mirrors


def test_gbm_job_ledger_matches_dispatch_spans():
    """The build job's ledger device-seconds must equal the sum of its
    dispatch spans within 5% — same measurement accumulated two ways (ring
    events vs jobacct), so a drift means one side lost dispatches."""
    rng = np.random.default_rng(3)
    n = 500
    df = pd.DataFrame({
        "a": rng.normal(size=n),
        "b": rng.normal(size=n),
        "y": rng.normal(size=n),
    })
    fr = Frame.from_pandas(df, destination_frame="jobacct_ledger_train")
    jobacct.reset()
    flightrec.reset()
    GBM(ntrees=5, max_depth=3, seed=2).train(y="y", training_frame=fr)

    jobs = jobacct.all_jobs()
    assert jobs, "the build job never ledgered"
    job = max(jobs, key=lambda k: jobs[k]["device_seconds"])
    led = jobs[job]
    assert led["dispatches"].get("tree", 0) >= 1
    span_s = sum(e["dur_ms"] for e in flightrec.events(kind="dispatch_end")
                 if e.get("trace") == job) / 1e3
    assert span_s > 0
    assert led["device_seconds"] == pytest.approx(span_s, rel=0.05)
    # dispatch counts agree exactly with the job's dispatch_end spans
    n_spans = sum(1 for e in flightrec.events(kind="dispatch_end")
                  if e.get("trace") == job)
    assert sum(led["dispatches"].values()) == n_spans
    # the registry gauge mirrors the ledger total
    fam = _mx.REGISTRY.gauge("job_device_seconds")
    vals = {tuple(sorted(l.items())): v for l, v in fam.samples()}
    assert vals.get((("job", job),)) == pytest.approx(
        led["device_seconds"], rel=1e-6)


# ---------------------------------------------------------------------------
# pod federation


def test_pod_merge_sums_counters_and_rank_labels_gauges():
    mk_hist = lambda s, c, inf: {  # noqa: E731
        "labels": {}, "buckets": {"0.1": c, "+Inf": inf}, "sum": s,
        "count": inf}
    snap_a = {
        "reqs_total": {"type": "counter", "help": "h", "values": [
            {"labels": {"route": "/3/Ping"}, "value": 3}]},
        "models_resident": {"type": "gauge", "help": "", "values": [
            {"labels": {"tier": "hbm"}, "value": 1.5}]},
        "wait_seconds": {"type": "histogram", "help": "", "values": [
            mk_hist(1.0, 1, 2)]},
    }
    snap_b = {
        "reqs_total": {"type": "counter", "help": "h", "values": [
            {"labels": {"route": "/3/Ping"}, "value": 4}]},
        "models_resident": {"type": "gauge", "help": "", "values": [
            {"labels": {"tier": "hbm"}, "value": 2.5}]},
        "wait_seconds": {"type": "histogram", "help": "", "values": [
            mk_hist(3.04, 0, 2)]},
    }
    merged = federation.merge({0: snap_a, 1: snap_b})
    # counters SUM across ranks per label set
    assert merged["reqs_total"]["values"] == [
        {"labels": {"route": "/3/Ping"}, "value": 7}]
    # gauges keep one series per rank, rank-labeled
    gvals = {v["labels"]["rank"]: v["value"]
             for v in merged["models_resident"]["values"]}
    assert gvals == {"0": 1.5, "1": 2.5}
    assert all(v["labels"]["tier"] == "hbm"
               for v in merged["models_resident"]["values"])
    # histograms merge cumulative buckets / sums / counts
    (h,) = merged["wait_seconds"]["values"]
    assert h["count"] == 4 and h["sum"] == pytest.approx(4.04)
    assert h["buckets"] == {"0.1": 1, "+Inf": 4}
    # and the merged dict (which lives in no registry) renders as a normal
    # Prometheus exposition
    text = _mx.render_snapshot(merged)
    assert 'reqs_total{route="/3/Ping"} 7' in text
    assert 'models_resident{rank="0",tier="hbm"} 1.5' in text
    assert 'wait_seconds_bucket{le="+Inf"} 4' in text


def test_single_process_pod_snapshot_is_rank0():
    snap = federation.pod_snapshot()
    assert isinstance(snap, dict) and snap
    for fam in snap.values():
        if fam.get("type") == "gauge":
            for v in fam["values"]:
                assert v["labels"].get("rank") == "0"


# ---------------------------------------------------------------------------
# METRICS=0: trace ids are attribution, not telemetry


def test_metrics_off_keeps_spans_in_ring_not_registry():
    _mx.set_enabled(False)
    try:
        jobacct.reset()
        flightrec.reset()
        with _mx.trace("job-gated"):
            with _mx.span("gated.build"):
                with flightrec.dispatch("tree", program="p"):
                    pass
        ev = flightrec.events(kind="dispatch_end")[-1]
        # the ring event still carries the full span identity
        assert ev["trace"] == "job-gated"
        assert ev.get("span") is not None
        # ...and the ledger still accumulated (the scheduler's signal)
        led = jobacct.snapshot("job-gated")
        assert led is not None and led["dispatches"] == {"tree": 1}
        # ...but the REGISTRY recorded nothing: no span tree, no gauge child
        assert _mx.trace_events("job-gated") == []
        fam = _mx.REGISTRY.gauge("job_device_seconds")
        assert not any(l.get("job") == "job-gated"
                       for l, _v in fam.samples())
    finally:
        _mx.set_enabled(True)


def test_ring_append_stays_microseconds_with_span_fields():
    """The PR-13 O(µs) append bound, re-run with the ISSUE-18 span fields
    attached — the trace plane must not buy attribution with hot-path
    time."""
    n = 20_000
    t0 = time.perf_counter()
    for i in range(n):
        flightrec.record("dispatch_end", site="tree", dur_ms=0.5,
                         trace="job-bound", span=i, parent=i - 1)
    per_event = (time.perf_counter() - t0) / n
    assert per_event < 100e-6, f"{per_event * 1e6:.1f}µs per append"
