"""Device-resident whole-tree build contracts (ISSUE 1): O(1) host
dispatches per tree, shape-bucketed padding that is provably inert, and
compile amortization across same-shape builds."""

import numpy as np
import pandas as pd
import pytest

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.tree import GBM
from h2o3_tpu.models.tree import shared_tree as st


def _df(n=2000, seed=0, c=5):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, c))
    df = pd.DataFrame(X, columns=[f"x{i}" for i in range(c)])
    df["y"] = X[:, 0] * 2 - X[:, 1] + 0.3 * rng.normal(size=n)
    return df


def _train(fr, **kw):
    params = dict(ntrees=10, max_depth=4, seed=7, distribution="gaussian",
                  score_tree_interval=5)
    params.update(kw)
    return GBM(**params).train(y="y", training_frame=fr)


def test_whole_tree_dispatches_o1_per_tree():
    """The whole-tree contract: host dispatches per tree are O(1), not
    O(depth). With the scanned chunk builder they are FRACTIONAL (one
    dispatch covers a whole scoring interval); the per-level escape hatch
    (H2O3_TPU_WHOLE_TREE=0) pays >= depth dispatches per tree — the counter
    must see both regimes or it is not counting."""
    fr = Frame.from_pandas(_df())
    st.reset_build_stats()
    _train(fr)
    fused = st.reset_build_stats()
    assert fused["trees_built"] == 10
    # ntrees=10, interval=5 -> 2 chunk dispatches, NOT 10 * (depth + 1)
    assert fused["dispatches"] <= 2
    assert fused["dispatches"] / fused["trees_built"] < 1  # O(1), amortized


def test_per_level_escape_hatch_dispatches_o_depth(monkeypatch):
    monkeypatch.setenv("H2O3_TPU_WHOLE_TREE", "0")
    fr = Frame.from_pandas(_df())
    st.reset_build_stats()
    _train(fr)
    legacy = st.reset_build_stats()
    assert legacy["trees_built"] == 10
    # per-level loop: every tree pays at least one dispatch per grown level
    assert legacy["dispatches"] >= legacy["trees_built"] * 2
    assert legacy["dispatches"] > 10 * 2  # strictly worse than whole-tree


def test_bucketed_padding_scores_identical(monkeypatch):
    """Shape-bucketed padding (H2O3_TPU_SHAPE_BUCKETS) must be inert: a
    bucketed build (cols padded to 8, bins to a power of two) scores
    IDENTICALLY to the exact-shape build — padded bins are empty, padded
    columns are disabled, and the column-sampling RNG draws at the real
    column count. Uses col_sample_rate < 1 so the RNG-width guarantee is
    actually load-bearing."""
    df = _df(c=5)  # 5 cols -> pads to 8 when bucketing
    kw = dict(col_sample_rate=0.7, sample_rate=0.8)

    monkeypatch.setenv("H2O3_TPU_SHAPE_BUCKETS", "1")
    fr = Frame.from_pandas(df)
    p_bucketed = _train(fr, **kw).predict(fr).vec("predict").to_numpy()
    vi_bucketed = _train(fr, **kw).varimp()

    monkeypatch.setenv("H2O3_TPU_SHAPE_BUCKETS", "0")
    fr = Frame.from_pandas(df)
    p_exact = _train(fr, **kw).predict(fr).vec("predict").to_numpy()
    vi_exact = _train(fr, **kw).varimp()

    np.testing.assert_array_equal(np.asarray(p_bucketed), np.asarray(p_exact))
    assert len(vi_bucketed) == len(vi_exact)  # no phantom padded columns
    for ra, rb in zip(vi_bucketed, vi_exact):
        assert ra["variable"] == rb["variable"]
        assert float(ra["relative_importance"]) == pytest.approx(
            float(rb["relative_importance"])
        )


def test_same_shape_twice_compiles_once():
    """Two GBMs of the same shape in one process: the second build's tree
    programs must ALL come from the in-process cache (zero compiles) —
    the compile-amortization half of the whole-tree design."""
    fr = Frame.from_pandas(_df(seed=1))
    _train(fr)  # whatever this compiles...
    st.reset_build_stats()
    _train(fr, seed=99)  # ...a same-shape rebuild reuses, seed is not shape
    again = st.reset_build_stats()
    assert again["tree_programs_compiled"] == 0
    assert again["tree_program_cache_hits"] >= 1


def test_nbins_bucket_collapses_nearby_shapes(monkeypatch):
    """The bin-axis ladder: nbins 100 and 120 both round to 128, so the
    second model's tree program is a cache HIT — the AutoML/grid sweep
    amortization the ladder exists for. (Bin EDGES still differ — only the
    compiled program is shared, not the splits.)"""
    monkeypatch.setenv("H2O3_TPU_SHAPE_BUCKETS", "1")
    # many distinct values so fit_bins actually uses ~nbins quantile bins
    fr = Frame.from_pandas(_df(n=4000, seed=2))
    _train(fr, nbins=100)
    st.reset_build_stats()
    _train(fr, nbins=120)
    stats = st.reset_build_stats()
    assert stats["tree_programs_compiled"] == 0
    assert stats["tree_program_cache_hits"] >= 1
