"""Test harness — replicates H2O's "real stack, local topology" strategy
(SURVEY.md §4): H2O tests boot a real in-process (or N-local-JVM) cloud; here
we boot a real 8-device sharded mesh on CPU so multi-chip semantics run in CI
without TPUs. No mocks anywhere below this line.
"""

import os

# Must be set before the jax backend initializes (sitecustomize may already
# have imported jax, but backend init is lazy — this still lands in time).
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session", autouse=True)
def cloud():
    import h2o3_tpu

    info = h2o3_tpu.init()
    assert info["cloud_size"] == 8
    yield info


@pytest.fixture()
def rng():
    return np.random.default_rng(42)
