"""Test harness — replicates H2O's "real stack, local topology" strategy
(SURVEY.md §4): H2O tests boot a real in-process (or N-local-JVM) cloud; here
we boot a real 8-device sharded mesh on CPU so multi-chip semantics run in CI
without TPUs. No mocks anywhere below this line.
"""

import os

# Must be set before the jax backend initializes (sitecustomize may already
# have imported jax, but backend init is lazy — this still lands in time).
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# Persistent compilation cache shared across test processes/runs: most test
# wall time is XLA:CPU compilation of the same programs in every xdist
# worker, and the per-process compile COUNT is what intermittently aborts
# jaxlib (see pytest.ini). Cache hits fix both.
_cache_dir = os.path.join(os.path.dirname(__file__), ".jax_cache")
jax.config.update("jax_compilation_cache_dir", _cache_dir)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.3)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

import numpy as np  # noqa: E402
import pytest  # noqa: E402

# ---------------------------------------------------------------------------
# Tier control (SURVEY §4 test-size tiers; VERDICT r3 item 7): the default
# tier must stay under ~5 minutes so driver/CI timeouts never hit it. The
# heavyweight scenario/quality tests below run in the slow (nightly-style)
# tier: `pytest -m "" tests/`. Centralized here, measured from
# `--durations` on the build box — every family keeps at least one smoke in
# the default tier (gbm auc, mojo parity, client estimator, DL xor,
# multihost REST e2e, NA handling all stay).
_SLOW_BY_NAME = {
    "test_drf_multinomial",
    "test_automl_runs_xgboost_steps_first",
    "test_calibrate_model_platt_and_isotonic",
    "test_rulefit_binomial_and_linear_only",
    "test_rulefit_recovers_rules",
    "test_full_flow_over_client",
    "test_hist_subtraction_matches_direct",
    "test_stacked_ensemble_beats_or_matches_base_models",
    "test_stacked_ensemble_regression",
    "test_wave3_algos_build_over_rest",
    "test_native_scorer_bit_identical_to_numpy",
    "test_sklearn_proba_aligns_with_classes_for_numeric_labels",
    "test_gbm_multinomial",
    "test_calibration_survives_mojo_export",
    "test_pojo_standalone_scoring",
    "test_grid_parallel_respects_max_models",
    "test_grid_parallelism_matches_sequential",
    "test_scanned_chunk_builder_matches_loop_quality",
    "test_gbm_early_stopping",
    "test_dl_regression",
    "test_dl_reproducible",
    "test_bin_code_equality_device_vs_mojo",
    "test_gbm_sampling_reproducible",
    "test_gbm_poisson",
    "test_varimp_and_heatmap",
    "test_drf_mojo_parity",
    "test_gbm_varimp_ranks_informative_feature",
    "test_cartesian_grid_covers_product_and_ranks",
    "test_drf_checkpoint_adds_trees",
    "test_gbm_regression_beats_baseline_and_tracks_sklearn",
    # re-measured 2026-08-06 (--durations=60, tier-1 at ~18.5 min against
    # the 870 s window): the heaviest compile-bound cases move to the slow
    # tier. Families keep a tier-1 smoke — e.g. the binomial mojo parity,
    # the gbm worker-death resume, and one param variant of each swept
    # parity case stay (bracketed entries below mark ONE variant, not all).
    "test_profiler_writes_trace",
    "test_glm_fused_multinomial_parity_and_dispatches",
    "test_automl_budget_caps_each_model",
    "test_gbm_elastic_resume_8_to_4",
    "test_compile_cache_cross_process",
    "test_automl_poison_step_skipped_after_retry_budget",
    "test_pdp_recovers_shape",
    "test_gbm_regression_mojo_parity",
    "test_automl_worker_death_auto_resumes",
    "test_streamed_mono_matches_resident",
    "test_oversized_streamed_train_bounds_ledger_claims",
    "test_plot_surface_renders",
    "test_streamed_gbm_parity_on_2d_mesh",
    "test_adversarial_tie_suites_bit_exact_under_quant",
    "test_fused_parity_coarsened_saturated_levels",
    "test_get_leaderboard_extra_columns",
    "test_infogram_core_ranks_signal_over_noise",
    "test_oversized_frame_trains_through_eviction_cycles",
    "test_fused_mono_tie_break[1]",
    "test_fused_mono_constrained_signal[8]",
    "test_gbm_streaming_matches_resident[2]",
    "test_fused_cat_sharded_tie_break[2]",
    "test_fused_tie_break_duplicated_columns_nonzero_gains[8]",
    "test_upliftdrf_recovers_heterogeneous_effect[KL]",
}


def pytest_collection_modifyitems(config, items):
    for item in items:
        if (item.name in _SLOW_BY_NAME
                or item.name.split("[")[0] in _SLOW_BY_NAME):
            item.add_marker(pytest.mark.slow)


@pytest.fixture(scope="session", autouse=True)
def cloud():
    import h2o3_tpu

    info = h2o3_tpu.init()
    assert info["cloud_size"] == 8
    yield info


@pytest.fixture()
def rng():
    return np.random.default_rng(42)
