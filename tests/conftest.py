"""Test harness — replicates H2O's "real stack, local topology" strategy
(SURVEY.md §4): H2O tests boot a real in-process (or N-local-JVM) cloud; here
we boot a real 8-device sharded mesh on CPU so multi-chip semantics run in CI
without TPUs. No mocks anywhere below this line.
"""

import os

# Must be set before the jax backend initializes (sitecustomize may already
# have imported jax, but backend init is lazy — this still lands in time).
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# Persistent compilation cache shared across test processes/runs: most test
# wall time is XLA:CPU compilation of the same programs in every xdist
# worker, and the per-process compile COUNT is what intermittently aborts
# jaxlib (see pytest.ini). Cache hits fix both.
_cache_dir = os.path.join(os.path.dirname(__file__), ".jax_cache")
jax.config.update("jax_compilation_cache_dir", _cache_dir)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.3)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session", autouse=True)
def cloud():
    import h2o3_tpu

    info = h2o3_tpu.init()
    assert info["cloud_size"] == 8
    yield info


@pytest.fixture()
def rng():
    return np.random.default_rng(42)
