"""REST hardening + health surfacing (round-4 ADVICE/VERDICT items):
CSRF/DNS-rebinding guard on state-changing routes, real device health in
/3/Cloud, isotonic-calibration knot collapse, native-build atomicity."""

import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from h2o3_tpu.api.server import start_server


@pytest.fixture(scope="module")
def server():
    return start_server(port=0)


def _post_raw(server, path, payload, headers):
    data = json.dumps(payload).encode()
    h = {"Content-Type": "application/json", **headers}
    req = urllib.request.Request(server.url + path, data=data, headers=h,
                                 method="POST")
    return urllib.request.urlopen(req)


def test_foreign_origin_post_rejected(server):
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post_raw(server, "/99/Rapids", {"ast": "(+ 1 2)"},
                  {"Origin": "http://evil.example"})
    assert ei.value.code == 403


def test_rebound_host_browser_post_rejected(server):
    # DNS-rebound page: same-origin fetch, so Origin matches Host — only the
    # Host allowlist can catch it. Browsers always send Sec-Fetch-* markers.
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post_raw(server, "/99/Rapids", {"ast": "(+ 1 2)"},
                  {"Host": "attacker.example",
                   "Origin": "http://attacker.example",
                   "Sec-Fetch-Site": "same-origin"})
    assert ei.value.code == 403


def test_dns_name_non_browser_client_passes(server):
    # python/R/curl via a k8s service name: Host is a DNS name but there are
    # no browser markers — must NOT be blocked
    with _post_raw(server, "/99/Rapids", {"ast": "(+ 1 2)"},
                   {"Host": "tpu-coordinator.cluster.internal:54321"}) as r:
        assert r.status == 200


def test_same_origin_post_accepted(server):
    host = server.url.split("//", 1)[1]
    with _post_raw(server, "/99/Rapids", {"ast": "(+ 1 2)"},
                   {"Origin": f"http://{host}"}) as r:
        assert r.status == 200
    # plain client POST (no Origin, IP-literal Host) keeps working
    with _post_raw(server, "/99/Rapids", {"ast": "(+ 2 2)"}, {}) as r:
        assert r.status == 200


def test_get_never_blocked_by_guard(server):
    with urllib.request.urlopen(
        urllib.request.Request(server.url + "/3/Cloud",
                               headers={"Origin": "http://evil.example"})
    ) as r:
        assert r.status == 200


def test_cloud_health_reflects_real_probe(server, monkeypatch):
    import h2o3_tpu.cluster.cloud as cloud_mod

    real = cloud_mod.cluster_info()
    assert real["cloud_healthy"] is True

    def sick():
        info = dict(real)
        info["cloud_healthy"] = False
        info["nodes"] = [{"id": 0, "healthy": False}]
        return info

    monkeypatch.setattr(cloud_mod, "cluster_info", sick)
    with urllib.request.urlopen(server.url + "/3/Cloud") as r:
        out = json.loads(r.read())
    assert out["cloud_healthy"] is False
    assert out["nodes"][0]["healthy"] is False
    monkeypatch.undo()
    with urllib.request.urlopen(server.url + "/3/Cloud") as r:
        out = json.loads(r.read())
    assert out["cloud_healthy"] is True
    assert all(n["healthy"] for n in out["nodes"])


def test_flow_page_escapes_server_strings():
    """The Flow console must escape interpolated server strings (stored-XSS
    guard): the esc()/setMsg helpers exist and no raw key interpolation
    remains in onclick handlers."""
    from h2o3_tpu.api.flow import FLOW_HTML

    assert "const esc =" in FLOW_HTML
    assert "setMsg" in FLOW_HTML
    # the old vulnerable pattern: onclick="fn('${...}')"
    assert "onclick=\"frameSummary('" not in FLOW_HTML
    assert "onclick=\"modelDetail('" not in FLOW_HTML
    # error objects are never innerHTML'd
    assert "innerHTML = `<span class=\"err\">${e}" not in FLOW_HTML


def test_isotonic_knots_collapsed():
    from h2o3_tpu.models.calibration import apply_calibration, fit_isotonic

    rng = np.random.default_rng(0)
    n = 5000
    p1 = rng.random(n)
    y = (rng.random(n) < p1).astype(np.float64)
    cal = fit_isotonic(p1, y, np.ones(n))
    # PAV pools heavily on noisy data: stored knots must be way below n
    assert len(cal["thresholds_x"]) < n // 2
    # predictions stay monotone and calibrated-ish
    q = np.linspace(0, 1, 101)
    pq = apply_calibration(cal, q)
    assert (np.diff(pq) >= -1e-12).all()
    assert abs(pq[50] - 0.5) < 0.12


# -- opt-in token auth (the -hash_login analog, SURVEY §5.6) -----------------


def _get_raw(server, path, headers=None):
    req = urllib.request.Request(server.url + path, headers=headers or {})
    return urllib.request.urlopen(req)


def test_auth_off_by_default(server):
    # upstream's default is open; auth is strictly opt-in
    assert _get_raw(server, "/3/Cloud").status == 200


def test_auth_token_enforced(server, monkeypatch):
    import base64

    monkeypatch.setenv("H2O3_TPU_AUTH_TOKEN", "sekrit-42")

    with pytest.raises(urllib.error.HTTPError) as ei:
        _get_raw(server, "/3/Cloud")
    assert ei.value.code == 401
    assert "Basic" in ei.value.headers.get("WWW-Authenticate", "")

    with pytest.raises(urllib.error.HTTPError) as ei:
        _get_raw(server, "/3/Cloud", {"Authorization": "Bearer wrong"})
    assert ei.value.code == 401

    ok = _get_raw(server, "/3/Cloud", {"Authorization": "Bearer sekrit-42"})
    assert ok.status == 200

    basic = base64.b64encode(b"anyuser:sekrit-42").decode()
    ok = _get_raw(server, "/3/Cloud", {"Authorization": f"Basic {basic}"})
    assert ok.status == 200

    # POSTs are covered too (auth runs before route dispatch)
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post_raw(server, "/99/Rapids", {"ast": "(+ 1 2)"}, {})
    assert ei.value.code == 401


def test_auth_client_pairs_with_token(server, monkeypatch):
    from h2o3_tpu.client import H2OConnection

    monkeypatch.setenv("H2O3_TPU_AUTH_TOKEN", "sekrit-43")
    conn = H2OConnection(server.url, token="sekrit-43")
    assert conn.cloud.get("cloud_healthy")
    # the env default pairs automatically when token isn't passed
    conn2 = H2OConnection(server.url)
    assert conn2.token == "sekrit-43"
