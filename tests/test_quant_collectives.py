"""Quantized collective lane + hierarchical reduction placement (ISSUE 9,
``ops/collectives.py``): the block-quantized reduces must (a) be bit-for-bit
inert when off, (b) keep the PR-5 adversarial tie suites bit-exact when on
(power-of-two scales make integer payloads lossless), (c) keep model quality
inside the pinned envelopes (GBM AUC, GLM coefficients), and (d) report the
wire-compression claim through the new ``{lane}`` counter dimension. Also
pins the satellite fix: saturated-region byte tallies now scale by the
EXECUTED while_loop iterations, not the trace-time n_sat upper bound.
"""

import os

import numpy as np
import pandas as pd
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from h2o3_tpu.ops import collectives as cl
from h2o3_tpu.parallel import mesh as pm
from tests.test_split_shard import (
    _assert_trees_bit_equal,
    _bits,
    _build_one,
    _env,
    _pad_rows,
    _tie_data,
    _use_mesh,
)

QUANT1 = {"H2O3_TPU_COLLECTIVE_QUANT": "1"}
QUANT0 = {"H2O3_TPU_COLLECTIVE_QUANT": "0"}


def _sharded(fn, out_spec):
    mesh = pm.get_mesh()
    return jax.jit(pm.shard_map(
        fn, mesh=mesh, in_specs=(P(),), out_specs=out_spec, check_vma=False))


def _rs_exact(v):
    return jax.lax.psum_scatter(
        v, pm.ROWS_AXIS, scatter_dimension=0, tiled=True)


# ---------------------------------------------------------------------------
# quantizer + wrapper semantics


def test_block_quantizer_lossless_for_small_integers():
    """Power-of-two scales: any block of integer values with |x| <= 127
    round-trips bit-exactly — the adversarial tie suites' regime."""
    rng = np.random.default_rng(0)
    x = rng.integers(-127, 128, (4, 2, 64)).astype(np.float32)
    q, s = cl._encode8(jnp.asarray(x))
    back = np.asarray(cl._decode8(q, s))
    assert _bits(back) == _bits(x)
    # scales are exact powers of two (or the all-zero-block placeholder 1)
    sv = np.asarray(s).ravel()
    assert np.all(np.logical_or(sv == 1.0, np.log2(sv) == np.round(np.log2(sv))))
    # and a lossy block still lands within half a scale step
    big = rng.normal(size=(1, 2, 64)).astype(np.float32) * 1000
    q, s = cl._encode8(jnp.asarray(big))
    err = np.abs(np.asarray(cl._decode8(q, s)) - big)
    assert err.max() <= np.asarray(s).max() / 2 + 1e-3


def test_quant_reduce_scatter_bit_exact_on_integer_payloads():
    """The wrapped reduce-scatter under QUANT=1 equals the stock
    psum_scatter bit-for-bit when local contributions are small integers."""
    with _use_mesh(8), _env(**QUANT1):
        rng = np.random.default_rng(1)
        x = jnp.asarray(rng.integers(-120, 121, (16, 33)).astype(np.float32))
        got = _sharded(
            lambda v: cl.psum_scatter(v, n_dev=8), P(pm.ROWS_AXIS))(x)
        want = _sharded(_rs_exact, P(pm.ROWS_AXIS))(x)
        assert _bits(got) == _bits(want)


def test_quant_float_error_bounded_and_residual_pass_tightens():
    """General float payloads: single-pass int8 error stays under the
    scale-step bound; the residual-correction pass (passes=2, the
    Gram/gradient lane) cuts it by ~two orders of magnitude."""
    with _use_mesh(8), _env(**QUANT1):
        rng = np.random.default_rng(2)
        x = jnp.asarray(rng.normal(size=(16, 257)).astype(np.float32))
        want = np.asarray(_sharded(_rs_exact, P(pm.ROWS_AXIS))(x))
        got1 = np.asarray(_sharded(
            lambda v: cl.psum_scatter(v, n_dev=8), P(pm.ROWS_AXIS))(x))
        got2 = np.asarray(_sharded(
            lambda v: cl.psum_scatter(v, n_dev=8, passes=2),
            P(pm.ROWS_AXIS))(x))
        amax = float(np.abs(np.asarray(x)).max())
        err1 = np.abs(got1 - want).max()
        err2 = np.abs(got2 - want).max()
        # 8 senders x half a scale step each, scales <= 2*amax/127
        assert err1 <= 8 * amax / 127 + 1e-5
        assert err2 < err1 / 20


def test_quant_psum_chunks_match_scatter_blocks():
    """The consistency invariant behind the tie-suite parity: a wrapped
    replicated psum is the wrapped reduce-scatter + exact gather, so chunk
    d of the replicated result is BIT-identical to sharded device d's
    block — for arbitrary float data."""
    with _use_mesh(8), _env(**QUANT1):
        rng = np.random.default_rng(3)
        x = jnp.asarray(rng.normal(size=(16, 19)).astype(np.float32))
        full = _sharded(lambda v: cl.psum(v, n_dev=8), P())(x)
        blocks = _sharded(
            lambda v: cl.psum_scatter(v, n_dev=8), P(pm.ROWS_AXIS))(x)
        assert _bits(full) == _bits(blocks)


def test_hierarchical_two_stage_bit_exact_on_integers():
    """H2O3_TPU_COLLECTIVE_HIER=2 on the 8-device proxy (4 fake-ICI pairs):
    stage-1 exact inner reduce + stage-2 quantized cross exchange must
    still deal device d global chunk d, bit-exactly for integer data."""
    with _use_mesh(8), _env(H2O3_TPU_COLLECTIVE_HIER="2", **QUANT1):
        rng = np.random.default_rng(4)
        x = jnp.asarray(rng.integers(-100, 101, (16, 21)).astype(np.float32))
        got = _sharded(
            lambda v: cl.psum_scatter(v, n_dev=8), P(pm.ROWS_AXIS))(x)
        gotf = _sharded(lambda v: cl.psum(v, n_dev=8), P())(x)
    with _use_mesh(8), _env(**QUANT0):
        want = _sharded(_rs_exact, P(pm.ROWS_AXIS))(x)
        wantf = _sharded(lambda v: jax.lax.psum(v, pm.ROWS_AXIS), P())(x)
    assert _bits(got) == _bits(want)
    assert _bits(gotf) == _bits(wantf)


# ---------------------------------------------------------------------------
# end-to-end: trees


def test_quant_off_is_bit_identical_to_unset():
    """H2O3_TPU_COLLECTIVE_QUANT=0 must be byte-for-byte today's path."""
    with _use_mesh(8):
        n_pad = _pad_rows(700)
        rng = np.random.default_rng(7)
        bins = rng.integers(0, 16, (n_pad, 7)).astype(np.uint8)
        t = rng.normal(size=n_pad).astype(np.float32)
        t0, p0, v0 = _build_one(bins, t, split_shard=1)
        tq, pq, vq = _build_one(bins, t, split_shard=1, env=QUANT0)
        _assert_trees_bit_equal(tq, t0, "QUANT=0 vs unset")
        assert _bits(pq) == _bits(p0) and _bits(vq) == _bits(v0)


@pytest.mark.parametrize("k", [2, 8])
def test_adversarial_tie_suites_bit_exact_under_quant(k):
    """The PR-5 adversarial tie suites under QUANT=1: unit weights +
    integer targets make every local payload an exact int8 block, so split
    decisions stay bit-identical to the exact lane — and the sharded and
    replicated pipelines stay bit-identical to each other."""
    with _use_mesh(k):
        n_pad = _pad_rows(960)
        bins, t = _tie_data(n_pad, C=13, n_bins=16, dup_all=True)
        tq1, pq1, vq1 = _build_one(bins, t, split_shard=1, env=QUANT1)
        tq0, pq0, vq0 = _build_one(bins, t, split_shard=0, env=QUANT1)
        te, pe, ve = _build_one(bins, t, split_shard=1, env=QUANT0)
        _assert_trees_bit_equal(tq1, tq0, f"quant ties shard-vs-repl/{k}dev")
        _assert_trees_bit_equal(tq1, te, f"quant-vs-exact ties/{k}dev")
        assert _bits(pq1) == _bits(pe) and _bits(vq1) == _bits(ve)
        # dup columns with real signal: identical best gains in every
        # block — the lowest-global-index tie-break must survive the lane
        rng = np.random.default_rng(3)
        bins2, _ = _tie_data(n_pad, C=16, n_bins=16, dup_all=True, seed=3)
        t2 = (rng.integers(0, 2, n_pad) * 2 - 1).astype(np.float32)
        tq, _, _ = _build_one(bins2, t2, split_shard=1, max_depth=4, env=QUANT1)
        te2, _, _ = _build_one(bins2, t2, split_shard=1, max_depth=4, env=QUANT0)
        _assert_trees_bit_equal(tq, te2, f"dup-cols quant-vs-exact/{k}dev")


def test_quant_counters_report_lane_and_2x_fewer_bytes():
    """The {lane} dimension on tree_collective_bytes_total: a QUANT=1 build
    tallies its hist_reduce volume on the quant lane at >=2x (3.94x
    modeled: int8 + one f32 scale per 256 block vs f32) fewer bytes than
    the exact control at the same shape."""
    from h2o3_tpu.utils import metrics as mx

    def deltas(env):
        keys = [dict(phase="hist_reduce"),
                dict(phase="hist_reduce", lane="quant"),
                dict(phase="hist_reduce", lane="exact")]
        before = [mx.counter_value("tree_collective_bytes_total", **k)
                  for k in keys]
        _build_one(bins, t, split_shard=1, n_bins=32, seed=23, env=env)
        return [mx.counter_value("tree_collective_bytes_total", **k) - b
                for k, b in zip(keys, before)]

    with _use_mesh(8):
        n_pad = _pad_rows(700)
        rng = np.random.default_rng(19)
        bins = rng.integers(0, 32, (n_pad, 28)).astype(np.uint8)
        t = rng.normal(size=n_pad).astype(np.float32)
        tot_q, lane_q, lane_e = deltas(QUANT1)
        tot_x, lane_qx, lane_ex = deltas(QUANT0)
    assert tot_q > 0 and lane_q == tot_q and lane_e == 0
    assert tot_x > 0 and lane_qx == 0 and lane_ex == tot_x
    assert tot_x >= 2 * tot_q, (tot_x, tot_q)


def test_hierarchical_lane_splits_counter_by_stage():
    """Under HIER the stage-1 (intra-group, exact) and stage-2 (cross-group,
    quantized) volumes land on their own lanes."""
    from h2o3_tpu.utils import metrics as mx

    with _use_mesh(8), _env(H2O3_TPU_COLLECTIVE_HIER="2"):
        n_pad = _pad_rows(700)
        rng = np.random.default_rng(5)
        bins = rng.integers(0, 16, (n_pad, 8)).astype(np.uint8)
        t = rng.normal(size=n_pad).astype(np.float32)
        q0 = mx.counter_value(
            "tree_collective_bytes_total", phase="hist_reduce", lane="quant")
        e0 = mx.counter_value(
            "tree_collective_bytes_total", phase="hist_reduce", lane="exact")
        _build_one(bins, t, split_shard=1, env=QUANT1)
        dq = mx.counter_value(
            "tree_collective_bytes_total", phase="hist_reduce",
            lane="quant") - q0
        de = mx.counter_value(
            "tree_collective_bytes_total", phase="hist_reduce",
            lane="exact") - e0
    assert dq > 0 and de > 0  # both stages accounted, on their own lanes
    assert de > dq  # stage-1 moves the full f32 volume, stage-2 the 1/P int8


# ---------------------------------------------------------------------------
# end-to-end: model quality envelopes


def _class_frame(n, c, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, c)).astype(np.float32)
    eta = X[:, 0] - 0.5 * X[:, 1] + 0.25 * X[:, 2] * X[:, 3]
    y = rng.random(n) < 1.0 / (1.0 + np.exp(-eta))
    df = pd.DataFrame(X, columns=[f"x{i}" for i in range(c)])
    df["label"] = np.where(y, "s", "b")
    return df


@pytest.mark.slow
def test_gbm_auc_delta_within_pin_under_quant():
    """8-device mesh, the A/B shape (16k rows): training-AUC delta between
    the quantized and exact lanes stays inside the acceptance pin 1e-3."""
    from h2o3_tpu.frame.frame import Frame
    from h2o3_tpu.models.tree import GBM

    df = _class_frame(16000, 12)

    def auc(env):
        with _env(**env):
            m = GBM(ntrees=10, max_depth=5, seed=7).train(
                y="label", training_frame=Frame.from_pandas(df))
            return float(m.training_metrics.auc)

    with _use_mesh(8):
        delta = abs(auc(QUANT1) - auc(QUANT0))
    assert delta <= 1e-3, delta


@pytest.mark.parametrize("k", [1, 2, 8])
def test_glm_coefficients_within_envelope_under_quant(k):
    """The Gram reduce rides the quant lane with the residual-correction
    pass: IRLS coefficients stay within the pinned parity envelope on
    1/2/8-device meshes (on 1 device the lane is inert — delta exactly 0)."""
    from h2o3_tpu.frame.frame import Frame
    from h2o3_tpu.models.glm import GLM

    df = _class_frame(2000, 8, seed=1)

    def coefs(env):
        with _env(**env):
            m = GLM(family="binomial", lambda_=1e-4, max_iterations=20,
                    seed=1).train(y="label", training_frame=Frame.from_pandas(df))
            return m.coef

    with _use_mesh(k):
        c1 = coefs(QUANT1)
        c0 = coefs(QUANT0)
    dmax = max(abs(c1[key] - c0[key]) for key in c0)
    if k == 1:
        assert dmax == 0.0
    else:
        assert dmax <= 2e-3, dmax


@pytest.mark.slow
def test_dl_sharded_grad_quant_parity():
    """DL's flat-gradient scatter under QUANT=1 (residual pass): final
    predictions stay close to the exact lane's — the per-step ~1e-5
    relative gradient error must not compound into divergence."""
    from h2o3_tpu.frame.frame import Frame
    from h2o3_tpu.models.deeplearning import DeepLearning

    df = _class_frame(4096, 8, seed=2)

    def preds(env):
        with _env(**env):
            fr = Frame.from_pandas(df)
            m = DeepLearning(hidden=[16, 16], epochs=3, mini_batch_size=256,
                             seed=3).train(y="label", training_frame=fr)
            return np.asarray(
                m.predict(fr).vec("s").to_numpy(), np.float64)

    with _use_mesh(8):
        p1 = preds(QUANT1)
        p0 = preds(QUANT0)
    assert np.max(np.abs(p1 - p0)) <= 0.05


# ---------------------------------------------------------------------------
# satellite: saturated-region tallies scale by EXECUTED iterations


def test_sat_region_tally_counts_executed_not_nsat():
    """Two same-shape deep builds (max_depth=8, node_cap=8 — a 5-level
    saturated while_loop region): one on data that stops splitting after
    depth 1 (2 distinct bin values), one on rich data that splits to the
    bottom. The old tally scaled both by n_sat; the fixed one reads the
    executed iteration count from the build stats, so the early-exit build
    must tally strictly less and the sat counter must match reality."""
    from h2o3_tpu.models.tree import shared_tree as st
    from h2o3_tpu.utils import metrics as mx

    def build(bins, t):
        h0 = mx.counter_value(
            "tree_collective_bytes_total", phase="hist_reduce")
        s0 = st.BUILD_STATS["sat_levels_executed"]
        _build_one(bins, t, split_shard=1, max_depth=8, node_cap=8,
                   env={"H2O3_TPU_SHAPE_BUCKETS": "0"})
        return (
            mx.counter_value(
                "tree_collective_bytes_total", phase="hist_reduce") - h0,
            st.BUILD_STATS["sat_levels_executed"] - s0,
        )

    with _use_mesh(8):
        n_pad = _pad_rows(600)
        rng = np.random.default_rng(11)
        shifts = st._bin_shifts(8, 16, ())
        assert st._sat_region(8, 8, shifts)[1] >= 2  # region must exist
        # early-exit data: one informative column with two values — after
        # the depth-0 split both children are single-bin pure nodes
        bins_small = rng.integers(1, 3, (n_pad, 3)).astype(np.uint8)
        bins_small[:, 1:] = bins_small[:, :1]  # duplicates, same 2 bins
        t_small = (bins_small[:, 0] == 1).astype(np.float32)
        bytes_small, sat_small = build(bins_small, t_small)
        # rich data: splits keep landing until depth exhausts
        bins_rich = rng.integers(0, 16, (n_pad, 3)).astype(np.uint8)
        t_rich = rng.normal(size=n_pad).astype(np.float32)
        bytes_rich, sat_rich = build(bins_rich, t_rich)
    assert sat_small < sat_rich, (sat_small, sat_rich)
    # identical shapes → identical per-level tally; only the executed sat
    # count differs, so the early-exit build must tally strictly less (the
    # old n_sat scaling made these equal)
    assert bytes_small < bytes_rich, (bytes_small, bytes_rich)
