"""Fixed-dataset accuracy cases — the ``h2o-test-accuracy/`` successor
(SURVEY.md §4): each case trains a flagship config on a deterministic seeded
dataset and reports metrics that are compared against stored expectations in
``tests/accuracy_expectations.json``.

Unlike the rest of the suite (which pins against sklearn computed at test
time), these catch *silent metric drift* in our own engine with no runtime
dependency on sklearn's behavior. Regenerate expectations deliberately with
``python tools/gen_accuracy_expectations.py`` and review the diff.
"""

from __future__ import annotations

import numpy as np
import pandas as pd


def _classif_df(n=5000, c=8, seed=13):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, c)).astype(np.float32)
    eta = 1.2 * X[:, 0] - 0.8 * X[:, 1] + 0.6 * X[:, 2] * X[:, 3] + np.sin(X[:, 4])
    y = rng.random(n) < 1 / (1 + np.exp(-eta))
    df = pd.DataFrame(X, columns=[f"f{i}" for i in range(c)])
    # a categorical + some NAs so the cases exercise domains and NA paths
    df["cat"] = pd.Categorical(np.where(X[:, 5] > 0.5, "a", np.where(X[:, 5] < -0.5, "b", "c")))
    df.loc[:: 97, "f0"] = np.nan
    df["label"] = np.where(y, "yes", "no")
    return df


def _regress_df(n=5000, c=8, seed=29):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, c)).astype(np.float32)
    y = 2.0 * X[:, 0] + X[:, 1] ** 2 - 1.5 * X[:, 2] + 0.3 * rng.normal(size=n)
    df = pd.DataFrame(X, columns=[f"f{i}" for i in range(c)])
    df["y"] = y.astype(np.float32)
    return df


def run_cases(progress=None) -> dict[str, dict[str, float]]:
    """Train every case and return {case: {metric: value}}."""
    import sys

    def _tick(name):
        if progress:
            print(f"[accuracy] {name}", file=sys.stderr, flush=True)
    import h2o3_tpu
    from h2o3_tpu.models.tree.gbm import GBM
    from h2o3_tpu.models.tree.drf import DRF
    from h2o3_tpu.models.tree.xgboost import XGBoost
    from h2o3_tpu.models.glm import GLM
    from h2o3_tpu.models.kmeans import KMeans
    from h2o3_tpu.models.deeplearning import DeepLearning

    cls_fr = h2o3_tpu.upload_file(_classif_df())
    reg_fr = h2o3_tpu.upload_file(_regress_df())
    out: dict[str, dict[str, float]] = {}

    _tick("gbm_binomial")
    m = GBM(ntrees=20, max_depth=5, learn_rate=0.2, seed=42).train(
        y="label", training_frame=cls_fr
    )
    out["gbm_binomial"] = {
        "auc": m.training_metrics.auc,
        "logloss": m.training_metrics.logloss,
    }

    _tick("gbm_gaussian")
    m = GBM(ntrees=20, max_depth=5, learn_rate=0.2, seed=42).train(
        y="y", training_frame=reg_fr
    )
    out["gbm_gaussian"] = {
        "rmse": m.training_metrics.rmse,
        "mae": m.training_metrics.mae,
    }

    _tick("xgboost_binomial")
    m = XGBoost(ntrees=20, max_depth=5, seed=42).train(
        y="label", training_frame=cls_fr
    )
    out["xgboost_binomial"] = {
        "auc": m.training_metrics.auc,
        "logloss": m.training_metrics.logloss,
    }

    _tick("drf_binomial")
    m = DRF(ntrees=20, max_depth=8, seed=42).train(y="label", training_frame=cls_fr)
    out["drf_binomial"] = {"auc": m.training_metrics.auc}

    _tick("glm_binomial")
    m = GLM(family="binomial", lambda_=1e-4, seed=42).train(
        y="label", training_frame=cls_fr
    )
    out["glm_binomial"] = {
        "auc": m.training_metrics.auc,
        "logloss": m.training_metrics.logloss,
    }

    _tick("glm_gaussian")
    m = GLM(family="gaussian", lambda_=1e-4, seed=42).train(
        y="y", training_frame=reg_fr
    )
    out["glm_gaussian"] = {"rmse": m.training_metrics.rmse}

    _tick("kmeans")
    m = KMeans(k=5, seed=42, max_iterations=20).train(
        x=[f"f{i}" for i in range(8)], training_frame=reg_fr
    )
    out["kmeans"] = {
        "tot_withinss": m.output["tot_withinss"],
        "totss": m.output["totss"],
    }

    _tick("deeplearning")
    m = DeepLearning(
        hidden=[16, 16], epochs=10, seed=42, reproducible=True
    ).train(y="label", training_frame=cls_fr)
    out["deeplearning_binomial"] = {"auc": m.training_metrics.auc}

    return {
        case: {k: float(v) for k, v in metrics.items()}
        for case, metrics in out.items()
    }


# per-metric absolute tolerances: tight enough to catch drift, loose enough
# for cross-jaxlib float jitter (f32 reductions reassociate across versions)
TOLERANCES = {
    "auc": 2e-3,
    "logloss": 2e-3,
    "rmse": 2e-3,
    "mae": 2e-3,
    "tot_withinss": 50.0,  # absolute SS on 5000x8 standardized-ish data
    "totss": 50.0,
}
