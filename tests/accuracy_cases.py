"""Fixed-dataset accuracy cases — the ``h2o-test-accuracy/`` successor
(SURVEY.md §4): each case trains a flagship config on a deterministic seeded
dataset and reports metrics that are compared against stored expectations in
``tests/accuracy_expectations.json``.

Unlike the rest of the suite (which pins against sklearn computed at test
time), these catch *silent metric drift* in our own engine with no runtime
dependency on sklearn's behavior. Regenerate expectations deliberately with
``python tools/gen_accuracy_expectations.py`` and review the diff.
"""

from __future__ import annotations

import numpy as np
import pandas as pd


def _classif_df(n=5000, c=8, seed=13):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, c)).astype(np.float32)
    eta = 1.2 * X[:, 0] - 0.8 * X[:, 1] + 0.6 * X[:, 2] * X[:, 3] + np.sin(X[:, 4])
    y = rng.random(n) < 1 / (1 + np.exp(-eta))
    df = pd.DataFrame(X, columns=[f"f{i}" for i in range(c)])
    # a categorical + some NAs so the cases exercise domains and NA paths
    df["cat"] = pd.Categorical(
        np.where(X[:, 5] > 0.5, "a", np.where(X[:, 5] < -0.5, "b", "c"))
    )
    df.loc[::97, "f0"] = np.nan
    df["label"] = np.where(y, "yes", "no")
    return df


def _regress_df(n=5000, c=8, seed=29):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, c)).astype(np.float32)
    y = 2.0 * X[:, 0] + X[:, 1] ** 2 - 1.5 * X[:, 2] + 0.3 * rng.normal(size=n)
    df = pd.DataFrame(X, columns=[f"f{i}" for i in range(c)])
    df["y"] = y.astype(np.float32)
    return df


def _case_gbm_binomial(cls_fr, reg_fr):
    from h2o3_tpu.models.tree.gbm import GBM

    m = GBM(ntrees=20, max_depth=5, learn_rate=0.2, seed=42).train(
        y="label", training_frame=cls_fr
    )
    return {"auc": m.training_metrics.auc, "logloss": m.training_metrics.logloss}


def _case_gbm_gaussian(cls_fr, reg_fr):
    from h2o3_tpu.models.tree.gbm import GBM

    m = GBM(ntrees=20, max_depth=5, learn_rate=0.2, seed=42).train(
        y="y", training_frame=reg_fr
    )
    return {"rmse": m.training_metrics.rmse, "mae": m.training_metrics.mae}


def _case_xgboost_binomial(cls_fr, reg_fr):
    from h2o3_tpu.models.tree.xgboost import XGBoost

    m = XGBoost(ntrees=20, max_depth=5, seed=42).train(
        y="label", training_frame=cls_fr
    )
    return {"auc": m.training_metrics.auc, "logloss": m.training_metrics.logloss}


def _case_drf_binomial(cls_fr, reg_fr):
    from h2o3_tpu.models.tree.drf import DRF

    m = DRF(ntrees=20, max_depth=8, seed=42).train(y="label", training_frame=cls_fr)
    return {"auc": m.training_metrics.auc}


def _case_glm_binomial(cls_fr, reg_fr):
    from h2o3_tpu.models.glm import GLM

    m = GLM(family="binomial", lambda_=1e-4, seed=42).train(
        y="label", training_frame=cls_fr
    )
    return {"auc": m.training_metrics.auc, "logloss": m.training_metrics.logloss}


def _case_glm_gaussian(cls_fr, reg_fr):
    from h2o3_tpu.models.glm import GLM

    m = GLM(family="gaussian", lambda_=1e-4, seed=42).train(
        y="y", training_frame=reg_fr
    )
    return {"rmse": m.training_metrics.rmse}


def _case_kmeans(cls_fr, reg_fr):
    from h2o3_tpu.models.kmeans import KMeans

    m = KMeans(k=5, seed=42, max_iterations=20).train(
        x=[f"f{i}" for i in range(8)], training_frame=reg_fr
    )
    mm = m.training_metrics
    return {"tot_withinss": mm._v["tot_withinss"], "totss": mm._v["totss"]}


def _case_deeplearning_binomial(cls_fr, reg_fr):
    from h2o3_tpu.models.deeplearning import DeepLearning

    m = DeepLearning(hidden=[16, 16], epochs=10, seed=42, reproducible=True).train(
        y="label", training_frame=cls_fr
    )
    return {"auc": m.training_metrics.auc}


_CASES = {
    "gbm_binomial": _case_gbm_binomial,
    "gbm_gaussian": _case_gbm_gaussian,
    "xgboost_binomial": _case_xgboost_binomial,
    "drf_binomial": _case_drf_binomial,
    "glm_binomial": _case_glm_binomial,
    "glm_gaussian": _case_glm_gaussian,
    "kmeans": _case_kmeans,
    "deeplearning_binomial": _case_deeplearning_binomial,
}


def run_cases(progress=None, cases=None) -> dict[str, dict[str, float]]:
    """Train the requested cases (default: all); {case: {metric: value}}."""
    import sys

    import h2o3_tpu

    cls_fr = h2o3_tpu.upload_file(_classif_df())
    reg_fr = h2o3_tpu.upload_file(_regress_df())
    names = list(_CASES) if cases is None else [c for c in _CASES if c in set(cases)]
    out = {}
    for name in names:
        if progress:
            print(f"[accuracy] {name}", file=sys.stderr, flush=True)
        metrics = _CASES[name](cls_fr, reg_fr)
        out[name] = {k: float(v) for k, v in metrics.items()}
    return out


# per-metric absolute tolerances: tight enough to catch drift, loose enough
# for cross-jaxlib float jitter (f32 reductions reassociate across versions)
TOLERANCES = {
    "auc": 2e-3,
    "logloss": 2e-3,
    "rmse": 2e-3,
    "mae": 2e-3,
    "tot_withinss": 50.0,  # absolute SS on 5000x8 standardized-ish data
    "totss": 50.0,
}
