"""Self-healing chaos suite (ISSUE 10): supervised auto-resume from
checkpoints, generation-fenced spmd, batcher degradation + circuit breaker,
the AutoML poison-step guard, and the new ``die``/``blackout`` fault
primitives. Everything is deterministic (utils/faults.py) and fast enough
for tier-1; ``pytest -m chaos`` selects the failure-semantics layer."""

import glob
import json
import os
import threading
import time

import numpy as np
import pandas as pd
import pytest

import h2o3_tpu
from h2o3_tpu.cluster import cloud, recovery, spmd
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models import GBM, GLM
from h2o3_tpu.utils import faults
from h2o3_tpu.utils import metrics as mx

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _fast_recovery(monkeypatch):
    monkeypatch.setenv("H2O3_TPU_RECOVERY", "1")
    monkeypatch.setenv("H2O3_TPU_RECOVERY_BACKOFF", "0.01")
    monkeypatch.setenv("H2O3_TPU_PERSIST_BACKOFF", "0.01")
    cloud.clear_degraded()
    yield
    faults.reset()
    cloud.clear_degraded()


def _df(n=1500, seed=3):
    rng = np.random.default_rng(seed)
    df = pd.DataFrame({
        "a": rng.normal(size=n),
        "b": rng.normal(size=n),
        "c": rng.choice(["x", "y", "z"], n),
    })
    eta = df["a"] * 1.5 + (df["c"] == "x") * 2 - df["b"]
    df["y"] = np.where(eta + rng.normal(size=n) > 0, "p", "n")
    return df


# ---------------------------------------------------------------------------
# the recover() state machine and generation semantics


def test_recover_ticks_generation_and_transitions():
    g0 = cloud.generation()
    before = mx.counter_value("cloud_health_transitions_total", to="recovering")
    assert cloud.recover("noop") == g0  # healthy: recover is a no-op
    cloud.mark_degraded("test: member died")
    g1 = cloud.recover("supervised reform")
    assert g1 == g0 + 1
    assert cloud.degraded_reason() is None
    assert cloud.cluster_info()["generation"] == g1
    assert mx.counter_value(
        "cloud_health_transitions_total", to="recovering") == before + 1


def test_clear_degraded_never_ticks_generation():
    """The manual escape hatch keeps today's semantics exactly: latch
    released, generation untouched — the fence stays inert for operators
    asserting the OLD cloud is fine."""
    g0 = cloud.generation()
    cloud.mark_degraded("test")
    cloud.clear_degraded()
    assert cloud.generation() == g0
    assert cloud.degraded_reason() is None


def test_adopt_generation_moves_forward_only():
    g0 = cloud.generation()
    cloud.adopt_generation(g0 + 3)
    assert cloud.generation() == g0 + 3
    cloud.adopt_generation(g0)  # never backwards
    assert cloud.generation() == g0 + 3


# ---------------------------------------------------------------------------
# generation fencing in spmd (the auto-restart correctness keystone)


def test_command_stamped_old_generation_is_rejected(monkeypatch):
    """A command that entered under generation N and queued behind a wedged
    command must fail-stop when it finally gets the lock on a cloud that
    re-formed to N+1 — it may NOT execute against the new formation."""
    monkeypatch.setattr(spmd, "_IS_MULTI", True)
    monkeypatch.setattr(spmd, "is_coordinator", lambda: True)
    from h2o3_tpu.cluster.registry import DKV

    DKV.put("fence_probe", "still here")
    outcome = []
    assert spmd._LOCK.acquire(timeout=1)  # stand-in for the wedged command
    try:
        def _caller():
            try:
                spmd.run("remove", key="fence_probe")
                outcome.append(None)
            except Exception as e:  # noqa: BLE001 — captured for assert
                outcome.append(e)

        t = threading.Thread(target=_caller)
        t.start()
        time.sleep(0.4)
        assert t.is_alive() and not outcome  # queued on the lock, gen N
        # the reform lands while the waiter sleeps (generation N -> N+1;
        # latch already released) — then the lock frees
        cloud.adopt_generation(cloud.generation() + 1)
        spmd._LOCK.release()
        t.join(timeout=5)
    except BaseException:
        spmd._LOCK.release()
        raise
    assert isinstance(outcome[0], spmd.StaleGeneration)
    assert "generation" in str(outcome[0])
    assert DKV.get("fence_probe") == "still here"  # never executed
    DKV.remove("fence_probe")


def test_queued_waiter_observes_failstop_during_reform(monkeypatch):
    """While the wedged command still holds the lock, a reform (latch set →
    recover) must unblock the waiter with a fail-stop — the generation poll
    in the bounded acquire, since the degraded window may close before the
    waiter ever polls the latch."""
    monkeypatch.setattr(spmd, "_IS_MULTI", True)
    monkeypatch.setattr(spmd, "is_coordinator", lambda: True)
    outcome = []
    assert spmd._LOCK.acquire(timeout=1)
    try:
        def _caller():
            try:
                spmd.run("remove", key="nope")
                outcome.append(None)
            except Exception as e:  # noqa: BLE001
                outcome.append(e)

        t = threading.Thread(target=_caller)
        t.start()
        time.sleep(0.4)
        assert t.is_alive() and not outcome
        cloud.mark_degraded("test: wedge")
        cloud.recover("reform while the wedge still holds the lock")
        t.join(timeout=5)  # lock is STILL held — only the poll frees it
        assert not t.is_alive()
    finally:
        spmd._LOCK.release()
    # the waiter observed the fail-stop — as a stale-generation rejection
    # (it slept through the whole degraded window) or, if a poll landed
    # inside the brief latched window, as the degraded fail-stop error;
    # either way it never executed against the re-formed cloud
    assert isinstance(outcome[0], (spmd.StaleGeneration, RuntimeError))
    assert ("generation" in str(outcome[0])
            or "fail-stop" in str(outcome[0]))


def test_follower_fence_rejects_stale_adopts_newer():
    g = cloud.generation()
    assert spmd._stale_reason(None) is None       # legacy payloads pass
    assert spmd._stale_reason(g) is None          # current generation passes
    reason = spmd._stale_reason(g - 1)            # pre-reform: rejected
    assert reason and "stale-generation" in reason
    assert spmd._stale_reason(g + 2) is None      # newer: adopted
    assert cloud.generation() == g + 2


# ---------------------------------------------------------------------------
# supervised auto-resume: worker death mid-train completes WITHOUT operator
# action, pinned against the uninterrupted run (the acceptance drills)


def _latest_snapshot(ckdir, prefix):
    files = glob.glob(os.path.join(ckdir, f"{prefix}_ckpt_*"))
    assert files, f"no {prefix} snapshot in {ckdir}"
    return max(files, key=os.path.getmtime)


def test_gbm_worker_death_auto_resumes(tmp_path):
    fr = Frame.from_pandas(_df())
    kw = dict(max_depth=3, seed=11, learn_rate=0.2, score_tree_interval=2)
    full = GBM(ntrees=8, **kw).train(y="y", training_frame=fr)

    ckdir = str(tmp_path / "gbm_heal")
    g0 = cloud.generation()
    resumed_before = mx.counter_value("recovery_attempts_total",
                                      outcome="resumed")

    def _launch(ckpt):
        kw2 = dict(kw, export_checkpoints_dir=ckdir)
        if ckpt:
            kw2["checkpoint"] = ckpt
        return GBM(ntrees=8, **kw2).train(y="y", training_frame=fr)

    with faults.inject(die={"gbm"}):
        healed = recovery.run_supervised(_launch, ckdir=ckdir, algo="gbm",
                                         description="gbm drill")
    # no operator action: the run completed, the cloud re-formed once
    assert healed.output["ntrees_actual"] == 8
    assert cloud.degraded_reason() is None
    assert cloud.generation() == g0 + 1
    assert mx.counter_value("recovery_attempts_total",
                            outcome="resumed") == resumed_before + 1
    np.testing.assert_allclose(
        healed.training_metrics.logloss, full.training_metrics.logloss,
        atol=1e-6)
    pa = full.predict(fr).vec("p").to_numpy()
    pb = healed.predict(fr).vec("p").to_numpy()
    np.testing.assert_allclose(pa, pb, atol=1e-5)


def test_glm_worker_death_auto_resumes(tmp_path):
    fr = Frame.from_pandas(_df(seed=5))
    kw = dict(family="binomial", max_iterations=25, seed=1)
    full = GLM(**kw).train(y="y", training_frame=fr)

    ckdir = str(tmp_path / "glm_heal")

    def _launch(ckpt):
        kw2 = dict(kw, export_checkpoints_dir=ckdir)
        if ckpt:
            kw2["checkpoint"] = ckpt
        return GLM(**kw2).train(y="y", training_frame=fr)

    with faults.inject(die={"glm"}):
        healed = recovery.run_supervised(_launch, ckdir=ckdir, algo="glm",
                                         description="glm drill")
    # the restored loop position replays the identical IRLS trajectory
    np.testing.assert_array_equal(
        np.asarray(healed.output["beta_std"]),
        np.asarray(full.output["beta_std"]))
    np.testing.assert_allclose(
        healed.training_metrics.logloss, full.training_metrics.logloss,
        atol=1e-6)


def test_automl_worker_death_auto_resumes(tmp_path, monkeypatch):
    import h2o3_tpu.automl.automl as A

    fr = Frame.from_pandas(_df(600, seed=7))
    tiny = [
        A._Step("s_gbm1", "model", "gbm",
                dict(ntrees=6, max_depth=3, score_tree_interval=3)),
        A._Step("s_glm", "model", "glm", dict()),
        A._Step("s_gbm2", "model", "gbm",
                dict(ntrees=6, max_depth=2, score_tree_interval=3)),
    ]
    monkeypatch.setattr(
        A, "_default_plan",
        lambda: [A._Step(s.name, s.kind, s.algo, dict(s.params),
                         dict(s.hyper), s.weight) for s in tiny],
    )
    spec = dict(max_models=3, nfolds=2, seed=11, max_runtime_secs=0.0,
                project_name="healml")

    def lb_table(aml):
        return sorted(
            (r["model_id"].split("_")[0], round(float(r["auc"]), 10))
            for r in aml.leaderboard.as_table()
        )

    full = A.AutoML(**spec)
    full.train(y="y", training_frame=fr)

    ckdir = str(tmp_path / "aml_heal")

    def _launch(_ckpt):
        # each attempt is a fresh AutoML over the same dir: the step
        # manifest IS the checkpoint (finished steps recover, the poisoned
        # ones are guarded)
        aml = A.AutoML(export_checkpoints_dir=ckdir, **spec)
        aml.train(y="y", training_frame=fr)
        return aml

    with faults.inject(die={"automl"}):
        healed = recovery.run_supervised(_launch, description="automl drill")
    assert "recover" in {e["stage"] for e in healed.event_log}
    assert lb_table(healed) == lb_table(full)
    assert cloud.degraded_reason() is None


def test_rest_build_supervised_auto_resume(tmp_path):
    """The production surface end-to-end: a checkpointed REST build survives
    an injected worker death — the job completes DONE with restarts=1 in
    /3/Jobs, no operator in the path."""
    from h2o3_tpu.api.server import start_server
    from h2o3_tpu.client import H2OConnection

    srv = start_server(port=0)
    Frame.from_pandas(_df(400, seed=13), destination_frame="heal_fr")
    conn = H2OConnection(srv.url)
    ckdir = str(tmp_path / "rest_heal")
    with faults.inject(die={"gbm"}):
        model = conn.train("gbm", y="y", training_frame="heal_fr",
                           ntrees=4, max_depth=2, seed=1,
                           score_tree_interval=2,
                           export_checkpoints_dir=ckdir)
    # the build completed: the DKV model is the full 4-tree forest
    mkey = model["model_id"]["name"]
    from h2o3_tpu.cluster.registry import DKV

    assert DKV.get(mkey).output["ntrees_actual"] == 4
    jkey = None
    for j in conn.get("/3/Jobs")["jobs"]:
        if j.get("restarts"):
            jkey = j["key"]["name"]
            assert j["restarts"] == 1
            assert j["status"] == "DONE"
    assert jkey, "no job surfaced a supervised restart over /3/Jobs"
    info = conn.get("/3/Cloud")
    assert info["cloud_healthy"] and info["generation"] >= 1


def test_rest_recover_route(monkeypatch):
    from h2o3_tpu.api.server import start_server
    from h2o3_tpu.client import H2OConnection

    srv = start_server(port=0)
    conn = H2OConnection(srv.url)
    g0 = cloud.generation()
    out = conn.post("/3/Recover")  # healthy: idempotent no-op
    assert out["recovered"] is False and out["generation"] == g0
    cloud.mark_degraded("test: REST recover drill")
    out = conn.post("/3/Recover")
    assert out["recovered"] is True and out["generation"] == g0 + 1
    assert out["cloud_healthy"] is True
    # disabled: the latch is one-way over REST too
    cloud.mark_degraded("test: latched under RECOVERY=0")
    monkeypatch.setenv("H2O3_TPU_RECOVERY", "0")
    from h2o3_tpu.client import H2OClientError

    with pytest.raises(H2OClientError) as ei:
        conn._request_once("POST", "/3/Recover", None, False)
    assert ei.value.status == 409
    assert cloud.degraded_reason() is not None


def test_crash_during_checkpoint_write_falls_back(tmp_path):
    """A run that dies WHILE writing its interval snapshot leaves a
    truncated ``<algo>_ckpt_*`` — latest_snapshot must skip the torn file
    (with a warning, not a crash) and the supervisor falls back to the
    previous intact snapshot, still resuming 1e-6-clean."""
    fr = Frame.from_pandas(_df())
    kw = dict(ntrees=8, max_depth=3, seed=11, learn_rate=0.2,
              score_tree_interval=2)
    full = GBM(**kw).train(y="y", training_frame=fr)

    ckdir = str(tmp_path / "torn_ck")
    # first attempt dies at tree 4 with an intact 4-tree snapshot...
    with faults.inject(abort={"gbm": 4}):
        with pytest.raises(faults.TrainAbort):
            GBM(export_checkpoints_dir=ckdir, **kw).train(
                y="y", training_frame=fr)
    snap4 = recovery.latest_snapshot(ckdir, "gbm")
    assert snap4 is not None
    # ...then the crash-during-write: a NEWER but truncated snapshot file
    with open(snap4, "rb") as f:
        blob = f.read()
    torn = os.path.join(ckdir, "gbm_ckpt_torn")
    with open(torn, "wb") as f:
        f.write(blob[: len(blob) // 3])
    now = time.time()
    os.utime(torn, (now + 60, now + 60))
    assert recovery.latest_snapshot(ckdir, "gbm") == snap4  # torn skipped

    def _launch(ckpt):
        kw2 = dict(kw, export_checkpoints_dir=ckdir)
        if ckpt:
            kw2["checkpoint"] = ckpt
        return GBM(**kw2).train(y="y", training_frame=fr)

    with faults.inject(die={"gbm"}):
        healed = recovery.run_supervised(_launch, ckdir=ckdir, algo="gbm",
                                         description="torn-ckpt gbm")
    assert healed.output["ntrees_actual"] == 8
    np.testing.assert_allclose(
        healed.training_metrics.logloss, full.training_metrics.logloss,
        atol=1e-6)


# ---------------------------------------------------------------------------
# H2O3_TPU_RECOVERY=0 restores today's fail-stop semantics bit-for-bit


def test_recovery_disabled_restores_failstop(monkeypatch):
    monkeypatch.setenv("H2O3_TPU_RECOVERY", "0")
    g0 = cloud.generation()
    calls = []

    def _launch(ckpt):
        calls.append(ckpt)
        raise faults.make_death_error()

    with pytest.raises(faults.XlaRuntimeError):
        recovery.run_supervised(_launch, description="disabled drill")
    assert calls == [None]  # exactly one attempt, no reform
    assert cloud.generation() == g0
    # and the latch stays one-way: nothing auto-clears it
    cloud.mark_degraded("test: latched")
    time.sleep(0.1)
    assert cloud.degraded_reason() is not None


def test_recovery_budget_exhausted(monkeypatch):
    monkeypatch.setenv("H2O3_TPU_RECOVERY_MAX_RESTARTS", "2")
    exhausted_before = mx.counter_value("recovery_attempts_total",
                                        outcome="exhausted")
    calls = []

    def _launch(ckpt):
        calls.append(ckpt)
        raise faults.make_death_error()

    with pytest.raises(recovery.RecoveryExhausted, match="gave up after 2"):
        recovery.run_supervised(_launch, description="hopeless drill")
    assert len(calls) == 3  # 1 + 2 restarts
    assert mx.counter_value("recovery_attempts_total",
                            outcome="exhausted") == exhausted_before + 1


def test_deterministic_failure_never_retried():
    calls = []

    def _launch(ckpt):
        calls.append(ckpt)
        raise ValueError("bad params")

    with pytest.raises(ValueError):
        recovery.run_supervised(_launch, description="deterministic")
    assert calls == [None]
    # TrainAbort (simulated kill -9 of THIS process) is not a cloud failure
    assert not recovery.is_cloud_failure(faults.TrainAbort("kill -9"))


# ---------------------------------------------------------------------------
# batcher degradation + circuit breaker (the serving half)


class _WedgeScorer:
    """First dispatch wedges (a dead collective); later ones return."""

    def __init__(self):
        self.release = threading.Event()
        self.calls = 0

    def score_table(self, cols, n):
        self.calls += 1
        if self.calls == 1:
            self.release.wait(15)
        return {"predict": np.zeros(n)}


class _FakeModel:
    key = "breaker_model"


def test_batcher_degradation_fails_fast_and_breaker_reopens(monkeypatch):
    from h2o3_tpu.serving import ShedError
    from h2o3_tpu.serving.batcher import ModelBatcher

    monkeypatch.setenv("H2O3_TPU_SCORE_BATCH_WINDOW_MS", "10")
    monkeypatch.setenv("H2O3_TPU_SCORE_DEADLINE_MS", "8000")  # deliberately long
    sc = _WedgeScorer()
    b = ModelBatcher(_FakeModel(), sc)
    cols = {"a": np.zeros(1)}
    results = []

    def _req():
        try:
            b.submit(dict(cols), 1)
            results.append(None)
        except Exception as e:  # noqa: BLE001 — captured for assert
            results.append(e)

    t1 = threading.Thread(target=_req)
    t1.start()
    deadline = time.time() + 5
    while sc.calls == 0 and time.time() < deadline:
        time.sleep(0.01)
    assert sc.calls == 1  # dispatcher is now wedged mid-dispatch
    t2 = threading.Thread(target=_req)
    t2.start()
    time.sleep(0.2)

    shed_before = mx.counter_value("serving_shed_total", reason="degraded")
    t0 = time.time()
    cloud.mark_degraded("test: training cloud incident")
    t1.join(timeout=5)
    t2.join(timeout=5)
    fast = time.time() - t0
    # both in-flight requests failed FAST with the 503 contract — nowhere
    # near the 8 s deadline they would otherwise burn
    assert fast < 2.0, fast
    assert len(results) == 2
    for e in results:
        assert isinstance(e, ShedError) and e.status == 503
        assert e.retry_after
    assert mx.counter_value("serving_shed_total",
                            reason="degraded") >= shed_before + 1

    # breaker is open while the cloud stays degraded: instant shed
    t0 = time.time()
    with pytest.raises(ShedError) as ei:
        b.submit(dict(cols), 1)
    assert ei.value.status == 503 and "breaker" in str(ei.value)
    assert time.time() - t0 < 0.2

    # recovery half-opens the breaker; the probe re-admits traffic
    cloud.recover("test: incident over")
    out = b.submit(dict(cols), 1)  # the probe — dispatches on a fresh thread
    assert len(out["predict"]) == 1
    assert b._breaker.state == "closed"
    out = b.submit(dict(cols), 1)  # steady state restored
    assert len(out["predict"]) == 1
    sc.release.set()  # unwedge the stuck dispatcher for cleanup


# ---------------------------------------------------------------------------
# AutoML poison-step guard


def test_automl_poison_step_skipped_after_retry_budget(tmp_path, monkeypatch):
    import h2o3_tpu.automl.automl as A

    monkeypatch.setenv("H2O3_TPU_AUTOML_STEP_RETRIES", "2")
    fr = Frame.from_pandas(_df(600, seed=17))
    tiny = [
        A._Step("poison_gbm", "model", "gbm",
                dict(ntrees=4, max_depth=3, score_tree_interval=2)),
        A._Step("ok_glm", "model", "glm", dict()),
        A._Step("ok_gbm", "model", "gbm",
                dict(ntrees=4, max_depth=2, score_tree_interval=2)),
    ]
    monkeypatch.setattr(
        A, "_default_plan",
        lambda: [A._Step(s.name, s.kind, s.algo, dict(s.params),
                         dict(s.hyper), s.weight) for s in tiny],
    )
    ckdir = str(tmp_path / "poison_ck")
    spec = dict(max_models=3, nfolds=0, seed=11, max_runtime_secs=0.0,
                project_name="poisonml", export_checkpoints_dir=ckdir)

    # the poison step crashes DETERMINISTICALLY on every resume (re-armed
    # abort at the same tree) — without the guard this loops forever
    for attempt in range(2):
        with faults.inject(abort={"gbm": 2}):
            with pytest.raises(faults.TrainAbort):
                A.AutoML(**spec).train(y="y", training_frame=fr)
        manifest = json.load(
            open(os.path.join(ckdir, "poisonml.automl.json")))
        assert manifest["attempts"]["poison_gbm"] == attempt + 1

    # third resume: budget exhausted → the step is SKIPPED and the run
    # completes with the healthy steps
    healed = A.AutoML(**spec)
    healed.train(y="y", training_frame=fr)
    stages = {e["stage"] for e in healed.event_log}
    assert "skip" in stages
    assert any("poison_gbm" in e["message"] for e in healed.event_log
               if e["stage"] == "skip")
    assert len(healed.leaderboard.models) == 2  # glm + the healthy gbm


# ---------------------------------------------------------------------------
# blackout fault primitive: a persist outage window


def test_blackout_rides_out_within_retry_budget(tmp_path, monkeypatch):
    from h2o3_tpu.persist import write_bytes

    monkeypatch.setenv("H2O3_TPU_PERSIST_RETRIES", "8")
    monkeypatch.setenv("H2O3_TPU_PERSIST_BACKOFF", "0.05")
    tgt = str(tmp_path / "rode_out.bin")
    t0 = time.time()
    with faults.inject(blackout=0.15):
        write_bytes(b"payload", tgt)
        assert faults.counts()["persist_write"] >= 2  # retried through it
    assert time.time() - t0 >= 0.15  # the outage was real
    with open(tgt, "rb") as f:
        assert f.read() == b"payload"


def test_blackout_surfaces_past_budget(tmp_path, monkeypatch):
    from h2o3_tpu.persist import write_bytes

    monkeypatch.setenv("H2O3_TPU_PERSIST_RETRIES", "1")
    monkeypatch.setenv("H2O3_TPU_PERSIST_BACKOFF", "0.01")
    tgt = str(tmp_path / "never.bin")
    with faults.inject(blackout=5.0):
        with pytest.raises(faults.InjectedIOError, match="blackout"):
            write_bytes(b"payload", tgt)
    assert not os.path.exists(tgt)


# ---------------------------------------------------------------------------
# client: failure/timeout errors embed the recovery pointer


def test_client_job_failure_embeds_recovery_pointer(tmp_path):
    from h2o3_tpu.api.server import start_server
    from h2o3_tpu.client import H2OClientError, H2OConnection

    srv = start_server(port=0)
    Frame.from_pandas(_df(400, seed=23), destination_frame="ptr_fr")
    conn = H2OConnection(srv.url, retries=0)
    ckdir = str(tmp_path / "ptr_ck")
    # TrainAbort is NOT a cloud failure: the supervised path propagates it
    # (a dead process cannot supervise itself) and the job FAILS with its
    # recovery block populated — which the client error must carry
    with faults.inject(abort={"gbm": 2}):
        with pytest.raises(H2OClientError) as ei:
            conn.train("gbm", y="y", training_frame="ptr_fr",
                       ntrees=6, max_depth=2, seed=1, score_tree_interval=2,
                       export_checkpoints_dir=ckdir)
    e = ei.value
    assert e.recovery, "client error carries no recovery pointer"
    assert e.recovery["checkpoint_path"] == _latest_snapshot(ckdir, "gbm")
    assert "resumable" in str(e) and e.recovery["checkpoint_path"] in str(e)
    # the pointer is live: resuming from it works without a /3/Jobs trip
    prior = h2o3_tpu.load_model(e.recovery["checkpoint_path"])
    assert prior.output["ntrees_actual"] == 2
