"""Codegen output stays in sync and structurally sound.

The generated estimator surfaces (Python + R) are checked in, like
upstream's h2o-bindings output; these tests catch a params-dataclass edit
that was not followed by a regen, and structural breakage in the R file
(which no R runtime on CI can parse for us).
"""

from __future__ import annotations

import re
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent


def _gen():
    sys.path.insert(0, str(REPO / "tools"))
    try:
        import gen_bindings
    finally:
        sys.path.pop(0)
    return gen_bindings


def test_python_bindings_up_to_date():
    gb = _gen()
    assert gb.render() == (REPO / "h2o3_tpu" / "estimators_gen.py").read_text(), (
        "estimators_gen.py is stale — run: python tools/gen_bindings.py"
    )


def test_r_bindings_up_to_date():
    gb = _gen()
    assert gb.render_r() == (REPO / "r" / "estimators_gen.R").read_text(), (
        "r/estimators_gen.R is stale — run: python tools/gen_bindings.py"
    )


def test_r_bindings_structure():
    src = (REPO / "r" / "estimators_gen.R").read_text()
    # every algo function present, one definition each
    funcs = re.findall(r"^(h2o\.\w+) <- function\(", src, re.M)
    assert len(funcs) == len(set(funcs)) == 29
    # balanced delimiters (cheap parse sanity without an R runtime)
    for o, c in ("()", "{}"):
        assert src.count(o) == src.count(c), f"unbalanced {o}{c}"
    # no Python literals leaked through the default renderer
    assert not re.search(r"= (True|False|None)\b", src)
    # upstream arg-name parity: GLM exposes `lambda`, not the field name
    assert "lambda = NULL" in src
    assert "lambda_" not in src.replace("lambda_search", "").replace(
        "lambda_min_ratio", ""
    )


def test_glm_lambda_alias_resolves():
    from h2o3_tpu.models.glm import GLM

    b = GLM(**{"lambda": 0.25, "family": "gaussian"})
    assert b.params.lambda_ == 0.25
    with pytest.raises(ValueError, match="alias"):
        GLM(**{"lambda": 0.1, "lambda_": 0.1})


def test_estimator_accepts_lambda_alias():
    from h2o3_tpu.estimators_gen import H2OGeneralizedLinearEstimator

    # the generated signature uses lambda_ (Python keyword), but the runtime
    # estimator path accepts the alias too
    from h2o3_tpu.estimators import _EstimatorBase

    class _E(_EstimatorBase):
        _BUILDER = "GLM"

    e = _E(**{"lambda": 0.5})
    assert e._kwargs == {"lambda": 0.5}
    assert H2OGeneralizedLinearEstimator(lambda_=0.5)._kwargs == {"lambda_": 0.5}
