"""Multi-chip correctness = equality: the sharded reductions (histogram
psum, Gram einsum) and whole-model results must be independent of the mesh
size — an 8-device run is the same computation as a 1-device run, just
distributed. This pins the actual multi-chip correctness claim, not merely
"it executes" (VERDICT r3 weak #10)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _mesh(k: int) -> Mesh:
    devs = jax.devices("cpu")
    assert len(devs) >= k, (
        f"need {k} CPU devices for the cross-mesh equality claim, have "
        f"{len(devs)} — the 8-device conftest pin did not land"
    )
    return Mesh(np.array(devs[:k]), ("rows",))


def test_histogram_equal_across_mesh_sizes():
    from h2o3_tpu.ops.histogram import histogram_in_jit

    rng = np.random.default_rng(0)
    n, c, n_nodes, n_bins = 4096, 6, 16, 64
    bins = jnp.asarray(rng.integers(0, n_bins, (n, c)).astype(np.uint8))
    nid = jnp.asarray(rng.integers(-1, n_nodes, n).astype(np.int32))
    w = jnp.asarray(rng.random(n).astype(np.float32))
    wy = jnp.asarray(rng.normal(size=n).astype(np.float32))
    wh = w

    def run(k):
        m = _mesh(k)
        sh = NamedSharding(m, P("rows"))
        args = [jax.device_put(a, sh) for a in (bins, nid, w, wy, wh)]
        f = jax.jit(
            lambda b, i, *s: histogram_in_jit(b, i, s, n_nodes, n_bins, mesh=m)
        )
        return np.asarray(f(*args))

    h1, h8 = run(1), run(8)
    # f32 partial-sum order differs across shard counts; the envelope is a
    # few ulps of the accumulated mass
    np.testing.assert_allclose(h8, h1, rtol=3e-6, atol=3e-4)


def test_gram_equal_across_mesh_sizes():
    from h2o3_tpu.ops.gram import weighted_gram

    rng = np.random.default_rng(1)
    n, p = 8192, 12
    X = rng.normal(size=(n, p)).astype(np.float32)
    w = rng.random(n).astype(np.float32)
    z = rng.normal(size=n).astype(np.float32)

    def run(k):
        sh = NamedSharding(_mesh(k), P("rows"))
        G, b, ws = weighted_gram(
            jax.device_put(X, sh), jax.device_put(w, sh), jax.device_put(z, sh)
        )
        return np.asarray(G), np.asarray(b), float(ws)

    G1, b1, ws1 = run(1)
    G8, b8, ws8 = run(8)
    np.testing.assert_allclose(G8, G1, rtol=2e-6, atol=2e-3)
    np.testing.assert_allclose(b8, b1, rtol=2e-6, atol=2e-3)
    assert abs(ws8 - ws1) < 1e-2
