"""Fused Pallas histogram→split pipeline (ISSUE 6, ``H2O3_TPU_SPLIT_FUSE``):
the blocked-layout histogram kernel + VMEM-tile split kernel + winner
assembly must be INDISTINGUISHABLE from the unfused pipeline — split
decisions, predictions and varimp bit-equal on the PR-5 adversarial tie
suites across 1/2/8-device meshes (interpret mode on the CPU CI cloud),
mixed categorical/numeric frames must route cat columns to the fallback
scan, and the kernel result must track an f64 reference within the bf16
2-term split's accuracy envelope (carried over from test_hist_pallas.py).
"""

import contextlib
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from h2o3_tpu.models.tree import shared_tree as st
from h2o3_tpu.parallel import mesh as pm


@contextlib.contextmanager
def _use_mesh(k: int):
    devs = jax.devices("cpu")
    assert len(devs) >= k, "8-device conftest pin did not land"
    old = pm._mesh
    pm.set_mesh(Mesh(np.array(devs[:k]), (pm.ROWS_AXIS,)))
    try:
        yield
    finally:
        pm.set_mesh(old)


@contextlib.contextmanager
def _env(**kv):
    old = {k: os.environ.get(k) for k in kv}
    os.environ.update({k: str(v) for k, v in kv.items()})
    try:
        yield
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _bits(a) -> bytes:
    return np.ascontiguousarray(np.asarray(a)).tobytes()


_FIELDS = (
    "split_col", "split_bin", "is_cat", "cat_mask", "na_left", "leaf_now",
    "leaf_val", "child_base", "gain", "node_w",
)


def _assert_trees_bit_equal(a: st.Tree, b: st.Tree, what: str):
    ha, hb = a.to_host(), b.to_host()
    assert len(ha.levels) == len(hb.levels), what
    for li, (la, lb) in enumerate(zip(ha.levels, hb.levels)):
        for k in _FIELDS:
            assert _bits(getattr(la, k)) == _bits(getattr(lb, k)), (
                f"{what}: level {li} field {k} diverged between fused and "
                f"unfused split pipelines"
            )


def _build_one(bins_np, t_np, *, split_fuse, hist="pallas", max_depth=3,
               n_bins=16, node_cap=2048, min_rows=1.0, env=None,
               is_cat=None, seed=5, monotone=None):
    """build_tree under the given H2O3_TPU_SPLIT_FUSE on the CURRENT mesh.
    ``hist='pallas'`` pins BOTH pipelines to the Pallas histogram kernel
    (interpreter on CPU) so the comparison isolates the split pipeline."""
    n, C = bins_np.shape
    with _env(H2O3_TPU_SPLIT_FUSE=split_fuse, H2O3_TPU_HIST=hist,
              **(env or {})):
        bins = pm.shard_rows(jnp.asarray(bins_np))
        w = pm.shard_rows(jnp.ones(n, jnp.float32))
        t = pm.shard_rows(jnp.asarray(t_np, dtype=jnp.float32))
        h = pm.shard_rows(jnp.ones(n, jnp.float32))
        preds = pm.shard_rows(jnp.zeros(n, jnp.float32))
        tree, preds, varimp = st.build_tree(
            bins, w, t, h,
            n_bins=n_bins,
            is_cat_cols=(np.zeros(C, bool) if is_cat is None else is_cat),
            max_depth=max_depth,
            min_rows=min_rows,
            min_split_improvement=0.0,
            learn_rate=0.1,
            preds=preds,
            key=jax.random.PRNGKey(seed),
            varimp=jnp.zeros(C, jnp.float32),
            node_cap=node_cap,
            monotone=monotone,
        )
        return tree, np.asarray(preds), np.asarray(varimp)


def _tie_data(n_pad: int, C: int, n_bins: int, seed=0):
    """PR-5 adversarial exact-tie data: unit weights, constant target —
    every candidate gain is exactly 0.0 and every column is a duplicate,
    so only lowest-global-index tie-breaking picks the winner."""
    rng = np.random.default_rng(seed)
    base = rng.integers(1, n_bins, n_pad).astype(np.uint8)
    return np.tile(base[:, None], (1, C)), np.ones(n_pad, np.float32)


@pytest.mark.parametrize("k", [1, 2, 8])
def test_fused_tie_break_constant_target(k):
    """Constant target: every (col, bin) candidate gain is exactly 0.0;
    the fused kernel's per-column argmax + the assembly's column argmax
    must land on jnp.argmax's lowest-global-index choice on any mesh."""
    with _use_mesh(k):
        n_pad = pm.pad_to_shards(960)
        bins, t = _tie_data(n_pad, C=13, n_bins=16)
        t1, p1, v1 = _build_one(bins, t, split_fuse="1")
        t0, p0, v0 = _build_one(bins, t, split_fuse="0")
        _assert_trees_bit_equal(t1, t0, f"fused-ties/{k}dev")
        assert _bits(p1) == _bits(p0)
        assert _bits(v1) == _bits(v0)
        assert int(np.asarray(t1.levels[0].split_col)[0]) == 0


@pytest.mark.parametrize("k", [2, 8])
def test_fused_tie_break_duplicated_columns_nonzero_gains(k):
    """Duplicated columns spanning blocks with a real ±1 signal (exact in
    f32): identical non-zero best gains in several column tiles at once —
    the sharded fused merge must pick the lowest global column."""
    with _use_mesh(k):
        n_pad = pm.pad_to_shards(960)
        rng = np.random.default_rng(3)
        bins, _ = _tie_data(n_pad, C=16, n_bins=16, seed=3)
        t = (rng.integers(0, 2, n_pad) * 2 - 1).astype(np.float32)
        t1, p1, v1 = _build_one(bins, t, split_fuse="1", max_depth=4)
        t0, p0, v0 = _build_one(bins, t, split_fuse="0", max_depth=4)
        _assert_trees_bit_equal(t1, t0, f"fused-dup-cols/{k}dev")
        assert _bits(p1) == _bits(p0) and _bits(v1) == _bits(v0)
        masks = t0.real_level_masks()
        for lv, m in zip(t0.to_host().levels, masks):
            split = ~np.asarray(lv.leaf_now) & m
            assert (np.asarray(lv.split_col)[split] == 0).all()


@pytest.mark.parametrize("subtract", ["1", "0"])
def test_fused_parity_both_force_leaf_paths(subtract):
    """Both terminal regimes under fuse: subtract=1 derives leaf stats from
    the parents' splits (no histogram), subtract=0 force-leafs from the
    blocked histogram's column-0 totals. Integer targets keep every sum
    exact, so parity is bitwise."""
    n_pad = pm.pad_to_shards(700)
    rng = np.random.default_rng(7)
    bins = rng.integers(0, 16, (n_pad, 7)).astype(np.uint8)  # 7 % 8 != 0
    t = rng.integers(-3, 4, n_pad).astype(np.float32)
    env = {"H2O3_TPU_HIST_SUBTRACT": subtract}
    t1, p1, v1 = _build_one(bins, t, split_fuse="1", env=env)
    t0, p0, v0 = _build_one(bins, t, split_fuse="0", env=env)
    _assert_trees_bit_equal(t1, t0, f"fused-force-leaf/subtract={subtract}")
    assert _bits(p1) == _bits(p0) and _bits(v1) == _bits(v0)


def test_fused_parity_coarsened_saturated_levels():
    """Deep tree, small node_cap, bin adaptivity on: the saturated
    while_loop runs at coarsened bins — blocked_coarsen + the blocked
    sibling-subtraction carry must stay bit-equal to the dense pipeline."""
    n_pad = pm.pad_to_shards(600)
    rng = np.random.default_rng(11)
    bins = rng.integers(0, 255, (n_pad, 6)).astype(np.uint8)
    t = rng.integers(-3, 4, n_pad).astype(np.float32)
    env = {"H2O3_TPU_BIN_ADAPT": "1", "H2O3_TPU_SHAPE_BUCKETS": "0"}
    kw = dict(max_depth=8, n_bins=255, node_cap=8)
    t1, p1, v1 = _build_one(bins, t, split_fuse="1", env=env, **kw)
    t0, p0, v0 = _build_one(bins, t, split_fuse="0", env=env, **kw)
    shifts = st._bin_shifts(8, 255, ())
    assert st._sat_region(8, 8, shifts)[1] >= 2
    _assert_trees_bit_equal(t1, t0, "fused-coarsened-sat")
    assert _bits(p1) == _bits(p0) and _bits(v1) == _bits(v0)


@pytest.mark.parametrize("k", [1, 8])
def test_fused_mixed_categorical_routes_to_fallback(k):
    """Mixed categorical/numeric frame: on 1 device the fused pipeline
    routes cat columns to the mean-sort fallback branch (numeric stays on
    the kernel); on an 8-device mesh every block runs the mean-sort branch
    on its BLOCK-LOCAL dense gather inside the fused sharded scan (the
    ISSUE-15 closure — the build no longer drops to the dense scan).
    Either way: bit parity."""
    with _use_mesh(k):
        n_pad = pm.pad_to_shards(700)
        rng = np.random.default_rng(13)
        bins = rng.integers(0, 16, (n_pad, 7)).astype(np.uint8)
        bins[:, 2] = rng.integers(0, 7, n_pad)   # cat col, 6 levels
        bins[:, 5] = rng.integers(0, 5, n_pad)   # cat col, 4 levels
        is_cat = np.zeros(7, bool)
        is_cat[[2, 5]] = True
        t = rng.integers(-3, 4, n_pad).astype(np.float32)
        t1, p1, v1 = _build_one(bins, t, split_fuse="1", is_cat=is_cat)
        t0, p0, v0 = _build_one(bins, t, split_fuse="0", is_cat=is_cat)
        _assert_trees_bit_equal(t1, t0, f"fused-cat/{k}dev")
        assert _bits(p1) == _bits(p0) and _bits(v1) == _bits(v0)
        # the trees must actually use a categorical split somewhere, or the
        # routing was never exercised
        assert any(
            np.asarray(lv.is_cat)[~np.asarray(lv.leaf_now) & m].any()
            for lv, m in zip(t0.to_host().levels, t0.real_level_masks())
        )
        assert _split_fuse_expected(k, is_cat.any())


def _split_fuse_expected(k: int, any_cat: bool) -> bool:
    """Document the POST-CLOSURE fallback matrix in executable form: with
    the gate on, categorical + sharded builds fuse too (only uplift falls
    back structurally — and tallies tree_fused_fallbacks_total)."""
    with _env(H2O3_TPU_SPLIT_FUSE="1"):
        active = st._split_fuse_active(
            (2, 5) if any_cat else (), st._split_shard_on()
        )
    return active


def _free_compile_state():
    """Drop in-memory compiled executables after a compile-heavy test.

    These ISSUE-15 suites add ~50 whole-tree-sized programs (mono/cat
    sweeps across three sub-meshes, the autotuner's candidate grid) to a
    tier-1 process that already holds several hundred; past that point
    this jaxlib's CPU backend can segfault inside XLA codegen on the NEXT
    large compile (reproduced at test_fused_via_dense/f64_accuracy —
    fresh-process compiles of the identical HLO are fine). Freeing the
    one-shot executables keeps the long-lived process at its pre-ISSUE-15
    footprint; later tests re-read the persistent compile cache instead
    of recompiling, so the wall cost is small."""
    jax.clear_caches()


@pytest.mark.parametrize("k", [1, 2, 8])
def test_fused_mono_tie_break(k):
    """ISSUE-15 closure (a): monotone builds run the fused Pallas lane —
    the constraint mask lives in the kernel grid step and the bound state
    rides the fused level carry. Adversarial exact-tie data (constant
    target, duplicated columns): decisions must be bit-equal to the
    SPLIT_FUSE=0 path (the legacy per-level mono loop) on every mesh."""
    with _use_mesh(k):
        n_pad = pm.pad_to_shards(960)
        bins, t = _tie_data(n_pad, C=13, n_bins=16)
        mono = np.zeros(13, np.int32)
        mono[[0, 4, 9]] = 1
        mono[[2, 7]] = -1
        t1, p1, v1 = _build_one(bins, t, split_fuse="1", monotone=mono)
        t0, p0, v0 = _build_one(bins, t, split_fuse="0", monotone=mono)
        _assert_trees_bit_equal(t1, t0, f"fused-mono-ties/{k}dev")
        assert _bits(p1) == _bits(p0) and _bits(v1) == _bits(v0)
    _free_compile_state()


@pytest.mark.parametrize("k", [1, 2, 8])
def test_fused_mono_constrained_signal(k):
    """Monotone fused lane on a frame with a REAL signal that violates the
    constraint on some columns: the fused build must both match the
    unfused mono path bit-for-bit (integer-exact sums) and actually
    enforce the constraint (leaf means along a +1 column never decrease
    with the bin, checked through predictions on a 1-column sweep)."""
    with _use_mesh(k):
        n_pad = pm.pad_to_shards(960)
        rng = np.random.default_rng(31)
        bins = rng.integers(1, 16, (n_pad, 6)).astype(np.uint8)
        # target ANTI-monotone in column 0 — the +1 constraint must refuse
        # those splits (or clamp their children)
        t = (16.0 - bins[:, 0].astype(np.float32)
             + rng.integers(-2, 3, n_pad).astype(np.float32))
        mono = np.zeros(6, np.int32)
        mono[0] = 1
        t1, p1, v1 = _build_one(bins, t, split_fuse="1", monotone=mono,
                                max_depth=4)
        t0, p0, v0 = _build_one(bins, t, split_fuse="0", monotone=mono,
                                max_depth=4)
        _assert_trees_bit_equal(t1, t0, f"fused-mono-signal/{k}dev")
        assert _bits(p1) == _bits(p0) and _bits(v1) == _bits(v0)
        # enforcement probe: per-row prediction as a function of col-0's
        # bin must be non-decreasing when every other column is constant
        probe = np.zeros((16, 6), np.uint8)
        probe[:, :] = 8
        probe[:, 0] = np.arange(16)
        tr = t1
        nid = jnp.zeros(16, jnp.int32)
        pp = jnp.zeros(16, jnp.float32)
        _, pp = tr.replay(jnp.asarray(probe), nid, pp)
        pp = np.asarray(pp)[1:]  # bin 0 is the NA slot — direction-free
        assert (np.diff(pp) >= -1e-6).all(), pp
    _free_compile_state()


@pytest.mark.parametrize("k", [2, 8])
def test_fused_cat_sharded_tie_break(k):
    """ISSUE-15 closure (a): categorical frames on SHARDED meshes run the
    fused lane (block-local mean-sort gather). Adversarial ties: duplicated
    categorical columns spanning column blocks plus duplicated numeric
    columns — winner merge must still be lowest-global-index, bit-equal to
    the unfused dense sharded scan."""
    with _use_mesh(k):
        n_pad = pm.pad_to_shards(960)
        rng = np.random.default_rng(37)
        base_cat = rng.integers(0, 7, n_pad).astype(np.uint8)
        base_num = rng.integers(1, 16, n_pad).astype(np.uint8)
        # 10 columns: cat duplicates at 1,4,8 / numeric duplicates elsewhere
        bins = np.tile(base_num[:, None], (1, 10))
        is_cat = np.zeros(10, bool)
        for c in (1, 4, 8):
            bins[:, c] = base_cat
            is_cat[c] = True
        t = rng.integers(-3, 4, n_pad).astype(np.float32)
        t1, p1, v1 = _build_one(bins, t, split_fuse="1", is_cat=is_cat,
                                max_depth=4)
        t0, p0, v0 = _build_one(bins, t, split_fuse="0", is_cat=is_cat,
                                max_depth=4)
        _assert_trees_bit_equal(t1, t0, f"fused-cat-sharded-ties/{k}dev")
        assert _bits(p1) == _bits(p0) and _bits(v1) == _bits(v0)
        # a categorical split must actually win somewhere, and among the
        # duplicated cat columns only the LOWEST index may appear
        host = t0.to_host()
        used_cat_cols = set()
        for lv, m in zip(host.levels, t0.real_level_masks()):
            sel = ~np.asarray(lv.leaf_now) & m & np.asarray(lv.is_cat)
            used_cat_cols |= set(np.asarray(lv.split_col)[sel].tolist())
        assert used_cat_cols and used_cat_cols <= {1}, used_cat_cols
    _free_compile_state()


def test_streamed_mono_matches_resident():
    """Satellite: the streamed-GBM gate accepts monotone builds — the
    bound state is per-node, so it rides the host level loop across row
    blocks. Split decisions must equal the resident mono build's
    level-for-level (same integer-tie regime as the oocore pins), preds
    within the block-summation envelope."""
    import pandas as pd

    from h2o3_tpu.frame.frame import Frame
    from h2o3_tpu.models.tree import GBM

    rng = np.random.default_rng(41)
    n = 4096
    df = pd.DataFrame({
        "a": rng.integers(0, 50, n).astype(np.float64),
        "b": rng.normal(size=n),
        "c": rng.normal(size=n),
    })
    df["y"] = (df["a"] * 0.1 + 0.5 * df["b"]
               + 0.1 * rng.normal(size=n)).astype(np.float64)
    kw = dict(ntrees=4, max_depth=3, seed=7,
              monotone_constraints={"a": 1})

    def run(window):
        env = {"H2O3_TPU_HBM_WINDOW_BYTES": window} if window else {}
        with _env(**env):
            fr = Frame.from_pandas(df)
            m = GBM(**kw).train(y="y", training_frame=fr)
            pr = m.predict(fr)
            return m, pr.vec(pr.names[-1]).to_numpy()

    m_res, p_res = run(None)
    # ~8 blocks through a 1/8th window
    bytes_per_row = 3 + 28
    m_str, p_str = run(str(n * bytes_per_row // 8))
    np.testing.assert_allclose(p_str, p_res, rtol=1e-5, atol=1e-5)
    for g_res, g_str in zip(m_res.output["trees"], m_str.output["trees"]):
        for lv_r, lv_s in zip(g_res[0].to_host().levels,
                              g_str[0].to_host().levels):
            np.testing.assert_array_equal(lv_r.split_col, lv_s.split_col)
            np.testing.assert_array_equal(lv_r.split_bin, lv_s.split_bin)
    _free_compile_state()


def test_tile_autotuner_sweeps_once_per_bucket(tmp_path, monkeypatch):
    """H2O3_TPU_PALLAS_TILES=auto (ISSUE 15 / ROADMAP 4b): the first
    resolve of a shape bucket runs ONE micro-sweep, a same-bucket resolve
    adds zero (counter-pinned), the winner persists to the compile-cache
    dir (a fresh in-process cache reads it back sweep-free), and explicit
    'ROW,COL,NODE' values bypass the tuner unchanged. The grid shrinks to
    two candidates here — the test pins the CACHING contract, not sweep
    quality, and the full grid's 12 interpret-mode compiles would bloat
    the tier-1 process (see _free_compile_state)."""
    from h2o3_tpu.ops import hist_pallas as hp
    from h2o3_tpu.utils import metrics as mx

    monkeypatch.setattr(
        hp, "_sweep_grid", lambda c, n: [(256, 4, 32), (512, 8, 64)])
    with _env(H2O3_TPU_PALLAS_TILES="auto",
              H2O3_TPU_COMPILE_CACHE=str(tmp_path)):
        s0 = mx.counter_value("pallas_tile_sweeps_total")
        tiles = hp.tiles_for(12, 64, 32, 3)
        assert mx.counter_value("pallas_tile_sweeps_total") == s0 + 1
        assert len(tiles) == 3 and all(v > 0 for v in tiles)
        # same bucket (cols round to 16, nodes/bins to pow2): zero sweeps
        assert hp.tiles_for(10, 50, 30, 3) == tiles
        assert mx.counter_value("pallas_tile_sweeps_total") == s0 + 1
        # cold in-process cache, warm persistent store: still zero sweeps
        hp._TUNED_TILES.clear()
        assert hp.tiles_for(12, 64, 32, 3) == tiles
        assert mx.counter_value("pallas_tile_sweeps_total") == s0 + 1
    with _env(H2O3_TPU_PALLAS_TILES="256,4,32"):
        assert hp.tiles_for(12, 64, 32, 3) == (256, 4, 32)
        assert mx.counter_value("pallas_tile_sweeps_total") == s0 + 1
    _free_compile_state()


def test_fused_fallback_counter_uplift():
    """tree_fused_fallbacks_total{reason=uplift}: the one structural hole
    left in the tree matrix tallies when the fuse gate is on; the closed
    mono/cat_sharded cases must NOT tally."""
    from h2o3_tpu.utils import metrics as mx

    with _env(H2O3_TPU_SPLIT_FUSE="1"):
        u0 = mx.counter_value("tree_fused_fallbacks_total", reason="uplift")
        m0 = mx.counter_value("tree_fused_fallbacks_total", reason="mono")
        c0 = mx.counter_value("tree_fused_fallbacks_total",
                              reason="cat_sharded")
        assert st._split_fuse_active((), st._split_shard_on(), uplift=True) \
            is False
        assert mx.counter_value(
            "tree_fused_fallbacks_total", reason="uplift") == u0 + 1
        # the closed cases fuse — and tally nothing
        assert st._split_fuse_active((2, 5), True) is True
        assert mx.counter_value(
            "tree_fused_fallbacks_total", reason="mono") == m0
        assert mx.counter_value(
            "tree_fused_fallbacks_total", reason="cat_sharded") == c0


def test_fused_via_dense_impls_parity():
    """H2O3_TPU_HIST=scatter + FUSE=1: the blocked layout is produced by
    re-blocking the scatter histogram (the CPU correctness lane) — the
    split kernel must still match the dense scan bit-for-bit."""
    n_pad = pm.pad_to_shards(700)
    rng = np.random.default_rng(17)
    bins = rng.integers(0, 16, (n_pad, 9)).astype(np.uint8)
    t = rng.integers(-2, 3, n_pad).astype(np.float32)
    t1, p1, v1 = _build_one(bins, t, split_fuse="1", hist="scatter")
    t0, p0, v0 = _build_one(bins, t, split_fuse="0", hist="scatter")
    _assert_trees_bit_equal(t1, t0, "fused-via-scatter")
    assert _bits(p1) == _bits(p0) and _bits(v1) == _bits(v0)


def test_fused_f64_accuracy_bound():
    """Carried over from test_hist_pallas: the fused pipeline built on the
    Pallas histogram kernel must track a float64 scatter+scan reference —
    the winner's child stats within the kernel's 5e-5 relative envelope,
    and the winning gain within 5e-4 of the f64 gain evaluated at the SAME
    candidate (gains subtract nearly-equal numbers, so their envelope is
    looser than the stats')."""
    from h2o3_tpu.ops.hist_pallas import hist_pallas_local, plan_layout
    from h2o3_tpu.ops.split_pallas import fused_split_scan

    rng = np.random.default_rng(9)
    n, c, N, B = 4096, 6, 16, 64
    bins = rng.integers(1, B, size=(n, c)).astype(np.uint8)
    bins[rng.random((n, c)) < 0.1] = 0  # NA bin occupied
    nid = rng.integers(0, N, size=n).astype(np.int32)
    w = rng.random(n).astype(np.float32)
    t = rng.normal(size=n).astype(np.float32)
    stats = np.stack([w, w * t, w], axis=1).astype(np.float32)

    lay = plan_layout(c, N, B, 3)
    blk = hist_pallas_local(
        jnp.asarray(bins), jnp.asarray(nid), jnp.asarray(stats), N, B,
        interpret=True, blocked=True,
    )
    sp = fused_split_scan(
        blk, lay, jnp.zeros(c, bool), jnp.ones((N, c), jnp.float32),
        10.0, 0.0, (), interpret=True,
    )

    # f64 reference: exact scatter histogram + exact prefix scan
    ref = np.zeros((N, c, B, 3), np.float64)
    st64 = stats.astype(np.float64)
    for col in range(c):
        np.add.at(ref[:, col], (nid, bins[:, col]), st64)
    na = ref[:, :, 0, :]
    data = ref[:, :, 1:, :]
    cum = np.cumsum(data, axis=2)
    left = cum[:, :, :-1, :]
    right = cum[:, :, -1:, :] - left
    tot = ref.sum(axis=2)[:, 0, :]

    def fit(s):
        w_ = s[..., 0]
        return -np.where(w_ > 0, s[..., 1] ** 2 / np.maximum(w_, 1e-300), 0.0)

    col_i = np.asarray(sp["col"])
    t_i = np.asarray(sp["split_bin"]) - 1
    nal = np.asarray(sp["na_left"])
    nodes = np.arange(N)
    L64 = left[nodes, col_i, t_i] + np.where(
        nal[:, None], na[nodes, col_i], 0.0
    )
    R64 = right[nodes, col_i, t_i] + np.where(
        ~nal[:, None], na[nodes, col_i], 0.0
    )
    for got, want in ((np.asarray(sp["Lst"]), L64), (np.asarray(sp["Rst"]), R64)):
        err = np.abs(got - want) / np.maximum(np.abs(want), 1.0)
        assert err.max() < 5e-5, f"child stats rel err {err.max():.2e}"
    g64 = (
        fit(tot)[nodes]
        - fit(left[nodes, col_i, t_i] + np.where(nal[:, None], na[nodes, col_i], 0))
        - fit(right[nodes, col_i, t_i] + np.where(~nal[:, None], na[nodes, col_i], 0))
    )
    gerr = np.abs(np.asarray(sp["gain"]) - g64) / np.maximum(np.abs(g64), 1.0)
    assert gerr.max() < 5e-4, f"gain rel err vs f64 {gerr.max():.2e}"


def test_hist_hbm_counter_measures_the_claim():
    """tree_hist_hbm_bytes_total{path}: the fused pipeline's modeled
    hist+split HBM traffic must undercut the unfused Pallas pipeline's
    ≥2× at the same shape (it drops both unscramble passes), and each mode
    must tally under its own path label."""
    from h2o3_tpu.utils import metrics as mx

    with _use_mesh(8):
        n_pad = pm.pad_to_shards(700)
        rng = np.random.default_rng(19)
        bins = rng.integers(0, 32, (n_pad, 28)).astype(np.uint8)
        t = rng.integers(-3, 4, n_pad).astype(np.float32)

        def bytes_for(fuse, path):
            before = mx.counter_value("tree_hist_hbm_bytes_total", path=path)
            _build_one(bins, t, split_fuse=fuse, n_bins=32, seed=23)
            return mx.counter_value(
                "tree_hist_hbm_bytes_total", path=path) - before

        fused_b = bytes_for("1", "fused")
        unfused_b = bytes_for("0", "pallas_unfused")
        assert fused_b > 0 and unfused_b > 0
        assert unfused_b >= 2 * fused_b, (unfused_b, fused_b)


def test_fused_hist_reduce_bytes_shrink_with_sharding():
    """Under fuse the hist_reduce collective still reduce-scatters: the
    8-device sharded tally must undercut the fused replicated one ≥2×."""
    from h2o3_tpu.utils import metrics as mx

    with _use_mesh(8):
        n_pad = pm.pad_to_shards(700)
        rng = np.random.default_rng(29)
        bins = rng.integers(0, 32, (n_pad, 28)).astype(np.uint8)
        t = rng.integers(-3, 4, n_pad).astype(np.float32)

        def bytes_for(shard):
            before = mx.counter_value(
                "tree_collective_bytes_total", phase="hist_reduce")
            _build_one(bins, t, split_fuse="1", n_bins=32, seed=31,
                       env={"H2O3_TPU_SPLIT_SHARD": shard})
            return mx.counter_value(
                "tree_collective_bytes_total", phase="hist_reduce") - before

        sharded = bytes_for("1")
        replicated = bytes_for("0")
        assert sharded > 0 and replicated >= 2 * sharded, (replicated, sharded)


def test_pallas_tiles_knob():
    """H2O3_TPU_PALLAS_TILES reshapes the kernel grid (the sweep hook) and
    the result still matches the default-tile kernel within the bf16
    envelope; a malformed spec fails loudly."""
    from h2o3_tpu.ops import hist_pallas as hp

    rng = np.random.default_rng(21)
    n, c, N, B = 1000, 11, 8, 17
    bins = jnp.asarray(rng.integers(0, B, (n, c)).astype(np.uint8))
    nid = jnp.asarray(rng.integers(0, N, n).astype(np.int32))
    stats = jnp.asarray(
        np.stack([np.ones(n), rng.normal(size=n), np.ones(n)], 1)
        .astype(np.float32))

    base = hp.hist_pallas_local(
        bins, nid, stats, N, B, interpret=True, tiles=hp._tiles())
    with _env(H2O3_TPU_PALLAS_TILES="256,4,32"):
        tiles = hp._tiles()
        assert tiles == (256, 4, 32)
        lay = hp.plan_layout(c, N, B, 3, tiles=tiles)
        assert lay.ct == 4 and lay.nt == 8  # nt clamps to n_nodes
        swept = hp.hist_pallas_local(
            bins, nid, stats, N, B, interpret=True, tiles=tiles)
    np.testing.assert_allclose(
        np.asarray(swept), np.asarray(base), rtol=1e-4, atol=1e-3)
    with _env(H2O3_TPU_PALLAS_TILES="16,0"):
        with pytest.raises(ValueError):
            hp._tiles()


def test_fused_scanned_chunk_close():
    """build_trees_scanned (the bench/GBM hot path) under fuse: multi-tree
    residuals are no longer integer-exact, so the pin is a tight allclose
    on predictions plus identical level-0 split decisions."""
    with _use_mesh(8):
        n = pm.pad_to_shards(2000)
        rng = np.random.default_rng(23)
        bins = pm.shard_rows(jnp.asarray(
            rng.integers(0, 32, (n, 12)).astype(np.uint8)))
        y = pm.shard_rows(jnp.asarray(rng.normal(size=n).astype(np.float32)))
        w = pm.shard_rows(jnp.ones(n, jnp.float32))

        def grad_fn(F, y_, w_):
            return y_ - F, jnp.ones_like(F)

        def run(fuse):
            with _env(H2O3_TPU_SPLIT_FUSE=fuse, H2O3_TPU_HIST="pallas"):
                preds = pm.shard_rows(jnp.zeros(n, jnp.float32))
                F, vi, stacked = st.build_trees_scanned(
                    bins, w, y, preds, jnp.zeros(12, jnp.float32),
                    jax.random.PRNGKey(7), 3, grad_fn=grad_fn,
                    grad_key=("fuse-ab", fuse), sample_rate=1.0, n_bins=32,
                    is_cat_cols=np.zeros(12, bool), max_depth=4,
                    min_rows=10.0, min_split_improvement=1e-5,
                    learn_rates=np.full(3, 0.3, np.float32),
                    max_abs_leaf=float("inf"), col_sample_rate=1.0,
                    col_sample_rate_per_tree=1.0,
                )
                trees = st.trees_from_stacked(stacked, 3)
                return np.asarray(F), trees

        f1, trees1 = run("1")
        f0, trees0 = run("0")
        np.testing.assert_allclose(f1, f0, rtol=1e-5, atol=1e-6)
        for a, b in zip(trees1, trees0):
            np.testing.assert_array_equal(
                a.levels[0].split_col, b.levels[0].split_col)
            np.testing.assert_array_equal(
                a.levels[0].split_bin, b.levels[0].split_bin)
