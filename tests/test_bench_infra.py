"""Bench infrastructure guards — the TPU measurement window depends on
bench.py and the watcher gate NOT bitrotting between windows (round 4 lost
its window partly to untested glue). Cheap structural checks run in the
default tier; one real phase runs in the slow tier."""

import json
import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(args, env_extra=None, timeout=600):
    env = dict(os.environ, **(env_extra or {}))
    return subprocess.run(
        [sys.executable, *args], capture_output=True, text=True,
        timeout=timeout, env=env, cwd=ROOT,
    )


def test_latest_bench_ok_gate(monkeypatch):
    """The gate's phase list must track bench._PHASES (minus headline)."""
    monkeypatch.syspath_prepend(os.path.join(ROOT, "tools"))
    import latest_bench_ok as gate

    import bench

    assert set(gate.POST_HEADLINE) == set(bench._PHASES) - {"headline"}


@pytest.mark.parametrize(
    "payload,want_rc",
    [
        ({"value": 2.5, "glm_1m": {"seconds": 1},
          "metrics_registry": {"tree_dispatches_total": 4}}, 0),
        ({"value": 2.5, "glm_1m_error": "boom",
          "metrics_registry": {"tree_dispatches_total": 4}}, 1),  # r4 cascade
        # headline + phases but NO registry-snapshot block: produced by a
        # pre-observability bench — must not stand the watcher down
        ({"value": 2.5, "glm_1m": {"seconds": 1}}, 1),
        ({"value": 2.5, "glm_1m": {"seconds": 1}, "metrics_registry": {}}, 1),
        ({"value": 0.0, "error": "init hung"}, 1),
        ({}, 1),
    ],
)
def test_latest_bench_ok_cases(tmp_path, payload, want_rc):
    # run against a scratch dir via a copied script (the tool globs its
    # parent dir, so exercise it with a fabricated artifact set)
    import shutil

    from datetime import datetime, timezone

    tool = os.path.join(ROOT, "tools", "latest_bench_ok.py")
    scratch_tools = tmp_path / "tools"
    scratch_tools.mkdir()
    shutil.copy(tool, scratch_tools / "latest_bench_ok.py")
    # recency comes from the UTC stamp in the FILENAME (mtime is re-stamped
    # by git checkouts and proves nothing)
    stamp = datetime.now(timezone.utc).strftime("%Y%m%dT%H%M%SZ")
    (tmp_path / f"BENCH_builder_{stamp}.json").write_text(
        json.dumps(payload) + "\n"
    )
    # an OLD full artifact must never qualify, whatever its mtime
    (tmp_path / "BENCH_builder_20200101T000000Z.json").write_text(
        json.dumps({"value": 9.9, "glm_1m": {"seconds": 1}}) + "\n"
    )
    r = subprocess.run(
        [sys.executable, str(scratch_tools / "latest_bench_ok.py")],
        capture_output=True, text=True, timeout=60,
    )
    assert r.returncode == want_rc, r.stdout + r.stderr


def test_latest_bench_ok_tolerates_missing_and_garbage(tmp_path):
    """Missing or torn bench files must yield a clean message + rc 1, never
    a traceback (the watcher parses this output)."""
    import shutil

    from datetime import datetime, timezone

    tool = os.path.join(ROOT, "tools", "latest_bench_ok.py")
    scratch_tools = tmp_path / "tools"
    scratch_tools.mkdir()
    shutil.copy(tool, scratch_tools / "latest_bench_ok.py")

    def run():
        return subprocess.run(
            [sys.executable, str(scratch_tools / "latest_bench_ok.py")],
            capture_output=True, text=True, timeout=60,
        )

    # no artifacts at all
    r = run()
    assert r.returncode == 1 and "Traceback" not in r.stderr, r.stderr
    assert "no recent BENCH_builder artifacts" in r.stdout
    # a recent artifact that is NOT json
    stamp = datetime.now(timezone.utc).strftime("%Y%m%dT%H%M%SZ")
    (tmp_path / f"BENCH_builder_{stamp}.json").write_text("NOT { JSON\n")
    r = run()
    assert r.returncode == 1 and "Traceback" not in r.stderr, r.stderr
    assert "unparseable" in r.stdout


def test_knob_docs_check_gate():
    """Every H2O3_TPU_* knob in config.py must be documented under docs/
    (tools/knob_docs_check.py), and the gate must actually fail on an
    undocumented knob (the --extra self-test)."""
    r = _run(["tools/knob_docs_check.py"], timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    r = _run(["tools/knob_docs_check.py",
              "--extra", "H2O3_TPU_NOT_A_REAL_KNOB"], timeout=120)
    assert r.returncode == 1
    assert "H2O3_TPU_NOT_A_REAL_KNOB" in r.stdout


def test_bench_phases_registry():
    import bench

    # every phase has a runner and a positive budget; headline first (the
    # driver contract requires its fields even on failure)
    names = list(bench._PHASES)
    assert names[0] == "headline"
    for name, (fn, budget) in bench._PHASES.items():
        assert callable(fn) and budget > 0, name
    assert bench.BASELINE_TREES_PER_SEC > 1.0  # measured, not the old 1.0


@pytest.mark.slow
def test_glm_phase_emits_valid_json():
    """One real phase end-to-end in a fresh subprocess at 1% scale — the
    exact invocation shape the TPU backlog uses."""
    r = _run(
        ["bench.py", "--phase", "glm_1m"],
        env_extra={
            "H2O3_TPU_BENCH_SCALE": "0.01",
            "JAX_PLATFORMS": "cpu",
            "PALLAS_AXON_POOL_IPS": "",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        },
        timeout=500,
    )
    assert r.stdout.strip(), f"no stdout (rc={r.returncode}):\n{r.stderr[-2000:]}"
    line = r.stdout.strip().splitlines()[-1]
    out = json.loads(line)
    assert "error" not in out, out
    assert out["rows"] >= 10_000 and "auc" in out
