"""Elastic recovery chaos suite (ISSUE 17): topology is a RESUMABLE
parameter, not an invariant. A checkpointed job killed mid-train by an
induced topology change (``reshape:RxC``) must resume its snapshot on the
NEW mesh shape and land within the PR-2 1e-6 resume pin of the
uninterrupted run — while ``H2O3_TPU_RECOVERY=0`` and same-shape resume
keep today's semantics bit-for-bit. The measured-artifact version of the
full shape-change matrix lives in ``tools/recovery_drill.py --elastic``."""

import os
import pickle
import time

import numpy as np
import pandas as pd
import pytest

from h2o3_tpu import persist
from h2o3_tpu.cluster import cloud, multihost, recovery
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models import GBM, GLM
from h2o3_tpu.parallel import mesh
from h2o3_tpu.utils import faults

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _fast_recovery(monkeypatch):
    monkeypatch.setenv("H2O3_TPU_RECOVERY", "1")
    monkeypatch.setenv("H2O3_TPU_RECOVERY_BACKOFF", "0.01")
    cloud.clear_degraded()
    yield
    faults.reset()
    cloud.clear_degraded()
    # every test leaves the default mesh behind for the rest of the suite
    if dict(mesh.get_mesh().shape).get("rows") != 8:
        mesh.reform_mesh((1, 8))


def _df(n=1500, seed=3):
    rng = np.random.default_rng(seed)
    df = pd.DataFrame({
        "a": rng.normal(size=n),
        "b": rng.normal(size=n),
        "c": rng.choice(["x", "y", "z"], n),
    })
    eta = df["a"] * 1.5 + (df["c"] == "x") * 2 - df["b"]
    df["y"] = np.where(eta + rng.normal(size=n) > 0, "p", "n")
    return df


# ---------------------------------------------------------------------------
# mesh re-planning and the topology epoch


def test_plan_mesh_knob_matrix(monkeypatch):
    monkeypatch.setenv("H2O3_TPU_MESH_ROWS", "")
    assert mesh.plan_mesh(8) == (1, 8)
    assert mesh.plan_mesh(4) == (1, 4)
    monkeypatch.setenv("H2O3_TPU_MESH_ROWS", "2")
    assert mesh.plan_mesh(8) == (2, 4)
    assert mesh.plan_mesh(4) == (2, 2)
    # a rows knob that no longer divides the shrunken formation falls back
    # to 1-D instead of refusing to re-form
    assert mesh.plan_mesh(5) == (1, 5)
    monkeypatch.setenv("H2O3_TPU_MESH_ROWS", "auto")
    assert mesh.plan_mesh(8, n_hosts=1) == (1, 8)
    assert mesh.plan_mesh(8, n_hosts=2) == (4, 2)
    assert mesh.plan_mesh(8, n_hosts=4) == (2, 4)


def test_reform_mesh_explicit_shape_ticks_epoch():
    e0 = mesh.mesh_epoch()
    m = mesh.reform_mesh((2, 4))
    assert mesh.mesh_epoch() == e0 + 1
    assert dict(m.shape) == {"rows": 2, "cols": 4}
    m = mesh.reform_mesh((1, 4))
    assert mesh.mesh_epoch() == e0 + 2
    assert dict(m.shape) == {"rows": 4}
    assert mesh.n_shards() == 4
    with pytest.raises(ValueError, match="needs 16 devices"):
        mesh.reform_mesh((2, 8))
    with pytest.raises(ValueError, match="bad shape"):
        mesh.reform_mesh((0, 4))
    m = mesh.reform_mesh((1, 8))
    assert dict(m.shape) == {"rows": 8}


def test_set_mesh_never_ticks_epoch():
    """Tests (and the 2-D A/B lane) swap sub-meshes with set_mesh and manage
    their own frames — that must NOT invalidate every Vec placement."""
    e0 = mesh.mesh_epoch()
    m = mesh.get_mesh()
    mesh.set_mesh(m)
    assert mesh.mesh_epoch() == e0


def test_vec_reshards_host_mirror_across_epochs():
    fr = Frame.from_pandas(_df(900, seed=21))
    before = {n: fr.vec(n).to_numpy().copy() for n in fr.names}
    npad8 = fr.npad
    mesh.reform_mesh((1, 4))
    # lazily re-derived on next touch: new padded width, identical values
    assert fr.npad == mesh.pad_to_shards(fr.nrow)
    for n in fr.names:
        np.testing.assert_array_equal(fr.vec(n).to_numpy(), before[n])
    assert fr.vec("a").data.shape[0] == fr.npad
    mesh.reform_mesh((2, 4))
    for n in fr.names:
        np.testing.assert_array_equal(fr.vec(n).to_numpy(), before[n])
    mesh.reform_mesh((1, 8))
    assert fr.npad == npad8
    np.testing.assert_array_equal(fr.vec("a").to_numpy(), before["a"])


def test_reshard_host_mirrors_eager_helper():
    from h2o3_tpu.frame.chunkstore import reshard_host_mirrors

    fr = Frame.from_pandas(_df(600, seed=31))
    assert reshard_host_mirrors(fr) == 0  # same epoch: nothing to do
    mesh.reform_mesh((1, 4))
    assert reshard_host_mirrors(fr) == len(fr.names)
    assert reshard_host_mirrors(fr) == 0  # idempotent


# ---------------------------------------------------------------------------
# the reshape:RxC chaos primitive


def test_reshape_spec_parsing():
    assert faults._parse_reshape("2x4") == (2, 4)
    assert faults._parse_reshape("1X8") == (1, 8)
    assert faults._parse_reshape("4×2") == (4, 2)  # unicode ×
    with pytest.raises(ValueError, match="bad reshape spec"):
        faults._parse_reshape("8")
    with pytest.raises(ValueError, match="rows/cols"):
        faults._parse_reshape("0x4")


def test_reshape_fault_fires_once_and_parks_for_reform():
    with faults.inject(reshape="1x4"):
        with pytest.raises(faults.XlaRuntimeError, match="topology changed"):
            faults.die_check("gbm")
        faults.die_check("gbm")  # one-shot: the second boundary passes
        assert faults.take_reshape() == (1, 4)
        assert faults.take_reshape() is None  # consumed
    assert faults.take_reshape() is None  # reset clears the pending slot


def test_env_spec_arms_reshape(monkeypatch):
    monkeypatch.setenv("H2O3_TPU_FAULTS", "reshape:2x4")
    faults.reset()  # re-reads the env knob
    assert faults.armed()
    with pytest.raises(faults.XlaRuntimeError):
        faults.die_check("bcast")
    assert faults.take_reshape() == (2, 4)
    monkeypatch.delenv("H2O3_TPU_FAULTS")
    faults.reset()


def test_reform_consumes_pending_reshape():
    e0 = mesh.mesh_epoch()
    g0 = cloud.generation()
    faults.configure(reshape=(1, 4))
    with pytest.raises(faults.XlaRuntimeError):
        faults.die_check("glm")
    recovery.reform("elastic unit test")
    assert dict(mesh.get_mesh().shape) == {"rows": 4}
    assert mesh.mesh_epoch() == e0 + 1
    assert cloud.generation() == g0 + 1
    assert cloud.degraded_reason() is None


# ---------------------------------------------------------------------------
# end-to-end: kill mid-train, resume on a CHANGED topology (the fast CI
# version of tools/recovery_drill.py --elastic)


def test_gbm_elastic_resume_8_to_4(tmp_path):
    fr = Frame.from_pandas(_df())
    kw = dict(ntrees=8, max_depth=3, seed=11, learn_rate=0.2,
              score_tree_interval=2)
    full = GBM(**kw).train(y="y", training_frame=fr)

    ckdir = str(tmp_path / "elastic_gbm")

    def _launch(ckpt):
        kw2 = dict(kw, export_checkpoints_dir=ckdir)
        if ckpt:
            kw2["checkpoint"] = ckpt
        return GBM(**kw2).train(y="y", training_frame=fr)

    e0 = mesh.mesh_epoch()
    with faults.inject(reshape=(1, 4)):
        healed = recovery.run_supervised(_launch, ckdir=ckdir, algo="gbm",
                                         description="elastic gbm 8->4")
    # the resume landed on the SHRUNKEN formation, not the boot-time one
    assert dict(mesh.get_mesh().shape) == {"rows": 4}
    assert mesh.mesh_epoch() == e0 + 1
    assert cloud.degraded_reason() is None
    assert healed.output["ntrees_actual"] == 8
    np.testing.assert_allclose(
        healed.training_metrics.logloss, full.training_metrics.logloss,
        atol=1e-6)
    pa = full.predict(fr).vec("p").to_numpy()
    pb = healed.predict(fr).vec("p").to_numpy()
    np.testing.assert_allclose(pa, pb, atol=1e-5)


def test_glm_elastic_resume_1d_to_2d(tmp_path):
    fr = Frame.from_pandas(_df(seed=5))
    kw = dict(family="binomial", max_iterations=20, seed=1)
    full = GLM(**kw).train(y="y", training_frame=fr)

    ckdir = str(tmp_path / "elastic_glm")

    def _launch(ckpt):
        kw2 = dict(kw, export_checkpoints_dir=ckdir)
        if ckpt:
            kw2["checkpoint"] = ckpt
        return GLM(**kw2).train(y="y", training_frame=fr)

    with faults.inject(reshape=(2, 4)):
        healed = recovery.run_supervised(_launch, ckdir=ckdir, algo="glm",
                                         description="elastic glm 1d->2d")
    assert dict(mesh.get_mesh().shape) == {"rows": 2, "cols": 4}
    np.testing.assert_allclose(
        healed.training_metrics.logloss, full.training_metrics.logloss,
        atol=1e-6)
    np.testing.assert_allclose(
        np.asarray(healed.output["beta_std"]),
        np.asarray(full.output["beta_std"]), atol=1e-5)


def test_same_shape_resume_stays_bitexact(tmp_path):
    """A reform that does NOT change the shape (today's worker-death path)
    keeps the PR-10 contract bit-for-bit: the epoch ticks and every Vec
    makes a host round trip, which must be an identity."""
    fr = Frame.from_pandas(_df(seed=5))
    kw = dict(family="binomial", max_iterations=20, seed=1)
    full = GLM(**kw).train(y="y", training_frame=fr)

    ckdir = str(tmp_path / "same_shape")

    def _launch(ckpt):
        kw2 = dict(kw, export_checkpoints_dir=ckdir)
        if ckpt:
            kw2["checkpoint"] = ckpt
        return GLM(**kw2).train(y="y", training_frame=fr)

    with faults.inject(die={"glm"}):
        healed = recovery.run_supervised(_launch, ckdir=ckdir, algo="glm",
                                         description="same-shape glm")
    assert dict(mesh.get_mesh().shape) == {"rows": 8}
    np.testing.assert_array_equal(
        np.asarray(healed.output["beta_std"]),
        np.asarray(full.output["beta_std"]))


def test_recovery_disabled_reshape_failstops(monkeypatch):
    """H2O3_TPU_RECOVERY=0: the induced topology change surfaces as today's
    fail-stop — no reform, no epoch tick, the mesh stays what it was."""
    monkeypatch.setenv("H2O3_TPU_RECOVERY", "0")
    e0 = mesh.mesh_epoch()
    g0 = cloud.generation()

    def _launch(ckpt):
        faults.die_check("gbm")

    with faults.inject(reshape=(1, 4)):
        with pytest.raises(faults.XlaRuntimeError, match="topology changed"):
            recovery.run_supervised(_launch, description="disabled elastic")
    assert mesh.mesh_epoch() == e0
    assert cloud.generation() == g0
    assert dict(mesh.get_mesh().shape) == {"rows": 8}


# ---------------------------------------------------------------------------
# latest_snapshot: counter preference, mtime tiebreak, torn-file skip


def _fake_ckpt(path, output):
    payload = {"cls_module": "h2o3_tpu.models.model_base",
               "cls_name": "Model", "algo": "gbm",
               "state": {"output": output}}
    with open(path, "wb") as f:
        f.write(persist.FORMAT_MAGIC + pickle.dumps(payload))


def test_latest_snapshot_prefers_progress_counter(tmp_path):
    d = str(tmp_path)
    a = os.path.join(d, "gbm_ckpt_aaa")
    b = os.path.join(d, "gbm_ckpt_bbb")
    _fake_ckpt(a, {"ntrees_actual": 6})
    _fake_ckpt(b, {"ntrees_actual": 2})
    # clock skew stamps the STALE snapshot newest — the embedded counter,
    # not mtime, must decide
    now = time.time()
    os.utime(a, (now - 600, now - 600))
    os.utime(b, (now, now))
    assert recovery.latest_snapshot(d, "gbm") == a
    # equal counters: mtime is the tiebreak
    _fake_ckpt(b, {"ntrees_actual": 6})
    os.utime(b, (now, now))
    assert recovery.latest_snapshot(d, "gbm") == b


def test_latest_snapshot_irls_position_orders_glm(tmp_path):
    d = str(tmp_path)
    a = os.path.join(d, "glm_ckpt_aaa")
    b = os.path.join(d, "glm_ckpt_bbb")
    _fake_ckpt(a, {"irls_state": {"li": 0, "iters": 9}})
    _fake_ckpt(b, {"irls_state": {"li": 1, "iters": 2}})
    now = time.time()
    os.utime(a, (now, now))            # newest mtime...
    os.utime(b, (now - 600, now - 600))
    # ...but lambda index 1 is FURTHER along the path than li 0 iter 9
    assert recovery.latest_snapshot(d, "glm") == b


def test_latest_snapshot_skips_torn_files(tmp_path):
    d = str(tmp_path)
    good = os.path.join(d, "gbm_ckpt_good")
    _fake_ckpt(good, {"ntrees_actual": 4})
    torn = os.path.join(d, "gbm_ckpt_torn")
    blob = persist.FORMAT_MAGIC + pickle.dumps({"state": {}})
    with open(torn, "wb") as f:
        f.write(blob[: len(blob) // 2])  # crash mid-write
    foreign = os.path.join(d, "gbm_ckpt_foreign")
    with open(foreign, "wb") as f:
        f.write(b"not a model at all")
    now = time.time()
    os.utime(good, (now - 600, now - 600))
    os.utime(torn, (now, now))
    os.utime(foreign, (now, now))
    assert recovery.latest_snapshot(d, "gbm") == good
    assert recovery.latest_snapshot(None, "gbm") is None
    assert recovery.latest_snapshot(d, None) is None


# ---------------------------------------------------------------------------
# restart-budget reset after a healthy window (SATELLITE 2)


def test_restart_budget_resets_after_healthy_window(monkeypatch):
    monkeypatch.setenv("H2O3_TPU_RECOVERY_MAX_RESTARTS", "1")
    monkeypatch.setenv("H2O3_TPU_RECOVERY_RESET_SECS", "0.2")
    calls = []

    def _launch(ckpt):
        calls.append(ckpt)
        if len(calls) == 2:
            time.sleep(0.3)  # ran healthy PAST the reset window
        if len(calls) < 3:
            raise faults.make_death_error()
        return "done"

    # without the reset, a 1-restart budget dies on the second failure;
    # the healthy window between them gives the budget back
    assert recovery.run_supervised(_launch, description="reset drill") == "done"
    assert len(calls) == 3


def test_restart_budget_reset_disabled_by_zero(monkeypatch):
    monkeypatch.setenv("H2O3_TPU_RECOVERY_MAX_RESTARTS", "1")
    monkeypatch.setenv("H2O3_TPU_RECOVERY_RESET_SECS", "0")
    calls = []

    def _launch(ckpt):
        calls.append(ckpt)
        if len(calls) == 2:
            time.sleep(0.3)
        raise faults.make_death_error()

    with pytest.raises(recovery.RecoveryExhausted):
        recovery.run_supervised(_launch, description="lifetime budget")
    assert len(calls) == 2  # 1 + 1 restart, no reset


# ---------------------------------------------------------------------------
# formation manifest (cluster/multihost.py)


def test_formation_manifest_roundtrip(tmp_path, monkeypatch):
    path = str(tmp_path / "formation.json")
    monkeypatch.setenv("H2O3_TPU_FORMATION_MANIFEST", path)
    assert multihost.read_manifest() is None  # missing: no opinion
    rec = {"processes": 2, "mesh": {"rows": 8}, "cloud_size": 16}
    multihost.write_manifest(rec)
    assert multihost.read_manifest() == rec
    # torn manifest: no opinion, never a crash
    with open(path, "w") as f:
        f.write('{"processes": 2, "mesh"')
    assert multihost.read_manifest() is None


def test_formation_manifest_disabled(monkeypatch):
    monkeypatch.setenv("H2O3_TPU_FORMATION_MANIFEST", "0")
    assert multihost._manifest_path() is None
    multihost.write_manifest({"processes": 1})  # no-op, no crash
    assert multihost.read_manifest() is None
    monkeypatch.setenv("H2O3_TPU_FORMATION_MANIFEST", "")
    assert multihost._manifest_path()  # default: per-uid tempdir path


def test_retired_rank_exits_clean(tmp_path, monkeypatch):
    """A restarted pod scales 4 -> 2: ranks 2 and 3 come back up with stale
    launch env, observe the manifest, and exit 0 instead of crash-looping
    against a formation that no longer includes them."""
    path = str(tmp_path / "formation.json")
    monkeypatch.setenv("H2O3_TPU_FORMATION_MANIFEST", path)
    multihost.write_manifest({"processes": 4, "mesh": {"rows": 8}})
    monkeypatch.setenv("H2O3_TPU_NUM_PROCESSES", "2")
    monkeypatch.setenv("H2O3_TPU_PROCESS_ID", "3")
    monkeypatch.setenv("H2O3_TPU_COORDINATOR", "127.0.0.1:7777")
    with pytest.raises(SystemExit) as ei:
        multihost.pod_env()
    assert ei.value.code == 0
    # a rank that was NEVER part of the formation is still a config error
    multihost.write_manifest({"processes": 2, "mesh": {"rows": 8}})
    with pytest.raises(ValueError, match="out of range"):
        multihost.pod_env()


# ---------------------------------------------------------------------------
# ChunkStore epoch guard: block geometry bakes the shard count in


def test_chunkstore_refuses_stale_epoch():
    from h2o3_tpu.frame import chunkstore as cs

    store = cs.ChunkStore(1024, 16, window=4096, prefetch=1)
    store.add_empty("x", (1024, 4), np.float32)
    store.fetch(0, ("x",))  # same epoch: fine
    mesh.reform_mesh((1, 4))
    with pytest.raises(RuntimeError, match="topology epoch"):
        store.fetch(0, ("x",))
