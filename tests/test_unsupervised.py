"""KMeans / PCA / SVD / NaiveBayes / IsolationForest tests — scenario style
of upstream ``hex/kmeans``, ``hex/pca``, ``hex/naivebayes``,
``hex/tree/isofor`` test suites [UNVERIFIED upstream paths]."""

import numpy as np
import pandas as pd
import pytest

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.kmeans import KMeans
from h2o3_tpu.models.pca import PCA, SVD
from h2o3_tpu.models.naive_bayes import NaiveBayes
from h2o3_tpu.models.isolation_forest import IsolationForest


def _blobs(n=1500, seed=0):
    rng = np.random.default_rng(seed)
    centers = np.array([[0, 0], [6, 6], [-6, 6]])
    lbl = rng.integers(0, 3, n)
    X = centers[lbl] + rng.normal(size=(n, 2))
    return pd.DataFrame({"x": X[:, 0], "y": X[:, 1]}), lbl


def test_kmeans_recovers_blobs():
    df, lbl = _blobs()
    fr = Frame.from_pandas(df)
    m = KMeans(k=3, max_iterations=20, standardize=False, seed=3).train(
        training_frame=fr
    )
    assign = m._predict_raw(fr)
    # clusters should align with true labels up to permutation
    from scipy.stats import mode

    acc = 0
    for c in range(3):
        sel = assign == c
        if sel.sum():
            acc += (lbl[sel] == mode(lbl[sel]).mode).sum()
    assert acc / len(lbl) > 0.95
    mm = m.training_metrics
    assert mm.tot_withinss > 0 and mm.betweenss > mm.tot_withinss
    assert sorted(len(x) if hasattr(x, "__len__") else 1 for x in [mm.cluster_sizes])


def test_kmeans_standardize_and_predict_frame():
    df, _ = _blobs(800, seed=2)
    fr = Frame.from_pandas(df)
    m = KMeans(k=3, seed=1).train(training_frame=fr)
    pred = m.predict(fr)
    assert pred.names == ["predict"]
    assert pred.nrow == 800


def test_pca_matches_sklearn():
    from sklearn.decomposition import PCA as SKPCA

    rng = np.random.default_rng(4)
    X = rng.normal(size=(2000, 4)) @ np.diag([3.0, 2.0, 1.0, 0.1])
    df = pd.DataFrame(X, columns=list("abcd"))
    fr = Frame.from_pandas(df)
    m = PCA(k=2, transform="DEMEAN").train(training_frame=fr)
    sk = SKPCA(n_components=2).fit(X)
    np.testing.assert_allclose(
        m.output["std_deviation"], np.sqrt(sk.explained_variance_), rtol=0.02
    )
    # scores correlate (sign-invariant)
    scores = m._predict_raw(fr)
    sk_scores = sk.transform(X)
    for i in range(2):
        c = np.corrcoef(scores[:, i], sk_scores[:, i])[0, 1]
        assert abs(c) > 0.999


def test_svd_randomized():
    rng = np.random.default_rng(5)
    X = rng.normal(size=(3000, 6)) @ np.diag([5, 3, 1, 0.5, 0.1, 0.05])
    fr = Frame.from_pandas(pd.DataFrame(X, columns=[f"c{i}" for i in range(6)]))
    m = SVD(nv=3, svd_method="Randomized", max_iterations=6).train(training_frame=fr)
    s_ref = np.linalg.svd(X, compute_uv=False)[:3]
    np.testing.assert_allclose(m.output["d"], s_ref, rtol=0.02)


def test_naive_bayes_vs_sklearn():
    from sklearn.naive_bayes import GaussianNB

    rng = np.random.default_rng(6)
    n = 3000
    y = rng.integers(0, 2, n)
    X = rng.normal(size=(n, 3)) + y[:, None] * np.array([1.5, -1.0, 0.5])
    df = pd.DataFrame(X, columns=list("abc"))
    df["cls"] = np.where(y == 1, "t", "f")
    fr = Frame.from_pandas(df)
    m = NaiveBayes().train(y="cls", training_frame=fr)
    sk = GaussianNB().fit(X, y)
    P = m._predict_raw(fr)[:, 1]
    P_sk = sk.predict_proba(X)[:, 1]
    assert np.corrcoef(P, P_sk)[0, 1] > 0.999
    assert m.training_metrics.auc > 0.85


def test_naive_bayes_categorical_laplace():
    rng = np.random.default_rng(7)
    n = 2000
    g = rng.choice(["u", "v", "w"], n, p=[0.5, 0.3, 0.2])
    y = np.where((g == "u") & (rng.random(n) < 0.8), "yes", "no")
    fr = Frame.from_pandas(pd.DataFrame({"g": g, "y": y}))
    m = NaiveBayes(laplace=1.0).train(y="y", training_frame=fr)
    assert m.training_metrics.auc > 0.6
    tab = m.output["cat_stats"]["g"]["cond"]
    np.testing.assert_allclose(tab.sum(axis=0), 1.0, atol=1e-9)


def test_isolation_forest_flags_outliers():
    rng = np.random.default_rng(8)
    X = rng.normal(size=(1000, 2))
    X[:20] += 8.0  # planted anomalies
    fr = Frame.from_pandas(pd.DataFrame(X, columns=["a", "b"]))
    m = IsolationForest(ntrees=40, sample_size=128, seed=4).train(training_frame=fr)
    pred = m.predict(fr)
    assert pred.names == ["predict", "mean_length"]
    score = pred.vec("predict").to_numpy()
    # planted outliers should rank in the top chunk by anomaly score
    top = np.argsort(-score)[:40]
    assert (top < 20).sum() >= 15
