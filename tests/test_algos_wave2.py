"""Algorithm wave 2 — Isotonic, DT, AdaBoost, ExtendedIsolationForest
(SURVEY.md §2.2 rows C25/C32), accuracy pinned against sklearn where a
counterpart exists."""

import numpy as np
import pandas as pd
import pytest

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models import DT, AdaBoost, ExtendedIsolationForest, IsotonicRegression


def test_isotonic_matches_sklearn():
    from sklearn.isotonic import IsotonicRegression as SkIso

    rng = np.random.default_rng(0)
    n = 3000
    x = rng.uniform(0, 10, n)
    y = np.log1p(x) + rng.normal(0, 0.3, n)
    fr = Frame.from_pandas(pd.DataFrame({"x": x, "y": y}))
    m = IsotonicRegression().train(x=["x"], y="y", training_frame=fr)
    ours = m.predict(fr).vec("predict").to_numpy()
    sk = SkIso(out_of_bounds="clip").fit(x, y).predict(x)
    np.testing.assert_allclose(ours, sk, atol=1e-6)
    assert m.training_metrics.rmse < 0.35


def test_isotonic_weighted_and_na():
    rng = np.random.default_rng(1)
    x = rng.uniform(0, 5, 500)
    y = x + rng.normal(0, 0.1, 500)
    w = rng.uniform(0.5, 2.0, 500)
    x[:5] = np.nan
    fr = Frame.from_pandas(pd.DataFrame({"x": x, "y": y, "w": w}))
    m = IsotonicRegression(weights_column="w").train(x=["x"], y="y", training_frame=fr)
    pred = m.predict(fr).vec("predict").to_numpy()
    assert np.isnan(pred[:5]).all()
    assert np.all(np.diff(m.output["thresholds_y"]) >= -1e-12)


def _binary(n=3000, seed=2):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 4))
    eta = X[:, 0] * 2 + X[:, 1] ** 2 - X[:, 2] - 1
    y = (rng.random(n) < 1 / (1 + np.exp(-eta))).astype(int)
    df = pd.DataFrame(X, columns=list("abcd"))
    df["y"] = np.where(y == 1, "Y", "N")
    return df, y


def test_dt_tracks_sklearn_tree():
    from sklearn.metrics import roc_auc_score
    from sklearn.tree import DecisionTreeClassifier

    df, y = _binary()
    fr = Frame.from_pandas(df)
    m = DT(max_depth=5, min_rows=10).train(y="y", training_frame=fr)
    p1 = m.predict(fr).vec("Y").to_numpy()
    ours = roc_auc_score(y, p1)
    sk = roc_auc_score(
        y,
        DecisionTreeClassifier(max_depth=5, min_samples_leaf=10)
        .fit(df[list("abcd")], y)
        .predict_proba(df[list("abcd")])[:, 1],
    )
    assert ours > 0.85 and ours > sk - 0.05


def test_dt_regression():
    rng = np.random.default_rng(3)
    n = 2000
    df = pd.DataFrame({"a": rng.uniform(-2, 2, n), "b": rng.normal(size=n)})
    df["y"] = np.where(df["a"] > 0, 3.0, -1.0) + 0.1 * rng.normal(size=n)
    fr = Frame.from_pandas(df)
    m = DT(max_depth=3).train(y="y", training_frame=fr)
    assert m.training_metrics.r2 > 0.9


def test_dt_rejects_multiclass():
    rng = np.random.default_rng(4)
    df = pd.DataFrame({"a": rng.normal(size=100), "y": rng.choice(list("rgb"), 100)})
    with pytest.raises(Exception, match="binary"):
        DT().train(y="y", training_frame=Frame.from_pandas(df))


def test_adaboost_beats_stump_and_tracks_sklearn():
    from sklearn.ensemble import AdaBoostClassifier
    from sklearn.metrics import roc_auc_score

    df, y = _binary(seed=5)
    fr = Frame.from_pandas(df)
    m = AdaBoost(nlearners=40, seed=3).train(y="y", training_frame=fr)
    p1 = m.predict(fr).vec("Y").to_numpy()
    ours = roc_auc_score(y, p1)
    stump = DT(max_depth=1).train(y="y", training_frame=fr)
    stump_auc = roc_auc_score(y, stump.predict(fr).vec("Y").to_numpy())
    sk = roc_auc_score(
        y,
        AdaBoostClassifier(n_estimators=40, random_state=0)
        .fit(df[list("abcd")], y)
        .predict_proba(df[list("abcd")])[:, 1],
    )
    assert ours > stump_auc + 0.05  # boosting must beat its weak learner
    assert ours > sk - 0.05
    assert len(m.output["alphas"]) == m.output["ntrees_actual"]


def test_extended_isolation_forest_flags_outliers():
    rng = np.random.default_rng(6)
    inliers = rng.normal(0, 1, size=(1000, 3))
    outliers = rng.normal(0, 1, size=(20, 3)) + 8.0
    X = np.vstack([inliers, outliers])
    fr = Frame.from_pandas(pd.DataFrame(X, columns=["a", "b", "c"]))
    m = ExtendedIsolationForest(ntrees=60, sample_size=128, seed=9).train(
        training_frame=fr
    )
    scores = m.predict(fr).vec("anomaly_score").to_numpy()
    # outliers (last 20 rows) must rank clearly above inliers
    cutoff = np.quantile(scores[:1000], 0.95)
    assert (scores[1000:] > cutoff).mean() > 0.9
    assert m.training_metrics.mean_score > 0


def test_eif_extension_level_zero_is_axis_parallel():
    rng = np.random.default_rng(7)
    X = rng.normal(size=(400, 3))
    fr = Frame.from_pandas(pd.DataFrame(X, columns=["a", "b", "c"]))
    m = ExtendedIsolationForest(ntrees=10, sample_size=64, extension_level=0, seed=1).train(
        training_frame=fr
    )
    for levels in m.output["stacked_trees"]:
        for normals, _, is_leaf, _ in levels:
            nz = (normals != 0).sum(axis=1)
            assert np.all(nz[~is_leaf] == 1)  # exactly one feature per split


# ---------------------------------------------------------------------------
# wave 2b: TargetEncoder, GLRM, CoxPH, Word2Vec


def test_target_encoder_means_blending_loo():
    from h2o3_tpu.models import TargetEncoder

    rng = np.random.default_rng(8)
    n = 2000
    lev = rng.choice(["a", "b", "c"], n, p=[0.5, 0.3, 0.2])
    y = (rng.random(n) < np.select([lev == "a", lev == "b"], [0.8, 0.4], 0.1)).astype(int)
    df = pd.DataFrame({"g": lev, "y": np.where(y == 1, "T", "F")})
    fr = Frame.from_pandas(df)

    te = TargetEncoder(holdout_type="none").fit(fr, "y", ["g"])
    out = te.transform(fr)
    enc = out.vec("g_te").to_numpy()
    for L in ("a", "b", "c"):
        m = enc[lev == L]
        assert np.allclose(m, m[0])
        assert abs(m[0] - y[lev == L].mean()) < 1e-6

    # LOO excludes the row's own target
    te2 = TargetEncoder(holdout_type="loo").fit(fr, "y", ["g"])
    enc2 = te2.transform(fr, as_training=True).vec("g_te").to_numpy()
    i = int(np.flatnonzero(lev == "a")[0])
    na, sa = (lev == "a").sum(), y[lev == "a"].sum()
    expect = (sa - y[i]) / (na - 1)
    assert abs(enc2[i] - expect) < 1e-6

    # blending pulls sparse levels toward the prior
    te3 = TargetEncoder(holdout_type="none", blending=True, inflection_point=5000).fit(fr, "y", ["g"])
    enc3 = te3.transform(fr).vec("g_te").to_numpy()
    prior = y.mean()
    assert np.all(np.abs(enc3 - prior) < np.abs(enc - prior) + 1e-12)

    # kfold transform works and differs from the global means
    te4 = TargetEncoder(holdout_type="kfold", nfolds=4).fit(fr, "y", ["g"])
    enc4 = te4.transform(fr, as_training=True).vec("g_te").to_numpy()
    assert np.isfinite(enc4).all() and not np.allclose(enc4, enc)


def test_glrm_recovers_low_rank_structure():
    from h2o3_tpu.models import GLRM

    rng = np.random.default_rng(9)
    n, d, k = 1000, 8, 3
    U = rng.normal(size=(n, k))
    W = rng.normal(size=(k, d))
    A = U @ W + 0.01 * rng.normal(size=(n, d))
    A[rng.random(A.shape) < 0.1] = np.nan  # 10% missing
    fr = Frame.from_pandas(pd.DataFrame(A, columns=[f"c{i}" for i in range(d)]))
    m = GLRM(k=k, max_iterations=200, transform="DEMEAN", seed=2).train(training_frame=fr)
    objs = [h["objective"] for h in m.scoring_history]
    assert objs[-1] < objs[0] * 0.1  # objective collapsed
    rec = m.reconstruct(fr)
    Ahat = np.stack([rec.vec(i).to_numpy() for i in range(d)], axis=1)
    ok = ~np.isnan(A)
    rel = np.sqrt(np.nanmean((Ahat[:1000] - A) ** 2)) / np.nanstd(A)
    assert rel < 0.2


def test_glrm_nonneg_regularization():
    from h2o3_tpu.models import GLRM

    rng = np.random.default_rng(10)
    A = np.abs(rng.normal(size=(300, 5)))
    fr = Frame.from_pandas(pd.DataFrame(A, columns=[f"c{i}" for i in range(5)]))
    m = GLRM(k=2, regularization_x="NonNegative", regularization_y="NonNegative",
             transform="NONE", max_iterations=100, seed=3, init="Random").train(training_frame=fr)
    assert (m.output["archetypes"] >= 0).all()
    assert (m.output["x_factor"] >= 0).all()


def test_coxph_recovers_coefficients():
    from h2o3_tpu.models import CoxPH

    rng = np.random.default_rng(11)
    n = 4000
    x1 = rng.normal(size=n)
    x2 = rng.normal(size=n)
    beta_true = np.array([0.8, -0.5])
    lam = 0.1 * np.exp(x1 * beta_true[0] + x2 * beta_true[1])
    t = rng.exponential(1.0 / lam)
    cens = rng.exponential(1.0 / 0.05, n)
    time = np.minimum(t, cens)
    event = (t <= cens).astype(int)
    df = pd.DataFrame({"x1": x1, "x2": x2, "time": time, "event": event})
    fr = Frame.from_pandas(df)
    m = CoxPH(stop_column="time").train(x=["x1", "x2"], y="event", training_frame=fr)
    beta = m.output["coefficients"]
    np.testing.assert_allclose(beta, beta_true, atol=0.1)
    assert m.training_metrics.value("concordance") > 0.65
    # breslow ties variant also converges nearby
    mb = CoxPH(stop_column="time", ties="breslow").train(x=["x1", "x2"], y="event", training_frame=fr)
    np.testing.assert_allclose(mb.output["coefficients"], beta_true, atol=0.12)


def test_word2vec_embeds_cooccurring_words_close():
    from h2o3_tpu.models import Word2Vec

    rng = np.random.default_rng(12)
    # two topic clusters; words within a topic co-occur
    topics = [["cat", "dog", "pet", "fur"], ["car", "road", "wheel", "engine"]]
    rows = []
    for _ in range(800):
        t = topics[rng.integers(2)]
        rows.extend(rng.choice(t, 6).tolist())
        rows.append(None)  # sentence break
    fr = Frame.from_pandas(pd.DataFrame({"words": rows}), column_types={"words": "string"})
    m = Word2Vec(vec_size=16, epochs=8, min_word_freq=5, window_size=3, seed=5,
                 sent_sample_rate=0.0).train(training_frame=fr)
    syn = m.find_synonyms("cat", 3)
    assert set(syn) <= {"dog", "pet", "fur"}, syn
    tv = m.transform(fr[["words"]])
    assert tv.ncol == 16


@pytest.mark.slow
def test_automl_with_target_encoding_preprocessing():
    from h2o3_tpu.automl.automl import AutoML

    rng = np.random.default_rng(14)
    n = 1500
    lev = rng.choice([f"L{i}" for i in range(12)], n)
    strength = {f"L{i}": i / 11 for i in range(12)}
    y = (rng.random(n) < np.vectorize(strength.get)(lev)).astype(int)
    df = pd.DataFrame({"g": lev, "x": rng.normal(size=n),
                       "y": np.where(y == 1, "T", "F")})
    fr = Frame.from_pandas(df)
    aml = AutoML(max_models=2, nfolds=0, seed=3, preprocessing=["target_encoding"],
                 include_algos=["GBM"], max_runtime_secs=300)
    aml.train(y="y", training_frame=fr)
    lb = aml.leaderboard.as_table()
    assert len(lb) >= 1
    best = aml.leader
    assert "g_te" in best.output["names"]


# ---------------------------------------------------------------------------
# TreeSHAP + tree inspection


def test_shap_local_accuracy_gbm():
    """Σ contributions + bias == raw margin (the TreeSHAP contract)."""
    from h2o3_tpu.models import GBM

    df, y = _binary(n=600, seed=21)
    fr = Frame.from_pandas(df)
    m = GBM(ntrees=5, max_depth=3, seed=4).train(y="y", training_frame=fr)
    contrib = m.predict_contributions(fr)
    mat = np.stack([contrib.vec(i).to_numpy() for i in range(contrib.ncol)], axis=1)
    total = mat.sum(axis=1)
    # raw margin = logit of predicted p1
    p1 = m.predict(fr).vec("Y").to_numpy().astype(np.float64)
    margin = np.log(p1 / (1 - p1))
    np.testing.assert_allclose(total, margin, atol=1e-4)
    assert contrib.names[-1] == "BiasTerm"


def test_shap_stump_closed_form():
    """Depth-1 stump: phi_j = f(x) − E[f] on the split feature, 0 elsewhere."""
    from h2o3_tpu.models import GBM

    rng = np.random.default_rng(22)
    n = 1000
    df = pd.DataFrame({"a": rng.normal(size=n), "b": rng.normal(size=n)})
    df["y"] = np.where(df["a"] > 0, 2.0, -1.0)
    fr = Frame.from_pandas(df)
    m = GBM(ntrees=1, max_depth=1, learn_rate=1.0, distribution="gaussian",
            seed=1).train(y="y", training_frame=fr)
    contrib = m.predict_contributions(fr)
    cb = contrib.vec("b").to_numpy()
    np.testing.assert_allclose(cb, 0.0, atol=1e-6)
    pred = m.predict(fr).vec("predict").to_numpy().astype(np.float64)
    ca = contrib.vec("a").to_numpy()
    bias = contrib.vec("BiasTerm").to_numpy()
    np.testing.assert_allclose(ca + bias, pred, atol=1e-4)
    assert np.allclose(bias, bias[0])  # constant bias = E[f]


def test_shap_drf_and_tree_view():
    from h2o3_tpu.models import DRF

    df, y = _binary(n=500, seed=23)
    fr = Frame.from_pandas(df)
    m = DRF(ntrees=4, max_depth=4, seed=5).train(y="y", training_frame=fr)
    contrib = m.predict_contributions(fr)
    mat = np.stack([contrib.vec(i).to_numpy() for i in range(contrib.ncol)], axis=1)
    raw = m._replay_all(fr) / m.output["ntrees_actual"]
    np.testing.assert_allclose(mat.sum(axis=1), raw, atol=1e-4)

    tv = m.tree_view(0)
    assert tv["node_id"][0] == 0 and not tv["is_leaf"][0]
    internal = [i for i, lf in enumerate(tv["is_leaf"]) if not lf and tv["cover"][i] > 0]
    for i in internal:
        assert tv["feature"][i] in ("a", "b", "c", "d")
        assert tv["left_child"][i] >= 0 and tv["right_child"][i] >= 0


def test_shap_survives_save_load(tmp_path):
    """node_w (TreeSHAP covers) must round-trip binary save/load."""
    import h2o3_tpu
    from h2o3_tpu.models import GBM

    df, y = _binary(n=400, seed=30)
    fr = Frame.from_pandas(df)
    m = GBM(ntrees=3, max_depth=3, seed=2).train(y="y", training_frame=fr)
    before = m.predict_contributions(fr)
    bmat = np.stack([before.vec(i).to_numpy() for i in range(before.ncol)], 1)
    p = h2o3_tpu.save_model(m, str(tmp_path) + "/")
    h2o3_tpu.remove(m.key)
    m2 = h2o3_tpu.load_model(p)
    after = m2.predict_contributions(fr)
    amat = np.stack([after.vec(i).to_numpy() for i in range(after.ncol)], 1)
    np.testing.assert_allclose(bmat, amat, atol=1e-6)
    tv = m2.tree_view(0)
    assert all(c > 0 for i, c in enumerate(tv["cover"]) if not tv["is_leaf"][i])


def test_leaf_node_assignment_node_id_matches_leaf_values():
    """Single gaussian tree at learn_rate=1: prediction == init + leaf value
    at the assigned Node_ID — the leaf assignment must agree with replay."""
    from h2o3_tpu.models import GBM
    from h2o3_tpu.models.tree.shap import _tree_nodes

    rng = np.random.default_rng(7)
    n = 500
    X = rng.normal(size=(n, 3))
    yv = X[:, 0] * 2 + (X[:, 1] > 0) - X[:, 2] ** 2 + rng.normal(size=n) * 0.1
    df = pd.DataFrame(X, columns=list("abc"))
    df["y"] = yv
    fr = Frame.from_pandas(df)
    m = GBM(ntrees=1, max_depth=3, learn_rate=1.0, distribution="gaussian",
            seed=5).train(y="y", training_frame=fr)

    la = m.predict_leaf_node_assignment(fr, type="Node_ID")
    assert la.names == ["T1.C1"]
    nid = la.vec("T1.C1").to_numpy().astype(int)
    nodes = _tree_nodes(m.output["trees"][0][0])
    assert all(nodes[j].is_leaf for j in np.unique(nid))
    leaf_vals = np.array([nodes[j].value for j in nid])
    pred = m.predict(fr).vec("predict").to_numpy()
    init = float(np.asarray(m.output["init_f"]))
    np.testing.assert_allclose(pred, init + leaf_vals, rtol=1e-5, atol=1e-5)


def test_leaf_node_assignment_paths_consistent_with_node_ids():
    from h2o3_tpu.models import GBM

    df, _ = _binary(n=400, seed=3)
    fr = Frame.from_pandas(df)
    m = GBM(ntrees=3, max_depth=3, seed=9).train(y="y", training_frame=fr)
    paths = m.predict_leaf_node_assignment(fr, type="Path")
    ids = m.predict_leaf_node_assignment(fr, type="Node_ID")
    assert paths.names == ids.names == ["T1.C1", "T2.C1", "T3.C1"]
    for c in paths.names:
        pv = paths.vec(c)
        s = np.asarray(pv.levels())[pv.to_numpy().astype(int)]
        assert all(set(p) <= {"L", "R"} for p in s)
        # same path <-> same node id, bijectively
        iv = ids.vec(c).to_numpy().astype(int)
        assert len(set(zip(s, iv))) == len(set(s)) == len(set(iv))


def test_leaf_node_assignment_handles_adaptive_ragged_masks():
    """Bin-adaptive models record NARROWER cat_mask at deep levels
    (numeric-only coarsening); the leaf walk must pad, not crash, and the
    masks must not affect numeric decisions."""
    from h2o3_tpu.models import GBM
    from h2o3_tpu.models.tree.shap import predict_leaf_node_assignment

    df, _ = _binary(n=300, seed=11)
    fr = Frame.from_pandas(df)
    m = GBM(ntrees=2, max_depth=4, seed=2).train(y="y", training_frame=fr)
    ref = predict_leaf_node_assignment(m, fr, type="Node_ID")
    # simulate adaptivity: truncate deep levels' masks to half width
    for group in m.output["trees"]:
        for t in group:
            for lv in t.levels[3:]:
                w = np.asarray(lv.cat_mask)
                lv.cat_mask = w[..., : max(w.shape[-1] // 2, 1)]
    out = predict_leaf_node_assignment(m, fr, type="Node_ID")
    for c in ref.names:
        np.testing.assert_array_equal(
            ref.vec(c).to_numpy(), out.vec(c).to_numpy()
        )
