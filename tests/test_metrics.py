"""Observability layer tests: the metrics registry (Prometheus exposition,
histogram bucket semantics), span tracing (nesting under concurrent jobs,
Chrome-trace serving), the BUILD_STATS back-compat alias, the /3/Metrics +
/3/Logs + /3/Jobs/{key}/trace routes, job timing fields, the /3/Timeline
merge, and the persist retry counter."""

import json
import re
import threading
import time
import urllib.error
import urllib.parse
import urllib.request

import numpy as np
import pandas as pd
import pytest

import h2o3_tpu
from h2o3_tpu.api.server import start_server
from h2o3_tpu.utils import metrics


@pytest.fixture(scope="module")
def server():
    return start_server(port=0)


def _get_json(server, path):
    with urllib.request.urlopen(server.url + path) as r:
        return json.loads(r.read())


def _get_text(server, path):
    with urllib.request.urlopen(server.url + path) as r:
        return r.read().decode(), r.headers.get("Content-Type", "")


def _post(server, path, payload):
    data = urllib.parse.urlencode(payload).encode()
    req = urllib.request.Request(server.url + path, data=data, method="POST")
    with urllib.request.urlopen(req) as r:
        return json.loads(r.read())


def _wait_job(server, job_key, timeout=120.0):
    t0 = time.time()
    while time.time() - t0 < timeout:
        j = _get_json(server, f"/3/Jobs/{job_key}")["jobs"][0]
        if j["status"] in ("DONE", "FAILED", "CANCELLED"):
            return j
    raise TimeoutError(job_key)


def _upload_frame(n=600, seed=0, key="metrics_train"):
    rng = np.random.default_rng(seed)
    df = pd.DataFrame({
        "a": rng.normal(size=n),
        "b": rng.normal(size=n),
        "y": np.where(rng.random(n) < 0.5, "dog", "cat"),
    })
    return h2o3_tpu.upload_file(df, destination_frame=key)


# ---------------------------------------------------------------------------
# registry semantics


def test_prometheus_exposition_parses_names_types_and_escaping():
    c = metrics.counter("px_demo_total", 'demo with "quotes"\nand newline')
    c.inc(3, route='/3/"x"\\y', method="GET")
    g = metrics.gauge("px_gauge", "a gauge")
    g.set(2.5)
    text = metrics.REGISTRY.to_prometheus()

    # TYPE lines present and correct
    assert "# TYPE px_demo_total counter" in text
    assert "# TYPE px_gauge gauge" in text
    # HELP newline is escaped — the exposition stays line-oriented
    help_line = next(
        ln for ln in text.splitlines() if ln.startswith("# HELP px_demo_total")
    )
    assert "\\n" in help_line and "\n" not in help_line[1:]
    # label values escape backslash and double-quote
    sample = next(
        ln for ln in text.splitlines()
        if ln.startswith("px_demo_total{") and ln.endswith(" 3")
    )
    assert '\\"x\\"' in sample and "\\\\y" in sample
    # every non-comment line is `name{labels} value` or `name value`
    for ln in text.splitlines():
        if not ln or ln.startswith("#"):
            continue
        assert re.match(
            r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{.*\})? [0-9eE+.inf-]+$", ln
        ), ln


def test_histogram_buckets_are_cumulative():
    h = metrics.histogram("hb_seconds", "x", buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.05, 0.5, 5.0, 50.0):
        h.observe(v)
    [(labels, cum, s, n)] = h.samples()
    assert labels == {}
    assert cum == [2, 3, 4, 5]  # le=0.1, le=1, le=10, +Inf — prefix sums
    assert n == 5 and s == pytest.approx(55.6)
    # rendered form repeats the cumulative contract with an +Inf bucket
    text = metrics.REGISTRY.to_prometheus()
    assert 'hb_seconds_bucket{le="+Inf"} 5' in text
    assert "hb_seconds_count 5" in text


def test_build_stats_alias_stays_in_sync_with_registry():
    from h2o3_tpu.models.tree import shared_tree as st

    st.reset_build_stats()
    st.BUILD_STATS["dispatches"] += 2
    assert metrics.counter_value("tree_dispatches_total") == 2
    # registry-side bump is visible through the alias too — one source of truth
    metrics.counter("tree_dispatches_total").inc(1)
    assert st.BUILD_STATS["dispatches"] == 3
    snap = st.reset_build_stats()
    assert snap["dispatches"] == 3
    assert st.BUILD_STATS["dispatches"] == 0
    assert metrics.counter_value("tree_dispatches_total") == 0


def test_span_nesting_reconstructs_tree_under_concurrent_jobs():
    metrics.reset_spans()

    def work(trace_id, tag):
        with metrics.trace(trace_id):
            with metrics.span(f"outer.{tag}"):
                with metrics.span(f"mid.{tag}"):
                    with metrics.span(f"leaf.{tag}"):
                        time.sleep(0.01)
                with metrics.span(f"leaf2.{tag}"):
                    pass

    threads = [
        threading.Thread(target=work, args=(f"job_t{i}", f"t{i}"))
        for i in range(3)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    for i in range(3):
        evs = metrics.trace_events(f"job_t{i}")
        by_name = {e["name"]: e for e in evs}
        # only this job's spans — no cross-thread contamination
        assert set(by_name) == {f"outer.t{i}", f"mid.t{i}",
                                f"leaf.t{i}", f"leaf2.t{i}"}
        assert by_name[f"outer.t{i}"]["parent"] is None
        assert by_name[f"mid.t{i}"]["parent"] == by_name[f"outer.t{i}"]["id"]
        assert by_name[f"leaf.t{i}"]["parent"] == by_name[f"mid.t{i}"]["id"]
        # sibling after a closed child re-parents to mid's PARENT level
        assert by_name[f"leaf2.t{i}"]["parent"] == by_name[f"outer.t{i}"]["id"]
        assert by_name[f"leaf.t{i}"]["dur_s"] >= 0.01


def test_metrics_disabled_is_inert():
    metrics.set_enabled(False)
    try:
        c = metrics.counter("gated_total", "x")
        base = c.value()
        c.inc(5)
        assert c.value() == base
        with metrics.span("gated.span"):
            pass
        assert all(
            e["name"] != "gated.span" for e in metrics.recent_spans(1000)
        )
        # always-on counters (the BUILD_STATS contract) keep counting
        from h2o3_tpu.models.tree import shared_tree as st

        st.reset_build_stats()
        st.BUILD_STATS["trees_built"] += 4
        assert st.reset_build_stats()["trees_built"] == 4
    finally:
        metrics.set_enabled(True)


# ---------------------------------------------------------------------------
# REST serving


def test_metrics_endpoint_prometheus_and_json(server):
    fr = _upload_frame(key="metrics_train_a")
    # touch GLM + GBM + persist + cluster so families from every subsystem
    # exist (the live-endpoint acceptance: >= 10 families across REST,
    # tree-build, GLM, persist, cluster)
    from h2o3_tpu.models.glm import GLM
    from h2o3_tpu.models.tree import GBM

    GBM(ntrees=2, max_depth=3, seed=1).train(y="y", training_frame=fr)
    GLM(family="binomial", lambda_=1e-4, max_iterations=3).train(
        y="y", training_frame=fr)
    _get_json(server, "/3/Cloud")

    text, ctype = _get_text(server, "/3/Metrics")
    assert ctype.startswith("text/plain")
    families = {
        m.group(1): m.group(2)
        for m in re.finditer(r"^# TYPE ([a-zA-Z0-9_:]+) (\w+)$", text, re.M)
    }
    for fam in ("rest_requests_total", "rest_request_seconds",
                "rest_requests_in_flight", "tree_dispatches_total",
                "tree_trees_built_total", "tree_programs_compiled_total",
                "glm_irls_iterations_total", "glm_irls_iteration_seconds",
                "persist_retries_total", "cloud_healthy", "jobs_total",
                "span_seconds", "mrtask_dispatches_total",
                "models_built_total"):
        assert fam in families, f"{fam} missing from /3/Metrics"
    assert len(families) >= 10
    assert families["rest_request_seconds"] == "histogram"
    assert families["rest_requests_in_flight"] == "gauge"
    # sample values present for the instrumented request counter
    assert re.search(r'^rest_requests_total\{.*route=.*\} \d+$', text, re.M)

    j = _get_json(server, "/3/Metrics?format=json")
    assert j["__meta"]["schema_type"] == "Metrics"
    assert "rest_requests_total" in j["families"]
    assert j["families"]["rest_requests_total"]["type"] == "counter"


def test_job_trace_endpoint_serves_chrome_trace_with_nested_builds(server):
    _upload_frame(key="metrics_train_b")
    resp = _post(server, "/3/ModelBuilders/gbm", {
        "training_frame": "metrics_train_b", "response_column": "y",
        "ntrees": 3, "max_depth": 3, "seed": 7,
    })
    key = resp["job"]["key"]["name"]
    j = _wait_job(server, key)
    assert j["status"] == "DONE", j

    # the per-job resource ledger rides the /3/Jobs wire schema (the
    # budget signal a fleet scheduler reads): device-seconds and the
    # tree-dispatch counts this build just spent
    led = j.get("ledger")
    assert led, "no ledger block on /3/Jobs"
    assert led["device_seconds"] > 0
    assert led["dispatches"].get("tree", 0) >= 1

    trace = _get_json(server, f"/3/Jobs/{key}/trace")
    evs = trace["traceEvents"]
    assert isinstance(evs, list) and evs, trace
    complete = [e for e in evs if e.get("ph") == "X"]
    for e in complete:
        assert {"name", "ts", "dur", "pid", "tid", "args"} <= set(e)
    names = {e["name"] for e in complete}
    assert "job" in names
    assert "gbm.build_tree" in names, names
    # nesting reconstructs: every build span's parent chain reaches the root
    ids = {e["args"]["span_id"]: e for e in complete}
    build = next(e for e in complete if e["name"] == "gbm.build_tree")
    seen = set()
    cur = build
    while cur["args"]["parent_id"] is not None:
        assert cur["args"]["parent_id"] in ids, "broken parent chain"
        assert cur["args"]["parent_id"] not in seen, "parent cycle"
        seen.add(cur["args"]["parent_id"])
        cur = ids[cur["args"]["parent_id"]]
    assert cur["name"] == "job"

    # 404 for unknown jobs
    with pytest.raises(urllib.error.HTTPError) as ei:
        _get_json(server, "/3/Jobs/nope_123/trace")
    assert ei.value.code == 404


def test_job_schema_reports_stable_duration(server):
    _upload_frame(key="metrics_train_c")
    resp = _post(server, "/3/ModelBuilders/gbm", {
        "training_frame": "metrics_train_c", "response_column": "y",
        "ntrees": 2, "max_depth": 2, "seed": 3,
    })
    key = resp["job"]["key"]["name"]
    j1 = _wait_job(server, key)
    assert j1["status"] == "DONE"
    assert j1["started_at"] > 0
    assert j1["duration_ms"] > 0
    time.sleep(0.05)
    j2 = _get_json(server, f"/3/Jobs/{key}")["jobs"][0]
    # finished: duration frozen at end_time, identical across polls
    assert j2["duration_ms"] == j1["duration_ms"]
    assert j2["started_at"] == j1["started_at"]
    # the per-phase rollup covers the build
    assert "span_summary" in j2 and "job" in j2["span_summary"]
    assert j2["span_summary"]["job"]["total_ms"] > 0


def test_logs_route_tails_and_filters_by_level(server):
    from h2o3_tpu.utils.log import Log

    Log.warn("metrics-test warn line")
    Log.info("metrics-test info line")
    out = _get_json(server, "/3/Logs?n=200")
    assert out["count"] == len(out["lines"]) > 0
    assert any("metrics-test info line" in ln for ln in out["lines"])
    warn_only = _get_json(server, "/3/Logs?n=200&level=WARN")
    assert any("metrics-test warn line" in ln for ln in warn_only["lines"])
    assert not any("metrics-test info line" in ln for ln in warn_only["lines"])
    with pytest.raises(urllib.error.HTTPError) as ei:
        _get_json(server, "/3/Logs?level=NOPE")
    assert ei.value.code == 400


def test_timeline_merges_span_events(server):
    from h2o3_tpu.utils import telemetry

    telemetry.record("test", "timeline merge marker")
    with metrics.span("timeline.merge.probe"):
        pass
    tl = _get_json(server, "/3/Timeline?n=500")
    kinds = {e["kind"] for e in tl["events"]}
    assert "span" in kinds
    assert isinstance(tl["compile_count"], int)
    assert tl["span_count"] >= 1
    span_evs = [e for e in tl["events"] if e["kind"] == "span"]
    assert any(e["msg"] == "timeline.merge.probe" for e in span_evs)
    assert all("dur_ms" in e for e in span_evs)


def test_timeline_compile_count_consistent_under_concurrent_records():
    """The satellite-fix regression: timeline() counting from the live deque
    while another thread records raced (RuntimeError: deque mutated during
    iteration). Hammer it."""
    from h2o3_tpu.utils import telemetry

    stop = threading.Event()
    errors = []

    def recorder():
        while not stop.is_set():
            telemetry.record("compile", "x")

    def reader():
        try:
            for _ in range(300):
                tl = telemetry.timeline(50)
                assert tl["compile_count"] >= 0
        except Exception as e:  # the pre-fix failure mode
            errors.append(e)

    t1 = threading.Thread(target=recorder)
    t2 = threading.Thread(target=reader)
    t1.start(); t2.start()
    t2.join(); stop.set(); t1.join()
    assert not errors, errors


def test_persist_retry_bumps_counter_and_logs(monkeypatch, tmp_path):
    from h2o3_tpu import persist
    from h2o3_tpu.utils.log import Log

    monkeypatch.setenv("H2O3_TPU_PERSIST_RETRIES", "3")
    monkeypatch.setenv("H2O3_TPU_PERSIST_BACKOFF", "0.0")
    before = metrics.counter_value("persist_retries_total", op="write")
    attempts = []

    def flaky():
        attempts.append(1)
        if len(attempts) < 3:
            raise OSError("transient blip")
        return "done"

    assert persist._with_retries(flaky, "write /tmp/flaky-probe") == "done"
    after = metrics.counter_value("persist_retries_total", op="write")
    assert after - before == 2
    tail = "\n".join(Log.tail(50, level="WARN"))
    assert "flaky-probe" in tail and "retrying" in tail
