"""REST client tests — the h2o-py connection-flow successor driven against
a real in-process server (SURVEY.md §4 'real stack, local topology')."""

import numpy as np
import pandas as pd
import pytest

from h2o3_tpu.api.server import start_server
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.client import H2OClientError, connect


@pytest.fixture(scope="module")
def conn():
    server = start_server(port=0)
    return connect(server.url)


def test_connect_and_cluster(conn):
    assert conn.cloud["cloud_size"] >= 1


def test_full_flow_over_client(conn, tmp_path):
    rng = np.random.default_rng(8)
    n = 600
    df = pd.DataFrame({
        "a": rng.normal(size=n), "b": rng.normal(size=n),
        "y": np.where(rng.normal(size=n) + 0.8 * rng.normal(size=n) > 0, "up", "down"),
    })
    p = tmp_path / "train.csv"
    df.to_csv(p, index=False)

    key = conn.import_file(str(p), destination_frame="client_train")
    fr = conn.frame(key)
    assert fr["rows"] == n

    model = conn.train("gbm", y="y", training_frame=key, ntrees=5, max_depth=3)
    assert model["algo"] == "gbm"
    auc = model["output"]["training_metrics"]["auc"]
    assert 0.4 <= auc <= 1.0

    pred_key = conn.predict(model["model_id"]["name"], key)
    pfr = conn.frame(pred_key)
    assert pfr["rows"] == n

    mm = conn.model_performance(model["model_id"]["name"], key)
    assert mm["auc"] == pytest.approx(auc, abs=1e-9)

    out = conn.rapids(f"(mean (cols_py {key} 'a'))")
    assert out["scalar"] == pytest.approx(float(df["a"].mean()), rel=1e-5)


def test_client_error_surface(conn):
    with pytest.raises(H2OClientError) as ei:
        conn.frame("no_such_frame")
    assert ei.value.status == 404


def _mkdf(n, c, seed=6):
    rng = np.random.default_rng(seed)
    df = pd.DataFrame({f"f{i}": rng.normal(size=n) for i in range(c)})
    eta = df["f0"] * 2 - df["f1"] + 0.5 * df["f2"]
    df["y"] = np.where(eta + rng.normal(size=n) > 0, "P", "N")
    return df


def test_estimator_surface_h2o_py_style(tmp_path):
    """An h2o-py-shaped script runs unmodified (module path aside)."""
    from h2o3_tpu.estimators import (
        H2OGeneralizedLinearEstimator,
        H2OGradientBoostingEstimator,
    )

    df = _mkdf(2000, 3)
    fr = Frame.from_pandas(df)
    m = H2OGradientBoostingEstimator(ntrees=8, max_depth=3, seed=1)
    m.train(x=[c for c in df.columns if c != "y"], y="y", training_frame=fr)
    assert m.auc() > 0.8
    assert m.model_id.startswith("gbm")
    pred = m.predict(fr)
    assert "predict" in pred.names
    p = m.download_mojo(str(tmp_path))
    assert p.endswith(".zip")
    vi = m.varimp(use_pandas=True)
    assert "variable" in vi.columns

    g = H2OGeneralizedLinearEstimator(family="binomial", lambda_=1e-4)
    g.train(y="y", training_frame=fr)
    assert 0 < g.logloss() < 1

    import pytest as _pytest

    with _pytest.raises(TypeError, match="unknown parameters"):
        H2OGradientBoostingEstimator(no_such_param=1)


def test_rest_grids_logs_mojo_upload(tmp_path):
    """The new REST surface: /99/Grid, /3/Models/{id}/mojo, /3/Logs,
    /3/PostFile — driven through the thin client against a live server."""
    import h2o3_tpu

    srv = h2o3_tpu.start_server(port=0)
    try:
        conn = h2o3_tpu.connect(srv.url)

        df = _mkdf(1200, 3)
        csv = str(tmp_path / "up.csv")
        df.to_csv(csv, index=False)
        key = conn.upload_file(csv, destination_frame="uploaded_fr")
        assert key == "uploaded_fr"
        assert conn.frame(key)["rows"] == 1200

        grid = conn.grid(
            "gbm", {"max_depth": [2, 3]}, y="y", training_frame=key,
            ntrees=3, seed=1,
        )
        assert len(grid["model_ids"]) == 2
        assert grid["summary_table"][0]["model_id"]

        best = grid["model_ids"][0]["name"]
        mojo = str(tmp_path / "dl.zip")
        conn.download_mojo(best, mojo)
        from h2o3_tpu.genmodel import MojoModel

        mm = MojoModel.load(mojo)
        out = mm.predict(df.drop(columns=["y"]).head(5))
        assert len(out["predict"]) == 5

        log = conn.logs(tail=50)
        assert "gbm" in log or "grid" in log
    finally:
        srv.stop()
