"""REST client tests — the h2o-py connection-flow successor driven against
a real in-process server (SURVEY.md §4 'real stack, local topology')."""

import numpy as np
import pandas as pd
import pytest

from h2o3_tpu.api.server import start_server
from h2o3_tpu.client import H2OClientError, connect


@pytest.fixture(scope="module")
def conn():
    server = start_server(port=0)
    return connect(server.url)


def test_connect_and_cluster(conn):
    assert conn.cloud["cloud_size"] >= 1


def test_full_flow_over_client(conn, tmp_path):
    rng = np.random.default_rng(8)
    n = 600
    df = pd.DataFrame({
        "a": rng.normal(size=n), "b": rng.normal(size=n),
        "y": np.where(rng.normal(size=n) + 0.8 * rng.normal(size=n) > 0, "up", "down"),
    })
    p = tmp_path / "train.csv"
    df.to_csv(p, index=False)

    key = conn.import_file(str(p), destination_frame="client_train")
    fr = conn.frame(key)
    assert fr["rows"] == n

    model = conn.train("gbm", y="y", training_frame=key, ntrees=5, max_depth=3)
    assert model["algo"] == "gbm"
    auc = model["output"]["training_metrics"]["auc"]
    assert 0.4 <= auc <= 1.0

    pred_key = conn.predict(model["model_id"]["name"], key)
    pfr = conn.frame(pred_key)
    assert pfr["rows"] == n

    mm = conn.model_performance(model["model_id"]["name"], key)
    assert mm["auc"] == pytest.approx(auc, abs=1e-9)

    out = conn.rapids(f"(mean (cols_py {key} 'a'))")
    assert out["scalar"] == pytest.approx(float(df["a"].mean()), rel=1e-5)


def test_client_error_surface(conn):
    with pytest.raises(H2OClientError) as ei:
        conn.frame("no_such_frame")
    assert ei.value.status == 404
