"""Scoring-tier tests (ISSUE 7): the coalescing batch scorer, the
``/3/Predictions/rows`` route, bounded prediction-frame retention, and the
persistent-compile-cache cross-process proof.

The parity suite is the load-bearing part: the compiled batch scorer must be
BYTE-equal to ``Model.predict`` through the frame path (same replay ops in
the same order, no cross-row reductions — the same inertness argument as the
PR-1 shape buckets) and must agree with the offline MOJO scorer, including
NA and unseen-categorical rows.
"""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np
import pandas as pd
import pytest

from h2o3_tpu.cluster.registry import DKV
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models import GBM
from h2o3_tpu.utils import metrics as _mx

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# rows exercising the adaptation corners: NA numeric, missing column,
# unseen categorical level, numeric-typed payload for everything else
SCORE_ROWS = [
    {"a": 0.37, "b": -1.25, "c": "x"},
    {"a": None, "b": 0.0, "c": "NEVER_SEEN"},
    {"a": 2.25, "b": float("nan"), "c": "z"},
    {"b": 0.5, "c": "y"},  # a absent entirely
    {"a": -0.75, "b": 1.5, "c": None},
]


def _rows_df(rows=SCORE_ROWS):
    return pd.DataFrame({
        "a": [r.get("a") for r in rows],
        "b": [r.get("b") for r in rows],
        "c": [r.get("c") for r in rows],
    })


@pytest.fixture(scope="module")
def binom_model():
    rng = np.random.default_rng(7)
    n = 900
    df = pd.DataFrame({
        "a": rng.normal(size=n),
        "b": rng.normal(size=n),
        "c": rng.choice(["x", "y", "z"], n),
        "y": np.where(rng.random(n) < 0.5, "dog", "cat"),
    })
    df.loc[::13, "a"] = np.nan
    fr = Frame.from_pandas(df, destination_frame="serve_train")
    return GBM(ntrees=8, max_depth=3, seed=1).train(y="y", training_frame=fr)


def _frame_path_probs(model, rows=SCORE_ROWS):
    pf = model.predict(Frame.from_pandas(_rows_df(rows)))
    dom = model.output["response_domain"]
    probs = np.stack([pf.vec(str(d)).to_numpy() for d in dom], axis=1)
    codes = pf.vec("predict").to_numpy()
    labels = np.asarray(dom, dtype=object)[codes]
    return probs, labels


def test_rows_scorer_byte_equal_frame_path(binom_model):
    from h2o3_tpu import serving

    out = serving.score_rows(binom_model, SCORE_ROWS)
    dom = binom_model.output["response_domain"]
    got = np.stack([np.asarray(out[str(d)], np.float32) for d in dom], axis=1)
    want, labels = _frame_path_probs(binom_model)
    assert got.tobytes() == want.tobytes()  # BYTE-equal, not allclose
    assert list(out["predict"]) == list(labels)


def test_rows_scorer_column_table_payload(binom_model):
    """The column-table payload shape scores identically to row dicts."""
    from h2o3_tpu import serving

    table = {
        "a": [r.get("a") for r in SCORE_ROWS],
        "b": [r.get("b") for r in SCORE_ROWS],
        "c": [r.get("c") for r in SCORE_ROWS],
    }
    a = serving.score_rows(binom_model, SCORE_ROWS)
    b = serving.score_rows(binom_model, table)
    for k in a:
        np.testing.assert_array_equal(np.asarray(a[k]), np.asarray(b[k]))


def test_rows_scorer_matches_mojo(binom_model, tmp_path):
    from h2o3_tpu import serving
    from h2o3_tpu.genmodel import MojoModel
    from h2o3_tpu.models.export import export_mojo

    path = str(tmp_path / "serve.zip")
    export_mojo(binom_model, path)
    mojo = MojoModel.load(path)
    live = serving.score_rows(binom_model, SCORE_ROWS)
    # the MOJO scores the SAME rows (dict rows include the NA/unseen cases)
    off = mojo.predict(_rows_df(SCORE_ROWS))
    dom = binom_model.output["response_domain"]
    for d in dom:
        np.testing.assert_allclose(
            np.asarray(live[str(d)], np.float64),
            np.asarray(off[str(d)], np.float64), atol=1e-5)
    assert [str(v) for v in live["predict"]] == [str(v) for v in off["predict"]]


def test_regression_and_multinomial_byte_equal(rng):
    from h2o3_tpu import serving

    n = 500
    rows = [{"a": 0.5, "b": 1.0}, {"a": None, "b": -2.0}]
    df2 = pd.DataFrame({"a": [0.5, None], "b": [1.0, -2.0]})
    # regression
    df = pd.DataFrame({"a": rng.normal(size=n), "b": rng.normal(size=n),
                       "y": rng.normal(size=n)})
    m = GBM(ntrees=5, max_depth=3, seed=1).train(
        y="y", training_frame=Frame.from_pandas(df, destination_frame="sv_reg"))
    out = serving.score_rows(m, rows)
    pf = m.predict(Frame.from_pandas(df2))
    assert (pf.vec("predict").to_numpy().tobytes()
            == np.asarray(out["predict"], np.float32).tobytes())
    # multinomial
    df = pd.DataFrame({"a": rng.normal(size=n), "b": rng.normal(size=n),
                       "y": rng.choice(["r", "g", "bl"], n)})
    m3 = GBM(ntrees=4, max_depth=3, seed=1).train(
        y="y", training_frame=Frame.from_pandas(df, destination_frame="sv_mul"))
    out = serving.score_rows(m3, rows)
    pf = m3.predict(Frame.from_pandas(df2))
    for c in ("r", "g", "bl"):
        assert (pf.vec(c).to_numpy().tobytes()
                == np.asarray(out[c], np.float32).tobytes())


def test_batch_bucket_reuses_program(binom_model):
    """Batch sizes within one rows-bucket (and a second scoring pass of the
    same model) compile ZERO new scorer programs — the serving half of the
    PR-1 shape-bucket contract."""
    from h2o3_tpu import serving

    serving.score_rows(binom_model, SCORE_ROWS)  # warm the bucket
    compiled = _mx.counter_value("serving_scorer_programs_total",
                                 event="compile")
    hits0 = _mx.counter_value("serving_scorer_programs_total", event="hit")
    serving.score_rows(binom_model, SCORE_ROWS[:2])
    serving.score_rows(binom_model, SCORE_ROWS * 4)  # 20 rows, same bucket
    assert _mx.counter_value(
        "serving_scorer_programs_total", event="compile") == compiled
    assert _mx.counter_value(
        "serving_scorer_programs_total", event="hit") >= hits0 + 2


def test_coalescing_batches_concurrent_requests(binom_model, monkeypatch):
    """Concurrent submits coalesce into fewer dispatches (occupancy > 1)
    and every request still gets ITS rows' predictions."""
    from h2o3_tpu import serving
    from h2o3_tpu.serving import BATCH_OCCUPANCY

    monkeypatch.setenv("H2O3_TPU_SCORE_BATCH_WINDOW_MS", "60")
    occ0 = [(s, c) for _, _, s, c in BATCH_OCCUPANCY.samples()]
    sum0 = occ0[0][0] if occ0 else 0.0
    cnt0 = occ0[0][1] if occ0 else 0

    results = [None] * 8
    errors = []

    def worker(i):
        try:
            results[i] = serving.score_rows(binom_model, [SCORE_ROWS[i % 5]])
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
    barrier_start = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert not errors
    occ1 = [(s, c) for _, _, s, c in BATCH_OCCUPANCY.samples()]
    dsum, dcnt = occ1[0][0] - sum0, occ1[0][1] - cnt0
    assert dsum == 8  # every request accounted for
    assert dcnt < 8  # ...in fewer dispatches than requests
    assert dsum / dcnt > 1.0  # mean occupancy > 1
    # per-request results match the inline (window=0) path bitwise
    monkeypatch.setenv("H2O3_TPU_SCORE_BATCH_WINDOW_MS", "0")
    for i, res in enumerate(results):
        want = serving.score_rows(binom_model, [SCORE_ROWS[i % 5]])
        for k in want:
            np.testing.assert_array_equal(np.asarray(res[k]),
                                          np.asarray(want[k]))
    assert time.monotonic() - barrier_start < 30


def test_deadline_shed(binom_model, monkeypatch):
    from h2o3_tpu import serving

    monkeypatch.setenv("H2O3_TPU_SCORE_BATCH_WINDOW_MS", "120")
    monkeypatch.setenv("H2O3_TPU_SCORE_DEADLINE_MS", "1")
    with pytest.raises(serving.ShedError) as ei:
        serving.score_rows(binom_model, [SCORE_ROWS[0]])
    assert ei.value.status == 504


def test_queue_full_shed(binom_model, monkeypatch):
    from h2o3_tpu import serving
    from h2o3_tpu.serving.batcher import batcher_for

    monkeypatch.setenv("H2O3_TPU_SCORE_BATCH_WINDOW_MS", "150")
    monkeypatch.setenv("H2O3_TPU_SCORE_QUEUE_MAX", "3")
    done = threading.Event()

    def filler():
        try:
            serving.score_rows(binom_model, SCORE_ROWS[:3])  # 3 rows queue up
        finally:
            done.set()

    t = threading.Thread(target=filler)
    t.start()
    # wait until the filler's rows are actually queued
    b = batcher_for(binom_model)
    t0 = time.monotonic()
    while b._rows_queued < 3 and time.monotonic() - t0 < 5:
        time.sleep(0.005)
    assert b._rows_queued >= 3
    with pytest.raises(serving.ShedError) as ei:
        serving.score_rows(binom_model, [SCORE_ROWS[0]])
    assert ei.value.status == 429
    done.wait(timeout=30)
    t.join(timeout=5)


# ---------------------------------------------------------------------------
# REST surface


@pytest.fixture(scope="module")
def server():
    from h2o3_tpu.api.server import start_server

    return start_server(port=0)


def _post_json(server, path, payload):
    req = urllib.request.Request(
        server.url + path, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req) as r:
        return json.loads(r.read())


def test_rows_route_over_rest(binom_model, server):
    out = _post_json(server, "/3/Predictions/rows",
                     {"model": binom_model.key, "rows": SCORE_ROWS})
    assert out["rows"] == len(SCORE_ROWS)
    preds = out["predictions"]
    want, labels = _frame_path_probs(binom_model)
    dom = binom_model.output["response_domain"]
    for k, d in enumerate(dom):
        # json round-trips float32 exactly through float(); compare exact
        assert preds[str(d)] == [float(v) for v in want[:, k]]
    assert preds["predict"] == list(labels)


def test_rows_route_client(binom_model, server):
    from h2o3_tpu.client import connect

    conn = connect(server.url)
    preds = conn.predict_rows(binom_model.key, SCORE_ROWS[:2])
    want, _ = _frame_path_probs(binom_model, SCORE_ROWS[:2])
    dom = binom_model.output["response_domain"]
    assert preds[str(dom[1])] == [float(v) for v in want[:, 1]]


def test_rows_route_errors(binom_model, server):
    import urllib.error

    with pytest.raises(urllib.error.HTTPError) as ei:
        _post_json(server, "/3/Predictions/rows", {"rows": SCORE_ROWS})
    assert ei.value.code == 400
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post_json(server, "/3/Predictions/rows",
                   {"model": "no_such_model", "rows": SCORE_ROWS})
    assert ei.value.code == 404
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post_json(server, "/3/Predictions/rows",
                   {"model": binom_model.key, "rows": []})
    assert ei.value.code == 400


def test_prediction_frame_retention(binom_model, server, monkeypatch):
    """Hammering /3/Predictions with generated dest keys must not grow the
    DKV beyond the retention bound (the serving-load DKV leak fix)."""
    monkeypatch.setenv("H2O3_TPU_PREDICTIONS_RETAIN", "4")
    before = _mx.counter_value("rest_prediction_frames_evicted_total")
    path = (f"/3/Predictions/models/{binom_model.key}"
            f"/frames/serve_train")
    made = []
    for _ in range(10):
        out = _post_json(server, path, {})
        made.append(out["predictions_frame"]["name"])
    live = [k for k in made if DKV.get(k) is not None]
    assert len(live) <= 4, f"retention bound leaked: {live}"
    # the newest frames survive (a client polling its own result in time
    # still finds it)
    assert DKV.get(made[-1]) is not None
    assert _mx.counter_value(
        "rest_prediction_frames_evicted_total") >= before + 6
    # an explicitly-named dest is NEVER auto-evicted
    out = _post_json(server, path, {"predictions_frame": "my_kept_preds"})
    for _ in range(6):
        _post_json(server, path, {})
    assert DKV.get("my_kept_preds") is not None
    DKV.remove("my_kept_preds")


# ---------------------------------------------------------------------------
# persistent compilation cache: cross-process zero-compile proof


_CACHE_PROBE = r"""
import json, os, sys
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
# the jax_compilation_cache_dir hook (cluster/cloud.py wires this for
# accelerator backends; CPU sets it explicitly here — same machine, so the
# AOT feature-mismatch hazard that disables it by default does not apply)
jax.config.update("jax_compilation_cache_dir", sys.argv[1])
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
import numpy as np, pandas as pd
import h2o3_tpu
h2o3_tpu.init()
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models import GBM
from h2o3_tpu import serving
from h2o3_tpu.utils import metrics as mx
rng = np.random.default_rng(11)
n = 400
df = pd.DataFrame({"a": rng.normal(size=n), "b": rng.normal(size=n),
                   "y": np.where(rng.random(n) < 0.5, "p", "q")})
m = GBM(ntrees=4, max_depth=3, seed=5).train(
    y="y", training_frame=Frame.from_pandas(df, destination_frame="cc"))
out = serving.score_rows(m, [{"a": 0.1, "b": -0.2}, {"a": None, "b": 3.0}])
print(json.dumps({
    "p_q": [float(v) for v in out["q"]],
    "cache_hits": mx.counter_value("compile_cache_hits_total"),
}))
"""


def _cache_files(d):
    out = set()
    for root, _dirs, files in os.walk(d):
        out.update(os.path.join(root, f) for f in files)
    return out


def test_compile_cache_cross_process(tmp_path):
    """A second process training + scoring the SAME shape bucket compiles
    zero new programs: the persistent XLA cache (the
    ``jax_compilation_cache_dir`` hook at cluster/cloud.py) serves every
    program, proven by the cache dir gaining no new entries while the run
    still produces identical predictions."""
    cache = str(tmp_path / "xla_cache")
    os.makedirs(cache)
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}

    def run():
        p = subprocess.run(
            [sys.executable, "-c", _CACHE_PROBE, cache],
            capture_output=True, text=True, timeout=420, env=env, cwd=ROOT)
        assert p.returncode == 0, p.stderr[-3000:]
        return json.loads(p.stdout.strip().splitlines()[-1])

    first = run()
    files_after_first = _cache_files(cache)
    assert files_after_first, "first process persisted no cache entries"
    second = run()
    files_after_second = _cache_files(cache)
    new = files_after_second - files_after_first
    assert not new, f"second process compiled {len(new)} new programs"
    # identical predictions from the cache-served programs
    assert second["p_q"] == first["p_q"]
    # the registry surfaces cache effectiveness (jax monitoring bridge);
    # soft on jax versions without the event, hard on this container's
    assert second["cache_hits"] >= first["cache_hits"]
