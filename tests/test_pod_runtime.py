"""Multihost pod runtime (ISSUE 14): env-driven bootstrap + formation,
pod-restart wiring, coordinator-free sharded ingest byte-parity, and the
satellite lanes (object-store watch etags, the categorical iforest serving
lane, serving-registry warm boot).
"""

import os
import socket
import subprocess
import sys
import textwrap
import time

import numpy as np
import pandas as pd
import pytest

from test_multihost import _skip_unless_two_process_capable


# ---------------------------------------------------------------------------
# env-driven bootstrap + formation


def test_pod_env_parsing(monkeypatch):
    from h2o3_tpu.cluster import multihost

    for var in ("H2O3_TPU_COORDINATOR", "H2O3_TPU_NUM_PROCESSES",
                "H2O3_TPU_PROCESS_ID", "H2O3_TPU_POD_NAME", "POD_NAME"):
        monkeypatch.delenv(var, raising=False)
    assert multihost.pod_env() is None  # unset → single-host mode

    monkeypatch.setenv("H2O3_TPU_COORDINATOR", "pod-0.svc:1234")
    with pytest.raises(ValueError, match="NUM_PROCESSES"):
        multihost.pod_env()  # half-configured pods must fail loudly

    monkeypatch.setenv("H2O3_TPU_NUM_PROCESSES", "4")
    monkeypatch.setenv("POD_NAME", "h2o3-tpu-2")  # StatefulSet ordinal
    env = multihost.pod_env()
    assert env == {"coordinator": "pod-0.svc:1234", "num_processes": 4,
                   "process_id": 2}

    monkeypatch.setenv("H2O3_TPU_PROCESS_ID", "3")  # explicit id wins
    assert multihost.pod_env()["process_id"] == 3

    monkeypatch.setenv("H2O3_TPU_PROCESS_ID", "9")  # out of range
    with pytest.raises(ValueError, match="out of range"):
        multihost.pod_env()


def test_formation_single_process():
    """The degenerate 1-process pod still forms: barrier no-ops, per-host
    device enumeration covers the local devices, and the record carries the
    mesh shape the program caches will key on."""
    from h2o3_tpu.cluster import multihost

    rec = multihost.formation()
    assert rec["processes"] == 1 and rec["process_index"] == 0
    assert rec["devices"] == 8 and rec["hosts"] == {
        "0": list(range(8))}
    assert rec["mesh"] in ({"rows": 8}, {"rows": 1, "cols": 8})
    assert multihost.probe_capability() == ""  # single-process: capable


def test_pod_restart_watcher_inert_by_default():
    """H2O3_TPU_POD_EXIT_DEGRADED=0 (default) + single-process: the watcher
    installs, never exits the process even with the latch set, and
    uninstalls cleanly — the two-process recovery fixture depends on the
    in-process survivor island staying available."""
    from h2o3_tpu.cluster import cloud, multihost

    multihost.install_pod_restart(poll=0.05)
    try:
        cloud.mark_degraded("pod-restart inertness probe")
        time.sleep(0.3)  # an exit would kill this pytest process
        assert cloud.degraded_reason() is not None
    finally:
        cloud.clear_degraded()
        multihost.uninstall_pod_restart()


@pytest.mark.slow
def test_two_process_bootstrap_formation_and_capability(tmp_path):
    """Env-driven bootstrap on a REAL two-process cloud: both ranks form
    through cluster/multihost.bootstrap_from_env (no args), the formation
    barrier passes, per-host device enumeration shows 2 hosts × 2 devices,
    and the runtime capability probe agrees with the test-suite probe.
    Auto-skips with root cause where this jaxlib refuses cross-process CPU
    collectives (the PR-4 contract)."""
    _skip_unless_two_process_capable()
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    prog = textwrap.dedent(f"""
        import os, sys
        os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ.pop("PALLAS_AXON_POOL_IPS", None)
        os.environ["H2O3_TPU_COORDINATOR"] = "127.0.0.1:{port}"
        os.environ["H2O3_TPU_NUM_PROCESSES"] = "2"
        os.environ["H2O3_TPU_POD_NAME"] = "h2o3-tpu-" + sys.argv[1]
        import jax
        jax.config.update("jax_platforms", "cpu")
        from h2o3_tpu.cluster import multihost
        rec = multihost.bootstrap_from_env()
        assert rec is not None
        assert rec["processes"] == 2, rec
        assert rec["devices"] == 4, rec
        assert len(rec["hosts"]) == 2, rec
        assert all(len(v) == 2 for v in rec["hosts"].values()), rec
        assert multihost.probe_capability() == "", multihost.probe_capability()
        print(f"proc {{sys.argv[1]}} FORMED", rec["mesh"])
    """)
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", prog, str(i)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        for i in range(2)
    ]
    outs = [p.communicate(timeout=180)[0].decode() for p in procs]
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} failed:\n{out[-3000:]}"
        assert f"proc {i} FORMED" in out


# ---------------------------------------------------------------------------
# coordinator-free sharded ingest: byte-range parses pinned byte-equal


def test_sharded_ingest_multirange_byte_equal(tmp_path, monkeypatch):
    """H2O3_TPU_INGEST_SHARDS=3 splits the parse into three byte ranges
    (each located by the streaming newline scan and tokenized by the native
    byte-range parser) — values, categorical codes and domains must be
    BYTE-equal to the one-shot parse (the pod ingest acceptance pin)."""
    from h2o3_tpu.frame.parse import parse, parse_sharded

    rng = np.random.default_rng(3)
    n = 3001  # deliberately not a shard multiple
    df = pd.DataFrame({
        "x": rng.normal(size=n),
        "g": rng.choice(["u", "v", "w"], n),
        "i": rng.integers(0, 9, n),
    })
    df.loc[::13, "x"] = np.nan
    csv = tmp_path / "pod.csv"
    df.to_csv(csv, index=False)
    a = parse({"source_frames": [str(csv)]}, destination_frame="pod_a")
    monkeypatch.setenv("H2O3_TPU_INGEST_SHARDS", "3")
    b = parse_sharded({"source_frames": [str(csv)]},
                      destination_frame="pod_b")
    assert b.nrow == a.nrow == n
    for col in ("x", "i"):
        assert (np.asarray(a.vec(col).to_numpy(), np.float32).tobytes()
                == np.asarray(b.vec(col).to_numpy(), np.float32).tobytes()), col
    assert tuple(a.vec("g").domain) == tuple(b.vec("g").domain)
    assert (a.vec("g").to_numpy().tobytes()
            == b.vec("g").to_numpy().tobytes())


def test_sharded_ingest_seeds_chunkstore_mirrors(tmp_path, monkeypatch):
    """With an HBM window configured (the out-of-core plane armed), the
    single-process sharded parse seeds each Vec's spill-tier host mirror so
    streaming builds never pay a device pull per column."""
    from h2o3_tpu.frame.parse import parse_sharded

    rng = np.random.default_rng(5)
    n = 2000
    df = pd.DataFrame({"x": rng.normal(size=n), "i": rng.integers(0, 5, n)})
    csv = tmp_path / "mirror.csv"
    df.to_csv(csv, index=False)
    monkeypatch.setenv("H2O3_TPU_HBM_WINDOW_BYTES", str(1 << 20))
    fr = parse_sharded({"source_frames": [str(csv)]},
                       destination_frame="pod_mirror")
    for col in ("x", "i"):
        assert fr.vec(col)._hostbuf is not None, col


# ---------------------------------------------------------------------------
# satellite: object-store etags (the registry's model store need not be FS)


class _FakeS3:
    """Minimal boto3-client stand-in: enough surface for probe/list_dir."""

    def __init__(self):
        self.objects = {
            ("bucket", "models/m1"): (b"one", "etag-1"),
            ("bucket", "models/m2"): (b"twotwo", "etag-2"),
            ("bucket", "models/sub/nested"): (b"x", "etag-3"),
            ("bucket", "other/m3"): (b"y", "etag-4"),
        }

    def head_object(self, Bucket, Key):
        data, etag = self.objects[(Bucket, Key)]
        return {"ETag": f'"{etag}"', "ContentLength": len(data)}

    def list_objects_v2(self, Bucket, Prefix, Delimiter,
                        ContinuationToken=None):
        names = set()
        for (b, k) in self.objects:
            if b != Bucket or not k.startswith(Prefix):
                continue
            rest = k[len(Prefix):]
            if Delimiter in rest:
                continue  # pseudo-directory: excluded like a real listing
            names.add(k)
        return {"Contents": [{"Key": k} for k in sorted(names)],
                "IsTruncated": False}


def test_s3_probe_and_list_dir_etags():
    from h2o3_tpu.persist import PersistS3

    b = PersistS3.__new__(PersistS3)  # skip boto3 import (not in image)
    b._s3 = _FakeS3()
    # probe: ETag + size, changes when content does, never a read
    assert b.probe("s3://bucket/models/m1") == ("etag-1", 3)
    assert b.probe("s3://bucket/models/gone") is None
    # list_dir: direct children only, sorted
    assert b.list_dir("s3://bucket/models") == ["m1", "m2"]


class _FakeBlob:
    def __init__(self, name, etag, generation, size):
        self.name, self.etag = name, etag
        self.generation, self.size = generation, size

    def reload(self):
        if self.etag is None:
            raise FileNotFoundError(self.name)


class _FakeGSClient:
    def __init__(self, blobs):
        self._blobs = blobs

    def bucket(self, name):
        client = self

        class _B:
            def blob(self, key):
                for bl in client._blobs:
                    if bl.name == key:
                        return bl
                return _FakeBlob(key, None, 0, 0)

        return _B()

    def list_blobs(self, bucket, prefix, delimiter):
        return [b for b in self._blobs
                if b.name.startswith(prefix)
                and delimiter not in b.name[len(prefix):]]


def test_gs_probe_and_list_dir_etags():
    from h2o3_tpu.persist import PersistGS

    b = PersistGS.__new__(PersistGS)
    b._client = _FakeGSClient([
        _FakeBlob("models/m1", "e1", 7, 11),
        _FakeBlob("models/m2", "e2", 3, 22),
        _FakeBlob("models/sub/nested", "e3", 1, 5),
    ])
    assert b.probe("gs://bucket/models/m1") == ("e1", 7, 11)
    assert b.probe("gs://bucket/models/gone") is None
    assert b.list_dir("gs://bucket/models") == ["m1", "m2"]


def test_fs_probe_unchanged_pin(tmp_path):
    """The FS backend's etag/listing behavior is byte-identical to before
    the object-store SPI growth: (mtime_ns, size) stats, sorted names."""
    from h2o3_tpu import persist

    p = tmp_path / "m"
    p.write_bytes(b"abc")
    st = os.stat(p)
    assert persist.probe(str(p)) == (st.st_mtime_ns, st.st_size)
    (tmp_path / "b").write_bytes(b"")
    assert persist.list_dir(str(tmp_path)) == ["b", "m"]


# ---------------------------------------------------------------------------
# satellite: categorical isolation-forest serving lane


def test_iforest_categorical_lane_byte_equal():
    """An IF trained on a frame WITH categorical features rides the
    compiled iforest lane (no generic fallback) and row-payload scores are
    byte-equal to the frame path — including a scoring frame whose local
    category interning DIFFERS from training (the training-domain codes
    satellite, ROADMAP 3b)."""
    from h2o3_tpu import serving
    from h2o3_tpu.frame.frame import Frame
    from h2o3_tpu.models.isolation_forest import IsolationForest

    rng = np.random.default_rng(9)
    n = 300
    df = pd.DataFrame({
        "a": rng.normal(size=n),
        "c": pd.Categorical(rng.choice(list("pqrs"), n)),
    })
    fr = Frame.from_pandas(df, destination_frame="pod_if_train")
    m = IsolationForest(ntrees=10, sample_size=64, seed=5).train(
        x=["a", "c"], training_frame=fr)
    assert m.output["feature_domains"][1] == ("p", "q", "r", "s")
    assert serving.scorer_for(m).lane == "iforest"

    rows = [{"a": 0.3, "c": "q"}, {"a": None, "c": "zz"},  # zz: unseen
            {"a": -1.0, "c": None}, {"a": 2.0, "c": "s"}]
    out = serving.score_rows(m, rows)
    # the scoring frame interns only the levels it SEES (q, s, zz) — its
    # frame-local codes differ from training; the remap must reconcile
    sf = Frame.from_pandas(pd.DataFrame({
        "a": [r["a"] for r in rows],
        "c": pd.Categorical([r["c"] for r in rows]),
    }))
    assert tuple(sf.vec("c").domain) != m.output["feature_domains"][1]
    pf = m.predict(sf)
    for col in ("predict", "mean_length"):
        assert (pf.vec(col).to_numpy()[:4].tobytes()
                == np.asarray(out[col]).tobytes()), col


def test_iforest_training_frame_predictions_unchanged():
    """On the training frame itself the domain remap is the identity —
    numeric-only models keep their exact pre-change scores (regression
    guard for the feature_domains growth)."""
    from h2o3_tpu.frame.frame import Frame
    from h2o3_tpu.models.isolation_forest import IsolationForest

    rng = np.random.default_rng(4)
    n = 200
    df = pd.DataFrame({"a": rng.normal(size=n), "b": rng.normal(size=n)})
    fr = Frame.from_pandas(df)
    m = IsolationForest(ntrees=8, sample_size=64, seed=3).train(
        x=["a", "b"], training_frame=fr)
    raw1 = m._predict_raw(fr)
    m.output.pop("feature_domains")  # a pre-ISSUE-14 snapshot
    raw0 = m._predict_raw(fr)
    assert raw1.tobytes() == raw0.tobytes()


# ---------------------------------------------------------------------------
# satellite: serving-registry warm boot


def test_registry_warm_boot_prepages_and_precompiles(tmp_path, monkeypatch):
    """With H2O3_TPU_SERVE_WARM_MODELS=2 and three snapshots in the store,
    warm_boot loads the newest two, leaves their scorers built (compiled
    lane + device residency) and the third untouched until the regular
    poll."""
    from h2o3_tpu import persist
    from h2o3_tpu.frame.frame import Frame
    from h2o3_tpu.models import GBM
    from h2o3_tpu.serving.registry import ServingRegistry

    rng = np.random.default_rng(11)
    n = 400
    df = pd.DataFrame({
        "a": rng.normal(size=n), "b": rng.normal(size=n),
        "y": np.where(rng.random(n) < 0.5, "dog", "cat"),
    })
    fr = Frame.from_pandas(df, destination_frame="warm_train")
    wd = str(tmp_path / "store")
    os.makedirs(wd)
    models = []
    for i in range(3):
        m = GBM(ntrees=3, max_depth=3, seed=40 + i).train(
            y="y", training_frame=fr)
        persist.save_model(m, os.path.join(wd, f"warm_m{i}"))
        os.utime(os.path.join(wd, f"warm_m{i}"),
                 ns=(1_000_000_000 * (1000 + i),) * 2)  # deterministic age
        models.append(m)
    monkeypatch.setenv("H2O3_TPU_SERVE_WATCH_DIR", wd)
    monkeypatch.setenv("H2O3_TPU_SERVE_WARM_MODELS", "2")
    reg = ServingRegistry()
    try:
        assert reg.warm_boot() == 2
        # the two NEWEST snapshots (m1, m2) are serving with scorers built
        for m in models[1:]:
            served = reg.resolve(m.key)
            assert served is not None, m.key
            sc = served.__dict__.get("_h2o3_batch_scorer")
            assert sc is not None and sc.lane == "tree"
        assert reg.resolve(models[0].key) is None  # oldest: not warmed
        assert reg.poll_once() == 1  # the regular poll picks it up
        assert reg.resolve(models[0].key) is not None
    finally:
        reg.reset()
