"""Fleet serving plane tests (ISSUE 12): the model registry's
watch-and-load + generation swap + rollout breaker, device-residency LRU
paging, the per-algo compiled scorer lanes, batcher idle reaping, and
per-model dispatch fairness.

The load-bearing pins:
- a new snapshot is picked up within one poll and swaps in atomically
  under concurrent scoring (every response matches exactly one generation);
- a bad rollout keeps the old generation serving (corrupt file) or rolls
  back to it (rollout breaker on scoring failures);
- LRU paging bounds resident model bytes at N× oversubscription with
  BYTE-equal scores across page-out/page-in;
- DRF/IF/EIF lanes are byte-equal to ``Model.predict`` through the frame
  path, GLM/DL lanes 1e-6;
- one hot model cannot starve cold models past their deadline.
"""

import copy
import os
import threading
import time

import numpy as np
import pandas as pd
import pytest

from h2o3_tpu import persist, serving
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models import GBM
from h2o3_tpu.serving.registry import REGISTRY, ServingRegistry
from h2o3_tpu.serving.residency import MANAGER
from h2o3_tpu.utils import metrics as _mx

ROWS = [{"a": 0.37, "b": -1.25}, {"a": None, "b": 0.0},
        {"a": 2.25, "b": 1.5}]


def _rows_df(rows=ROWS, cols=("a", "b")):
    return pd.DataFrame({c: [r.get(c) for r in rows] for c in cols})


@pytest.fixture(scope="module")
def train_frame():
    rng = np.random.default_rng(11)
    n = 500
    df = pd.DataFrame({
        "a": rng.normal(size=n), "b": rng.normal(size=n),
        "y": np.where(rng.random(n) < 0.5, "dog", "cat"),
    })
    df.loc[::13, "a"] = np.nan
    return Frame.from_pandas(df, destination_frame="fleet_train")


def _train(train_frame, seed=1, ntrees=4):
    return GBM(ntrees=ntrees, max_depth=3, seed=seed).train(
        y="y", training_frame=train_frame)


def _probs(out, domain):
    return np.stack([np.asarray(out[str(d)], np.float32) for d in domain],
                    axis=1)


# ---------------------------------------------------------------------------
# watch-and-load + generation swap


def test_watch_and_load_within_one_poll(train_frame, tmp_path, monkeypatch):
    """A snapshot written to the watch dir is serving within one poll of
    the background watcher — no operator action."""
    wd = str(tmp_path / "store")
    os.makedirs(wd)
    monkeypatch.setenv("H2O3_TPU_SERVE_WATCH_DIR", wd)
    monkeypatch.setenv("H2O3_TPU_SERVE_POLL_SECS", "0.1")
    m = _train(train_frame, seed=21)
    want = serving.score_rows(m, ROWS)
    reg = ServingRegistry()
    try:
        assert reg.install()
        persist.save_model(m, os.path.join(wd, "fleet_m1"))
        deadline = time.monotonic() + 10
        while reg.resolve(m.key) is None and time.monotonic() < deadline:
            time.sleep(0.02)
        served = reg.resolve(m.key)
        assert served is not None, "watcher never picked up the snapshot"
        assert served.serving_generation == 1
        got = serving.score_rows(served, ROWS)
        dom = m.output["response_domain"]
        assert _probs(got, dom).tobytes() == _probs(want, dom).tobytes()
    finally:
        reg.stop()


def test_generation_swap_atomic_under_concurrent_scoring(
        train_frame, tmp_path, monkeypatch):
    """Scores taken across a rollout each match EXACTLY one generation —
    never a blend — and after the swap every request serves the new one."""
    wd = str(tmp_path / "store")
    os.makedirs(wd)
    monkeypatch.setenv("H2O3_TPU_SERVE_WATCH_DIR", wd)
    m1 = _train(train_frame, seed=31, ntrees=3)
    m2_src = _train(train_frame, seed=32, ntrees=5)
    dom = m1.output["response_domain"]
    want1 = _probs(serving.score_rows(m1, ROWS), dom)
    want2 = _probs(serving.score_rows(m2_src, ROWS), dom)
    assert want1.tobytes() != want2.tobytes()  # distinguishable generations

    reg = ServingRegistry()
    persist.save_model(m1, os.path.join(wd, "fleet_swap"))
    assert reg.poll_once() == 1
    key = m1.key

    stop = threading.Event()
    results, errors = [], []

    def scorer():
        while not stop.is_set():
            try:
                served = reg.resolve(key)
                out = serving.score_rows(served, ROWS)
                results.append(_probs(out, dom).tobytes())
            except Exception as e:  # noqa: BLE001
                errors.append(e)
                return

    threads = [threading.Thread(target=scorer) for _ in range(4)]
    for t in threads:
        t.start()
    time.sleep(0.3)
    m2 = copy.copy(m2_src)
    m2.key = key  # same model key: a retrained winner rolling out
    time.sleep(0.02)  # distinct mtime etag even on coarse clocks
    persist.save_model(m2, os.path.join(wd, "fleet_swap"))
    assert reg.poll_once() == 1
    time.sleep(0.3)
    stop.set()
    for t in threads:
        t.join(timeout=30)
    assert not errors, errors
    assert results
    legal = {want1.tobytes(), want2.tobytes()}
    assert set(results) <= legal  # atomic: one generation per response
    # steady state after the swap: the new generation serves
    out = serving.score_rows(reg.resolve(key), ROWS)
    assert _probs(out, dom).tobytes() == want2.tobytes()
    assert reg.resolve(key).serving_generation == 2


def test_bad_snapshot_keeps_old_generation(train_frame, tmp_path,
                                           monkeypatch):
    wd = str(tmp_path / "store")
    os.makedirs(wd)
    monkeypatch.setenv("H2O3_TPU_SERVE_WATCH_DIR", wd)
    m = _train(train_frame, seed=41)
    dom = m.output["response_domain"]
    want = _probs(serving.score_rows(m, ROWS), dom)
    reg = ServingRegistry()
    persist.save_model(m, os.path.join(wd, "fleet_bad"))
    assert reg.poll_once() == 1
    served = reg.resolve(m.key)
    failed0 = _mx.counter_value("serving_rollouts_total", event="failed")
    time.sleep(0.02)
    with open(os.path.join(wd, "fleet_bad"), "wb") as f:
        f.write(b"garbage, not a model file")
    assert reg.poll_once() == 0
    assert reg.resolve(m.key) is served  # old generation keeps serving
    got = _probs(serving.score_rows(reg.resolve(m.key), ROWS), dom)
    assert got.tobytes() == want.tobytes()
    assert _mx.counter_value(
        "serving_rollouts_total", event="failed") == failed0 + 1
    # quarantined: the same bad etag is not retried every poll
    assert reg.poll_once() == 0


def test_rollout_breaker_rolls_back_over_rest(train_frame, tmp_path,
                                              monkeypatch):
    """A generation that loads but cannot score trips the rollout breaker
    THROUGH the REST route and the previous generation resumes serving."""
    import json
    import urllib.error
    import urllib.request

    from h2o3_tpu.api.server import start_server

    srv = start_server(port=0)
    wd = str(tmp_path / "store")
    os.makedirs(wd)
    monkeypatch.setenv("H2O3_TPU_SERVE_WATCH_DIR", wd)
    monkeypatch.setenv("H2O3_TPU_SERVE_BAD_GEN_ERRORS", "1")
    m1 = _train(train_frame, seed=51, ntrees=3)
    m2_src = _train(train_frame, seed=52, ntrees=5)
    dom = m1.output["response_domain"]
    want1 = _probs(serving.score_rows(m1, ROWS), dom)
    key = m1.key
    try:
        persist.save_model(m1, os.path.join(wd, "fleet_breaker"))
        assert REGISTRY.poll_once() == 1
        m2 = copy.copy(m2_src)
        m2.key = key
        time.sleep(0.02)
        persist.save_model(m2, os.path.join(wd, "fleet_breaker"))
        assert REGISTRY.poll_once() == 1
        served = REGISTRY.resolve(key)
        assert served.serving_generation == 2
        # sabotage the rolled-out generation's scorer: every dispatch dies
        sc = serving.scorer_for(served)

        def boom(*a, **k):
            raise RuntimeError("bad generation: scorer exploded")

        monkeypatch.setattr(sc, "score_table", boom)

        def post(rows):
            req = urllib.request.Request(
                srv.url + "/3/Predictions/rows",
                data=json.dumps({"model": key, "rows": rows}).encode(),
                headers={"Content-Type": "application/json"}, method="POST")
            with urllib.request.urlopen(req) as r:
                return json.loads(r.read())

        with pytest.raises(urllib.error.HTTPError) as ei:
            post(ROWS)
        assert ei.value.code == 500
        # the breaker rolled the key back: generation 1's snapshot serves
        back = REGISTRY.resolve(key)
        assert back is not served
        out = post(ROWS)
        got = np.stack([np.asarray(out["predictions"][str(d)], np.float32)
                        for d in dom], axis=1)
        assert got.tobytes() == want1.tobytes()
        assert _mx.counter_value(
            "serving_rollouts_total", event="rolled_back") >= 1
    finally:
        REGISTRY.reset()


def test_registry_disabled_restores_manual_load(train_frame, monkeypatch):
    """H2O3_TPU_SERVE_REGISTRY=0: resolution is off and scoring runs the
    PR-7 DKV path bit-for-bit."""
    monkeypatch.setenv("H2O3_TPU_SERVE_REGISTRY", "0")
    monkeypatch.setenv("H2O3_TPU_SERVE_WATCH_DIR", "/nonexistent")
    m = _train(train_frame, seed=61)
    assert REGISTRY.resolve(m.key) is None
    assert not REGISTRY.install()
    dom = m.output["response_domain"]
    got = _probs(serving.score_rows(m, ROWS), dom)
    pf = m.predict(Frame.from_pandas(_rows_df()))
    want = np.stack([pf.vec(str(d)).to_numpy() for d in dom], axis=1)
    assert got.tobytes() == want.tobytes()


def test_serving_registry_route(train_frame, tmp_path, monkeypatch):
    import json
    import urllib.request

    from h2o3_tpu.api.server import start_server

    srv = start_server(port=0)
    wd = str(tmp_path / "store")
    os.makedirs(wd)
    monkeypatch.setenv("H2O3_TPU_SERVE_WATCH_DIR", wd)
    m = _train(train_frame, seed=71)
    try:
        persist.save_model(m, os.path.join(wd, "fleet_route"))
        assert REGISTRY.poll_once() == 1
        serving.score_rows(REGISTRY.resolve(m.key), ROWS)
        with urllib.request.urlopen(srv.url + "/3/ServingRegistry") as r:
            out = json.loads(r.read())
        assert out["enabled"] is True
        assert out["watch_dir"] == wd
        entry = [e for e in out["models"] if e["key"] == m.key]
        assert entry and entry[0]["generation"] >= 1  # seq is registry-wide
        assert entry[0]["lane"] == "tree"
        assert entry[0]["residency"] in ("hbm", "host")
        assert out["residency"]["models_tracked"] >= 1
    finally:
        REGISTRY.reset()


# ---------------------------------------------------------------------------
# device-residency paging


def test_lru_paging_bounds_resident_bytes(train_frame, monkeypatch):
    """6 models through a ~2-model HBM budget: resident bytes stay under
    the budget, evictions happen, and every model's scores stay BYTE-equal
    across page-out/page-in cycles."""
    models = [_train(train_frame, seed=100 + s) for s in range(6)]
    dom = models[0].output["response_domain"]
    base = [_probs(serving.score_rows(m, ROWS), dom) for m in models]
    sizes = []
    for m in models:
        sc = serving.scorer_for(m)
        sizes.append(sum(leaf.nbytes for leaf in
                         __import__("jax").tree_util.tree_leaves(
                             sc._host_args)))
    budget = int(2 * max(sizes) + 1024)
    monkeypatch.setenv("H2O3_TPU_SERVE_HBM_BYTES", str(budget))
    ev0 = MANAGER.evictions
    pi0 = MANAGER.page_ins
    for _round in range(2):
        for i, m in enumerate(models):
            got = _probs(serving.score_rows(m, ROWS), dom)
            assert got.tobytes() == base[i].tobytes(), i
            st = MANAGER.status()
            assert st["hbm_bytes"] <= budget, st
    st = MANAGER.status()
    assert MANAGER.evictions > ev0, "oversubscription never evicted"
    assert MANAGER.page_ins > pi0 + len(models), "no page-in cycles"
    assert st["hbm_bytes"] <= budget
    # gauges track the tiers
    hbm = _mx.counter_value  # gauges share the read helper
    assert _mx.counter_value("serving_model_bytes", tier="hbm") <= budget
    assert _mx.counter_value("serving_models_resident", tier="host") >= 6
    assert hbm("serving_model_evictions_total", kind="demoted") > 0


def test_retire_releases_scorer_and_batcher(train_frame):
    from h2o3_tpu.serving.batcher import _BATCHERS

    m = _train(train_frame, seed=200)
    serving.score_rows(m, ROWS)
    assert m.key in _BATCHERS
    sc = m.__dict__.get("_h2o3_batch_scorer")
    assert sc is not None and MANAGER.tier_of(sc) is not None
    serving.retire_model(m.key, m)
    # the dispatcher drains and releases asynchronously; wait on the result
    deadline = time.monotonic() + 15
    while MANAGER.tier_of(sc) is not None and time.monotonic() < deadline:
        time.sleep(0.02)
    assert m.key not in _BATCHERS
    assert "_h2o3_batch_scorer" not in m.__dict__
    assert MANAGER.tier_of(sc) is None  # released from both tiers


def test_idle_reap_drops_batcher_and_demotes(train_frame, monkeypatch):
    from h2o3_tpu.serving.batcher import _BATCHERS

    monkeypatch.setenv("H2O3_TPU_SCORE_IDLE_SECS", "0.2")
    m = _train(train_frame, seed=201)
    serving.score_rows(m, ROWS)
    assert m.key in _BATCHERS
    sc = serving.scorer_for(m)
    deadline = time.monotonic() + 15
    while m.key in _BATCHERS and time.monotonic() < deadline:
        time.sleep(0.05)
    assert m.key not in _BATCHERS, "idle batcher never reaped"
    assert MANAGER.tier_of(sc) == "host"  # demoted, not released
    # next request rebuilds transparently, byte-equal
    dom = m.output["response_domain"]
    a = _probs(serving.score_rows(m, ROWS), dom)
    monkeypatch.setenv("H2O3_TPU_SCORE_IDLE_SECS", "30")
    b = _probs(serving.score_rows(m, ROWS), dom)
    assert a.tobytes() == b.tobytes()


# ---------------------------------------------------------------------------
# per-model fairness


def test_hot_model_does_not_starve_cold(train_frame, monkeypatch):
    """1 hot + 8 cold models: the round-robin dispatch gate keeps every
    cold request inside its deadline while the hot model floods its queue."""
    monkeypatch.setenv("H2O3_TPU_SCORE_BATCH_WINDOW_MS", "5")
    monkeypatch.setenv("H2O3_TPU_SCORE_DEADLINE_MS", "5000")
    hot = _train(train_frame, seed=300, ntrees=3)
    cold = [_train(train_frame, seed=301 + i, ntrees=3) for i in range(8)]
    for m in [hot] + cold:  # warm programs out of the measured window
        serving.score_rows(m, ROWS)
    stop = threading.Event()
    hot_errors = []

    def hammer():
        while not stop.is_set():
            try:
                serving.score_rows(hot, ROWS * 4)
            except serving.ShedError:
                pass  # the hot model MAY shed; the cold ones must not
            except Exception as e:  # noqa: BLE001
                hot_errors.append(e)
                return

    hammers = [threading.Thread(target=hammer) for _ in range(3)]
    for t in hammers:
        t.start()
    time.sleep(0.2)
    cold_lat, cold_errors = [], []

    def probe(m):
        try:
            for _ in range(3):
                t0 = time.monotonic()
                serving.score_rows(m, [ROWS[0]])
                cold_lat.append(time.monotonic() - t0)
        except Exception as e:  # noqa: BLE001
            cold_errors.append(e)

    probes = [threading.Thread(target=probe, args=(m,)) for m in cold]
    for t in probes:
        t.start()
    for t in probes:
        t.join(timeout=60)
    stop.set()
    for t in hammers:
        t.join(timeout=30)
    assert not cold_errors, f"cold models starved: {cold_errors[:3]}"
    assert not hot_errors, hot_errors
    assert len(cold_lat) == 24  # every cold request completed
    assert max(cold_lat) < 5.0  # inside H2O3_TPU_SCORE_DEADLINE_MS


# ---------------------------------------------------------------------------
# compiled lane parity: DRF / IF / EIF / GLM / DL


def test_drf_lane_byte_equal(train_frame, rng):
    from h2o3_tpu.models.tree.drf import DRF

    m = DRF(ntrees=5, max_depth=4, seed=3).train(
        y="y", training_frame=train_frame)
    assert serving.scorer_for(m).lane == "tree"
    dom = m.output["response_domain"]
    got = _probs(serving.score_rows(m, ROWS), dom)
    pf = m.predict(Frame.from_pandas(_rows_df()))
    want = np.stack([pf.vec(str(d)).to_numpy() for d in dom], axis=1)
    assert got.tobytes() == want.tobytes()
    # regression DRF
    n = 400
    df = pd.DataFrame({"a": rng.normal(size=n), "b": rng.normal(size=n),
                       "y": rng.normal(size=n)})
    mr = DRF(ntrees=4, max_depth=4, seed=4).train(
        y="y", training_frame=Frame.from_pandas(
            df, destination_frame="fleet_drf_reg"))
    assert serving.scorer_for(mr).lane == "tree"
    out = serving.score_rows(mr, ROWS)
    pfr = mr.predict(Frame.from_pandas(_rows_df()))
    assert (np.asarray(out["predict"], np.float32).tobytes()
            == pfr.vec("predict").to_numpy().tobytes())


def test_iforest_lane_byte_equal(rng):
    from h2o3_tpu.models.isolation_forest import IsolationForest

    n = 300
    df = pd.DataFrame({"a": rng.normal(size=n), "b": rng.normal(size=n),
                       "d": rng.normal(size=n)})
    fr = Frame.from_pandas(df, destination_frame="fleet_if")
    m = IsolationForest(ntrees=12, sample_size=64, seed=5).train(
        x=["a", "b", "d"], training_frame=fr)
    assert serving.scorer_for(m).lane == "iforest"
    rows = [{"a": 0.3, "b": -1.0, "d": 0.1}, {"a": None, "b": 2.0, "d": -.5}]
    out = serving.score_rows(m, rows)
    pf = m.predict(Frame.from_pandas(_rows_df(rows, ("a", "b", "d"))))
    for col in ("predict", "mean_length"):
        assert np.array_equal(pf.vec(col).to_numpy()[:2],
                              np.asarray(out[col])), col


def test_eif_lane_byte_equal(rng):
    from h2o3_tpu.models.extended_isolation_forest import (
        ExtendedIsolationForest,
    )

    n = 300
    df = pd.DataFrame({"a": rng.normal(size=n), "b": rng.normal(size=n),
                       "d": rng.normal(size=n)})
    fr = Frame.from_pandas(df, destination_frame="fleet_eif")
    m = ExtendedIsolationForest(ntrees=10, sample_size=64, seed=6).train(
        training_frame=fr)
    assert serving.scorer_for(m).lane == "eif"
    rows = [{"a": 0.3, "b": -1.0, "d": 0.1}, {"a": None, "b": 2.0, "d": -.5}]
    out = serving.score_rows(m, rows)
    pf = m.predict(Frame.from_pandas(_rows_df(rows, ("a", "b", "d"))))
    for col in ("anomaly_score", "mean_length"):
        assert np.array_equal(pf.vec(col).to_numpy()[:2],
                              np.asarray(out[col])), col


def test_glm_lane_parity(rng):
    from h2o3_tpu.models.glm import GLM

    n = 400
    df = pd.DataFrame({"a": rng.normal(size=n), "b": rng.normal(size=n),
                       "c": rng.choice(["x", "y", "z"], n),
                       "y": np.where(rng.random(n) < 0.5, "p", "q")})
    df.loc[::17, "a"] = np.nan
    fr = Frame.from_pandas(df, destination_frame="fleet_glm")
    rows = [{"a": 0.3, "b": -1.0, "c": "x"},
            {"a": None, "b": 2.0, "c": "NEVER_SEEN"},
            {"b": 0.5, "c": "z"}]
    df2 = _rows_df(rows, ("a", "b", "c"))
    m = GLM(family="binomial", seed=1).train(y="y", training_frame=fr)
    assert serving.scorer_for(m).lane == "glm"
    out = serving.score_rows(m, rows)
    pf = m.predict(Frame.from_pandas(df2))
    dom = m.output["response_domain"]
    for d in dom:
        np.testing.assert_allclose(
            np.asarray(out[str(d)], np.float64),
            pf.vec(str(d)).to_numpy()[:3].astype(np.float64), atol=1e-6)
    assert list(out["predict"]) == [
        dom[i] for i in
        (pf.vec("predict").to_numpy()[:3]).astype(int)]
    # multinomial
    dfm = df.copy()
    dfm["y"] = rng.choice(["r", "g", "bl"], n)
    mm = GLM(family="multinomial", seed=1).train(
        y="y", training_frame=Frame.from_pandas(
            dfm, destination_frame="fleet_glm_m"))
    assert serving.scorer_for(mm).lane == "glm"
    outm = serving.score_rows(mm, rows)
    pfm = mm.predict(Frame.from_pandas(df2))
    for d in mm.output["response_domain"]:
        np.testing.assert_allclose(
            np.asarray(outm[str(d)], np.float64),
            pfm.vec(str(d)).to_numpy()[:3].astype(np.float64), atol=1e-6)
    # regression
    dfr = df.copy()
    dfr["y"] = rng.normal(size=n)
    mr = GLM(family="gaussian", seed=1).train(
        y="y", training_frame=Frame.from_pandas(
            dfr, destination_frame="fleet_glm_r"))
    assert serving.scorer_for(mr).lane == "glm"
    outr = serving.score_rows(mr, rows)
    pfr = mr.predict(Frame.from_pandas(df2))
    np.testing.assert_allclose(
        np.asarray(outr["predict"], np.float64),
        pfr.vec("predict").to_numpy()[:3].astype(np.float64), atol=1e-6)


def test_dl_lane_parity(rng):
    from h2o3_tpu.models.deeplearning import DeepLearning

    n = 400
    df = pd.DataFrame({"a": rng.normal(size=n), "b": rng.normal(size=n),
                       "c": rng.choice(["x", "y"], n),
                       "y": np.where(rng.random(n) < 0.5, "p", "q")})
    fr = Frame.from_pandas(df, destination_frame="fleet_dl")
    m = DeepLearning(hidden=[8, 8], epochs=2, seed=2,
                     reproducible=True).train(y="y", training_frame=fr)
    assert serving.scorer_for(m).lane == "dl"
    rows = [{"a": 0.3, "b": -1.0, "c": "x"}, {"a": None, "b": 2.0, "c": "y"}]
    out = serving.score_rows(m, rows)
    pf = m.predict(Frame.from_pandas(_rows_df(rows, ("a", "b", "c"))))
    for d in m.output["response_domain"]:
        np.testing.assert_allclose(
            np.asarray(out[str(d)], np.float64),
            pf.vec(str(d)).to_numpy()[:2].astype(np.float64), atol=1e-6)


def test_lane_program_reuse_same_bucket(train_frame):
    """A second same-shape DRF model scores with ZERO new scorer program
    shapes — the arguments-not-constants contract beyond the GBM family."""
    from h2o3_tpu.models.tree.drf import DRF

    m1 = DRF(ntrees=4, max_depth=4, seed=8).train(
        y="y", training_frame=train_frame)
    serving.score_rows(m1, ROWS)
    compiled = _mx.counter_value("serving_scorer_programs_total",
                                 event="compile")
    m2 = DRF(ntrees=4, max_depth=4, seed=9).train(
        y="y", training_frame=train_frame)
    serving.score_rows(m2, ROWS)
    assert _mx.counter_value(
        "serving_scorer_programs_total", event="compile") == compiled
