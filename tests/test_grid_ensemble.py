"""Grid search + Stacked Ensemble tests — modeled on upstream
``hex/grid`` and ``hex/ensemble`` test scenarios [UNVERIFIED upstream
paths, SURVEY.md §4]."""

import numpy as np
import pandas as pd
import pytest

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models import GBM, GLM, DRF, GridSearch, StackedEnsemble


def _binary_df(n=2500, seed=7):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 4))
    eta = X[:, 0] * 2 + X[:, 1] ** 2 - X[:, 2] - 1
    y = (rng.random(n) < 1 / (1 + np.exp(-eta))).astype(int)
    df = pd.DataFrame(X, columns=list("abcd"))
    df["y"] = np.where(y == 1, "Y", "N")
    return df


def test_cartesian_grid_covers_product_and_ranks():
    fr = Frame.from_pandas(_binary_df())
    gs = GridSearch(
        GBM,
        {"max_depth": [2, 4], "learn_rate": [0.1, 0.3]},
        ntrees=10,
        seed=42,
    )
    grid = gs.train(y="y", training_frame=fr)
    assert len(grid.models) == 4
    tab = grid.sorted_metric_table("auc")
    assert len(tab) == 4
    aucs = [r["auc"] for r in tab]
    assert aucs == sorted(aucs, reverse=True)
    best = grid.best_model("auc")
    assert best.training_metrics.value("auc") == pytest.approx(max(aucs))


def test_random_grid_respects_max_models_and_seed():
    fr = Frame.from_pandas(_binary_df(n=1200))
    crit = {"strategy": "RandomDiscrete", "max_models": 3, "seed": 99}
    gs1 = GridSearch(GBM, {"max_depth": [2, 3, 4], "learn_rate": [0.05, 0.1, 0.3]},
                     search_criteria=crit, ntrees=5, seed=1)
    g1 = gs1.train(y="y", training_frame=fr)
    gs2 = GridSearch(GBM, {"max_depth": [2, 3, 4], "learn_rate": [0.05, 0.1, 0.3]},
                     search_criteria=crit, ntrees=5, seed=1)
    g2 = gs2.train(y="y", training_frame=fr)
    assert len(g1.models) == 3
    assert g1.hyper_values == g2.hyper_values  # seeded walker is deterministic


def test_grid_keeps_failures_without_dying():
    fr = Frame.from_pandas(_binary_df(n=800))
    gs = GridSearch(GBM, {"max_depth": [2, -5]}, ntrees=3, seed=1)
    grid = gs.train(y="y", training_frame=fr)
    assert len(grid.models) == 1
    assert len(grid.failures) == 1


def test_stacked_ensemble_beats_or_matches_base_models():
    fr = Frame.from_pandas(_binary_df(n=3000, seed=11))
    common = dict(nfolds=3, keep_cross_validation_predictions=True, seed=5)
    gbm = GBM(ntrees=20, max_depth=3, **common).train(y="y", training_frame=fr)
    drf = DRF(ntrees=20, max_depth=6, **common).train(y="y", training_frame=fr)
    glm = GLM(family="binomial", **common).train(y="y", training_frame=fr)
    se = StackedEnsemble(base_models=[gbm, drf.key, glm]).train(
        y="y", training_frame=fr
    )
    se_auc = se.training_metrics.value("auc")
    base_best = max(
        m.cross_validation_metrics.value("auc") for m in (gbm, drf, glm)
    )
    assert se_auc > 0.5
    # SE on the level-one frame should at least be in the ballpark of the best base
    assert se_auc >= base_best - 0.02
    # predict surface: label + 2 prob columns
    pred = se.predict(fr)
    assert pred.names == ["predict", "N", "Y"]
    p = pred.vec("Y").to_numpy()
    assert np.all((p >= 0) & (p <= 1))


def test_stacked_ensemble_regression():
    rng = np.random.default_rng(3)
    X = rng.random((2000, 4))
    y = 3 * X[:, 0] + np.sin(3 * X[:, 1]) + 0.1 * rng.normal(size=2000)
    df = pd.DataFrame(X, columns=list("abcd"))
    df["y"] = y
    fr = Frame.from_pandas(df)
    common = dict(nfolds=3, keep_cross_validation_predictions=True, seed=5)
    gbm = GBM(ntrees=25, max_depth=3, **common).train(y="y", training_frame=fr)
    glm = GLM(family="gaussian", **common).train(y="y", training_frame=fr)
    se = StackedEnsemble(base_models=[gbm, glm]).train(y="y", training_frame=fr)
    assert not se.is_classifier
    r2 = se.training_metrics.value("r2")
    assert r2 > 0.8


def test_cv_folds_share_compiled_programs(caplog):
    """CV folds are weight masks over one padded frame: fold shapes are
    identical, so folds 2..k must trigger ZERO new XLA compilations."""
    import logging

    import jax

    from h2o3_tpu.models import GBM

    df = _binary_df(n=1200, seed=13)
    fr = Frame.from_pandas(df)

    jax.config.update("jax_log_compiles", True)
    try:
        logger = logging.getLogger("jax._src.dispatch")
        logger.setLevel(logging.DEBUG)
        builder = GBM(ntrees=3, max_depth=3, seed=7, nfolds=4,
                      keep_cross_validation_predictions=True)
        with caplog.at_level(logging.DEBUG, logger="jax._src.dispatch"):
            m = builder.train(y="y", training_frame=fr)
        msgs = [r.message for r in caplog.records if "compil" in r.message.lower()]
        # Everything compiles during fold 1 (and the main model before it);
        # assert the LAST quarter of the build produced no compile events by
        # re-running a 4-fold CV fully warm: it must log zero compiles.
        caplog.clear()
        with caplog.at_level(logging.DEBUG, logger="jax._src.dispatch"):
            GBM(ntrees=3, max_depth=3, seed=8, nfolds=4).train(
                y="y", training_frame=fr
            )
        warm = [r.message for r in caplog.records if "compil" in r.message.lower()]
        assert not warm, f"warm CV recompiled: {warm[:3]}"
    finally:
        jax.config.update("jax_log_compiles", False)
    assert m.cross_validation_metrics.auc > 0.7
    assert m.cv_predictions is not None and len(m.cv_models) == 4
