"""2-D rows×cols pod mesh (ISSUE 14): row sharding and the PR-5/PR-6
column blocks compose over separate axes instead of sharing one.

The acceptance pins, exercised on the 8-device CPU proxy's 2-D sub-mesh
shapes (1×8 / 2×4 / 4×2 / 8×1):

- the PR-5 adversarial tie suites stay BIT-equal between the sharded and
  replicated split pipelines on every shape, and split decisions are
  bit-equal ACROSS shapes (the tie data is exact in f32, so any reduce
  regrouping that changed a decision would show);
- the legacy 1-D mesh and the degenerate 1×8 2-D mesh produce
  bit-identical trees (the 2-D generalization is a strict superset);
- ``histogram_in_jit(col_sharded=True)`` blocks equal the replicated
  reduction's slices bit-for-bit on every 2-D shape (the stage-1 rows-axis
  psum is shared by both wrappers);
- the PR-9 quant/hier lanes ride the 2-D mesh: QUANT=1 keeps the tie
  suites bit-exact (power-of-two scales) and 'auto' hierarchy resolves to
  0 there (the mesh IS the hierarchy);
- streamed (out-of-core) GBM keeps resident split decisions on a 2-D mesh;
- GLM coefficients and DL predictions match the 1-D mesh within their
  pinned envelopes on ≥2 genuinely-2-D shapes.
"""

import contextlib
import os

import numpy as np
import pandas as pd
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from h2o3_tpu.models.tree import shared_tree as st
from h2o3_tpu.parallel import mesh as pm

SHAPES = [(1, 8), (2, 4), (4, 2), (8, 1)]
SHAPES_2D = [(2, 4), (4, 2)]  # rows>1 AND cols>1: both stages real


@contextlib.contextmanager
def _use_mesh2d(r: int, c: int):
    devs = jax.devices("cpu")
    assert len(devs) >= r * c, "8-device conftest pin did not land"
    old = pm._mesh
    pm.set_mesh(pm.make_mesh_2d(r, c, devs))
    try:
        yield
    finally:
        pm.set_mesh(old)


@contextlib.contextmanager
def _use_mesh1d(k: int):
    devs = jax.devices("cpu")
    old = pm._mesh
    pm.set_mesh(Mesh(np.array(devs[:k]), (pm.ROWS_AXIS,)))
    try:
        yield
    finally:
        pm.set_mesh(old)


@contextlib.contextmanager
def _env(**kv):
    old = {k: os.environ.get(k) for k in kv}
    os.environ.update({k: str(v) for k, v in kv.items()})
    try:
        yield
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _bits(a) -> bytes:
    return np.ascontiguousarray(np.asarray(a)).tobytes()


def _tree_fields(tree: st.Tree) -> list[dict]:
    host = tree.to_host()
    return [
        {
            "split_col": lv.split_col, "split_bin": lv.split_bin,
            "is_cat": lv.is_cat, "na_left": lv.na_left,
            "leaf_now": lv.leaf_now, "leaf_val": lv.leaf_val,
            "child_base": lv.child_base, "gain": lv.gain,
        }
        for lv in host.levels
    ]


def _assert_trees_bit_equal(a: st.Tree, b: st.Tree, what: str):
    fa, fb = _tree_fields(a), _tree_fields(b)
    assert len(fa) == len(fb), what
    for li, (la, lb) in enumerate(zip(fa, fb)):
        for k in la:
            assert _bits(la[k]) == _bits(lb[k]), (
                f"{what}: level {li} field {k} diverged")


def _build_one(bins_np, t_np, *, split_shard: int, max_depth=3, n_bins=16,
               env=None, seed=5):
    n, C = bins_np.shape
    with _env(H2O3_TPU_SPLIT_SHARD=split_shard, **(env or {})):
        bins = pm.shard_rows(jnp.asarray(bins_np))
        w = pm.shard_rows(jnp.ones(n, jnp.float32))
        t = pm.shard_rows(jnp.asarray(t_np, dtype=jnp.float32))
        preds = pm.shard_rows(jnp.zeros(n, jnp.float32))
        tree, preds, varimp = st.build_tree(
            bins, w, t, pm.shard_rows(jnp.ones(n, jnp.float32)),
            n_bins=n_bins, is_cat_cols=np.zeros(C, bool),
            max_depth=max_depth, min_rows=1.0, min_split_improvement=0.0,
            learn_rate=0.1, preds=preds, key=jax.random.PRNGKey(seed),
            varimp=jnp.zeros(C, jnp.float32), node_cap=2048,
        )
        return tree, np.asarray(preds), np.asarray(varimp)


def _tie_data(n_pad: int, C: int, n_bins: int, seed=0):
    rng = np.random.default_rng(seed)
    base = rng.integers(1, n_bins, n_pad).astype(np.uint8)
    bins = np.tile(base[:, None], (1, C))
    t = np.ones(n_pad, np.float32)  # every candidate gain exactly 0.0
    return bins, t


# ---------------------------------------------------------------------------
# mesh construction + geometry


def test_mesh_rows_knob_builds_2d_and_falls_back():
    with _env(H2O3_TPU_MESH_ROWS="2"):
        pm.set_mesh(None)
        m = pm.get_mesh()
        assert pm.is_2d(m) and dict(m.shape) == {"rows": 2, "cols": 4}
        assert pm.n_shards() == 8 and pm.n_col_shards(m) == 4
        assert pm.n_row_groups(m) == 2
    with _env(H2O3_TPU_MESH_ROWS="3"):  # does not divide 8 → 1-D fallback
        pm.set_mesh(None)
        m = pm.get_mesh()
        assert not pm.is_2d(m) and dict(m.shape) == {"rows": 8}
    pm.set_mesh(None)
    m = pm.get_mesh()  # default stays the legacy 1-D mesh
    assert not pm.is_2d(m) and pm.n_col_shards(m) == 8


def test_2d_mesh_row_shard_order_matches_device_order():
    """Cols-major row sharding: shard i of a row-sharded array must sit on
    jax.devices()[i] exactly like the 1-D mesh (per-process contiguity is
    the sharded-ingest contract)."""
    devs = jax.devices("cpu")
    with _use_mesh2d(2, 4):
        x = pm.shard_rows(np.arange(pm.pad_to_shards(64), dtype=np.float32))
        per = x.shape[0] // 8
        for s in x.addressable_shards:
            lo = int(np.asarray(s.data)[0])
            assert devs.index(s.device) == lo // per


def test_hier_auto_is_zero_on_2d_mesh():
    with _use_mesh2d(2, 4), _env(H2O3_TPU_COLLECTIVE_HIER="auto"):
        assert pm.hier_inner(4) == 0
    with _use_mesh2d(2, 4), _env(H2O3_TPU_COLLECTIVE_HIER="2"):
        assert pm.hier_inner(4) == 2  # explicit ints still subdivide cols


# ---------------------------------------------------------------------------
# adversarial tie suites over the 2-D shape ladder


@pytest.mark.parametrize("r,c", SHAPES)
def test_tie_suite_sharded_equals_replicated_2d(r, c):
    with _use_mesh2d(r, c):
        n_pad = pm.pad_to_shards(960)
        bins, t = _tie_data(n_pad, C=13, n_bins=16)
        t1, p1, v1 = _build_one(bins, t, split_shard=1)
        t0, p0, v0 = _build_one(bins, t, split_shard=0)
        _assert_trees_bit_equal(t1, t0, f"ties/{r}x{c}")
        assert _bits(p1) == _bits(p0) and _bits(v1) == _bits(v0)
        # lowest-global-index tie-break must survive the 2-D merge
        assert int(np.asarray(t1.levels[0].split_col)[0]) == 0


def test_tie_suite_decisions_bit_equal_across_shapes():
    """Split decisions on the exact-tie suite are bit-equal across every
    2-D shape AND the legacy 1-D mesh (exact f32 sums: regrouping the
    reduce cannot change any histogram cell)."""
    n_pad = pm.pad_to_shards(960)
    bins, t = _tie_data(n_pad, C=13, n_bins=16, seed=3)
    with _use_mesh1d(8):
        t_ref, p_ref, v_ref = _build_one(bins, t, split_shard=1)
    for r, c in SHAPES:
        with _use_mesh2d(r, c):
            t2, p2, v2 = _build_one(bins, t, split_shard=1)
            _assert_trees_bit_equal(t2, t_ref, f"cross-shape {r}x{c}")
            assert _bits(p2) == _bits(p_ref) and _bits(v2) == _bits(v_ref)


def test_real_signal_preds_close_across_shapes():
    """Non-tie data: decisions may legitimately differ only if a gain
    comparison flips on the last f32 bit — preds stay within 1e-6 across
    shapes (the acceptance envelope)."""
    n_pad = pm.pad_to_shards(960)
    rng = np.random.default_rng(7)
    bins = rng.integers(0, 16, (n_pad, 9)).astype(np.uint8)
    t = rng.normal(size=n_pad).astype(np.float32)
    with _use_mesh1d(8):
        _, p_ref, _ = _build_one(bins, t, split_shard=1)
    for r, c in SHAPES:
        with _use_mesh2d(r, c):
            _, p2, _ = _build_one(bins, t, split_shard=1)
            np.testing.assert_allclose(p2, p_ref, atol=1e-6)


def test_1d_mesh_equals_1x8_2d_bitwise():
    n_pad = pm.pad_to_shards(700)
    rng = np.random.default_rng(11)
    bins = rng.integers(0, 16, (n_pad, 7)).astype(np.uint8)
    t = rng.normal(size=n_pad).astype(np.float32)
    with _use_mesh1d(8):
        ta, pa, va = _build_one(bins, t, split_shard=1)
    with _use_mesh2d(1, 8):
        tb, pb, vb = _build_one(bins, t, split_shard=1)
    _assert_trees_bit_equal(ta, tb, "1d-vs-1x8")
    assert _bits(pa) == _bits(pb) and _bits(va) == _bits(vb)


# ---------------------------------------------------------------------------
# histogram blocks + quant lane on the 2-D mesh


@pytest.mark.parametrize("r,c", SHAPES_2D)
def test_sharded_histogram_blocks_bit_equal_2d(r, c):
    from h2o3_tpu.ops.histogram import histogram_in_jit

    with _use_mesh2d(r, c):
        rng = np.random.default_rng(2)
        n, C, N, B = pm.pad_to_shards(2000), 7, 8, 16
        bins = pm.shard_rows(jnp.asarray(
            rng.integers(0, B, (n, C)), jnp.uint8))
        nid = pm.shard_rows(jnp.asarray(rng.integers(-1, N, n), jnp.int32))
        w = pm.shard_rows(jnp.asarray(rng.random(n), jnp.float32))
        wy = pm.shard_rows(jnp.asarray(rng.normal(size=n), jnp.float32))
        rep = jax.jit(
            lambda b, i, *s: histogram_in_jit(b, i, s, N, B)
        )(bins, nid, w, wy, w)
        shd = jax.jit(
            lambda b, i, *s: histogram_in_jit(b, i, s, N, B, col_sharded=True)
        )(bins, nid, w, wy, w)
        rep, shd = np.asarray(rep), np.asarray(shd)
        Cp = pm.pad_cols_to_shards(C)
        assert Cp % c == 0 and shd.shape[1] == Cp
        assert _bits(rep) == _bits(shd[:, :C])
        assert not shd[:, C:].any()


def test_quant_lane_tie_suite_bit_exact_on_2d():
    """QUANT=1 on a genuinely-2-D mesh: the cols-stage quantizes (power-of-
    two scales, integer payloads ≤127 lossless) after the exact rows-stage
    psum — the tie suite must stay bit-equal sharded vs replicated."""
    with _use_mesh2d(2, 4), _env(H2O3_TPU_COLLECTIVE_QUANT="1"):
        n_pad = pm.pad_to_shards(960)
        bins, t = _tie_data(n_pad, C=13, n_bins=16, seed=5)
        t1, p1, v1 = _build_one(bins, t, split_shard=1)
        t0, p0, v0 = _build_one(bins, t, split_shard=0)
        _assert_trees_bit_equal(t1, t0, "quant-2d")
        assert _bits(p1) == _bits(p0) and _bits(v1) == _bits(v0)


def test_collective_bytes_record_both_stages_on_2d():
    """The hist_reduce tally on a 2-D mesh carries the stage-1 exact psum
    PLUS the cols-stage scatter — strictly more than the pure scatter, and
    the winner gather shrinks to the cols width."""
    from h2o3_tpu.utils import metrics as mx

    n_pad = pm.pad_to_shards(700)
    rng = np.random.default_rng(19)
    bins = rng.integers(0, 32, (n_pad, 28)).astype(np.uint8)
    t = rng.normal(size=n_pad).astype(np.float32)

    def run():
        h0 = mx.counter_value(
            "tree_collective_bytes_total", phase="hist_reduce")
        w0 = mx.counter_value(
            "tree_collective_bytes_total", phase="winner_gather")
        _build_one(bins, t, split_shard=1, n_bins=32, seed=23)
        return (
            mx.counter_value(
                "tree_collective_bytes_total", phase="hist_reduce") - h0,
            mx.counter_value(
                "tree_collective_bytes_total", phase="winner_gather") - w0,
        )

    with _use_mesh2d(2, 4):
        h2d, w2d = run()
    with _use_mesh1d(8):
        h1d, w1d = run()
    assert h2d > 0 and w2d > 0
    assert h2d > h1d  # the exact rows-stage volume is accounted
    assert w2d < w1d  # winners gather over 4 blocks instead of 8


# ---------------------------------------------------------------------------
# streamed (out-of-core) GBM + GLM + DL on 2-D meshes


def _frame(n, c, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, c)).astype(np.float32)
    eta = X[:, 0] - 0.5 * X[:, 1] + 0.25 * X[:, 2]
    df = pd.DataFrame(X, columns=[f"x{i}" for i in range(c)])
    y = rng.random(n) < 1.0 / (1.0 + np.exp(-eta))
    df["label"] = np.where(y, "s", "b")
    from h2o3_tpu.frame.frame import Frame

    return Frame.from_pandas(df)


def _p1(model, fr):
    pf = model.predict(fr)
    return pf.vec(pf.names[-1]).to_numpy()


def _tree_decisions(model):
    out = []
    for group in model.output["trees"]:
        for t in group:
            h = t.to_host()
            out.append([(np.asarray(lv.split_col), np.asarray(lv.split_bin),
                         np.asarray(lv.leaf_now)) for lv in h.levels])
    return out


def test_streamed_gbm_parity_on_2d_mesh():
    from h2o3_tpu.frame import chunkstore as cs
    from h2o3_tpu.models.tree import GBM

    with _use_mesh2d(2, 4):
        kw = dict(ntrees=4, max_depth=4, seed=11, score_tree_interval=2)
        fr = _frame(3000, 6, seed=7)
        m_res = GBM(**kw).train(y="label", training_frame=fr)
        with _env(H2O3_TPU_HBM_WINDOW_BYTES=str(48 * 1024)):
            fr2 = _frame(3000, 6, seed=7)
            m_str = GBM(**kw).train(y="label", training_frame=fr2)
        assert cs.LAST_STORE_STATS["n_blocks"] > 1  # really streamed
        dres, dstr = _tree_decisions(m_res), _tree_decisions(m_str)
        assert len(dres) == len(dstr)
        for tr, ts in zip(dres, dstr):
            for (c1, b1, l1), (c2, b2, l2) in zip(tr, ts):
                assert np.array_equal(l1, l2)
                live = ~l1
                assert np.array_equal(c1[live], c2[live])
                assert np.array_equal(b1[live], b2[live])
        np.testing.assert_allclose(_p1(m_res, fr), _p1(m_str, fr), atol=1e-6)


@pytest.mark.parametrize("r,c", SHAPES_2D)
def test_glm_coef_parity_2d(r, c):
    from h2o3_tpu.models.glm import GLM

    kw = dict(family="binomial", lambda_=1e-4, max_iterations=10, seed=1)
    fr = _frame(2000, 6, seed=13)
    m_ref = GLM(**kw).train(y="label", training_frame=fr)
    with _use_mesh2d(r, c):
        fr2 = _frame(2000, 6, seed=13)
        m_2d = GLM(**kw).train(y="label", training_frame=fr2)
    delta = max(abs(m_ref.coef[k] - m_2d.coef[k]) for k in m_ref.coef)
    assert delta < 2e-4, delta  # observed ~3e-7: f32 reduce regrouping only


@pytest.mark.parametrize("r,c", SHAPES_2D)
def test_dl_preds_parity_2d(r, c):
    from h2o3_tpu.models.deeplearning import DeepLearning

    kw = dict(hidden=[16], epochs=2, mini_batch_size=200, seed=3)
    fr = _frame(2000, 6, seed=17)
    m_ref = DeepLearning(**kw).train(y="label", training_frame=fr)
    p_ref = _p1(m_ref, fr)
    with _use_mesh2d(r, c):
        fr2 = _frame(2000, 6, seed=17)
        m_2d = DeepLearning(**kw).train(y="label", training_frame=fr2)
        p_2d = _p1(m_2d, fr2)
    np.testing.assert_allclose(p_2d, p_ref, atol=1e-4)  # PR-8 envelope
